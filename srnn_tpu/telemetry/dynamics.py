"""Replication-dynamics telemetry: device-side lineage, event edges, and
fixpoint-distance census for the soup scan.

The science questions of the source papers — which lineages dominate a
soup, who attacked whom, how far does each particle sit from its own
fixpoint — need *experiment*-level observability that PR 2/4's system
metrics (counters, health sentinels) do not carry.  This module is the
device half of that layer, accumulated INSIDE the jitted generations scan
with the same discipline as :class:`~srnn_tpu.telemetry.device.SoupMetrics`:
zero host round-trips, one flush per chunk, population state bit-identical
to the unmetered program (the carry only reads weights and the phase
gates the step already computes).

Three pieces ride the scan behind the ``lineage=True`` static flag on
``evolve`` / ``evolve_multi`` / ``sharded_evolve`` / ``sharded_evolve_multi``:

  * :class:`LineageState` — per-particle persistent instance ids (pid)
    with parent pid and birth generation.  A NEW pid is minted whenever a
    slot's identity changes: at seed and respawn (roots, ``parent=-1``)
    and when an attack overwrites the victim (``parent`` = attacker's
    pid — self-replication is the lineage link).  ``learn_from`` perturbs
    but does not replace, so it mints nothing and only contributes an
    event edge.  Pids are globally unique across shards: mint bases come
    from the all-gathered mint mask's global rank (the same shard-offset
    construction the respawn uids use), so the sharded popmajor path
    assigns bit-identical pids to the single-device run.
  * :class:`LineageWindow` — fixed-capacity per-window event-edge buffers
    (``(kind, gen, src_pid, dst_pid, prev_pid)`` int32 rows) with the
    compact-lanes discipline by rank: each gated lane's append slot is
    its mask rank (a cumsum the mint already pays) and a generation's
    rows land with ONE fused ``mode='drop'`` scatter
    (:func:`record_step`).  Capacity overflow drops the excess edges and
    counts them in ``dropped`` (``births`` stays exact — it is summed
    from the masks, not the buffer), so a mega-scale window degrades to
    an honest *sample* of the event graph, never a stall.
  * :class:`FixpointStats` — end-of-window per-particle self-application
    distance ``‖f(w) − w‖`` (L2 + L∞), sketched into the same log2
    bucket layout as ``HealthStats``, a per-particle basin label
    (fixpoint / drifting / divergent / zero — thresholds below) counted
    into a census, and the window-over-window basin transition matrix
    (previous labels ride the lineage carry, so the matrix is exact
    without shipping per-particle labels to the host).

Basin labels (DESIGN.md §11): ``divergent`` iff any weight (or the
self-application distance) is NaN/Inf; else ``zero`` iff every weight is
within ``[-epsilon, +epsilon]`` (the ``is_zero`` predicate); else
``fixpoint`` iff ``L∞ < epsilon`` (the reference's degree-1
``is_fixpoint`` criterion, strict); else ``drifting``.  Particles minted
during the window enter the transition matrix from the ``unknown`` row.

Like :mod:`srnn_tpu.telemetry.device` this module is import-cycle-free
towards the soup modules (``jax``/``jnp`` + stdlib/numpy for the host
half only), so the jitted bodies can import it freely; the
self-application ``f(w)`` is computed by the CALLER (it owns the variant
dispatch) and passed in.  The host half (:class:`LineageWriter`,
:func:`update_dynamics_registry`) turns flushed windows into the
append-only ``lineage.jsonl`` stream next to the ``.traj`` store and the
``soup_dynamics_*`` registry metrics; :mod:`srnn_tpu.telemetry.genealogy`
reconstructs the ancestry forest offline.

Pids are int32 like the uids: a 1M-particle run mints ~0.2 pids per
particle-generation at the paper's rates, so the 2^31 ceiling is ~10k
generations at mega scale — beyond any BASELINE workload; the host
registry tracks ``next_pid`` so an approach to the ceiling is visible.
"""

import json
import math
import os
from typing import NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .device import HEALTH_BUCKET_LO, HEALTH_BUCKET_STEP, N_HEALTH_BUCKETS

#: basin labels of the fixpoint census (order is the label precedence
#: used by :func:`fixpoint_stats`, mirroring the reference classification:
#: divergent > zero > fixpoint > drifting)
N_BASINS = 4
BASIN_NAMES = ("fixpoint", "drifting", "divergent", "zero")
BASIN_FIX, BASIN_DRIFT, BASIN_DIV, BASIN_ZERO = range(N_BASINS)
BASIN_UNKNOWN = -1  # minted this window / first window: no previous label

#: event-edge kinds (the ``kind`` column of a :class:`LineageWindow` row)
EDGE_NAMES = ("attack", "learn", "respawn")
EDGE_ATTACK, EDGE_LEARN, EDGE_RESPAWN = range(3)
#: columns of one edge row: (kind, gen, src_pid, dst_pid, prev_pid) —
#: ``src`` is the attacker/teacher pid (-1 for respawn roots), ``dst`` the
#: (possibly freshly minted) pid at the receiving slot, ``prev`` the pid
#: the slot held before (-1 when the slot's identity did not change)
EDGE_WIDTH = 5

#: per-window per-shard edge-buffer rows (the mega loops' --lineage-edges
#: default; also what the AOT warmup sweep compiles against)
DEFAULT_EDGE_CAPACITY = 4096


class LineageState(NamedTuple):
    """Persistent per-particle lineage carry (rides the scan like the
    metrics/health carries, but is INPUT as well as output: pids persist
    across chunks)."""
    pid: jnp.ndarray      # (N,) int32 — current instance id of each slot
    parent: jnp.ndarray   # (N,) int32 — parent pid (-1 for roots)
    birth: jnp.ndarray    # (N,) int32 — generation the instance was minted
    basin: jnp.ndarray    # (N,) int32 — label at last window close (-1 unknown)
    next_pid: jnp.ndarray  # () int32 — global mint counter (replicated)


class LineageWindow(NamedTuple):
    """Per-flush-interval event-edge buffer (per shard under sharding:
    every field's leading axis concatenates over shards at the
    ``shard_map`` boundary, so the host sees ``(D*cap, 5)`` edges with a
    ``(D,)`` valid-row count)."""
    edges: jnp.ndarray    # (cap, EDGE_WIDTH) int32
    n_edges: jnp.ndarray  # (1,) int32 — valid rows (per shard)
    dropped: jnp.ndarray  # (1,) int32 — edges lost to capacity (per shard)
    births: jnp.ndarray   # (1, 2) int32 — exact attack/respawn mints (per shard)


class FixpointStats(NamedTuple):
    """End-of-window fixpoint census (global after the shard psum)."""
    census: jnp.ndarray       # (N_BASINS,) int32
    transitions: jnp.ndarray  # (N_BASINS + 1, N_BASINS) int32 — rows: unknown + prev basin
    l2_hist: jnp.ndarray      # (N_HEALTH_BUCKETS,) int32 — log2 sketch of finite L2
    linf_hist: jnp.ndarray    # (N_HEALTH_BUCKETS,) int32
    l2_max: jnp.ndarray       # () f32 — max finite L2 distance (-inf if none)
    linf_max: jnp.ndarray     # () f32


def seed_lineage(n: int, base: int = 0, time: int = 0) -> LineageState:
    """Fresh lineage for an ``n``-particle population: the seed particles
    are roots ``pid = base + [0, n)`` born at ``time``."""
    return LineageState(
        pid=jnp.arange(base, base + n, dtype=jnp.int32),
        parent=jnp.full(n, -1, jnp.int32),
        birth=jnp.full(n, time, jnp.int32),
        basin=jnp.full(n, BASIN_UNKNOWN, jnp.int32),
        next_pid=jnp.int32(base + n),
    )


def seed_lineage_blocks(sizes: Sequence[int], time: int = 0
                        ) -> Tuple[LineageState, ...]:
    """Per-type lineage carries over ONE shared pid space: type ``t``'s
    seed pids are its uid block ``[offs[t], offs[t+1])`` and every carry
    starts from the same global mint counter (``sum(sizes)``)."""
    total = sum(sizes)
    lins, off = [], 0
    for n in sizes:
        lin = seed_lineage(n, base=off, time=time)
        lins.append(lin._replace(next_pid=jnp.int32(total)))
        off += n
    return tuple(lins)


def zero_window(capacity: int) -> LineageWindow:
    """The empty per-window buffer the scan carry starts from."""
    if capacity < 1:
        raise ValueError(f"lineage edge capacity must be >= 1, got {capacity}")
    return LineageWindow(
        edges=jnp.full((capacity, EDGE_WIDTH), -1, jnp.int32),
        n_edges=jnp.zeros(1, jnp.int32),
        dropped=jnp.zeros(1, jnp.int32),
        births=jnp.zeros((1, 2), jnp.int32),
    )


def edge_capacity(n: int, rate: float) -> int:
    """Static per-generation compaction width for a Binomial(n, rate)
    gated-lane count: mean + 8 sd rounded up to a 128 multiple (the same
    bound the compact attack/learn phases use; P(overflow) < 1e-14 —
    and here overflow only drops edges, never changes semantics)."""
    rate = min(max(rate, 0.0), 1.0)
    mean = n * rate
    sd = math.sqrt(n * rate * (1.0 - rate))
    cap = int(math.ceil(mean + 8.0 * sd)) + 16
    return min(n, ((cap + 127) // 128) * 128)


def _rank_and_total(mask: jnp.ndarray, axes) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Global mint rank of each masked lane + the global mint count.

    Single device: a plain cumsum.  Inside a ``shard_map`` body (``axes``
    = the particle mesh axis name/tuple): the all-gathered mask's global
    cumsum, sliced back to the local lanes — the same construction the
    sharded respawn uids use, so shard boundaries never change which pid
    a lane receives."""
    if axes is None:
        rank = jnp.cumsum(mask) - 1
        return rank.astype(jnp.int32), mask.sum(dtype=jnp.int32)
    n_loc = mask.shape[0]
    all_mask = jax.lax.all_gather(mask, axes, tiled=True)
    rank = jnp.cumsum(all_mask) - 1
    d = jax.lax.axis_index(axes)
    rank_loc = jax.lax.dynamic_slice_in_dim(rank, d * n_loc, n_loc)
    return rank_loc.astype(jnp.int32), all_mask.sum(dtype=jnp.int32)


def lookup_pids(pid: jnp.ndarray, idx: jnp.ndarray, axes=None) -> jnp.ndarray:
    """pid of a (global) particle index — the shard-aware uid-table gather."""
    table = pid if axes is None else jax.lax.all_gather(pid, axes, tiled=True)
    return table[idx]


def mint(lin: LineageState, mask: jnp.ndarray, parent_pid: jnp.ndarray,
         gen: jnp.ndarray, axes=None) -> LineageState:
    """Mint a fresh pid for every masked lane (attack victims or respawned
    slots): globally-ranked ids from ``next_pid``, ``parent_pid`` recorded
    per lane (-1 for roots), birth = ``gen``, basin reset to unknown."""
    rank, total = _rank_and_total(mask, axes)
    new_pid = lin.next_pid + rank
    return LineageState(
        pid=jnp.where(mask, new_pid, lin.pid),
        parent=jnp.where(mask, parent_pid, lin.parent),
        birth=jnp.where(mask, gen.astype(jnp.int32), lin.birth),
        basin=jnp.where(mask, BASIN_UNKNOWN, lin.basin),
        next_pid=lin.next_pid + total,
    )


def record_step(lin: LineageState, win: LineageWindow, *,
                gen: jnp.ndarray, attacked: jnp.ndarray,
                attacker_pid: jnp.ndarray, learn_gate: jnp.ndarray,
                learn_tgt: jnp.ndarray, dead: jnp.ndarray,
                caps: Tuple[int, int, int], capacity: int, axes=None
                ) -> Tuple[LineageState, LineageWindow]:
    """One generation's COMPLETE lineage bookkeeping in one call, fed the
    phase info the step already computed: ``attacked`` lanes with their
    winning ``attacker_pid`` (start-of-generation pids, resolve with
    :func:`lookup_pids`), learner lanes with their teacher's
    population-global index (the teacher pid resolves POST-attack-minting
    — a particle imitating a just-attacked victim learns from the NEW
    instance), and the respawned ``dead`` lanes.  Attack mints, then
    learn edges, then respawn mints; every edge row of the generation
    lands with ONE ``mode='drop'`` scatter, and each mask's cumsum is
    shared between its mint rank and its append slots.  At small
    populations the per-lane int ops ARE the lineage bill, so the fusion
    is what keeps the micro_dispatch ``lineage`` row inside its
    documented overhead bound.  A zero entry in ``caps`` (static
    per-phase compaction widths, see :func:`edge_capacity`) elides that
    whole edge block — the caller's way of saying the phase cannot fire
    (e.g. ``learn_from_rate <= 0``, the homogeneous mega default).

    The heterogeneous loops call this per type AFTER their whole weights
    loop (``multisoup._record_multi_lineage``), chaining mint bases
    type-major through one shared counter."""
    gen = gen.astype(jnp.int32)
    g = jnp.broadcast_to(gen, attacked.shape)
    neg1 = jnp.full_like(lin.pid, -1)
    zero = jnp.int32(0)

    def mint_ranked(l, mask, parent_pid):
        rank = (jnp.cumsum(mask) - 1).astype(jnp.int32)
        cnt = mask.sum(dtype=jnp.int32)
        if axes is None:
            minted = l._replace(
                pid=jnp.where(mask, l.next_pid + rank, l.pid),
                parent=jnp.where(mask, parent_pid, l.parent),
                birth=jnp.where(mask, gen, l.birth),
                basin=jnp.where(mask, BASIN_UNKNOWN, l.basin),
                next_pid=l.next_pid + cnt)
        else:
            minted = mint(l, mask, parent_pid, gen, axes)
        return minted, rank, cnt

    old_pid = lin.pid
    cnt_att = cnt_learn = cnt_dead = zero
    if caps[0] > 0:
        lin, rank_att, cnt_att = mint_ranked(lin, attacked, attacker_pid)
    if caps[1] > 0:
        teacher_pid = lookup_pids(lin.pid, learn_tgt, axes)
        rank_learn = (jnp.cumsum(learn_gate) - 1).astype(jnp.int32)
        cnt_learn = learn_gate.sum(dtype=jnp.int32)
    mid_pid = lin.pid
    if caps[2] > 0:
        lin, rank_dead, cnt_dead = mint_ranked(lin, dead, neg1)

    base = win.n_edges[0]
    pos_parts, row_parts = [], []
    appended = zero
    if caps[0] > 0:
        app = jnp.minimum(jnp.minimum(cnt_att, caps[0]),
                          jnp.maximum(capacity - base, 0))
        pos_parts.append(jnp.where(attacked & (rank_att < caps[0]),
                                   base + rank_att, capacity))
        row_parts.append(jnp.stack(
            [jnp.full_like(old_pid, EDGE_ATTACK), g, attacker_pid, mid_pid,
             old_pid], axis=1))
        base, appended = base + app, appended + app
    if caps[1] > 0:
        app = jnp.minimum(jnp.minimum(cnt_learn, caps[1]),
                          jnp.maximum(capacity - base, 0))
        pos_parts.append(jnp.where(learn_gate & (rank_learn < caps[1]),
                                   base + rank_learn, capacity))
        row_parts.append(jnp.stack(
            [jnp.full_like(old_pid, EDGE_LEARN), g, teacher_pid, mid_pid,
             neg1], axis=1))
        base, appended = base + app, appended + app
    if caps[2] > 0:
        app = jnp.minimum(jnp.minimum(cnt_dead, caps[2]),
                          jnp.maximum(capacity - base, 0))
        pos_parts.append(jnp.where(dead & (rank_dead < caps[2]),
                                   base + rank_dead, capacity))
        row_parts.append(jnp.stack(
            [jnp.full_like(old_pid, EDGE_RESPAWN), g, neg1, lin.pid,
             mid_pid], axis=1))
        appended = appended + app
    if not pos_parts:
        return lin, win
    total = cnt_att + cnt_learn + cnt_dead
    return lin, win._replace(
        edges=win.edges.at[jnp.concatenate(pos_parts)].set(
            jnp.concatenate(row_parts), mode="drop"),
        n_edges=win.n_edges + appended,
        dropped=win.dropped + (total - appended),
        births=win.births.at[0].add(jnp.stack([cnt_att, cnt_dead])),
    )


def _log2_hist(values: jnp.ndarray, include: jnp.ndarray) -> jnp.ndarray:
    """The HealthStats log2 bucket sketch over a nonnegative statistic:
    exact zeros land in bucket 0, excluded lanes count nowhere."""
    safe = jnp.where(include & (values > 0), values,
                     jnp.float32(2.0) ** HEALTH_BUCKET_LO)
    b = jnp.clip(
        (jnp.floor(jnp.log2(safe)).astype(jnp.int32) - HEALTH_BUCKET_LO)
        // HEALTH_BUCKET_STEP, 0, N_HEALTH_BUCKETS - 1)
    codes = jnp.arange(N_HEALTH_BUCKETS, dtype=jnp.int32)
    return ((b[None, :] == codes[:, None]) & include[None, :]).sum(
        axis=1, dtype=jnp.int32)


def fixpoint_stats(w: jnp.ndarray, fw: jnp.ndarray, axis: int,
                   epsilon: float, prev_basin: jnp.ndarray
                   ) -> Tuple[jnp.ndarray, FixpointStats]:
    """Per-particle basin labels + census from the self-application
    ``fw = f(w)`` (computed by the caller, which owns the variant
    dispatch).  ``axis`` is the weight axis ((N, P) row-major: -1;
    (P, N) lane-major: 0); ``epsilon`` doubles as the zero-collapse bound
    and the strict L∞ fixpoint threshold (reference degree-1
    ``is_fixpoint``)."""
    diff = fw - w
    l2 = jnp.sqrt((diff * diff).sum(axis=axis, dtype=jnp.float32))
    linf = jnp.max(jnp.abs(diff), axis=axis).astype(jnp.float32)
    div = jnp.any(~jnp.isfinite(w), axis=axis) | ~jnp.isfinite(linf)
    zero = jnp.all((w >= -epsilon) & (w <= epsilon), axis=axis) & ~div
    fix = ~div & ~zero & (linf < epsilon)
    basin = jnp.where(
        div, BASIN_DIV,
        jnp.where(zero, BASIN_ZERO,
                  jnp.where(fix, BASIN_FIX, BASIN_DRIFT))).astype(jnp.int32)
    codes = jnp.arange(N_BASINS, dtype=jnp.int32)
    census = (basin[None, :] == codes[:, None]).sum(axis=1, dtype=jnp.int32)
    pair = (prev_basin + 1) * N_BASINS + basin
    pcodes = jnp.arange((N_BASINS + 1) * N_BASINS, dtype=jnp.int32)
    transitions = (pair[None, :] == pcodes[:, None]).sum(
        axis=1, dtype=jnp.int32).reshape(N_BASINS + 1, N_BASINS)
    finite = jnp.isfinite(l2) & jnp.isfinite(linf)
    stats = FixpointStats(
        census=census,
        transitions=transitions,
        l2_hist=_log2_hist(l2, finite),
        linf_hist=_log2_hist(linf, finite),
        l2_max=jnp.where(finite, l2, -jnp.inf).max(),
        linf_max=jnp.where(finite, linf, -jnp.inf).max(),
    )
    return basin, stats


def close_window(lin: LineageState, w: jnp.ndarray, fw: jnp.ndarray,
                 axis: int, epsilon: float
                 ) -> Tuple[LineageState, FixpointStats]:
    """End-of-window close: label every particle's basin, fold the
    window-over-window transition matrix from the carried previous labels,
    and store the new labels for the next window."""
    basin, stats = fixpoint_stats(w, fw, axis, epsilon, lin.basin)
    return lin._replace(basin=basin), stats


def psum_fixpoints(s: FixpointStats, axis_name) -> FixpointStats:
    """Global census from per-shard stats inside a ``shard_map`` body."""
    return FixpointStats(
        census=jax.lax.psum(s.census, axis_name),
        transitions=jax.lax.psum(s.transitions, axis_name),
        l2_hist=jax.lax.psum(s.l2_hist, axis_name),
        linf_hist=jax.lax.psum(s.linf_hist, axis_name),
        l2_max=jax.lax.pmax(s.l2_max, axis_name),
        linf_max=jax.lax.pmax(s.linf_max, axis_name),
    )


# ---------------------------------------------------------------------------
# sharding specs (shared by the two sharded twins)
# ---------------------------------------------------------------------------


def lineage_specs(axes) -> LineageState:
    """Placement of the lineage carry under the soup sharding: per-particle
    arrays sharded, the mint counter replicated."""
    from jax.sharding import PartitionSpec as P

    return LineageState(pid=P(axes), parent=P(axes), birth=P(axes),
                        basin=P(axes), next_pid=P())


def window_specs(axes) -> LineageWindow:
    """Per-SHARD window buffers: every field concatenates over the mesh
    axis, so the host receives all shards' edges side by side with their
    per-shard valid counts."""
    from jax.sharding import PartitionSpec as P

    return LineageWindow(edges=P(axes), n_edges=P(axes), dropped=P(axes),
                         births=P(axes))


def fixpoint_specs() -> FixpointStats:
    """Replicated placement of a psum'd ``FixpointStats``."""
    from jax.sharding import PartitionSpec as P

    return FixpointStats(census=P(), transitions=P(), l2_hist=P(),
                         linf_hist=P(), l2_max=P(), linf_max=P())


def place_lineage(mesh, lin: LineageState) -> LineageState:
    """Place a host-constructed lineage carry with the soup sharding."""
    from jax.sharding import NamedSharding

    from ..parallel.mesh import global_device_put
    from ..parallel.sharded_soup import _soup_axes

    specs = lineage_specs(_soup_axes(mesh))
    return jax.tree.map(
        lambda x, spec: global_device_put(x, NamedSharding(mesh, spec)),
        lin, specs)


# ---------------------------------------------------------------------------
# host half: the lineage.jsonl stream + registry metrics
# ---------------------------------------------------------------------------


def window_edge_rows(win: LineageWindow, capacity: int) -> list:
    """Valid edge rows of a flushed window as a plain list of 5-int lists
    (all shards' segments in shard order)."""
    edges = np.asarray(win.edges).reshape(-1, capacity, EDGE_WIDTH)
    counts = np.asarray(win.n_edges).reshape(-1)
    rows = []
    for seg, cnt in zip(edges, counts):
        rows.extend(seg[: int(cnt)].tolist())
    return rows


def _fixpoint_doc(s: FixpointStats) -> dict:
    census = np.asarray(s.census)
    l2m, linfm = float(s.l2_max), float(s.linf_max)
    return {
        "census": {name: int(census[i]) for i, name in enumerate(BASIN_NAMES)},
        "transitions": np.asarray(s.transitions).tolist(),
        "l2_hist": np.asarray(s.l2_hist).tolist(),
        "linf_hist": np.asarray(s.linf_hist).tolist(),
        "l2_max": l2m if math.isfinite(l2m) else None,
        "linf_max": linfm if math.isfinite(linfm) else None,
    }


def window_record(gen_start: int, gen_end: int, win: LineageWindow,
                  stats, capacity: int, next_pid: int,
                  type_names: Optional[Sequence[str]] = None) -> dict:
    """One flushed window as the ``lineage.jsonl`` row the genealogy layer
    reads.  ``stats`` is one :class:`FixpointStats` (homogeneous soup) or
    a per-type sequence (multisoup, with ``type_names`` labels)."""
    births = np.asarray(win.births).reshape(-1, 2).sum(axis=0)
    doc = {
        "kind": "window",
        "gen_start": int(gen_start),
        "gen_end": int(gen_end),
        "edges": window_edge_rows(win, capacity),
        "edges_dropped": int(np.asarray(win.dropped).sum()),
        "births_attack": int(births[0]),
        "births_respawn": int(births[1]),
        "next_pid": int(next_pid),
    }
    if type_names is not None:
        doc["fixpoints_by_type"] = {
            name: _fixpoint_doc(s) for name, s in zip(type_names, stats)}
    else:
        doc["fixpoints"] = _fixpoint_doc(stats)
    return doc


def probe_record(gen_start: int, gen_end: int, stats,
                 type_names: Optional[Sequence[str]] = None) -> dict:
    """Census-only window row for capture-mode chunks (the in-scan carry
    is unavailable there; an end-of-chunk :func:`fixpoint_stats` probe
    stands in — no edges, no pids, transitions from the unknown row)."""
    doc = {"kind": "probe", "gen_start": int(gen_start),
           "gen_end": int(gen_end)}
    if type_names is not None:
        doc["fixpoints_by_type"] = {
            name: _fixpoint_doc(s) for name, s in zip(type_names, stats)}
    else:
        doc["fixpoints"] = _fixpoint_doc(stats)
    return doc


class LineageWriter:
    """Append-only ``lineage.jsonl`` stream next to the ``.traj`` store.

    One JSON object per line: a header row per writer epoch (a fresh run —
    or a resume that could not restore the lineage carry — starts a new
    epoch; pids are unique WITHIN an epoch), then one row per flushed
    window.  A resume that DID restore the carry passes
    ``continue_epoch=True`` and its header extends the previous epoch
    (``"continues": true``) instead of opening a new one.  Writes are
    plain buffered appends meant to ride the ``BackgroundWriter``
    (``submit_or_run(writer, lineage.append, row)``), with a flush per
    row so a killed run keeps every completed window."""

    NAME = "lineage.jsonl"

    def __init__(self, run_dir: str, *, n: int, capacity: int,
                 epsilon: float, resume: bool = False,
                 continue_epoch: bool = False,
                 meta: Optional[dict] = None):
        self.path = os.path.join(run_dir, self.NAME)
        last = None
        if resume and os.path.exists(self.path):
            try:
                with open(self.path) as f:
                    for line in f:
                        try:
                            row = json.loads(line)
                        except json.JSONDecodeError:
                            continue
                        if row.get("kind") == "header":
                            last = int(row.get("epoch", 0))
            except OSError:
                pass
        continues = continue_epoch and last is not None
        self.epoch = last if continues else (0 if last is None else last + 1)
        self._f = open(self.path, "a" if resume else "w")
        if resume:
            # a kill mid-append can leave a torn final line with no
            # newline; writing the header straight after it would glue
            # the two into one unparseable line and collapse the epoch
            # boundary (the new epoch's windows would fall into the old
            # one) — terminate the fragment first
            try:
                with open(self.path, "rb") as f:
                    f.seek(0, os.SEEK_END)
                    if f.tell() > 0:
                        f.seek(-1, os.SEEK_END)
                        if f.read(1) != b"\n":
                            self._f.write("\n")
            except OSError:
                pass
        header = {"kind": "header", "epoch": self.epoch,
                  "continues": continues, "n": int(n),
                  "capacity": int(capacity), "epsilon": float(epsilon),
                  "basins": list(BASIN_NAMES), "edge_kinds": list(EDGE_NAMES)}
        header.update(meta or {})
        self.append(header)

    def append(self, row: dict) -> None:
        self._f.write(json.dumps(row, separators=(",", ":")) + "\n")
        self._f.flush()

    def close(self) -> None:
        self._f.close()


def update_dynamics_registry(registry, row: dict) -> None:
    """Fold one flushed window row into the ``soup_dynamics_*`` metrics."""
    registry.counter("soup_dynamics_windows_total",
                     help="flushed replication-dynamics windows").inc(1)
    if "edges" in row:
        by_kind = {}
        for e in row["edges"]:
            by_kind[e[0]] = by_kind.get(e[0], 0) + 1
        for code, name in enumerate(EDGE_NAMES):
            registry.counter(
                "soup_dynamics_edges_total",
                help="recorded lineage event edges").inc(
                    by_kind.get(code, 0), kind=name)
        registry.counter(
            "soup_dynamics_edges_dropped_total",
            help="event edges lost to window capacity").inc(
                int(row.get("edges_dropped", 0)))
        registry.counter("soup_dynamics_births_total",
                         help="fresh particle instances minted").inc(
                             int(row.get("births_attack", 0)), kind="attack")
        registry.counter("soup_dynamics_births_total",
                         help="fresh particle instances minted").inc(
                             int(row.get("births_respawn", 0)),
                             kind="respawn")
        registry.gauge("soup_dynamics_next_pid",
                       help="global lineage mint counter").set(
                           int(row.get("next_pid", 0)))
    docs = ([(None, row["fixpoints"])] if "fixpoints" in row
            else list(row.get("fixpoints_by_type", {}).items()))
    for tname, doc in docs:
        labels = {"type": tname} if tname else {}
        for name, count in doc.get("census", {}).items():
            registry.gauge("soup_dynamics_basin_particles",
                           help="particles per fixpoint basin").set(
                               int(count), basin=name, **labels)
        trans = doc.get("transitions")
        if trans:
            src_names = ("unknown",) + BASIN_NAMES
            for i, src in enumerate(src_names):
                for j, dst in enumerate(BASIN_NAMES):
                    v = int(trans[i][j])
                    if v:
                        registry.counter(
                            "soup_dynamics_basin_transitions_total",
                            help="window-over-window basin transitions"
                        ).inc(v, src=src, dst=dst, **labels)
        for key, metric in (("l2_max", "soup_dynamics_fixpoint_l2_max"),
                            ("linf_max", "soup_dynamics_fixpoint_linf_max")):
            if doc.get(key) is not None:
                registry.gauge(
                    metric,
                    help="max finite self-application distance").set(
                        float(doc[key]), **labels)


# ---------------------------------------------------------------------------
# lineage-carry checkpoint sidecar (mega-loop resume)
# ---------------------------------------------------------------------------

STATE_NAME = "lineage_state.npz"


def save_lineage_state(run_dir: str, lin, gen: int) -> None:
    """Rolling sidecar next to the orbax checkpoints: the lineage carry at
    generation ``gen`` (atomic replace so a kill never leaves a torn
    file).  ``lin`` may be one :class:`LineageState` or a per-type tuple."""
    # one LineageState (itself a NamedTuple) or a per-type tuple of them
    lins = (lin,) if hasattr(lin, "next_pid") else tuple(lin)
    arrays = {"gen": np.int64(gen), "types": np.int64(len(lins))}
    for t, l in enumerate(lins):
        for field, v in l._asdict().items():
            arrays[f"t{t}_{field}"] = np.asarray(v)
    path = os.path.join(run_dir, STATE_NAME)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
        f.flush()
        os.fsync(f.fileno())  # replace alone doesn't force data to disk;
        # a preemption right after the rename must not leave a torn sidecar
    os.replace(tmp, path)


def load_lineage_state(run_dir: str, expect_gen: int):
    """Restore the sidecar if it matches the resumed generation; ``None``
    (caller starts a fresh epoch) otherwise."""
    path = os.path.join(run_dir, STATE_NAME)
    if not os.path.exists(path):
        return None
    try:
        with np.load(path) as z:
            if int(z["gen"]) != int(expect_gen):
                return None
            lins = tuple(
                LineageState(**{f: jnp.asarray(z[f"t{t}_{f}"])
                                for f in LineageState._fields})
                for t in range(int(z["types"])))
    except (OSError, KeyError, ValueError):
        return None
    return lins if len(lins) > 1 else lins[0]
