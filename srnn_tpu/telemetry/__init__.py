"""srnn_tpu.telemetry — metrics, span tracing, and run heartbeats.

The observability triad for soup evolution at production scale:

  * **Metrics** — soup-science counters accumulated INSIDE the jitted
    generations scan as an extra carry (``device.SoupMetrics``; zero host
    round-trips, flushed every K-generation chunk) plus a host-side typed
    registry (``metrics.MetricsRegistry``) with two sinks: structured
    ``events.jsonl`` rows through ``Experiment.event`` and a
    Prometheus-textfile exposition for scraping long mega runs.  Runtime
    metrics (AOT cache hits, compile seconds, span wall-clock) land on
    the process-wide ``RUNTIME`` registry.
  * **Tracing** — ``span()`` wall-clock blocks layered on
    ``jax.named_scope`` + scalar-readback sync; ``annotate`` for
    zero-cost phase names in profiler traces; ``trace`` re-exported for
    full ``jax.profiler`` captures.
  * **Heartbeats** — fsync'd liveness rows (stage, generation, gens/sec,
    rss, device memory) so a killed run leaves an attributable trail,
    and ``python -m srnn_tpu.telemetry.report <run_dir>`` to render it.
"""

from .device import (N_ACTIONS, N_HEALTH_BUCKETS, HealthStats, SoupMetrics,
                     accumulate_health, accumulate_soup_metrics,
                     count_events, merge_health, merge_soup_metrics,
                     probe_health, psum_health, psum_soup_metrics,
                     zero_health, zero_soup_metrics)
from .metrics import (Counter, Gauge, Histogram, MetricsRegistry, RUNTIME)
from .tracing import Span, SpanStream, annotate, span, trace
from .heartbeat import Heartbeat, device_memory_stats, rss_bytes
from .soup_metrics import (EVENT_COUNTERS, update_class_gauges,
                           update_multi_registry, update_registry)
from .flightrec import (FlightRecorder, StallSentinel, Watchdog,
                        combined_health_summary, health_summary,
                        update_health_gauges, write_triage_bundle)
from .dynamics import (BASIN_NAMES, EDGE_NAMES, FixpointStats, LineageState,
                       LineageWindow, LineageWriter, seed_lineage,
                       seed_lineage_blocks, update_dynamics_registry,
                       window_record)
from .exporter import (HEALTHZ_METRICS, LivePlane, MetricsExporter,
                       healthz_metrics, worker_liveness)
from .timeseries import (MetricHistory, load_history_rows, sparkline,
                         summarize_history)
from .alerts import (AlertEngine, Rule, default_run_rules,
                     default_serve_rules)

__all__ = [
    "N_ACTIONS", "SoupMetrics", "accumulate_soup_metrics", "count_events",
    "merge_soup_metrics", "psum_soup_metrics", "zero_soup_metrics",
    "N_HEALTH_BUCKETS", "HealthStats", "accumulate_health", "merge_health",
    "probe_health", "psum_health", "zero_health",
    "Counter", "Gauge", "Histogram", "MetricsRegistry", "RUNTIME",
    "Span", "SpanStream", "annotate", "span", "trace",
    "Heartbeat", "device_memory_stats", "rss_bytes",
    "EVENT_COUNTERS", "update_class_gauges", "update_multi_registry",
    "update_registry",
    "FlightRecorder", "StallSentinel", "Watchdog",
    "combined_health_summary", "health_summary", "update_health_gauges",
    "write_triage_bundle",
    "BASIN_NAMES", "EDGE_NAMES", "FixpointStats", "LineageState",
    "LineageWindow", "LineageWriter", "seed_lineage", "seed_lineage_blocks",
    "update_dynamics_registry", "window_record",
    "HEALTHZ_METRICS", "LivePlane", "MetricsExporter", "healthz_metrics",
    "worker_liveness",
    "MetricHistory", "load_history_rows", "sparkline", "summarize_history",
    "AlertEngine", "Rule", "default_run_rules", "default_serve_rules",
]
