"""Host-side metrics registry and sinks.

A small, dependency-free implementation of the standard training-stack
metric kinds — :class:`Counter` (monotone totals), :class:`Gauge` (last
value wins), :class:`Histogram` (cumulative buckets + sum/count) — with
label support and two sinks:

  * structured ``events.jsonl`` rows through the existing
    ``Experiment.event`` channel (:meth:`MetricsRegistry.flush_events`):
    one ``{"kind": "metrics", "metrics": {name{labels}: value}}`` record
    per flush, cumulative values so the LAST row of a (possibly killed)
    run is the whole story;
  * a Prometheus textfile exposition (:meth:`MetricsRegistry.write_textfile`)
    for node-exporter-style scraping of long mega runs — written
    atomically (tmp + rename) so a scraper never reads a torn file.

``RUNTIME`` is the process-wide default registry used for host-side
runtime metrics (AOT compile seconds and memo hits from ``utils/aot.py``,
span wall-clock from ``telemetry.tracing``); run loops create their own
registry per run so per-run sinks stay isolated.

All metric names are prefixed ``srnn_`` on export; values live under the
bare name in-process.  This module imports nothing from ``srnn_tpu`` —
the soup-science interpretation of device carries lives in
:mod:`srnn_tpu.telemetry.soup_metrics`.
"""

import json
import math
import os
import tempfile
import threading
from typing import Dict, Iterable, List, Optional, Tuple

_NAMESPACE = "srnn"

LabelKey = Tuple[Tuple[str, str], ...]


def _fsync_dir(path: str) -> None:
    # inlined twin of utils.atomicio.fsync_dir (this module imports
    # nothing from srnn_tpu — see the module docstring): rename alone
    # leaves the directory entry unsynced, so a power loss could
    # resurrect a STALE metrics.prom beside a newer events.jsonl.
    # Fail-soft on filesystems that refuse directory fsync.
    try:
        fd = os.open(path, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def _label_key(labels: Dict[str, str]) -> LabelKey:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _label_value(v: str) -> str:
    # text-format 0.0.4 label escaping: one malformed series makes a
    # textfile collector drop the WHOLE metrics.prom, so arbitrary
    # caller-supplied values (span notes, type names) must be sanitized
    return v.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")


def _label_suffix(key: LabelKey) -> str:
    if not key:
        return ""
    return "{" + ",".join(f'{k}="{_label_value(v)}"' for k, v in key) + "}"


class _Metric:
    kind = "untyped"

    def __init__(self, name: str, help: str = "", unit: str = ""):
        self.name = name
        self.help = help
        self.unit = unit
        self._values: Dict[LabelKey, float] = {}
        # async-safe: the pipeline's background writer resolves registry
        # updates and renders sinks while the run loop keeps recording, so
        # every mutation and snapshot takes the metric's lock
        self._lock = threading.RLock()

    @property
    def full_name(self) -> str:
        return f"{_NAMESPACE}_{self.name}"

    def samples(self) -> Iterable[Tuple[str, float]]:
        """(exposition-suffix, value) pairs, one per label set."""
        with self._lock:
            items = sorted(self._values.items())
        for key, value in items:
            yield _label_suffix(key), value

    def expose(self) -> List[str]:
        lines = [f"# HELP {self.full_name} {self.help}".rstrip(),
                 f"# TYPE {self.full_name} {self.kind}"]
        for suffix, value in self.samples():
            lines.append(f"{self.full_name}{suffix} {_fmt(value)}")
        return lines


def _fmt(v: float) -> str:
    if isinstance(v, float) and v.is_integer() and abs(v) < 2 ** 53:
        return str(int(v))
    return repr(v)


class Counter(_Metric):
    """Monotone total; ``inc`` only (negative increments are a bug)."""
    kind = "counter"

    def inc(self, amount: float = 1, **labels) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative inc {amount}")
        key = _label_key(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + amount

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0)


class Gauge(_Metric):
    """Last-write-wins instantaneous value."""
    kind = "gauge"

    def set(self, value: float, **labels) -> None:
        with self._lock:
            self._values[_label_key(labels)] = value

    def value(self, **labels) -> float:
        with self._lock:
            return self._values.get(_label_key(labels), 0)


#: span/compile wall-clock buckets: 100us .. ~2 min, roughly x4 apart
DEFAULT_BUCKETS = (1e-4, 5e-4, 2e-3, 1e-2, 5e-2, 0.25, 1.0, 4.0, 15.0,
                   60.0, 120.0)


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus semantics: each ``le``
    bucket counts observations <= its bound; ``+Inf`` == count)."""
    kind = "histogram"

    def __init__(self, name: str, help: str = "", unit: str = "",
                 buckets: Tuple[float, ...] = DEFAULT_BUCKETS):
        super().__init__(name, help, unit)
        self.buckets = tuple(sorted(buckets))
        # per label set: [bucket_counts..., +Inf count, sum]
        self._hist: Dict[LabelKey, List[float]] = {}

    def observe(self, value: float, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            h = self._hist.setdefault(key,
                                      [0] * (len(self.buckets) + 1) + [0.0])
            for i, b in enumerate(self.buckets):
                if value <= b:
                    h[i] += 1
            h[len(self.buckets)] += 1  # +Inf
            h[-1] += value

    def count(self, **labels) -> int:
        with self._lock:
            h = self._hist.get(_label_key(labels))
            return int(h[len(self.buckets)]) if h else 0

    def quantile(self, q: float) -> Optional[float]:
        """Upper-bound q-quantile across ALL label sets (bucket merge):
        the smallest bucket bound whose cumulative count covers ``q`` of
        the observations, or ``None`` while empty / when the quantile
        falls in the ``+Inf`` bucket.  Conservative by construction —
        the serve SLO view wants "p95 is at most X", not an
        interpolated guess."""
        with self._lock:
            hists = list(self._hist.values())
        if not hists:
            return None
        total = sum(h[len(self.buckets)] for h in hists)
        if total <= 0:
            return None
        need = q * total
        for i, b in enumerate(self.buckets):
            if sum(h[i] for h in hists) >= need:
                return float(b)
        return None

    def sum(self, **labels) -> float:
        with self._lock:
            h = self._hist.get(_label_key(labels))
            return float(h[-1]) if h else 0.0

    def _snapshot(self):
        with self._lock:
            return sorted((k, list(h)) for k, h in self._hist.items())

    def samples(self):
        # suffix BEFORE the label braces (``name_sum{labels}``) so
        # rows()/flush_events name each series exactly as to_prometheus()
        # exposes it — the two sinks must correlate
        for key, h in self._snapshot():
            yield "_sum" + _label_suffix(key), h[-1]
            yield "_count" + _label_suffix(key), h[len(self.buckets)]

    def expose(self) -> List[str]:
        lines = [f"# HELP {self.full_name} {self.help}".rstrip(),
                 f"# TYPE {self.full_name} {self.kind}"]
        for key, h in self._snapshot():
            for i, b in enumerate(self.buckets):
                lab = _label_suffix(key + (("le", repr(float(b))),))
                lines.append(f"{self.full_name}_bucket{lab} {_fmt(h[i])}")
            inf_lab = _label_suffix(key + (("le", "+Inf"),))
            lines.append(
                f"{self.full_name}_bucket{inf_lab} "
                f"{_fmt(h[len(self.buckets)])}")
            lines.append(f"{self.full_name}_sum{_label_suffix(key)} "
                         f"{_fmt(h[-1])}")
            lines.append(f"{self.full_name}_count{_label_suffix(key)} "
                         f"{_fmt(h[len(self.buckets)])}")
        return lines


class MetricsRegistry:
    """Named, typed metric registry — get-or-create accessors, flat
    snapshot rows, and the two sinks (events.jsonl / Prometheus file)."""

    def __init__(self):
        self._metrics: Dict[str, _Metric] = {}
        self._lock = threading.RLock()  # async-safe get-or-create

    def _get(self, cls, name: str, help: str, unit: str, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, help=help, unit=unit, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"requested {cls.kind}")
            return m

    def counter(self, name: str, help: str = "", unit: str = "") -> Counter:
        return self._get(Counter, name, help, unit)

    def gauge(self, name: str, help: str = "", unit: str = "") -> Gauge:
        return self._get(Gauge, name, help, unit)

    def histogram(self, name: str, help: str = "", unit: str = "",
                  buckets: Tuple[float, ...] = DEFAULT_BUCKETS) -> Histogram:
        return self._get(Histogram, name, help, unit, buckets=buckets)

    # -- snapshots and sinks ---------------------------------------------

    def rows(self) -> Dict[str, float]:
        """Flat ``{exposition-name: value}`` snapshot (cumulative values;
        histograms contribute their ``_sum``/``_count`` series)."""
        out: Dict[str, float] = {}
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            for suffix, value in m.samples():
                out[m.full_name + suffix] = value
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition format (0.0.4) of every metric."""
        lines: List[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            lines.extend(m.expose())
        return "\n".join(lines) + ("\n" if lines else "")

    def write_textfile(self, path: str) -> str:
        """Atomically write the exposition to ``path`` (tmp + fsync +
        rename + parent-directory fsync, so a concurrent scraper never
        sees a torn file and a power loss cannot resurrect a STALE
        snapshot beside a newer events.jsonl — the checkpoint-marker
        discipline).  Returns ``path``."""
        body = self.to_prometheus()
        d = os.path.dirname(os.path.abspath(path))
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".prom_")
        try:
            with os.fdopen(fd, "w") as f:
                f.write(body)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            _fsync_dir(d)
        except BaseException:
            try:
                os.remove(tmp)
            except OSError:
                pass
            raise
        return path

    def flush_events(self, exp, **extra) -> Dict[str, float]:
        """Emit one cumulative-snapshot record through ``exp.event`` (the
        structured ``events.jsonl`` channel).  Returns the snapshot."""
        snap = self.rows()
        exp.event(kind="metrics", metrics=snap, **extra)
        return snap

    def dumps(self) -> str:
        return json.dumps(self.rows(), sort_keys=True)


def quantile_from_times(times, q: float) -> float:
    """Tiny helper for report-side summaries: q-quantile of a list by
    nearest-rank (no numpy dependency in the CLI path)."""
    if not times:
        return math.nan
    xs = sorted(times)
    i = min(len(xs) - 1, max(0, int(math.ceil(q * len(xs))) - 1))
    return xs[i]


#: process-wide default registry for host-side RUNTIME metrics (AOT cache
#: hits / compile seconds, span wall-clock).  Run loops make their own.
RUNTIME = MetricsRegistry()
