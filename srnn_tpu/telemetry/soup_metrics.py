"""Host-side interpretation of the device metrics carry.

Maps the :class:`~srnn_tpu.telemetry.device.SoupMetrics` action histogram
onto named registry counters (the soup-science metrics: attack /
learn_from / train event counts, divergent / zero respawn counts) and
maintains the class-histogram gauges + deltas the mega-run loops flush
every chunk.  Rates are left to readers (the report CLI divides by
``soup_particle_generations_total``), so everything stored is a plain
monotone counter or a last-value gauge.
"""

import math
from typing import Optional, Sequence

import numpy as np

from ..ops.predicates import CLASS_NAMES
from ..soup import ACTION_NAMES
from .device import N_ACTIONS, SoupMetrics
from .metrics import MetricsRegistry

assert len(ACTION_NAMES) == N_ACTIONS, (
    "telemetry.device.N_ACTIONS fell out of sync with soup.ACTION_NAMES")

#: action-code -> (counter name, help).  'none'/'init' are not events.
#: The zero-respawn action is 'zero_dead' (the reference's persisted
#: 'zweo' typo is fixed at the label level; the COUNTER name below never
#: carried it and is unchanged — old events.jsonl rows with the
#: misspelled key are normalized by ``telemetry.report``).
EVENT_COUNTERS = {
    "attacking": ("soup_attacks_total",
                  "particles whose last action was attacking another"),
    "learn_from": ("soup_learns_total",
                   "particles whose last action was imitation SGD"),
    "train_self": ("soup_train_events_total",
                   "particles whose last action was self-training"),
    "divergent_dead": ("soup_respawns_divergent_total",
                       "particles respawned after diverging"),
    "zero_dead": ("soup_respawns_zero_total",
                  "particles respawned after collapsing to zero"),
}


def update_registry(registry: MetricsRegistry, m: SoupMetrics,
                    type_name: Optional[str] = None,
                    n_particles: Optional[int] = None) -> None:
    """Fold one flushed device carry into ``registry``'s counters.

    ``type_name`` labels heterogeneous (multisoup) per-type carries;
    ``n_particles`` additionally advances the particle-generations
    denominator counter so readers can compute per-particle rates.
    """
    labels = {"type": type_name} if type_name else {}
    actions = np.asarray(m.actions)
    gens = int(m.generations)
    registry.counter("soup_generations_total",
                     help="soup generations evolved").inc(gens, **labels)
    if n_particles is not None:
        registry.counter(
            "soup_particle_generations_total",
            help="particles x generations (rate denominator)").inc(
                gens * int(n_particles), **labels)
    for code, action_name in enumerate(ACTION_NAMES):
        named = EVENT_COUNTERS.get(action_name)
        if named is None:
            continue
        name, help_ = named
        registry.counter(name, help=help_).inc(int(actions[code]), **labels)
    # a soup with diverging particles legitimately produces inf/nan train
    # losses; a counter must stay finite and monotone, so count those
    # windows separately instead of poisoning (or crashing) the total
    loss = float(m.loss_sum)
    if math.isfinite(loss) and loss >= 0:
        registry.counter("soup_train_loss_sum",
                         help="summed per-particle train losses").inc(
                             loss, **labels)
    else:
        registry.counter(
            "soup_train_loss_nonfinite_flushes_total",
            help="flush windows whose loss sum was inf/nan (divergence)"
        ).inc(1, **labels)


def update_class_gauges(registry: MetricsRegistry, counts,
                        type_name: Optional[str] = None,
                        prev=None) -> None:
    """Record a (5,) class histogram as gauges — current particle count
    per class plus, when ``prev`` (the previous flush's histogram) is
    given, the delta since then (the chunk-over-chunk drift the science
    watches)."""
    labels = {"type": type_name} if type_name else {}
    counts = np.asarray(counts)
    prev = None if prev is None else np.asarray(prev)
    for i, cls in enumerate(CLASS_NAMES):
        registry.gauge("soup_class_particles",
                       help="particles per class").set(
                           int(counts[i]), cls=cls, **labels)
        if prev is not None:
            registry.gauge(
                "soup_class_delta",
                help="particles-per-class change since last flush").set(
                    int(counts[i]) - int(prev[i]), cls=cls, **labels)


def type_names(config) -> list:
    """Per-type label values for a ``MultiSoupConfig``: the variant name
    when unique, disambiguated by type index otherwise — two same-variant
    subpopulations (e.g. weightwise at two widths) must not silently merge
    their counters under one label."""
    names = [t.variant for t in config.topos]
    if len(set(names)) == len(names):
        return names
    return [f"{v}[{i}]" for i, v in enumerate(names)]


def update_multi_registry(registry: MetricsRegistry,
                          ms: Sequence[SoupMetrics], config) -> None:
    """Per-type carries of a ``MultiSoupConfig`` run, labeled by variant."""
    labels = type_names(config)
    for t, m in enumerate(ms):
        update_registry(registry, m, type_name=labels[t],
                        n_particles=config.sizes[t])


def set_precision_gauges(registry: MetricsRegistry, config) -> None:
    """Run-start gauges for the population's precision mode: storage bits
    per weight and the resulting population bytes (``SoupConfig`` or
    ``MultiSoupConfig``)."""
    bits = {"bf16": 16, "int8": 8}.get(config.population_dtype, 32)
    if hasattr(config, "topos"):
        weights = sum(t.num_weights * n
                      for t, n in zip(config.topos, config.sizes))
    else:
        weights = config.topo.num_weights * config.size
    registry.gauge("soup_precision_weight_bits",
                   help="population storage bits per weight").set(bits)
    # int8's per-particle scale vector is an O(N) float rider next to the
    # O(N*P) codes; the footprint gauge counts the weight storage only
    registry.gauge("soup_precision_population_bytes",
                   help="population storage footprint at the configured "
                   "dtype").set(weights * bits // 8)


def update_fused_counters(registry: MetricsRegistry, generations: int,
                          kernel: bool,
                          type_name: Optional[str] = None) -> None:
    """Per-chunk fused-generation accounting for ``generation_impl='fused'``
    runs: generations executed under the fused spelling, split by whether
    the Mosaic megakernel route was live or the XLA phase-chain fallback
    ran (non-Mosaic backend, or an off-envelope type in the multisoup's
    silent per-type fallback — which is why the heterogeneous loop calls
    this once per TYPE: a mixed-eligibility run must show its fallback
    types, not report the whole chunk as kernel-executed)."""
    labels = {"type": type_name} if type_name else {}
    registry.counter(
        "soup_fused_generations_total",
        help="generations run under generation_impl='fused'").inc(
            int(generations), **labels)
    if not kernel:
        registry.counter(
            "soup_fused_fallback_generations_total",
            help="fused-spelling generations that ran the XLA phase-chain "
            "fallback (no Mosaic backend / off-envelope type)").inc(
                int(generations), **labels)
