"""Continuous profiling plane: always-on host sampling, utilization
decomposition, and anomaly-triggered black-box capture.

The paper's central phenomena — divergence to NaN, zero-collapse, basin
escapes — are transient: by the time an operator reads the alert trail
(PR 15) the moment is gone, and every wedged TPU attempt in BENCH history
died with no record of where host time was going.  This module is the
layer that turns "alert fired" into "here is the stack and the device
state when it did":

  * :class:`SamplingProfiler` — a stdlib sampling profiler: one daemon
    thread walks ``sys._current_frames()`` at ``--profile-hz`` (~50Hz)
    and folds each thread's stack into a bounded per-thread counter
    table, keyed by the thread names the ``spawn_thread`` registry
    assigned (loop / ``<stage>-io`` writer / dispatcher / exporter).  A
    rolling ring keeps the last ``ring_s`` seconds of raw per-tick
    samples for forensic bundles.  ``flush()`` publishes cumulative
    folded output (``profile.folded`` — flamegraph-ready ``stack count``
    lines — and ``profile.jsonl``) through the run's BackgroundWriter,
    so profile I/O obeys the same ordered-host-job discipline as every
    other sink.  The whole plane is host-side: ``--no-profile`` never
    builds it and results are bitwise-identical either way — the
    ``--no-spans``/``--no-costs``/``--no-export`` A/B oracle family.
  * :func:`utilization_from_pipeline` — per-chunk device-busy /
    host-blocked / idle fractions derived from the OverlapMeter's
    attribution row (the ``soup_utilization_*`` gauges): device-busy is
    the device-wait share of the chunk wall (a lower bound on device
    busyness — the host can only observe its own blocking), host-blocked
    is the host-I/O share NOT hidden behind device compute, and idle is
    the remainder.  Rendered in ``watch``/``report`` and exported as a
    Perfetto counter track by ``fleet.perfetto_trace``.
  * :class:`AnomalyCapture` — the black box: hooked on the AlertEngine's
    FIRING edge (rules latch, so one storm = one capture), it atomically
    publishes a bounded ``anomaly/<rule>-<seq>/`` bundle — the sample
    ring's last seconds, a full thread dump (every live thread's current
    stack + registry accounting), a cumulative registry snapshot, the
    recent request exemplars, and an armed ``jax.profiler`` device trace
    on accelerator backends — with FIFO retention (oldest bundle evicted
    past ``max_bundles``).  ``report --profile <run_dir>`` renders top
    stacks + utilization + the capture index.

Daemon-ness of the sampler thread is deliberate (whitelisted in the
thread-hygiene gate): it is a forensic observer of threads that may be
wedged, owns no buffered I/O (flushes ride the run's writer), and a
non-daemon spelling would hang interpreter exit on the very wedge the
profiler exists to explain.

Deliberately NOT captured: population arrays (the watchdog's triage
bundles own state snapshots), per-sample timestamps finer than the tick,
and anything requiring a device round-trip — a capture must cost
milliseconds even when the device is the thing that is broken.
"""

from __future__ import annotations

import json
import os
import shutil
import sys
import threading
import time
from collections import Counter, deque
from typing import Any, Dict, List, Optional

#: run-dir artifact names (cumulative, atomically rewritten per flush)
PROFILE_FOLDED_NAME = "profile.folded"
PROFILE_JSONL_NAME = "profile.jsonl"
#: bundle subdirectory under the run dir
ANOMALY_DIR = "anomaly"


def _frame_token(frame) -> str:
    """One fold-stable frame label: ``file.func`` (no line numbers —
    they churn the bounded tables; the thread dump keeps them)."""
    code = frame.f_code
    base = os.path.basename(code.co_filename)
    if base.endswith(".py"):
        base = base[:-3]
    return f"{base}.{code.co_name}"


def _fold_stack(frame, max_depth: int) -> str:
    """Root-first ``;``-joined folded stack of one frame chain, deeper
    chains truncated root-side (the leaf frames are the interesting
    half) behind a ``...`` marker."""
    tokens: List[str] = []
    while frame is not None:
        tokens.append(_frame_token(frame))
        frame = frame.f_back
    tokens.reverse()  # root first, flamegraph convention
    if len(tokens) > max_depth:
        tokens = ["..."] + tokens[-max_depth:]
    return ";".join(tokens)


def _raw_stack(frame) -> List[str]:
    """Leaf-first frame list WITH file:line — the thread-dump view."""
    out: List[str] = []
    while frame is not None:
        code = frame.f_code
        out.append(f"{code.co_name} "
                   f"({os.path.basename(code.co_filename)}:"
                   f"{frame.f_lineno})")
        frame = frame.f_back
    return out


def thread_dump() -> Dict[str, Any]:
    """Full point-in-time dump of every live thread: current stack
    (leaf-first, with file:line), daemon-ness, and whether the thread is
    accounted for in the ``spawn_thread`` join-on-exit registry.  Pure
    host reads — callable even mid-wedge, from any thread."""
    from ..utils.pipeline import live_threads

    registered = {id(t) for t in live_threads()}
    frames = sys._current_frames()
    threads = []
    for t in threading.enumerate():
        threads.append({
            "name": t.name,
            "ident": t.ident,
            "daemon": t.daemon,
            "alive": t.is_alive(),
            "registered": id(t) in registered,
            "stack": _raw_stack(frames.get(t.ident)),
        })
    return {"t": round(time.time(), 3), "n_threads": len(threads),
            "threads": sorted(threads, key=lambda d: d["name"])}


class SamplingProfiler:
    """The always-on host sampler.

    >>> prof = SamplingProfiler(hz=50.0, ring_s=30.0)
    >>> prof.start()
    >>> ...                      # run; tables fold in the background
    >>> prof.flush(run_dir, writer)   # cumulative folded output
    >>> prof.stop()

    Bounds: each thread's fold table holds at most ``max_stacks``
    distinct stacks — overflow folds into an ``<overflow>`` bucket and
    counts ``stacks_dropped`` (the profile degrades to a coarser view,
    never grows without bound).  The raw-sample ring holds
    ``hz * ring_s`` ticks (one row per tick, all threads folded in).
    """

    #: the sampler never profiles itself or other srnn observer threads
    #: whose steady-state is a timed wait (pure noise in the tables)
    THREAD_NAME = "srnn-profiler"

    def __init__(self, hz: float = 50.0, ring_s: float = 30.0,
                 max_stacks: int = 512, max_depth: int = 48):
        self.hz = max(1.0, float(hz))
        self.ring_s = max(1.0, float(ring_s))
        self.max_stacks = max(16, int(max_stacks))
        self.max_depth = max(4, int(max_depth))
        self._interval = 1.0 / self.hz
        self._lock = threading.Lock()
        self._tables: Dict[str, Counter] = {}
        self._ring: "deque[dict]" = deque(
            maxlen=max(1, int(self.hz * self.ring_s)))
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._t0 = time.time()
        self.samples = 0          # ticks taken
        self.overruns = 0         # ticks that missed their deadline
        self.stacks_dropped = 0   # folds past the per-thread bound
        # counter-delta bookkeeping: update_gauges advances the registry
        # counters by delta so repeated folds stay monotone
        self._counted = {"samples": 0, "overruns": 0, "stacks_dropped": 0}

    # -- the sampling loop ------------------------------------------------

    def start(self) -> "SamplingProfiler":
        """Spawn the sampler daemon thread (idempotent)."""
        if self._thread is not None:
            return self
        from ..utils.pipeline import spawn_thread

        # daemon by design: this thread observes threads that may be
        # wedged and owns no buffered I/O — see the module docstring and
        # the thread-hygiene whitelist entry
        self._thread = spawn_thread(self._run, name=self.THREAD_NAME,
                                    daemon=True)
        return self

    def _run(self) -> None:
        own = threading.get_ident()
        next_tick = time.perf_counter()
        while not self._stop.is_set():
            next_tick += self._interval
            self._sample_once(own)
            delay = next_tick - time.perf_counter()
            if delay <= 0:
                # the tick overran its budget (a long frame walk under a
                # contended GIL); resynchronize instead of spiraling
                with self._lock:
                    self.overruns += 1
                next_tick = time.perf_counter()
                continue
            self._stop.wait(delay)

    def _sample_once(self, own_ident: int) -> None:
        names = {t.ident: t.name for t in threading.enumerate()}
        frames = sys._current_frames()
        stacks: Dict[str, str] = {}
        for ident, frame in frames.items():
            if ident == own_ident:
                continue
            name = names.get(ident, f"thread-{ident}")
            stacks[name] = _fold_stack(frame, self.max_depth)
        row = {"t": round(time.time(), 4), "stacks": stacks}
        with self._lock:
            self.samples += 1
            self._ring.append(row)
            for name, folded in stacks.items():
                table = self._tables.setdefault(name, Counter())
                if folded not in table and len(table) >= self.max_stacks:
                    self.stacks_dropped += 1
                    table["<overflow>"] += 1
                else:
                    table[folded] += 1

    def stop(self) -> None:
        """Stop sampling and join the sampler thread (idempotent; the
        join is bounded — a daemon observer must never block teardown)."""
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=max(1.0, 4 * self._interval))

    def __enter__(self) -> "SamplingProfiler":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.stop()

    # -- reads ------------------------------------------------------------

    def tables(self) -> Dict[str, Dict[str, int]]:
        """Per-thread folded-stack counts (copies, safe to mutate)."""
        with self._lock:
            return {name: dict(c) for name, c in self._tables.items()}

    def ring_tail(self, seconds: Optional[float] = None) -> List[dict]:
        """Raw tick rows of the last ``seconds`` (default: the whole
        ring), oldest first."""
        with self._lock:
            rows = list(self._ring)
        if seconds is None:
            return rows
        cutoff = time.time() - max(0.0, float(seconds))
        return [r for r in rows if r["t"] >= cutoff]

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "hz": self.hz,
                "uptime_s": round(time.time() - self._t0, 3),
                "samples": self.samples,
                "overruns": self.overruns,
                "stacks_dropped": self.stacks_dropped,
                "threads": len(self._tables),
                "stacks": sum(len(c) for c in self._tables.values()),
                "ring_rows": len(self._ring),
            }

    # -- metrics + flushes ------------------------------------------------

    def update_gauges(self, registry) -> None:
        """Fold the sampler's own accounting into a run registry (the
        ``soup_profile_*`` family).  Counters advance by delta so
        repeated folds stay monotone; the counters are registered
        eagerly (inc 0) so a quiet profiler still exposes the family."""
        s = self.stats()
        for key, name, help_ in (
                ("samples", "soup_profile_samples_total",
                 "profiler stack-sample ticks taken"),
                ("overruns", "soup_profile_overruns_total",
                 "sampler ticks that missed their deadline"),
                ("stacks_dropped", "soup_profile_stacks_dropped_total",
                 "stack folds past the bounded per-thread table")):
            delta = s[key] - self._counted[key]
            self._counted[key] = s[key]
            registry.counter(name, help=help_).inc(max(0, delta))
        registry.gauge("soup_profile_threads",
                       help="threads with folded-stack tables").set(
                           s["threads"])
        registry.gauge("soup_profile_stacks",
                       help="distinct folded stacks tracked").set(
                           s["stacks"])

    def folded_lines(self) -> List[str]:
        """The flamegraph exchange format: ``thread;frame;... count``."""
        lines = []
        for name, table in sorted(self.tables().items()):
            for folded, n in sorted(table.items(),
                                    key=lambda kv: (-kv[1], kv[0])):
                lines.append(f"{name};{folded} {n}")
        return lines

    def write_files(self, run_dir: str) -> None:
        """Atomically (re)write the cumulative profile artifacts — the
        job :meth:`flush` routes through the run's writer."""
        from ..utils.atomicio import atomic_write_text

        atomic_write_text(os.path.join(run_dir, PROFILE_FOLDED_NAME),
                          "\n".join(self.folded_lines()) + "\n")
        rows = [json.dumps({"kind": "profile_meta", **self.stats()})]
        for name, table in sorted(self.tables().items()):
            for folded, n in sorted(table.items(),
                                    key=lambda kv: (-kv[1], kv[0])):
                rows.append(json.dumps(
                    {"thread": name, "stack": folded, "count": n}))
        atomic_write_text(os.path.join(run_dir, PROFILE_JSONL_NAME),
                          "\n".join(rows) + "\n")

    def flush(self, run_dir: str, writer=None, registry=None) -> None:
        """One flush turn: fold the profiler gauges (inline — registry
        mutations are lock-per-metric) and ride the artifact rewrite on
        the run's writer in submission order."""
        from ..utils.pipeline import submit_or_run

        if registry is not None:
            self.update_gauges(registry)
        submit_or_run(writer, self.write_files, run_dir)


# ---------------------------------------------------------------------------
# utilization decomposition
# ---------------------------------------------------------------------------


def utilization_from_pipeline(row: Dict[str, Any]) -> Dict[str, float]:
    """Device-busy / host-blocked / idle fractions of one chunk, from
    the OverlapMeter attribution row (``wall_s``/``device_wait_s``/
    ``host_io_s``).

    Formula (documented in DESIGN §25): ``device_busy`` is the
    device-wait share of the wall — the host-observable LOWER bound on
    device busyness; ``host_blocked`` is the host-I/O share that could
    NOT have been hidden behind device compute
    (``min(host_io, wall - device_wait) / wall``); ``idle`` is the
    remainder — an upper bound on true device idleness.  All three sum
    to 1 (clamped)."""
    wall = float(row.get("wall_s", 0.0) or 0.0)
    if wall <= 0:
        return {"device_busy": 0.0, "host_blocked": 0.0, "idle": 0.0}
    wait = max(0.0, float(row.get("device_wait_s", 0.0) or 0.0))
    io = max(0.0, float(row.get("host_io_s", 0.0) or 0.0))
    busy = min(1.0, wait / wall)
    blocked = min(min(io, max(0.0, wall - wait)) / wall, 1.0 - busy)
    idle = max(0.0, 1.0 - busy - blocked)
    return {"device_busy": round(busy, 4),
            "host_blocked": round(blocked, 4),
            "idle": round(idle, 4)}


def update_utilization_gauges(registry,
                              pipeline_row: Dict[str, Any]
                              ) -> Dict[str, float]:
    """Export one chunk's utilization decomposition as the
    ``soup_utilization_*`` gauges (unlabeled — a run dir is one stage)
    and return the fractions (the chunk row / Perfetto counter-track
    source)."""
    u = utilization_from_pipeline(pipeline_row)
    g = registry.gauge
    g("soup_utilization_device_busy",
      help="device-busy fraction of the last chunk (host-observed "
           "lower bound: device-wait share of wall)").set(
          u["device_busy"])
    g("soup_utilization_host_blocked",
      help="host-blocked fraction of the last chunk (host I/O not "
           "hidden behind device compute)").set(u["host_blocked"])
    g("soup_utilization_idle",
      help="idle fraction of the last chunk (upper bound on device "
           "idleness: 1 - busy - blocked)").set(u["idle"])
    return u


# ---------------------------------------------------------------------------
# anomaly-triggered capture
# ---------------------------------------------------------------------------


class AnomalyCapture:
    """Black-box capture on the alert engine's firing edge.

    Hooked wherever transitions surface (``LivePlane.sample``'s writer
    job in the mega loops, ``ExperimentService._sample_live`` in the
    serve tier): each ``state == "firing"`` transition publishes one
    bounded bundle under ``<run_dir>/anomaly/<rule>-<seq>/``:

    * ``capture.json`` — the transition, profiler stats, backend
      metadata (always lands; everything else is best-effort with
      errors recorded here).
    * ``samples.jsonl`` — the profiler ring's last ``ring_s`` seconds.
    * ``threads.json`` — :func:`thread_dump` at the edge.
    * ``metrics.json`` — cumulative registry snapshot.
    * ``exemplars.jsonl`` — copy of the run's recent request exemplars.
    * ``trace/`` — an armed ``jax.profiler`` device trace on
      accelerator backends, covering roughly the interval to the NEXT
      sample turn (:meth:`turn` closes it, the watchdog's window
      discipline).

    Publication is atomic: the bundle is assembled in a dot-tmp sibling
    and ``os.rename``d into place, so a concurrent ``report --profile``
    never reads a half-written bundle.  Retention is FIFO: past
    ``max_bundles`` the oldest bundle is evicted (an alert storm tells
    its story in N bundles, not N thousand).  Fail-soft throughout —
    capture must never take down the run it is explaining."""

    def __init__(self, run_dir: str, profiler: Optional[SamplingProfiler]
                 = None, registry=None, max_bundles: int = 4,
                 ring_s: float = 30.0, device_trace: bool = True):
        self.run_dir = run_dir
        self.profiler = profiler
        self.registry = registry
        self.max_bundles = max(1, int(max_bundles))
        self.ring_s = float(ring_s)
        self.device_trace = bool(device_trace)
        self.captures: List[str] = []
        self.errors = 0
        self._seq = 0
        self._lock = threading.Lock()
        self._trace_active = False

    # -- the hook ---------------------------------------------------------

    def on_transitions(self, transitions: List[dict], **context) -> None:
        """One sample turn's worth of alert transitions: close any trace
        window armed by the previous firing edge, then capture each new
        firing edge (rules latch upstream, so a sustained condition
        captures exactly once)."""
        self.turn()
        for t in transitions or []:
            if t.get("state") == "firing":
                try:
                    self.capture(t, **context)
                except Exception as e:  # forensic, never load-bearing
                    self.errors += 1
                    print(f"anomaly capture failed for "
                          f"{t.get('rule')}: {type(e).__name__}: {e}",
                          file=sys.stderr, flush=True)

    def capture(self, transition: dict, **context) -> str:
        """Publish one bundle for a firing transition; returns its path."""
        rule = str(transition.get("rule", "anomaly")).replace("/", "_")
        with self._lock:
            seq = self._seq
            self._seq += 1
        root = os.path.join(self.run_dir, ANOMALY_DIR)
        os.makedirs(root, exist_ok=True)
        final = os.path.join(root, f"{rule}-{seq:04d}")
        while os.path.exists(final):  # a restarted attempt resumes seq
            with self._lock:
                seq = self._seq
                self._seq += 1
            final = os.path.join(root, f"{rule}-{seq:04d}")
        tmp = os.path.join(root, f".tmp-{rule}-{seq:04d}-{os.getpid()}")
        os.makedirs(tmp)

        doc: Dict[str, Any] = {
            "rule": rule,
            "seq": seq,
            "time": round(time.time(), 3),
            "transition": dict(transition),
            "context": {k: v for k, v in context.items() if v is not None},
            "ring_s": self.ring_s,
        }
        errors: Dict[str, str] = {}
        if self.profiler is not None:
            doc["profiler"] = self.profiler.stats()
            try:
                with open(os.path.join(tmp, "samples.jsonl"), "w") as f:
                    for row in self.profiler.ring_tail(self.ring_s):
                        f.write(json.dumps(row) + "\n")
            except OSError as e:
                errors["samples"] = str(e)
        try:
            with open(os.path.join(tmp, "threads.json"), "w") as f:
                json.dump(thread_dump(), f, indent=1)
        except Exception as e:
            errors["threads"] = f"{type(e).__name__}: {e}"
        if self.registry is not None:
            try:
                with open(os.path.join(tmp, "metrics.json"), "w") as f:
                    json.dump(self.registry.rows(), f, indent=1,
                              sort_keys=True)
            except Exception as e:
                errors["metrics"] = f"{type(e).__name__}: {e}"
        from .exemplars import EXEMPLARS_NAME

        ex_src = os.path.join(self.run_dir, EXEMPLARS_NAME)
        if os.path.exists(ex_src):
            try:
                shutil.copy(ex_src, os.path.join(tmp, EXEMPLARS_NAME))
            except OSError as e:
                errors["exemplars"] = str(e)
        from .flightrec import _backend_metadata

        doc["backend"] = _backend_metadata()
        if errors:
            doc["errors"] = errors
        with open(os.path.join(tmp, "capture.json"), "w") as f:
            json.dump(doc, f, indent=1, default=str)
        os.rename(tmp, final)  # atomic publish

        self.captures.append(final)
        if self.registry is not None:
            self.registry.counter(
                "soup_anomaly_captures_total",
                help="anomaly bundles captured on alert firing "
                     "edges").inc(1, rule=rule)
        self._arm_trace(os.path.join(final, "trace"),
                        doc["backend"].get("backend"))
        self._retain(root)
        return final

    def _retain(self, root: str) -> None:
        """FIFO eviction past the bundle bound (oldest by mtime)."""
        try:
            dirs = [os.path.join(root, d) for d in os.listdir(root)
                    if not d.startswith(".")
                    and os.path.isdir(os.path.join(root, d))]
        except OSError:
            return
        if len(dirs) <= self.max_bundles:
            return
        dirs.sort(key=lambda p: os.path.getmtime(p))
        for victim in dirs[:len(dirs) - self.max_bundles]:
            try:
                shutil.rmtree(victim)
            except OSError:
                pass

    # -- the armed device-trace window ------------------------------------

    def _arm_trace(self, path: str, backend: Optional[str]) -> None:
        """Arm a ``jax.profiler`` trace into the bundle on accelerator
        backends (a CPU trace is all host anyway — the sampler already
        has that).  One window at a time; :meth:`turn` closes it."""
        if not self.device_trace or self._trace_active:
            return
        if backend in (None, "cpu"):
            return
        try:
            import jax

            jax.profiler.start_trace(path)
            self._trace_active = True
        except Exception:
            pass  # a broken profiler must never break the run

    def turn(self) -> None:
        """Close a trace window armed by the previous firing edge (the
        sample cadence bounds the window — the watchdog's
        ``chunk_boundary`` discipline)."""
        self.stop_trace()

    def stop_trace(self) -> None:
        if not self._trace_active:
            return
        self._trace_active = False
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:
            pass

    def close(self) -> None:
        """Teardown: close any armed trace window (idempotent)."""
        self.stop_trace()


def capture_index(run_dir: str) -> List[Dict[str, Any]]:
    """The run's published anomaly bundles, oldest first: bundle name,
    rule/seq/time from capture.json, and which artifacts landed.  Used
    by ``report --profile`` and the archive ingester (presence joins the
    run summary row).  Dot-tmp assembly dirs are invisible by
    construction."""
    root = os.path.join(run_dir, ANOMALY_DIR)
    if not os.path.isdir(root):
        return []
    out = []
    for name in sorted(os.listdir(root)):
        path = os.path.join(root, name)
        if name.startswith(".") or not os.path.isdir(path):
            continue
        entry: Dict[str, Any] = {"name": name, "path": os.path.abspath(path)}
        try:
            with open(os.path.join(path, "capture.json")) as f:
                doc = json.load(f)
            entry.update({k: doc.get(k) for k in
                          ("rule", "seq", "time", "context")})
            entry["profiler"] = doc.get("profiler")
        except (OSError, json.JSONDecodeError):
            entry["unreadable"] = True
        for artifact in ("samples.jsonl", "threads.json", "metrics.json",
                         "exemplars.jsonl"):
            entry[artifact.split(".")[0]] = os.path.exists(
                os.path.join(path, artifact))
        trace_dir = os.path.join(path, "trace")
        entry["trace"] = os.path.isdir(trace_dir) \
            and any(os.scandir(trace_dir))
        out.append(entry)
    return sorted(out, key=lambda e: (e.get("time") or 0, e["name"]))
