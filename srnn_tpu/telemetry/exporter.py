"""Live metrics endpoint: an OpenMetrics-style HTTP exporter.

Every telemetry surface before this module is pull-from-disk: the
``metrics.prom`` textfile is a point-in-time snapshot the chunk finisher
publishes, ``events.jsonl`` needs a reader on the same filesystem, and
``watch`` polls both.  The exporter is the live half — a stdlib
``http.server`` on a ``spawn_thread`` serving THE SAME per-run
:class:`~srnn_tpu.telemetry.metrics.MetricsRegistry` the sinks flush, so
a scrape at a round boundary and the on-disk ``metrics.prom`` agree by
construction (one registry, two views).

Endpoints (GET only):

  * ``/metrics`` — the registry's Prometheus text exposition (format
    0.0.4, the dialect every OpenMetrics scraper ingests), rendered
    per-request from the live registry.  Each scrape counts into
    ``soup_scrapes_total`` AFTER its body renders, so a response never
    includes its own scrape.
  * ``/healthz`` — one JSON liveness object from the caller-supplied
    ``healthz()`` provider (plus ``uptime_s``/``port``/``scrapes``
    stamped here); ``ok: false`` answers 503 so a plain HTTP prober
    needs no JSON parsing.  The distributed primary's provider
    aggregates worker liveness from the PR 12 heartbeat lanes via
    :func:`worker_liveness` — file mtime reads only, so the
    no-collectives-off-the-loop rule (DESIGN §16) holds trivially.

Threading: the accept/serve loop runs on one registered
``spawn_thread``; per-request handler threads are stdlib
``ThreadingHTTPServer`` internals, marked daemon so a scraper that
connects and stalls can never hang ``close()`` (handlers own no
buffered I/O — every sink write belongs to the run's BackgroundWriter).
The registry itself is lock-per-metric, so scrapes concurrent with the
run loop's mutations always see a consistent per-series value.

The whole plane is host-side: ``--no-export`` (the mega loops' A/B
oracle) never builds it, and results are bitwise-identical either way —
tested, like ``--no-spans`` and ``--no-costs`` before it.
"""

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, Optional

#: registry names a /healthz body surfaces as its ``metrics`` field (the
#: scraped-endpoint allowlist): every entry must exist in
#: ``telemetry.names.CANONICAL_METRICS`` — the srnnlint metric-names
#: pass (M006) enforces it, the inverse of the M005 liveness check.
HEALTHZ_METRICS = (
    "heartbeat_generation",
    "gens_per_sec",
    "serve_queue_depth",
    "soup_health_nan_frac",
    "soup_alerts_active",
)

#: exposition content type (Prometheus text format 0.0.4 — the dialect
#: OpenMetrics scrapers ingest; matches what metrics.prom holds)
_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def healthz_metrics(registry) -> Dict[str, float]:
    """The :data:`HEALTHZ_METRICS` slice of one registry's flat rows —
    what a /healthz provider embeds so a single probe answers "is it up
    AND roughly where is it" without a full scrape."""
    rows = registry.rows()
    out: Dict[str, float] = {}
    for name in HEALTHZ_METRICS:
        prefix = f"srnn_{name}"
        for key, value in rows.items():
            if key == prefix or key.startswith(prefix + "{"):
                out[key] = value
    return out


def worker_liveness(run_dir: str, num_processes: int,
                    stale_after_s: float = 120.0) -> Dict[str, dict]:
    """Per-process liveness from the heartbeat lanes: seconds since each
    process's event file was last written (process 0's ``events.jsonl``,
    workers' ``events-p<i>.jsonl``).  Pure ``mtime`` reads — callable
    from any thread, never a collective.  A missing file or an age past
    ``stale_after_s`` marks that process ``ok: false``."""
    from .fleet import event_paths

    paths = event_paths(run_dir)
    now = time.time()
    out: Dict[str, dict] = {}
    for p in range(max(1, int(num_processes))):
        path = paths.get(p)
        try:
            age = round(now - os.path.getmtime(path), 1) \
                if path is not None else None
        except OSError:
            age = None
        out[str(p)] = {"age_s": age,
                       "ok": age is not None and age <= stale_after_s}
    return out


class _Handler(BaseHTTPRequestHandler):
    # per-request logging is noise for a scrape endpoint; failures
    # surface as HTTP statuses, not stderr lines
    def log_message(self, fmt, *args):  # noqa: ARG002
        pass

    def _send(self, code: int, body: bytes, content_type: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler contract
        exporter: "MetricsExporter" = self.server.exporter  # type: ignore
        path = self.path.split("?", 1)[0]
        # count AFTER rendering (a response never includes its own
        # scrape) but BEFORE sending: once the client has the response
        # it may scrape again immediately, and that next body must see
        # this increment
        if path == "/metrics":
            body = exporter.registry.to_prometheus().encode("utf-8")
            exporter.count_scrape("metrics")
            self._send(200, body, _CONTENT_TYPE)
        elif path == "/healthz":
            doc = exporter.healthz_doc()
            body = (json.dumps(doc, default=str) + "\n").encode("utf-8")
            exporter.count_scrape("healthz")
            self._send(200 if doc.get("ok", True) else 503, body,
                       "application/json")
        else:
            self._send(404, b"not found\n", "text/plain; charset=utf-8")


class _Server(ThreadingHTTPServer):
    #: handler threads are stdlib internals serving one short response
    #: each and own no buffered I/O; daemon-ness means a stalled scraper
    #: connection cannot hang exporter.close() (which joins only the
    #: registered accept-loop thread)
    daemon_threads = True
    allow_reuse_address = True


class MetricsExporter:
    """One process's live ``/metrics`` + ``/healthz`` endpoint.

    >>> ex = MetricsExporter(registry, port=9100,
    ...                      healthz=lambda: {"ok": True, "stage": "run"})
    >>> ex.port        # the bound port (ephemeral when constructed with 0)
    >>> ex.close()     # shutdown + join, idempotent

    ``port=0`` binds an OS-assigned ephemeral port (tests); CLI callers
    gate on their ``--metrics-port`` flag BEFORE constructing (0 = off is
    the flag's contract, not this class's).  ``healthz`` is a zero-arg
    callable returning the liveness dict (``ok`` defaults true); it runs
    on handler threads, so providers must only read thread-safe state
    (registry reads, file mtimes, alert-engine snapshots all qualify).
    """

    def __init__(self, registry, port: int = 0, host: str = "127.0.0.1",
                 healthz: Optional[Callable[[], dict]] = None):
        from ..utils.pipeline import spawn_thread

        self.registry = registry
        self._healthz = healthz
        self._t0 = time.monotonic()
        self._lock = threading.Lock()
        self._scrapes = 0
        self._closed = False
        self._server = _Server((host, int(port)), _Handler)
        self._server.exporter = self  # type: ignore[attr-defined]
        self.host = host
        self.port = int(self._server.server_address[1])
        self._thread = spawn_thread(self._server.serve_forever,
                                    name=f"srnn-metrics-exporter-{self.port}")

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def count_scrape(self, endpoint: str) -> None:
        with self._lock:
            self._scrapes += 1
        self.registry.counter(
            "soup_scrapes_total",
            help="HTTP scrapes served by the live exporter").inc(
                1, endpoint=endpoint)

    @property
    def scrapes(self) -> int:
        with self._lock:
            return self._scrapes

    def healthz_doc(self) -> dict:
        doc = {"ok": True}
        if self._healthz is not None:
            try:
                doc.update(self._healthz() or {})
            except Exception as e:  # a broken provider is itself unhealth
                doc = {"ok": False,
                       "error": f"healthz provider: {type(e).__name__}: {e}"}
        doc.setdefault("uptime_s", round(time.monotonic() - self._t0, 1))
        doc.setdefault("port", self.port)
        doc.setdefault("scrapes", self.scrapes)
        return doc

    def close(self) -> None:
        """Stop serving and join the accept thread; idempotent."""
        if self._closed:
            return
        self._closed = True
        self._server.shutdown()
        self._thread.join()
        self._server.server_close()

    def __enter__(self) -> "MetricsExporter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class LivePlane:
    """The composed live telemetry plane of one process: the history
    ring (:class:`~srnn_tpu.telemetry.timeseries.MetricHistory`), the
    alert engine (:class:`~srnn_tpu.telemetry.alerts.AlertEngine`,
    primary-only in distributed runs — one alert stream per run), and
    the optional HTTP exporter.  ``sample()`` is the once-per-chunk (or
    once-per-dispatch) turn: ring + jsonl row, then rule evaluation,
    with every transition emitted as a ``{"kind": "alert"}`` event row —
    all as ONE ordered job on the run's BackgroundWriter, so an alert
    can never cite registry state newer than its chunk.  An optional
    :class:`~srnn_tpu.telemetry.profiler.AnomalyCapture` rides the same
    job: firing edges publish their black-box bundle from the writer
    thread, ordered against the alert rows that cite them."""

    def __init__(self, history=None, engine=None, exporter=None,
                 capture=None):
        self.history = history
        self.engine = engine
        self.exporter = exporter
        self.capture = capture

    def sample(self, exp, writer=None, **context) -> None:
        from ..utils.pipeline import submit_or_run

        def job():
            if self.history is not None:
                self.history.sample()
            transitions = []
            if self.engine is not None:
                for transition in self.engine.evaluate():
                    exp.event(kind="alert", **context, **transition)
                    transitions.append(transition)
            if self.capture is not None:
                self.capture.on_transitions(transitions, **context)

        submit_or_run(writer, job)

    def active_alerts(self):
        return self.engine.active() if self.engine is not None else []

    def close(self) -> None:
        """Exporter first (no scrape may outlive the registry's run),
        then the history file.  Call AFTER the run's writer drained —
        queued sample jobs reference the history handle."""
        if self.exporter is not None:
            self.exporter.close()
        if self.history is not None:
            self.history.close()
        if self.capture is not None:
            self.capture.close()
