"""Offline genealogy reconstruction from a run's ``lineage.jsonl`` stream.

The host-side analysis half of the replication-dynamics observatory
(:mod:`srnn_tpu.telemetry.dynamics` is the device half): reads the
append-only window stream a ``--lineage`` mega run leaves next to its
``.traj`` store and reconstructs the ancestry forest —

  * **forest**: every pid with its parent pid, birth generation and mint
    kind (``seed`` / ``attack`` / ``respawn``); attack edges are the
    lineage links (the attacker reproduced onto the victim's slot),
    respawns and the seed population are roots.
  * **dominant-lineage table**: live descendants and total mints per
    root, the "which lineage took over the soup" ranking.
  * **clone-survival curve**: lifespan distribution of terminated
    instances (birth → overwrite/respawn generation).
  * **attack / imitation graph stats**: out-degree distributions and the
    top attackers/teachers.
  * **basin-transition matrix** and the **fixpoint census trajectory**
    summed/collected over windows.

Edge buffers are fixed-capacity samples (``edges_dropped`` > 0 on a
window means the graph is subsampled for that window — counts become
lower bounds; the census/births/transition numbers are always exact
because they are mask-sums, not buffer reads).  A stream may contain
several epochs (a resume that could not restore the lineage carry starts
a new header); pids are unique within an epoch, so all per-pid analysis
is per-epoch and the CLI reports the last (current) epoch by default.

Rendered by ``python -m srnn_tpu.telemetry.report --dynamics <run_dir>``.
"""

import json
import os
from typing import Dict, List, Optional, Tuple

from .dynamics import (BASIN_NAMES, EDGE_ATTACK, EDGE_LEARN, EDGE_RESPAWN,
                       LineageWriter)

#: mint kinds of a forest node
KIND_SEED, KIND_ATTACK, KIND_RESPAWN = "seed", "attack", "respawn"


def load_lineage(path: str) -> List[dict]:
    """Parse a ``lineage.jsonl`` (file path or run dir) into epochs:
    ``[{"header": {...}, "windows": [row, ...]}, ...]``.  Torn tails of a
    killed run are skipped like every other jsonl reader in the package."""
    if os.path.isdir(path):
        path = os.path.join(path, LineageWriter.NAME)
    epochs: List[dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if row.get("kind") == "header":
                if row.get("continues") and epochs:
                    # a resume that restored the lineage carry: the same
                    # epoch keeps accumulating under its original header
                    continue
                epochs.append({"header": row, "windows": []})
            elif epochs:
                epochs[-1]["windows"].append(row)
    if not epochs:
        raise ValueError(f"{path}: no lineage header rows")
    return epochs


class Forest:
    """Ancestry forest of one epoch: pid -> (parent, birth, kind), plus
    termination generations and the imitation edge list."""

    def __init__(self) -> None:
        self.parent: Dict[int, int] = {}
        self.birth: Dict[int, Optional[int]] = {}
        self.kind: Dict[int, str] = {}
        self.ended: Dict[int, int] = {}
        self.learn_edges: List[Tuple[int, int, int]] = []  # (gen, teacher, student)
        self.dropped = 0
        self._root_memo: Dict[int, int] = {}

    def add(self, pid: int, parent: int, birth: Optional[int],
            kind: str) -> None:
        self.parent[pid] = parent
        self.birth[pid] = birth
        self.kind[pid] = kind

    def _ensure(self, pid: int) -> None:
        # a pid referenced by a surviving edge whose own mint edge was
        # dropped: keep it as an implicit root so walks never KeyError
        if pid >= 0 and pid not in self.parent:
            self.add(pid, -1, None, KIND_SEED)

    def root(self, pid: int) -> int:
        """Walk parents to the founding root (memoized)."""
        chain = []
        while pid not in self._root_memo:
            chain.append(pid)
            self._ensure(pid)
            parent = self.parent.get(pid, -1)
            if parent < 0 or parent not in self.parent:
                self._root_memo[pid] = pid
                break
            pid = parent
        root = self._root_memo[pid if pid in self._root_memo else chain[-1]]
        for p in chain:
            self._root_memo[p] = root
        return root

    @property
    def alive(self) -> List[int]:
        return [p for p in self.parent if p not in self.ended]


def build_forest(epoch: dict) -> Forest:
    """Reconstruct one epoch's forest from its header + window rows."""
    header = epoch["header"]
    f = Forest()
    base = int(header.get("pid_base", 0))
    start = int(header.get("start_gen", 0))
    for pid in range(base, base + int(header.get("n", 0))):
        f.add(pid, -1, start, KIND_SEED)
    for w in epoch["windows"]:
        f.dropped += int(w.get("edges_dropped", 0))
        for kind, gen, src, dst, prev in w.get("edges", ()):
            if kind == EDGE_ATTACK:
                f._ensure(src)
                f.add(dst, src, gen, KIND_ATTACK)
                if prev >= 0:
                    f._ensure(prev)
                    f.ended.setdefault(prev, gen)
            elif kind == EDGE_RESPAWN:
                f.add(dst, -1, gen, KIND_RESPAWN)
                if prev >= 0:
                    f._ensure(prev)
                    f.ended.setdefault(prev, gen)
            elif kind == EDGE_LEARN:
                f._ensure(src)
                f._ensure(dst)
                f.learn_edges.append((gen, src, dst))
    return f


def dominant_lineages(f: Forest, top: int = 10) -> List[dict]:
    """Roots ranked by live descendants (the dominant-lineage table)."""
    live: Dict[int, int] = {}
    total: Dict[int, int] = {}
    for pid in f.parent:
        r = f.root(pid)
        total[r] = total.get(r, 0) + 1
        if pid not in f.ended:
            live[r] = live.get(r, 0) + 1
    rows = [
        {"root": r, "alive": live.get(r, 0), "minted": total[r],
         "kind": f.kind.get(r, KIND_SEED), "birth": f.birth.get(r)}
        for r in total]
    rows.sort(key=lambda d: (-d["alive"], -d["minted"], d["root"]))
    return rows[:top]


def survival_stats(f: Forest) -> dict:
    """Lifespan distribution of terminated instances plus a survival
    curve (fraction of terminated clones living >= g generations)."""
    spans = sorted(
        f.ended[p] - f.birth[p]
        for p in f.ended if f.birth.get(p) is not None
        and f.ended[p] >= f.birth[p])
    if not spans:
        return {"terminated": 0}
    n = len(spans)

    def q(frac: float) -> int:
        return spans[min(n - 1, int(frac * n))]

    horizon = spans[-1]
    points = []
    for g in sorted({0, 1, 2, 5, 10, 20, 50, 100, horizon}):
        if g > horizon:
            continue
        surviving = sum(1 for s in spans if s >= g)
        points.append({"generations": g, "fraction": round(surviving / n, 4)})
    return {
        "terminated": n,
        "lifespan": {"min": spans[0], "p50": q(0.5), "p90": q(0.9),
                     "max": horizon},
        "curve": points,
    }


def graph_stats(f: Forest, top: int = 5) -> dict:
    """Attack / imitation graph statistics from the surviving edges."""
    attacks: Dict[int, int] = {}
    for pid, kind in f.kind.items():
        if kind == KIND_ATTACK:
            src = f.parent.get(pid, -1)
            if src >= 0:
                attacks[src] = attacks.get(src, 0) + 1
    teaches: Dict[int, int] = {}
    for _gen, teacher, _student in f.learn_edges:
        teaches[teacher] = teaches.get(teacher, 0) + 1

    def summary(deg: Dict[int, int]) -> dict:
        if not deg:
            return {"edges": 0}
        counts = sorted(deg.values(), reverse=True)
        return {
            "edges": sum(counts),
            "actors": len(deg),
            "max_out_degree": counts[0],
            "top": [{"pid": p, "count": c} for p, c in
                    sorted(deg.items(), key=lambda kv: (-kv[1], kv[0]))[:top]],
        }

    return {"attack": summary(attacks), "imitation": summary(teaches),
            "edges_dropped": f.dropped}


def _sum_matrices(a: Optional[List[List[int]]], b: List[List[int]]):
    if a is None:
        return [row[:] for row in b]
    return [[x + y for x, y in zip(ra, rb)] for ra, rb in zip(a, b)]


def _fix_docs(w: dict) -> List[Tuple[Optional[str], dict]]:
    if "fixpoints" in w:
        return [(None, w["fixpoints"])]
    return list(w.get("fixpoints_by_type", {}).items())


def basin_matrix(windows: List[dict]) -> Dict[Optional[str], list]:
    """Per-type (or ``None``-keyed homogeneous) transition-matrix sums."""
    out: Dict[Optional[str], list] = {}
    for w in windows:
        for tname, doc in _fix_docs(w):
            trans = doc.get("transitions")
            if trans:
                out[tname] = _sum_matrices(out.get(tname), trans)
    return out


def census_trajectory(windows: List[dict]) -> List[dict]:
    """``[{gen, <basin counts or per-type census>}, ...]`` per window —
    what the viz fixpoint-census panel plots."""
    rows = []
    for w in windows:
        row: dict = {"gen": w.get("gen_end"), "probe": w.get("kind") == "probe"}
        for tname, doc in _fix_docs(w):
            census = doc.get("census", {})
            if tname is None:
                row.update(census)
            else:
                row[tname] = census
        rows.append(row)
    return rows


def summarize_dynamics(run_dir: str, top: int = 10) -> dict:
    """Machine-readable dynamics summary of a run dir (the
    ``report --dynamics --json`` payload; the text renderer formats it)."""
    epochs = load_lineage(run_dir)
    epoch = epochs[-1]
    windows = epoch["windows"]
    forest = build_forest(epoch)
    alive = forest.alive
    return {
        "run_dir": os.path.abspath(run_dir),
        "epochs": len(epochs),
        "header": epoch["header"],
        "windows": len(windows),
        "minted": len(forest.parent),
        "alive": len(alive),
        "dominant_lineages": dominant_lineages(forest, top=top),
        "survival": survival_stats(forest),
        "graph": graph_stats(forest),
        "basin_matrix": {k if k is not None else "": v
                         for k, v in basin_matrix(windows).items()},
        "census_trajectory": census_trajectory(windows),
        "basins": list(BASIN_NAMES),
    }
