"""Device-side metric accumulation for the jitted soup scan.

The soup-science counters (attack / learn_from / train / respawn event
counts, summed train loss) are accumulated **inside** the jitted
generations scan as an extra carry — one tiny reduction per generation on
device, zero host round-trips — and transferred to the host only at flush
points (every K-generation chunk of the mega-run loops).  The carry is a
plain pytree, so it rides ``lax.scan``, ``shard_map`` (with a
:func:`psum_soup_metrics` at the shard boundary) and buffer donation
unchanged.

This module is deliberately dependency-free (``jax``/``jnp`` only — no
``srnn_tpu`` imports) so ``soup.py`` / ``multisoup.py`` / the sharded
twins can import it from inside their jitted bodies without any import
cycle.  The action-code layout mirrors ``soup.ACTION_NAMES`` (asserted in
``tests/test_telemetry.py``); the host-side interpretation of the
histogram lives in :mod:`srnn_tpu.telemetry.soup_metrics`.

Counters are int32 (jnp's default integer without x64): a flush interval
accumulates at most ``N x K`` events, so at the 1M-particle mega scale the
default 100-generation chunk stays 20x under the int32 ceiling; the host
registry accumulates across flushes in unbounded python ints.
"""

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

#: length of the per-action histogram — mirrors ``len(soup.ACTION_NAMES)``
#: (kept as a literal so this module stays import-cycle-free; parity is
#: asserted by tests).
N_ACTIONS = 7


class SoupMetrics(NamedTuple):
    """Per-flush-interval science counters, accumulated on device."""
    generations: jnp.ndarray  # () int32 — generations accumulated
    actions: jnp.ndarray      # (N_ACTIONS,) int32 — last-action histogram
    loss_sum: jnp.ndarray     # () float32 — sum of per-particle train losses


def zero_soup_metrics() -> SoupMetrics:
    """The identity element the scan carry starts from."""
    return SoupMetrics(
        generations=jnp.int32(0),
        actions=jnp.zeros(N_ACTIONS, jnp.int32),
        loss_sum=jnp.float32(0.0),
    )


def accumulate_soup_metrics(m: SoupMetrics, action: jnp.ndarray,
                            loss: jnp.ndarray) -> SoupMetrics:
    """Fold one generation's ``SoupEvents`` fields into the carry.

    ``action`` is the (N,) int32 last-action code vector, ``loss`` the (N,)
    train-loss vector (zeros when the train phase is off) — exactly the
    per-generation record the soup step already computes, so metering adds
    one small histogram + two adds per generation and nothing else.

    The histogram is a compare-and-reduce, NOT ``jnp.bincount``: bincount
    lowers to a scatter-add, which serializes on both XLA:CPU and TPU and
    was measured at ~20% generation overhead at small N — the (A, N)
    equality mask + row-sum is pure vectorized work and disappears into
    the step's other elementwise ops (<1%).
    """
    codes = jnp.arange(N_ACTIONS, dtype=action.dtype)
    hist = (action[None, :] == codes[:, None]).sum(axis=1, dtype=jnp.int32)
    return SoupMetrics(
        generations=m.generations + 1,
        actions=m.actions + hist,
        loss_sum=m.loss_sum + loss.sum(dtype=jnp.float32),
    )


def merge_soup_metrics(a: SoupMetrics, b: SoupMetrics) -> SoupMetrics:
    """Combine two disjoint accumulation windows (e.g. the strided capture
    loop's intermediate chunk + its captured final step)."""
    return SoupMetrics(
        generations=a.generations + b.generations,
        actions=a.actions + b.actions,
        loss_sum=a.loss_sum + b.loss_sum,
    )


def psum_soup_metrics(m: SoupMetrics, axis_name) -> SoupMetrics:
    """Global metrics from per-shard carries inside a ``shard_map`` body.

    ``actions``/``loss_sum`` are summed over the particle-sharded mesh
    axis (or axis tuple, multislice); ``generations`` is replicated —
    every shard stepped the same count — and must NOT be summed.
    """
    return SoupMetrics(
        generations=m.generations,
        actions=jax.lax.psum(m.actions, axis_name),
        loss_sum=jax.lax.psum(m.loss_sum, axis_name),
    )


@jax.jit
def count_events(action: jnp.ndarray, loss: jnp.ndarray) -> SoupMetrics:
    """One-generation metrics from an events record already in hand (the
    capture helpers' final step of each stride).  A single tiny dispatch;
    under GSPMD a sharded ``action`` reduces with one collective."""
    return accumulate_soup_metrics(zero_soup_metrics(), action, loss)


# ---------------------------------------------------------------------------
# population-health sentinel carry (the flight recorder's device half)
# ---------------------------------------------------------------------------

#: log2-bucket layout of the weight-norm quantile sketch: bucket ``i``
#: covers max-|w| in ``[2^(LO + i*STEP), 2^(LO + (i+1)*STEP))``, clipped at
#: both ends, so the sketch spans 2^-64 .. 2^32 — from deep zero-collapse
#: territory to far past any finite divergence precursor.
N_HEALTH_BUCKETS = 24
HEALTH_BUCKET_LO = -64
HEALTH_BUCKET_STEP = 4


class HealthStats(NamedTuple):
    """Per-flush-interval population-health sentinels, accumulated on
    device alongside :class:`SoupMetrics`.

    The per-particle statistic everything derives from is ``max|w|`` over
    the particle's weights — nonfinite iff any weight is NaN/Inf (the
    divergence predicate), ``<= epsilon`` iff the particle zero-collapsed
    (the ``is_zero`` predicate), and its log2 bucket is the quantile
    sketch the host turns into min/median/max weight norms.

    ``nonfinite``/``zero`` are END-of-window snapshots (the state the next
    chunk starts from); the ``*_peak`` twins are window maxima, so a NaN
    storm that respawn briefly cleans up is still visible.  Under sharding
    the peaks psum per-shard maxima — an upper bound on the true global
    per-generation peak (shards may peak in different generations); the
    end-of-window counts and the histogram are exact.
    """
    checks: jnp.ndarray          # () int32 — generations folded in
    nonfinite: jnp.ndarray       # () int32 — end-of-window NaN/Inf particles
    nonfinite_peak: jnp.ndarray  # () int32 — window max of the above
    zero: jnp.ndarray            # () int32 — end-of-window zero-collapsed
    zero_peak: jnp.ndarray       # () int32
    norm_min: jnp.ndarray        # () f32 — window min of finite max-|w|
    norm_max: jnp.ndarray        # () f32 — window max of finite max-|w|
    norm_hist: jnp.ndarray       # (N_HEALTH_BUCKETS,) int32 — per-gen sketch


def zero_health() -> HealthStats:
    """The identity element the scan carry starts from."""
    return HealthStats(
        checks=jnp.int32(0),
        nonfinite=jnp.int32(0),
        nonfinite_peak=jnp.int32(0),
        zero=jnp.int32(0),
        zero_peak=jnp.int32(0),
        norm_min=jnp.float32(jnp.inf),
        norm_max=jnp.float32(-jnp.inf),
        norm_hist=jnp.zeros(N_HEALTH_BUCKETS, jnp.int32),
    )


def accumulate_health(h: HealthStats, w: jnp.ndarray, axis: int,
                      epsilon: float) -> HealthStats:
    """Fold one generation's post-step weights into the carry.

    ``w`` is the population matrix — (N, P) row-major with ``axis=-1``, or
    the transposed (P, N) lane layout with ``axis=0``; ``epsilon`` is the
    config's zero-collapse bound.  Pure vectorized work (one abs, one
    max-reduce over the tiny weight axis, a compare-and-reduce histogram —
    the same discipline that kept the action histogram under the scatter
    overhead), reads the weights and writes nothing, so the evolved state
    stays bit-identical to the unmetered program.
    """
    norm = jnp.max(jnp.abs(w), axis=axis)           # (N,) per-particle
    finite = jnp.isfinite(norm)
    nonf = (~finite).sum(dtype=jnp.int32)
    zero = (finite & (norm <= epsilon)).sum(dtype=jnp.int32)
    # log2 sketch: exactly-zero norms land in bucket 0; nonfinite lanes are
    # excluded (counted by ``nonfinite`` instead)
    safe = jnp.where(finite & (norm > 0), norm,
                     jnp.float32(2.0) ** HEALTH_BUCKET_LO)
    b = jnp.clip(
        (jnp.floor(jnp.log2(safe)).astype(jnp.int32) - HEALTH_BUCKET_LO)
        // HEALTH_BUCKET_STEP, 0, N_HEALTH_BUCKETS - 1)
    codes = jnp.arange(N_HEALTH_BUCKETS, dtype=jnp.int32)
    hist = ((b[None, :] == codes[:, None]) & finite[None, :]).sum(
        axis=1, dtype=jnp.int32)
    return HealthStats(
        checks=h.checks + 1,
        nonfinite=nonf,
        nonfinite_peak=jnp.maximum(h.nonfinite_peak, nonf),
        zero=zero,
        zero_peak=jnp.maximum(h.zero_peak, zero),
        norm_min=jnp.minimum(h.norm_min,
                             jnp.where(finite, norm, jnp.inf).min()),
        norm_max=jnp.maximum(h.norm_max,
                             jnp.where(finite, norm, -jnp.inf).max()),
        norm_hist=h.norm_hist + hist,
    )


def merge_health(a: HealthStats, b: HealthStats) -> HealthStats:
    """Combine two CONSECUTIVE accumulation windows over the same
    population (``b`` later than ``a``): end-of-window snapshots take
    ``b``'s, peaks/extrema/hist fold."""
    return HealthStats(
        checks=a.checks + b.checks,
        nonfinite=b.nonfinite,
        nonfinite_peak=jnp.maximum(a.nonfinite_peak, b.nonfinite_peak),
        zero=b.zero,
        zero_peak=jnp.maximum(a.zero_peak, b.zero_peak),
        norm_min=jnp.minimum(a.norm_min, b.norm_min),
        norm_max=jnp.maximum(a.norm_max, b.norm_max),
        norm_hist=a.norm_hist + b.norm_hist,
    )


def psum_health(h: HealthStats, axis_name) -> HealthStats:
    """Global health from per-shard carries inside a ``shard_map`` body:
    counts/hist psum over the particle-sharded axis, extrema pmin/pmax;
    ``checks`` is replicated (every shard stepped the same count).  The
    psum'd peaks are a shard-wise upper bound (see :class:`HealthStats`)."""
    return HealthStats(
        checks=h.checks,
        nonfinite=jax.lax.psum(h.nonfinite, axis_name),
        nonfinite_peak=jax.lax.psum(h.nonfinite_peak, axis_name),
        zero=jax.lax.psum(h.zero, axis_name),
        zero_peak=jax.lax.psum(h.zero_peak, axis_name),
        norm_min=jax.lax.pmin(h.norm_min, axis_name),
        norm_max=jax.lax.pmax(h.norm_max, axis_name),
        norm_hist=jax.lax.psum(h.norm_hist, axis_name),
    )


@functools.partial(jax.jit, static_argnames=("axis", "epsilon"))
def probe_health(w: jnp.ndarray, axis: int = -1,
                 epsilon: float = 1e-4) -> HealthStats:
    """One-shot health stats of a population already in hand — the
    capture-mode chunks' cheap substitute for the in-scan carry (one tiny
    extra dispatch per chunk; under GSPMD a sharded ``w`` reduces with
    collectives)."""
    return accumulate_health(zero_health(), w, axis, epsilon)
