"""Device-side metric accumulation for the jitted soup scan.

The soup-science counters (attack / learn_from / train / respawn event
counts, summed train loss) are accumulated **inside** the jitted
generations scan as an extra carry — one tiny reduction per generation on
device, zero host round-trips — and transferred to the host only at flush
points (every K-generation chunk of the mega-run loops).  The carry is a
plain pytree, so it rides ``lax.scan``, ``shard_map`` (with a
:func:`psum_soup_metrics` at the shard boundary) and buffer donation
unchanged.

This module is deliberately dependency-free (``jax``/``jnp`` only — no
``srnn_tpu`` imports) so ``soup.py`` / ``multisoup.py`` / the sharded
twins can import it from inside their jitted bodies without any import
cycle.  The action-code layout mirrors ``soup.ACTION_NAMES`` (asserted in
``tests/test_telemetry.py``); the host-side interpretation of the
histogram lives in :mod:`srnn_tpu.telemetry.soup_metrics`.

Counters are int32 (jnp's default integer without x64): a flush interval
accumulates at most ``N x K`` events, so at the 1M-particle mega scale the
default 100-generation chunk stays 20x under the int32 ceiling; the host
registry accumulates across flushes in unbounded python ints.
"""

from typing import NamedTuple

import jax
import jax.numpy as jnp

#: length of the per-action histogram — mirrors ``len(soup.ACTION_NAMES)``
#: (kept as a literal so this module stays import-cycle-free; parity is
#: asserted by tests).
N_ACTIONS = 7


class SoupMetrics(NamedTuple):
    """Per-flush-interval science counters, accumulated on device."""
    generations: jnp.ndarray  # () int32 — generations accumulated
    actions: jnp.ndarray      # (N_ACTIONS,) int32 — last-action histogram
    loss_sum: jnp.ndarray     # () float32 — sum of per-particle train losses


def zero_soup_metrics() -> SoupMetrics:
    """The identity element the scan carry starts from."""
    return SoupMetrics(
        generations=jnp.int32(0),
        actions=jnp.zeros(N_ACTIONS, jnp.int32),
        loss_sum=jnp.float32(0.0),
    )


def accumulate_soup_metrics(m: SoupMetrics, action: jnp.ndarray,
                            loss: jnp.ndarray) -> SoupMetrics:
    """Fold one generation's ``SoupEvents`` fields into the carry.

    ``action`` is the (N,) int32 last-action code vector, ``loss`` the (N,)
    train-loss vector (zeros when the train phase is off) — exactly the
    per-generation record the soup step already computes, so metering adds
    one small histogram + two adds per generation and nothing else.

    The histogram is a compare-and-reduce, NOT ``jnp.bincount``: bincount
    lowers to a scatter-add, which serializes on both XLA:CPU and TPU and
    was measured at ~20% generation overhead at small N — the (A, N)
    equality mask + row-sum is pure vectorized work and disappears into
    the step's other elementwise ops (<1%).
    """
    codes = jnp.arange(N_ACTIONS, dtype=action.dtype)
    hist = (action[None, :] == codes[:, None]).sum(axis=1, dtype=jnp.int32)
    return SoupMetrics(
        generations=m.generations + 1,
        actions=m.actions + hist,
        loss_sum=m.loss_sum + loss.sum(dtype=jnp.float32),
    )


def merge_soup_metrics(a: SoupMetrics, b: SoupMetrics) -> SoupMetrics:
    """Combine two disjoint accumulation windows (e.g. the strided capture
    loop's intermediate chunk + its captured final step)."""
    return SoupMetrics(
        generations=a.generations + b.generations,
        actions=a.actions + b.actions,
        loss_sum=a.loss_sum + b.loss_sum,
    )


def psum_soup_metrics(m: SoupMetrics, axis_name) -> SoupMetrics:
    """Global metrics from per-shard carries inside a ``shard_map`` body.

    ``actions``/``loss_sum`` are summed over the particle-sharded mesh
    axis (or axis tuple, multislice); ``generations`` is replicated —
    every shard stepped the same count — and must NOT be summed.
    """
    return SoupMetrics(
        generations=m.generations,
        actions=jax.lax.psum(m.actions, axis_name),
        loss_sum=jax.lax.psum(m.loss_sum, axis_name),
    )


@jax.jit
def count_events(action: jnp.ndarray, loss: jnp.ndarray) -> SoupMetrics:
    """One-generation metrics from an events record already in hand (the
    capture helpers' final step of each stride).  A single tiny dispatch;
    under GSPMD a sharded ``action`` reduces with one collective."""
    return accumulate_soup_metrics(zero_soup_metrics(), action, loss)
