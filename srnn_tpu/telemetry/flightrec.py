"""Flight recorder: bounded per-chunk health ring, anomaly/stall watchdog,
and triage bundles.

The paper's central phenomena are pathologies — repeated self-application
reaches a fixpoint, diverges to NaN/Inf, or collapses to the zero fixpoint,
and the soup respawns the casualties.  PR 2/3 made those outcomes visible
as monotone counters and heartbeat rows; this module adds the FORENSIC
layer: when a mega-run goes sideways (NaN storm, whole-population zero
collapse, a chunk that silently hangs the dispatch-ahead loop) it records
*what the population looked like when it happened* and writes an artifact
to debug from.

  * :class:`FlightRecorder` — a bounded ring of per-chunk summaries
    (health-sentinel stats from the device carry, class counts, gens/sec,
    overlap-meter attribution, rng seed).  Cheap enough to be always-on;
    the ring IS the black box a post-mortem replays.
  * :class:`Watchdog` — evaluates trip rules against each chunk's row
    (NaN/zero fraction, respawn rate, gens/sec regression vs the ring
    median).  A trip writes a **triage bundle** and arms a
    ``jax.profiler`` trace window over the next chunk.
  * :func:`write_triage_bundle` — the artifact: trip.json (reason, row,
    thresholds, backend/compile metadata), the full ring as ring.jsonl, a
    config.json copy, a cumulative metrics snapshot, and — when a
    population snapshot is in hand — an orbax checkpoint named
    ``ckpt-gen<N>`` so the bundle doubles as a ``--resume``-able run dir.
  * :class:`StallSentinel` — a dead-man's switch for code that may wedge
    below Python (backend init, a hung tunnel): a daemon timer thread
    fires ``on_stall`` once if no :meth:`~StallSentinel.mark` lands within
    the deadline.  ``bench.py`` arms one around its child stages so a
    killed child's stage_log row points at a bundle, not just "timeout".

The hot-path contract: with the watchdog disabled nothing here runs; with
it enabled the per-chunk cost is one dict append and a handful of float
comparisons.  Everything device-side lives in
:mod:`srnn_tpu.telemetry.device` (the ``health=True`` carry).
"""

from __future__ import annotations

import json
import math
import os
import shutil
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .device import (HEALTH_BUCKET_LO, HEALTH_BUCKET_STEP, N_HEALTH_BUCKETS,
                     HealthStats)

# ---------------------------------------------------------------------------
# health-carry interpretation
# ---------------------------------------------------------------------------


def _bucket_mid(i: int) -> float:
    """Geometric midpoint of log2 bucket ``i`` (the sketch's quantile
    resolution is one bucket: HEALTH_BUCKET_STEP powers of two)."""
    return float(2.0 ** (HEALTH_BUCKET_LO + (i + 0.5) * HEALTH_BUCKET_STEP))


def _hist_quantile(hist: np.ndarray, q: float) -> float:
    total = int(hist.sum())
    if total == 0:
        return math.nan
    target = max(1, int(math.ceil(q * total)))
    cum = np.cumsum(hist)
    i = int(np.searchsorted(cum, target))
    return _bucket_mid(min(i, N_HEALTH_BUCKETS - 1))


def health_summary(h: HealthStats, n_particles: int) -> Dict[str, Any]:
    """Flatten one flushed device carry into the JSON-ready row the ring
    stores: fractions over ``n_particles``, window peaks, and the
    weight-norm min/p50/max read off the log2 sketch."""
    hist = np.asarray(h.norm_hist)
    n = max(1, int(n_particles))
    nmin, nmax = float(h.norm_min), float(h.norm_max)
    return {
        "generations": int(h.checks),
        "n_particles": int(n_particles),
        "nonfinite": int(h.nonfinite),
        "nonfinite_peak": int(h.nonfinite_peak),
        "nan_frac": int(h.nonfinite) / n,
        "nan_frac_peak": int(h.nonfinite_peak) / n,
        "zero": int(h.zero),
        "zero_peak": int(h.zero_peak),
        "zero_frac": int(h.zero) / n,
        "zero_frac_peak": int(h.zero_peak) / n,
        "norm_min": nmin if math.isfinite(nmin) else None,
        "norm_p50": _hist_quantile(hist, 0.5),
        "norm_max": nmax if math.isfinite(nmax) else None,
    }


def combined_health_summary(parts: List[Dict[str, Any]]) -> Dict[str, Any]:
    """Whole-population view of per-type summaries (disjoint
    subpopulations of the same window): counts sum, fractions re-derive
    over the total, norm extrema fold.  ``norm_p50`` is not recombinable
    from summaries alone and reports the per-type median range instead."""
    if not parts:
        return {}
    n = sum(p["n_particles"] for p in parts)
    out = {
        "generations": max(p["generations"] for p in parts),
        "n_particles": n,
        "nonfinite": sum(p["nonfinite"] for p in parts),
        "nonfinite_peak": sum(p["nonfinite_peak"] for p in parts),
        "zero": sum(p["zero"] for p in parts),
        "zero_peak": sum(p["zero_peak"] for p in parts),
    }
    n = max(1, n)
    out["nan_frac"] = out["nonfinite"] / n
    out["nan_frac_peak"] = out["nonfinite_peak"] / n
    out["zero_frac"] = out["zero"] / n
    out["zero_frac_peak"] = out["zero_peak"] / n
    mins = [p["norm_min"] for p in parts if p.get("norm_min") is not None]
    maxs = [p["norm_max"] for p in parts if p.get("norm_max") is not None]
    p50s = [p["norm_p50"] for p in parts
            if p.get("norm_p50") is not None
            and not (isinstance(p["norm_p50"], float)
                     and math.isnan(p["norm_p50"]))]
    out["norm_min"] = min(mins) if mins else None
    out["norm_max"] = max(maxs) if maxs else None
    out["norm_p50"] = (min(p50s), max(p50s)) if p50s else None
    return out


def update_health_gauges(registry, summary: Dict[str, Any],
                         type_name: Optional[str] = None) -> None:
    """Export one chunk's health summary as registry gauges, so the
    Prometheus sink scrapes the same sentinels the ring records."""
    labels = {"type": type_name} if type_name else {}
    g = registry.gauge
    g("soup_health_nonfinite_particles",
      help="NaN/Inf particles at the last flush").set(
          summary["nonfinite"], **labels)
    g("soup_health_zero_particles",
      help="zero-collapsed particles at the last flush").set(
          summary["zero"], **labels)
    g("soup_health_nan_frac",
      help="NaN/Inf particle fraction at the last flush").set(
          round(summary["nan_frac"], 6), **labels)
    g("soup_health_zero_frac",
      help="zero-collapsed particle fraction at the last flush").set(
          round(summary["zero_frac"], 6), **labels)
    for k, name in (("norm_min", "soup_health_weight_norm_min"),
                    ("norm_max", "soup_health_weight_norm_max")):
        v = summary.get(k)
        if isinstance(v, (int, float)) and math.isfinite(v):
            g(name, help="population weight-norm extremum "
              "(max-|w| per particle) over the flush window").set(v, **labels)


def record_recovery(registry, recorder: "FlightRecorder", ctx) -> None:
    """Fold a supervised attempt's recovery history into the run's
    telemetry: the restart/re-ramp counters and the per-recovery seconds
    histogram on ``registry``, and one ``kind="restart"`` row in the
    flight-recorder ring so a later triage bundle shows WHEN the run was
    patched back together, interleaved with the health rows.

    ``ctx`` is the supervisor's AttemptContext (duck-typed: ``restarts``,
    ``attempt``, ``device_budget``, ``recoveries``).  Each attempt builds
    a fresh registry, so folding the *cumulative* history keeps the
    exported counters monotone across restarts.  No-op on the first
    attempt (or unsupervised runs) — the steady-state hot path pays
    nothing."""
    if ctx is None or not getattr(ctx, "restarts", 0):
        return
    registry.counter("soup_restarts_total",
                     help="supervised in-process restarts").inc(ctx.restarts)
    reramps = sum(1 for r in ctx.recoveries if r.get("reramped"))
    if reramps:
        registry.counter("soup_topology_reramps_total",
                         help="mesh rebuilds onto a changed device "
                              "topology").inc(reramps)
    host_losses = sum(1 for r in ctx.recoveries
                      if r.get("kind") == "host_loss")
    if host_losses:
        registry.counter("soup_distributed_host_losses_total",
                         help="host/slice losses recovered in-process "
                              "(multi-process losses exit for the "
                              "launcher tier instead)").inc(host_losses)
    hist = registry.histogram("soup_recovery_seconds",
                              help="seconds from fault to restarted "
                                   "attempt (incl. backoff)",
                              unit="seconds")
    for r in ctx.recoveries:
        # "seconds" spans catch → restart decision and already contains
        # the backoff sleep; do not add backoff_s on top
        hist.observe(float(r.get("seconds", 0.0)))
    if recorder is not None:
        recorder.record({"kind": "restart", "attempt": ctx.attempt,
                         "restarts": ctx.restarts,
                         "device_budget": ctx.device_budget,
                         "recoveries": list(ctx.recoveries)})


# ---------------------------------------------------------------------------
# the ring
# ---------------------------------------------------------------------------


class FlightRecorder:
    """Bounded ring of per-chunk summary rows — the run's black box.

    Rows are plain JSON-able dicts; :meth:`record` stamps a monotone
    ``seq`` and wall-clock ``t``.  Thread-safe: the mega loops record from
    (possibly deferred) chunk finishers while a stall handler may dump the
    ring from the producing thread.
    """

    def __init__(self, capacity: int = 256):
        self.capacity = max(1, int(capacity))
        self._rows: "deque[dict]" = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self._seq = 0

    def record(self, row: Dict[str, Any]) -> Dict[str, Any]:
        with self._lock:
            row = dict(row)
            row["seq"] = self._seq
            row.setdefault("t", round(time.time(), 3))
            self._seq += 1
            self._rows.append(row)
        return row

    def rows(self) -> List[dict]:
        with self._lock:
            return list(self._rows)

    def tail(self, n: int) -> List[dict]:
        with self._lock:
            return list(self._rows)[-max(0, int(n)):]

    def __len__(self) -> int:
        with self._lock:
            return len(self._rows)

    def write(self, path: str) -> str:
        """Dump the ring as jsonl (oldest first)."""
        with open(path, "w") as f:
            for row in self.rows():
                f.write(json.dumps(row, default=str) + "\n")
        return path


# ---------------------------------------------------------------------------
# triage bundles
# ---------------------------------------------------------------------------


def _backend_metadata() -> Dict[str, Any]:
    """Backend + compile provenance for trip.json.  Fail-soft: triage must
    work even when the backend is the thing that broke."""
    out: Dict[str, Any] = {}
    try:
        import jax

        out["jax_version"] = jax.__version__
        out["backend"] = jax.default_backend()
        devs = jax.local_devices()
        out["device_count"] = jax.device_count()
        out["local_devices"] = [str(d) for d in devs[:8]]
        if devs:
            out["device_kind"] = devs[0].device_kind
    except Exception as e:  # pragma: no cover - backend wedge path
        out["backend_error"] = f"{type(e).__name__}: {e}"
    try:
        from .metrics import RUNTIME

        out["runtime_metrics"] = RUNTIME.rows()  # aot compile counters etc.
    except Exception:
        pass
    return out


def write_triage_bundle(
    run_dir: str,
    reasons: List[str],
    row: Optional[Dict[str, Any]],
    recorder: Optional[FlightRecorder] = None,
    snapshot_state: Any = None,
    save_fn: Optional[Callable[[str, Any], str]] = None,
    registry=None,
    thresholds: Optional[Dict[str, Any]] = None,
    generation: Optional[int] = None,
) -> str:
    """Write one self-contained triage bundle under ``run_dir`` and return
    its path.

    Layout (everything best-effort except trip.json, which always lands):

    * ``trip.json`` — reasons, the tripping row, thresholds, backend and
      compile metadata, ring length.
    * ``ring.jsonl`` — the full flight-recorder ring, oldest first.
    * ``config.json`` — copied from the run dir, so the bundle resumes
      with the run's own dynamics.
    * ``metrics.json`` — cumulative registry snapshot at trip time.
    * ``ckpt-gen<N>/`` — ``save_fn(path, snapshot_state)`` (the mega
      loops pass ``experiment.save_checkpoint`` and the chunk's
      pre-donation ``pipeline.snapshot``), named with the run-dir
      checkpoint convention so ``--resume <bundle_dir>`` replays from the
      moment of the trip.
    * ``trace/`` — created by the watchdog's armed ``jax.profiler``
      window over the NEXT chunk (absent for stall bundles: the device is
      presumed hung).
    """
    gen = int(generation if generation is not None
              else (row or {}).get("gen", 0) or 0)
    slug = "-".join(reasons)[:48].replace("/", "_") or "trip"
    base = os.path.join(run_dir, f"triage-gen{gen:08d}-{slug}")
    bundle = base
    i = 1
    while os.path.exists(bundle):
        bundle = f"{base}.{i}"
        i += 1
    os.makedirs(bundle)

    trip: Dict[str, Any] = {
        "reasons": list(reasons),
        "generation": gen,
        "time": time.time(),
        "row": row,
        "thresholds": dict(thresholds or {}),
        "ring_len": len(recorder) if recorder is not None else 0,
        "backend": _backend_metadata(),
    }
    errors: Dict[str, str] = {}
    if recorder is not None:
        try:
            recorder.write(os.path.join(bundle, "ring.jsonl"))
        except OSError as e:
            errors["ring"] = str(e)
    cfg_src = os.path.join(run_dir, "config.json")
    if os.path.exists(cfg_src):
        try:
            shutil.copy(cfg_src, os.path.join(bundle, "config.json"))
        except OSError as e:
            errors["config"] = str(e)
    if registry is not None:
        try:
            with open(os.path.join(bundle, "metrics.json"), "w") as f:
                json.dump(registry.rows(), f, indent=1, sort_keys=True)
        except Exception as e:
            errors["metrics"] = f"{type(e).__name__}: {e}"
    if snapshot_state is not None and save_fn is not None:
        try:
            trip["snapshot"] = os.path.basename(
                save_fn(os.path.join(bundle, f"ckpt-gen{gen:08d}"),
                        snapshot_state))
        except Exception as e:
            errors["snapshot"] = f"{type(e).__name__}: {e}"
    if errors:
        trip["errors"] = errors
    with open(os.path.join(bundle, "trip.json"), "w") as f:
        json.dump(trip, f, indent=1, default=str)
    return bundle


# ---------------------------------------------------------------------------
# the watchdog
# ---------------------------------------------------------------------------


class Watchdog:
    """Per-chunk anomaly rules over flight-recorder rows.

    Thresholds (``None``/``<= 0`` disables a rule):

    * ``nan_frac`` — trip when a chunk's end-of-window NaN/Inf particle
      fraction exceeds it (catches sustained NaN presence when respawn is
      off, or a storm faster than respawn).
    * ``zero_frac`` — same for the zero-collapse fraction (the
      whole-population zero-fixpoint collapse mode).
    * ``respawn_frac`` — trip when the chunk's respawns exceed this
      fraction of its particle-generations (a respawn storm: divergence
      being cleaned up as fast as it appears — invisible to ``nan_frac``).
    * ``gens_regress`` — trip when the chunk's gens/sec falls below
      ``(1 - gens_regress)`` of the ring's median (needs
      ``min_history`` prior rows; 0 disables — wall-clock on shared
      hosts is noisy, so this rule is opt-in).

    ``max_bundles`` bounds how many bundles one run writes (a NaN storm
    trips every chunk; two bundles tell the story, two hundred fill the
    disk).  After a trip the watchdog arms a ``jax.profiler`` trace into
    the bundle; the mega loop calls :meth:`chunk_boundary` at the next
    finisher so the window covers roughly one chunk, and
    :meth:`stop_trace` in its epilogue/teardown.
    """

    RULES = ("nan_frac", "zero_frac", "respawn_frac", "gens_regress")

    def __init__(self, recorder: FlightRecorder,
                 nan_frac: Optional[float] = 0.02,
                 zero_frac: Optional[float] = 0.9,
                 respawn_frac: Optional[float] = 0.25,
                 gens_regress: Optional[float] = 0.0,
                 max_bundles: int = 2,
                 min_history: int = 3,
                 profile_trips: bool = True):
        self.recorder = recorder
        self.nan_frac = nan_frac
        self.zero_frac = zero_frac
        self.respawn_frac = respawn_frac
        self.gens_regress = gens_regress
        self.max_bundles = max(0, int(max_bundles))
        self.min_history = max(1, int(min_history))
        self.profile_trips = profile_trips
        self.bundles: List[str] = []
        self.trips = 0
        self._trace_active = False

    def thresholds(self) -> Dict[str, Any]:
        return {r: getattr(self, r) for r in self.RULES}

    # -- rules -----------------------------------------------------------

    @staticmethod
    def _on(threshold: Optional[float]) -> bool:
        return threshold is not None and threshold > 0

    def check(self, row: Dict[str, Any]) -> List[str]:
        """Evaluate every rule against one chunk row (the row's ``health``
        is a :func:`health_summary` dict; ``respawns``/``particle_gens``
        come from the metrics carry).  Returns the tripped rule names."""
        reasons = []
        health = row.get("health") or {}
        if self._on(self.nan_frac) \
                and health.get("nan_frac", 0) > self.nan_frac:
            reasons.append("nan_frac")
        if self._on(self.zero_frac) \
                and health.get("zero_frac", 0) > self.zero_frac:
            reasons.append("zero_frac")
        if self._on(self.respawn_frac) and row.get("particle_gens"):
            if row.get("respawns", 0) / row["particle_gens"] \
                    > self.respawn_frac:
                reasons.append("respawn_frac")
        if self._on(self.gens_regress) and row.get("gens_per_sec"):
            prior = [r["gens_per_sec"] for r in self.recorder.rows()
                     if r.get("gens_per_sec") and r.get("seq") != row.get("seq")]
            if len(prior) >= self.min_history:
                prior.sort()
                median = prior[len(prior) // 2]
                if row["gens_per_sec"] < (1.0 - self.gens_regress) * median:
                    reasons.append("gens_regress")
        return reasons

    # -- trips and the armed profiler window -----------------------------

    def trip(self, reasons: List[str], row: Optional[Dict[str, Any]],
             run_dir: str, snapshot_state: Any = None,
             save_fn: Optional[Callable] = None, registry=None,
             generation: Optional[int] = None) -> Optional[str]:
        """Record a trip; write a bundle unless ``max_bundles`` is spent.
        Returns the bundle path (or None when rate-limited)."""
        self.trips += 1
        if len(self.bundles) >= self.max_bundles:
            return None
        bundle = write_triage_bundle(
            run_dir, reasons, row, recorder=self.recorder,
            snapshot_state=snapshot_state, save_fn=save_fn,
            registry=registry, thresholds=self.thresholds(),
            generation=generation)
        self.bundles.append(bundle)
        if self.profile_trips:
            self._start_trace(os.path.join(bundle, "trace"))
        return bundle

    def _start_trace(self, path: str) -> None:
        if self._trace_active:
            return
        try:
            import jax

            jax.profiler.start_trace(path)
            self._trace_active = True
        except Exception:
            pass  # a broken profiler must never break the run

    def stop_trace(self) -> None:
        """Stop an armed trace window (idempotent, fail-soft)."""
        if not self._trace_active:
            return
        self._trace_active = False
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:
            pass

    def chunk_boundary(self) -> None:
        """Called by the run loop at each chunk finisher BEFORE evaluating
        rules: closes a trace window armed by the previous chunk's trip, so
        the captured window spans roughly the next chunk after the trip."""
        self.stop_trace()


# ---------------------------------------------------------------------------
# the dead-man's switch
# ---------------------------------------------------------------------------


class StallSentinel:
    """Fire ``on_stall(last_mark, elapsed_s)`` once if no progress mark
    lands within ``deadline_s``.

    Built for code that can wedge BELOW Python (backend init dialing a
    dead tunnel, a compile that never returns): the timer runs on a
    daemon thread — a blocking C call releases the GIL, so the sentinel
    still fires and can write a host-only triage bundle while the main
    thread hangs.  Daemon-ness is deliberate (whitelisted in the
    thread-hygiene gate): the sentinel owns no buffered I/O, and a
    non-daemon timer would keep a wedged process alive forever.
    ``on_stall`` errors are swallowed — the sentinel is forensic, never
    load-bearing.
    """

    def __init__(self, deadline_s: float, on_stall: Callable[[str, float], None],
                 name: str = "srnn-stall-sentinel"):
        from ..utils.pipeline import spawn_thread

        self.deadline_s = float(deadline_s)
        self.on_stall = on_stall
        self.fired = False
        self._mark = "armed"
        self._t_mark = time.monotonic()
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._thread = spawn_thread(self._run, name=name, daemon=True)

    def mark(self, note: str = "") -> None:
        """Record progress: resets the deadline."""
        with self._lock:
            self._mark = note or "mark"
            self._t_mark = time.monotonic()

    def stop(self) -> None:
        self._stop.set()

    def _run(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                waited = time.monotonic() - self._t_mark
            remaining = self.deadline_s - waited
            if remaining <= 0:
                self.fired = True
                try:
                    self.on_stall(self._mark, waited)
                except Exception:
                    pass
                return
            self._stop.wait(min(remaining, 1.0))
