"""Declarative alert engine over the registry + history rings.

The paper's pathologies — populations diverging to NaN, collapsing to
zero, a straggling host dragging the fleet, a service queue quietly
saturating — all have registry signals (PR 2's health gauges, PR 12's
straggler gauges and SLO counter, PR 13's admission gauges) but until
this module nothing WATCHED them: an operator discovered a bad run by
reading files after it ended.  An :class:`AlertEngine` evaluates a small
declarative :class:`Rule` table at every history sample (once per chunk
or dispatch — alerting shares the telemetry cadence, it never adds one):

  * ``threshold`` — the metric's latest value (label sets summed)
    compared against a bound: ``soup_health_nan_frac > 0.02``,
    ``serve_queue_depth >= max_queue``.
  * ``rate`` — the per-second rate over a trailing window:
    ``serve_slo_violations_total`` burning, watchdog trips arriving.
  * ``absence`` — the metric has never been sampled (or its last sample
    is older than the window).  Absence rules get a grace period of one
    window from the engine's first evaluation, so bring-up is never a
    false page.  Scope honesty: ``sample()`` snapshots EVERY registered
    series each turn, so within one process a registered metric's
    series can only go stale if the sampling cadence itself stops — and
    a stopped cadence stops rule evaluation with it.  In-process,
    absence therefore means "never REGISTERED within the window" (a
    fleet fold that never produced, a subsystem that never came up);
    detecting a wedged sampler from outside is the scraper's job (a
    flat ``heartbeat_generation`` across scrapes, or /healthz worker
    staleness — both live independently of the run loop).

Rules latch per name: the ``firing -> cleared`` edge is reported exactly
once each way (a NaN storm is one alert, not one per chunk).  Every
transition increments ``soup_alerts_total{rule=}``, the active count
rides the ``soup_alerts_active`` gauge (so alert state is itself
scrapeable), and the CALLER emits each transition as a
``{"kind": "alert"}`` events row — rendering in ``watch`` (active-alerts
panel), ``report`` (alert trail), and the Perfetto export (markers).

What this is intentionally NOT: a pager.  No delivery, no dedup windows,
no escalation — the engine names conditions in the run's own telemetry
channels; routing them somewhere is the scraper's job (README).

Every ``metric=`` a rule references must exist in
``telemetry.names.CANONICAL_METRICS`` — the srnnlint metric-names pass
(M006) fails the build otherwise, the inverse of its M005 liveness
check, so a rule cannot silently watch a metric nobody emits.
"""

import threading
from typing import Callable, Dict, List, Optional

_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
}

KINDS = ("threshold", "rate", "absence")


class Rule:
    """One declarative alert rule.

    ``metric`` is the BARE registry name (no ``srnn_`` prefix; label
    sets fold by sum — see ``telemetry.timeseries``).  ``kind`` selects
    the evaluation (``threshold`` | ``rate`` | ``absence``); ``op`` and
    ``value`` bound threshold/rate rules; ``window_s`` is the rate
    window or the absence staleness bound."""

    def __init__(self, *, name: str, metric: str, kind: str = "threshold",
                 op: str = ">", value: float = 0.0, window_s: float = 60.0,
                 help: str = ""):
        if kind not in KINDS:
            raise ValueError(f"rule {name!r}: unknown kind {kind!r} "
                             f"(expected one of {KINDS})")
        if op not in _OPS:
            raise ValueError(f"rule {name!r}: unknown op {op!r} "
                             f"(expected one of {sorted(_OPS)})")
        self.name = name
        self.metric = metric
        self.kind = kind
        self.op = op
        self.value = float(value)
        self.window_s = float(window_s)
        self.help = help

    def __repr__(self):
        bound = (f"stale>{self.window_s:g}s" if self.kind == "absence"
                 else f"{self.op}{self.value:g}"
                 + (f"/{self.window_s:g}s" if self.kind == "rate" else ""))
        return f"Rule({self.name}: {self.kind} {self.metric} {bound})"


def default_run_rules(*, nan_frac: float = 0.02, zero_frac: float = 0.9,
                      straggler_skew: float = 4.0) -> List[Rule]:
    """The mega loops' rule table (thresholds mirror the watchdog's CLI
    defaults — the watchdog acts in-process, the alert makes the same
    condition visible to a scraper).  Threshold/rate rules over metrics
    a run never registers (e.g. straggler gauges in a solo run) simply
    never fire — no mode split needed.  Deliberately NO absence rule
    over the process's own heartbeat: every registered series is
    re-stamped each sample, and a wedged loop stops evaluation with the
    cadence, so such a rule is structurally unable to fire — false
    coverage, worse than none (see the module docstring; wedge
    detection belongs to the in-process watchdog and to scrapers)."""
    return [
        Rule(name="soup_nan_frac", metric="soup_health_nan_frac",
             kind="threshold", op=">", value=nan_frac,
             help="NaN/Inf particle fraction past the divergence bound"),
        Rule(name="soup_zero_collapse", metric="soup_health_zero_frac",
             kind="threshold", op=">", value=zero_frac,
             help="population collapsing to the zero fixpoint"),
        Rule(name="soup_straggler_skew",
             metric="soup_straggler_skew_ratio",
             kind="threshold", op=">=", value=straggler_skew,
             help="fastest/slowest process gens-per-sec skew (a host is "
                  "dragging the fleet)"),
        Rule(name="soup_watchdog_burn", metric="soup_watchdog_trips_total",
             kind="rate", op=">", value=0.0, window_s=600.0,
             help="watchdog trips arriving (anomalous chunks)"),
    ]


def default_serve_rules(*, max_queue: int = 0,
                        window_s: float = 60.0) -> List[Rule]:
    """The experiment service's rule table.  The queue-depth bound is
    ``--max-queue`` when admission control is armed (depth AT the bound
    means submits are being rejected) and a generous default otherwise."""
    depth = float(max_queue) if max_queue else 512.0
    return [
        Rule(name="serve_queue_full", metric="serve_queue_depth",
             kind="threshold", op=">=", value=depth,
             help="dispatch queue at the admission bound"),
        Rule(name="serve_slo_burn", metric="serve_slo_violations_total",
             kind="rate", op=">", value=0.0, window_s=window_s,
             help="requests exceeding the --slo-p95-ms target"),
        Rule(name="serve_overload", metric="serve_overload_rejections_total",
             kind="rate", op=">", value=0.0, window_s=window_s,
             help="submits rejected at admission"),
    ]


def default_pool_rules(*, workers: int,
                       window_s: float = 60.0) -> List[Rule]:
    """The serve FLEET's rule table (the front process's engine, layered
    on ``default_serve_rules``): a fleet running below its configured
    worker count, and worker deaths arriving at all, both page — the
    replay ladder heals the work, the alert names the capacity loss."""
    return [
        Rule(name="serve_worker_down", metric="serve_workers",
             kind="threshold", op="<", value=float(workers),
             help="live workers below the configured --workers count"),
        Rule(name="serve_worker_churn", metric="serve_worker_deaths_total",
             kind="rate", op=">", value=0.0, window_s=window_s,
             help="worker processes dying (replay ladder active)"),
    ]


class AlertEngine:
    """Evaluate a rule table against one registry + history pair.

    ``evaluate()`` returns the TRANSITIONS of this turn (``state:
    "firing" | "cleared"`` dicts, ready to ride an events row);
    ``active()`` snapshots the currently-firing set (the watch panel and
    /healthz read it from other threads — locked)."""

    def __init__(self, rules: List[Rule], registry, history):
        self.rules = list(rules)
        self.registry = registry
        self.history = history
        self._lock = threading.Lock()
        self._state: Dict[str, dict] = {}
        self._born: Optional[float] = None
        # registered eagerly so a clean run scrapes the 0, not a missing
        # series (the serve counters' discipline)
        registry.counter("soup_alerts_total",
                         help="alert rule firing transitions")
        registry.gauge("soup_alerts_active",
                       help="alert rules currently firing").set(0)

    def _check(self, rule: Rule, now: float):
        """(value, firing) for one rule at ``now``."""
        if rule.kind == "absence":
            age = self.history.age_s(rule.metric, now=now)
            if age is None:
                # never sampled: grace of one window from first evaluate
                born = self._born if self._born is not None else now
                return None, (now - born) > rule.window_s
            return round(age, 3), age > rule.window_s
        if rule.kind == "rate":
            r = self.history.rate(rule.metric, rule.window_s, now=now)
            if r is None:
                return None, False
            return round(r, 6), _OPS[rule.op](r, rule.value)
        v = self.history.latest_sum(rule.metric)
        if v is None:
            return None, False
        return round(v, 6), _OPS[rule.op](v, rule.value)

    def evaluate(self, now: Optional[float] = None) -> List[dict]:
        """One evaluation turn (call AFTER ``history.sample()`` so rules
        see the sample they ride with).  Returns the transitions."""
        now = self.history.now() if now is None else float(now)
        transitions: List[dict] = []
        with self._lock:
            if self._born is None:
                self._born = now
            for rule in self.rules:
                value, firing = self._check(rule, now)
                st = self._state.setdefault(
                    rule.name, {"firing": False, "since": None,
                                "value": None})
                if firing:
                    st["value"] = value
                if firing and not st["firing"]:
                    st.update(firing=True, since=now)
                    transitions.append(self._transition(
                        rule, "firing", value))
                    self.registry.counter(
                        "soup_alerts_total",
                        help="alert rule firing transitions").inc(
                            1, rule=rule.name)
                elif not firing and st["firing"]:
                    st.update(firing=False, since=now)
                    transitions.append(self._transition(
                        rule, "cleared", value))
            n_active = sum(1 for st in self._state.values()
                           if st["firing"])
        self.registry.gauge("soup_alerts_active",
                            help="alert rules currently firing").set(
                                n_active)
        return transitions

    @staticmethod
    def _transition(rule: Rule, state: str, value) -> dict:
        return {"rule": rule.name, "state": state, "metric": rule.metric,
                "rule_kind": rule.kind, "value": value,
                "threshold": (None if rule.kind == "absence"
                              else rule.value),
                "window_s": (rule.window_s
                             if rule.kind in ("rate", "absence") else None),
                "help": rule.help or None}

    def active(self) -> List[dict]:
        """Currently-firing rules (name, observed value, seconds since
        the firing edge) — the watch panel / healthz payload."""
        now = self.history.now()
        with self._lock:
            return [{"rule": name, "value": st["value"],
                     "for_s": round(now - st["since"], 1)
                     if st["since"] is not None else None}
                    for name, st in sorted(self._state.items())
                    if st["firing"]]
