"""Tail-based exemplar retention for fleet traces.

Distributed tracing's storage problem in miniature: keeping every span
of every ticket forever turns events.jsonl into the product, while
sampling heads (keep 1-in-N at admission) systematically loses exactly
the traces an operator opens the tooling for — the slow one, the one
that died with its worker, the one bisection quarantined.  This module
is the TAIL-sampling answer at self-replicator scale: the serve tier
decides at ticket RESOLUTION what to keep — a ticket that violated the
SLO, failed, was quarantined, or was replayed across a worker death
retains its full span family; every other ticket retains only its root
span (enough for rate/latency accounting, one line).

Records land in a bounded ``exemplars.jsonl`` ring next to the run's
``events.jsonl``.  The ring is append-mostly: writes are plain appends
(one open/write/close per retained ticket, off the dispatch thread via
the service's BackgroundWriter), and when the file exceeds twice its
capacity it compacts down to the newest ``capacity`` records through
``atomic_write_text`` — the same publish discipline as the ticket
journal, so a crash mid-compaction leaves the complete old ring, never
a torn new one.  A torn TAIL line (kill -9 mid-append) is skipped on
read; the record it would have held described an already-resolved
ticket, so nothing operational is lost.

Deliberately jax-free: the pool front (``serve.pool``) keeps its own
ring for replayed tickets and must import this without dragging jax
into the front process.
"""

import json
import os
import threading
from typing import List, Optional

from ..utils.atomicio import atomic_write_text

EXEMPLARS_NAME = "exemplars.jsonl"

#: records kept after a compaction; the file itself may grow to twice
#: this between compactions (amortized O(1) rewrite per append)
DEFAULT_CAPACITY = 256


def read_exemplars(path: str) -> List[dict]:
    """All readable records in ``path``, oldest first; torn/corrupt
    lines are skipped (the expected kill -9 tail case)."""
    out: List[dict] = []
    if not os.path.exists(path):
        return out
    with open(path, "r", encoding="utf-8", errors="replace") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                row = json.loads(line)
            except ValueError:
                continue
            if isinstance(row, dict):
                out.append(row)
    return out


def find_exemplar(path: str, ticket: str) -> Optional[dict]:
    """The NEWEST record for ``ticket`` (by ticket id or trace id), or
    None.  Newest wins so a replayed ticket's post-replay record — the
    one with the full span family — shadows its pre-death root."""
    found = None
    for row in read_exemplars(path):
        if row.get("ticket") == ticket or row.get("trace_id") == ticket:
            found = row
    return found


class ExemplarRing:
    """Bounded append-mostly jsonl ring of retained trace records.

    Thread-safe; every :meth:`add` is one append, and past
    ``2 * capacity`` lines the ring compacts (atomic publish) down to
    the newest ``capacity`` records.  Restart-safe: an existing file's
    line count is adopted, so a long-lived root dir never grows
    unboundedly across service generations either."""

    def __init__(self, path: str, capacity: int = DEFAULT_CAPACITY):
        self.path = path
        self.capacity = max(1, int(capacity))
        self._lock = threading.Lock()
        self._count = self._count_existing()

    def _count_existing(self) -> int:
        try:
            with open(self.path, "r", encoding="utf-8",
                      errors="replace") as f:
                return sum(1 for _ in f)
        except OSError:
            return 0

    def add(self, record: dict) -> None:
        """Append one retained-trace record; fail-soft (retention must
        never take down the dispatch path it describes)."""
        try:
            line = json.dumps(record)
        except (TypeError, ValueError):
            return
        with self._lock:
            try:
                with open(self.path, "a", encoding="utf-8") as f:
                    f.write(line + "\n")
                self._count += 1
                if self._count > 2 * self.capacity:
                    self._compact_locked()
            except OSError:
                pass

    def _compact_locked(self) -> None:
        rows = read_exemplars(self.path)[-self.capacity:]
        atomic_write_text(self.path,
                          "".join(json.dumps(r) + "\n" for r in rows))
        self._count = len(rows)
