"""Cost-ledger-driven block autotuner for the lane-blocked fast paths.

The lane-block knobs (``ops.pallas_generation.generation_block`` for the
fused-generation megakernel, the ``block`` tile of
``apply_chain_blocked`` for the bench/CPU chained-application path) were
fixed heuristics: a VMEM-budget formula and a ``block=2048`` default
picked on one machine.  BENCH probes show the optimum moves with
``(N, P, backend)`` — on the CPU rescue shape (N=100k, P=14 weightwise)
``block=256`` runs the apply chain ~1.9x faster than the 2048 default,
because the whole working tile must stay L2-resident for the chain
unroll to pay.

This module measures a SMALL candidate grid once per
``(kind, variant, N, P, backend, dtype)`` key at warmup, judges
candidates by achieved fraction of the compile-ledger roofline
(``telemetry.costs`` HLO flops of the compiled candidate divided by its
measured wall; min-wall fallback when the backend reports no flops —
the candidates run identical math, so the two rankings agree whenever
both exist), and persists the winner in ``tuning.json`` next to the
persistent executable cache (:func:`utils.aot.default_cache_dir`) so a
restart memo-hits instead of re-measuring.

Correctness contract: tuning only ever changes a TILE SIZE, and both
consumers compute each output column from that column alone, so results
are bitwise block-invariant; ``SRNN_NO_AUTOTUNE=1`` (or the mega loops'
``--no-autotune``) disables lookup *and* measurement and is the A/B
oracle for exactly that claim.  ``SRNN_AUTOTUNE_FIXED=1`` replaces wall
measurement with a deterministic synthetic schedule (tests: the grid
walk, judgment and persistence become reproducible without timing
jitter — no jax work runs at all in that mode).

Everything here is host-side and fail-soft: a corrupt ``tuning.json``
is skipped (and overwritten on the next save), write failures are
swallowed after being counted, and a measurement error falls back to
the untuned default.  Ordering: the mega loops and bench children tune
BEFORE AOT warmup, so the warmed executables are built against the
tuned block and the run's first dispatch deserializes them.
"""

import json
import os
import threading
from typing import Dict, Iterable, Optional, Tuple

DISABLE_ENV = "SRNN_NO_AUTOTUNE"
FIXED_ENV = "SRNN_AUTOTUNE_FIXED"
TUNING_NAME = "tuning.json"
SCHEMA_VERSION = 1

#: candidate lane blocks (128-multiples bracketing the old defaults).
#: apply-chain tiles sweep wider because the CPU cache cliff sits low;
#: the generation kernel's grid stays inside the VMEM-budget envelope.
APPLY_CHAIN_CANDIDATES: Tuple[int, ...] = (256, 512, 1024, 2048, 4096)
GENERATION_CANDIDATES: Tuple[int, ...] = (256, 512, 1024, 2048)

_lock = threading.Lock()
_table: Optional[dict] = None   # in-memory memo of tuning.json
_measured_keys: set = set()     # keys measured by THIS process


def enabled() -> bool:
    return os.environ.get(DISABLE_ENV, "0") in ("", "0")


def fixed() -> bool:
    """Deterministic synthetic-wall mode (tests)."""
    return os.environ.get(FIXED_ENV, "0") not in ("", "0")


def tuning_path() -> Optional[str]:
    """``tuning.json`` next to (inside) the persistent executable cache —
    the tuned blocks and the executables built against them travel
    together.  ``None`` when autotuning is disabled."""
    if not enabled():
        return None
    from .utils import aot

    base = aot._cache_dir_enabled or aot.default_cache_dir()
    return os.path.join(base, TUNING_NAME)


def reset_for_tests() -> None:
    """Drop the in-memory table memo (tests only; the file stays)."""
    global _table
    with _lock:
        _table = None
        _measured_keys.clear()


def make_key(kind: str, variant: str, n: int, p: int, backend: str,
             dtype: str) -> str:
    """One tuning-table key: the measurement's full identity."""
    return f"{kind}|{variant}|n{int(n)}|p{int(p)}|{backend}|{dtype}"


# ---------------------------------------------------------------------------
# the persisted table (corrupt-file graceful skip, atomic writes)
# ---------------------------------------------------------------------------


def _load_table() -> dict:
    """Read-through memo of ``tuning.json``.  An unreadable or
    schema-mismatched file yields an empty table (and the next save
    overwrites it) — tuning is advice, never a crash."""
    global _table
    with _lock:
        if _table is not None:
            return _table
        table: dict = {"version": SCHEMA_VERSION, "entries": {}}
        path = tuning_path()
        if path is not None:
            try:
                with open(path) as f:
                    raw = json.load(f)
                if (isinstance(raw, dict)
                        and raw.get("version") == SCHEMA_VERSION
                        and isinstance(raw.get("entries"), dict)):
                    table = raw
            except (OSError, ValueError):
                pass
        _table = table
        return _table


def _save_table(table: dict) -> bool:
    """Atomic persist (tmp + rename): a killed process can never leave a
    torn ``tuning.json`` for the next one to skip."""
    path = tuning_path()
    if path is None:
        return False
    try:
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        tmp = path + f".tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            json.dump(table, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
        return True
    except OSError:
        return False


def lookup(kind: str, variant: str, n: int, p: int,
           backend: Optional[str] = None,
           dtype: str = "float32") -> Optional[int]:
    """The consumers' read path: the tuned block for a key, or ``None``
    (untuned / disabled — caller uses its built-in default).  Pure table
    read; never measures.  ``backend=None`` resolves the live jax
    backend lazily (kept out of the hot path's import time)."""
    if not enabled():
        return None
    if backend is None:
        import jax

        backend = jax.default_backend()
    entry = _load_table()["entries"].get(
        make_key(kind, variant, n, p, backend, dtype))
    if not isinstance(entry, dict):
        return None
    block = entry.get("block")
    if isinstance(block, int) and block > 0:
        _emit_metrics(kind, variant, entry, hit=True)
        return block
    return None


# ---------------------------------------------------------------------------
# measurement + judgment
# ---------------------------------------------------------------------------


def _judge(walls: Dict[int, float],
           flops: Dict[int, Optional[float]]) -> Tuple[int, dict]:
    """Pick the winner: highest achieved flops/s fraction of the
    grid's roofline (best achieved = fraction 1.0); min-wall fallback
    when no candidate reported flops.  Returns ``(block, report)`` with
    per-candidate walls/fractions for the persisted entry."""
    achieved = {b: (flops.get(b) / w) if (flops.get(b) and w > 0) else None
                for b, w in walls.items()}
    have = {b: a for b, a in achieved.items() if a is not None}
    if have:
        roof = max(have.values())
        fractions = {b: (a / roof if roof else None)
                     for b, a in have.items()}
        winner = max(have, key=lambda b: (have[b], -b))
        judged_by = "roofline"
    else:
        fractions = {}
        winner = min(walls, key=lambda b: (walls[b], b))
        judged_by = "min_wall"
    report = {
        "block": winner,
        "judged_by": judged_by,
        "walls_s": {str(b): round(w, 6) for b, w in sorted(walls.items())},
        "roofline_fraction": {str(b): round(f, 4)
                              for b, f in sorted(fractions.items())},
        "flops": flops.get(winner),
    }
    return winner, report


def _synthetic_walls(candidates: Iterable[int]) -> Dict[int, float]:
    """``SRNN_AUTOTUNE_FIXED=1``: walls are a pure function of the block
    value, so the grid walk / judgment / persistence is byte-reproducible
    (smallest candidate always wins, via the min-wall fallback)."""
    return {int(b): float(b) * 1e-6 for b in candidates}


def _measure_walls(run_fn, candidates: Iterable[int],
                   calls: int = 3) -> Dict[int, float]:
    """Wall per candidate: one untimed compile+warm dispatch, then the
    min over ``calls`` timed dispatches (min, not mean — the quantity
    being compared is the program's speed, and scheduler noise only ever
    adds)."""
    import time as _time

    walls: Dict[int, float] = {}
    for b in candidates:
        b = int(b)
        run_fn(b)  # compile (persistent-cache served) + warm
        best = float("inf")
        for _ in range(calls):
            t0 = _time.perf_counter()
            run_fn(b)
            best = min(best, _time.perf_counter() - t0)
        walls[b] = best
    return walls


def _emit_metrics(kind: str, variant: str, entry: dict, *, hit: bool,
                  measured: int = 0, registry=None) -> None:
    """Fold one lookup/measurement outcome into RUNTIME (and optionally a
    run registry): the ``soup_autotune_*`` family."""
    try:
        from .telemetry.metrics import RUNTIME

        regs = [RUNTIME] + ([registry] if registry is not None else [])
        for reg in regs:
            if hit:
                reg.counter(
                    "soup_autotune_cache_hits_total",
                    help="tuned-block lookups served by tuning.json").inc()
            if measured:
                reg.counter(
                    "soup_autotune_measurements_total",
                    help="autotune candidate dispatch measurements").inc(
                        measured)
            block = entry.get("block")
            if isinstance(block, int):
                reg.gauge(
                    "soup_autotune_block",
                    help="tuned lane block chosen per key").set(
                        block, kind=kind, variant=variant)
            fr = entry.get("roofline_fraction")
            if isinstance(fr, dict) and str(block) in fr:
                reg.gauge(
                    "soup_autotune_roofline_fraction",
                    help="winner's achieved fraction of the measured "
                         "grid roofline").set(
                        float(fr[str(block)]), kind=kind, variant=variant)
    except Exception:
        pass


def _tune(kind: str, variant: str, n: int, p: int, dtype: str,
          candidates: Tuple[int, ...], run_fn, flops_fn=None,
          registry=None) -> Optional[dict]:
    """The shared tune path: memo-hit ``tuning.json``, else measure the
    grid, judge, persist, emit metrics.  ``run_fn(block)`` dispatches one
    measured unit; ``flops_fn(block)`` returns the candidate's HLO flops
    (``None`` ok).  Returns the table entry (or ``None`` when disabled /
    measurement failed)."""
    if not enabled():
        return None
    import jax

    backend = jax.default_backend()
    key = make_key(kind, variant, n, p, backend, dtype)
    table = _load_table()
    entry = table["entries"].get(key)
    if isinstance(entry, dict) and isinstance(entry.get("block"), int):
        _emit_metrics(kind, variant, entry, hit=True, registry=registry)
        return entry
    try:
        if fixed():
            walls = _synthetic_walls(candidates)
            flops = {b: None for b in walls}
        else:
            walls = _measure_walls(run_fn, candidates)
            flops = {b: (flops_fn(b) if flops_fn is not None else None)
                     for b in walls}
        winner, report = _judge(walls, flops)
    except Exception:
        return None
    entry = dict(report, kind=kind, variant=variant, n=int(n), p=int(p),
                 backend=backend, dtype=dtype,
                 candidates=[int(b) for b in candidates])
    with _lock:
        if _table is not None:
            _table["entries"][key] = entry
            table = _table
    _save_table(table)
    _measured_keys.add(key)
    _emit_metrics(kind, variant, entry, hit=False,
                  measured=len(candidates), registry=registry)
    return entry


# ---------------------------------------------------------------------------
# the two tuned kinds
# ---------------------------------------------------------------------------


def autotune_apply_chain(topo, n: int, steps: int, *,
                         candidates: Tuple[int, ...] = None,
                         registry=None) -> Optional[dict]:
    """Tune ``apply_chain_blocked``'s tile for ``(topo, n)``: dispatch the
    real chained-application program per candidate block, record each
    candidate's compile through the cost ledger (``autotune.apply_chain``
    entries), judge by flops ÷ wall.  The measured program is exactly
    the one ``bench.py``'s non-Mosaic route runs."""
    candidates = candidates or APPLY_CHAIN_CANDIDATES
    run = [None]

    def run_fn(block):
        import jax

        from . import init_population
        from .ops.pallas_generation import _apply_chain_blocked

        if run[0] is None:
            wT = (init_population(topo, jax.random.key(0), n) * 0.05).T
            run[0] = wT
        out = _apply_chain_blocked(topo, run[0], steps, block=min(block, n))
        jax.block_until_ready(out)

    def flops_fn(block):
        try:
            import math

            from .ops.pallas_generation import _apply_chain_blocked
            from .telemetry import costs
            from .utils.aot import aot_compile

            b = min(block, n)
            e = aot_compile(f"autotune.apply_chain.b{b}",
                            _apply_chain_blocked, (topo, run[0]),
                            {"steps": steps, "block": b})
            f = costs.extract_costs(e.compiled).get("flops")
            if not f:
                f = costs.entry_flops(f"autotune.apply_chain.b{b}")
            # XLA cost analysis counts the tile scan's BODY once, not x
            # trip count — scale by tiles so candidates compare on total
            # program flops (padding waste charged to the candidate that
            # causes it)
            return f * math.ceil(n / b) if f else None
        except Exception:
            return None

    p = topo.num_weights
    return _tune("apply_chain", topo.variant, n, p, "float32",
                 tuple(min(int(b), n) for b in candidates), run_fn,
                 flops_fn, registry=registry)


def autotune_generation(topo, n: int, *, dtype: str = "float32",
                        train: int = 1,
                        candidates: Tuple[int, ...] = None,
                        registry=None) -> Optional[dict]:
    """Tune the fused-generation megakernel's lane block.  Only measured
    where the kernel actually routes (native Mosaic backend inside the
    fused envelope) — elsewhere the fused spelling runs the XLA phase
    chain, which has no block knob, and this returns ``None`` without
    dispatching anything.  Under ``SRNN_AUTOTUNE_FIXED=1`` the synthetic
    grid runs regardless of backend (tests)."""
    from .ops.pallas_generation import (fused_kernel_route,
                                        generation_block)

    candidates = candidates or GENERATION_CANDIDATES
    train_mode = getattr(topo, "train_mode", "sequential")
    if not fixed() and not fused_kernel_route(topo, train_mode):
        return None
    # the key carries the KERNEL-visible dtype, matching the consumer's
    # ``str(wT.dtype)`` lookup: bf16 populations enter the kernel as
    # bf16 storage, but int8 dequants OUTSIDE the kernel (the quantize-
    # point contract), so its kernel program — and its tuning key — is
    # the f32 one
    kdt = "bfloat16" if dtype in ("bf16", "bfloat16") else "float32"
    # clamp to the VMEM-budget fence: candidates above the formula's
    # budget for this P risk VMEM pressure the formula exists to avoid
    fence = generation_block(topo.num_weights)
    cands = tuple(sorted({min(int(b), fence, n) for b in candidates}))
    run = [None]

    def run_fn(block):
        import jax
        import jax.numpy as jnp

        from . import init_population
        from .ops.pallas_generation import _generation_popmajor

        if run[0] is None:
            wT = (init_population(topo, jax.random.key(0), n) * 0.05).T
            if kdt == "bfloat16":
                wT = wT.astype(jnp.bfloat16)
            run[0] = (wT, wT * 0)
        wT, freshT = run[0]
        out = _generation_popmajor(topo, wT, freshT, train=train,
                                   remove_divergent=True, remove_zero=True,
                                   block=block)
        jax.block_until_ready(out)

    return _tune("generation", topo.variant, n, topo.num_weights, kdt,
                 cands, run_fn, None, registry=registry)


# ---------------------------------------------------------------------------
# run-level hook (mega loops / serve warmup / bench children)
# ---------------------------------------------------------------------------


def autotune_for_run(config, *, registry=None, exp=None,
                     no_autotune: bool = False) -> list:
    """The warmup hook: tune every kind relevant to ``config`` (a
    ``SoupConfig`` or ``MultiSoupConfig``), emit ``soup_autotune_*``
    metrics into ``registry`` and ONE ``{"kind": "autotune"}`` events
    row via ``exp`` (when given).  Fail-soft and host-side: results are
    tile sizes only, so runs stay bitwise identical with or without it
    (``no_autotune`` / ``SRNN_NO_AUTOTUNE=1`` is the A/B oracle).
    Returns the tuned entries."""
    if no_autotune or not enabled():
        return []
    entries = []
    try:
        dtype = getattr(config, "population_dtype", "f32")
        dt = {"f32": "float32", "bf16": "bfloat16", "int8": "int8"}.get(
            dtype, dtype)
        topos = getattr(config, "topos", None)
        pairs = (list(zip(topos, config.sizes)) if topos is not None
                 else [(config.topo, config.size)])
        if getattr(config, "generation_impl", "phases") == "fused":
            for topo, size in pairs:
                e = autotune_generation(topo, size, dtype=dt,
                                        train=getattr(config, "train", 0),
                                        registry=registry)
                if e:
                    entries.append(e)
        if exp is not None and entries:
            exp.event(kind="autotune", path=tuning_path(),
                      entries=[{k: e[k] for k in
                                ("kind", "variant", "n", "p", "backend",
                                 "dtype", "block", "judged_by")}
                               for e in entries])
    except Exception:
        pass
    return entries
