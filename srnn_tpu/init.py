"""Weight initialization matching keras defaults.

The reference never sets initializers, so it inherits keras defaults
(``network.py:226-230,329-333,531-535``): Dense kernels are glorot_uniform;
SimpleRNN input kernels are glorot_uniform and recurrent kernels orthogonal.
Matching these distributions matters — the fixpoint-density experiment
(``setups/fixpoint-density.py``) classifies *untrained random* nets, so its
statistics are a direct function of the init law.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .topology import Topology


def _glorot_uniform(key, shape, dtype):
    fan_in, fan_out = shape
    limit = jnp.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(key, shape, dtype, minval=-limit, maxval=limit)


def _orthogonal(key, shape, dtype):
    return jax.nn.initializers.orthogonal()(key, shape, dtype)


def init_flat(topo: Topology, key: jax.Array, dtype=jnp.float32) -> jnp.ndarray:
    """Sample one particle's flat weight vector ``(P,)``."""
    shapes = topo.layer_shapes
    keys = jax.random.split(key, len(shapes))
    parts = []
    for i, (shape, k) in enumerate(zip(shapes, keys)):
        if topo.variant == "recurrent" and i % 2 == 1:
            # odd entries are SimpleRNN recurrent kernels
            parts.append(_orthogonal(k, shape, dtype).reshape(-1))
        else:
            parts.append(_glorot_uniform(k, shape, dtype).reshape(-1))
    return jnp.concatenate(parts)


# ---------------------------------------------------------------------------
# Fused mega-population draws (the soup-respawn fast path).
#
# ``init_population`` splits N per-particle keys and vmaps tiny per-layer
# draws — faithful to "construct a fresh keras net per particle" and the
# right default, but at mega-soup scale the respawn phase pays it EVERY
# generation (N=1M: ~1M key splits + 3M tiny uniform calls ≈ 83% of an
# apply-only generation's cost in the profile_soup breakdown).  For the
# variants whose init law is pure per-weight glorot_uniform (weightwise /
# aggregating / fft — everything except the recurrent variant's orthogonal
# kernels), the whole population init is ONE U(-1, 1) draw of shape (P, N)
# scaled by a constant per-row limit vector: the same iid law, one threefry
# call.  A DIFFERENT stream than init_population (distributionally
# identical), so it is opt-in via ``SoupConfig.respawn_draws='fused'``.
# ---------------------------------------------------------------------------


@functools.lru_cache(maxsize=None)
def _glorot_limit_rows(topo: Topology) -> np.ndarray:
    """(P,) per-weight glorot_uniform limits, in flat order."""
    assert topo.variant != "recurrent", (
        "fused init is undefined for orthogonal recurrent kernels")
    rows = []
    for (a, b) in topo.layer_shapes:
        rows.append(np.full(a * b, np.sqrt(6.0 / (a + b)), np.float32))
    return np.concatenate(rows)


def supports_fused_init(topo: Topology) -> bool:
    """True when the variant's init law is pure glorot_uniform (no
    orthogonal kernels), i.e. the fused draw is exactly the same law."""
    return topo.variant != "recurrent"


def init_popmajor_fused(topo: Topology, key: jax.Array, n: int,
                        dtype=jnp.float32) -> jnp.ndarray:
    """Sample ``n`` particles as ONE fused (P, n) lane-layout draw.

    Same distribution as ``init_population(topo, key, n).T`` (iid
    U(-limit_p, limit_p) per weight), different stream.  Row-major callers
    transpose; the draw is generated lane-major so the popmajor and
    row-major layouts consume bitwise-identical values.
    """
    if not supports_fused_init(topo):
        raise ValueError(
            f"variant {topo.variant!r} has orthogonal kernels; fused init "
            "is only defined for pure-glorot variants")
    lim = jnp.asarray(_glorot_limit_rows(topo), dtype)
    u = jax.random.uniform(key, (topo.num_weights, n), dtype,
                           minval=-1.0, maxval=1.0)
    return u * lim[:, None]


def fresh_rows(topo: Topology, key: jax.Array, n: int,
               draws: str = "perparticle") -> jnp.ndarray:
    """Respawn replacements in row-major (n, P) layout.  ``draws='fused'``
    takes the one-call path for pure-glorot variants and falls back to the
    per-particle draw for the recurrent variant."""
    if draws == "fused" and supports_fused_init(topo):
        return init_popmajor_fused(topo, key, n).T
    if draws not in ("perparticle", "fused"):
        raise ValueError(f"unknown respawn_draws {draws!r}")
    return init_population(topo, key, n)


def fresh_lanes(topo: Topology, key: jax.Array, n: int,
                draws: str = "perparticle") -> jnp.ndarray:
    """Respawn replacements in lane-major (P, n) layout (same values as
    ``fresh_rows(...).T``)."""
    if draws == "fused" and supports_fused_init(topo):
        return init_popmajor_fused(topo, key, n)
    if draws not in ("perparticle", "fused"):
        raise ValueError(f"unknown respawn_draws {draws!r}")
    return init_population(topo, key, n).T


# Chunk size for mega-population init.  The orthogonal initializer lowers to
# a batched QR custom call whose scoped-VMEM footprint grows with batch size
# and overflows around ~300k tiny matrices on v5e; a lax.map over fixed-size
# chunks keeps each QR batch small with no measurable init-time cost.
_INIT_CHUNK = 65536


def init_population(topo: Topology, key: jax.Array, n: int, dtype=jnp.float32) -> jnp.ndarray:
    """Sample ``n`` particles -> (n, P). vmap of :func:`init_flat`,
    chunked via ``lax.map`` at mega-population sizes."""
    keys = jax.random.split(key, n)
    sample = jax.vmap(lambda k: init_flat(topo, k, dtype))
    if n <= _INIT_CHUNK:
        return sample(keys)
    split = n - n % _INIT_CHUNK
    head = keys[:split].reshape(-1, _INIT_CHUNK, *keys.shape[1:])
    out = jax.lax.map(sample, head).reshape(split, topo.num_weights)
    if split < n:
        out = jnp.concatenate([out, sample(keys[split:])])
    return out
