"""Weight initialization matching keras defaults.

The reference never sets initializers, so it inherits keras defaults
(``network.py:226-230,329-333,531-535``): Dense kernels are glorot_uniform;
SimpleRNN input kernels are glorot_uniform and recurrent kernels orthogonal.
Matching these distributions matters — the fixpoint-density experiment
(``setups/fixpoint-density.py``) classifies *untrained random* nets, so its
statistics are a direct function of the init law.
"""

import jax
import jax.numpy as jnp

from .topology import Topology


def _glorot_uniform(key, shape, dtype):
    fan_in, fan_out = shape
    limit = jnp.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(key, shape, dtype, minval=-limit, maxval=limit)


def _orthogonal(key, shape, dtype):
    return jax.nn.initializers.orthogonal()(key, shape, dtype)


def init_flat(topo: Topology, key: jax.Array, dtype=jnp.float32) -> jnp.ndarray:
    """Sample one particle's flat weight vector ``(P,)``."""
    shapes = topo.layer_shapes
    keys = jax.random.split(key, len(shapes))
    parts = []
    for i, (shape, k) in enumerate(zip(shapes, keys)):
        if topo.variant == "recurrent" and i % 2 == 1:
            # odd entries are SimpleRNN recurrent kernels
            parts.append(_orthogonal(k, shape, dtype).reshape(-1))
        else:
            parts.append(_glorot_uniform(k, shape, dtype).reshape(-1))
    return jnp.concatenate(parts)


# Chunk size for mega-population init.  The orthogonal initializer lowers to
# a batched QR custom call whose scoped-VMEM footprint grows with batch size
# and overflows around ~300k tiny matrices on v5e; a lax.map over fixed-size
# chunks keeps each QR batch small with no measurable init-time cost.
_INIT_CHUNK = 65536


def init_population(topo: Topology, key: jax.Array, n: int, dtype=jnp.float32) -> jnp.ndarray:
    """Sample ``n`` particles -> (n, P). vmap of :func:`init_flat`,
    chunked via ``lax.map`` at mega-population sizes."""
    keys = jax.random.split(key, n)
    sample = jax.vmap(lambda k: init_flat(topo, k, dtype))
    if n <= _INIT_CHUNK:
        return sample(keys)
    split = n - n % _INIT_CHUNK
    head = keys[:split].reshape(-1, _INIT_CHUNK, *keys.shape[1:])
    out = jax.lax.map(sample, head).reshape(split, topo.num_weights)
    if split < n:
        out = jnp.concatenate([out, sample(keys[split:])])
    return out
