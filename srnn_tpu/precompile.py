"""Fill the persistent executable cache for the soup hot path ahead of a run.

    python -m srnn_tpu.precompile --size 1000000 --generations 100
    python -m srnn_tpu.precompile --multi --engine --json

AOT-lowers and compiles the hot entry points (``srnn_tpu.utils.aot``) for
the given (topology, config, shapes) on the current backend, writing the
executables into jax's persistent on-disk cache
(``JAX_COMPILATION_CACHE_DIR`` / ``SRNN_COMPILE_CACHE_DIR``, see
``aot.default_cache_dir``).  A later process — a bench child, a mega-run,
a CI shard — that compiles the same program deserializes it instead of
re-paying XLA, so its measurement (or production) window spends its time
executing.  Safe to run on a login CPU for the CPU cache, or inside an
accelerator allocation for the device cache; a cache-dir problem degrades
to plain compilation, never an error.

Config knobs mirror ``python -m srnn_tpu.setups mega_soup`` so the default
invocation warms exactly the flagship configuration.
"""

import argparse
import json
import sys
import time

from .soup import SoupConfig
from .topology import Topology


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--variant", default="weightwise",
                   choices=("weightwise", "aggregating", "fft", "recurrent"))
    p.add_argument("--width", type=int, default=2)
    p.add_argument("--depth", type=int, default=2)
    p.add_argument("--size", type=int, default=1_000_000)
    p.add_argument("--generations", type=int, default=100,
                   help="scan length of the evolve executable (the mega "
                        "runs' per-chunk generation count)")
    p.add_argument("--attacking-rate", type=float, default=0.1)
    p.add_argument("--learn-from-rate", type=float, default=-1.0)
    p.add_argument("--train", type=int, default=0)
    p.add_argument("--train-mode", default="sequential",
                   choices=("sequential", "full_batch"))
    p.add_argument("--layout", default="popmajor",
                   choices=("rowmajor", "popmajor"))
    p.add_argument("--respawn-draws", choices=("perparticle", "fused"),
                   default="fused")
    p.add_argument("--train-impl", choices=("xla", "pallas"), default="xla")
    p.add_argument("--generation-impl", choices=("phases", "fused"),
                   default="phases",
                   help="whole-generation execution: 'fused' pre-warms the "
                        "single-launch megakernel spellings "
                        "(ops/pallas_generation.py) so a fused run on a "
                        "fresh TPU window deserializes instead of paying "
                        "full compile inside the bench deadline")
    p.add_argument("--population-dtype", choices=("f32", "bf16", "int8"),
                   default="f32",
                   help="population storage dtype of the warmed "
                        "executables (bf16 = mixed-precision population "
                        "mode; a different program than f32)")
    p.add_argument("--attack-impl", choices=("full", "compact"),
                   default="full")
    p.add_argument("--learn-from-impl", choices=("full", "compact"),
                   default="full")
    p.add_argument("--epsilon", type=float, default=1e-4)
    p.add_argument("--multi", action="store_true",
                   help="also warm the heterogeneous (ww+agg+rnn) "
                        "multisoup twins at ~size/3 per type")
    p.add_argument("--engine", action="store_true",
                   help="also warm run_fixpoint / run_mixed_fixpoint / "
                        "run_training for the config's topology+size")
    p.add_argument("--sharded", action="store_true",
                   help="also warm the sharded steps over all visible "
                        "devices")
    p.add_argument("--stacked", type=int, default=0, metavar="K",
                   help="also warm the serve tenant-axis spellings at "
                        "stack width K (srnn_tpu.serve; skipped for "
                        "configs that cannot stack — popmajor/sequential)")
    p.add_argument("--no-donate", action="store_true",
                   help="warm the value-preserving spellings instead of "
                        "the buffer-donating production ones")
    p.add_argument("--both", action="store_true",
                   help="warm donated AND non-donated spellings")
    p.add_argument("--cache-dir", default=None,
                   help="persistent executable cache location (default: "
                        "$JAX_COMPILATION_CACHE_DIR / "
                        "$SRNN_COMPILE_CACHE_DIR / ~/.cache/srnn_tpu/xla)")
    p.add_argument("--no-autotune", action="store_true",
                   help="skip the block autotuner (srnn_tpu.autotune) "
                        "before warmup; lane blocks stay at the built-in "
                        "defaults (equivalent: SRNN_NO_AUTOTUNE=1)")
    p.add_argument("--json", action="store_true",
                   help="print one machine-readable JSON line instead of "
                        "the human summary")
    return p


def _make_config(args) -> SoupConfig:
    return SoupConfig(
        topo=Topology(args.variant, width=args.width, depth=args.depth),
        size=args.size,
        attacking_rate=args.attacking_rate,
        learn_from_rate=args.learn_from_rate,
        train=args.train,
        train_mode=args.train_mode,
        remove_divergent=True,
        remove_zero=True,
        epsilon=args.epsilon,
        layout=args.layout,
        respawn_draws=args.respawn_draws,
        attack_impl=args.attack_impl,
        learn_from_impl=args.learn_from_impl,
        train_impl=args.train_impl,
        generation_impl=args.generation_impl,
        population_dtype=args.population_dtype,
    )


def _make_multi(args):
    from .multisoup import MultiSoupConfig

    third = args.size // 3
    return MultiSoupConfig(
        topos=(Topology("weightwise", width=2, depth=2),
               Topology("aggregating", width=2, depth=2),
               Topology("recurrent", width=2, depth=2)),
        sizes=(args.size - 2 * third, third, third),
        attacking_rate=args.attacking_rate,
        learn_from_rate=args.learn_from_rate,
        train=args.train,
        train_mode=args.train_mode,
        remove_divergent=True,
        remove_zero=True,
        epsilon=args.epsilon,
        layout=args.layout,
        respawn_draws=args.respawn_draws,
        train_impl=args.train_impl,
        generation_impl=args.generation_impl,
        population_dtype=args.population_dtype,
    )


def run(args) -> dict:
    from .utils import aot

    cache = aot.ensure_compilation_cache(args.cache_dir)
    import jax  # after the cache config so nothing compiles uncached

    mesh = None
    if args.sharded:
        from .parallel import soup_mesh
        mesh = soup_mesh()

    cfg = _make_config(args)
    multi = _make_multi(args) if args.multi else None
    # tune lane blocks BEFORE warmup so the warmed executables are the
    # tuned programs (a run then deserializes them; --no-autotune /
    # SRNN_NO_AUTOTUNE=1 keeps the built-in defaults, bit-identically)
    from . import autotune

    tuned = autotune.autotune_for_run(cfg, no_autotune=args.no_autotune)
    if multi is not None:
        tuned += autotune.autotune_for_run(multi,
                                           no_autotune=args.no_autotune)
    donate_modes = [True, False] if args.both \
        else [not args.no_donate]
    t0 = time.perf_counter()
    rows = []
    for donate in donate_modes:
        rows += aot.warmup(cfg, multi=multi, mesh=mesh,
                           generations=args.generations, donate=donate,
                           engine=args.engine, stacked=args.stacked,
                           verbose=not args.json)
    return {
        "backend": jax.default_backend(),
        "device_count": jax.device_count(),
        "cache_dir": cache,
        "entries": len(rows),
        "total_s": round(time.perf_counter() - t0, 3),
        "rows": rows,
        "autotuned": [{k: e[k] for k in ("kind", "variant", "n", "p",
                                         "block", "judged_by")}
                      for e in tuned],
    }


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    out = run(args)
    if args.json:
        print(json.dumps(out), flush=True)
    else:
        print(f"precompiled {out['entries']} entries on "
              f"{out['backend']} x{out['device_count']} in "
              f"{out['total_s']:.1f}s"
              + (f"; persistent cache: {out['cache_dir']}"
                 if out["cache_dir"] else "; persistent cache DISABLED"))
    return 0


if __name__ == "__main__":
    sys.exit(main())
