"""Static topology descriptors for self-replicating networks.

A *topology* captures everything shape-related about one network variant so
that a particle's parameters can live as a single flat ``(P,)`` vector and all
transforms become pure jittable functions of that vector.  This replaces the
reference's keras ``Sequential`` objects (reference: ``network.py:213-574``)
with trace-time constants: layer shapes, flat offsets, and the precomputed
positional-encoding table used by the weightwise variant
(reference ``network.py:239-255``).

Weight layout parity: the reference stores weights as keras' list of 2-D
kernels iterated layer -> cell (row) -> weight (column)
(``network.py:64-74``).  We keep exactly that enumeration order when
flattening, so flat index <-> (layer, cell, weight) coordinates match the
reference bit-for-bit.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field, replace
from typing import Tuple

import numpy as np

VARIANTS = ("weightwise", "aggregating", "fft", "recurrent")


@dataclass(frozen=True)
class Topology:
    """Hashable, trace-static description of one network variant.

    Attributes mirror the reference constructors:
      - ``weightwise``  : MLP f: R^4 -> R^1      (``network.py:222-230``)
      - ``aggregating`` : MLP f: R^k -> R^k      (``network.py:324-333``)
      - ``fft``         : MLP f: R^k -> R^k      (``network.py:465-474``)
      - ``recurrent``   : SimpleRNN stack, feature dim 1 (``network.py:526-535``)

    ``activation`` applies to every layer (keras_params semantics,
    ``network.py:80``); default 'linear', no biases anywhere.
    """

    variant: str
    width: int = 2
    depth: int = 2
    aggregates: int = 4          # only used by aggregating / fft
    activation: str = "linear"
    # aggregating-variant options (reference ``network.py:338-345``):
    #   aggregator: 'average' (default) | 'max' | 'max_buggy'
    #     'max_buggy' replicates the reference's falsy-max quirk
    #     (``network.py:303-308``) where a candidate equal to 0.0 never wins.
    #   shuffler: 'not' (default) | 'random' — 'random' requires a PRNG key
    #     at apply time (functional stand-in for ``shuffle_random``).
    aggregator: str = "average"
    shuffler: str = "not"
    # fft-variant option: the reference transform FFTs its *own* current
    # weights and ignores the passed-in target (``network.py:494-499``), so
    # ``attack(other)`` writes self-derived values. False keeps that
    # behavior; True fixes the quirk and transforms the target instead.
    fft_use_target: bool = False
    # fft-variant transform: 'fft' (reference ``aggregate_fft``,
    # ``network.py:444-448``) or 'rfft' — the real-input transform the
    # related/EP prototype's FeatureReduction offered alongside fft
    # (``related/EP/src/FeatureReduction.py:9-16``); coefficients are the
    # first k real-FFT bins, inverse via irfft.
    fft_mode: str = "fft"
    # matmul precision: 'highest' keeps f32 accumulation on the MXU so that
    # |delta| < 1e-4 fixpoint thresholds are meaningful on TPU (bf16 rounding
    # is ~3e-3 at unit scale — larger than epsilon).  'default' opts into
    # fast bf16 passes for throughput-only workloads.
    precision: str = "highest"
    # recurrent-variant option: 'sequential' (default) is the serial
    # lax.scan matching keras step order; 'associative' exploits that the
    # linear-activation recurrence is affine and solves each layer with an
    # associative scan in O(log T) depth — the TPU-native fast path for
    # giant-particle sequences (requires activation='linear'; floating-point
    # reassociation means bitwise differences from the serial scan).
    rnn_scan: str = "sequential"

    def __post_init__(self):
        if self.variant not in VARIANTS:
            raise ValueError(f"unknown variant {self.variant!r}; expected one of {VARIANTS}")
        if self.width < 1 or self.depth < 1:
            raise ValueError("width and depth must be >= 1")
        if self.variant in ("aggregating", "fft") and self.aggregates < 1:
            raise ValueError("aggregates must be >= 1")
        if self.precision not in ("default", "high", "highest"):
            raise ValueError(f"unknown precision {self.precision!r}")
        if self.aggregator not in ("average", "max", "max_buggy"):
            raise ValueError(f"unknown aggregator {self.aggregator!r}")
        if self.shuffler not in ("not", "random"):
            raise ValueError(f"unknown shuffler {self.shuffler!r}")
        if self.fft_mode not in ("fft", "rfft"):
            raise ValueError(f"unknown fft_mode {self.fft_mode!r}")
        if self.rnn_scan not in ("sequential", "associative"):
            raise ValueError(f"unknown rnn_scan {self.rnn_scan!r}")
        if (self.variant == "recurrent" and self.rnn_scan == "associative"
                and self.activation != "linear"):
            raise ValueError(
                "rnn_scan='associative' requires activation='linear' "
                "(the recurrence must be affine)")

    # ---- shape metadata -------------------------------------------------

    @property
    def layer_shapes(self) -> Tuple[Tuple[int, int], ...]:
        """Kernel shapes in keras ``get_weights()`` order.

        Dense kernels are ``(fan_in, fan_out)``.  SimpleRNN layers contribute
        two entries each — input kernel then recurrent kernel — matching
        keras' weight list for ``use_bias=False``.
        """
        w, d = self.width, self.depth
        if self.variant == "weightwise":
            return ((4, w),) + ((w, w),) * (d - 1) + ((w, 1),)
        if self.variant in ("aggregating", "fft"):
            k = self.aggregates
            return ((k, w),) + ((w, w),) * (d - 1) + ((w, k),)
        # recurrent: depth SimpleRNN(units=w) layers + final SimpleRNN(units=1)
        shapes = [(1, w), (w, w)]
        for _ in range(d - 1):
            shapes += [(w, w), (w, w)]
        shapes += [(w, 1), (1, 1)]
        return tuple(shapes)

    @property
    def num_weights(self) -> int:
        """Total scalar parameter count P (``get_amount_of_weights``, ``network.py:347-353``)."""
        return int(sum(a * b for a, b in self.layer_shapes))

    @property
    def offsets(self) -> Tuple[int, ...]:
        """Flat start offset of each kernel, plus the total as last element."""
        offs = [0]
        for a, b in self.layer_shapes:
            offs.append(offs[-1] + a * b)
        return tuple(offs)

    @property
    def num_layers(self) -> int:
        return len(self.layer_shapes)

    # ---- recurrent helpers ---------------------------------------------

    @property
    def rnn_layer_dims(self) -> Tuple[Tuple[int, int], ...]:
        """(input_dim, units) per SimpleRNN layer, in order."""
        assert self.variant == "recurrent"
        w, d = self.width, self.depth
        dims = [(1, w)] + [(w, w)] * (d - 1) + [(w, 1)]
        return tuple(dims)

    # ---- convenience ----------------------------------------------------

    def with_(self, **kw) -> "Topology":
        return replace(self, **kw)


# ---------------------------------------------------------------------------
# Precomputed constants (cached per topology; numpy so they become XLA
# constants when closed over inside jit).
# ---------------------------------------------------------------------------


def _normalize_id(value: np.ndarray, norm: float) -> np.ndarray:
    """Reference ``normalize_id`` (``network.py:215-220``): divide only when
    the max index exceeds 1, else keep the raw index."""
    if norm > 1:
        return value / float(norm)
    return value.astype(np.float64)


@functools.lru_cache(maxsize=None)
def weight_coords(topo: Topology) -> np.ndarray:
    """Integer (layer, cell, weight) ids per flat position — shape (P, 3)."""
    rows = []
    for layer_id, (a, b) in enumerate(topo.layer_shapes):
        for cell_id in range(a):
            for weight_id in range(b):
                rows.append((layer_id, cell_id, weight_id))
    return np.asarray(rows, dtype=np.int32)


@functools.lru_cache(maxsize=None)
def normalized_weight_coords(topo: Topology) -> np.ndarray:
    """Normalized duplex points, shape (P, 3) float32.

    Matches ``compute_all_duplex_weight_points`` (``network.py:239-255``):
    each id is divided by the *max id in its own axis scope* — layer ids by
    the global max layer id, cell ids by (rows-in-this-layer - 1), weight ids
    by (cols-in-this-cell - 1) — but only when that max exceeds 1.
    """
    coords = weight_coords(topo).astype(np.float64)
    out = np.empty_like(coords)
    max_layer_id = topo.num_layers - 1
    out[:, 0] = _normalize_id(coords[:, 0], max_layer_id)
    pos = 0
    for layer_id, (a, b) in enumerate(topo.layer_shapes):
        n = a * b
        sl = slice(pos, pos + n)
        out[sl, 1] = _normalize_id(coords[sl, 1], a - 1)
        out[sl, 2] = _normalize_id(coords[sl, 2], b - 1)
        pos += n
    return out.astype(np.float32)


@functools.lru_cache(maxsize=None)
def segments_for(p: int, k: int) -> Tuple[np.ndarray, np.ndarray]:
    """Segment ids + counts chunking ``p`` weights into ``k`` collections.

    Reference ``collect_weights`` (``network.py:388-403``): weights are
    chunked into groups of ``p // k`` in flat order; the trailing ``p % k``
    leftovers are appended to the *last* collection.  Keyed by (p, k) so
    cross-architecture application (an aggregating attacker chunking a
    *victim's* weight count) shares the same rule.

    Returns (segment_ids (p,) int32, counts (k,) int32).
    """
    size = p // k
    if size == 0:
        raise ValueError(f"aggregates={k} exceeds weight count {p}")
    seg = np.minimum(np.arange(p) // size, k - 1).astype(np.int32)
    counts = np.bincount(seg, minlength=k).astype(np.int32)
    return seg, counts


def aggregation_segments(topo: Topology) -> Tuple[np.ndarray, np.ndarray]:
    """Segments of a topology's own weights under its own ``aggregates``."""
    return segments_for(topo.num_weights, topo.aggregates)
