"""Known-fixpoint fixtures and fault injection.

The reference's canonical regression fixture is the analytically-known
identity fixpoint of the weightwise net
(``setups/known-fixpoint-variation.py:20-25``, reused by ``test.py:95-99``):
with kernels ``[[1,0],[0,0],...]`` the net computes f([w, ids]) = w, so
self-application reproduces every weight exactly.  ``vary`` is the
reference's fault-injection operator (``known-fixpoint-variation.py:37-46``):
perturb each weight by ±U(0,1)·e with a fair sign coin.

Generalized here beyond the hardcoded 2×2 case: the identity chain routes
input feature 0 (the weight value) through unit 0 of every hidden layer.
"""

import jax
import jax.numpy as jnp
import numpy as np

from .topology import Topology


def identity_fixpoint_flat(topo: Topology) -> jnp.ndarray:
    """The exact identity fixpoint of a weightwise net as a flat vector.

    Layer 0 kernel (4, w): route input 0 (the weight value) to unit 0;
    hidden kernels (w, w): identity on unit 0; final kernel (w, 1): read
    unit 0.  For width=2, depth=2 this reproduces the reference's fixture
    matrices bit-for-bit (``known-fixpoint-variation.py:20-25``).
    """
    if topo.variant != "weightwise":
        raise ValueError("the known identity fixpoint exists for the "
                         "weightwise variant only (reference note at "
                         "known-fixpoint-variation.py:29)")
    parts = []
    for a, b in topo.layer_shapes:
        k = np.zeros((a, b), np.float32)
        k[0, 0] = 1.0
        parts.append(k.reshape(-1))
    return jnp.asarray(np.concatenate(parts))


def vary(key: jax.Array, flat: jnp.ndarray, e: float = 1.0) -> jnp.ndarray:
    """Perturb every weight by ±U(0,1)·e, sign chosen by a fair coin
    (``known-fixpoint-variation.py:37-46``).  Functional: the PRNG key
    replaces the reference's global ``prng()`` stream."""
    k_sign, k_mag = jax.random.split(key)
    sign = jnp.where(jax.random.uniform(k_sign, flat.shape) < 0.5, 1.0, -1.0)
    return flat + sign * jax.random.uniform(k_mag, flat.shape) * e
