"""Soup sweep over imitation severity.

Reference: ``setups/learn_from_soup.py`` — weightwise only (``:71-73``),
soup of 10, life 100, attack off (−1), learn_from_rate 0.1, sweep
learn_from_severity ∈ {0, 10, ..., 100} (``:66``), 10 trials; record avg
zero / non-zero fixpoints per soup; saves ``all_names``/``all_data`` and a
final ``soup`` state artifact (``:104-106``).
"""

import jax
import numpy as np

from ..experiment import Experiment
from ..soup import SoupConfig
from .common import (STANDARD_VARIANTS, base_parser, count_soup_trials,
                     evolve_trials, log_sweep, register)


def build_parser():
    p = base_parser(__doc__)
    p.add_argument("--trials", type=int, default=10)
    p.add_argument("--soup-size", type=int, default=10)
    p.add_argument("--soup-life", type=int, default=100)
    p.add_argument("--severity-values", type=int, nargs="*",
                   default=[10 * i for i in range(11)])
    p.add_argument("--learn-from-rate", type=float, default=0.1)
    p.add_argument("--train-mode", default="sequential",
                   choices=("sequential", "full_batch"))
    return p


def run(args):
    if args.smoke:
        args.trials, args.soup_life, args.severity_values = 2, 3, [0, 2]
    key = jax.random.key(args.seed)
    name, topo = STANDARD_VARIANTS[0]  # weightwise only (:71-73)
    with Experiment("learn-from-soup", root=args.root, seed=args.seed) as exp:
        xs, ys, zs = [], [], []
        last_states = None
        for j, severity in enumerate(args.severity_values):
            cfg = SoupConfig(
                topo=topo, size=args.soup_size,
                attacking_rate=-1.0, learn_from_rate=args.learn_from_rate,
                learn_from_severity=severity, train=0,
                epsilon=args.epsilon, train_mode=args.train_mode)
            states = evolve_trials(cfg, jax.random.fold_in(key, j),
                                   args.trials, args.soup_life)
            counts = count_soup_trials(cfg, states)
            xs.append(severity)
            ys.append(float(counts[1]) / args.trials)
            zs.append(float(counts[2]) / args.trials)
            last_states = states
        all_names = [name]
        all_data = [{"xs": xs, "ys": ys, "zs": zs}]
        log_sweep(exp, name, all_data[0])
        exp.save(all_names=all_names, all_data=all_data,
                 soup={"weights": np.asarray(last_states.weights),
                       "uids": np.asarray(last_states.uids)})
        return exp.dir


@register("learn_from_soup")
def main(argv=None):
    return run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
