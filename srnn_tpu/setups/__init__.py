"""Paper-experiment entry points (TPU-native equivalents of the reference's
``code/setups/*.py`` scripts, SURVEY §2.2).

Run one with ``python -m srnn_tpu.setups <name> [flags]``; every script
supports ``--smoke`` for a seconds-scale sanity run and writes a reference-
style run directory (log.txt + npz/json artifacts) under ``--root``.
"""

from . import (  # noqa: F401  (import for registration side effect)
    applying_fixpoints,
    fixpoint_density,
    known_fixpoint_variation,
    learn_from_soup,
    mega_multisoup,
    mega_soup,
    mixed_self_fixpoints,
    mixed_soup,
    network_trajectorys,
    soup_trajectorys,
    training_fixpoints,
)
from .common import REGISTRY

__all__ = ["REGISTRY"]
