"""The heterogeneous mega-soup: the BASELINE mixed-type configuration
(weightwise + aggregating + recurrent subpopulations with cross-type
attacks) as a resumable production run.

No reference equivalent at any scale — the reference's mixed-soup
experiment runs SEPARATE homogeneous soups per architecture
(``mixed-soup.py:66-68``); its object design cannot mix types in one
population, and it cannot exceed a few hundred particles.  This entry
point composes ``srnn_tpu.multisoup`` (one typed population, any-on-any
attacks) with the production runtime: lane-major layout, periodic orbax
checkpoints with bit-exact ``--resume``, per-chunk per-type class-count
logging, and the sharded (ICI data-parallel) path.

    python -m srnn_tpu.setups mega_multisoup --size 1000000 --generations 1000
    python -m srnn_tpu.setups mega_multisoup --resume experiments/exp-mega-multisoup-…-0

Trajectory capture writes one ``.traj`` store per type (``soup.t0.traj``,
``soup.t1.traj``, ...) via ``utils.evolve_multi_captured``; capture under
sharding stays with the homogeneous ``mega_soup`` entry point (per-process
AND per-type shards would compose, but nothing exercises it yet — a
documented boundary, not an accident).
"""

import os
import sys

import jax
import numpy as np

from ..experiment import restore_multi_checkpoint, save_multi_checkpoint
from ..multisoup import (MultiSoupConfig, count_multi, evolve_multi,
                         evolve_multi_donated, seed_multi)
from ..soup import ACT_DIV_DEAD, ACT_ZERO_DEAD
from ..telemetry import Heartbeat, MetricsRegistry
from ..telemetry.device import probe_health
from ..telemetry.flightrec import (combined_health_summary, health_summary,
                                   update_health_gauges)
from ..telemetry.soup_metrics import (type_names, update_class_gauges,
                                      set_precision_gauges,
                                      update_fused_counters,
                                      update_multi_registry)
from ..resilience import Preempted, supervised_run
from ..telemetry.flightrec import record_recovery
from ..utils.aot import ensure_compilation_cache
from ..utils.pipeline import snapshot, submit_or_run
from ..ops.predicates import CLASS_NAMES
from ..topology import Topology
from ..distributed import add_distributed_args
from ..telemetry.profiler import update_utilization_gauges
from .common import (add_dynamics_args, add_flightrec_args,
                     add_pipeline_args, add_profile_args,
                     add_resilience_args, add_telemetry_args, base_parser,
                     build_soup_mesh, chunk_boundary_faults, close_spans,
                     emit_chunk_spans, fetch_for_checkpoint,
                     finish_pipeline, flush_lineage_probe,
                     flush_lineage_window, init_distributed,
                     latest_checkpoint, make_flightrec, make_lineage,
                     make_live_plane, make_on_stall, make_pipeline,
                     make_profiler, make_spans, load_run_config,
                     note_restart, open_run, probe_run_costs, register,
                     save_run_config, set_distributed_gauges, stage_label,
                     update_fleet_gauges, watchdog_chunk)


def build_parser():
    p = base_parser(__doc__)
    p.add_argument("--size", type=int, default=1_000_000,
                   help="total particles, split ~1/3 per type (weightwise "
                        "gets the remainder)")
    p.add_argument("--generations", type=int, default=1000)
    p.add_argument("--attacking-rate", type=float, default=0.1)
    p.add_argument("--learn-from-rate", type=float, default=0.1)
    p.add_argument("--learn-from-severity", type=int, default=1)
    p.add_argument("--train", type=int, default=10)
    p.add_argument("--train-mode", default="sequential",
                   choices=("sequential", "full_batch"))
    p.add_argument("--layout", default="popmajor",
                   choices=("rowmajor", "popmajor"))
    p.add_argument("--respawn-draws", choices=("perparticle", "fused"),
                   default="fused")
    p.add_argument("--train-impl", choices=("xla", "pallas"), default="xla")
    p.add_argument("--generation-impl", choices=("phases", "fused"),
                   default="phases",
                   help="'fused' fuses each type's learn+train+respawn "
                        "into one megakernel launch on Mosaic backends "
                        "(popmajor; cross-type attack stays XLA; "
                        "bit-identical XLA fallback elsewhere)")
    p.add_argument("--population-dtype", choices=("f32", "bf16", "int8"),
                   default="f32",
                   help="per-type population storage dtype (bf16 = "
                        "mixed-precision mode, see PARITY.md)")
    p.add_argument("--apply-impl", choices=("xla", "pallas"), default="xla",
                   help="'pallas': fused VMEM forward for recurrent "
                        "attackers in the cross-type attack phase "
                        "(ops/pallas_rnn_apply.py)")
    p.add_argument("--checkpoint-every", type=int, default=100)
    p.add_argument("--capture-every", type=int, default=0, metavar="K",
                   help="stream every K-th generation's per-type frames to "
                        "soup.tN.traj stores (0 = off); must divide "
                        "--checkpoint-every and --generations; not combined "
                        "with --sharded")
    p.add_argument("--resume", default=None, metavar="RUN_DIR")
    p.add_argument("--sharded", action="store_true",
                   help="shard every type's particle axis over ALL visible "
                        "devices (shard_map data parallel)")
    add_pipeline_args(p)
    add_telemetry_args(p)
    add_profile_args(p)
    add_flightrec_args(p)
    add_dynamics_args(p)
    add_resilience_args(p)
    add_distributed_args(p)
    return p


_CONFIG_FIELDS = ("size", "attacking_rate", "learn_from_rate",
                  "learn_from_severity", "train", "train_mode", "layout",
                  "epsilon", "sharded", "respawn_draws", "train_impl",
                  "apply_impl", "capture_every", "generation_impl",
                  "population_dtype")


def _make_config(args, n_dev: int = 1) -> MultiSoupConfig:
    """Split ~1/3 per type; under sharding each type's size is rounded to a
    device-count multiple so every shard is equal (the weightwise remainder
    stays divisible because the total is validated divisible upfront)."""
    third = args.size // 3
    if n_dev > 1:
        third = (third // n_dev) * n_dev
    return MultiSoupConfig(
        topos=(Topology("weightwise", width=2, depth=2),
               Topology("aggregating", width=2, depth=2),
               Topology("recurrent", width=2, depth=2)),
        sizes=(args.size - 2 * third, third, third),
        attacking_rate=args.attacking_rate,
        learn_from_rate=args.learn_from_rate,
        learn_from_severity=args.learn_from_severity,
        train=args.train,
        train_mode=args.train_mode,
        remove_divergent=True,
        remove_zero=True,
        epsilon=args.epsilon,
        layout=args.layout,
        respawn_draws=args.respawn_draws,
        train_impl=args.train_impl,
        apply_impl=args.apply_impl,
        generation_impl=args.generation_impl,
        population_dtype=args.population_dtype,
    )


def _format_type_counts(counts: np.ndarray) -> str:
    names = ("ww", "agg", "rnn")
    parts = []
    for t, row in enumerate(counts):
        cells = ", ".join(f"{c}={int(v)}" for c, v in zip(CLASS_NAMES, row)
                          if v)
        parts.append(f"{names[t]}[{cells or '0'}]")
    return " ".join(parts)


def run(args):
    """One supervised heterogeneous mega run (see ``mega_soup.run`` — the
    same elastic-supervisor contract)."""
    return supervised_run(args, _run_once)


def _run_once(args, ctx=None):
    chaos = ctx.chaos if ctx is not None else None
    # multi-process bring-up FIRST (before anything probes devices); see
    # mega_soup — `primary` gates all host I/O but heartbeats
    dist = init_distributed(args)
    primary = dist.primary if dist.active else True
    if args.smoke:
        args.size = 48 if args.size == 1_000_000 else args.size
        args.generations = 6 if args.generations == 1000 else args.generations
        args.checkpoint_every = 2 if args.checkpoint_every == 100 \
            else args.checkpoint_every
        args.train = 1 if args.train == 10 else args.train
    # validate everything cheap BEFORE creating/attaching the Experiment,
    # so a bad invocation can never leave a run dir without meta.json
    ckpt = None
    if args.resume:
        # original dynamics win over CLI; configs written before the
        # round-6 fields must resume with the behavior they actually ran
        # (phase-chain generations, f32 storage), never a newer CLI value
        load_run_config(args.resume, args, _CONFIG_FIELDS,
                        legacy_defaults={"generation_impl": "phases",
                                         "population_dtype": "f32"})
        ckpt = latest_checkpoint(args.resume)
    if (args.train_impl == "pallas" or args.apply_impl == "pallas") \
            and args.layout != "popmajor":
        raise SystemExit("--train-impl/--apply-impl pallas are popmajor "
                         "lane kernels; --layout rowmajor needs 'xla'")
    if args.generation_impl == "fused" and args.layout != "popmajor":
        raise SystemExit("--generation-impl fused is the popmajor lane "
                         "megakernel; --layout rowmajor needs phases")
    if args.capture_every < 0:
        raise SystemExit("--capture-every must be >= 0")
    if args.capture_every and args.checkpoint_every % args.capture_every:
        raise SystemExit("--capture-every must divide --checkpoint-every")
    if args.capture_every and args.generations % args.capture_every:
        raise SystemExit("--capture-every must divide --generations")
    if args.capture_every and args.sharded:
        raise SystemExit("--capture-every is single-process for the "
                         "heterogeneous soup; drop --sharded (the "
                         "homogeneous mega_soup captures under sharding)")
    mesh = None
    n_dev = 1
    if args.sharded:
        # device budget (--max-devices, shrunk by a topology re-ramp to
        # the verified survivors, by identity).  The total size is
        # published so a re-ramp snaps to a device count it divides;
        # per-type checkpoint sizes are re-validated after restore (the
        # adoption branch below) — a residual mismatch there still exits,
        # by design.  build_soup_mesh routes multislice topologies
        # through reramp_soup_mesh (the live 2-D path), like mega_soup.
        if ctx is not None:
            ctx.shard_sizes = (args.size,)
        mesh = build_soup_mesh(ctx, (args.size,))  # sets last_seen_devices
        n_dev = mesh.devices.size
        if args.size % n_dev:
            raise SystemExit(
                f"--sharded needs --size divisible by the {n_dev} visible "
                f"devices (got {args.size})")
        if args.size < 3 * n_dev:
            # the per-type rounding below would otherwise zero out a type
            # and silently run a homogeneous soup from this entry point
            raise SystemExit(
                f"--sharded needs --size >= 3x the {n_dev} visible devices "
                "so every type keeps at least one shard per device")
    cfg = _make_config(args, n_dev)
    ensure_compilation_cache()  # warm-start executables across processes

    if args.resume:
        exp = open_run(args, "mega-multisoup", dist, resume=args.resume)
        state = restore_multi_checkpoint(ckpt)
        got = tuple(w.shape[0] for w in state.weights)
        if got != cfg.sizes:
            # per-type sizes derive from the CURRENT device count under
            # --sharded; a resume on a different mesh would slice the
            # restored arrays with wrong offsets deep in jit otherwise.
            # A topology re-ramp is the sanctioned exception: keep the
            # CHECKPOINT's sizes whenever every type still shards evenly
            # onto the surviving mesh — the population is what it is, the
            # mesh is what remains.
            if mesh is not None and all(s % n_dev == 0 for s in got):
                cfg = cfg._replace(sizes=got)
                exp.log(f"re-ramped topology: keeping checkpoint per-type "
                        f"sizes {got} on {n_dev} device(s)")
            else:
                raise SystemExit(
                    f"checkpointed per-type sizes {got} do not match this "
                    f"host's derived sizes {cfg.sizes}; resume on the "
                    "original device count (or one each size divides)")
        if mesh is not None:
            from ..parallel import place_sharded_multi_state
            state = place_sharded_multi_state(mesh, state)
        else:
            # restored arrays may be zero-copy host views; the all-donated
            # chunk loop requires jax-owned buffers
            from ..utils.aot import own_pytree
            state = own_pytree(state)
        exp.log(f"resumed from {os.path.basename(ckpt)} "
                f"at generation {int(state.time)}")
    else:
        exp = open_run(args, "mega-multisoup", dist)
        if primary:
            save_run_config(exp.dir, args, _CONFIG_FIELDS,
                            extra={"type_names": [t.variant
                                                  for t in cfg.topos]})
        if mesh is not None:
            from ..parallel import make_sharded_multi_state
            state = make_sharded_multi_state(cfg, mesh, jax.random.key(args.seed))
        else:
            state = seed_multi(cfg, jax.random.key(args.seed))
        from ..ops.popmajor import resolved_train_impl
        impls = ",".join(
            f"{t.variant}={resolved_train_impl(t, cfg.train_mode, cfg.train_impl)}"
            for t in cfg.topos) if cfg.layout == "popmajor" else cfg.train_impl
        exp.log(f"mega-multisoup N={cfg.total} sizes={cfg.sizes} "
                f"layout={cfg.layout} attack={cfg.attacking_rate} "
                f"train={cfg.train}/{cfg.train_mode} train_impl={impls}"
                + (f" sharded over {mesh.devices.size} devices"
                   if mesh is not None else ""))
    note_restart(exp, ctx)

    def _count(s):
        # device array out: dispatched before the next chunk donates s's
        # buffers, resolved in the (possibly deferred) chunk finisher
        if mesh is not None:
            from ..parallel import sharded_count_multi
            return sharded_count_multi(cfg, mesh, s)
        return count_multi(cfg, s)

    # Donation discipline (see mega_soup): unsharded chunks are
    # ALL-donated (states entering the loop are jax-owned — seeds are jit
    # outputs, restores own_pytree-copied — and one executable for every
    # chunk keeps resume bitwise); the sharded path donates only states
    # this loop itself produced (first chunk plain).
    def _evolve(s, gens, owned, health, lkw):
        if mesh is not None:
            from ..parallel import (sharded_evolve_multi,
                                    sharded_evolve_multi_donated)
            run = sharded_evolve_multi_donated if owned \
                else sharded_evolve_multi
            return run(cfg, mesh, s, generations=gens, metrics=True,
                       health=health, **lkw)
        return evolve_multi_donated(cfg, s, generations=gens, metrics=True,
                                    health=health, **lkw)

    # telemetry: per-run registry (per-type science counters from the
    # in-scan carries, class gauges per type) + fsync'd heartbeats; both
    # flushed every chunk to events.jsonl and metrics.prom
    registry = MetricsRegistry()
    set_precision_gauges(registry, cfg)
    set_distributed_gauges(registry, dist, mesh)
    # block autotuner (srnn_tpu.autotune; --no-autotune = the A/B bitwise
    # oracle): per-type lane blocks measured-or-memoed BEFORE warmup, so
    # the run's executables are the tuned programs from the first compile
    if primary:
        from .. import autotune
        autotune.autotune_for_run(cfg, registry=registry, exp=exp,
                                  no_autotune=args.no_autotune)
    if cfg.generation_impl == "fused":
        from ..multisoup import resolved_generation_impl
        exp.log("generation_impl=fused: " + ",".join(
            f"{t.variant}={resolved_generation_impl(cfg, t)}"
            for t in cfg.topos)
            + f", population_dtype={cfg.population_dtype}")
    # flight recorder + watchdog (see mega_soup / telemetry.flightrec)
    health_on = not args.no_health
    flightrec, watchdog = make_flightrec(args)
    if not primary:
        # triage bundles are run-dir artifacts: process-0-gated (see
        # mega_soup)
        watchdog = None
    # restarted attempt: fold the recovery history (counters + ring row)
    record_recovery(registry, flightrec, ctx)
    # replication-dynamics observatory (telemetry.dynamics): per-type
    # lineage carries over one shared pid space + the lineage.jsonl stream
    tnames = type_names(cfg)
    lins, lin_writer, lincap = make_lineage(
        args, exp.dir, sizes=cfg.sizes, start_gen=int(state.time),
        resume=bool(args.resume), mesh=mesh, type_names=tnames,
        primary=primary)
    lineage_on = lins is not None
    if lineage_on and lin_writer is not None:
        exp.log(f"lineage: epoch {lin_writer.epoch}, "
                f"{lincap} edge rows/window -> lineage.jsonl")
    stores = writer = live = prof = capture = None
    import time as _time
    try:
        # writer spawns INSIDE the try (see mega_soup): a crash in this
        # window must reach writer.close() or the non-daemon worker
        # hangs interpreter shutdown
        pipelined, writer, meter, driver = make_pipeline(args, registry,
                                                         "mega_multisoup")
        if chaos is not None and writer is not None:
            chaos.attach_writer(writer)
        driver.on_stall = make_on_stall(exp, flightrec, registry,
                                        lambda: gen) if primary else None
        # fleet observatory: structured chunk/gather spans (host-only;
        # --no-spans is the bit-identical A/B reference)
        spans = make_spans(args, exp, registry, writer, dist,
                           "mega_multisoup")
        # live telemetry plane (--no-export = the bitwise A/B oracle;
        # see mega_soup / telemetry.exporter)
        # continuous profiling plane (--no-profile = its bitwise A/B
        # oracle) + anomaly capture on the alert firing edge, riding the
        # live plane's ordered sample job — see mega_soup
        prof, capture = make_profiler(args, exp, registry, dist,
                                      "mega_multisoup")
        live = make_live_plane(args, exp, registry, dist,
                               "mega_multisoup", capture=capture)
        hb = Heartbeat(exp, stage=stage_label("mega_multisoup", dist),
                       total_generations=args.generations,
                       registry=registry,
                       fsync_every=args.heartbeat_fsync_every,
                       writer=writer)
        hb.beat(generation=int(state.time))

        if args.capture_every:
            from ..utils import TrajStore, truncate_frames
            paths = [os.path.join(exp.dir, f"soup.t{t}.traj")
                     for t in range(len(cfg.topos))]
            if args.resume:
                # reconcile every per-type store to the restored checkpoint
                # so re-evolved generations aren't appended twice
                for path in paths:
                    truncate_frames(path,
                                    int(state.time) // args.capture_every)
            stores = [TrajStore(path, n_particles=cfg.sizes[t],
                                n_weights=cfg.topos[t].num_weights,
                                mode="a" if args.resume else "w")
                      for t, path in enumerate(paths)]
            frames = {s_.existing_frames for s_ in stores}
            if len(frames) > 1:
                # one torn/missing per-type store would otherwise restart
                # fresh while siblings keep history, silently misaligning
                # frame indices across types
                raise SystemExit(
                    f"per-type stores disagree on existing frames {frames}; "
                    "repair or remove soup.t*.traj before resuming")
            if stores[0].existing_frames:
                exp.log(f"soup.t*.traj: appending after "
                        f"{stores[0].existing_frames} existing frames")
            exp.log(f"capturing every {args.capture_every} generations to "
                    f"{len(stores)} per-type stores")
            if writer is not None:
                for store in stores:
                    # crash path: close() drains queued appends + flushes
                    writer.add_close_hook(store.join)
        with meter.waiting():
            counts = np.asarray(_count(state))
        # Pipelined order per iteration (see mega_soup): dispatch the
        # chunk, dispatch its count, snapshot the state for the checkpoint
        # — all before chunk k+1's donating dispatch — then defer the host
        # finisher one iteration.  `gen` advances host-side so the loop
        # condition never forces a device sync.
        owned = False
        gen = int(state.time)
        # cost plane (telemetry.costs; --no-costs = the A/B oracle): see
        # mega_soup — probe the chunk program's cost against the
        # warmup-identical abstract skeleton, fold the cost gauges, emit
        # the {"kind":"cost"} roofline source row
        if primary and stores is None and gen < args.generations:
            from ..utils.aot import abstract_lineage_state, \
                abstract_multi_state
            chunk0 = min(args.checkpoint_every, args.generations - gen)
            pkw = {"generations": chunk0, "metrics": True,
                   "health": health_on}
            if lineage_on:
                pkw.update(lineage=True, lineage_state=tuple(
                    abstract_lineage_state(n, mesh=mesh)
                    for n in cfg.sizes), lineage_capacity=lincap)
            st_abs = abstract_multi_state(cfg, mesh=mesh)
            if mesh is not None:
                from ..parallel import sharded_evolve_multi
                probe_run_costs(args, exp, registry,
                                "mega_multisoup.chunk",
                                sharded_evolve_multi,
                                (cfg, mesh, st_abs), pkw,
                                particles=sum(cfg.sizes),
                                generations=chunk0)
            else:
                probe_run_costs(args, exp, registry,
                                "mega_multisoup.chunk",
                                evolve_multi_donated, (cfg, st_abs), pkw,
                                particles=sum(cfg.sizes),
                                generations=chunk0)
        t_last = _time.perf_counter()

        def _class_gauges(counts, prev):
            for t, tname in enumerate(type_names(cfg)):
                update_class_gauges(registry, counts[t],
                                    type_name=tname, prev=prev[t])

        def _finisher(gen, chunk, counts_dev, ckpt_state, ms=None, hs=None,
                      ldata=None):
            def finish():
                nonlocal counts, t_last
                with meter.waiting():
                    new_counts = np.asarray(counts_dev)  # chunk landed
                prev, counts = counts, new_counts
                now = _time.perf_counter()
                dt, t_last = max(now - t_last, 1e-9), now
                exp.log(f"gen {gen}/{args.generations}  "
                        f"{chunk / dt:.2f} gens/s  "
                        f"{_format_type_counts(counts)}",
                        generation=gen, gens_per_sec=round(chunk / dt, 3),
                        counts=counts.tolist())
                # flight-recorder row (see mega_soup): whole-population
                # health drives the watchdog; per-type detail rides along
                row = {"gen": gen, "chunk": chunk,
                       "gens_per_sec": round(chunk / dt, 3),
                       "counts": counts.tolist(), "seed": args.seed}
                by_type = None
                if ms is not None:
                    div = sum(int(np.asarray(m.actions)[ACT_DIV_DEAD])
                              for m in ms)
                    zero = sum(int(np.asarray(m.actions)[ACT_ZERO_DEAD])
                               for m in ms)
                    row["respawns_divergent"] = div
                    row["respawns_zero"] = zero
                    row["respawns"] = div + zero
                    row["particle_gens"] = chunk * cfg.total
                if hs is not None:
                    by_type = {tname: health_summary(h, cfg.sizes[t])
                               for t, (tname, h)
                               in enumerate(zip(type_names(cfg), hs))}
                    row["health"] = combined_health_summary(
                        list(by_type.values()))
                    row["health_by_type"] = by_type
                # registry-mutation ordering + host_io window: see the
                # mega_soup finisher — chunk k's mutations ride the
                # writer ahead of chunk k's flush_events
                with meter.host_io():
                    if ms is not None:
                        submit_or_run(writer, update_multi_registry,
                                      registry, ms, cfg)
                    if cfg.generation_impl == "fused":
                        from ..multisoup import _fused_type_route
                        for tname, t in zip(type_names(cfg), cfg.topos):
                            submit_or_run(
                                writer, update_fused_counters, registry,
                                chunk, _fused_type_route(cfg, t),
                                type_name=tname)
                    submit_or_run(writer, _class_gauges, counts, prev)
                    if by_type is not None:
                        for tname, hsum in by_type.items():
                            submit_or_run(writer, update_health_gauges,
                                          registry, hsum, tname)
                    if ldata is not None and lin_writer is not None:
                        kind, payload = ldata
                        if kind == "window":
                            flush_lineage_window(
                                lin_writer, registry, writer, exp.dir,
                                gen - chunk, gen, payload, lincap,
                                type_names=tnames)
                        else:
                            flush_lineage_probe(lin_writer, registry,
                                                writer, gen - chunk, gen,
                                                payload, type_names=tnames)
                    hb.beat(generation=gen, gens_per_sec=chunk / dt,
                            chunk_seconds=round(dt, 3))
                    if live is not None:
                        # history sample + alert evaluation, ordered
                        # with this chunk's registry mutations (see
                        # mega_soup)
                        live.sample(exp, writer, generation=gen)
                    if prof is not None:
                        if primary:
                            # profile gauges + cumulative folded rewrite
                            # ahead of this chunk's flush_events
                            prof.flush(exp.dir, writer, registry)
                        else:
                            # workers fold gauges only (DESIGN §16)
                            submit_or_run(writer, prof.update_gauges,
                                          registry)
                    # run-dir artifacts are process-0-gated (DESIGN §16)
                    if primary:
                        if dist.active:
                            # live straggler gauges (tail-read on the
                            # writer — file I/O only, see mega_soup)
                            submit_or_run(writer, update_fleet_gauges,
                                          registry, exp.dir, dist)
                        submit_or_run(writer, registry.flush_events, exp)
                        submit_or_run(writer, registry.write_textfile,
                                      os.path.join(exp.dir, "metrics.prom"))
                        if not dist.active:
                            # distributed checkpoints were already saved
                            # synchronously on the loop thread (orbax
                            # barriers across processes)
                            submit_or_run(writer, save_multi_checkpoint,
                                          os.path.join(
                                              exp.dir,
                                              f"ckpt-gen{gen:08d}"),
                                          ckpt_state)
                row["pipeline"] = meter.chunk_done(dt)
                if prof is not None:
                    # utilization decomposition inline after chunk_done —
                    # see mega_soup
                    row["utilization"] = update_utilization_gauges(
                        registry, row["pipeline"])
                # chunk span family reusing the attribution just computed
                emit_chunk_spans(spans, "mega_multisoup", gen, chunk,
                                 row["pipeline"])
                # stamped copy: see mega_soup (gens_regress seq exclusion)
                row = flightrec.record(row)
                # distributed runs skip the bundle's state snapshot (its
                # orbax save would barrier across processes; see mega_soup)
                watchdog_chunk(watchdog, row, exp=exp, registry=registry,
                               snapshot_state=None if dist.active
                               else ckpt_state,
                               save_fn=None if dist.active
                               else save_multi_checkpoint, gen=gen)
            return finish

        preempted = False
        while gen < args.generations:
            if chunk_boundary_faults(exp, chaos, gen, args.generations):
                preempted = True
                break
            chunk = min(args.checkpoint_every, args.generations - gen)
            # non-capture chunks hand their metrics + health (+ lineage)
            # carries to the finisher, which orders them ahead of the
            # chunk's flush
            ms = hs = ldata = None
            lkw = {"lineage": True, "lineage_state": lins,
                   "lineage_capacity": lincap} if lineage_on else {}
            if stores is not None:
                from ..utils import evolve_multi_captured
                # owned=True: state is jax-owned (seed/own_pytree) and
                # rebound every chunk — skip capture's defensive copy
                state = evolve_multi_captured(cfg, state, chunk, stores,
                                              every=args.capture_every,
                                              owned=True, registry=registry,
                                              pipelined=pipelined,
                                              writer=writer)
                if health_on:
                    # end-of-chunk probe per type (one tiny dispatch each,
                    # ordered before the next donation; see mega_soup)
                    hs = tuple(probe_health(w, -1, cfg.epsilon)
                               for w in state.weights)
                if lineage_on:
                    # census-only stand-in for the dynamics carry (no
                    # pids/edges in capture mode; see telemetry.dynamics)
                    from ..soup import probe_dynamics
                    ldata = ("probe",
                             tuple(probe_dynamics(t, w, cfg.epsilon)
                                   for t, w in zip(cfg.topos,
                                                   state.weights)))
            else:
                out = _evolve(state, chunk, owned, health_on, lkw)
                state, ms = out[0], out[1]
                rest = list(out[2:])
                if health_on:
                    hs = rest.pop(0)
                if lineage_on:
                    lt = rest.pop(0)
                    lins, ldata = lt[0], ("window", lt)
            owned = True
            gen += chunk
            # both dispatched BEFORE the next iteration donates state
            # (the metrics/health/lineage carries are fresh jit outputs,
            # never donated):
            counts_dev = _count(state)
            if dist.active:
                # distributed checkpoint: synchronous gather + orbax
                # multihost save on EVERY process's loop thread (see
                # mega_soup — a writer-thread save wedges the mesh)
                ckpt_state = fetch_for_checkpoint(
                    state, dist, meter, registry if primary else None)
                save_multi_checkpoint(os.path.join(exp.dir,
                                                   f"ckpt-gen{gen:08d}"),
                                      ckpt_state, primary=primary)
                if ldata is not None:
                    from ..distributed.hostio import fetch_tree
                    ldata = (ldata[0], fetch_tree(ldata[1]))
            else:
                ckpt_state = snapshot(state) if pipelined else state
            fin = _finisher(gen, chunk, counts_dev, ckpt_state, ms, hs,
                            ldata)
            if chaos is not None:
                fin = chaos.wrap_finisher(fin, gen)
            driver.step(fin)
        finish_pipeline(exp, driver, writer, meter, pipelined)
        if preempted:
            raise Preempted(gen)
        exp.log(f"done: {_format_type_counts(counts)}")
    finally:
        # teardown order (see mega_soup): armed profiler window, pipeline
        # writer, then stores, then the experiment — nested finallys keep
        # meta.json guaranteed
        if watchdog is not None:
            watchdog.stop_trace()
        # stop the sampler + close any armed anomaly trace window before
        # the writer drains (see mega_soup)
        if prof is not None:
            prof.stop()
        if capture is not None:
            capture.close()
        # clear the hostio span sink before this attempt's writer goes
        # down (see mega_soup)
        close_spans()
        try:
            try:
                try:
                    try:
                        if writer is not None:
                            writer.close()
                    finally:
                        # after the writer drained (see mega_soup): stop
                        # the exporter, close metrics_history.jsonl
                        if live is not None:
                            live.close()
                finally:
                    if stores is not None:
                        for store in stores:
                            store.close()
            finally:
                # after the pipeline drained: every queued lineage row is
                # already appended
                if lin_writer is not None:
                    lin_writer.close()
        finally:
            exp.__exit__(*sys.exc_info())
    return exp.dir


@register("mega_multisoup")
def main(argv=None):
    return run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
