"""Pure self-training ("learn to be a fixpoint"), per architecture.

Reference: ``setups/training-fixpoints.py`` — 50 trials × {WW, Agg, RNN},
1000 batch-size-1 SGD epochs on the net's own samples (loop at ``:55-56``),
then classify; saves ``all_counters``/``trajectorys``/``all_names``.
"""

import jax

from ..engine import run_training
from ..experiment import Experiment
from ..init import init_population
from .common import STANDARD_VARIANTS, base_parser, log_counters, register


def build_parser():
    p = base_parser(__doc__)
    p.add_argument("--trials", type=int, default=50)
    p.add_argument("--epochs", type=int, default=1000,
                   help="train calls per trial (training-fixpoints.py:37)")
    p.add_argument("--train-mode", default="sequential",
                   choices=("sequential", "full_batch"),
                   help="sequential = faithful batch_size=1 SGD (SURVEY §2.4.10)")
    p.add_argument("--record", action="store_true")
    return p


def run(args):
    if args.smoke:
        args.trials, args.epochs = 4, 20
    key = jax.random.key(args.seed)
    with Experiment("training_fixpoint", root=args.root, seed=args.seed) as exp:
        all_counters, all_names, trajectories = [], [], {}
        for i, (name, topo) in enumerate(STANDARD_VARIANTS):
            pop = init_population(topo, jax.random.fold_in(key, i), args.trials)
            res = run_training(topo, pop, epochs=args.epochs,
                               epsilon=args.epsilon, train_mode=args.train_mode,
                               record=args.record)
            log_counters(exp, name, res.counts)
            all_counters.append(res.counts)
            all_names.append(name)
            if args.record:
                trajectories[topo.variant] = res.trajectory
        exp.save(all_counters=jax.numpy.stack(all_counters), all_names=all_names)
        if args.record:
            exp.save(trajectorys=trajectories)
        return exp.dir


@register("training_fixpoints")
def main(argv=None):
    return run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
