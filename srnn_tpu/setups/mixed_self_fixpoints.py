"""Interleaved self-attack + self-training sweep.

Reference: ``setups/mixed-self-fixpoints.py`` — per arch, sweep
trains-per-self-attack over {0, 50, ..., 500} (``:58``), 20 trials of up to
4 self-attacks each (``:81-86``), record the fixpoint rate
(fix_zero + fix_other) / trials; saves ``all_names``/``all_data`` with
``{'xs', 'ys'}`` per arch.
"""

import jax
import numpy as np

from ..engine import run_mixed_fixpoint
from ..experiment import Experiment
from ..init import init_population
from .common import STANDARD_VARIANTS, base_parser, log_sweep, register


def build_parser():
    p = base_parser(__doc__)
    p.add_argument("--trials", type=int, default=20)
    p.add_argument("--selfattacks", type=int, default=4)
    p.add_argument("--train-values", type=int, nargs="*",
                   default=[50 * i for i in range(11)])
    p.add_argument("--train-mode", default="sequential",
                   choices=("sequential", "full_batch"))
    return p


def run(args):
    if args.smoke:
        args.trials, args.selfattacks, args.train_values = 3, 2, [0, 5]
    key = jax.random.key(args.seed)
    with Experiment("mixed-self-fixpoints", root=args.root, seed=args.seed) as exp:
        all_names, all_data = [], []
        for i, (name, topo) in enumerate(STANDARD_VARIANTS):
            xs, ys = [], []
            for j, trains in enumerate(args.train_values):
                pop = init_population(
                    topo, jax.random.fold_in(jax.random.fold_in(key, i), j),
                    args.trials)
                res = run_mixed_fixpoint(
                    topo, pop, trains_per_application=trains,
                    step_limit=args.selfattacks, epsilon=args.epsilon,
                    train_mode=args.train_mode)
                counts = np.asarray(res.counts)
                xs.append(trains)
                # fixpoint rate = (fix_zero + fix_other) / trials (:90)
                ys.append(float(counts[1] + counts[2]) / args.trials)
            all_names.append(name)
            all_data.append({"xs": xs, "ys": ys})
            log_sweep(exp, name, all_data[-1])
        exp.save(all_names=all_names, all_data=all_data)
        return exp.dir


@register("mixed_self_fixpoints")
def main(argv=None):
    return run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
