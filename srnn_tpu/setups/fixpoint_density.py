"""Natural density of fixpoints among random initializations.

Reference: ``setups/fixpoint-density.py`` — 100,000 random inits per arch
(WW and Agg; the script notes "FFT doesn't work though", ``:34-35``),
classified immediately with no dynamics (``:54``).  Statistics are a direct
function of the init law, which matches keras defaults (``srnn_tpu.init``).

On TPU the 100k trials classify as a handful of batched forwards instead of
100k ``model.predict`` calls.
"""

import jax

from ..engine import fixpoint_density
from ..experiment import Experiment
from ..init import init_population
from .common import (STANDARD_VARIANTS, base_parser, log_counters, register,
                     save_run_config, submit_to_service)


def build_parser():
    p = base_parser(__doc__)
    p.add_argument("--trials", type=int, default=100_000)
    p.add_argument("--batch", type=int, default=25_000,
                   help="classification batch (bounds device memory)")
    return p


def run(args):
    if args.smoke:
        args.trials, args.batch = 64, 32
    variants = STANDARD_VARIANTS[:2]  # WW + Agg, like the reference (:42-43)
    with Experiment("fixpoint_density", root=args.root, seed=args.seed) as exp:
        # the PRNG stream is keyed per batch on the cumulative sample count,
        # so reproducing/rescanning a run needs trials AND batch — record
        # the invocation (examples/natural_cycles.py reads this; the
        # execution_mode field says whether a service computed it)
        save_run_config(exp.dir, args, ("trials", "batch", "epsilon"))
        if args.service:
            # submit mode: the service runs the same sweep (stacked with
            # other tenants when shapes match — bitwise-equal results)
            # and this process only logs/saves the artifacts
            result = submit_to_service(
                args, "fixpoint_density",
                {"seed": args.seed, "trials": args.trials,
                 "batch": args.batch, "epsilon": args.epsilon},
                tenant=f"fixpoint_density-seed{args.seed}")
            all_names = result["variant_names"]
            all_counters = [jax.numpy.asarray(c, jax.numpy.int32)
                            for c in result["counters"]]
            for name, total in zip(all_names, all_counters):
                log_counters(exp, name, total)
            exp.save(all_counters=jax.numpy.stack(all_counters),
                     all_names=all_names)
            return exp.dir
        key = jax.random.key(args.seed)
        all_counters, all_names = [], []
        for i, (name, topo) in enumerate(variants):
            total = jax.numpy.zeros(5, jax.numpy.int32)
            done = 0
            while done < args.trials:
                n = min(args.batch, args.trials - done)
                pop = init_population(
                    topo, jax.random.fold_in(jax.random.fold_in(key, i), done), n)
                total = total + fixpoint_density(topo, pop, args.epsilon)
                done += n
            log_counters(exp, name, total)
            all_counters.append(total)
            all_names.append(name)
        exp.save(all_counters=jax.numpy.stack(all_counters), all_names=all_names)
        return exp.dir


@register("fixpoint_density")
def main(argv=None):
    return run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
