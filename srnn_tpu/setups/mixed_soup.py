"""Soup sweep over self-training intensity.

Reference: ``setups/mixed-soup.py`` — per arch (WW, Agg; RNN commented out
there), soup of 10 particles, life 5 generations, attacking_rate 0.1,
learn_from off (−1 sentinel), sweep train ∈ {0, 10, ..., 100} (``:61``),
10 trial soups per point; record avg zero-fixpoints (ys) and avg non-zero
fixpoints (zs) per soup (``:94-96``); saves ``all_names``/``all_data``.
"""

import jax

from ..experiment import Experiment
from ..soup import SoupConfig
from .common import (STANDARD_VARIANTS, base_parser, count_soup_trials,
                     evolve_trials, log_sweep, register)


def build_parser():
    p = base_parser(__doc__)
    p.add_argument("--trials", type=int, default=10)
    p.add_argument("--soup-size", type=int, default=10)
    p.add_argument("--soup-life", type=int, default=5)
    p.add_argument("--train-values", type=int, nargs="*",
                   default=[10 * i for i in range(11)])
    p.add_argument("--attacking-rate", type=float, default=0.1)
    p.add_argument("--train-mode", default="sequential",
                   choices=("sequential", "full_batch"))
    return p


def run(args):
    if args.smoke:
        args.trials, args.soup_life, args.train_values = 2, 2, [0, 3]
    key = jax.random.key(args.seed)
    variants = STANDARD_VARIANTS[:2]  # reference runs WW + Agg only (:66-68)
    with Experiment("mixed-soup", root=args.root, seed=args.seed) as exp:
        all_names, all_data = [], []
        for i, (name, topo) in enumerate(variants):
            xs, ys, zs = [], [], []
            for j, trains in enumerate(args.train_values):
                cfg = SoupConfig(
                    topo=topo, size=args.soup_size,
                    attacking_rate=args.attacking_rate,
                    learn_from_rate=-1.0, learn_from_severity=-1,
                    train=trains, epsilon=args.epsilon,
                    train_mode=args.train_mode)
                states = evolve_trials(
                    cfg, jax.random.fold_in(jax.random.fold_in(key, i), j),
                    args.trials, args.soup_life)
                counts = count_soup_trials(cfg, states)
                xs.append(trains)
                ys.append(float(counts[1]) / args.trials)  # avg fix_zero per soup
                zs.append(float(counts[2]) / args.trials)  # avg fix_other per soup
            all_names.append(name)
            all_data.append({"xs": xs, "ys": ys, "zs": zs})
            log_sweep(exp, name, all_data[-1])
        exp.save(all_names=all_names, all_data=all_data)
        return exp.dir


@register("mixed_soup")
def main(argv=None):
    return run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
