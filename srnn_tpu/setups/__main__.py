import os
import sys

from . import REGISTRY


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    if not argv or argv[0] in ("-h", "--help") or argv[0] not in REGISTRY:
        names = "\n  ".join(sorted(REGISTRY))
        print(f"usage: python -m srnn_tpu.setups <name> [flags]\n\nnames:\n  {names}")
        return 2 if argv and argv[0] not in ("-h", "--help") else 0
    if os.environ.get("SRNN_SETUPS_PLATFORM") == "cpu":
        # config-level CPU pin for subprocess callers (tests, CI): the axon
        # sitecustomize overrides the JAX_PLATFORMS env var at register()
        # time, so the env route cannot keep a child off a wedged tunnel
        from ..utils.backend import force_cpu

        force_cpu()
    # supervised mega runs speak a CLI exit-code vocabulary (0 clean,
    # 3 recovered; the raising outcomes — 75 preempted-clean, 69
    # retries-exhausted, 71 host-lost (a distributed peer/coordinator is
    # gone; distributed.launch re-ramps) — exit via SystemExit from the
    # run): tpu_watch.sh keys on these instead of treating every nonzero
    # exit as a wedge.
    # Reset first: a command that never enters Supervisor.run must not
    # inherit the previous command's report in a long-lived process.
    from ..resilience import exit_code_for_report, supervisor

    supervisor.LAST_REPORT = None
    try:
        out = REGISTRY[argv[0]](argv[1:])
    except SystemExit as e:
        from ..distributed import context

        if context().active and isinstance(e.code, int) and e.code:
            # a multi-process worker's failing exit code must SURVIVE:
            # normal interpreter teardown runs jax.distributed's atexit
            # shutdown barrier, which blocks on peers still
            # mid-collective and then ABORTS the process (SIGABRT 134),
            # destroying the code the launcher tier keys on.  Everything
            # durable (checkpoint, writer drain, meta.json) already
            # happened in the run's own finally blocks.
            sys.stdout.flush()
            sys.stderr.flush()
            os._exit(e.code)
        raise
    if isinstance(out, str):
        print(out)  # the run directory — scriptable like the run() API
    return exit_code_for_report(supervisor.LAST_REPORT)


if __name__ == "__main__":
    sys.exit(main())
