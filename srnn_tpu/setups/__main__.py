import sys

from . import REGISTRY


def main(argv=None):
    argv = sys.argv[1:] if argv is None else argv
    if not argv or argv[0] in ("-h", "--help") or argv[0] not in REGISTRY:
        names = "\n  ".join(sorted(REGISTRY))
        print(f"usage: python -m srnn_tpu.setups <name> [flags]\n\nnames:\n  {names}")
        return 2 if argv and argv[0] not in ("-h", "--help") else 0
    return REGISTRY[argv[0]](argv[1:]) and 0


if __name__ == "__main__":
    sys.exit(main())
