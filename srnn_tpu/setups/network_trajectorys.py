"""Trajectory recordings of single-net self-application runs.

Reference: ``setups/network_trajectorys.py`` — the active block runs 20
weightwise nets through ``FixpointExperiment.run_net`` with state recording
(``:20-29``); dormant ``if False`` blocks cover the other archs and
training-trajectory variants.  Here every arch is a flag away, and the
trajectory artifact is the dense ``(steps+1, N, P)`` weight history that
``srnn_tpu.viz`` embeds (replacing ``trajectorys.dill``).
"""

import jax

from ..engine import run_fixpoint, run_training
from ..experiment import Experiment
from ..init import init_population
from ..topology import Topology
from .common import base_parser, log_counters, register

_TOPOS = {
    "weightwise": Topology("weightwise", width=2, depth=2),
    "aggregating": Topology("aggregating", width=2, depth=2, aggregates=4),
    "fft": Topology("fft", width=2, depth=2, aggregates=4),
    "recurrent": Topology("recurrent", width=2, depth=2),
}


def build_parser():
    p = base_parser(__doc__)
    p.add_argument("--variant", default="weightwise", choices=sorted(_TOPOS))
    p.add_argument("--runs", type=int, default=20,
                   help="trajectories to record (:23)")
    p.add_argument("--steps", type=int, default=100)
    p.add_argument("--mode", default="apply", choices=("apply", "train"),
                   help="'apply' = self-application runs (:20-29); 'train' = "
                        "the dormant weightwise_learning block (:53-67)")
    return p


def run(args):
    if args.smoke:
        args.runs, args.steps = 3, 10
    topo = _TOPOS[args.variant]
    key = jax.random.key(args.seed)
    name = f"{args.variant}_self_application" if args.mode == "apply" \
        else f"{args.variant}_learning"
    with Experiment(name, root=args.root, seed=args.seed) as exp:
        pop = init_population(topo, key, args.runs)
        if args.mode == "apply":
            res = run_fixpoint(topo, pop, step_limit=args.steps,
                               epsilon=args.epsilon, record=True)
        else:
            res = run_training(topo, pop, epochs=args.steps,
                               epsilon=args.epsilon, record=True)
        log_counters(exp, name, res.counts)
        exp.save(trajectorys={"weights": res.trajectory, "classes": res.classes},
                 all_counters=res.counts)
        return exp.dir


@register("network_trajectorys")
def main(argv=None):
    return run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
