"""Soup run with full trajectory + event recording.

Reference: ``setups/soup_trajectorys.py`` — Soup(20, weightwise+train),
train=30, learn_from off, remove divergent/zero, 100 generations, log the
final count and save ``soup.dill`` (``:12-32``).  The artifact here is the
dense per-generation history (weights, uids, action codes, counterparts) —
the vectorized equivalent of ``historical_particles[uid].states``.
"""

import jax
import numpy as np

from ..experiment import Experiment, format_counters, save_checkpoint
from ..soup import ACTION_NAMES, SoupConfig, count, evolve, seed
from ..topology import Topology
from .common import base_parser, register, save_run_config, submit_to_service


def build_parser():
    p = base_parser(__doc__)
    p.add_argument("--soup-size", type=int, default=20)
    p.add_argument("--generations", type=int, default=100)
    p.add_argument("--train", type=int, default=30)
    p.add_argument("--attacking-rate", type=float, default=0.1)
    p.add_argument("--train-mode", default="sequential",
                   choices=("sequential", "full_batch"))
    p.add_argument("--checkpoint", action="store_true",
                   help="also write a resumable orbax checkpoint of the final state")
    p.add_argument("--store", action="store_true",
                   help="stream frames to a native trajstore (soup.traj) "
                        "instead of materializing the full history on device "
                        "— the mega-soup path")
    p.add_argument("--capture-every", type=int, default=1,
                   help="store every k-th generation (trajectory stride)")
    return p


def run(args):
    if args.smoke:
        args.soup_size, args.generations, args.train = 6, 5, 2
    topo = Topology("weightwise", width=2, depth=2)
    cfg = SoupConfig(
        topo=topo, size=args.soup_size, attacking_rate=args.attacking_rate,
        learn_from_rate=-1.0, train=args.train,
        remove_divergent=True, remove_zero=True,
        epsilon=args.epsilon, train_mode=args.train_mode)
    with Experiment("soup", root=args.root, seed=args.seed) as exp:
        if args.service and not args.store:
            # submit mode: the service evolves this soup (stacked with
            # matching tenants — bitwise-equal to the local run) and
            # returns counters + final state.  The dense per-generation
            # history is NOT batched: runs that need it (--store or the
            # record path below) dispatch locally.
            save_run_config(exp.dir, args,
                            ("soup_size", "generations", "train",
                             "attacking_rate", "epsilon", "train_mode"))
            result = submit_to_service(
                args, "soup",
                {"seed": args.seed, "size": args.soup_size,
                 "generations": args.generations, "train": args.train,
                 "attacking_rate": args.attacking_rate,
                 "learn_from_rate": -1.0, "remove_divergent": True,
                 "remove_zero": True, "epsilon": args.epsilon,
                 "train_mode": args.train_mode},
                tenant=f"soup-seed{args.seed}")
            counts = np.asarray(result["counters"])
            exp.log(format_counters(counts), counts=counts)
            exp.save(action_names=list(ACTION_NAMES), all_counters=counts)
            # the final state goes under its OWN artifact name: "soup" is
            # the (G, N, P) per-generation history below, and readers
            # (viz) take weights.shape[0] as the time axis — a final
            # (N, P) state under that key would render silently wrong.
            # The service omits the state above a size ceiling; counters
            # and the log line are the run's record either way.
            if "weights" in result:
                exp.save(soup_final={
                    "weights": np.asarray(result["weights"], np.float32),
                    "uids": np.asarray(result["uids"], np.int32)})
            return exp.dir
        state = seed(cfg, jax.random.key(args.seed))
        if args.store:
            from ..utils import TrajStore, evolve_captured

            with TrajStore(f"{exp.dir}/soup.traj", cfg.size,
                           topo.num_weights) as store:
                final = evolve_captured(cfg, state, args.generations, store,
                                        every=args.capture_every)
            counts = count(cfg, final)
            exp.log(format_counters(counts), counts=np.asarray(counts))
            exp.save(action_names=list(ACTION_NAMES), all_counters=counts)
            if args.checkpoint:
                save_checkpoint(f"{exp.dir}/checkpoint", final)
            return exp.dir
        final, (events, weights_hist, uids_hist) = evolve(
            cfg, state, generations=args.generations, record=True)
        counts = count(cfg, final)
        exp.log(format_counters(counts), counts=np.asarray(counts))
        exp.save(soup={
            "weights": np.asarray(weights_hist),      # (G, N, P)
            "uids": np.asarray(uids_hist),            # (G, N)
            "action": np.asarray(events.action),      # (G, N) ACTION_NAMES codes
            "counterpart": np.asarray(events.counterpart),
            "loss": np.asarray(events.loss),
        }, action_names=list(ACTION_NAMES), all_counters=counts)
        if args.checkpoint:
            save_checkpoint(f"{exp.dir}/checkpoint", final)
        return exp.dir


@register("soup_trajectorys")
def main(argv=None):
    return run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
