"""The north-star mega-soup: BASELINE.json's 1M-particle / 1000-generation
workload as a resumable production run.

No reference equivalent — the reference cannot exceed a few hundred
particles (one keras model per particle, ``soup.py:37-49``).  This entry
point is the showcase composition of the runtime: the weightwise soup at
mega scale (``layout='popmajor'`` by default — particle axis on the TPU
lanes), periodic orbax checkpoints with bit-exact ``--resume``, per-chunk
class-count logging, and optional strided trajectory capture to the native
``.traj`` store.

    python -m srnn_tpu.setups mega_soup --size 1000000 --generations 1000
    python -m srnn_tpu.setups mega_soup --resume experiments/exp-mega-soup-…-0

Interrupted runs continue from the last checkpoint on the SAME PRNG stream,
so an interrupted-and-resumed run reproduces an uninterrupted one exactly.
"""

import os
import sys

import jax
import numpy as np

from ..experiment import (counters_dict, format_counters,
                          restore_checkpoint, save_checkpoint)
from ..soup import (ACT_DIV_DEAD, ACT_ZERO_DEAD, SoupConfig, count, evolve,
                    evolve_donated, probe_dynamics, seed)
from ..telemetry import Heartbeat, MetricsRegistry
from ..telemetry.device import probe_health
from ..telemetry.flightrec import health_summary, update_health_gauges
from ..telemetry.soup_metrics import (set_precision_gauges,
                                      update_class_gauges,
                                      update_fused_counters, update_registry)
from ..resilience import Preempted, supervised_run
from ..telemetry.flightrec import record_recovery
from ..utils.aot import ensure_compilation_cache
from ..utils.pipeline import snapshot, submit_or_run
from ..topology import Topology
from ..distributed import add_distributed_args
from ..telemetry.profiler import update_utilization_gauges
from .common import (add_dynamics_args, add_flightrec_args,
                     add_pipeline_args, add_profile_args,
                     add_resilience_args, add_telemetry_args, base_parser,
                     build_soup_mesh, chunk_boundary_faults, close_spans,
                     emit_chunk_spans, fetch_for_checkpoint,
                     finish_pipeline, flush_lineage_probe,
                     flush_lineage_window, init_distributed,
                     latest_checkpoint, load_run_config, make_flightrec,
                     make_lineage, make_live_plane, make_on_stall,
                     make_pipeline, make_profiler, make_spans,
                     note_restart, open_run, probe_run_costs, register,
                     save_run_config, set_distributed_gauges, stage_label,
                     update_fleet_gauges, watchdog_chunk)


def build_parser():
    p = base_parser(__doc__)
    p.add_argument("--size", type=int, default=1_000_000)
    p.add_argument("--generations", type=int, default=1000)
    p.add_argument("--attacking-rate", type=float, default=0.1)
    p.add_argument("--learn-from-rate", type=float, default=-1.0)
    p.add_argument("--train", type=int, default=0)
    p.add_argument("--train-mode", default="sequential",
                   choices=("sequential", "full_batch"))
    p.add_argument("--layout", default="popmajor",
                   choices=("rowmajor", "popmajor"))
    p.add_argument("--checkpoint-every", type=int, default=100,
                   help="generations per checkpoint/log chunk")
    p.add_argument("--capture-every", type=int, default=0, metavar="K",
                   help="stream every K-th generation's full soup frame to "
                        "the native .traj store (0 = off); must divide "
                        "--checkpoint-every")
    p.add_argument("--resume", default=None, metavar="RUN_DIR",
                   help="continue a previous run from its latest checkpoint")
    p.add_argument("--attack-impl", choices=("full", "compact"),
                   default="full",
                   help="'compact': transform only attacked lanes "
                        "(popmajor; see SoupConfig.attack_impl)")
    p.add_argument("--learn-from-impl", choices=("full", "compact"),
                   default="full",
                   help="'compact': imitation-SGD on learner lanes only")
    p.add_argument("--train-impl", choices=("xla", "pallas"),
                   default="xla",
                   help="'pallas': fused VMEM batch-1 SGD chain for the "
                        "train/learn phases (TPU-measured 3.5x on the "
                        "full-dynamics generation; see SoupConfig.train_impl)")
    p.add_argument("--generation-impl", choices=("phases", "fused"),
                   default="phases",
                   help="'fused' runs the whole generation as one "
                        "megakernel launch per lane block on Mosaic "
                        "backends (popmajor; ops/pallas_generation.py; "
                        "bit-identical XLA fallback elsewhere)")
    p.add_argument("--population-dtype", choices=("f32", "bf16", "int8"),
                   default="f32",
                   help="population storage dtype; bf16 halves population "
                        "HBM and gather bytes, computes in f32, weight "
                        "drift documented in PARITY.md")
    p.add_argument("--respawn-draws", choices=("perparticle", "fused"),
                   default="fused",
                   help="respawn replacement draws: 'fused' (default here — "
                        "one-call draw, same iid glorot law, the mega-scale "
                        "fast path) or 'perparticle' (seed-identical "
                        "reference-style per-net draws)")
    p.add_argument("--sharded", action="store_true",
                   help="shard the particle axis over ALL visible devices "
                        "(shard_map data parallel); trajectory capture then "
                        "writes one .traj shard per process (multihost-safe) "
                        "merged offline by read_sharded_store")
    add_pipeline_args(p)
    add_telemetry_args(p)
    add_profile_args(p)
    add_flightrec_args(p)
    add_dynamics_args(p)
    add_resilience_args(p)
    add_distributed_args(p)
    return p


_CONFIG_FIELDS = ("size", "attacking_rate", "learn_from_rate", "train",
                  "train_mode", "layout", "epsilon", "capture_every",
                  "sharded", "respawn_draws", "attack_impl",
                  "learn_from_impl", "train_impl", "generation_impl",
                  "population_dtype")


def run(args):
    """One supervised mega run: ``_run_once`` under the elastic
    supervisor (``srnn_tpu.resilience``) — classified faults restart from
    the newest intact checkpoint with backoff and, on device loss, a
    topology re-ramp; SIGTERM exits preempted-clean after a graceful
    drain.  ``--max-restarts 0`` degrades to the bare loop (faults
    propagate unchanged)."""
    return supervised_run(args, _run_once)


def _run_once(args, ctx=None):
    chaos = ctx.chaos if ctx is not None else None
    # multi-process bring-up FIRST (before anything probes devices);
    # inactive (free) for plain runs.  `primary` gates all host I/O but
    # heartbeats — the process-0 contract, DESIGN §16.
    dist = init_distributed(args)
    primary = dist.primary if dist.active else True
    if args.smoke:
        # shrink only the knobs left at their defaults, so e.g.
        # `--smoke --generations 4` still means 4 generations
        args.size = 64 if args.size == 1_000_000 else args.size
        args.generations = 6 if args.generations == 1000 else args.generations
        args.checkpoint_every = 2 if args.checkpoint_every == 100 \
            else args.checkpoint_every
    # validate everything cheap BEFORE creating/attaching the Experiment, so
    # a bad invocation can never leave a run dir without meta.json
    ckpt = None
    if args.resume:
        # original dynamics win over CLI; legacy configs written before a
        # field existed must resume with the behavior they actually ran
        # (per-particle draws, full-width phases) — never a newer CLI value
        load_run_config(args.resume, args, _CONFIG_FIELDS,
                        legacy_defaults={"respawn_draws": "perparticle",
                                         "attack_impl": "full",
                                         "learn_from_impl": "full",
                                         "train_impl": "xla",
                                         "generation_impl": "phases",
                                         "population_dtype": "f32"})
        ckpt = latest_checkpoint(args.resume)
    if (args.attack_impl != "full" or args.learn_from_impl != "full") \
            and args.layout != "popmajor":
        raise SystemExit("--attack-impl/--learn-from-impl compact need "
                         "--layout popmajor")
    if args.train_impl == "pallas" and args.layout != "popmajor":
        raise SystemExit("--train-impl pallas is the popmajor lane kernel; "
                         "--layout rowmajor needs --train-impl xla")
    if args.generation_impl == "fused" and args.layout != "popmajor":
        raise SystemExit("--generation-impl fused is the popmajor lane "
                         "megakernel; --layout rowmajor needs phases")
    if args.capture_every < 0:
        raise SystemExit("--capture-every must be >= 0")
    if args.capture_every and args.checkpoint_every % args.capture_every:
        raise SystemExit("--capture-every must divide --checkpoint-every")
    if args.capture_every and args.generations % args.capture_every:
        # otherwise the FINAL partial chunk (generations % checkpoint_every)
        # fails evolve_captured's divisibility check hours into the run
        raise SystemExit("--capture-every must divide --generations")
    cfg = _make_config(args)
    # persistent executable cache: a restarted/resumed run (or one warmed by
    # `python -m srnn_tpu.precompile`) deserializes the chunk executable
    # instead of re-paying XLA inside the first timed chunk
    ensure_compilation_cache()

    mesh = None
    if args.sharded:
        # the supervisor's device budget (initially --max-devices, shrunk
        # by a topology re-ramp) bounds the mesh — by verified-survivor
        # IDENTITY after a device/host loss, not just count; None = all
        # visible.  Publishing the population size first lets a re-ramp
        # snap to a device count the shards actually divide over.
        # build_soup_mesh routes multislice topologies (TPU pods,
        # multi-process CPU meshes, SRNN_FORCE_SLICES CI splits) through
        # reramp_soup_mesh — the live (slices, soup) 2-D path.
        if ctx is not None:
            ctx.shard_sizes = (args.size,)
        mesh = build_soup_mesh(ctx, (args.size,))

    if args.resume:
        exp = open_run(args, "mega-soup", dist, resume=args.resume)
        # every process restores the same checkpoint files; placement is
        # multi-process-aware (each contributes its addressable shards)
        state = restore_checkpoint(ckpt)
        if mesh is not None:
            from ..parallel import place_sharded_state
            state = place_sharded_state(mesh, state)
        else:
            # restored arrays may be zero-copy views of host memory; the
            # donated chunk loop below must only ever donate jax-owned
            # buffers, so materialize a device-owned copy first
            from ..utils.aot import own_pytree
            state = own_pytree(state)
        exp.log(f"resumed from {os.path.basename(ckpt)} "
                f"at generation {int(state.time)}")
    else:
        exp = open_run(args, "mega-soup", dist)
        if primary:
            save_run_config(exp.dir, args, _CONFIG_FIELDS)
        if mesh is not None:
            from ..parallel import make_sharded_state
            state = make_sharded_state(cfg, mesh, jax.random.key(args.seed))
        else:
            state = seed(cfg, jax.random.key(args.seed))
        exp.log(f"mega-soup N={cfg.size} layout={cfg.layout} "
                f"attack={cfg.attacking_rate} train={cfg.train}/{cfg.train_mode}"
                + (f" sharded over {mesh.devices.size} devices"
                   if mesh is not None else "")
                + (f" across {dist.num_processes} processes"
                   if dist.active else ""))
    note_restart(exp, ctx)

    def _count(s):
        # returns the DEVICE array: the dispatch is cheap and ordered
        # before the next chunk donates s's buffers; the np.asarray
        # resolve happens in the chunk's (possibly deferred) finisher
        if mesh is not None:
            from ..parallel import sharded_count
            return sharded_count(cfg, mesh, s)
        return count(cfg, s)

    # telemetry: per-run metrics registry (science counters from the
    # in-scan device carry, class gauges from the chunk counts) flushed to
    # events.jsonl + metrics.prom every chunk, and fsync'd heartbeat rows
    # so a killed run names its last stage/generation/rate
    registry = MetricsRegistry()
    set_precision_gauges(registry, cfg)
    set_distributed_gauges(registry, dist, mesh)
    # block autotuner (srnn_tpu.autotune; --no-autotune = the A/B bitwise
    # oracle): measure-or-memo the fused generation's lane block BEFORE
    # warmup/first compile, so every executable this run builds is the
    # tuned program; emits soup_autotune_* + one {"kind":"autotune"} row
    if primary:
        from .. import autotune
        autotune.autotune_for_run(cfg, registry=registry, exp=exp,
                                  no_autotune=args.no_autotune)
    if cfg.generation_impl == "fused":
        from ..soup import _fused_kernel_route
        exp.log("generation_impl=fused: "
                + ("Mosaic megakernel" if _fused_kernel_route(cfg)
                   else "XLA phase-chain fallback (no Mosaic backend)")
                + f", population_dtype={cfg.population_dtype}")
    # flight recorder: bounded ring of per-chunk health rows + the anomaly
    # watchdog that turns a pathological chunk into a triage bundle
    health_on = not args.no_health
    flightrec, watchdog = make_flightrec(args)
    if not primary:
        # triage bundles are run-dir artifacts: process-0-gated like every
        # other host write (two processes tripping at the same generation
        # would collide on the bundle dir)
        watchdog = None
    # a restarted attempt folds its recovery history into THIS attempt's
    # registry + ring (restart counters, recovery-seconds histogram)
    record_recovery(registry, flightrec, ctx)
    # replication-dynamics observatory: the persistent lineage carry + the
    # lineage.jsonl window stream (telemetry.dynamics; --lineage opt-in)
    lin, lin_writer, lincap = make_lineage(
        args, exp.dir, sizes=(cfg.size,), start_gen=int(state.time),
        resume=bool(args.resume), mesh=mesh, primary=primary)
    lineage_on = lin is not None
    if lineage_on and lin_writer is not None:
        exp.log(f"lineage: epoch {lin_writer.epoch}, "
                f"{lincap} edge rows/window -> lineage.jsonl")
    store = writer = live = prof = capture = None
    import time as _time
    try:
        # the writer's non-daemon worker spawns INSIDE the try: any
        # exception from here on (a bad-restore readback in the first
        # beat, a store open failure, ^C) reaches writer.close() in the
        # finally — outside it, a crash would strand the thread in
        # q.get() and hang interpreter shutdown instead of exiting
        pipelined, writer, meter, driver = make_pipeline(args, registry,
                                                         "mega_soup")
        if chaos is not None and writer is not None:
            chaos.attach_writer(writer)
        driver.on_stall = make_on_stall(exp, flightrec, registry,
                                        lambda: gen) if primary else None
        # fleet observatory: structured chunk/gather spans (host-only —
        # the evolved state is bit-identical with --no-spans, tested)
        spans = make_spans(args, exp, registry, writer, dist, "mega_soup")
        # live telemetry plane (--no-export = the bitwise A/B oracle):
        # history rings + metrics_history.jsonl + alert engine, sampled
        # once per chunk in the finisher; /metrics + /healthz HTTP
        # endpoint when --metrics-port is set
        # continuous profiling plane (--no-profile = its bitwise A/B
        # oracle): the 50Hz host stack sampler on every process, the
        # anomaly capture primary-only, hooked on the alert engine's
        # firing edge through the live plane's ordered sample job
        prof, capture = make_profiler(args, exp, registry, dist,
                                      "mega_soup")
        live = make_live_plane(args, exp, registry, dist, "mega_soup",
                               capture=capture)
        hb = Heartbeat(exp, stage=stage_label("mega_soup", dist),
                       total_generations=args.generations,
                       registry=registry,
                       fsync_every=args.heartbeat_fsync_every,
                       writer=writer)
        hb.beat(generation=int(state.time))

        if args.capture_every:
            from ..utils import TrajStore, truncate_sharded_frames
            traj_path = os.path.join(exp.dir, "soup.traj")
            if args.resume:
                # drop frames captured AFTER the restored checkpoint (a kill
                # between a capture flush and the next checkpoint finalizing)
                # so the re-evolved generations aren't appended twice —
                # across every per-process shard in a sharded run
                truncate_sharded_frames(
                    traj_path, int(state.time) // args.capture_every)
            # resume APPENDS to the existing store (header-validated, torn
            # tail dropped) — previously captured frames are never lost
            if mesh is not None:
                from ..utils import open_process_shard
                store = open_process_shard(cfg, traj_path,
                                           mode="a" if args.resume else "w")
            else:
                store = TrajStore(traj_path,
                                  n_particles=cfg.size,
                                  n_weights=cfg.topo.num_weights,
                                  mode="a" if args.resume else "w")
            if store.existing_frames:
                exp.log(f"soup.traj: appending after "
                        f"{store.existing_frames} existing frames")
            exp.log(f"capturing every {args.capture_every} generations "
                    f"to soup.traj"
                    + (f" ({jax.process_count()} process shards)"
                       if mesh is not None and jax.process_count() > 1 else ""))
            if writer is not None:
                # crash path: even if the loop dies mid-chunk, close()
                # drains the queued appends and joins the store's flush
                writer.add_close_hook(store.join)
        with meter.waiting():
            counts = np.asarray(_count(state))
        # Donation discipline.  Unsharded chunks are ALL-donated — every
        # state entering the loop is jax-owned (seed is a jit output, a
        # restore is own_pytree-copied above), and using ONE executable for
        # every chunk keeps runs bitwise chunking-invariant (the donated
        # and plain programs may differ by fusion ulps, so mixing them
        # would break bit-exact resume).  The sharded path donates only
        # states this loop itself produced (first chunk plain): a
        # device_put-placed restore has no such ownership guarantee.
        #
        # Pipelined order per iteration: dispatch chunk k's device work,
        # dispatch its count, snapshot the state for the checkpoint (both
        # MUST precede chunk k+1's donating dispatch — device-stream order
        # makes them read pre-donation bytes), then hand the host finisher
        # to the driver, which runs it one iteration later — with chunk
        # k+1 already queued on the device.  `gen` advances host-side so
        # the loop condition never forces a device sync.
        sh_owned = False
        gen = int(state.time)
        # cost plane (telemetry.costs; --no-costs = the A/B oracle):
        # AOT-probe the chunk program against the warmup-identical
        # abstract skeleton — ledger row, soup_hlo_flops/soup_hbm_bytes
        # gauges into this run's registry, and the {"kind":"cost"} row
        # the report roofline derives from.  Host-side only; capture
        # chunks dispatch per-generation programs, so no probe there.
        if primary and store is None and gen < args.generations:
            from ..utils.aot import abstract_lineage_state, \
                abstract_soup_state
            chunk0 = min(args.checkpoint_every, args.generations - gen)
            pkw = {"generations": chunk0, "metrics": True}
            if health_on:
                pkw["health"] = True
            if lineage_on:
                pkw.update(lineage=True,
                           lineage_state=abstract_lineage_state(
                               cfg.size, mesh=mesh),
                           lineage_capacity=lincap)
            st_abs = abstract_soup_state(cfg, mesh=mesh)
            if mesh is not None:
                from ..parallel import sharded_evolve
                probe_run_costs(args, exp, registry, "mega_soup.chunk",
                                sharded_evolve, (cfg, mesh, st_abs), pkw,
                                particles=cfg.size, generations=chunk0)
            else:
                probe_run_costs(args, exp, registry, "mega_soup.chunk",
                                evolve_donated, (cfg, st_abs), pkw,
                                particles=cfg.size, generations=chunk0)
        t_last = _time.perf_counter()

        def _finisher(gen, chunk, counts_dev, ckpt_state, m=None, h=None,
                      ldata=None):
            def finish():
                nonlocal counts, t_last
                with meter.waiting():
                    new_counts = np.asarray(counts_dev)  # chunk landed
                prev, counts = counts, new_counts
                now = _time.perf_counter()
                dt, t_last = max(now - t_last, 1e-9), now
                exp.log(f"gen {gen}/{args.generations}  "
                        f"{chunk / dt:.2f} gens/s  {format_counters(counts)}",
                        generation=gen, gens_per_sec=round(chunk / dt, 3),
                        counts=counters_dict(counts))
                # flight-recorder row: resolve the tiny health/metrics
                # carries now (the chunk landed with the counts above)
                row = {"gen": gen, "chunk": chunk,
                       "gens_per_sec": round(chunk / dt, 3),
                       "counts": counters_dict(counts), "seed": args.seed}
                hsum = None
                if m is not None:
                    acts = np.asarray(m.actions)
                    row["respawns_divergent"] = int(acts[ACT_DIV_DEAD])
                    row["respawns_zero"] = int(acts[ACT_ZERO_DEAD])
                    row["respawns"] = row["respawns_divergent"] \
                        + row["respawns_zero"]
                    row["particle_gens"] = chunk * cfg.size
                if h is not None:
                    hsum = health_summary(h, cfg.size)
                    row["health"] = hsum
                # EVERY registry mutation of chunk k — the in-scan
                # metrics carry, class gauges, health gauges, heartbeat
                # gauges — rides the writer HERE, in submission order
                # ahead of chunk k's flush_events, so the metrics row can
                # never see chunk k+1's values (capture-mode science
                # counters are the documented exception: they enqueue per
                # generation during chunk k+1's producer loop, so a flush
                # may count them up to one chunk early).  The host_io
                # window times the inline work in the blocking loop and
                # the enqueue/backpressure stall in the pipelined one.
                with meter.host_io():
                    if m is not None:
                        submit_or_run(writer, update_registry, registry,
                                      m, n_particles=cfg.size)
                    if cfg.generation_impl == "fused":
                        from ..soup import _fused_kernel_route
                        submit_or_run(writer, update_fused_counters,
                                      registry, chunk,
                                      _fused_kernel_route(cfg))
                    submit_or_run(writer, update_class_gauges, registry,
                                  counts, prev=prev)
                    if hsum is not None:
                        submit_or_run(writer, update_health_gauges,
                                      registry, hsum)
                    if ldata is not None and lin_writer is not None:
                        kind, payload = ldata
                        if kind == "window":
                            flush_lineage_window(
                                lin_writer, registry, writer, exp.dir,
                                gen - chunk, gen, payload, lincap)
                        else:
                            flush_lineage_probe(lin_writer, registry,
                                                writer, gen - chunk, gen,
                                                payload)
                    hb.beat(generation=gen, gens_per_sec=chunk / dt,
                            chunk_seconds=round(dt, 3))
                    if live is not None:
                        # history sample + alert evaluation ride the
                        # writer AFTER this chunk's gauge updates and
                        # BEFORE its flush_events, so an alert row can
                        # never cite registry state newer than its chunk
                        live.sample(exp, writer, generation=gen)
                    if prof is not None:
                        if primary:
                            # fold the profiler gauges, then ride the
                            # cumulative profile.folded/.jsonl rewrite on
                            # the writer ahead of this chunk's flush_events
                            prof.flush(exp.dir, writer, registry)
                        else:
                            # workers fold their own gauges only — run-dir
                            # artifacts are process-0's (DESIGN §16)
                            submit_or_run(writer, prof.update_gauges,
                                          registry)
                    # run-dir artifacts are process-0-gated (DESIGN §16):
                    # workers contribute through the collective shard
                    # boundaries, never through these sinks
                    if primary:
                        if dist.active:
                            # live straggler gauges: tail-read every
                            # process's heartbeat file on the writer
                            # (file I/O only — never a collective) so
                            # this chunk's metrics row names the current
                            # fleet straggler
                            submit_or_run(writer, update_fleet_gauges,
                                          registry, exp.dir, dist)
                        submit_or_run(writer, registry.flush_events, exp)
                        submit_or_run(writer, registry.write_textfile,
                                      os.path.join(exp.dir, "metrics.prom"))
                        if not dist.active:
                            # distributed checkpoints were already saved
                            # synchronously on the loop thread (orbax
                            # barriers across processes)
                            submit_or_run(writer, save_checkpoint,
                                          os.path.join(
                                              exp.dir,
                                              f"ckpt-gen{gen:08d}"),
                                          ckpt_state)
                row["pipeline"] = meter.chunk_done(dt)
                if prof is not None:
                    # utilization decomposition of the chunk just
                    # attributed: soup_utilization_* gauges inline (the
                    # chunk_done discipline) + the flight-recorder copy
                    row["utilization"] = update_utilization_gauges(
                        registry, row["pipeline"])
                # chunk span family (root + device_wait/host_io children)
                # reusing the attribution just computed above
                emit_chunk_spans(spans, "mega_soup", gen, chunk,
                                 row["pipeline"])
                # the stamped copy (seq/t) is what the rules see — the
                # gens_regress median excludes the current row by seq
                row = flightrec.record(row)
                # distributed runs keep the watchdog rules + host-only
                # bundles but skip the bundle's state snapshot: its orbax
                # save would barrier across processes from a path only
                # process 0 takes
                watchdog_chunk(watchdog, row, exp=exp, registry=registry,
                               snapshot_state=None if dist.active
                               else ckpt_state,
                               save_fn=None if dist.active
                               else save_checkpoint, gen=gen)
            return finish

        preempted = False
        while gen < args.generations:
            if chunk_boundary_faults(exp, chaos, gen, args.generations):
                preempted = True
                break
            chunk = min(args.checkpoint_every, args.generations - gen)
            # non-capture chunks hand their metrics + health (+ lineage)
            # carries to the finisher, which orders them ahead of the
            # chunk's flush
            m = h = ldata = None
            kw = {"generations": chunk, "metrics": True}
            if health_on:
                kw["health"] = True
            if lineage_on:
                kw.update(lineage=True, lineage_state=lin,
                          lineage_capacity=lincap)
            if store is not None and mesh is not None:
                from ..utils import sharded_evolve_captured
                state = sharded_evolve_captured(cfg, mesh, state, chunk, store,
                                                every=args.capture_every,
                                                registry=registry,
                                                pipelined=pipelined,
                                                writer=writer)
            elif store is not None:
                from ..utils import evolve_captured
                # owned=True: this loop's state is always jax-owned (seed
                # is a jit output, a restore is own_pytree-copied above)
                # and rebound, so capture skips its defensive copy
                state = evolve_captured(cfg, state, chunk, store,
                                        every=args.capture_every,
                                        owned=True, registry=registry,
                                        pipelined=pipelined, writer=writer)
            elif mesh is not None:
                from ..parallel import (sharded_evolve,
                                        sharded_evolve_donated)
                run = sharded_evolve_donated if sh_owned else sharded_evolve
                out = run(cfg, mesh, state, **kw)
                state, m = out[0], out[1]
                rest = list(out[2:])
                if health_on:
                    h = rest.pop(0)
                if lineage_on:
                    lt = rest.pop(0)
                    lin, ldata = lt[0], ("window", lt)
                sh_owned = True
            else:
                out = evolve_donated(cfg, state, **kw)
                state, m = out[0], out[1]
                rest = list(out[2:])
                if health_on:
                    h = rest.pop(0)
                if lineage_on:
                    lt = rest.pop(0)
                    lin, ldata = lt[0], ("window", lt)
            if store is not None and health_on:
                # capture chunks meter through the capture helpers and lack
                # the in-scan carry; probe end-of-chunk health with one
                # tiny extra dispatch (ordered before the next donation)
                h = probe_health(state.weights, -1, cfg.epsilon)
            if store is not None and lineage_on:
                # same stand-in for the dynamics carry: a census-only
                # self-application probe (no pids/edges in capture mode —
                # a documented boundary, see telemetry.dynamics)
                ldata = ("probe",
                         probe_dynamics(cfg.topo, state.weights,
                                        cfg.epsilon))
            gen += chunk
            # both dispatched BEFORE the next iteration donates state
            # (the metrics/health/lineage carries are fresh jit outputs,
            # never donated):
            counts_dev = _count(state)
            if dist.active:
                # distributed checkpoint: ONE synchronous collective gather
                # on the loop thread (identical order on every process),
                # then orbax's multihost save — ALSO on the loop thread of
                # EVERY process, because orbax barriers across processes
                # internally (a writer-thread save on process 0 alone
                # wedges the whole mesh; observed, not hypothetical).  The
                # lineage flush payload rides the same gather discipline.
                ckpt_state = fetch_for_checkpoint(
                    state, dist, meter, registry if primary else None)
                save_checkpoint(os.path.join(exp.dir,
                                             f"ckpt-gen{gen:08d}"),
                                ckpt_state, primary=primary)
                if ldata is not None:
                    from ..distributed.hostio import fetch_tree
                    ldata = (ldata[0], fetch_tree(ldata[1]))
            else:
                ckpt_state = snapshot(state) if pipelined else state
            fin = _finisher(gen, chunk, counts_dev, ckpt_state, m, h,
                            ldata)
            if chaos is not None:
                fin = chaos.wrap_finisher(fin, gen)
            driver.step(fin)
        finish_pipeline(exp, driver, writer, meter, pipelined)
        if preempted:
            raise Preempted(gen)
        exp.log(f"done: {counters_dict(counts)}")
    finally:
        # teardown order: any armed watchdog profiler window first (it
        # must not outlive the run), then the pipeline writer (drains
        # queued frame appends/checkpoints and joins its thread,
        # re-raising any job failure), then the capture store (joins the
        # native writer thread so every appended frame hits disk even on
        # a crash path), then the experiment exactly once with real
        # exception info so meta.json records crashes.  Nested finallys
        # guarantee meta.json is written even when a close itself raises
        # (e.g. disk full).
        if watchdog is not None:
            watchdog.stop_trace()
        # stop the profiler's sampler thread and close any armed anomaly
        # trace window before the writer drains — queued flush jobs read
        # the frozen tables (stop() only halts sampling)
        if prof is not None:
            prof.stop()
        if capture is not None:
            capture.close()
        # the hostio span sink closes over this attempt's writer; clear it
        # before the writer goes down (a restart installs a fresh one)
        close_spans()
        try:
            try:
                try:
                    try:
                        if writer is not None:
                            writer.close()
                    finally:
                        # after the writer drained (queued history/alert
                        # sample jobs reference the live plane's handles):
                        # stop the exporter, close metrics_history.jsonl
                        if live is not None:
                            live.close()
                finally:
                    if store is not None:
                        store.close()
            finally:
                # after the pipeline drained: every queued lineage row is
                # already appended
                if lin_writer is not None:
                    lin_writer.close()
        finally:
            exp.__exit__(*sys.exc_info())
    return exp.dir


def _make_config(args) -> SoupConfig:
    return SoupConfig(
        topo=Topology("weightwise", width=2, depth=2),
        size=args.size,
        attacking_rate=args.attacking_rate,
        learn_from_rate=args.learn_from_rate,
        train=args.train,
        train_mode=args.train_mode,
        remove_divergent=True,
        remove_zero=True,
        epsilon=args.epsilon,
        layout=args.layout,
        respawn_draws=args.respawn_draws,
        attack_impl=args.attack_impl,
        learn_from_impl=args.learn_from_impl,
        train_impl=args.train_impl,
        generation_impl=args.generation_impl,
        population_dtype=args.population_dtype,
    )


@register("mega_soup")
def main(argv=None):
    return run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
