"""The north-star mega-soup: BASELINE.json's 1M-particle / 1000-generation
workload as a resumable production run.

No reference equivalent — the reference cannot exceed a few hundred
particles (one keras model per particle, ``soup.py:37-49``).  This entry
point is the showcase composition of the runtime: the weightwise soup at
mega scale (``layout='popmajor'`` by default — particle axis on the TPU
lanes), periodic orbax checkpoints with bit-exact ``--resume``, per-chunk
class-count logging, and optional strided trajectory capture to the native
``.traj`` store.

    python -m srnn_tpu.setups mega_soup --size 1000000 --generations 1000
    python -m srnn_tpu.setups mega_soup --resume experiments/exp-mega-soup-…-0

Interrupted runs continue from the last checkpoint on the SAME PRNG stream,
so an interrupted-and-resumed run reproduces an uninterrupted one exactly.
"""

import glob
import json
import os
import sys

import jax
import numpy as np

from ..experiment import (Experiment, counters_dict, format_counters,
                          restore_checkpoint, save_checkpoint)
from ..soup import SoupConfig, count, evolve, seed
from ..topology import Topology
from .common import base_parser, register


def build_parser():
    p = base_parser(__doc__)
    p.add_argument("--size", type=int, default=1_000_000)
    p.add_argument("--generations", type=int, default=1000)
    p.add_argument("--attacking-rate", type=float, default=0.1)
    p.add_argument("--learn-from-rate", type=float, default=-1.0)
    p.add_argument("--train", type=int, default=0)
    p.add_argument("--train-mode", default="sequential",
                   choices=("sequential", "full_batch"))
    p.add_argument("--layout", default="popmajor",
                   choices=("rowmajor", "popmajor"))
    p.add_argument("--checkpoint-every", type=int, default=100,
                   help="generations per checkpoint/log chunk")
    p.add_argument("--capture-every", type=int, default=0, metavar="K",
                   help="stream every K-th generation's full soup frame to "
                        "the native .traj store (0 = off); must divide "
                        "--checkpoint-every")
    p.add_argument("--resume", default=None, metavar="RUN_DIR",
                   help="continue a previous run from its latest checkpoint")
    return p


def _latest_checkpoint(run_dir: str):
    # only finalized checkpoints: a kill during save leaves orbax tmp dirs
    # (ckpt-genNNN.orbax-checkpoint-tmp-*) that must not be picked up
    ckpts = sorted(
        (p for p in glob.glob(os.path.join(run_dir, "ckpt-gen*"))
         if p.rsplit("gen", 1)[1].isdigit()),
        key=lambda p: int(p.rsplit("gen", 1)[1]))
    if not ckpts:
        raise FileNotFoundError(f"no finalized ckpt-gen* checkpoints under {run_dir}")
    return ckpts[-1]


_CONFIG_FIELDS = ("size", "attacking_rate", "learn_from_rate", "train",
                  "train_mode", "layout", "epsilon")


def _save_config(run_dir: str, args) -> None:
    with open(os.path.join(run_dir, "config.json"), "w") as f:
        json.dump({k: getattr(args, k) for k in _CONFIG_FIELDS}, f, indent=1)


def _load_config(run_dir: str, args) -> None:
    """Resume must continue the ORIGINAL run's dynamics (size, rates, train
    schedule, layout), not whatever the resuming invocation's CLI defaults
    happen to be.  The horizon (``--generations``) and checkpoint cadence
    stay CLI-controlled — extending a finished run is legitimate."""
    path = os.path.join(run_dir, "config.json")
    with open(path) as f:
        saved = json.load(f)
    for k in _CONFIG_FIELDS:
        setattr(args, k, saved[k])


def run(args):
    if args.smoke:
        # shrink only the knobs left at their defaults, so e.g.
        # `--smoke --generations 4` still means 4 generations
        args.size = 64 if args.size == 1_000_000 else args.size
        args.generations = 6 if args.generations == 1000 else args.generations
        args.checkpoint_every = 2 if args.checkpoint_every == 100 \
            else args.checkpoint_every
    if args.layout == "popmajor" and args.train > 0 \
            and args.train_mode == "sequential" and args.size >= 100_000:
        raise SystemExit(
            "popmajor + sequential training at mega-N is a known remote-"
            "compile pathology (ops/popmajor.py); use --train-mode "
            "full_batch or --layout rowmajor")

    if args.resume:
        _load_config(args.resume, args)  # original dynamics win over CLI
        cfg = _make_config(args)
        exp = Experiment.attach(args.resume)
        ckpt = _latest_checkpoint(exp.dir)
        state = restore_checkpoint(ckpt)
        exp.log(f"resumed from {os.path.basename(ckpt)} "
                f"at generation {int(state.time)}")
    else:
        cfg = _make_config(args)
        exp = Experiment("mega-soup", root=args.root, seed=args.seed).__enter__()
        _save_config(exp.dir, args)
        state = seed(cfg, jax.random.key(args.seed))
        exp.log(f"mega-soup N={cfg.size} layout={cfg.layout} "
                f"attack={cfg.attacking_rate} train={cfg.train}/{cfg.train_mode}")

    store = None
    if args.capture_every:
        if args.checkpoint_every % args.capture_every:
            raise SystemExit("--capture-every must divide --checkpoint-every")
        from ..utils import TrajStore
        store = TrajStore(os.path.join(exp.dir, "soup.traj"),
                          n_particles=cfg.size,
                          n_weights=cfg.topo.num_weights)
        exp.log(f"capturing every {args.capture_every} generations to soup.traj")

    import time as _time
    try:
        counts = np.asarray(count(cfg, state))
        while int(state.time) < args.generations:
            chunk = min(args.checkpoint_every, args.generations - int(state.time))
            t0 = _time.perf_counter()
            if store is not None:
                from ..utils import evolve_captured
                state = evolve_captured(cfg, state, chunk, store,
                                        every=args.capture_every)
            else:
                state = evolve(cfg, state, generations=chunk)
            counts = np.asarray(count(cfg, state))
            dt = _time.perf_counter() - t0
            gen = int(state.time)
            exp.log(f"gen {gen}/{args.generations}  {chunk / dt:.2f} gens/s  "
                    f"{format_counters(counts)}",
                    generation=gen, gens_per_sec=round(chunk / dt, 3),
                    counts=counters_dict(counts))
            save_checkpoint(os.path.join(exp.dir, f"ckpt-gen{gen:08d}"), state)
        exp.log(f"done: {counters_dict(counts)}")
    finally:
        # exp is already entered (fresh or attached); close exactly once,
        # passing real exception info so meta.json records crashes
        exp.__exit__(*sys.exc_info())
    return exp.dir


def _make_config(args) -> SoupConfig:
    return SoupConfig(
        topo=Topology("weightwise", width=2, depth=2),
        size=args.size,
        attacking_rate=args.attacking_rate,
        learn_from_rate=args.learn_from_rate,
        train=args.train,
        train_mode=args.train_mode,
        remove_divergent=True,
        remove_zero=True,
        epsilon=args.epsilon,
        layout=args.layout,
    )


@register("mega_soup")
def main(argv=None):
    return run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
