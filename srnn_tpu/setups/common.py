"""Shared plumbing for the paper-experiment entry points.

Each module in this package is the TPU-native equivalent of one reference
``code/setups/*.py`` script (SURVEY §2.2): same experiment, same knobs, same
artifact names — but trials run as one vectorized batch instead of a Python
loop with ``keras.backend.clear_session()`` hygiene between iterations.

Every script exposes ``build_parser()``, ``run(args)`` and ``main(argv)``,
and registers itself so ``python -m srnn_tpu.setups <name>`` dispatches.
``--smoke`` shrinks every knob to seconds-scale for CI.
"""

import argparse
import os
from typing import Callable, Dict, Tuple

import jax
import numpy as np

from ..engine import classify_batch
from ..experiment import Experiment, format_counters
from ..soup import SoupConfig, SoupState, evolve, seed
from ..topology import Topology

REGISTRY: Dict[str, Callable] = {}


def register(name: str):
    def deco(main_fn):
        REGISTRY[name] = main_fn
        return main_fn
    return deco


# the three standard archs every sweep iterates, in the reference's order
# and with its display names (e.g. mixed-self-fixpoints.py:63-66)
STANDARD_VARIANTS: Tuple[Tuple[str, Topology], ...] = (
    ("WeightwiseNeuralNetwork activation='linear' use_bias=False",
     Topology("weightwise", width=2, depth=2)),
    ("AggregatingNeuralNetwork activation='linear' use_bias=False",
     Topology("aggregating", width=2, depth=2, aggregates=4)),
    ("RecurrentNeuralNetwork activation='linear' use_bias=False",
     Topology("recurrent", width=2, depth=2)),
)


def base_parser(description: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=description)
    p.add_argument("--root", default="experiments",
                   help="parent directory for run dirs")
    p.add_argument("--seed", type=int, default=0, help="PRNG seed")
    p.add_argument("--epsilon", type=float, default=1e-4,
                   help="fixpoint epsilon (every reference experiment uses 1e-4)")
    p.add_argument("--smoke", action="store_true",
                   help="shrink all knobs to a seconds-scale sanity run")
    p.add_argument("--service", default=None, metavar="SOCKET",
                   help="submit this experiment to a running experiment "
                        "service (python -m srnn_tpu.serve) on the given "
                        "Unix socket instead of dispatching locally — the "
                        "service may stack it with other tenants' requests "
                        "(bitwise-equal results either way); setups that "
                        "do not support submit mode ignore this")
    p.add_argument("--service-timeout-s", type=float, default=600.0,
                   metavar="S", help="client-side wait budget in submit mode")
    return p


def execution_mode(args) -> str:
    """How this run's compute was dispatched — recorded in config.json so
    artifact readers (``examples/natural_cycles.py``, ``--resume``) can
    tell service-stacked runs from solo-process runs."""
    return "service" if getattr(args, "service", None) else "process"


def submit_to_service(args, kind: str, params: dict, tenant: str = None):
    """Submit one experiment request to the service named by
    ``args.service`` and block for its result (the setups' submit mode)."""
    from ..serve.client import ServiceClient

    client = ServiceClient(args.service,
                           timeout_s=getattr(args, "service_timeout_s",
                                             600.0))
    return client.request(kind, params, tenant=tenant,
                          timeout_s=getattr(args, "service_timeout_s",
                                            600.0))


def evolve_trials(cfg: SoupConfig, key: jax.Array, trials: int,
                  generations: int) -> SoupState:
    """Seed and evolve ``trials`` independent soups as one batched program
    (the reference loops soups one at a time, e.g. ``mixed-soup.py:79-92``)."""
    keys = jax.random.split(key, trials)
    states = jax.vmap(lambda k: seed(cfg, k))(keys)
    return jax.vmap(lambda s: evolve(cfg, s, generations=generations))(states)


def count_soup_trials(cfg: SoupConfig, states: SoupState) -> np.ndarray:
    """(5,) histogram over ALL particles of all trial soups — the setups'
    per-particle ``count(counters, soup)`` accumulation (``mixed-soup.py:27-52``)."""
    classes = jax.vmap(lambda w: classify_batch(cfg.topo, w, cfg.epsilon))(states.weights)
    return np.bincount(np.asarray(classes).reshape(-1), minlength=5)


def log_sweep(exp: Experiment, name: str, data: dict):
    """Reference logging shape: name line, data dict line, blank line
    (``mixed-self-fixpoints.py:98-101``)."""
    exp.log(name)
    exp.log(data)
    exp.log("\n")


def log_counters(exp: Experiment, name: str, counts) -> None:
    exp.log(f"{name}: {format_counters(counts)}", counts=np.asarray(counts), name=name)


# ---- shared mega-run plumbing (mega_soup / mega_multisoup) ----------------


def checkpoint_intact(path: str) -> bool:
    """Is ``path`` a checkpoint dir a resume may trust?  Checkpoints
    written since the resilience round carry the ``SRNN_CKPT_OK`` marker
    (published tmp + fsync + atomic-rename AFTER orbax finishes) — its
    presence is the positive proof.  Legacy dirs (pre-marker) pass a
    structural heuristic instead: non-empty, with no zero-length file —
    a healthy orbax tree has none, while a torn write (kill or dying
    disk mid-copy) leaves exactly that."""
    from ..experiment import CKPT_OK_MARKER

    if not os.path.isdir(path):
        return False
    if os.path.exists(os.path.join(path, CKPT_OK_MARKER)):
        return True
    seen = 0
    for root, _dirs, files in os.walk(path):
        for fname in files:
            seen += 1
            try:
                if os.path.getsize(os.path.join(root, fname)) == 0:
                    return False
            except OSError:
                return False
    return seen > 0


def latest_checkpoint(run_dir: str) -> str:
    """Newest INTACT finalized ckpt-gen* dir.  A kill during save leaves
    orbax tmp dirs named ckpt-genNNN.orbax-checkpoint-tmp-* that must not
    be picked up (the isdigit filter excludes them), and a torn survivor
    (:func:`checkpoint_intact` fails) is SKIPPED with a warning — resume
    falls back to the newest checkpoint that is actually whole instead of
    crashing hours into a recovery."""
    import glob as _glob
    import sys as _sys

    ckpts = sorted(
        (p for p in _glob.glob(os.path.join(run_dir, "ckpt-gen*"))
         if p.rsplit("gen", 1)[1].isdigit()),
        key=lambda p: int(p.rsplit("gen", 1)[1]))
    for p in reversed(ckpts):
        if checkpoint_intact(p):
            return p
        print(f"latest_checkpoint: skipping torn checkpoint {p}",
              file=_sys.stderr, flush=True)
    raise FileNotFoundError(
        f"no finalized ckpt-gen* checkpoints under {run_dir}"
        + (f" ({len(ckpts)} torn candidate(s) skipped)" if ckpts else ""))


def save_run_config(run_dir: str, args, fields, extra=None) -> None:
    """Persist the run's dynamics knobs (and optional ``extra`` derived
    metadata, e.g. per-type names for the viz layer) as config.json —
    atomically, because ``--resume`` (and every supervised restart) reads
    this file first.  Every config additionally records the
    ``execution_mode`` ("process" | "service") so artifact readers can
    tell a service-stacked run's outputs from a solo process's."""
    import json as _json

    from ..utils.atomicio import atomic_write_text

    doc = {k: getattr(args, k) for k in fields}
    doc.setdefault("execution_mode", execution_mode(args))
    doc.update(extra or {})
    atomic_write_text(os.path.join(run_dir, "config.json"),
                      _json.dumps(doc, indent=1))


def load_run_config(run_dir: str, args, fields, legacy_defaults=None) -> None:
    """Resume continues the ORIGINAL run's dynamics: saved fields override
    the CLI.  ``legacy_defaults`` pins fields whose CLI default no longer
    matches the behavior that existed when old configs were written (e.g.
    respawn_draws) — falling back to the new CLI default would silently
    change a resumed run's dynamics."""
    import json as _json

    with open(os.path.join(run_dir, "config.json")) as f:
        saved = _json.load(f)
    legacy = legacy_defaults or {}
    for k in fields:
        fallback = legacy.get(k, getattr(args, k))
        setattr(args, k, saved.get(k, fallback))


def add_pipeline_args(p: argparse.ArgumentParser) -> argparse.ArgumentParser:
    """The async-pipeline CLI knobs shared by the mega-run entry points."""
    p.add_argument("--no-pipeline", action="store_true",
                   help="run the blocking chunk loop (frame pulls, "
                        "checkpoints and sink writes on the critical path) "
                        "instead of the default dispatch-ahead async "
                        "pipeline; the captured streams, checkpoints and "
                        "resume continuations are bit-identical either way")
    p.add_argument("--heartbeat-fsync-every", type=int, default=1,
                   metavar="N",
                   help="fsync every N-th heartbeat row (default 1: "
                        "row-by-row kill survival; raise to amortize the "
                        "sync on slow storage)")
    p.add_argument("--no-spans", action="store_true",
                   help="drop the fleet observatory's structured span "
                        "rows (per-chunk dispatch/host-I/O/gather "
                        "attribution in events*.jsonl); spans are "
                        "host-only, so results are bit-identical either "
                        "way — this knob exists as the A/B oracle for "
                        "exactly that claim")
    p.add_argument("--no-costs", action="store_true",
                   help="skip the cost plane (telemetry.costs): no "
                        "chunk-program cost probe, no compile-ledger "
                        "rows, no soup_hlo_flops/soup_hbm_bytes gauges. "
                        "Cost accounting is host-side compile metadata, "
                        "so results are bit-identical either way — the "
                        "--no-spans-style A/B oracle for that claim")
    p.add_argument("--no-autotune", action="store_true",
                   help="skip the block autotuner (srnn_tpu.autotune): "
                        "no tuned-block lookup or warmup grid "
                        "measurement; lane blocks fall back to the "
                        "built-in defaults.  Tuning only ever changes a "
                        "tile size, so results are bit-identical either "
                        "way — this knob is the A/B oracle for exactly "
                        "that claim (equivalent: SRNN_NO_AUTOTUNE=1)")
    return p


def make_pipeline(args, registry, stage: str):
    """Build a mega loop's async-pipeline trio (see ``utils.pipeline``):
    ONE background writer owning every host-I/O job in submission order,
    the overlap meter attributing each chunk's wall time, and the chunk
    driver deferring chunk k's host finisher until chunk k+1's device
    work is dispatched.  ``--no-pipeline`` degrades all three to the
    blocking order (writer=None, depth=0) — the bit-identical A/B
    reference.  ``--stall-timeout-s`` arms the driver's finisher deadline
    (the flight recorder's liveness half; the loop wires ``on_stall``
    after building its recorder).  Returns
    ``(pipelined, writer, meter, driver)``."""
    from ..utils.pipeline import BackgroundWriter, ChunkDriver, OverlapMeter

    pipelined = not args.no_pipeline
    writer = BackgroundWriter(name=f"{stage}-io") if pipelined else None
    meter = OverlapMeter(registry, stage=stage, writer=writer)
    driver = ChunkDriver(depth=1 if pipelined else 0,
                         stall_timeout_s=getattr(args, "stall_timeout_s",
                                                 0.0) or 0.0)
    return pipelined, writer, meter, driver


# ---- distributed-tier plumbing (mega_soup / mega_multisoup) ----------------


def init_distributed(args):
    """Multi-process bring-up for a mega loop (``distributed.bootstrap``):
    idempotent, inactive for plain runs.  Must run before anything probes
    devices.  A multi-process run without ``--sharded`` would leave every
    non-primary process's devices outside the population mesh, so it is
    refused up front."""
    from ..distributed import ensure_initialized

    dist = ensure_initialized(args)
    if dist.active and not getattr(args, "sharded", False):
        raise SystemExit("distributed runs need --sharded (the population "
                         "mesh must span every process's devices)")
    return dist


def build_soup_mesh(ctx, shard_sizes):
    """The mega loops' ONE mesh builder.  When the (surviving) devices
    span several slice groups — a TPU multislice topology, a multi-process
    CPU mesh (one group per process), or a forced CI split
    (``SRNN_FORCE_SLICES``) — the mesh comes from
    ``parallel.reramp_soup_mesh``: the largest regular ``(slices, soup)``
    grid whose device count divides every published shard size, which
    makes the re-ramp builder the LIVE bring-up path rather than recovery
    documentation.  Flat topologies keep the 1-D ``soup_mesh`` with the
    supervisor's count-snap."""
    from ..parallel import reramp_soup_mesh, slice_groups, soup_mesh

    devs = ctx.mesh_devices(snap=False) if ctx is not None else None
    actual = devs if devs is not None else list(jax.devices())
    if len(slice_groups(actual)) >= 2:
        mesh = reramp_soup_mesh(actual, shard_sizes=shard_sizes)
    else:
        mesh = soup_mesh(devices=ctx.mesh_devices()
                         if ctx is not None else None)
    if ctx is not None:
        ctx.last_seen_devices = int(mesh.devices.size)
    return mesh


def open_run(args, name, dist=None, resume=None):
    """Create/attach this run's Experiment under the process-0 I/O
    contract (DESIGN §16).  Single-process (or primary): the real
    Experiment — and in a distributed run the primary broadcasts its run
    dir.  Non-primary processes get a ``distributed.hostio.WorkerLog``
    bound to the broadcast dir: their narration goes to stderr, their
    heartbeats to ``events-p<i>.jsonl``, and every run artifact
    (log.txt/events.jsonl/metrics.prom/lineage.jsonl/checkpoints) is
    written exactly once, by process 0."""
    active = dist is not None and dist.active
    if not active or dist.primary:
        exp = Experiment.attach(resume) if resume \
            else Experiment(name, root=args.root, seed=args.seed).__enter__()
        if active:
            from ..distributed.hostio import broadcast_run_dir

            broadcast_run_dir(exp.dir)
        return exp
    from ..distributed.hostio import WorkerLog, broadcast_run_dir

    return WorkerLog(broadcast_run_dir(None), dist.process_id)


def stage_label(stage: str, dist=None) -> str:
    """Heartbeat stage label: per-process in distributed runs
    (``mega_soup@p1/2``) so the watch tier can tell a wedged worker from
    a wedged coordinator by WHICH heartbeat file went quiet."""
    if dist is None or not dist.active:
        return stage
    return f"{stage}@p{dist.process_id}/{dist.num_processes}"


def set_distributed_gauges(registry, dist, mesh) -> None:
    """The ``soup_distributed_*`` shape-of-the-run gauges (names.py)."""
    from ..parallel import slice_groups

    registry.gauge("soup_distributed_processes",
                   help="jax.distributed process count of this run").set(
        dist.num_processes if (dist is not None and dist.active) else 1)
    if mesh is not None:
        registry.gauge("soup_distributed_slices",
                       help="slice groups of the population mesh").set(
            len(slice_groups(list(mesh.devices.flat))))


def fetch_for_checkpoint(state, dist, meter, registry):
    """A distributed chunk's checkpoint source: ONE synchronous
    collective gather of the sharded state onto every host (the
    process-0 writer then persists it).  Must run on the loop thread —
    collectives from the background writer would interleave differently
    per process and deadlock the mesh — and BEFORE the next chunk's
    donating dispatch (it blocks until the bytes land, so donation
    safety comes for free).  Single-process runs never call this."""
    import time as _time

    from ..distributed.hostio import fetch_tree

    t0 = _time.perf_counter()
    with meter.waiting():
        host = fetch_tree(state)
    if registry is not None:
        registry.histogram("soup_distributed_gather_seconds",
                           help="per-chunk state gather (checkpoint "
                                "source) wall time",
                           unit="seconds").observe(
            _time.perf_counter() - t0)
    return host


# ---- fleet-observatory plumbing (mega_soup / mega_multisoup) ---------------


def make_spans(args, exp, registry, writer, dist, stage: str):
    """Build the run's structured-span stream (``telemetry.tracing.
    SpanStream``) and install it as the hostio collective span sink —
    every process gets one (workers' rows land in their
    ``events-p<i>.jsonl`` via ``WorkerLog.event``, the fleet merge
    reassembles them).  ``--no-spans`` returns ``None`` and clears the
    sink — the bit-identical A/B reference for "observability never
    perturbs results"."""
    from ..distributed.hostio import set_span_sink

    if getattr(args, "no_spans", False):
        set_span_sink(None)
        return None
    from ..telemetry.tracing import SpanStream

    active = dist is not None and dist.active
    spans = SpanStream(exp, trace_id=os.path.basename(exp.dir),
                       process=dist.process_id if active else 0,
                       writer=writer, registry=registry)

    def hostio_emit(name, dur_s, **labels):
        spans.emit(name, spans.now() - dur_s, dur_s, stage=stage, **labels)

    set_span_sink(hostio_emit)
    return spans


def close_spans() -> None:
    """Uninstall the hostio span sink (run teardown: the sink closes over
    this attempt's writer, and a supervisor restart builds a fresh one)."""
    from ..distributed.hostio import set_span_sink

    set_span_sink(None)


def emit_chunk_spans(spans, stage: str, gen: int, chunk: int,
                     pipeline_row: dict) -> None:
    """One chunk's span family, emitted from the finisher AFTER
    ``OverlapMeter.chunk_done`` so the attribution is reused, never
    re-measured: a ``<stage>.chunk`` root spanning the chunk wall, with
    ``device_wait`` (blocked on device results — the dispatch half) and
    ``host_io`` (foreground sink writes + background-writer busy delta)
    children.  The distributed gather's span is emitted separately by the
    hostio sink at gather time, same trace."""
    if spans is None:
        return
    end = spans.now()
    wall = float(pipeline_row.get("wall_s", 0.0))
    start = end - wall
    root = spans.emit(f"{stage}.chunk", start, wall, generation=gen,
                      generations=chunk)
    spans.emit(f"{stage}.device_wait", start,
               float(pipeline_row.get("device_wait_s", 0.0)), parent=root,
               generation=gen)
    spans.emit(f"{stage}.host_io", start,
               float(pipeline_row.get("host_io_s", 0.0)), parent=root,
               generation=gen)


def probe_run_costs(args, exp, registry, entry: str, jitted, jit_args,
                    jit_kwargs, *, particles: int, generations: int) -> None:
    """The mega loops' cost-plane hook (``telemetry.costs``): AOT-compile
    the EXACT chunk program the loop is about to dispatch (abstract
    shapes only — the build is served by the persistent cache, and the
    loop's own first dispatch then deserializes it, so the probe warms
    the run rather than taxing it), record its ledger row + XLA
    cost/memory analysis, fold the ``soup_compile_seconds_total`` /
    ``soup_aot_cache_*`` / ``soup_hlo_flops`` / ``soup_hbm_bytes``
    metrics into the run registry, and emit one ``{"kind": "cost"}``
    events row — what ``report`` derives the apps/s-vs-HLO-flops
    roofline line from.

    Skipped under ``--no-costs`` (the A/B bitwise oracle) and entirely
    host-side + fail-soft: a cost-plane failure is logged, never fatal."""
    if getattr(args, "no_costs", False):
        return
    from ..telemetry import costs

    if not costs.enabled():
        return
    try:
        from ..utils.aot import aot_compile

        e = aot_compile(entry, jitted, jit_args, jit_kwargs)
        # the memoized entry keeps its Compiled, so a memo hit (e.g. an
        # in-process restart re-entering the loop) yields the same full
        # cost/memory fields as the miss that filled it
        fields = costs.extract_costs(e.compiled)
        costs.fold_cost_metrics(registry)
        exp.event(kind="cost", entry=entry, particles=particles,
                  generations=generations, cached=e.cached,
                  lower_s=round(e.lower_s, 4),
                  compile_s=round(e.compile_s, 4),
                  ledger=costs.ledger_path(), **fields)
        errors = costs.consume_ledger_errors()
        if errors:
            exp.log(f"cost plane: {'; '.join(errors)}", kind="cost_error")
    except Exception as err:  # never let cost bookkeeping kill a run
        try:
            exp.log(f"cost plane probe failed: {type(err).__name__}: {err}",
                    kind="cost_error")
        except Exception:
            pass


def add_telemetry_args(p: argparse.ArgumentParser) -> argparse.ArgumentParser:
    """The live telemetry plane's CLI knobs shared by the mega-run entry
    points (see ``telemetry.exporter``/``timeseries``/``alerts``)."""
    p.add_argument("--metrics-port", type=int, default=0, metavar="PORT",
                   help="serve this process's live metrics registry at "
                        "http://127.0.0.1:PORT/metrics (+/healthz); 0 = "
                        "off.  Distributed runs export each worker at "
                        "PORT+process_id; the primary's /healthz "
                        "aggregates worker liveness from the heartbeat "
                        "lanes (file reads only)")
    p.add_argument("--no-export", action="store_true",
                   help="drop the whole live telemetry plane (HTTP "
                        "exporter, metric history rings + "
                        "metrics_history.jsonl, alert engine); the plane "
                        "is host-side, so results are bit-identical "
                        "either way — the --no-spans-style A/B oracle "
                        "for that claim")
    p.add_argument("--history-ring", type=int, default=512, metavar="N",
                   help="per-series metric-history ring capacity in "
                        "samples (one sample per chunk; overflow drops "
                        "the oldest points — the jsonl stream keeps the "
                        "full trail)")
    p.add_argument("--alert-nan-frac", type=float, default=0.02,
                   metavar="F",
                   help="alert when the NaN/Inf particle fraction "
                        "exceeds F (the soup_nan_frac rule)")
    p.add_argument("--alert-straggler-skew", type=float, default=4.0,
                   metavar="R",
                   help="alert when the fastest/slowest process "
                        "gens-per-sec ratio reaches R (the "
                        "soup_straggler_skew rule; distributed runs)")
    return p


def add_profile_args(p: argparse.ArgumentParser) -> argparse.ArgumentParser:
    """The continuous-profiling plane's CLI knobs shared by the mega-run
    entry points and the serve tier (see ``telemetry.profiler``)."""
    p.add_argument("--no-profile", action="store_true",
                   help="drop the continuous profiling plane (50Hz host "
                        "stack sampler, profile.folded/profile.jsonl, "
                        "utilization gauges, anomaly capture); the plane "
                        "is host-side, so results are bit-identical "
                        "either way — the --no-spans-style A/B oracle "
                        "for that claim")
    p.add_argument("--profile-hz", type=float, default=50.0, metavar="HZ",
                   help="host stack-sampling rate; each tick folds every "
                        "named thread's stack into the bounded profile "
                        "tables (overhead documented ≤5%% in "
                        "micro_dispatch's profile row)")
    p.add_argument("--profile-ring-s", type=float, default=30.0,
                   metavar="S",
                   help="seconds of raw per-tick samples kept in the "
                        "rolling ring — the pre-anomaly window an "
                        "anomaly bundle preserves as samples.jsonl")
    p.add_argument("--anomaly-captures", type=int, default=4, metavar="N",
                   help="FIFO retention bound on anomaly/<rule>-<seq>/ "
                        "bundles: past N the oldest bundle is evicted "
                        "(an alert storm tells its story in N bundles)")
    return p


def make_profiler(args, exp, registry, dist, stage: str):
    """Build one process's continuous-profiling plane
    (``telemetry.profiler``): the 50Hz stack sampler on EVERY process
    (each worker's threads are its own forensic surface), the anomaly
    capture primary-only — captures land in the run dir next to the
    alert stream that triggers them, honoring the process-0 I/O contract
    (DESIGN §16).  Returns ``(profiler, capture)``; ``--no-profile``
    returns ``(None, None)`` — the bitwise A/B reference.  The capture
    is handed to :func:`make_live_plane` so firing edges publish their
    bundle from the same ordered writer job as the alert rows; per-chunk
    ``profiler.flush(run_dir, writer, registry)`` calls stay inside the
    finisher's primary-gated block like every other run artifact."""
    if getattr(args, "no_profile", False):
        return None, None
    from ..telemetry.profiler import AnomalyCapture, SamplingProfiler

    profiler = SamplingProfiler(
        hz=getattr(args, "profile_hz", 50.0),
        ring_s=getattr(args, "profile_ring_s", 30.0)).start()
    active = dist is not None and dist.active
    primary = dist.primary if active else True
    capture = None
    if primary:
        capture = AnomalyCapture(
            exp.dir, profiler=profiler, registry=registry,
            max_bundles=getattr(args, "anomaly_captures", 4),
            ring_s=getattr(args, "profile_ring_s", 30.0))
    exp.log(f"profiler: sampling {profiler.hz:g}Hz "
            f"(ring {profiler.ring_s:g}s"
            + (f", anomaly captures ≤{capture.max_bundles}" if capture
               else "") + ")")
    return profiler, capture


def make_live_plane(args, exp, registry, dist, stage: str, capture=None):
    """Build one process's live telemetry plane (``telemetry.exporter.
    LivePlane``): the history ring (jsonl stream process-0-gated like
    every run artifact), the alert engine (primary-only — one alert
    stream per run), and the HTTP exporter when ``--metrics-port`` is
    set (workers bind PORT+process_id).  ``--no-export`` returns ``None``
    — the bitwise A/B reference.  An :class:`AnomalyCapture` from
    :func:`make_profiler` rides the plane's sample job so firing edges
    publish their black-box bundle ordered against the alert rows.
    Exporter bind failures are logged and non-fatal: observability must
    never take down a run."""
    if getattr(args, "no_export", False):
        return None
    from ..telemetry.alerts import AlertEngine, default_run_rules
    from ..telemetry.exporter import (LivePlane, MetricsExporter,
                                      healthz_metrics, worker_liveness)
    from ..telemetry.timeseries import MetricHistory

    active = dist is not None and dist.active
    primary = dist.primary if active else True
    history = MetricHistory(
        registry, capacity=getattr(args, "history_ring", 512),
        path=os.path.join(exp.dir, "metrics_history.jsonl")
        if primary else None)
    engine = None
    if primary:
        engine = AlertEngine(
            default_run_rules(
                nan_frac=getattr(args, "alert_nan_frac", 0.02),
                straggler_skew=getattr(args, "alert_straggler_skew", 4.0)),
            registry, history)
    exporter = None
    port = getattr(args, "metrics_port", 0) or 0
    if port:
        port += dist.process_id if active else 0
        run_dir = exp.dir
        nproc = dist.num_processes if active else 1

        def healthz():
            out = {"ok": True, "stage": stage,
                   "metrics": healthz_metrics(registry)}
            if engine is not None:
                out["active_alerts"] = engine.active()
            if active and primary:
                workers = worker_liveness(run_dir, nproc)
                out["workers"] = workers
                out["ok"] = all(w["ok"] for w in workers.values())
            return out

        try:
            exporter = MetricsExporter(registry, port=port,
                                       healthz=healthz)
            exp.log(f"telemetry: /metrics + /healthz live on "
                    f"{exporter.url}")
        except OSError as e:
            exp.log(f"telemetry: exporter bind failed on :{port} "
                    f"({e}); continuing without the live endpoint")
    return LivePlane(history=history, engine=engine, exporter=exporter,
                     capture=capture if engine is not None else None)


def update_fleet_gauges(registry, run_dir: str, dist) -> None:
    """Fold the LIVE straggler attribution into the registry (the
    ``soup_straggler_*`` gauges) from a bounded tail-read of every
    process's event file.  Called by the primary's chunk finisher via
    the background writer — pure file reads, never a collective, so the
    one no-collectives-on-the-writer rule (DESIGN §16) holds."""
    from ..telemetry import fleet

    att = fleet.live_attribution(run_dir, dist.num_processes)
    if att is not None:
        fleet.update_straggler_gauges(registry, att)


# ---- elastic-supervisor plumbing (mega_soup / mega_multisoup) --------------


def add_resilience_args(p: argparse.ArgumentParser) -> argparse.ArgumentParser:
    """The run-supervisor CLI knobs shared by the mega-run entry points
    (see ``srnn_tpu.resilience``): bounded retries with deterministic
    backoff, the device budget the topology re-ramp shrinks, and the
    chaos-harness schedule."""
    p.add_argument("--max-restarts", type=int, default=3, metavar="N",
                   help="in-process recovery budget: a classified "
                        "device-loss/stall/IO fault restarts the run from "
                        "its newest intact checkpoint at most N times "
                        "(0 = unsupervised, faults propagate unchanged; "
                        "exit codes: 0 clean, 3 recovered, 69 retries "
                        "exhausted, 75 preempted-clean)")
    p.add_argument("--backoff-base-s", type=float, default=2.0, metavar="S",
                   help="restart k backs off base*2^k seconds (capped by "
                        "--backoff-max-s) with deterministic +/-jitter "
                        "seeded by --seed")
    p.add_argument("--backoff-max-s", type=float, default=60.0, metavar="S",
                   help="backoff ceiling in seconds")
    p.add_argument("--backoff-jitter", type=float, default=0.1, metavar="F",
                   help="jitter fraction on each backoff delay (0 disables)")
    p.add_argument("--max-devices", type=int, default=0, metavar="N",
                   help="initial device budget for --sharded (0 = all "
                        "visible); a device-loss recovery may shrink it "
                        "(re-ramp: survivors win, else halve)")
    p.add_argument("--chaos", default=None, metavar="SPEC",
                   help="deterministic fault injection for recovery "
                        "drills: comma-separated events — "
                        "device_loss@G[:S] (raise at generation G, S "
                        "devices 'survive'), stall@G[:HOLD_S] (condemn "
                        "that chunk's finisher; needs --stall-timeout-s), "
                        "writer@N (poison the Nth background-writer job), "
                        "sigterm@G, sigkill@G; every event fires once "
                        "(see resilience.chaos)")
    return p


def note_restart(exp, ctx) -> None:
    """Publish a fresh attempt's Experiment to its supervisor
    (``ctx.run_dir`` is where a later recovery resumes from) and, on a
    restarted attempt, log the one ``supervisor: restart`` line the run
    log carries per recovery.  Shared by both mega loops."""
    if ctx is None:
        return
    ctx.run_dir = exp.dir
    if not ctx.restarts:
        return
    last = ctx.recoveries[-1]
    exp.log(f"supervisor: restart {ctx.restarts} after "
            f"{last['kind']} fault ({last['error']}; backoff "
            f"{last['backoff_s']}s"
            + (f", re-ramped to {ctx.device_budget} device(s)"
               if last["reramped"] else "") + ")",
            kind="restart", restarts=ctx.restarts,
            fault=last["kind"], reramped=last["reramped"])


def chunk_boundary_faults(exp, chaos, gen: int, total: int) -> bool:
    """Top-of-chunk supervision shared by both mega loops: honor a
    pending SIGTERM (returns True — the loop breaks; its drain makes the
    final checkpoint durable before the preempted-clean exit) and fire
    any due chaos events."""
    from ..resilience import preempt_requested

    if preempt_requested():
        exp.log(f"SIGTERM honored: stopping at generation {gen}/{total} "
                "(drain + final checkpoint, then exit preempted-clean)",
                kind="preempt", generation=gen)
        return True
    if chaos is not None:
        chaos.chunk_start(gen)
    return False


# ---- replication-dynamics plumbing (mega_soup / mega_multisoup) ------------


def add_dynamics_args(p: argparse.ArgumentParser) -> argparse.ArgumentParser:
    """The replication-dynamics observatory CLI knobs shared by the
    mega-run entry points (see ``telemetry.dynamics``)."""
    p.add_argument("--lineage", action="store_true",
                   help="carry per-particle lineage ids + attack/learn/"
                        "respawn event edges + a fixpoint-distance census "
                        "in the jitted scan and stream one window per "
                        "chunk to lineage.jsonl (population state is "
                        "bit-identical either way; render with "
                        "`report --dynamics <run_dir>`)")
    p.add_argument("--lineage-edges", type=int, default=4096, metavar="N",
                   help="per-window per-shard event-edge buffer rows; "
                        "overflow drops edges (counted in edges_dropped — "
                        "the stream degrades to a sample, never stalls)")
    return p


def make_lineage(args, exp_dir: str, *, sizes, start_gen: int,
                 resume: bool, mesh=None, type_names=None,
                 primary: bool = True):
    """Build the mega loops' lineage trio ``(state, writer, capacity)`` —
    ``(None, None, 0)`` without ``--lineage``.  ``primary=False`` (a
    distributed run's non-0 processes) builds the device carry WITHOUT a
    ``LineageWriter``: every process computes the same lineage, process 0
    alone streams lineage.jsonl and rolls the resume sidecar.

    On ``--resume`` the carry restores from the ``lineage_state.npz``
    sidecar when its generation stamp matches the checkpoint (the stream
    then CONTINUES the current epoch); otherwise a fresh carry starts a
    new epoch (pids are unique per epoch — genealogy analyzes epochs
    independently).  ``sizes`` is ``(n,)`` for the homogeneous soup or
    the per-type sizes; the multi carry shares one pid space."""
    if not getattr(args, "lineage", False):
        return None, None, 0
    from ..telemetry.dynamics import (LineageWriter, load_lineage_state,
                                      place_lineage, seed_lineage,
                                      seed_lineage_blocks)

    lin = None
    if resume:
        lin = load_lineage_state(exp_dir, start_gen)
    restored = lin is not None
    if lin is None:
        lin = (seed_lineage(sizes[0], time=start_gen) if len(sizes) == 1
               else seed_lineage_blocks(sizes, time=start_gen))
    if mesh is not None:
        lin = (place_lineage(mesh, lin) if hasattr(lin, "next_pid")
               else tuple(place_lineage(mesh, l) for l in lin))
    meta = {"start_gen": start_gen, "sizes": list(sizes)}
    if type_names is not None:
        meta["type_names"] = list(type_names)
    writer = None
    if primary:
        writer = LineageWriter(exp_dir, n=sum(sizes),
                               capacity=args.lineage_edges,
                               epsilon=args.epsilon, resume=resume,
                               continue_epoch=restored, meta=meta)
    return lin, writer, args.lineage_edges


def flush_lineage_window(lwriter, registry, writer, exp_dir: str,
                         gen_start: int, gen_end: int, ltriple,
                         capacity: int, type_names=None) -> None:
    """One chunk's lineage flush, called from the (possibly deferred)
    chunk finisher: resolve the window on host, append the jsonl row,
    fold the ``soup_dynamics_*`` metrics, and roll the resume sidecar —
    all riding the background writer in submission order."""
    from ..telemetry.dynamics import (save_lineage_state,
                                      update_dynamics_registry,
                                      window_record)
    from ..utils.pipeline import submit_or_run

    lin, win, stats = ltriple
    # lin is one LineageState (itself a NamedTuple) or a per-type tuple
    next_pid = (lin if hasattr(lin, "next_pid") else lin[0]).next_pid
    row = window_record(gen_start, gen_end, win, stats, capacity,
                        next_pid=int(next_pid), type_names=type_names)

    def flush():
        lwriter.append(row)
        update_dynamics_registry(registry, row)
        save_lineage_state(exp_dir, lin, gen_end)

    submit_or_run(writer, flush)


def flush_lineage_probe(lwriter, registry, writer, gen_start: int,
                        gen_end: int, stats, type_names=None) -> None:
    """Census-only flush for capture-mode chunks (no in-scan carry there;
    see ``soup.probe_dynamics``)."""
    from ..telemetry.dynamics import probe_record, update_dynamics_registry
    from ..utils.pipeline import submit_or_run

    row = probe_record(gen_start, gen_end, stats, type_names=type_names)

    def flush():
        lwriter.append(row)
        update_dynamics_registry(registry, row)

    submit_or_run(writer, flush)


# ---- flight recorder / watchdog plumbing (mega_soup / mega_multisoup) ------


def add_flightrec_args(p: argparse.ArgumentParser) -> argparse.ArgumentParser:
    """The flight-recorder CLI knobs shared by the mega-run entry points
    (see ``telemetry.flightrec``)."""
    p.add_argument("--no-health", action="store_true",
                   help="drop the in-scan population-health sentinel carry "
                        "(NaN/zero fractions, weight-norm sketch); the "
                        "evolved state is bit-identical either way")
    p.add_argument("--no-watchdog", action="store_true",
                   help="record the flight-recorder ring but never trip "
                        "or write triage bundles")
    p.add_argument("--flightrec-ring", type=int, default=256, metavar="N",
                   help="flight-recorder ring capacity in chunks")
    p.add_argument("--watchdog-nan-frac", type=float, default=0.02,
                   metavar="F",
                   help="trip when the NaN/Inf particle fraction exceeds F "
                        "(<=0 disables)")
    p.add_argument("--watchdog-zero-frac", type=float, default=0.9,
                   metavar="F",
                   help="trip when the zero-collapse fraction exceeds F "
                        "(<=0 disables)")
    p.add_argument("--watchdog-respawn-frac", type=float, default=0.25,
                   metavar="F",
                   help="trip when a chunk's respawns exceed F of its "
                        "particle-generations (<=0 disables)")
    p.add_argument("--watchdog-gens-regress", type=float, default=0.0,
                   metavar="F",
                   help="trip when gens/sec falls below (1-F) of the ring "
                        "median (0 disables; wall-clock is noisy on shared "
                        "hosts, so this rule is opt-in)")
    p.add_argument("--watchdog-max-bundles", type=int, default=2,
                   metavar="N",
                   help="most triage bundles one run may write (a NaN "
                        "storm trips every chunk; N bundles tell the story)")
    p.add_argument("--stall-timeout-s", type=float, default=0.0,
                   metavar="S",
                   help="chunk-finisher stall deadline: a chunk whose "
                        "device results do not land within S seconds "
                        "raises a named StallError carrying a host-only "
                        "triage bundle (0 = off)")
    return p


def make_flightrec(args):
    """Build the (recorder, watchdog) pair from the CLI knobs; watchdog is
    ``None`` under ``--no-watchdog``."""
    from ..telemetry.flightrec import FlightRecorder, Watchdog

    recorder = FlightRecorder(capacity=args.flightrec_ring)
    watchdog = None if args.no_watchdog else Watchdog(
        recorder,
        nan_frac=args.watchdog_nan_frac,
        zero_frac=args.watchdog_zero_frac,
        respawn_frac=args.watchdog_respawn_frac,
        gens_regress=args.watchdog_gens_regress,
        max_bundles=args.watchdog_max_bundles)
    return recorder, watchdog


def make_on_stall(exp, flightrec, registry, current_gen):
    """The ``ChunkDriver.on_stall`` handler both mega loops arm: write a
    HOST-ONLY triage bundle (the device is presumed hung, so no snapshot
    is attempted — the ring + metrics are what the host still has).
    ``current_gen`` is a zero-arg callable reading the loop's generation
    counter at stall time."""
    from ..telemetry.flightrec import write_triage_bundle

    def on_stall(timeout_s):
        return write_triage_bundle(
            exp.dir, ["stall"], (flightrec.tail(1) or [None])[-1],
            recorder=flightrec, registry=registry,
            thresholds={"stall_timeout_s": timeout_s},
            generation=current_gen())

    return on_stall


def watchdog_chunk(watchdog, row, *, exp, registry, snapshot_state,
                   save_fn, gen) -> None:
    """One chunk's watchdog turn, shared by both mega-loop finishers:
    close a profiler window armed by a previous trip (so the captured
    window spans roughly the chunk after the trip), evaluate the rules
    against the ring-stamped ``row``, and on a trip count it, write the
    bundle (``snapshot_state``/``save_fn`` = the chunk's pre-donation
    snapshot and the matching checkpoint writer), and log it."""
    if watchdog is None:
        return
    watchdog.chunk_boundary()
    reasons = watchdog.check(row)
    if not reasons:
        return
    registry.counter("soup_watchdog_trips_total",
                     help="watchdog anomaly trips").inc(1)
    bundle = watchdog.trip(reasons, row, run_dir=exp.dir,
                           snapshot_state=snapshot_state, save_fn=save_fn,
                           registry=registry, generation=gen)
    exp.log(f"WATCHDOG tripped [{', '.join(reasons)}]"
            + (f": triage bundle {bundle}" if bundle
               else " (bundle quota spent)"),
            kind="watchdog", reasons=reasons, bundle=bundle, generation=gen)


def finish_pipeline(exp, driver, writer, meter, pipelined: bool) -> None:
    """Chunk-loop epilogue: run the deferred finishers, drain the writer
    (all sinks/checkpoints durable before the final log line), record the
    run's overlap attribution."""
    driver.drain()
    if writer is not None:
        writer.flush()
    exp.event(kind="pipeline", pipelined=pipelined, **meter.summary())
