"""Shared plumbing for the paper-experiment entry points.

Each module in this package is the TPU-native equivalent of one reference
``code/setups/*.py`` script (SURVEY §2.2): same experiment, same knobs, same
artifact names — but trials run as one vectorized batch instead of a Python
loop with ``keras.backend.clear_session()`` hygiene between iterations.

Every script exposes ``build_parser()``, ``run(args)`` and ``main(argv)``,
and registers itself so ``python -m srnn_tpu.setups <name>`` dispatches.
``--smoke`` shrinks every knob to seconds-scale for CI.
"""

import argparse
import os
from typing import Callable, Dict, Tuple

import jax
import numpy as np

from ..engine import classify_batch
from ..experiment import Experiment, format_counters
from ..soup import SoupConfig, SoupState, evolve, seed
from ..topology import Topology

REGISTRY: Dict[str, Callable] = {}


def register(name: str):
    def deco(main_fn):
        REGISTRY[name] = main_fn
        return main_fn
    return deco


# the three standard archs every sweep iterates, in the reference's order
# and with its display names (e.g. mixed-self-fixpoints.py:63-66)
STANDARD_VARIANTS: Tuple[Tuple[str, Topology], ...] = (
    ("WeightwiseNeuralNetwork activation='linear' use_bias=False",
     Topology("weightwise", width=2, depth=2)),
    ("AggregatingNeuralNetwork activation='linear' use_bias=False",
     Topology("aggregating", width=2, depth=2, aggregates=4)),
    ("RecurrentNeuralNetwork activation='linear' use_bias=False",
     Topology("recurrent", width=2, depth=2)),
)


def base_parser(description: str) -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=description)
    p.add_argument("--root", default="experiments",
                   help="parent directory for run dirs")
    p.add_argument("--seed", type=int, default=0, help="PRNG seed")
    p.add_argument("--epsilon", type=float, default=1e-4,
                   help="fixpoint epsilon (every reference experiment uses 1e-4)")
    p.add_argument("--smoke", action="store_true",
                   help="shrink all knobs to a seconds-scale sanity run")
    return p


def evolve_trials(cfg: SoupConfig, key: jax.Array, trials: int,
                  generations: int) -> SoupState:
    """Seed and evolve ``trials`` independent soups as one batched program
    (the reference loops soups one at a time, e.g. ``mixed-soup.py:79-92``)."""
    keys = jax.random.split(key, trials)
    states = jax.vmap(lambda k: seed(cfg, k))(keys)
    return jax.vmap(lambda s: evolve(cfg, s, generations=generations))(states)


def count_soup_trials(cfg: SoupConfig, states: SoupState) -> np.ndarray:
    """(5,) histogram over ALL particles of all trial soups — the setups'
    per-particle ``count(counters, soup)`` accumulation (``mixed-soup.py:27-52``)."""
    classes = jax.vmap(lambda w: classify_batch(cfg.topo, w, cfg.epsilon))(states.weights)
    return np.bincount(np.asarray(classes).reshape(-1), minlength=5)


def log_sweep(exp: Experiment, name: str, data: dict):
    """Reference logging shape: name line, data dict line, blank line
    (``mixed-self-fixpoints.py:98-101``)."""
    exp.log(name)
    exp.log(data)
    exp.log("\n")


def log_counters(exp: Experiment, name: str, counts) -> None:
    exp.log(f"{name}: {format_counters(counts)}", counts=np.asarray(counts), name=name)


# ---- shared mega-run plumbing (mega_soup / mega_multisoup) ----------------


def latest_checkpoint(run_dir: str) -> str:
    """Newest FINALIZED ckpt-gen* dir (a kill during save leaves orbax tmp
    dirs named ckpt-genNNN.orbax-checkpoint-tmp-* that must not be picked
    up; the isdigit filter excludes them)."""
    import glob as _glob

    ckpts = sorted(
        (p for p in _glob.glob(os.path.join(run_dir, "ckpt-gen*"))
         if p.rsplit("gen", 1)[1].isdigit()),
        key=lambda p: int(p.rsplit("gen", 1)[1]))
    if not ckpts:
        raise FileNotFoundError(
            f"no finalized ckpt-gen* checkpoints under {run_dir}")
    return ckpts[-1]


def save_run_config(run_dir: str, args, fields, extra=None) -> None:
    """Persist the run's dynamics knobs (and optional ``extra`` derived
    metadata, e.g. per-type names for the viz layer) as config.json."""
    import json as _json

    doc = {k: getattr(args, k) for k in fields}
    doc.update(extra or {})
    with open(os.path.join(run_dir, "config.json"), "w") as f:
        _json.dump(doc, f, indent=1)


def load_run_config(run_dir: str, args, fields, legacy_defaults=None) -> None:
    """Resume continues the ORIGINAL run's dynamics: saved fields override
    the CLI.  ``legacy_defaults`` pins fields whose CLI default no longer
    matches the behavior that existed when old configs were written (e.g.
    respawn_draws) — falling back to the new CLI default would silently
    change a resumed run's dynamics."""
    import json as _json

    with open(os.path.join(run_dir, "config.json")) as f:
        saved = _json.load(f)
    legacy = legacy_defaults or {}
    for k in fields:
        fallback = legacy.get(k, getattr(args, k))
        setattr(args, k, saved.get(k, fallback))


def add_pipeline_args(p: argparse.ArgumentParser) -> argparse.ArgumentParser:
    """The async-pipeline CLI knobs shared by the mega-run entry points."""
    p.add_argument("--no-pipeline", action="store_true",
                   help="run the blocking chunk loop (frame pulls, "
                        "checkpoints and sink writes on the critical path) "
                        "instead of the default dispatch-ahead async "
                        "pipeline; the captured streams, checkpoints and "
                        "resume continuations are bit-identical either way")
    p.add_argument("--heartbeat-fsync-every", type=int, default=1,
                   metavar="N",
                   help="fsync every N-th heartbeat row (default 1: "
                        "row-by-row kill survival; raise to amortize the "
                        "sync on slow storage)")
    return p


def make_pipeline(args, registry, stage: str):
    """Build a mega loop's async-pipeline trio (see ``utils.pipeline``):
    ONE background writer owning every host-I/O job in submission order,
    the overlap meter attributing each chunk's wall time, and the chunk
    driver deferring chunk k's host finisher until chunk k+1's device
    work is dispatched.  ``--no-pipeline`` degrades all three to the
    blocking order (writer=None, depth=0) — the bit-identical A/B
    reference.  Returns ``(pipelined, writer, meter, driver)``."""
    from ..utils.pipeline import BackgroundWriter, ChunkDriver, OverlapMeter

    pipelined = not args.no_pipeline
    writer = BackgroundWriter(name=f"{stage}-io") if pipelined else None
    meter = OverlapMeter(registry, stage=stage, writer=writer)
    driver = ChunkDriver(depth=1 if pipelined else 0)
    return pipelined, writer, meter, driver


def finish_pipeline(exp, driver, writer, meter, pipelined: bool) -> None:
    """Chunk-loop epilogue: run the deferred finishers, drain the writer
    (all sinks/checkpoints durable before the final log line), record the
    run's overlap attribution."""
    driver.drain()
    if writer is not None:
        writer.flush()
    exp.event(kind="pipeline", pipelined=pipelined, **meter.summary())
