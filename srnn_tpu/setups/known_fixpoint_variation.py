"""Robustness of the known identity fixpoint under perturbation.

Reference: ``setups/known-fixpoint-variation.py`` — start from the
analytically-known weightwise identity fixpoint (``:20-25``), perturb each
weight by ±U(0,1)·scale (``vary``, ``:37-46``), sweep scale 1.0 → 1e-9
(÷10 per level, ``:59,89``), 100 trials × ≤100 self-attacks; measure
time-to-vergence (ys) and time-as-fixpoint (zs) per trial; log the per-scale
averages (``:90-93``).

Note: the reference *appears* to set activation='sigmoid' (``:30``) but
``with_keras_params`` after construction never rebuilds the model
(SURVEY §2.4.11), so the experiment actually ran linear — which this
config makes explicit.
"""

import jax
import numpy as np

from ..engine import run_known_fixpoint_variation
from ..experiment import Experiment
from ..fixtures import identity_fixpoint_flat, vary
from ..topology import Topology
from .common import base_parser, register


def build_parser():
    p = base_parser(__doc__)
    p.add_argument("--depth", type=int, default=10,
                   help="number of ÷10 scale levels (:51)")
    p.add_argument("--trials", type=int, default=100)
    p.add_argument("--max-steps", type=int, default=100)
    return p


def run(args):
    if args.smoke:
        args.depth, args.trials, args.max_steps = 3, 8, 20
    topo = Topology("weightwise", width=2, depth=2)
    fixpoint = identity_fixpoint_flat(topo)
    key = jax.random.key(args.seed)
    with Experiment("known-fixpoint-variation", root=args.root, seed=args.seed) as exp:
        xs, ys, zs = [], [], []
        scale = 1.0
        for level in range(args.depth):
            keys = jax.random.split(jax.random.fold_in(key, level), args.trials)
            pop = jax.vmap(lambda k: vary(k, fixpoint, scale))(keys)
            res = run_known_fixpoint_variation(
                topo, pop, max_steps=args.max_steps, epsilon=args.epsilon)
            xs += [scale] * args.trials
            ys += np.asarray(res.time_to_vergence).tolist()
            zs += np.asarray(res.time_as_fixpoint).tolist()
            scale /= 10.0
        for d in range(args.depth):
            sl = slice(d * args.trials, (d + 1) * args.trials)
            exp.log("variation 10e-" + str(d))
            exp.log("avg time to vergence " + str(float(np.mean(ys[sl]))))
            exp.log("avg time as fixpoint " + str(float(np.mean(zs[sl]))))
        exp.save(data={"xs": np.asarray(xs), "ys": np.asarray(ys, np.int32),
                       "zs": np.asarray(zs, np.int32)},
                 meta_sweep={"depth": args.depth, "trials": args.trials,
                             "max_steps": args.max_steps})
        return exp.dir


@register("known_fixpoint_variation")
def main(argv=None):
    return run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
