"""Pure self-application to fixpoint, per architecture.

Reference: ``setups/applying-fixpoints.py`` — 50 trials × {WW, Agg, RNN},
up to 100 self-attacks each (loop at ``:55-56``), classify into the 5-way
counters, save ``all_counters``/``trajectorys``/``all_names``.
"""

import jax

from ..engine import run_fixpoint
from ..experiment import Experiment
from ..init import init_population
from .common import STANDARD_VARIANTS, base_parser, log_counters, register


def build_parser():
    p = base_parser(__doc__)
    p.add_argument("--trials", type=int, default=50)
    p.add_argument("--run-count", type=int, default=100,
                   help="max self-attacks per trial (applying-fixpoints.py:37)")
    p.add_argument("--record", action="store_true",
                   help="also save full weight trajectories")
    return p


def run(args):
    if args.smoke:
        args.trials, args.run_count = 4, 10
    key = jax.random.key(args.seed)
    with Experiment("applying_fixpoint", root=args.root, seed=args.seed) as exp:
        all_counters, all_names, trajectories = [], [], {}
        for i, (name, topo) in enumerate(STANDARD_VARIANTS):
            pop = init_population(topo, jax.random.fold_in(key, i), args.trials)
            res = run_fixpoint(topo, pop, step_limit=args.run_count,
                               epsilon=args.epsilon, record=args.record)
            log_counters(exp, name, res.counts)
            all_counters.append(res.counts)
            all_names.append(name)
            if args.record:
                trajectories[topo.variant] = res.trajectory
        exp.save(all_counters=jax.numpy.stack(all_counters), all_names=all_names)
        if args.record:
            exp.save(trajectorys=trajectories)
        return exp.dir


@register("applying_fixpoints")
def main(argv=None):
    return run(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
