"""Tracing / profiling harness (SURVEY §5, tracing row).

The reference's only observability is ``tqdm`` progress bars
(``network.py:641``, ``experiment.py:101``).  Here:

  * :func:`timed` — wall-clock statistics for a jitted callable with
    compile/warmup excluded.  Synchronization is by scalar readback, not
    ``block_until_ready`` — on the tunneled axon platform the latter does
    not actually wait (see ``bench.py`` timing notes).
  * :func:`trace` — context manager around ``jax.profiler`` emitting a
    TensorBoard-loadable trace directory.
  * :func:`phase` — alias of ``jax.named_scope``: annotate apply / train /
    evolve phases so they are findable in profiles.
"""

import contextlib
import statistics
import time
from typing import Any, Callable, Dict

import jax
import jax.numpy as jnp

phase = jax.named_scope


def _sync(value) -> None:
    """Force completion of ``value``'s computation via a scalar readback."""
    leaves = jax.tree.leaves(value)
    if leaves:
        float(jnp.asarray(leaves[0]).ravel()[0])


def timed(fn: Callable, *args, iters: int = 10, warmup: int = 2,
          **kwargs) -> Dict[str, Any]:
    """Time ``fn(*args, **kwargs)`` over ``iters`` runs after ``warmup``
    (compile) runs.  Returns mean/median/min/max seconds + per-run list.

    ``warmup=0`` runs NO warm-up call, so the first timed iteration pays
    compile/cache-deserialize — the cold-start number the telemetry
    compile-time metrics want (earlier versions silently forced one
    warm-up run, skewing exactly that measurement)."""
    for _ in range(max(warmup, 0)):
        _sync(fn(*args, **kwargs))
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        _sync(fn(*args, **kwargs))
        times.append(time.perf_counter() - t0)
    return {
        "mean_s": statistics.fmean(times),
        "median_s": statistics.median(times),
        "min_s": min(times),
        "max_s": max(times),
        "iters": iters,
        "times_s": times,
    }


@contextlib.contextmanager
def trace(log_dir: str):
    """Capture a device/host profile into ``log_dir`` (TensorBoard format).

    >>> with trace('/tmp/profile'):
    ...     state = evolve(cfg, state, generations=10)
    """
    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
