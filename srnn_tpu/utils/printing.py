"""Silence-gated printing plumbing (reference layer L0).

Reference: ``util.py:1-39`` — ``PrintingObject`` gives every object a
``silent`` flag, a ``_print`` gate that honors it, and a ``SilenceSignal``
context manager (``obj.silence()``) that silences the object for a ``with``
block.  The TPU framework's runtime logging goes through ``Experiment.log``
instead, but the mixin keeps the exact reference surface —
``is_silent / get_silence / set_silence / unset_silence / with_silence /
silence / _print`` — so reference users migrating interactive scripts keep
their habits on any framework object.
"""

from __future__ import annotations


class PrintingObject:
    """Mixin: per-object ``silent`` flag gating ``_print`` (``util.py:1-39``)."""

    class SilenceSignal:
        """Context manager: force ``silent=value`` inside the block, restore
        the previous value on exit (``util.py:3-11``)."""

        def __init__(self, obj: "PrintingObject", value: bool):
            self.obj = obj
            self.new_silent = value

        def __enter__(self):
            self.old_silent = self.obj.get_silence()
            self.obj.set_silence(self.new_silent)

        def __exit__(self, exc_type, exc_value, traceback):
            self.obj.set_silence(self.old_silent)

    @property
    def silent(self) -> bool:
        # reference sets the flag in __init__ (util.py:13-14); a property
        # default keeps the mixin usable without requiring super().__init__()
        return getattr(self, "_silent", True)

    @silent.setter
    def silent(self, value: bool) -> None:
        self._silent = bool(value)

    def is_silent(self) -> bool:
        return self.silent

    def get_silence(self) -> bool:
        return self.is_silent()

    def set_silence(self, value: bool = True) -> "PrintingObject":
        self.silent = value
        return self

    def unset_silence(self) -> "PrintingObject":
        self.silent = False
        return self

    def with_silence(self, value: bool = True) -> "PrintingObject":
        self.set_silence(value)
        return self

    def silence(self, value: bool = True) -> "PrintingObject.SilenceSignal":
        return self.__class__.SilenceSignal(self, value)

    def _print(self, *args, **kwargs) -> None:
        if not self.silent:
            print(*args, **kwargs)
