"""AOT compile + persistent-executable subsystem for the soup hot path.

Two rounds of bench evidence (BENCH_r04/r05) showed the accelerator window
being eaten by COMPILATION, not execution: every ramp/full attempt paid
XLA compile time inside its measurement timeout.  This module moves that
cost out of the measured (and production) window:

  * :func:`ensure_compilation_cache` turns on jax's persistent executable
    cache for the whole package/process (the ``JAX_COMPILATION_CACHE_DIR``
    machinery ``bench.py`` already used for its children, generalized:
    any entry point compiled once on a machine is deserialized — not
    recompiled — by every later process).
  * :func:`aot_compile` AOT-lowers and compiles ONE jitted entry point
    against abstract (shape/dtype-only) arguments, memoized in-process by
    ``(entry, statics, arg-shape signature, backend, device_count)`` — the
    executable for a given (topology, config, shapes, backend) key is
    built exactly once and reused.
  * :func:`warmup` sweeps the hot entry points — the soup step/run, their
    heterogeneous (multisoup) twins, the fixpoint/training engines, and
    the sharded steps when a mesh is given — so a production run or bench
    child starts from warm executables end to end.
  * ``python -m srnn_tpu.precompile`` (see :mod:`srnn_tpu.precompile`)
    exposes the same sweep as a CLI for filling the on-disk cache ahead
    of a run.

Donation rides the same subsystem: ``donate=True`` (default) warms the
``*_donated`` spellings — the production hot loops' entry points, where
generation N+1 rewrites generation N's population buffers in place
(roughly halving peak HBM for the population at 1M-particle scale).
"""

import os
import sys
import time
from typing import Any, Dict, NamedTuple, Optional, Tuple

import jax

#: env var consulted first for the on-disk executable cache location
CACHE_DIR_ENV = "JAX_COMPILATION_CACHE_DIR"
#: package-specific override consulted second
SRNN_CACHE_DIR_ENV = "SRNN_COMPILE_CACHE_DIR"
#: set to "1" to disable the persistent cache entirely
DISABLE_ENV = "SRNN_NO_COMPILE_CACHE"

_cache_dir_enabled: Optional[str] = None


def default_cache_dir() -> str:
    """Resolve the on-disk executable cache directory: env overrides first
    (the same ``JAX_COMPILATION_CACHE_DIR`` bench.py exports to its
    children), then a stable per-user location."""
    return (os.environ.get(CACHE_DIR_ENV)
            or os.environ.get(SRNN_CACHE_DIR_ENV)
            or os.path.join(os.path.expanduser("~"), ".cache", "srnn_tpu",
                            "xla"))


def ensure_compilation_cache(path: Optional[str] = None) -> Optional[str]:
    """Idempotently enable jax's persistent compilation cache for this
    process (package-wide: every jitted entry point benefits, not just the
    bench children that historically set the env var).

    Returns the live cache dir, or ``None`` when disabled
    (``SRNN_NO_COMPILE_CACHE=1``) or the dir cannot be created — cache
    trouble must never break a run, it just compiles uncached.
    """
    global _cache_dir_enabled
    if os.environ.get(DISABLE_ENV, "0") not in ("", "0"):
        return None
    if path is None:
        path = default_cache_dir()
    if _cache_dir_enabled == path:
        return path
    try:
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        # cache every entry: the defaults skip sub-second compiles, which
        # is exactly the regime of the small parity/test configs whose
        # repeat compiles dominate CI time
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    except (OSError, AttributeError):
        return None
    _cache_dir_enabled = path
    return path


def own_pytree(tree):
    """Deep-copy every array leaf of ``tree`` into jax-owned device memory.

    Checkpoint-restored (or otherwise host-constructed) arrays can share
    their buffer with numpy zero-copy on CPU; DONATING such a buffer lets
    XLA reuse memory jax does not own (observed as corrupted scalars after
    a donated dispatch on a restored state).  Donation-using loops pass any
    externally-produced state through this first — jit outputs are already
    device-owned and never need it.
    """
    import jax.numpy as jnp

    def leaf(x):
        if not hasattr(x, "dtype"):
            return x
        if jax.dtypes.issubdtype(x.dtype, jax.dtypes.prng_key):
            return jax.random.wrap_key_data(
                jnp.array(jax.random.key_data(x)))
        return jnp.array(x)

    return jax.tree.map(leaf, tree)


def _reset_jax_cache_singleton() -> None:
    """Drop jax's in-process compilation-cache instance so the NEXT compile
    re-reads ``jax_compilation_cache_dir`` (cache-dir config changes are
    otherwise ignored once the singleton exists)."""
    try:
        from jax._src import compilation_cache as _cc

        _cc.reset_cache()
    except Exception:
        pass


class CompiledEntry(NamedTuple):
    """One AOT-compiled executable plus its build provenance."""
    name: str
    compiled: Any          # jax.stages.Compiled — call with the non-static args
    key: Tuple             # full memo key (statics + shapes + backend)
    lower_s: float         # trace+lower seconds (0.0 on a memo hit)
    compile_s: float       # backend compile seconds (0.0 on a memo hit)
    cached: bool           # True when served from the in-process memo


_EXECUTABLES: Dict[Tuple, CompiledEntry] = {}


def clear_executable_cache() -> None:
    """Drop the in-process executable memo (tests; the on-disk cache is
    jax's own and survives)."""
    _EXECUTABLES.clear()


def _is_arraylike(x) -> bool:
    # .shape alone is not enough: jax.sharding.Mesh has a .shape too
    return hasattr(x, "shape") and hasattr(x, "dtype")


def _abstract(tree):
    """Shape/dtype skeleton of a pytree of arrays (lower() input)."""
    return jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype)
        if _is_arraylike(x) else x, tree)


def _signature(tree) -> Tuple:
    leaves = jax.tree.leaves(tree)
    return tuple(
        (tuple(l.shape), str(l.dtype)) if _is_arraylike(l) else repr(l)
        for l in leaves)


def _key_array_struct() -> jax.ShapeDtypeStruct:
    """Abstract stand-in for a scalar PRNG key array (typed key dtype)."""
    return jax.eval_shape(lambda: jax.random.key(0))


def _with_shardings(state, specs, mesh):
    """Attach ``NamedSharding(mesh, spec)`` to every ShapeDtypeStruct leaf:
    lowering against unsharded skeletons produces a DIFFERENT program (no
    ``mhlo.sharding`` parameter attributes) than the real sharded dispatch,
    so the persistent-cache entry would never be reused."""
    from jax.sharding import NamedSharding

    return jax.tree.map(
        lambda l, spec: jax.ShapeDtypeStruct(
            l.shape, l.dtype, sharding=NamedSharding(mesh, spec)),
        state, specs)


def abstract_soup_state(config, mesh=None) -> "Any":
    """``SoupState`` skeleton for ``config`` — what :func:`aot_compile`
    lowers against, no population allocation needed.  With ``mesh`` the
    leaves carry the sharded-soup placement (particle axis sharded,
    scalars/key replicated), matching ``make_sharded_state``."""
    import jax.numpy as jnp

    from ..soup import SoupState, _pop_dtype

    int8 = config.population_dtype == "int8"
    st = SoupState(
        weights=jax.ShapeDtypeStruct(
            (config.size, config.topo.num_weights), _pop_dtype(config)),
        uids=jax.ShapeDtypeStruct((config.size,), jnp.int32),
        next_uid=jax.ShapeDtypeStruct((), jnp.int32),
        time=jax.ShapeDtypeStruct((), jnp.int32),
        key=_key_array_struct(),
        scales=jax.ShapeDtypeStruct((config.size,), jnp.float32)
        if int8 else None,
    )
    if mesh is None:
        return st
    from ..parallel.sharded_soup import _soup_axes, _state_specs

    return _with_shardings(st, _state_specs(_soup_axes(mesh), int8), mesh)


def abstract_lineage_state(n: int, mesh=None) -> "Any":
    """``telemetry.dynamics.LineageState`` skeleton for an ``n``-particle
    population (with ``mesh``: the sharded-soup placement, matching
    ``telemetry.dynamics.place_lineage``)."""
    import jax.numpy as jnp

    from ..telemetry.dynamics import LineageState, lineage_specs

    st = LineageState(
        pid=jax.ShapeDtypeStruct((n,), jnp.int32),
        parent=jax.ShapeDtypeStruct((n,), jnp.int32),
        birth=jax.ShapeDtypeStruct((n,), jnp.int32),
        basin=jax.ShapeDtypeStruct((n,), jnp.int32),
        next_pid=jax.ShapeDtypeStruct((), jnp.int32),
    )
    if mesh is None:
        return st
    from ..parallel.sharded_soup import _soup_axes

    return _with_shardings(st, lineage_specs(_soup_axes(mesh)), mesh)


def abstract_multi_state(config, mesh=None) -> "Any":
    """``MultiSoupState`` skeleton for a ``MultiSoupConfig`` (with ``mesh``:
    per-type particle axes sharded, matching ``make_sharded_multi_state``)."""
    import jax.numpy as jnp

    from ..multisoup import MultiSoupState
    from ..soup import _pop_dtype

    int8 = config.population_dtype == "int8"
    st = MultiSoupState(
        weights=tuple(
            jax.ShapeDtypeStruct((n, t.num_weights), _pop_dtype(config))
            for t, n in zip(config.topos, config.sizes)),
        uids=tuple(jax.ShapeDtypeStruct((n,), jnp.int32)
                   for n in config.sizes),
        next_uid=jax.ShapeDtypeStruct((), jnp.int32),
        time=jax.ShapeDtypeStruct((), jnp.int32),
        key=_key_array_struct(),
        scales=tuple(jax.ShapeDtypeStruct((n,), jnp.float32)
                     for n in config.sizes) if int8 else None,
    )
    if mesh is None:
        return st
    from ..parallel.sharded_multisoup import _mstate_specs

    return _with_shardings(st, _mstate_specs(len(config.topos), int8), mesh)


def _stack_abstract(tree, k: int):
    """Prepend a tenant axis of width ``k`` to every array leaf of an
    abstract state (the serve tenant-stacked spellings' input skeleton)."""
    return jax.tree.map(
        lambda l: jax.ShapeDtypeStruct((k,) + tuple(l.shape), l.dtype)
        if isinstance(l, jax.ShapeDtypeStruct) else l, tree)


def abstract_stacked_soup_state(config, k: int) -> "Any":
    """(K, ...) tenant-stacked ``SoupState`` skeleton (``serve.tenant``)."""
    return _stack_abstract(abstract_soup_state(config), k)


def abstract_stacked_multi_state(config, k: int) -> "Any":
    """(K, ...) tenant-stacked ``MultiSoupState`` skeleton."""
    return _stack_abstract(abstract_multi_state(config), k)


def abstract_stacked_lineage_state(n: int, k: int) -> "Any":
    """(K, ...) tenant-stacked ``LineageState`` skeleton."""
    return _stack_abstract(abstract_lineage_state(n), k)


def aot_compile(name: str, jitted, args: Tuple, kwargs: Optional[dict] = None,
                persistent: bool = True) -> CompiledEntry:
    """Lower + compile ``jitted`` against ``args``/``kwargs`` ahead of time.

    Array(-like) arguments may be concrete or ``ShapeDtypeStruct``s — only
    shapes/dtypes matter; hashable non-array arguments (configs,
    topologies, meshes, ints) are statics and become part of the memo key.
    Returns the memoized :class:`CompiledEntry` for
    ``(name, statics, shapes, backend, device_count)``; a second call with
    the same key is a cache hit and does no work.  The backend compile
    additionally goes through jax's persistent on-disk cache (see
    :func:`ensure_compilation_cache`), so even the first in-process call
    is a fast deserialization when any earlier process built the same
    program.

    ``persistent=False`` compiles FRESH with the on-disk cache bypassed:
    an executable deserialized from the cache reports an empty
    ``memory_analysis()`` (stats are not serialized), so donation-aliasing
    and peak-memory inspection must use this spelling.
    """
    kwargs = dict(kwargs or {})
    abstract_args = tuple(_abstract(a) for a in args)
    backend = jax.default_backend()
    # ``persistent`` is part of the memo key: a persistent=False build must
    # never be answered by a cache-deserialized executable memoized earlier
    # under the same signature (its empty memory_analysis() would fake
    # alias_bytes=0 in donation checks)
    key = (name, _signature(abstract_args),
           tuple(sorted((k, repr(v)) for k, v in kwargs.items())),
           backend, jax.device_count(), persistent)
    hit = _EXECUTABLES.get(key)
    if hit is not None:
        _record_aot_metrics(name, hit=True)
        _record_cost(name, cached=True, lower_s=0.0, compile_s=0.0,
                     persistent=persistent, compiled=None, backend=backend)
        return hit._replace(cached=True, lower_s=0.0, compile_s=0.0)
    prev_dir = None
    if persistent:
        if _cache_dir_enabled is None:
            # respect a dir an earlier ensure_compilation_cache(path) call
            # picked — re-resolving defaults here would silently re-point
            # the cache away from an operator's --cache-dir
            ensure_compilation_cache()
    else:
        # snapshot the LIVE config value (it may come from the env default
        # without any ensure_compilation_cache call), so the restore below
        # never leaves the process permanently uncached
        prev_dir = getattr(jax.config, "jax_compilation_cache_dir", None)
        try:
            jax.config.update("jax_compilation_cache_dir", None)
        except AttributeError:
            pass
        # the dir change alone is not enough: once jax's cache singleton is
        # initialized (any earlier compile this process), it keeps serving
        # the old dir — drop it so this compile really bypasses the cache
        _reset_jax_cache_singleton()
    try:
        t0 = time.perf_counter()
        lowered = jitted.lower(*abstract_args, **kwargs)
        t1 = time.perf_counter()
        compiled = lowered.compile()
        t2 = time.perf_counter()
    finally:
        if not persistent:
            # re-point the singleton at whatever dir was live before
            _reset_jax_cache_singleton()
            if prev_dir is not None:
                try:
                    jax.config.update("jax_compilation_cache_dir", prev_dir)
                except AttributeError:
                    pass
    entry = CompiledEntry(name=name, compiled=compiled, key=key,
                          lower_s=t1 - t0, compile_s=t2 - t1, cached=False)
    _EXECUTABLES[key] = entry
    _record_aot_metrics(name, hit=False, lower_s=entry.lower_s,
                        compile_s=entry.compile_s)
    _record_cost(name, cached=False, lower_s=entry.lower_s,
                 compile_s=entry.compile_s, persistent=persistent,
                 compiled=compiled, backend=backend)
    return entry


def _record_cost(name: str, **kw) -> None:
    """One cost-ledger row per aot_compile outcome (``telemetry.costs``:
    compile seconds, memo hit/miss, XLA cost/memory analysis into
    ``compile_ledger.jsonl`` next to the persistent cache + the
    ``soup_compile_*``/``soup_hlo_flops``/``soup_hbm_bytes`` metrics).
    Fail-soft like :func:`_record_aot_metrics` — the cost plane must
    never break a compile path."""
    try:
        from ..telemetry import costs

        costs.record_compile(name, **kw)
    except Exception:
        pass


def _record_aot_metrics(entry: str, hit: bool, lower_s: float = 0.0,
                        compile_s: float = 0.0) -> None:
    """Host-side runtime metrics on the process ``telemetry.RUNTIME``
    registry: memo hit/miss counts and trace/compile seconds per entry
    point.  (A fresh compile served fast from jax's on-disk persistent
    cache still counts as a compile — its near-zero ``compile_s`` is the
    cache's win showing up in the histogram.)  Fail-soft by construction:
    telemetry must never break a compile path."""
    try:
        from ..telemetry.metrics import RUNTIME
    except Exception:
        return
    if hit:
        RUNTIME.counter("aot_memo_hits_total",
                        help="aot_compile served from the in-process "
                        "executable memo").inc(1, entry=entry)
        return
    RUNTIME.counter("aot_compiles_total",
                    help="aot_compile lower+compile builds").inc(
                        1, entry=entry)
    RUNTIME.counter("aot_lower_seconds_total",
                    help="seconds spent tracing/lowering",
                    unit="seconds").inc(lower_s, entry=entry)
    RUNTIME.counter("aot_compile_seconds_total",
                    help="seconds spent in backend compile",
                    unit="seconds").inc(compile_s, entry=entry)
    RUNTIME.histogram("aot_compile_seconds",
                      help="per-build backend compile seconds",
                      unit="seconds").observe(compile_s, entry=entry)


# ---------------------------------------------------------------------------
# warmup sweep over the hot entry points
# ---------------------------------------------------------------------------


def _soup_entries(config, generations: int, donate: bool):
    from .. import soup

    st = abstract_soup_state(config)
    step = soup.evolve_step_donated if donate else soup.evolve_step
    run = soup.evolve_donated if donate else soup.evolve
    tag = ".donated" if donate else ""
    yield (f"soup.evolve_step{tag}", step, (config, st), {})
    yield (f"soup.evolve{tag}", run, (config, st),
           {"generations": generations})
    # the mega-run loops and capture helpers dispatch the chunk run with
    # the telemetry carry (metrics=True, a STATIC arg — a different
    # program); warm that spelling too or production's first chunk
    # re-pays the compile this subsystem exists to remove.  Same story for
    # the flight recorder's health sentinels (metrics+health, the mega
    # loops' default spelling).
    yield (f"soup.evolve{tag}.metered", run, (config, st),
           {"generations": generations, "metrics": True})
    yield (f"soup.evolve{tag}.metered.health", run, (config, st),
           {"generations": generations, "metrics": True, "health": True})
    # the --lineage spelling of the mega loop (replication-dynamics carry;
    # telemetry.dynamics) — a different program again
    from ..telemetry.dynamics import DEFAULT_EDGE_CAPACITY

    yield (f"soup.evolve{tag}.metered.health.lineage", run, (config, st),
           {"generations": generations, "metrics": True, "health": True,
            "lineage": True, "lineage_state": abstract_lineage_state(
                config.size),
            "lineage_capacity": DEFAULT_EDGE_CAPACITY})
    # --lineage --no-health (the .metered.lineage diagnostic spelling) is
    # setups-reachable too; warming it keeps the flag-parity baseline at
    # ZERO waivers (it was the repo's only waived F010 finding)
    yield (f"soup.evolve{tag}.metered.lineage", run, (config, st),
           {"generations": generations, "metrics": True,
            "lineage": True, "lineage_state": abstract_lineage_state(
                config.size),
            "lineage_capacity": DEFAULT_EDGE_CAPACITY})
    # the fused-megakernel spellings (generation_impl='fused') are their
    # own programs — warm them for every fused-eligible popmajor config so
    # a `--generation-impl fused` run's first chunk deserializes instead
    # of compiling (a fused config's OWN entries are already fused)
    from ..soup import fused_supported

    if config.generation_impl != "fused" and fused_supported(config):
        fcfg = config._replace(generation_impl="fused")
        yield (f"soup.evolve_step{tag}.fused", step, (fcfg, st), {})
        yield (f"soup.evolve{tag}.fused", run, (fcfg, st),
               {"generations": generations})
        yield (f"soup.evolve{tag}.fused.metered.health", run, (fcfg, st),
               {"generations": generations, "metrics": True, "health": True})


def _multi_entries(config, generations: int, donate: bool):
    from .. import multisoup

    st = abstract_multi_state(config)
    step = multisoup.evolve_multi_step_donated if donate \
        else multisoup.evolve_multi_step
    run = multisoup.evolve_multi_donated if donate \
        else multisoup.evolve_multi
    tag = ".donated" if donate else ""
    yield (f"multisoup.evolve_multi_step{tag}", step, (config, st), {})
    yield (f"multisoup.evolve_multi{tag}", run, (config, st),
           {"generations": generations})
    yield (f"multisoup.evolve_multi{tag}.metered", run, (config, st),
           {"generations": generations, "metrics": True})
    yield (f"multisoup.evolve_multi{tag}.metered.health", run, (config, st),
           {"generations": generations, "metrics": True, "health": True})
    from ..telemetry.dynamics import DEFAULT_EDGE_CAPACITY

    yield (f"multisoup.evolve_multi{tag}.metered.health.lineage", run,
           (config, st),
           {"generations": generations, "metrics": True, "health": True,
            "lineage": True, "lineage_state": tuple(
                abstract_lineage_state(n) for n in config.sizes),
            "lineage_capacity": DEFAULT_EDGE_CAPACITY})
    yield (f"multisoup.evolve_multi{tag}.metered.lineage", run,
           (config, st),
           {"generations": generations, "metrics": True,
            "lineage": True, "lineage_state": tuple(
                abstract_lineage_state(n) for n in config.sizes),
            "lineage_capacity": DEFAULT_EDGE_CAPACITY})
    from ..multisoup import fused_supported_multi

    if config.generation_impl != "fused" and fused_supported_multi(config):
        fcfg = config._replace(generation_impl="fused")
        yield (f"multisoup.evolve_multi_step{tag}.fused", step, (fcfg, st),
               {})
        yield (f"multisoup.evolve_multi{tag}.fused", run, (fcfg, st),
               {"generations": generations})
        yield (f"multisoup.evolve_multi{tag}.fused.metered.health", run,
               (fcfg, st),
               {"generations": generations, "metrics": True, "health": True})


def _engine_entries(topo, size: int, donate: bool, step_limit: int,
                    epochs: int, train_mode: str):
    import jax.numpy as jnp

    from .. import engine

    pop = jax.ShapeDtypeStruct((size, topo.num_weights), jnp.float32)
    tag = ".donated" if donate else ""
    fix = engine.run_fixpoint_donated if donate else engine.run_fixpoint
    mixed = engine.run_mixed_fixpoint_donated if donate \
        else engine.run_mixed_fixpoint
    train = engine.run_training_donated if donate else engine.run_training
    yield (f"engine.run_fixpoint{tag}", fix, (topo, pop),
           {"step_limit": step_limit})
    yield (f"engine.run_mixed_fixpoint{tag}", mixed, (topo, pop),
           {"step_limit": step_limit, "train_mode": train_mode})
    yield (f"engine.run_training{tag}", train, (topo, pop),
           {"epochs": epochs, "train_mode": train_mode})


def _sharded_entries(config, mesh, generations: int, donate: bool):
    from ..parallel import sharded_soup

    st = abstract_soup_state(config, mesh=mesh)
    step = sharded_soup.sharded_evolve_step_donated if donate \
        else sharded_soup.sharded_evolve_step
    run = sharded_soup.sharded_evolve_donated if donate \
        else sharded_soup.sharded_evolve
    tag = ".donated" if donate else ""
    yield (f"parallel.sharded_evolve_step{tag}", step, (config, mesh, st), {})
    yield (f"parallel.sharded_evolve{tag}", run, (config, mesh, st),
           {"generations": generations})
    yield (f"parallel.sharded_evolve{tag}.metered", run, (config, mesh, st),
           {"generations": generations, "metrics": True})
    yield (f"parallel.sharded_evolve{tag}.metered.health", run,
           (config, mesh, st),
           {"generations": generations, "metrics": True, "health": True})
    from ..telemetry.dynamics import DEFAULT_EDGE_CAPACITY

    yield (f"parallel.sharded_evolve{tag}.metered.health.lineage", run,
           (config, mesh, st),
           {"generations": generations, "metrics": True, "health": True,
            "lineage": True, "lineage_state": abstract_lineage_state(
                config.size, mesh=mesh),
            "lineage_capacity": DEFAULT_EDGE_CAPACITY})
    yield (f"parallel.sharded_evolve{tag}.metered.lineage", run,
           (config, mesh, st),
           {"generations": generations, "metrics": True,
            "lineage": True, "lineage_state": abstract_lineage_state(
                config.size, mesh=mesh),
            "lineage_capacity": DEFAULT_EDGE_CAPACITY})
    from ..soup import fused_supported

    if config.generation_impl != "fused" and fused_supported(config):
        fcfg = config._replace(generation_impl="fused")
        yield (f"parallel.sharded_evolve_step{tag}.fused", step,
               (fcfg, mesh, st), {})
        yield (f"parallel.sharded_evolve{tag}.fused", run, (fcfg, mesh, st),
               {"generations": generations})
        # the sharded mega chunk loop dispatches metrics+health by default
        # — warm that spelling too or a sharded fused run's first chunk
        # re-pays the compile (same rationale as the unsharded block)
        yield (f"parallel.sharded_evolve{tag}.fused.metered.health", run,
               (fcfg, mesh, st),
               {"generations": generations, "metrics": True, "health": True})


def _sharded_multi_entries(config, mesh, generations: int, donate: bool):
    from ..parallel import sharded_multisoup as sm

    st = abstract_multi_state(config, mesh=mesh)
    step = sm.sharded_evolve_multi_step_donated if donate \
        else sm.sharded_evolve_multi_step
    run = sm.sharded_evolve_multi_donated if donate \
        else sm.sharded_evolve_multi
    tag = ".donated" if donate else ""
    yield (f"parallel.sharded_evolve_multi_step{tag}", step,
           (config, mesh, st), {})
    yield (f"parallel.sharded_evolve_multi{tag}", run, (config, mesh, st),
           {"generations": generations})
    yield (f"parallel.sharded_evolve_multi{tag}.metered", run,
           (config, mesh, st),
           {"generations": generations, "metrics": True})
    yield (f"parallel.sharded_evolve_multi{tag}.metered.health", run,
           (config, mesh, st),
           {"generations": generations, "metrics": True, "health": True})
    from ..telemetry.dynamics import DEFAULT_EDGE_CAPACITY

    yield (f"parallel.sharded_evolve_multi{tag}.metered.health.lineage", run,
           (config, mesh, st),
           {"generations": generations, "metrics": True, "health": True,
            "lineage": True, "lineage_state": tuple(
                abstract_lineage_state(n, mesh=mesh)
                for n in config.sizes),
            "lineage_capacity": DEFAULT_EDGE_CAPACITY})
    yield (f"parallel.sharded_evolve_multi{tag}.metered.lineage", run,
           (config, mesh, st),
           {"generations": generations, "metrics": True,
            "lineage": True, "lineage_state": tuple(
                abstract_lineage_state(n, mesh=mesh)
                for n in config.sizes),
            "lineage_capacity": DEFAULT_EDGE_CAPACITY})
    from ..multisoup import fused_supported_multi

    if config.generation_impl != "fused" and fused_supported_multi(config):
        fcfg = config._replace(generation_impl="fused")
        yield (f"parallel.sharded_evolve_multi_step{tag}.fused", step,
               (fcfg, mesh, st), {})
        yield (f"parallel.sharded_evolve_multi{tag}.fused", run,
               (fcfg, mesh, st), {"generations": generations})
        yield (f"parallel.sharded_evolve_multi{tag}.fused.metered.health",
               run, (fcfg, mesh, st),
               {"generations": generations, "metrics": True, "health": True})


def _stacked_entries(config, k: int, generations: int, donate: bool):
    """The serve tenant-axis spellings (``serve.tenant.evolve_stacked``)
    for a K-tenant stack of ``config`` — the experiment service warms
    these so a stacked dispatch's first tenants only execute.  Covers the
    full carry lattice the service (and its clients) can dispatch:
    metrics alone, metrics+lineage, and the health twins."""
    from ..soup import tenant_stackable

    if not tenant_stackable(config):
        return
    from ..serve import tenant as serve_tenant

    st = abstract_stacked_soup_state(config, k)
    run = serve_tenant.evolve_stacked_donated if donate \
        else serve_tenant.evolve_stacked
    step = serve_tenant.evolve_stacked_step_donated if donate \
        else serve_tenant.evolve_stacked_step
    tag = ".donated" if donate else ""
    from ..telemetry.dynamics import DEFAULT_EDGE_CAPACITY

    lin = abstract_stacked_lineage_state(config.size, k)
    yield (f"serve.evolve_stacked_step{tag}", step, (config, st), {})
    yield (f"serve.evolve_stacked{tag}", run, (config, st),
           {"generations": generations})
    yield (f"serve.evolve_stacked{tag}.metered", run, (config, st),
           {"generations": generations, "metrics": True})
    yield (f"serve.evolve_stacked{tag}.metered.health", run, (config, st),
           {"generations": generations, "metrics": True, "health": True})
    yield (f"serve.evolve_stacked{tag}.metered.health.lineage", run,
           (config, st),
           {"generations": generations, "metrics": True, "health": True,
            "lineage": True, "lineage_state": lin,
            "lineage_capacity": DEFAULT_EDGE_CAPACITY})
    yield (f"serve.evolve_stacked{tag}.metered.lineage", run, (config, st),
           {"generations": generations, "metrics": True,
            "lineage": True, "lineage_state": lin,
            "lineage_capacity": DEFAULT_EDGE_CAPACITY})


def _stacked_multi_entries(config, k: int, generations: int, donate: bool):
    """Tenant-stacked spellings of the heterogeneous surface
    (``serve.tenant.evolve_multi_stacked``)."""
    from ..multisoup import tenant_stackable_multi

    if not tenant_stackable_multi(config):
        return
    from ..serve import tenant as serve_tenant

    st = abstract_stacked_multi_state(config, k)
    run = serve_tenant.evolve_multi_stacked_donated if donate \
        else serve_tenant.evolve_multi_stacked
    tag = ".donated" if donate else ""
    from ..telemetry.dynamics import DEFAULT_EDGE_CAPACITY

    lin = tuple(abstract_stacked_lineage_state(n, k) for n in config.sizes)
    yield (f"serve.evolve_multi_stacked{tag}", run, (config, st),
           {"generations": generations})
    yield (f"serve.evolve_multi_stacked{tag}.metered", run, (config, st),
           {"generations": generations, "metrics": True})
    yield (f"serve.evolve_multi_stacked{tag}.metered.health", run,
           (config, st),
           {"generations": generations, "metrics": True, "health": True})
    yield (f"serve.evolve_multi_stacked{tag}.metered.health.lineage", run,
           (config, st),
           {"generations": generations, "metrics": True, "health": True,
            "lineage": True, "lineage_state": lin,
            "lineage_capacity": DEFAULT_EDGE_CAPACITY})
    yield (f"serve.evolve_multi_stacked{tag}.metered.lineage", run,
           (config, st),
           {"generations": generations, "metrics": True,
            "lineage": True, "lineage_state": lin,
            "lineage_capacity": DEFAULT_EDGE_CAPACITY})


def warmup(config=None, *, multi=None, mesh=None, generations: int = 100,
           donate: bool = True, engine: bool = False, step_limit: int = 100,
           epochs: int = 100, stacked: int = 0,
           verbose: bool = False) -> "list[dict]":
    """AOT-compile the hot entry points so later dispatches only execute.

    ``config`` (a ``SoupConfig``) warms the homogeneous soup step/run;
    ``multi`` (a ``MultiSoupConfig``) the heterogeneous twins; ``mesh``
    additionally warms the sharded steps for whichever of the two configs
    are given; ``engine=True`` adds the fixpoint/training engines sized
    from ``config`` (or ``multi``'s per-type topos).  ``stacked=K`` (>0)
    additionally warms the serve TENANT-AXIS spellings at stack width K
    (``serve.tenant`` — skipped silently for configs that cannot stack).
    ``donate`` picks the buffer-donating production spellings (default) —
    pass ``False`` to warm the value-preserving ones used by parity
    tooling.

    Returns one row per entry: ``{"entry", "cached", "lower_s",
    "compile_s", "backend"}`` — ``cached`` meaning served from the
    in-process memo (an on-disk persistent-cache hit still shows as a
    fresh compile, just a fast one).
    """
    jobs = []
    if config is not None:
        jobs += list(_soup_entries(config, generations, donate))
        if mesh is not None:
            jobs += list(_sharded_entries(config, mesh, generations, donate))
        if stacked > 0:
            jobs += list(_stacked_entries(config, stacked, generations,
                                          donate))
    if multi is not None:
        jobs += list(_multi_entries(multi, generations, donate))
        if mesh is not None:
            jobs += list(_sharded_multi_entries(multi, mesh, generations,
                                                donate))
        if stacked > 0:
            jobs += list(_stacked_multi_entries(multi, stacked, generations,
                                                donate))
    if engine:
        # each topo keeps ITS config's train_mode — it is a static arg, so
        # warming the wrong mode would compile a dead executable
        topos = [(config.topo, config.size, config.train_mode)] \
            if config is not None else []
        if multi is not None:
            topos += [(t, n, multi.train_mode)
                      for t, n in zip(multi.topos, multi.sizes)]
        for topo, size, train_mode in topos:
            jobs += list(_engine_entries(topo, size, donate, step_limit,
                                         epochs, train_mode))
    rows = []
    for name, jitted, args, kwargs in jobs:
        entry = aot_compile(name, jitted, args, kwargs)
        row = {"entry": name, "cached": entry.cached,
               "lower_s": round(entry.lower_s, 4),
               "compile_s": round(entry.compile_s, 4),
               "backend": jax.default_backend()}
        rows.append(row)
        if verbose:
            print(f"warmup: {name}: "
                  + ("memo hit" if entry.cached else
                     f"lower {entry.lower_s:.2f}s compile "
                     f"{entry.compile_s:.2f}s"), file=sys.stderr, flush=True)
    return rows
