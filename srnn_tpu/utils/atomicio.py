"""Crash-safe small-file writes: write-tmp → fsync → atomic rename.

A mega run killed mid-write (SIGKILL, preemption, power) must never leave
a *plausible-looking but torn* file where the resume path will trip over
it.  ``os.replace`` alone survives a kill between open and rename, but
not a kill between rename and the data reaching disk — the fsync before
the rename closes that window (POSIX: an fsync'd tmp file renamed over
the target is the canonical atomic-publish sequence).

Used for the checkpoint ``SRNN_CKPT_OK`` markers (``experiment.py``),
``config.json`` (``setups.common.save_run_config``) and the lineage
resume sidecar — the files ``--resume`` reads first.
"""

import os


def fsync_dir(path: str) -> None:
    """fsync a DIRECTORY so a just-published rename inside it is durable:
    ``os.replace`` updates the directory entry, and until the directory
    itself syncs a power loss can resurrect the OLD file beside newer
    siblings (a stale ``metrics.prom`` next to a newer ``events.jsonl``,
    a vanished checkpoint marker).  Fail-soft: filesystems that refuse
    directory fsync (some network mounts) lose only this extra guarantee,
    never the write."""
    try:
        fd = os.open(path, os.O_RDONLY | getattr(os, "O_DIRECTORY", 0))
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path: str, data: bytes) -> str:
    """Publish ``data`` at ``path`` atomically (tmp + fsync + rename +
    parent-directory fsync).  The tmp file lives in the target's
    directory so the rename never crosses a filesystem boundary."""
    path = os.path.abspath(path)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(data)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    fsync_dir(os.path.dirname(path))
    return path


def atomic_write_text(path: str, text: str) -> str:
    return atomic_write_bytes(path, text.encode("utf-8"))
