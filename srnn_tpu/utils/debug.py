"""NaN/Inf provenance and checked execution (SURVEY §5, race/sanitizer row).

Divergence (NaN/Inf weights) is a *measured outcome* in this science, so it
must never be silently masked — but when it is unexpected, these tools
locate it:

  * :func:`checked_apply_to_weights` — checkify-wrapped self-application
    that raises with a readable message if the output goes non-finite
    (the debug-mode analog of the reference's ``are_weights_diverged``
    post-hoc predicate, ``network.py:43-52``).
  * :func:`divergence_onset` — scan a soup forward and report, per
    particle, the first generation its weights went non-finite (-1 if
    never).  One jitted program, no host round-trips per step.
"""

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import checkify

from ..nets import apply_to_weights
from ..ops.predicates import is_diverged
from ..soup import SoupConfig, SoupState, evolve_step
from ..topology import Topology


def checked_apply_to_weights(topo: Topology, self_flat, target_flat):
    """Self-application that *errors* (checkify) on non-finite output.

    Returns the new weights; raises ``checkify.JaxRuntimeError`` with the
    offending variant/shape context if any output weight is NaN/Inf while
    all inputs were finite.
    """

    def inner(s, t):
        out = apply_to_weights(topo, s, t)
        inputs_ok = ~is_diverged(s) & ~is_diverged(t)
        checkify.check(
            ~(inputs_ok & is_diverged(out)),
            f"apply_to_weights({topo.variant}) produced non-finite output "
            "from finite inputs (|self|={ns}, |target|={nt})",
            ns=jnp.abs(s).max(), nt=jnp.abs(t).max(),
        )
        return out

    err, out = checkify.checkify(inner)(self_flat, target_flat)
    err.throw()
    return out


@functools.partial(jax.jit, static_argnames=("config", "generations"))
def divergence_onset(config: SoupConfig, state: SoupState,
                     generations: int) -> Tuple[jnp.ndarray, SoupState]:
    """(N,) first generation (1-based) each SLOT went non-finite, -1 if
    never within ``generations``.  Runs with respawn disabled so the onset
    is observable (a respawning soup replaces divergent particles in the
    same step, reference ``soup.py:77-86``)."""
    probe_cfg = config._replace(remove_divergent=False, remove_zero=False)

    def step(carry, _):
        st, onset = carry
        new_st, _ev = evolve_step(probe_cfg, st)
        now_div = is_diverged(new_st.weights)
        onset = jnp.where((onset < 0) & now_div, new_st.time.astype(jnp.int32), onset)
        return (new_st, onset), None

    onset0 = jnp.where(is_diverged(state.weights), 0, -1).astype(jnp.int32)
    (final, onset), _ = jax.lax.scan(step, (state, onset0), None, length=generations)
    return onset, final
