"""Streaming soup evolution with strided trajectory capture.

Bridges the jitted soup engine and the host-side trajectory store: evolve
in device-resident chunks of ``every`` generations, pull only the LAST
frame of each chunk to host, append it to a :class:`TrajStore`.  With the
native store, the background writer thread overlaps the disk write with the
next chunk's device compute.

Capture stride is the knob SURVEY §5 calls for: full per-step history of a
mega-soup cannot leave the device, so the run records every ``every``-th
generation (``every=1`` reproduces the reference's full
``ParticleDecorator.save_state`` history).
"""

from typing import Optional, Tuple

import jax
import numpy as np

from ..soup import SoupConfig, SoupState, evolve, evolve_step
from .trajstore import TrajStore


def evolve_captured(
    config: SoupConfig,
    state: SoupState,
    generations: int,
    store: TrajStore,
    every: int = 1,
) -> SoupState:
    """Evolve ``generations`` steps, appending one frame per ``every``
    generations to ``store``.  Returns the final state.

    Frames carry the true per-generation event record (action/counterpart/
    loss of the captured generation), so the event-log semantics match the
    unsampled run at the captured points.
    """
    if generations % every != 0:
        raise ValueError(f"generations={generations} not divisible by every={every}")
    for _ in range(generations // every):
        if every > 1:
            state = evolve(config, state, generations=every - 1)
        state, events = evolve_step(config, state)
        # one host transfer per captured frame; everything else stays on device
        frame = jax.device_get(
            (state.time, state.weights, state.uids,
             events.action, events.counterpart, events.loss))
        t, w, uids, action, counterpart, loss = frame
        store.append(int(t), w, uids, action, counterpart, loss)
    store.flush()
    return state
