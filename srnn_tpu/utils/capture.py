"""Streaming soup evolution with strided trajectory capture.

Bridges the jitted soup engine and the host-side trajectory store: evolve
in device-resident chunks of ``every`` generations, pull only the LAST
frame of each chunk to host, append it to a :class:`TrajStore`.

Capture stride is the knob SURVEY §5 calls for: full per-step history of a
mega-soup cannot leave the device, so the run records every ``every``-th
generation (``every=1`` reproduces the reference's full
``ParticleDecorator.save_state`` history).

Pipelined capture (the default, ``pipelined=True``): frame pulls are
non-blocking — each captured step's arrays are device-copied
(:func:`pipeline.snapshot`, donation-safe: the copy is dispatched before
the source state is donated to the next step) with the device-to-host
transfer started immediately, and the resolve + ``TrajStore.append`` run
on a bounded :class:`pipeline.BackgroundWriter`, so the host loop keeps
dispatching device work while frames drain to disk.  Registry updates
ride the same writer (the count dispatch stays on the producing thread;
only the resolve moves).  The captured stream is BIT-IDENTICAL to the
blocking path: the same donated executables run in the same order, and
the snapshots are pure copies.  Pass ``writer=`` to share a mega-run
loop's writer (the caller then owns flush ordering across frames,
checkpoints, and sinks); otherwise a private writer is created and
closed — joined and flushed — before returning.  ``pipelined=False``
keeps the original blocking loop (parity tests, A/B measurement).
"""

from typing import Optional, Tuple

import jax
import numpy as np

from ..soup import (SoupConfig, SoupState, evolve_donated,
                    evolve_step_donated)
from .aot import own_pytree
from .pipeline import BackgroundWriter, resolve, snapshot
from .trajstore import TrajStore, shard_path


def _append_frame(store: TrajStore, snap) -> None:
    """Writer job: materialize one snapshotted frame and append it."""
    t, w, uids, action, counterpart, loss = resolve(snap)
    store.append(int(t), w, uids, action, counterpart, loss)


def _append_multi_frame(stores, snap) -> None:
    """Writer job: one snapshotted heterogeneous frame -> per-type stores."""
    t, ws, uids, action, counterpart, loss = resolve(snap)
    for i, store in enumerate(stores):
        store.append(int(t), ws[i], uids[i], action[i], counterpart[i],
                     loss[i])


def evolve_captured(
    config: SoupConfig,
    state: SoupState,
    generations: int,
    store: TrajStore,
    every: int = 1,
    owned: bool = False,
    registry=None,
    pipelined: bool = True,
    writer: Optional[BackgroundWriter] = None,
) -> SoupState:
    """Evolve ``generations`` steps, appending one frame per ``every``
    generations to ``store``.  Returns the final state.

    Frames carry the true per-generation event record (action/counterpart/
    loss of the captured generation), so the event-log semantics match the
    unsampled run at the captured points.

    ``owned=True`` asserts the caller hands over ``state``: it must be a
    jax-owned buffer (a jit output, or ``aot.own_pytree`` of a restore)
    that the caller never touches again — the mega-run loops, which rebind
    every chunk, pass this to skip the defensive copy below.

    ``registry`` (a ``telemetry.MetricsRegistry``) meters the run: the
    intermediate ``every - 1`` generations ride the in-scan metrics carry
    and the captured step's events — already in hand — are counted with
    one tiny extra dispatch, so the registry sees EVERY generation (not a
    stride sample) at no additional host transfers beyond the frames.

    ``pipelined``/``writer``: see the module docstring — non-blocking
    frame pulls resolved on a background writer, bit-identical stream.
    """
    if generations % every != 0:
        raise ValueError(f"generations={generations} not divisible by every={every}")
    if registry is not None:
        from ..telemetry.device import count_events
        from ..telemetry.soup_metrics import update_registry
    # ALL-donated internal stream: every generation executes the donated
    # executable, so the captured stream is bitwise chunking-invariant (the
    # donated and plain programs may differ by fusion ulps on some XLA
    # versions — mixing them would make resume/stride choices visible in
    # the bits).  By default the caller's state is never consumed: it is
    # first copied into jax-owned buffers (own_pytree) and only the copy
    # is donated; ``owned=True`` skips the copy (one population of peak
    # memory saved) for callers that hand the state over.
    if not owned:
        state = own_pytree(state)
    if not pipelined:
        for _ in range(generations // every):
            if every > 1:
                if registry is not None:
                    state, m = evolve_donated(config, state,
                                              generations=every - 1,
                                              metrics=True)
                    update_registry(registry, m, n_particles=config.size)
                else:
                    state = evolve_donated(config, state,
                                           generations=every - 1)
            state, events = evolve_step_donated(config, state)
            if registry is not None:
                update_registry(registry,
                                count_events(events.action, events.loss),
                                n_particles=config.size)
            # one host transfer per captured frame; all else stays on device
            frame = jax.device_get(
                (state.time, state.weights, state.uids,
                 events.action, events.counterpart, events.loss))
            t, w, uids, action, counterpart, loss = frame
            store.append(int(t), w, uids, action, counterpart, loss)
        store.flush()
        return state
    own_writer = writer is None
    w = BackgroundWriter(name="srnn-capture-io") if own_writer else writer
    if own_writer:
        w.add_close_hook(store.join)  # crash path: appended frames durable
    try:
        for _ in range(generations // every):
            if every > 1:
                if registry is not None:
                    state, m = evolve_donated(config, state,
                                              generations=every - 1,
                                              metrics=True)
                    w.submit(update_registry, registry, m,
                             n_particles=config.size)
                else:
                    state = evolve_donated(config, state,
                                           generations=every - 1)
            state, events = evolve_step_donated(config, state)
            if registry is not None:
                # count dispatch on THIS thread (device-stream order);
                # only the resolve moves to the writer
                w.submit(update_registry, registry,
                         count_events(events.action, events.loss),
                         n_particles=config.size)
            # snapshot BEFORE the next iteration donates state's buffers;
            # the append job resolves the in-flight transfer off-thread
            w.submit(_append_frame, store,
                     snapshot((state.time, state.weights, state.uids,
                               events.action, events.counterpart,
                               events.loss)))
        w.submit(store.flush)
    finally:
        if own_writer:
            w.close()  # join + flush; re-raises any writer-job error
    return state


def evolve_multi_captured(
    config,
    state,
    generations: int,
    stores,
    every: int = 1,
    owned: bool = False,
    registry=None,
    pipelined: bool = True,
    writer: Optional[BackgroundWriter] = None,
):
    """Heterogeneous-soup twin of :func:`evolve_captured`: one
    :class:`TrajStore` per TYPE (``stores[t]`` holds type t's (N_t, P_t)
    frames), so the mixed mega-soup's history survives at scale the same
    way the homogeneous one's does.  Returns the final state.

    ``registry`` meters every generation exactly as in
    :func:`evolve_captured`, with per-type labels (``type=<variant>``);
    ``pipelined``/``writer`` behave exactly as there (non-blocking frame
    pulls, background appends, bit-identical per-type streams)."""
    from ..multisoup import evolve_multi_donated, evolve_multi_step_donated

    if generations % every != 0:
        raise ValueError(
            f"generations={generations} not divisible by every={every}")
    if len(stores) != len(config.topos):
        raise ValueError(f"need one store per type "
                         f"({len(config.topos)}), got {len(stores)}")
    if registry is not None:
        from ..telemetry.device import count_events
        from ..telemetry.soup_metrics import (type_names,
                                              update_multi_registry,
                                              update_registry)

        tnames = type_names(config)
    # copy-then-donate unless the caller hands the state over: see
    # evolve_captured (chunking-invariant stream; ``owned=True`` skips the
    # defensive copy for rebinding callers)
    if not owned:
        state = own_pytree(state)
    if not pipelined:
        for _ in range(generations // every):
            if every > 1:
                if registry is not None:
                    state, ms = evolve_multi_donated(
                        config, state, generations=every - 1, metrics=True)
                    update_multi_registry(registry, ms, config)
                else:
                    state = evolve_multi_donated(config, state,
                                                 generations=every - 1)
            state, events = evolve_multi_step_donated(config, state)
            if registry is not None:
                for t, tname in enumerate(tnames):
                    update_registry(
                        registry,
                        count_events(events.action[t], events.loss[t]),
                        type_name=tname, n_particles=config.sizes[t])
            frame = jax.device_get(
                (state.time, state.weights, state.uids,
                 events.action, events.counterpart, events.loss))
            t, ws, uids, action, counterpart, loss = frame
            for i, store in enumerate(stores):
                store.append(int(t), ws[i], uids[i], action[i],
                             counterpart[i], loss[i])
        for store in stores:
            store.flush()
        return state
    own_writer = writer is None
    w = BackgroundWriter(name="srnn-capture-io") if own_writer else writer
    if own_writer:
        for store in stores:
            w.add_close_hook(store.join)
    try:
        for _ in range(generations // every):
            if every > 1:
                if registry is not None:
                    state, ms = evolve_multi_donated(
                        config, state, generations=every - 1, metrics=True)
                    w.submit(update_multi_registry, registry, ms, config)
                else:
                    state = evolve_multi_donated(config, state,
                                                 generations=every - 1)
            state, events = evolve_multi_step_donated(config, state)
            if registry is not None:
                for t, tname in enumerate(tnames):
                    w.submit(update_registry, registry,
                             count_events(events.action[t], events.loss[t]),
                             type_name=tname, n_particles=config.sizes[t])
            w.submit(_append_multi_frame, stores,
                     snapshot((state.time, state.weights, state.uids,
                               events.action, events.counterpart,
                               events.loss)))
        for store in stores:
            w.submit(store.flush)
    finally:
        if own_writer:
            w.close()
    return state


# ---------------------------------------------------------------------------
# Multihost-aware sharded capture (round-3 gap: the path above pulls FULL
# global frames to one host — ~56 MB x every captured frame over DCN at real
# multi-host mega-soup scale).
# ---------------------------------------------------------------------------


def _local_rows(arr, lo: int, hi: int, multihost: bool) -> np.ndarray:
    """This process's contiguous row block [lo, hi) of a particle-sharded
    array.  On a real multi-process runtime the rows come from the
    process's addressable shards (no cross-host transfer); otherwise —
    single process, or a test simulating process (lo, hi) windows — a plain
    slice of the (fully addressable) array."""
    if multihost:
        shards = sorted(arr.addressable_shards,
                        key=lambda s: s.index[0].start or 0)
        # every shard must sit exactly at the running offset — interleaved
        # ownership (this process holding non-adjacent row blocks) would
        # otherwise be written as a mislabeled contiguous block and corrupt
        # the merged timeline silently
        off = lo
        for s in shards:
            start = s.index[0].start or 0
            if start != off:
                raise RuntimeError(
                    f"process shard starts at row {start}, expected {off}: "
                    f"rows do not form the contiguous block [{lo}, {hi}); "
                    "re-check the mesh's device-to-process layout")
            off += s.data.shape[0]
        if off != hi:
            raise RuntimeError(
                f"process rows [{lo}, {off}) do not cover the expected "
                f"block [{lo}, {hi})")
        return np.concatenate([np.asarray(s.data) for s in shards], axis=0)
    return np.asarray(arr)[lo:hi]


def _append_sharded_frame(store: TrajStore, snap, lo: int, hi: int,
                          multihost: bool) -> None:
    """Writer job: this process's rows of one snapshotted sharded frame.
    The snapshot's jit copy preserved the particle-axis sharding, so the
    shard-local reads below touch only addressable data."""
    t, w, u, a, c, l = snap
    store.append(int(jax.device_get(t)),
                 _local_rows(w, lo, hi, multihost),
                 _local_rows(u, lo, hi, multihost),
                 _local_rows(a, lo, hi, multihost),
                 _local_rows(c, lo, hi, multihost),
                 _local_rows(l, lo, hi, multihost))


def open_process_shard(
    config: SoupConfig,
    base_path: str,
    mode: str = "w",
    process_index: Optional[int] = None,
    num_processes: Optional[int] = None,
) -> TrajStore:
    """Open THIS process's trajectory shard for a sharded captured run
    (``shard_path`` naming; plain ``base_path`` when single-process).
    ``process_index``/``num_processes`` default to the jax runtime's
    values; passing them explicitly lets a single-process test (or an
    external launcher) write any shard of the set."""
    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if num_processes is None else num_processes
    if config.size % pc:
        raise ValueError(f"size {config.size} not divisible by {pc} processes")
    return TrajStore(shard_path(base_path, pi, pc),
                     n_particles=config.size // pc,
                     n_weights=config.topo.num_weights, mode=mode)


def sharded_evolve_captured(
    config: SoupConfig,
    mesh,
    state: SoupState,
    generations: int,
    store: TrajStore,
    every: int = 1,
    process_index: Optional[int] = None,
    num_processes: Optional[int] = None,
    registry=None,
    pipelined: bool = True,
    writer: Optional[BackgroundWriter] = None,
) -> SoupState:
    """Sharded-soup evolution with PER-PROCESS trajectory shards.

    Each process appends only its own contiguous particle-row block (the
    ``store`` from :func:`open_process_shard`) — host IO and DCN traffic
    scale 1/processes, and ``trajstore.read_sharded_store`` merges the
    shards into global frames offline.  Scales the reference's
    never-lose-history registry (``soup.py:37-43``) to multihost.

    ``registry`` meters every generation with GLOBAL counters (the
    metered sharded scan psums at the shard boundary; the captured step's
    sharded events reduce under GSPMD) — every process sees the same
    totals, so a per-process sink stays consistent with its siblings.
    """
    from ..parallel import (sharded_evolve, sharded_evolve_donated,
                            sharded_evolve_step,
                            sharded_evolve_step_donated)

    pi = jax.process_index() if process_index is None else process_index
    pc = jax.process_count() if num_processes is None else num_processes
    if pc != jax.process_count() and jax.process_count() > 1:
        # explicit counts only simulate a multi-process layout on a
        # SINGLE-process runtime (tests); on a real multi-process runtime a
        # mismatched count would route through the plain-slice path below,
        # which would try to materialize non-addressable rows
        raise ValueError(
            f"num_processes={pc} does not match the live runtime's "
            f"{jax.process_count()} processes; omit the explicit counts "
            "under a real multi-process launcher")
    n_loc = config.size // pc
    if store.n != n_loc:
        raise ValueError(
            f"store holds {store.n} rows but process owns {n_loc}")
    lo, hi = pi * n_loc, (pi + 1) * n_loc
    multihost = jax.process_count() == pc and pc > 1
    if generations % every != 0:
        raise ValueError(f"generations={generations} not divisible by every={every}")

    if registry is not None:
        from ..telemetry.device import count_events
        from ..telemetry.soup_metrics import update_registry

    if not pipelined:
        owned = False  # donate internal states only, never the caller's
        for _ in range(generations // every):
            if every > 1:
                run = sharded_evolve_donated if owned else sharded_evolve
                if registry is not None:
                    state, m = run(config, mesh, state,
                                   generations=every - 1, metrics=True)
                    update_registry(registry, m, n_particles=config.size)
                else:
                    state = run(config, mesh, state, generations=every - 1)
                owned = True
            step = sharded_evolve_step_donated if owned \
                else sharded_evolve_step
            state, events = step(config, mesh, state)
            owned = True
            if registry is not None:
                update_registry(registry,
                                count_events(events.action, events.loss),
                                n_particles=config.size)
            t = int(jax.device_get(state.time))
            store.append(
                t,
                _local_rows(state.weights, lo, hi, multihost),
                _local_rows(state.uids, lo, hi, multihost),
                _local_rows(events.action, lo, hi, multihost),
                _local_rows(events.counterpart, lo, hi, multihost),
                _local_rows(events.loss, lo, hi, multihost))
        store.flush()
        return state
    own_writer = writer is None
    w = BackgroundWriter(name="srnn-capture-io") if own_writer else writer
    if own_writer:
        w.add_close_hook(store.join)
    try:
        owned = False  # donate internal states only, never the caller's
        for _ in range(generations // every):
            if every > 1:
                run = sharded_evolve_donated if owned else sharded_evolve
                if registry is not None:
                    state, m = run(config, mesh, state,
                                   generations=every - 1, metrics=True)
                    w.submit(update_registry, registry, m,
                             n_particles=config.size)
                else:
                    state = run(config, mesh, state, generations=every - 1)
                owned = True
            step = sharded_evolve_step_donated if owned \
                else sharded_evolve_step
            state, events = step(config, mesh, state)
            owned = True
            if registry is not None:
                w.submit(update_registry, registry,
                         count_events(events.action, events.loss),
                         n_particles=config.size)
            # sharding-preserving snapshot before the next donated
            # dispatch; the writer does only shard-LOCAL reads of it
            w.submit(_append_sharded_frame, store,
                     snapshot((state.time, state.weights, state.uids,
                               events.action, events.counterpart,
                               events.loss)),
                     lo, hi, multihost)
        w.submit(store.flush)
    finally:
        if own_writer:
            w.close()
    return state
