"""Backend probing/retry helpers for driver entry points.

The tunneled 'axon' TPU platform is flaky at *initialization* time: the
plugin sometimes raises ``RuntimeError: Unable to initialize backend 'axon'``
even though a retry seconds later succeeds (observed repeatedly; the round-1
bench failure was exactly this).  jax caches the failed client, so a bare
retry inside the same process does nothing — the backend registry must be
cleared between attempts.

``ensure_backend`` turns "tunnel luck" into a bounded retry loop with an
optional CPU fallback, so ``bench.py`` / benchmarks always produce a useful
JSON line instead of a stack trace.
"""

import os
import sys
import time
from typing import Optional


def _clear_backends() -> None:
    try:
        from jax.extend import backend as jax_backend

        jax_backend.clear_backends()
    except Exception:
        pass


def force_cpu(n_devices: Optional[int] = None) -> None:
    """Pin the process to the host-CPU platform (optionally with ``n_devices``
    virtual devices) WITHOUT ever touching the default backend — safe to call
    before any jax API that would initialize the flaky tunnel.

    Side effect by design: invalidates live Arrays/compiled fns
    (clear_backends).  Call at process start, never mid-computation.
    """
    import jax

    if n_devices is not None:
        try:
            # jax.config refuses jax_num_cpu_devices after backend init;
            # set_global skips that pre-init-only validator (private API,
            # jax 0.9.x) and clear_backends rebuilds the client.
            from jax._src import xla_bridge

            xla_bridge.num_cpu_devices.set_global(n_devices)
        except Exception:
            os.environ["XLA_FLAGS"] = (
                os.environ.get("XLA_FLAGS", "")
                + f" --xla_force_host_platform_device_count={n_devices}")
    jax.config.update("jax_platforms", "cpu")
    _clear_backends()


def ensure_backend(retries: int = 4, sleep_s: float = 10.0,
                   fallback_cpu: bool = True) -> "tuple[str, bool]":
    """Probe the default jax backend, retrying init failures with the backend
    registry cleared between attempts.  Returns ``(platform, fell_back)``
    where ``platform`` is the live platform name ('tpu', 'axon', 'cpu', ...)
    and ``fell_back`` is True only when the CPU fallback actually fired — a
    machine whose default backend IS the CPU returns ('cpu', False).

    With ``fallback_cpu`` the last resort is the host CPU platform (so a
    caller can still produce an honest, labeled result); otherwise the final
    error propagates.

    Note: this guards against init *errors*; an init that HANGS must be
    bounded by the caller (see :func:`watchdog`).
    """
    import jax

    last: Optional[BaseException] = None
    retries = max(retries, 1)
    for attempt in range(retries):
        try:
            return jax.devices()[0].platform, False
        except RuntimeError as e:
            last = e
            _clear_backends()
            if attempt < retries - 1:  # no pointless sleep after the last try
                print(f"ensure_backend: attempt {attempt + 1}/{retries} "
                      f"failed ({e}); retrying in {sleep_s:.0f}s",
                      file=sys.stderr)
                time.sleep(sleep_s)
            else:
                print(f"ensure_backend: attempt {attempt + 1}/{retries} "
                      f"failed ({e})", file=sys.stderr)
    if fallback_cpu:
        print("ensure_backend: default backend unavailable, falling back "
              "to CPU", file=sys.stderr)
        force_cpu()
        return jax.devices()[0].platform, True
    raise last  # type: ignore[misc]


def watchdog(seconds: float, on_fire=None, exit_code: int = 3):
    """Bound a whole process phase against backend WEDGES (an init or
    compile that hangs instead of raising — the tunneled platform's other
    failure mode).  After ``seconds``, runs ``on_fire()`` (e.g. print a
    fail-soft JSON line) and hard-exits.  Returns a ``cancel()`` callable.
    """
    import threading

    def fire():
        try:
            if on_fire is not None:
                on_fire()
        finally:
            print(f"watchdog: fired after {seconds:.0f}s — backend wedge or "
                  f"compile stall", file=sys.stderr, flush=True)
            os._exit(exit_code)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t.cancel
