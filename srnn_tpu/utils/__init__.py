from .trajstore import (TrajStore, read_store, read_store_artifact,
                        truncate_frames)
from .capture import evolve_captured
from .profiling import phase, timed, trace
from .debug import checked_apply_to_weights, divergence_onset
from .printing import PrintingObject

__all__ = [
    "TrajStore", "read_store", "read_store_artifact", "truncate_frames",
    "evolve_captured",
    "phase", "timed", "trace",
    "checked_apply_to_weights", "divergence_onset",
    "PrintingObject",
]
