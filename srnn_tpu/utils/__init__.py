from .trajstore import (TrajStore, read_sharded_store, read_store,
                        read_store_artifact, shard_path, truncate_frames,
                        truncate_sharded_frames)
from .capture import (evolve_captured, evolve_multi_captured,
                      open_process_shard, sharded_evolve_captured)
from .profiling import phase, timed, trace
from .debug import checked_apply_to_weights, divergence_onset
from .printing import PrintingObject
from .aot import (aot_compile, clear_executable_cache, default_cache_dir,
                  ensure_compilation_cache, warmup)

__all__ = [
    "TrajStore", "read_store", "read_store_artifact", "truncate_frames",
    "read_sharded_store", "shard_path", "truncate_sharded_frames",
    "evolve_captured", "evolve_multi_captured",
    "open_process_shard", "sharded_evolve_captured",
    "phase", "timed", "trace",
    "checked_apply_to_weights", "divergence_onset",
    "PrintingObject",
    "aot_compile", "clear_executable_cache", "default_cache_dir",
    "ensure_compilation_cache", "warmup",
]
