from .trajstore import TrajStore, read_store, read_store_artifact
from .capture import evolve_captured

__all__ = ["TrajStore", "read_store", "read_store_artifact", "evolve_captured"]
