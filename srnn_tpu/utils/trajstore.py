"""Trajectory/event store: ctypes bindings for the native writer.

The native library (``native/trajstore.cpp``) streams soup frames
(weights, uids, action codes, counterparts, losses per generation) to disk
from a background C++ thread, so host IO overlaps the next chunk of device
compute.  This replaces the reference's keep-everything-in-RAM
``ParticleDecorator.save_state`` history (``network.py:193-198``) with a
bounded-memory stream — the only workable shape at 1M particles
(SURVEY §5 / §7 hard parts).

The library is compiled on first use (``make -C native``, g++ baked into
the image).  If no toolchain is available a pure-Python writer produces the
identical file format (same header, same CRC32 per frame), so readers never
care which side wrote a file.
"""

from __future__ import annotations

import ctypes
import os
import struct
import subprocess
import zlib
from typing import Dict, Optional, Tuple

import numpy as np

_MAGIC = b"SRNNTRJ1"
_VERSION = 1
_HEADER = struct.Struct("<8sII QQ")  # magic, version, reserved, N, P


def _frame_bytes(n: int, p: int) -> int:
    """On-disk frame size: u64 generation + f32 weights[N*P] + 3x i32[N]
    (uids/action/counterpart) + f32 loss[N] + u32 crc.  Single source of
    truth for writer, reader, and resume reconciliation (mirror of
    ``payload_bytes`` in native/trajstore.cpp)."""
    return 8 + n * p * 4 + 3 * n * 4 + n * 4 + 4

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")

_lib: Optional[ctypes.CDLL] = None
_lib_tried = False


def _load_native() -> Optional[ctypes.CDLL]:
    """Build (if needed) and load libtrajstore.so; None if unavailable."""
    global _lib, _lib_tried
    if _lib_tried:
        return _lib
    _lib_tried = True
    so = os.path.join(_NATIVE_DIR, "libtrajstore.so")
    try:
        if not os.path.exists(so):
            subprocess.run(["make", "-C", _NATIVE_DIR],
                           check=True, capture_output=True)
        lib = ctypes.CDLL(so)
    except (OSError, subprocess.CalledProcessError):
        return None
    lib.ts_create.restype = ctypes.c_void_p
    lib.ts_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64]
    lib.ts_open_append.restype = ctypes.c_void_p
    lib.ts_open_append.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                   ctypes.c_uint64,
                                   ctypes.POINTER(ctypes.c_uint64)]
    lib.ts_append.restype = ctypes.c_int
    lib.ts_append.argtypes = [ctypes.c_void_p, ctypes.c_uint64] + \
        [ctypes.c_void_p] * 5
    lib.ts_flush.restype = ctypes.c_int
    lib.ts_flush.argtypes = [ctypes.c_void_p]
    lib.ts_close.restype = ctypes.c_int
    lib.ts_close.argtypes = [ctypes.c_void_p]
    lib.ts_open_read.restype = ctypes.c_void_p
    lib.ts_open_read.argtypes = [ctypes.c_char_p]
    lib.ts_meta.restype = ctypes.c_int
    lib.ts_meta.argtypes = [ctypes.c_void_p] + [ctypes.POINTER(ctypes.c_uint64)] * 3
    lib.ts_read_frames.restype = ctypes.c_int
    lib.ts_read_frames.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                   ctypes.c_uint64] + [ctypes.c_void_p] * 6
    lib.ts_close_read.restype = ctypes.c_int
    lib.ts_close_read.argtypes = [ctypes.c_void_p]
    _lib = lib
    return _lib


def native_available() -> bool:
    return _load_native() is not None


class TrajStore:
    """Appendable frame store for one soup run.

    >>> with TrajStore(path, n_particles=N, n_weights=P) as store:
    ...     store.append(gen, weights, uids, action, counterpart, loss)

    ``mode='w'`` starts a NEW store (truncates any existing file);
    ``mode='a'`` reopens an existing one for a resumed run — the header is
    validated against (N, P), a torn trailing frame from a crashed writer
    is dropped, and ``existing_frames`` reports what was already on disk.
    Previously captured frames are never lost on resume.

    Uses the native background-thread writer when available, else a
    format-identical pure-Python writer (``native=False`` forces that).
    """

    def __init__(self, path: str, n_particles: int, n_weights: int,
                 native: Optional[bool] = None, mode: str = "w"):
        if mode not in ("w", "a"):
            raise ValueError(f"mode must be 'w' or 'a', got {mode!r}")
        self.path = path
        self.n = int(n_particles)
        self.p = int(n_weights)
        self.existing_frames = 0
        lib = _load_native() if native in (None, True) else None
        if native is True and lib is None:
            raise RuntimeError("native trajstore requested but unavailable")
        self._lib = lib
        if lib is not None:
            if mode == "a":
                if os.path.exists(path) and os.path.getsize(path) < _HEADER.size:
                    os.remove(path)  # torn header: unrecoverable, start fresh
                existing = ctypes.c_uint64()
                self._h = lib.ts_open_append(path.encode(), self.n, self.p,
                                             ctypes.byref(existing))
                if not self._h:
                    raise OSError(
                        f"ts_open_append failed for {path} (header mismatch "
                        f"or IO error)")
                self.existing_frames = existing.value
            else:
                self._h = lib.ts_create(path.encode(), self.n, self.p)
                if not self._h:
                    raise OSError(f"ts_create failed for {path}")
            self._f = None
        else:
            self._h = None
            if mode == "a" and os.path.exists(path) \
                    and os.path.getsize(path) >= _HEADER.size:
                self._f = self._reopen_py(path)
            else:
                # absent file — or one whose buffered header never hit disk
                # (a crash right after creation): nothing recoverable, start
                # the store fresh rather than failing the resume
                self._f = open(path, "wb")
                self._f.write(_HEADER.pack(_MAGIC, _VERSION, 0, self.n, self.p))
        self.frames_written = 0

    def _reopen_py(self, path: str):
        """Pure-Python append reopen: validate header, truncate a torn tail,
        seek to the end of the last complete frame."""
        f = open(path, "r+b")
        try:
            head = f.read(_HEADER.size)
            if len(head) < _HEADER.size:
                raise OSError(f"{path}: truncated header")
            magic, version, _res, n, p = _HEADER.unpack(head)
            if magic != _MAGIC or version != _VERSION:
                raise OSError(f"{path}: not a trajstore file")
            if (n, p) != (self.n, self.p):
                raise OSError(
                    f"{path}: store is (N={n}, P={p}) but resume expects "
                    f"(N={self.n}, P={self.p})")
            frame_bytes = _frame_bytes(n, p)
            f.seek(0, os.SEEK_END)
            frames = (f.tell() - _HEADER.size) // frame_bytes
            valid_end = _HEADER.size + frames * frame_bytes
            f.truncate(valid_end)
            f.seek(valid_end)
            self.existing_frames = int(frames)
            return f
        except Exception:
            f.close()
            raise

    def append(self, generation: int, weights, uids, action, counterpart, loss):
        w = np.ascontiguousarray(np.asarray(weights, np.float32)
                                 .reshape(self.n, self.p))
        u = np.ascontiguousarray(np.asarray(uids, np.int32).reshape(self.n))
        a = np.ascontiguousarray(np.asarray(action, np.int32).reshape(self.n))
        c = np.ascontiguousarray(np.asarray(counterpart, np.int32).reshape(self.n))
        l = np.ascontiguousarray(np.asarray(loss, np.float32).reshape(self.n))
        if self._h is not None:
            rc = self._lib.ts_append(
                self._h, int(generation),
                w.ctypes.data_as(ctypes.c_void_p), u.ctypes.data_as(ctypes.c_void_p),
                a.ctypes.data_as(ctypes.c_void_p), c.ctypes.data_as(ctypes.c_void_p),
                l.ctypes.data_as(ctypes.c_void_p))
            if rc != 0:
                raise OSError(f"ts_append failed with {rc}")
        else:
            payload = (struct.pack("<Q", int(generation)) + w.tobytes() +
                       u.tobytes() + a.tobytes() + c.tobytes() + l.tobytes())
            self._f.write(payload + struct.pack("<I", zlib.crc32(payload)))
        self.frames_written += 1

    def flush(self):
        if self._h is not None:
            rc = self._lib.ts_flush(self._h)
            if rc != 0:
                raise OSError(f"ts_flush failed with {rc}")
        elif self._f is not None:
            self._f.flush()

    # -- pipeline hooks ---------------------------------------------------

    @property
    def closed(self) -> bool:
        return self._h is None and self._f is None

    def join(self):
        """Block until every queued frame is handed to the OS: the native
        writer drains its background C++ queue (``ts_flush`` joins the
        in-flight tail), the pure-Python writer flushes its buffer.  This
        is the flush/join hook an async pipeline's ``BackgroundWriter``
        owns (``add_close_hook``), so even an error-path shutdown leaves
        every frame that DID append durable.  No-op on a closed store —
        the hook may fire after the owning loop already closed it."""
        if not self.closed:
            self.flush()

    def close(self):
        if self._h is not None:
            rc = self._lib.ts_close(self._h)
            self._h = None
            if rc != 0:
                raise OSError(f"ts_close failed with {rc}")
        elif self._f is not None:
            self._f.close()
            self._f = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_store(path: str, start: int = 0, count: Optional[int] = None
               ) -> Dict[str, np.ndarray]:
    """Read frames [start, start+count) -> dict of arrays:
    generations (G,), weights (G, N, P), uids/action/counterpart (G, N),
    loss (G, N).  CRC failures raise; a torn trailing frame from a crashed
    writer is silently excluded (truncation recovery)."""
    lib = _load_native()
    if lib is not None:
        h = lib.ts_open_read(path.encode())
        if not h:
            raise OSError(f"cannot open {path}")
        try:
            n, p, frames = (ctypes.c_uint64(), ctypes.c_uint64(), ctypes.c_uint64())
            lib.ts_meta(h, ctypes.byref(n), ctypes.byref(p), ctypes.byref(frames))
            n, p, frames = n.value, p.value, frames.value
            count = frames - start if count is None else count
            out = {
                "generations": np.empty(count, np.uint64),
                "weights": np.empty((count, n, p), np.float32),
                "uids": np.empty((count, n), np.int32),
                "action": np.empty((count, n), np.int32),
                "counterpart": np.empty((count, n), np.int32),
                "loss": np.empty((count, n), np.float32),
            }
            rc = lib.ts_read_frames(
                h, start, count,
                *(out[k].ctypes.data_as(ctypes.c_void_p) for k in
                  ("generations", "weights", "uids", "action", "counterpart", "loss")))
            if rc != 0:
                raise OSError(f"ts_read_frames failed with {rc}"
                              + (" (CRC mismatch)" if rc == -2 else ""))
            return out
        finally:
            lib.ts_close_read(h)
    return _read_store_py(path, start, count)


def _read_store_py(path: str, start: int, count: Optional[int]
                   ) -> Dict[str, np.ndarray]:
    with open(path, "rb") as f:
        n, p = _parse_header(f, path)
        frame_bytes = _frame_bytes(n, p)
        body = frame_bytes - 4
        f.seek(0, os.SEEK_END)
        total = (f.tell() - _HEADER.size) // frame_bytes
        count = total - start if count is None else count
        if start + count > total:
            raise OSError(f"{path}: range [{start}, {start + count}) > {total}")
        out = {
            "generations": np.empty(count, np.uint64),
            "weights": np.empty((count, n, p), np.float32),
            "uids": np.empty((count, n), np.int32),
            "action": np.empty((count, n), np.int32),
            "counterpart": np.empty((count, n), np.int32),
            "loss": np.empty((count, n), np.float32),
        }
        f.seek(_HEADER.size + start * frame_bytes)
        for i in range(count):
            raw = f.read(frame_bytes)
            payload, crc = raw[:body], struct.unpack("<I", raw[body:])[0]
            if zlib.crc32(payload) != crc:
                raise OSError(f"{path}: CRC mismatch in frame {start + i}")
            off = 0
            out["generations"][i] = struct.unpack_from("<Q", payload, off)[0]
            off += 8
            out["weights"][i] = np.frombuffer(
                payload, np.float32, n * p, off).reshape(n, p)
            off += n * p * 4
            for key in ("uids", "action", "counterpart"):
                out[key][i] = np.frombuffer(payload, np.int32, n, off)
                off += n * 4
            out["loss"][i] = np.frombuffer(payload, np.float32, n, off)
    return out


def truncate_frames(path: str, keep: int) -> int:
    """Truncate a store to its first ``keep`` frames (no-op if it already
    has fewer).  Returns the frame count after truncation.

    Resume reconciliation: a run killed AFTER capture flushed frames but
    BEFORE the next checkpoint finalized would otherwise re-evolve and
    re-append those generations, duplicating frames.  The resuming caller
    truncates to the frames consistent with the restored checkpoint first.
    """
    if not os.path.exists(path) or os.path.getsize(path) < _HEADER.size:
        return 0
    with open(path, "r+b") as f:
        n, p = _parse_header(f, path)
        fb = _frame_bytes(n, p)
        f.seek(0, os.SEEK_END)
        frames = (f.tell() - _HEADER.size) // fb
        keep = min(int(keep), int(frames))
        f.truncate(_HEADER.size + keep * fb)
    return keep


def read_store_sampled(path: str, columns: np.ndarray,
                       chunk_frames: int = 4) -> Dict[str, np.ndarray]:
    """Read a store keeping only the given particle ``columns``, streaming
    ``chunk_frames`` frames at a time so peak memory is bounded by the
    WINDOW, not the store (a 1M-particle capture is ~56 MB/frame — a
    whole-store read of a long run OOMs exactly at the scale the sampling
    exists for).  Returns the full dict including ``generations``."""
    columns = np.asarray(columns)
    # one-frame peek fixes the shapes/keys without loading the store
    peek = read_sharded_store(path, 0, min(1, _total_frames(path)))
    total = _total_frames(path)
    parts = {k: [] for k in peek if k != "generations"}
    gens = []
    for start in range(0, total, chunk_frames):
        win = read_sharded_store(path, start,
                                 min(chunk_frames, total - start))
        gens.append(win.pop("generations"))
        for k, v in win.items():
            parts[k].append(v[:, columns] if v.ndim >= 2 else v)
    out = {k: np.concatenate(v, axis=0) if v else peek[k][:0]
           for k, v in parts.items()}
    out["generations"] = np.concatenate(gens) if gens else \
        peek["generations"][:0]
    return out


def _total_frames(path: str) -> int:
    """Complete merged frame count for a plain store or a shard set."""
    shards = _find_shards(path)
    if not shards:
        return store_frame_count(path)
    return min(store_frame_count(p) for _, _, p in shards)


def read_store_artifact(path: str,
                        columns: Optional[np.ndarray] = None
                        ) -> Dict[str, np.ndarray]:
    """Read a store in the soup-artifact shape ``srnn_tpu.viz`` consumes
    (weights/uids/action/counterpart/loss keys).  Accepts both a
    single-process store and the base path of a per-process shard set
    (merged via :func:`read_sharded_store`).  ``columns`` restricts to a
    particle subset via the memory-bounded streaming reader — pass it for
    mega-scale stores."""
    if columns is not None:
        out = read_store_sampled(path, columns)
    else:
        out = read_sharded_store(path)
    out.pop("generations")
    return out


# ---------------------------------------------------------------------------
# Multihost shards: one .traj per process, merged on read.
#
# At real multi-host mega-soup scale, pulling full GLOBAL frames through one
# process gathers ~56 MB x every captured frame over DCN (round-3 gap).
# Instead each process appends only its addressable particle rows to its own
# shard file; the merge reader reassembles global frames offline.  Scales
# the reference's never-lose-history registry (soup.py:37-43) to multihost.
# ---------------------------------------------------------------------------


def shard_path(base: str, process_index: int, num_processes: int) -> str:
    """Per-process shard file name.  A single-process run keeps the plain
    ``base`` path, so existing single-host artifacts/readers are unchanged."""
    if num_processes <= 1:
        return base
    return f"{base}.p{process_index:04d}of{num_processes:04d}"


def _find_shards(base: str):
    import glob as _glob
    import re

    paths = sorted(_glob.glob(base + ".p*of*"))
    shards = []
    for p in paths:
        m = re.search(r"\.p(\d+)of(\d+)$", p)
        if m:
            shards.append((int(m.group(1)), int(m.group(2)), p))
    return shards


def _parse_header(f, path: str):
    """Validate the magic/version and return (n_particles, n_weights).
    Single source for every reader/maintenance path."""
    head = f.read(_HEADER.size)
    if len(head) < _HEADER.size:
        raise OSError(f"{path}: truncated header")
    magic, version, _res, n, p = _HEADER.unpack(head)
    if magic != _MAGIC or version != _VERSION:
        raise OSError(f"{path}: not a trajstore file")
    return n, p


def store_frame_count(path: str) -> int:
    """Number of complete frames in a store, from the header + file size
    alone (no frame data read)."""
    with open(path, "rb") as f:
        n, p = _parse_header(f, path)
        f.seek(0, os.SEEK_END)
        return (f.tell() - _HEADER.size) // _frame_bytes(n, p)


def store_shape(path: str) -> "Tuple[int, int]":
    """(total particles, weights per particle) from headers alone — the
    merged particle count for a shard set, no frame data read."""
    shards = _find_shards(path)
    paths = [p for _, _, p in shards] if shards else [path]
    n_total, p_dim = 0, None
    for sp in paths:
        with open(sp, "rb") as f:
            n, p = _parse_header(f, sp)
        n_total += n
        p_dim = p
    return n_total, p_dim


def read_sharded_store(base: str, start: int = 0,
                       count: Optional[int] = None) -> Dict[str, np.ndarray]:
    """Merge per-process shards of a captured run into GLOBAL frames.

    Falls back to ``read_store(base)`` when no ``.pNNNNofMMMM`` shards
    exist (single-process store).  Shards are concatenated in process
    order along the particle axis — processes own contiguous global row
    blocks (``capture.sharded_evolve_captured``'s layout).  A run killed
    mid-capture may leave shards at different lengths; only frames present
    in EVERY shard are returned (the global frame is otherwise torn).

    Only the requested [start, start+count) window is read from each
    shard — a mega-soup global frame is ~56 MB, so reading whole shards to
    serve one frame would not scale.
    """
    shards = _find_shards(base)
    if not shards:
        return read_store(base, start, count)
    if os.path.exists(base):
        # a plain base store PLUS shards means the process count changed
        # across a resume; merging would silently drop one of the two
        # histories — refuse instead of losing frames
        raise OSError(
            f"{base}: both a single-process store and per-process shards "
            "exist; a resume must keep the original process count (or the "
            "histories must be merged explicitly)")
    num = shards[0][1]
    have = sorted(s[0] for s in shards)
    if have != list(range(num)) or any(s[1] != num for s in shards):
        raise OSError(
            f"{base}: incomplete shard set {have} (expected 0..{num - 1})")
    complete = min(store_frame_count(p) for _, _, p in shards)
    count = complete - start if count is None else count
    if count < 0 or start + count > complete:
        raise OSError(f"{base}: range [{start}, {start + count}) exceeds the "
                      f"{complete} complete merged frames")
    parts = [read_store(p, start, count) for _, _, p in shards]
    gens = parts[0]["generations"]
    for p in parts[1:]:
        if not np.array_equal(p["generations"], gens):
            raise OSError(f"{base}: shard generation sequences disagree")
    if len(gens) > 1 and not np.all(np.diff(gens) > 0):
        # shards agree but the shared timeline itself runs backwards: a
        # mis-reconciled resume (truncate_sharded_frames skipped, or applied
        # to only some shards before new appends) — e.g. [2, 4, 2, 4, 6]
        import warnings

        warnings.warn(
            f"{base}: merged generation sequence is not strictly "
            "increasing — a resume appended without truncating frames "
            "past the checkpoint; run truncate_sharded_frames before "
            "appending to repair the store", stacklevel=2)
    out = {"generations": gens}
    for key in ("weights", "uids", "action", "counterpart", "loss"):
        out[key] = np.concatenate([p[key] for p in parts], axis=1)
    return out


def truncate_sharded_frames(base: str, keep: int) -> int:
    """Resume reconciliation across shards: truncate the base store AND
    every shard to ``keep`` frames.  Returns the resulting complete-frame
    count (min across shards)."""
    shards = _find_shards(base)
    if not shards:
        return truncate_frames(base, keep)
    if os.path.exists(base):
        raise OSError(
            f"{base}: both a single-process store and per-process shards "
            "exist; a resume must keep the original process count")
    return min(truncate_frames(p, keep) for _, _, p in shards)
