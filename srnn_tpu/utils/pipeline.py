"""Async host/device pipeline: dispatch-ahead chunks, non-blocking frame
capture, and background checkpoint/telemetry I/O.

PR-1 made the soup's device compute AOT-compiled and donation-clean and
PR-2 metered it, but the mega-run chunk loop still serialized device work
against host I/O: every chunk blocked on ``jax.device_get`` frame pulls, a
synchronous orbax checkpoint, and per-row fsync'd heartbeat/telemetry
writes before the next chunk was dispatched.  This module is the missing
overlap layer — the device stays busy while a single background worker
drains the host work:

  * :func:`snapshot` — donation-safe device-side copy of a pytree whose
    device-to-host transfer is started immediately
    (``copy_to_host_async``) and resolved later, off the critical path.
    The copy is the load-bearing half: the ALL-donated chunk loops re-use
    a state's buffers in place one dispatch later, so an in-flight async
    transfer must read from a buffer jax owns and nothing ever donates.
    The copy is dispatched (async) *before* the donating dispatch, so
    device-stream order guarantees it reads the pre-donation bytes.
  * :class:`BackgroundWriter` — ONE worker thread draining a bounded FIFO
    queue of host jobs (TrajStore appends, orbax checkpoint saves,
    metrics-sink flushes, heartbeat rows).  ``submit`` blocks when the
    queue is full (**backpressure contract**: the producing loop can run
    at most ``maxsize`` host jobs ahead, which also bounds the device
    memory pinned by queued :func:`snapshot` trees).  Jobs execute in
    submission order, so cross-job invariants — frames flushed *before*
    the checkpoint that supersedes them — hold exactly as they do in the
    blocking loop, and a crash loses only a suffix of the job order
    (which bit-exact ``--resume`` already reconciles).  The first job
    error latches: later jobs are skipped (never a checkpoint racing
    ahead of failed frame appends) and the error re-raises on the next
    ``submit``/``flush``/``close``.  ``close()`` drains, joins, and runs
    registered close hooks (e.g. ``TrajStore.join``) so shutdown — clean
    or crashed — leaves no orphan thread and no buffered frame.
  * :class:`ChunkDriver` — the double-buffered dispatch-ahead scheduler:
    the mega loops dispatch chunk *k+1*'s device work, *then* run chunk
    *k*'s host finisher (``depth=1``); ``depth=0`` degrades to the
    blocking order for A/B measurement and parity tests.
  * :class:`OverlapMeter` — host-side attribution of each chunk's wall
    time into device-wait vs host-I/O seconds, exported as the
    ``pipeline_*`` gauges so a deadline-exhausted run (BENCH_r05) names
    host stall vs device compute.

Thread hygiene: :func:`spawn_thread` is the only sanctioned way to start
a thread under ``srnn_tpu`` — it registers the thread with the module's
join-on-exit registry (``live_threads`` audits it; the srnnlint
``thread-hygiene`` pass enforces the rule), and threads default
to non-daemon so interpreter exit cannot strand buffered I/O.
"""

from __future__ import annotations

import errno
import queue
import threading
import time
import weakref
from collections import deque
from contextlib import contextmanager
from typing import Any, Callable, List, Optional

import numpy as np

# ---------------------------------------------------------------------------
# thread registry: every thread this package starts is accounted for
# ---------------------------------------------------------------------------

_THREADS: "weakref.WeakSet[threading.Thread]" = weakref.WeakSet()
_THREADS_LOCK = threading.Lock()


def register_thread(thread: threading.Thread) -> threading.Thread:
    """Add ``thread`` to the join-on-exit registry.  Owners still join
    their own threads (``BackgroundWriter.close``); the registry exists so
    shutdown tests — and operators — can audit that nothing survived."""
    with _THREADS_LOCK:
        _THREADS.add(thread)
    return thread


def live_threads() -> List[threading.Thread]:
    """Registered threads that are still alive (empty after every pipeline
    owner has been ``close()``d — the no-orphan-threads invariant)."""
    with _THREADS_LOCK:
        return [t for t in _THREADS if t.is_alive()]


def spawn_thread(target: Callable, *, name: str, daemon: bool = False,
                 args: tuple = (), kwargs: Optional[dict] = None
                 ) -> threading.Thread:
    """The package's thread factory: explicit daemon-ness (non-daemon by
    default, so buffered I/O is never stranded by interpreter exit) and
    registration with the join-on-exit registry."""
    t = threading.Thread(target=target, name=name, args=args,
                         kwargs=kwargs or {}, daemon=daemon)
    register_thread(t)
    t.start()
    return t


# ---------------------------------------------------------------------------
# the bounded background writer
# ---------------------------------------------------------------------------


class WriterError(RuntimeError):
    """A background job failed; raised on the submitting thread at the
    next ``submit``/``flush``/``close`` after the failure.  The message
    names the failed job (its function name) so an operator — or the run
    supervisor's fault log — sees *which* write died, not just that one
    did."""


#: errnos retried in place by the worker before the permanent latch trips:
#: interrupted syscalls and would-block conditions are transient by
#: definition; ENOSPC gets its own *time*-bounded grace (logs rotate,
#: sibling runs finish) configured per writer.
_TRANSIENT_ERRNOS = frozenset({errno.EINTR, errno.EAGAIN})


class BackgroundWriter:
    """Single worker thread draining a bounded FIFO of host-I/O jobs.

    >>> w = BackgroundWriter(name="capture-io")
    >>> w.submit(store.append, gen, weights, ...)   # returns immediately
    >>> w.flush()                                   # barrier: queue drained
    >>> w.close()                                   # drain + join + hooks

    Contract:

    * **Order** — jobs run in submission order (one worker, FIFO queue),
      so "frames before the checkpoint that supersedes them" and every
      other cross-job invariant of the blocking loop is preserved.
    * **Backpressure** — ``submit`` blocks while ``maxsize`` jobs are
      pending; a producer can run at most one bounded window ahead.
    * **Errors** — *transient* I/O failures (``EINTR``/``EAGAIN``, and
      ``ENOSPC`` within a configurable grace window) are retried in
      place with exponential backoff; the first error that survives its
      retry budget latches: subsequent jobs are skipped (a checkpoint
      must never land after its chunk's frame appends failed) and the
      error re-raises, wrapped in :class:`WriterError` **naming the
      failed job**, on the next call into the writer.
    * **Shutdown** — ``close()`` drains the queue, joins the worker, runs
      close hooks (e.g. ``TrajStore.join``), and re-raises any latched
      error.  Idempotent; also the context-manager ``__exit__``.
    """

    def __init__(self, maxsize: int = 8, name: str = "srnn-io",
                 io_retries: int = 3, retry_backoff_s: float = 0.05,
                 enospc_grace_s: float = 5.0):
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, int(maxsize)))
        self._lock = threading.Lock()
        self._error: Optional[BaseException] = None
        self._error_job: Optional[str] = None
        self._failed = False       # latched forever once any job raised
        self._closed = False
        self._busy_s = 0.0
        self.jobs_done = 0
        self.jobs_retried = 0
        self.io_retries = max(0, int(io_retries))
        self.retry_backoff_s = max(0.0, float(retry_backoff_s))
        self.enospc_grace_s = max(0.0, float(enospc_grace_s))
        self._close_hooks: List[Callable[[], None]] = []
        self._thread = spawn_thread(self._run, name=name)

    # -- worker ----------------------------------------------------------

    def _execute(self, fn, args, kwargs) -> Optional[BaseException]:
        """Run one job with the transient-I/O retry loop; return the
        error that should latch (None on success).  EINTR/EAGAIN retry up
        to ``io_retries`` times; ENOSPC retries while the grace window is
        open (disk pressure is a fleet condition that clears on its own
        schedule, not a count of attempts).

        Caveat for append-shaped jobs: a retry re-runs the WHOLE job, so
        a partial write followed by a successful retry can leave torn
        bytes mid-stream.  Both stream formats tolerate it — ``.traj``
        frames are CRC-checked (a torn frame drops on read) and every
        jsonl reader in the repo skips unparseable lines — so the cost
        is one lost row, against the satellite win of surviving the
        EINTR/ENOSPC blips that used to kill whole mega runs."""
        attempt = 0
        t0 = time.monotonic()
        while True:
            try:
                fn(*args, **kwargs)
                return None
            except OSError as e:
                transient = e.errno in _TRANSIENT_ERRNOS \
                    and attempt < self.io_retries
                enospc = e.errno == errno.ENOSPC \
                    and (time.monotonic() - t0) < self.enospc_grace_s
                if not (transient or enospc):
                    return e
                attempt += 1
                with self._lock:
                    self.jobs_retried += 1
                time.sleep(min(self.retry_backoff_s * (2.0 ** (attempt - 1)),
                               1.0))
            except BaseException as e:
                return e

    def _run(self) -> None:
        while True:
            job = self._q.get()
            try:
                if job is None:
                    return
                fn, args, kwargs = job
                with self._lock:
                    skip = self._failed
                if skip:
                    continue
                t0 = time.perf_counter()
                try:
                    err = self._execute(fn, args, kwargs)
                    if err is not None:  # latch; surface on the producer
                        with self._lock:
                            self._error = err
                            self._error_job = getattr(fn, "__name__",
                                                      repr(fn))
                            self._failed = True
                finally:
                    dt = time.perf_counter() - t0
                    with self._lock:
                        self._busy_s += dt
                        self.jobs_done += 1
            finally:
                self._q.task_done()

    # -- producer API ----------------------------------------------------

    @property
    def busy_s(self) -> float:
        """Cumulative seconds the worker spent executing jobs (the
        host-I/O side of :class:`OverlapMeter`'s attribution)."""
        with self._lock:
            return self._busy_s

    @property
    def failed(self) -> bool:
        with self._lock:
            return self._failed

    def _job_failure_message(self, err: BaseException) -> str:
        job = self._error_job or "<unknown>"
        return (f"background writer job '{job}' failed: "
                f"{type(err).__name__}: {err}")

    def _raise_pending(self) -> None:
        with self._lock:
            err, self._error = self._error, None
        if err is not None:
            raise WriterError(self._job_failure_message(err)) from err

    def submit(self, fn: Callable, *args, **kwargs) -> None:
        """Enqueue ``fn(*args, **kwargs)``; blocks while the queue is full
        (the backpressure bound) and raises any latched job error.  A
        writer that has ever failed refuses all further jobs — they would
        be skipped anyway, and a silent no-op submit would let a producer
        loop run on believing its I/O is landing."""
        if self._closed:
            raise WriterError("submit() on a closed BackgroundWriter")
        self._raise_pending()
        if self.failed:
            raise WriterError(
                "background writer already failed; job refused")
        self._q.put((fn, args, kwargs))

    def flush(self) -> None:
        """Block until every submitted job has executed, then raise any
        latched job error."""
        self._q.join()
        self._raise_pending()

    def add_close_hook(self, fn: Callable[[], None]) -> None:
        """Run ``fn`` during ``close()`` after the queue drains — the slot
        a ``TrajStore`` hands its flush/join hook to, so even an
        error-path shutdown leaves the frames that DID append durable."""
        self._close_hooks.append(fn)

    def close(self) -> None:
        """Drain, join the worker, run close hooks; idempotent.  Raises
        the latched job (or hook) error after the thread is down."""
        with self._lock:
            already = self._closed
            self._closed = True
        if already:
            self._raise_pending()
            return
        self._q.put(None)               # after all queued jobs (FIFO)
        self._thread.join()
        hook_err: Optional[BaseException] = None
        for hook in self._close_hooks:
            try:
                hook()
            except BaseException as e:
                hook_err = hook_err or e
        # surface BOTH failure kinds in one error: a latched job error
        # must not swallow a close-hook failure (the operator needs to
        # know the store flush ALSO failed, i.e. what is actually durable)
        with self._lock:
            job_err, self._error = self._error, None
        if job_err is not None or hook_err is not None:
            parts = [self._job_failure_message(job_err)
                     ] if job_err is not None else []
            if hook_err is not None:
                parts.append(f"close hook failed: "
                             f"{type(hook_err).__name__}: {hook_err}")
            raise WriterError("; ".join(parts)) from (job_err or hook_err)

    def __enter__(self) -> "BackgroundWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def submit_or_run(writer: Optional[BackgroundWriter], fn: Callable,
                  *args, **kwargs) -> None:
    """Route one host job through ``writer`` when pipelining, else run it
    inline — the single switch the mega loops use for A/B parity."""
    if writer is None:
        fn(*args, **kwargs)
    else:
        writer.submit(fn, *args, **kwargs)


# ---------------------------------------------------------------------------
# donation-safe device snapshots with async device-to-host transfer
# ---------------------------------------------------------------------------


def _copy_leaf(x):
    import jax
    import jax.numpy as jnp

    if not hasattr(x, "dtype"):
        return x
    if jax.dtypes.issubdtype(x.dtype, jax.dtypes.prng_key):
        return jax.random.wrap_key_data(jnp.copy(jax.random.key_data(x)))
    return jnp.copy(x)


_device_copy = None  # lazily-built jitted tree copy (keeps jax import lazy)


def snapshot(tree: Any, transfer: bool = True) -> Any:
    """Device-side copy of every array leaf of ``tree``, with the
    device-to-host transfer of the copy started immediately.

    The copy runs as ONE jitted program, so (a) its outputs are fresh
    jax-owned buffers that never alias the (soon-to-be-donated) inputs —
    jit outputs only alias *donated* inputs — and (b) input shardings are
    preserved, so a sharded soup's snapshot keeps its per-device layout
    for shard-local reads.  Dispatch is async: calling this costs a
    dispatch, not a device round trip.  Resolve with :func:`resolve` (or
    shard-local reads) later, typically on the background writer.
    """
    global _device_copy
    import jax

    if _device_copy is None:
        _device_copy = jax.jit(lambda t: jax.tree.map(_copy_leaf, t))
    snap = _device_copy(tree)
    if transfer:
        for leaf in jax.tree.leaves(snap):
            start = getattr(leaf, "copy_to_host_async", None)
            if start is not None:
                try:
                    start()
                except Exception:
                    pass  # transfer overlap is an optimization, never load-bearing
    return snap


def resolve(tree: Any) -> Any:
    """Materialize a :func:`snapshot` (or any pytree of arrays) on host:
    blocks only until the already-started transfers land."""
    import jax

    return jax.tree.map(
        lambda x: np.asarray(x) if hasattr(x, "dtype") else x, tree)


# ---------------------------------------------------------------------------
# dispatch-ahead chunk scheduling
# ---------------------------------------------------------------------------


class StallError(RuntimeError):
    """A chunk finisher exceeded the :class:`ChunkDriver` stall deadline:
    the dispatch-ahead loop is wedged (a hung device resolve, a dead
    tunnel).  Carries ``bundle`` — the triage-bundle path the stall
    handler wrote, if any — so the failure names an artifact instead of
    an opaque timeout."""

    def __init__(self, message: str, bundle: Optional[str] = None):
        super().__init__(message)
        self.bundle = bundle


class ChunkDriver:
    """Run chunk *k*'s host finisher after chunk *k+1*'s device dispatch.

    The mega loops call ``step(finish)`` once per chunk, right after
    dispatching that chunk's device work; the driver holds up to ``depth``
    finishers and runs the oldest one as the ``depth+1``-th arrives —
    i.e. with the next chunk already queued on the device.  ``depth=1``
    is the double-buffered production shape; ``depth=0`` runs finishers
    immediately (the blocking order, for parity/A-B runs).  ``drain()``
    runs whatever is still pending (call it after the loop).

    **Stall deadline** (the flight recorder's liveness half):
    ``stall_timeout_s > 0`` runs each finisher on a watched daemon thread
    and raises :class:`StallError` if it does not complete in time — a
    chunk whose device results never land (wedged backend, dead tunnel)
    becomes a NAMED failure on the producing thread instead of an
    indefinite hang.  ``on_stall(elapsed_s)`` (set by the mega loops)
    runs first and may write a host-only triage bundle; its return value
    rides the error as ``StallError.bundle``.  The watched thread is
    daemon by design — it is exactly the thread presumed wedged, and a
    non-daemon spelling would hang interpreter exit on the very wedge
    this deadline exists to escape.  With ``stall_timeout_s=0`` (the
    default) finishers run inline and the hot path is unchanged.
    """

    def __init__(self, depth: int = 1, stall_timeout_s: float = 0.0,
                 on_stall: Optional[Callable[[float], Optional[str]]] = None):
        self.depth = max(0, int(depth))
        self.stall_timeout_s = float(stall_timeout_s)
        self.on_stall = on_stall
        self._pending: "deque[Callable[[], None]]" = deque()

    def _run(self, finish: Callable[[], None]) -> None:
        if self.stall_timeout_s <= 0:
            finish()
            return
        done = threading.Event()
        err: List[BaseException] = []

        def watched():
            try:
                finish()
            except BaseException as e:
                err.append(e)
            finally:
                done.set()

        spawn_thread(watched, name="srnn-chunk-finisher", daemon=True)
        if not done.wait(self.stall_timeout_s):
            bundle = None
            if self.on_stall is not None:
                try:
                    bundle = self.on_stall(self.stall_timeout_s)
                except Exception:
                    pass  # the stall itself is the failure to surface
            raise StallError(
                f"chunk finisher exceeded the {self.stall_timeout_s:.0f}s "
                "stall deadline (device results never landed)"
                + (f"; triage bundle: {bundle}" if bundle else ""),
                bundle=bundle)
        if err:
            raise err[0]

    def step(self, finish: Callable[[], None]) -> None:
        self._pending.append(finish)
        while len(self._pending) > self.depth:
            self._run(self._pending.popleft())

    def drain(self) -> None:
        while self._pending:
            self._run(self._pending.popleft())


# ---------------------------------------------------------------------------
# overlap attribution: host stall vs device compute
# ---------------------------------------------------------------------------


class OverlapMeter:
    """Per-chunk wall-time attribution for the async pipeline.

    Two accumulators per chunk, both host-observable and honest about
    what the host can know without a device profiler:

    * ``device_wait_s`` — seconds the producing thread spent *blocked on
      device results* (inside :meth:`waiting`): a lower bound on device
      busy time.
    * ``host_io_s`` — seconds of host I/O: foreground :meth:`host_io`
      blocks plus the attached :class:`BackgroundWriter`'s busy-seconds
      delta.  In the pipelined loop this work runs concurrently with
      device compute; in the blocking loop it is dead device time.  The
      writer delta necessarily folds into the GAUGES one chunk late (a
      chunk's queued jobs mostly execute after its :meth:`chunk_done`);
      :meth:`summary` adds the still-unfolded tail so run totals are
      complete once the writer has been flushed.

    ``chunk_done(wall_s)`` folds them into gauges (labeled ``stage=``):
    ``pipeline_chunk_wall_s``, ``pipeline_chunk_device_wait_s``,
    ``pipeline_chunk_host_io_s``, ``pipeline_chunk_device_idle_bound_s``
    (``wall - device_wait``: an upper bound on device idleness — in the
    blocking loop it IS the host stall; dispatch-ahead shrinks the true
    value below it) and ``pipeline_overlap_ratio``
    (``device_wait / wall``: →1.0 means host I/O fully hidden behind
    device compute) — plus ``pipeline_*_seconds_total`` counters.
    ``summary()`` returns run totals (the dict ``bench.py`` embeds in its
    per-attempt JSON).
    """

    def __init__(self, registry=None, stage: str = "",
                 writer: Optional[BackgroundWriter] = None):
        self.registry = registry
        self.stage = stage
        self.writer = writer
        self._lock = threading.Lock()
        self._wait = 0.0
        self._io = 0.0
        self._writer_mark = writer.busy_s if writer is not None else 0.0
        self.totals = {"wall_s": 0.0, "device_wait_s": 0.0,
                       "host_io_s": 0.0, "chunks": 0}

    @contextmanager
    def waiting(self):
        """Wrap a blocking device resolve (``np.asarray``, scalar
        readback): the time accrues to ``device_wait_s``."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self._wait += dt

    @contextmanager
    def host_io(self):
        """Wrap foreground host I/O (the blocking loop's sink writes)."""
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self._io += dt

    def chunk_done(self, wall_s: float) -> dict:
        """Close one chunk window: compute the attribution row, export the
        gauges, add to the run totals, reset the per-chunk accumulators."""
        with self._lock:
            wait, self._wait = self._wait, 0.0
            io, self._io = self._io, 0.0
        if self.writer is not None:
            busy = self.writer.busy_s
            io += busy - self._writer_mark
            self._writer_mark = busy
        wall = max(float(wall_s), 0.0)
        row = {
            "wall_s": wall,
            "device_wait_s": wait,
            "host_io_s": io,
            "device_idle_bound_s": max(0.0, wall - wait),
            "overlap_ratio": min(1.0, wait / wall) if wall > 0 else 0.0,
        }
        self.totals["wall_s"] += wall
        self.totals["device_wait_s"] += wait
        self.totals["host_io_s"] += io
        self.totals["chunks"] += 1
        if self.registry is not None:
            g = self.registry.gauge
            labels = {"stage": self.stage} if self.stage else {}
            g("pipeline_chunk_wall_s",
              help="last chunk wall seconds", unit="seconds").set(
                  round(wall, 4), **labels)
            g("pipeline_chunk_device_wait_s",
              help="last chunk seconds blocked on device results",
              unit="seconds").set(round(wait, 4), **labels)
            g("pipeline_chunk_host_io_s",
              help="last chunk host-I/O seconds (background + foreground)",
              unit="seconds").set(round(io, 4), **labels)
            g("pipeline_chunk_device_idle_bound_s",
              help="last chunk upper bound on device idle seconds "
                   "(wall - device wait)", unit="seconds").set(
                  round(row["device_idle_bound_s"], 4), **labels)
            g("pipeline_overlap_ratio",
              help="device-bound fraction of the last chunk "
                   "(1.0 = host I/O fully hidden)").set(
                  round(row["overlap_ratio"], 4), **labels)
            c = self.registry.counter
            c("pipeline_wall_seconds_total",
              help="chunk-loop wall seconds", unit="seconds").inc(
                  wall, **labels)
            c("pipeline_device_wait_seconds_total",
              help="seconds blocked on device results",
              unit="seconds").inc(wait, **labels)
            c("pipeline_host_io_seconds_total",
              help="host-I/O seconds", unit="seconds").inc(io, **labels)
        return row

    def summary(self) -> dict:
        """Run-total attribution (rounded, JSON-ready): wall/device-wait/
        host-I/O seconds, chunk count, overall overlap ratio and the
        device-idle upper bound.

        The writer's busy seconds fold into the per-chunk gauges one
        window LATE (a chunk's queued jobs mostly execute after its
        ``chunk_done``), so the run total here also counts the
        still-unfolded busy delta — call after ``writer.flush()`` (as the
        mega loops do) and the tail chunk's I/O is included too."""
        t = self.totals
        wall = t["wall_s"]
        io = t["host_io_s"]
        if self.writer is not None:
            # pending delta read non-destructively: _writer_mark stays,
            # so a later chunk_done still folds the same seconds into the
            # gauges and summary() stays idempotent
            io += max(0.0, self.writer.busy_s - self._writer_mark)
        return {
            "chunks": t["chunks"],
            "wall_s": round(wall, 4),
            "device_wait_s": round(t["device_wait_s"], 4),
            "host_io_s": round(io, 4),
            "device_idle_bound_s": round(max(0.0, wall - t["device_wait_s"]),
                                         4),
            "overlap_ratio": round(min(1.0, t["device_wait_s"] / wall), 4)
            if wall > 0 else 0.0,
        }
