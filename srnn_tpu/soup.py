"""The Soup: population dynamics of self-replicating particles.

Reference: ``Soup`` (``soup.py:10-108``).  Per generation, per particle:
with p=attacking_rate pick a uniform random other (possibly self) and
*attack* it (overwrite the victim's weights with self applied to them,
``soup.py:56-61``); with p=learn_from_rate imitate a random other for
``learn_from_severity`` SGD epochs (``soup.py:62-68``); run ``train``
self-training epochs (``soup.py:69-76``); respawn dead particles in place —
divergent first, then zero — with fresh uids (``soup.py:77-86``).  Rates
<= 0 disable a phase (sentinel -1 convention, ``mixed-soup.py:83``).

TPU-native redesign: the population is a struct-of-arrays ``SoupState``
pytree and one generation is a pure jitted function.  Two fidelity modes:

  * ``parallel`` (default): all particles step simultaneously from the
    start-of-phase state.  Attack conflicts (several attackers picking one
    victim) resolve **last-attacker-wins**: the highest-indexed attacker's
    result stands and earlier attackers' effects on that victim are dropped —
    a documented deviation from the reference, where colliding attacks
    compose in index order.  Collisions are rare at the paper's rates.  This
    is the mode that scales (vmap -> shard_map); the per-generation phase
    ORDER (attack -> learn_from -> train -> respawn) is preserved exactly
    because ordering changes the science (SURVEY §7 hard parts).
  * ``sequential``: a ``lax.scan`` over particles reproducing the
    reference's particle-by-particle in-place mutation (particle i+1 can be
    attacked by the already-updated particle i, ``soup.py:54-59``).  For
    validation at small N; identical phase semantics, no parallel speedup.

Event capture: each generation emits per-particle ``action`` codes and
``counterpart`` uids mirroring ``ParticleDecorator.save_state`` description
dicts, with the reference's keep-only-last-action quirk (``soup.py:55-87``)
preserved by construction (precedence respawn > train > learn_from > attack).
"""

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from .init import fresh_lanes, fresh_rows, init_population
from .nets import apply_to_weights, compute_samples
from .ops.predicates import DEFAULT_EPSILON, count_classes, is_diverged, is_zero
from .topology import Topology
from .train import DEFAULT_LR, fit_epochs_flat
from .engine import classify_batch

# action codes for the event log (reference action strings, soup.py:60-85).
# The reference persists the zero respawn as 'zweo_dead' [sic]; this label
# set fixes the typo — readers of OLD artifacts/rows that still carry the
# misspelled key are normalized in telemetry.report.
ACTION_NAMES = ("none", "init", "attacking", "learn_from", "train_self",
                "divergent_dead", "zero_dead")
(ACT_NONE, ACT_INIT, ACT_ATTACK, ACT_LEARN, ACT_TRAIN,
 ACT_DIV_DEAD, ACT_ZERO_DEAD) = range(7)


class SoupConfig(NamedTuple):
    """Static soup hyperparameters (reference ``Soup.params``, ``soup.py:17-18``)."""
    topo: Topology
    size: int
    attacking_rate: float = 0.1
    learn_from_rate: float = 0.1
    train: int = 0
    learn_from_severity: int = 1
    remove_divergent: bool = False
    remove_zero: bool = False
    epsilon: float = DEFAULT_EPSILON
    lr: float = DEFAULT_LR
    train_mode: str = "sequential"
    mode: str = "parallel"          # 'parallel' | 'sequential'
    # 'rowmajor' keeps (N, P) arrays and vmaps per particle; 'popmajor'
    # (parallel mode only) transposes the generation to (P, N) so the
    # particle axis rides the TPU lanes and the train/learn gradient
    # steps stay elementwise — ~4-16x faster phases at N=1M (see
    # ops/popmajor*.py).  Same math up to float reassociation.
    layout: str = "rowmajor"        # 'rowmajor' | 'popmajor'
    # 'perparticle' (default) draws respawn replacements exactly like
    # seeding — one keras-style init per particle (reference soup.py:77-86
    # constructs a fresh net).  'fused' draws the whole replacement
    # population as ONE U(-1,1)*(per-weight glorot limit) tensor — the
    # identical iid law for the pure-glorot variants, a different stream;
    # at N=1M the per-particle path is ~80% of an apply-only generation
    # (benchmarks/profile_soup.py), the fused path is one threefry call.
    # The recurrent variant (orthogonal kernels) always draws per-particle.
    respawn_draws: str = "perparticle"  # 'perparticle' | 'fused'
    # 'pallas' fuses the ENTIRE batch-1 SGD chain (train and learn_from
    # phases) in VMEM per lane block — one HBM round trip per phase instead
    # of one per gradient step (~140 at train=10 for weightwise; fwd+BPTT
    # scans per epoch for recurrent).  Popmajor only; covers every variant
    # (ops/pallas_{ww,rnn,kvec}_train.py) with hand-derived backwards for
    # activations whose derivative is output-expressible (linear, sigmoid,
    # tanh, relu) and particles up to 64 weights; parity-tested vs the XLA
    # path (weights bitwise on CPU interpret, float-noise on TPU).
    train_impl: str = "xla"             # 'xla' | 'pallas'
    # Attack-phase execution (popmajor only).  'full' transforms all N
    # lanes and selects (one gather + one forward over the whole
    # population).  'compact' exploits that at the paper's rates only
    # ~1-e^-rate of victims receive any attack (reference soup.py:56-61):
    # compact the attacked lanes into a fixed capacity block (mean + 8 sd
    # of the attacker Binomial), gather/transform only those, and scatter
    # back — ~1/rate less gather+forward traffic.  Unattacked lanes are
    # untouched (bitwise); attacked lanes agree with the full path up to
    # FMA contraction (<=1 ulp — the compiler may fuse the multiply-add
    # chain differently at the narrower block width).  The capacity
    # overflow branch (mean + 8 sd bound, P < 1e-14) falls back to the
    # full path via lax.cond, so semantics never depend on the bound.
    attack_impl: str = "full"           # 'full' | 'compact'
    # Same compaction for the learn_from phase (popmajor only): at
    # learn_from_rate=0.1 only ~10% of lanes run the severity-epoch
    # imitation-SGD chain, yet the full path computes it for every lane
    # and selects.  Learner count is exactly Binomial(n, rate), same
    # capacity bound and overflow fallback as the attack phase.
    learn_from_impl: str = "full"       # 'full' | 'compact'
    # Attack-phase TRANSFORM execution (popmajor only; orthogonal to
    # attack_impl, which picks WHICH lanes are transformed).  'pallas'
    # fuses the recurrent variant's serial T-step forward in VMEM
    # (ops/pallas_rnn_apply.py) — one HBM round trip per attack phase
    # instead of T; the other variants' dense lane programs are already
    # single XLA fusions, so only recurrent configs accept it.
    apply_impl: str = "xla"             # 'xla' | 'pallas'
    # Whole-generation execution (popmajor parallel only).  'fused' runs
    # attack + learn_from + self-train + respawn as ONE megakernel launch
    # per lane block on Mosaic backends (ops/pallas_generation.py):
    # weights stay resident in VMEM across phases and phase masks replace
    # the per-phase gather/compact/scatter glue (attack_impl /
    # learn_from_impl compaction is subsumed and ignored).  On non-Mosaic
    # backends 'fused' runs the full-width masked phase chain — the SAME
    # program as the default path, so f32 results are bit-identical to
    # 'phases' there (the CPU parity oracle); on TPU the kernel agrees to
    # float tolerance like every fused Pallas chain.
    generation_impl: str = "phases"     # 'phases' | 'fused'
    # Population storage dtype.  'bf16' halves the population's HBM (and
    # the sharded START-of-generation all-gather bytes; the post-attack
    # imitation re-gather stays f32 — mid-generation values must not take
    # an extra rounding); every phase still computes in f32 —
    # weights upcast at generation entry and round back to bf16 exactly
    # once at generation exit (the kernel rounds at the same points).
    # 'int8' quarters it: weights store as int8 codes with a per-particle
    # f32 scale (``SoupState.scales``; amax/127 symmetric, divergence
    # encoded as scale=inf — see DESIGN.md §23), dequantized to f32 at
    # generation entry and re-quantized at exactly ONE point per
    # generation (the same exit point in the fused and phase-chain
    # spellings, so fused==phases stays bitwise at int8 like bf16).
    # Integer state (uids, pids, counters) and the PRNG draw stream are
    # untouched; weight trajectories drift from f32 within the tolerance
    # documented in PARITY.md (benchmarks/parity_sweep.py measures it).
    population_dtype: str = "f32"       # 'f32' | 'bf16' | 'int8'


class SoupState(NamedTuple):
    """Population as struct-of-arrays; the whole soup is one pytree.

    ``scales`` is the int8 mode's per-particle dequantization scale
    vector ((N,) f32; ``weights`` then holds int8 codes).  It stays
    ``None`` — an EMPTY pytree subtree, not a leaf — for f32/bf16
    populations, so their state trees keep exactly the pre-int8 leaves
    (checkpoints, donation, tenant stacking and shard specs all see the
    unchanged pytree)."""
    weights: jnp.ndarray   # (N, P)
    uids: jnp.ndarray      # (N,) int32 — stable particle identity across respawns
    next_uid: jnp.ndarray  # () int32
    time: jnp.ndarray      # () int32 generation counter
    key: jax.Array         # PRNG state for this soup
    scales: Optional[jnp.ndarray] = None  # (N,) f32 int8 scales | None


class SoupEvents(NamedTuple):
    """Per-generation event record (one row per particle)."""
    action: jnp.ndarray       # (N,) int32 action code (last action of the step)
    counterpart: jnp.ndarray  # (N,) int32 counterpart uid or -1
    loss: jnp.ndarray         # (N,) f32 last train loss or 0


def _pop_dtype(config) -> jnp.dtype:
    """Storage dtype of the population (``population_dtype`` field)."""
    if config.population_dtype == "bf16":
        return jnp.bfloat16
    if config.population_dtype == "int8":
        return jnp.int8
    if config.population_dtype != "f32":
        raise ValueError(
            f"unknown population_dtype {config.population_dtype!r}; "
            "expected 'f32', 'bf16' or 'int8'")
    return jnp.float32


def _upcast(config, w: jnp.ndarray, scales: Optional[jnp.ndarray] = None,
            paxis: int = 0) -> jnp.ndarray:
    """Storage -> f32 compute view (no-op for f32 populations).

    bf16 upcasts exactly; int8 dequantizes ``codes * scale`` with the
    per-particle ``scales`` broadcast along the particle axis ``paxis``
    (0 for row-major (N, P) weights, -1 for the popmajor (P, N)
    transpose).  A diverged particle's scale is +inf and its codes are
    all 127, so the dequantized row is +inf and ``is_diverged`` keeps
    firing (the exact inf/nan pattern is not representable — PARITY.md
    documents the collapse)."""
    if config.population_dtype == "bf16":
        return w.astype(jnp.float32)
    if config.population_dtype == "int8":
        shape = [1] * w.ndim
        shape[paxis] = -1
        return w.astype(jnp.float32) * scales.reshape(shape)
    return w


def _downcast(config, w: jnp.ndarray, paxis: int = 0
              ) -> Tuple[jnp.ndarray, Optional[jnp.ndarray]]:
    """f32 compute result -> ``(storage, scales|None)``; the reduced-
    precision paths' single per-generation rounding point.

    int8 quantizes symmetrically per particle: ``scale = amax/127``,
    ``codes = clip(round(w/scale), -127, 127)`` (worst-case abs error
    scale/2 = amax/254 per weight per generation).  All-zero particles
    keep ``scale = 1`` so they dequantize to exact zeros; a particle
    with any non-finite weight encodes as ``scale = +inf, codes = 127``
    so divergence survives the storage round-trip."""
    if config.population_dtype == "bf16":
        return w.astype(jnp.bfloat16), None
    if config.population_dtype != "int8":
        return w, None
    axes = tuple(a for a in range(w.ndim) if a != paxis % w.ndim)
    amax = jnp.max(jnp.abs(w), axis=axes)
    div = ~jnp.isfinite(amax)
    safe = jnp.where(
        (amax > 0) & ~div,
        jnp.maximum(amax / 127.0, jnp.finfo(jnp.float32).tiny), 1.0)
    shape = [1] * w.ndim
    shape[paxis] = -1
    q = jnp.clip(jnp.round(w / safe.reshape(shape)), -127.0, 127.0)
    q = jnp.where(div.reshape(shape), 127.0, q).astype(jnp.int8)
    scales = jnp.where(div, jnp.inf, safe).astype(jnp.float32)
    return q, scales


def _stored_view(config, w: jnp.ndarray, scales: Optional[jnp.ndarray],
                 paxis: int = 0) -> jnp.ndarray:
    """Consumer view of STORED weights (health folds, trajectory records,
    classification): int8 codes are meaningless without their scales, so
    the int8 mode hands consumers the dequantized f32 view; f32/bf16
    consumers read storage directly, exactly as before this mode."""
    if config.population_dtype == "int8":
        return _upcast(config, w, scales, paxis)
    return w


def seed(config: SoupConfig, key: jax.Array) -> SoupState:
    """Create the initial population (``Soup.seed``, ``soup.py:45-49``)."""
    k_init, k_state = jax.random.split(key)
    w = init_population(config.topo, k_init, config.size)
    if config.population_dtype == "int8":
        w, scales = _downcast(config, w)
    else:
        w = w.astype(_pop_dtype(config))
        scales = None
    return SoupState(
        weights=w,
        uids=jnp.arange(config.size, dtype=jnp.int32),
        next_uid=jnp.int32(config.size),
        time=jnp.int32(0),
        key=k_state,
        scales=scales,
    )


def _learn_epochs(config: SoupConfig, w: jnp.ndarray, other_w: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``learn_from_severity`` imitation epochs toward other's samples
    (fixed across the call, as the reference recomputes per ``learn_from``
    call, ``network.py:620-626``).  Flattened epoch*sample scan so the
    soup's generations scan (and shard_map) stays compile-bounded."""
    x, y = compute_samples(config.topo, other_w)
    return fit_epochs_flat(config.topo, w, config.learn_from_severity,
                           config.lr, config.train_mode, xy=(x, y))


def _train_epochs(config: SoupConfig, w: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``train`` self-training epochs; samples are recomputed from the
    current weights before every epoch (``soup.py:69-76`` calls ``train()``
    repeatedly, and each call recomputes samples)."""
    return fit_epochs_flat(config.topo, w, config.train, config.lr,
                           config.train_mode)


def _respawn(config: SoupConfig, w, uids, uid_base, key):
    """Replace dead particles in place with fresh nets and fresh uids
    (``soup.py:77-86``). Divergent check precedes zero check; both act on the
    particle's end-of-step weights.

    ``uid_base`` is the first uid available to THIS block of particles —
    the global counter on one device, a per-device block base under
    sharding.  Returns the local death count so the caller can advance the
    global counter.
    """
    action = jnp.full(w.shape[0], ACT_NONE, jnp.int32)
    dead_div = is_diverged(w) if config.remove_divergent else jnp.zeros(w.shape[0], bool)
    dead_zero = (is_zero(w, config.epsilon) & ~dead_div) if config.remove_zero else jnp.zeros(w.shape[0], bool)
    dead = dead_div | dead_zero
    fresh = fresh_rows(config.topo, key, w.shape[0], config.respawn_draws)
    new_w = jnp.where(dead[:, None], fresh, w)
    # fresh uids: rank among the dead, offset by the block base
    rank = jnp.cumsum(dead) - 1
    new_uids = jnp.where(dead, uid_base + rank.astype(jnp.int32), uids)
    deaths = dead.sum(dtype=jnp.int32)
    action = jnp.where(dead_div, ACT_DIV_DEAD, action)
    action = jnp.where(dead_zero, ACT_ZERO_DEAD, action)
    # counterpart of a death event is the replacement's uid (soup.py:81,86)
    counterpart = jnp.where(dead, new_uids, -1)
    return new_w, new_uids, deaths, action, counterpart


def _event_record(n, attack_gate, attack_cp, learn_gate, learn_cp, train_on,
                  death_action, death_cp):
    """Last-action-wins event tail shared by the local and sharded paths
    (reference description-dict overwrite quirk, ``soup.py:55-87``)."""
    action = jnp.full(n, ACT_NONE, jnp.int32)
    counterpart = jnp.full(n, -1, jnp.int32)
    action = jnp.where(attack_gate, ACT_ATTACK, action)
    counterpart = jnp.where(attack_gate, attack_cp, counterpart)
    action = jnp.where(learn_gate, ACT_LEARN, action)
    counterpart = jnp.where(learn_gate, learn_cp, counterpart)
    if train_on:
        action = jnp.full(n, ACT_TRAIN, jnp.int32)
        counterpart = jnp.full(n, -1, jnp.int32)
    action = jnp.where(death_action != ACT_NONE, death_action, action)
    counterpart = jnp.where(death_action != ACT_NONE, death_cp, counterpart)
    return action, counterpart


def _evolve_parallel(config: SoupConfig, state: SoupState,
                     lin=None, win=None, lincfg=None):
    """One parallel row-major generation; with a lineage carry
    (``lin``/``win``/``lincfg`` = per-gen caps + window capacity, see
    ``telemetry.dynamics``) additionally returns the advanced carries."""
    n = config.size
    topo = config.topo
    key, k_ag, k_at, k_lg, k_lt, k_re = jax.random.split(state.key, 6)
    w = _upcast(config, state.weights, state.scales)
    has_attacker = jnp.zeros(n, bool)
    att_idx = jnp.full(n, -1, jnp.int32)

    # --- attack phase (soup.py:56-61) ---------------------------------
    with jax.named_scope("soup.attack"):
        if config.attacking_rate > 0:
            attack_gate = (jax.random.uniform(k_ag, (n,)) < config.attacking_rate)
            attack_tgt = jax.random.randint(k_at, (n,), 0, n)
            # victim-side resolution: the highest-indexed attacker targeting v
            # wins outright.  NOTE this is a documented deviation from the
            # reference for multi-attacker collisions: there, attacks compose in
            # index order (victim 7 hit by 2 then 5 ends as f_w5(f_w2(w7)),
            # soup.py:56-61); here earlier attackers' effects are dropped
            # (f_w5(w7_start)).  Collisions are rare at the paper's rates
            # (Binomial(N, rate/N)); use mode='sequential' for exact composition.
            att_idx = jax.ops.segment_max(
                jnp.where(attack_gate, jnp.arange(n), -1), attack_tgt, num_segments=n)
            has_attacker = att_idx >= 0  # un-targeted victims get the int identity (min) or -1
            attacker_w = w[jnp.clip(att_idx, 0)]
            attacked = jax.vmap(lambda s, t: apply_to_weights(topo, s, t))(attacker_w, w)
            w = jnp.where(has_attacker[:, None], attacked, w)
        else:
            attack_gate = jnp.zeros(n, bool)
            attack_tgt = jnp.zeros(n, jnp.int32)

    # --- learn_from phase (soup.py:62-68) ------------------------------
    with jax.named_scope("soup.learn_from"):
        if config.learn_from_rate > 0:
            # the gate (and its event-log entry) fires independently of severity,
            # like the reference, where severity=0 still logs 'learn_from'
            learn_gate = (jax.random.uniform(k_lg, (n,)) < config.learn_from_rate)
            learn_tgt = jax.random.randint(k_lt, (n,), 0, n)
            if config.learn_from_severity > 0:
                learned, _ = jax.vmap(lambda wi, ow: _learn_epochs(config, wi, ow))(w, w[learn_tgt])
                w = jnp.where(learn_gate[:, None], learned, w)
        else:
            learn_gate = jnp.zeros(n, bool)
            learn_tgt = jnp.zeros(n, jnp.int32)

    # --- train phase (soup.py:69-76) -----------------------------------
    with jax.named_scope("soup.train"):
        if config.train > 0:
            w, train_loss = jax.vmap(lambda wi: _train_epochs(config, wi))(w)
        else:
            train_loss = jnp.zeros(n, w.dtype)

    # --- respawn (soup.py:77-86) ---------------------------------------
    with jax.named_scope("soup.respawn"):
        w, uids, deaths, death_action, death_cp = _respawn(
            config, w, state.uids, state.next_uid, k_re)
        next_uid = state.next_uid + deaths

    # --- event record: last action wins (soup.py:55-87 quirk);
    # the reference logs 'attacking' on the ATTACKER; victims log nothing
    action, counterpart = _event_record(
        n, attack_gate, state.uids[attack_tgt], learn_gate, state.uids[learn_tgt],
        config.train > 0, death_action, death_cp)

    w, scales = _downcast(config, w)
    new_state = SoupState(w, uids, next_uid, state.time + 1, key, scales)
    events = SoupEvents(action, counterpart, train_loss)
    if lin is None:
        return new_state, events
    from .telemetry.dynamics import lookup_pids, record_step

    caps, capacity = lincfg
    lin, win = record_step(
        lin, win, gen=state.time, attacked=has_attacker,
        attacker_pid=lookup_pids(lin.pid, jnp.clip(att_idx, 0)),
        learn_gate=learn_gate, learn_tgt=learn_tgt,
        dead=death_action != ACT_NONE, caps=caps, capacity=capacity)
    return new_state, events, lin, win


def _attack_capacity(n: int, rate: float) -> int:
    """Static lane capacity for the compacted attack block: mean + 8 sd of
    the attacker count Binomial(n, rate) (an upper bound on distinct
    victims), rounded up to a 128-lane multiple.  P(overflow) < 1e-14."""
    import math

    rate = min(max(rate, 0.0), 1.0)
    mean = n * rate
    sd = math.sqrt(n * rate * (1.0 - rate))
    cap = int(math.ceil(mean + 8.0 * sd)) + 16
    return min(n, ((cap + 127) // 128) * 128)


def _compact_gated_lanes(wT: jnp.ndarray, gate: jnp.ndarray, cap: int,
                         block_fn) -> jnp.ndarray:
    """Shared core of the sparse-phase compactions: run ``block_fn`` on the
    gated lanes only and scatter the results back.

    ``block_fn(cols)`` must return the transformed columns ``wT[:, cols]``
    — per-lane math only, so computing it on a gathered subset is
    value-preserving up to FMA contraction (the compiler may fuse a
    multiply-add chain differently at the narrower width — observed <=1
    ulp on XLA:CPU); ungated lanes are bitwise untouched.  ``cap`` lanes
    are processed; overflow (more gated lanes than ``cap``) falls back to
    the full-width computation via ``lax.cond``, so semantics never
    depend on the capacity bound.
    """
    n = wT.shape[1]

    def compact(_):
        lanes = jnp.nonzero(gate, size=cap, fill_value=n)[0]
        safe = jnp.where(lanes < n, lanes, 0)  # gather-safe clone slot
        # scatter through the UNclipped indices: the fill slots are out of
        # bounds and mode='drop' discards them — a clipped fill index would
        # race a stale write against lane 0's real update
        return wT.at[:, lanes].set(block_fn(safe), mode="drop")

    def full(_):
        return jnp.where(gate[None, :], block_fn(jnp.arange(n)), wT)

    if cap >= n:
        return full(None)
    return jax.lax.cond(gate.sum(dtype=jnp.int32) > cap, full, compact, None)


def _attack_popmajor_compact(topo: Topology, wT: jnp.ndarray,
                             att_idx: jnp.ndarray, has_attacker: jnp.ndarray,
                             cap: int, source: Optional[jnp.ndarray] = None
                             ) -> jnp.ndarray:
    """Attack phase over compacted attacked-victim lanes only
    (:func:`_compact_gated_lanes` with the self-application transform).

    ``source`` is the matrix attacker columns are drawn from — ``wT``
    itself on one device; the all-gathered global population under
    sharding, where ``att_idx`` holds GLOBAL indices and victims are
    local lanes of ``wT``.
    """
    from .ops.popmajor import apply_popmajor

    src = wT if source is None else source

    def block(cols):
        return apply_popmajor(topo, src[:, jnp.clip(att_idx, 0)[cols]],
                              wT[:, cols])

    return _compact_gated_lanes(wT, has_attacker, cap, block)


def _learn_popmajor_compact(config: SoupConfig, wT: jnp.ndarray,
                            learn_gate: jnp.ndarray, learn_tgt: jnp.ndarray,
                            cap: int, source: Optional[jnp.ndarray] = None
                            ) -> jnp.ndarray:
    """learn_from phase over compacted learner lanes only (the imitation
    SGD chain, reference ``network.py:620-626``, runs on ~rate x N lanes
    instead of all N).  Same value guarantees and overflow fallback as
    ``_attack_popmajor_compact``; ``source`` is where counterpart columns
    come from (the all-gathered post-attack population under sharding,
    with ``learn_tgt`` holding global indices)."""
    from .ops.popmajor import learn_epochs_popmajor

    topo = config.topo
    src = wT if source is None else source

    def block(cols):
        learned, _ = learn_epochs_popmajor(
            topo, wT[:, cols], src[:, learn_tgt[cols]],
            config.learn_from_severity, config.lr, config.train_mode,
            config.train_impl)
        return learned

    return _compact_gated_lanes(wT, learn_gate, cap, block)


def _fused_kernel_route(config: SoupConfig) -> bool:
    """Does ``generation_impl='fused'`` take the Pallas megakernel on this
    backend?  (Delegates to the single routing predicate in
    ``ops.pallas_generation``; the multisoup's per-type dispatch uses the
    same one, so the two can never desynchronize.)"""
    from .ops.pallas_generation import fused_kernel_route

    return fused_kernel_route(config.topo, config.train_mode)


def _phases_view(config: SoupConfig) -> SoupConfig:
    """The phase-chain spelling a fused config falls back to: full-width
    masked phases (compaction and the per-phase pallas legs are subsumed
    by the megakernel, so they are coerced off rather than layered)."""
    return config._replace(generation_impl="phases", attack_impl="full",
                           learn_from_impl="full", apply_impl="xla")


def _evolve_parallel_popmajor(config: SoupConfig, state: SoupState,
                             wT: jnp.ndarray, lin=None, win=None,
                             lincfg=None):
    """Population-major twin of ``_evolve_parallel`` (all variants — the
    per-variant lane kernels live in ``ops/popmajor.py`` /
    ``ops/popmajor_kvec.py`` / ``ops/popmajor_rnn.py``).

    ``wT`` is the (P, N) transposed population (``state.weights`` is
    ignored and carried only for uid/time/key metadata); returns the new
    transposed weights alongside the state so ``evolve`` can keep the
    carry transposed across generations (one transpose per run, not per
    step).  Phase order and event semantics identical to the row-major
    path; arithmetic differs only by reassociation.

    ``generation_impl='fused'`` routes to the single-launch megakernel on
    Mosaic backends (``_evolve_fused_popmajor``) and to this body with
    compaction coerced off everywhere else.
    """
    from .ops.popmajor import (apply_popmajor, learn_epochs_popmajor,
                               train_epochs_popmajor)

    if config.generation_impl == "fused":
        if _fused_kernel_route(config):
            return _evolve_fused_popmajor(config, state, wT, lin, win,
                                          lincfg)
        config = _phases_view(config)

    n = config.size
    topo = config.topo
    key, k_ag, k_at, k_lg, k_lt, k_re = jax.random.split(state.key, 6)
    wT = _upcast(config, wT, state.scales, paxis=-1)
    has_attacker = jnp.zeros(n, bool)
    att_idx = jnp.full(n, -1, jnp.int32)

    # --- attack (soup.py:56-61); same last-attacker-wins resolution -----
    with jax.named_scope("soup.attack"):
        if config.attacking_rate > 0:
            attack_gate = (jax.random.uniform(k_ag, (n,)) < config.attacking_rate)
            attack_tgt = jax.random.randint(k_at, (n,), 0, n)
            att_idx = jax.ops.segment_max(
                jnp.where(attack_gate, jnp.arange(n), -1), attack_tgt, num_segments=n)
            has_attacker = att_idx >= 0
            if config.attack_impl == "compact":
                wT = _attack_popmajor_compact(
                    topo, wT, att_idx, has_attacker,
                    _attack_capacity(n, config.attacking_rate))
            else:
                attacked = apply_popmajor(topo, wT[:, jnp.clip(att_idx, 0)], wT,
                                          impl=config.apply_impl)
                wT = jnp.where(has_attacker[None, :], attacked, wT)
        else:
            attack_gate = jnp.zeros(n, bool)
            attack_tgt = jnp.zeros(n, jnp.int32)

    # --- learn_from (soup.py:62-68) -------------------------------------
    with jax.named_scope("soup.learn_from"):
        if config.learn_from_rate > 0:
            learn_gate = (jax.random.uniform(k_lg, (n,)) < config.learn_from_rate)
            learn_tgt = jax.random.randint(k_lt, (n,), 0, n)
            if config.learn_from_severity > 0:
                if config.learn_from_impl == "compact":
                    wT = _learn_popmajor_compact(
                        config, wT, learn_gate, learn_tgt,
                        _attack_capacity(n, config.learn_from_rate))
                else:
                    learned, _ = learn_epochs_popmajor(
                        topo, wT, wT[:, learn_tgt], config.learn_from_severity,
                        config.lr, config.train_mode, config.train_impl)
                    wT = jnp.where(learn_gate[None, :], learned, wT)
        else:
            learn_gate = jnp.zeros(n, bool)
            learn_tgt = jnp.zeros(n, jnp.int32)

    # --- train (soup.py:69-76) ------------------------------------------
    with jax.named_scope("soup.train"):
        if config.train > 0:
            wT, train_loss = train_epochs_popmajor(
                topo, wT, config.train, config.lr, config.train_mode,
                config.train_impl)
        else:
            train_loss = jnp.zeros(n, wT.dtype)

    # --- respawn (soup.py:77-86); per-lane masks ------------------------
    with jax.named_scope("soup.respawn"):
        action = jnp.full(n, ACT_NONE, jnp.int32)
        dead_div = is_diverged(wT, axis=0) if config.remove_divergent \
            else jnp.zeros(n, bool)
        dead_zero = (is_zero(wT, config.epsilon, axis=0) & ~dead_div) \
            if config.remove_zero else jnp.zeros(n, bool)
        dead = dead_div | dead_zero
        fresh = fresh_lanes(topo, k_re, n, config.respawn_draws)
        wT = jnp.where(dead[None, :], fresh, wT)
        rank = jnp.cumsum(dead) - 1
        uids = jnp.where(dead, state.next_uid + rank.astype(jnp.int32), state.uids)
        deaths = dead.sum(dtype=jnp.int32)
        action = jnp.where(dead_div, ACT_DIV_DEAD, action)
        action = jnp.where(dead_zero, ACT_ZERO_DEAD, action)
        death_cp = jnp.where(dead, uids, -1)
    wT, scales = _downcast(config, wT, paxis=-1)

    act, cp = _event_record(
        n, attack_gate, state.uids[attack_tgt], learn_gate, state.uids[learn_tgt],
        config.train > 0, action, death_cp)
    new_state = SoupState(state.weights, uids, state.next_uid + deaths,
                          state.time + 1, key, scales)
    events = SoupEvents(act, cp, train_loss)
    if lin is None:
        return new_state, events, wT
    from .telemetry.dynamics import lookup_pids, record_step

    caps, capacity = lincfg
    lin, win = record_step(
        lin, win, gen=state.time, attacked=has_attacker,
        attacker_pid=lookup_pids(lin.pid, jnp.clip(att_idx, 0)),
        learn_gate=learn_gate, learn_tgt=learn_tgt, dead=dead, caps=caps,
        capacity=capacity)
    return new_state, events, wT, lin, win


def _evolve_fused_popmajor(config: SoupConfig, state: SoupState,
                           wT: jnp.ndarray, lin=None, win=None, lincfg=None):
    """One generation as a single megakernel launch per lane block
    (``ops.pallas_generation``): same PRNG stream, phase order, event
    record and lineage bookkeeping as the phase chain; the attack /
    learn_from / train / respawn math runs on VMEM-resident rows with
    phase masks instead of per-phase gather/compact/scatter glue.

    Counterpart operands are gathered from the START-of-generation
    population; the kernel re-applies the attack to imitation targets
    in-block so learners see post-attack weights like the phase chain.
    The respawn draw happens in XLA (one threefry call) and rides in as
    the fresh block.  Mosaic backends only (see ``_fused_kernel_route``).

    int8 populations dequantize HERE, before the counterpart gathers, and
    re-quantize at the single exit point below — the kernel sees f32 rows
    either way, so the fused spelling hits the phase chain's exact
    quantize points by construction (the documented tradeoff: unlike
    bf16, int8 rows do not ride the kernel's VMEM blocks at storage
    width).  bf16 keeps the in-kernel cast protocol (loads upcast, the
    store rounds), whose points coincide with the phase chain's.
    """
    from .init import fresh_lanes as _fresh_lanes
    from .ops.pallas_generation import generation_popmajor

    n = config.size
    topo = config.topo
    key, k_ag, k_at, k_lg, k_lt, k_re = jax.random.split(state.key, 6)
    if config.population_dtype == "int8":
        wT = _upcast(config, wT, state.scales, paxis=-1)
    has_attacker = jnp.zeros(n, bool)
    att_idx = jnp.full(n, -1, jnp.int32)

    attacking = config.attacking_rate > 0
    learning = config.learn_from_rate > 0
    sgd_learn = learning and config.learn_from_severity > 0

    if attacking:
        attack_gate = (jax.random.uniform(k_ag, (n,)) < config.attacking_rate)
        attack_tgt = jax.random.randint(k_at, (n,), 0, n)
        att_idx = jax.ops.segment_max(
            jnp.where(attack_gate, jnp.arange(n), -1), attack_tgt,
            num_segments=n)
        has_attacker = att_idx >= 0
    else:
        attack_gate = jnp.zeros(n, bool)
        attack_tgt = jnp.zeros(n, jnp.int32)
    if learning:
        learn_gate = (jax.random.uniform(k_lg, (n,)) < config.learn_from_rate)
        learn_tgt = jax.random.randint(k_lt, (n,), 0, n)
    else:
        learn_gate = jnp.zeros(n, bool)
        learn_tgt = jnp.zeros(n, jnp.int32)

    attackerT = wT[:, jnp.clip(att_idx, 0)] if attacking else None
    otherT = other_attackerT = other_attacked = None
    if sgd_learn:
        otherT = wT[:, learn_tgt]
        if attacking:
            other_att = att_idx[learn_tgt]
            other_attackerT = wT[:, jnp.clip(other_att, 0)]
            other_attacked = other_att >= 0
    fresh = _fresh_lanes(topo, k_re, n, config.respawn_draws)

    with jax.named_scope("soup.fused_generation"):
        wT, train_loss, dead_div, dead_zero = generation_popmajor(
            topo, wT, fresh, attackerT, has_attacker if attacking else None,
            otherT, other_attackerT, other_attacked,
            learn_gate if sgd_learn else None,
            severity=config.learn_from_severity if sgd_learn else 0,
            train=config.train, lr=config.lr,
            remove_divergent=config.remove_divergent,
            remove_zero=config.remove_zero, epsilon=config.epsilon)

    scales = state.scales
    if config.population_dtype == "int8":
        wT, scales = _downcast(config, wT, paxis=-1)

    dead = dead_div | dead_zero
    action = jnp.full(n, ACT_NONE, jnp.int32)
    rank = jnp.cumsum(dead) - 1
    uids = jnp.where(dead, state.next_uid + rank.astype(jnp.int32),
                     state.uids)
    deaths = dead.sum(dtype=jnp.int32)
    action = jnp.where(dead_div, ACT_DIV_DEAD, action)
    action = jnp.where(dead_zero, ACT_ZERO_DEAD, action)
    death_cp = jnp.where(dead, uids, -1)

    act, cp = _event_record(
        n, attack_gate, state.uids[attack_tgt], learn_gate, state.uids[learn_tgt],
        config.train > 0, action, death_cp)
    new_state = SoupState(state.weights, uids, state.next_uid + deaths,
                          state.time + 1, key, scales)
    events = SoupEvents(act, cp, train_loss)
    if lin is None:
        return new_state, events, wT
    from .telemetry.dynamics import lookup_pids, record_step

    caps, capacity = lincfg
    lin, win = record_step(
        lin, win, gen=state.time, attacked=has_attacker,
        attacker_pid=lookup_pids(lin.pid, jnp.clip(att_idx, 0)),
        learn_gate=learn_gate, learn_tgt=learn_tgt, dead=dead, caps=caps,
        capacity=capacity)
    return new_state, events, wT, lin, win


def _check_popmajor(config: SoupConfig) -> None:
    if config.mode != "parallel":
        raise ValueError(
            "layout='popmajor' requires mode='parallel' (got "
            f"mode={config.mode!r}); the sequential-parity scan mutates one "
            "particle at a time and cannot ride the lane layout")
    if config.topo.shuffler == "random":
        raise ValueError(
            "layout='popmajor' requires shuffler='not': a per-particle "
            "random permutation of the weight axis is a per-lane gather "
            "that defeats the lane layout — use layout='rowmajor'")
    if config.train_impl not in ("xla", "pallas"):
        raise ValueError(f"unknown train_impl {config.train_impl!r}")
    if config.generation_impl not in ("phases", "fused"):
        raise ValueError(
            f"unknown generation_impl {config.generation_impl!r}")
    if config.generation_impl == "fused":
        from .ops.pallas_generation import fused_kernel_supported

        if config.train_impl == "pallas" or config.apply_impl == "pallas":
            raise ValueError(
                "generation_impl='fused' already fuses the SGD chains and "
                "the apply transform in one launch; use train_impl='xla' "
                "and apply_impl='xla' (the per-phase pallas legs are "
                "subsumed)")
        if not fused_kernel_supported(config.topo, config.train_mode):
            raise ValueError(
                "generation_impl='fused' fuses the whole generation with "
                "the hand-derived chains: activation with an "
                "output-expressible derivative (linear/sigmoid/tanh/relu), "
                "particles up to 64 weights, shuffler='not' (the "
                "weightwise variant additionally needs "
                "train_mode='sequential'); this config "
                f"(variant={config.topo.variant!r}, "
                f"activation={config.topo.activation!r}, "
                f"train_mode={config.train_mode!r}, "
                f"P={config.topo.num_weights}) needs "
                "generation_impl='phases'")
    if config.attack_impl not in ("full", "compact"):
        raise ValueError(f"unknown attack_impl {config.attack_impl!r}")
    if config.learn_from_impl not in ("full", "compact"):
        raise ValueError(
            f"unknown learn_from_impl {config.learn_from_impl!r}")
    if config.apply_impl not in ("xla", "pallas"):
        raise ValueError(f"unknown apply_impl {config.apply_impl!r}")
    if config.apply_impl == "pallas":
        from .ops.popmajor import _use_pallas_apply

        if not _use_pallas_apply(config.topo, "pallas"):
            raise ValueError(
                "apply_impl='pallas' fuses the RECURRENT variant's serial "
                "forward (activation with an output-expressible "
                "derivative, particles up to 64 weights); this config "
                f"(variant={config.topo.variant!r}, "
                f"activation={config.topo.activation!r}, "
                f"P={config.topo.num_weights}) needs apply_impl='xla'")
        if config.attack_impl == "compact":
            raise ValueError(
                "apply_impl='pallas' and attack_impl='compact' are "
                "mutually exclusive (the compact path's narrow block "
                "defeats the kernel's lane blocking; compact is a "
                "measured TPU loss anyway — use attack_impl='full')")
    if config.train_impl == "pallas":
        from .ops.activations import output_grad_activations

        if (config.topo.activation not in output_grad_activations()
                or config.topo.num_weights > 64
                or (config.topo.variant == "weightwise"
                    and config.train_mode != "sequential")):
            raise ValueError(
                "train_impl='pallas' fuses the batch-1 SGD chain with a "
                "hand-derived backward: any variant, activation in "
                f"{sorted(output_grad_activations())}, particles up to 64 "
                "weights (the weightwise kernel additionally needs "
                "train_mode='sequential' — its chain IS the per-sample "
                "order); this config "
                f"(variant={config.topo.variant!r}, "
                f"train_mode={config.train_mode!r}, "
                f"activation={config.topo.activation!r}, "
                f"P={config.topo.num_weights}) needs train_impl='xla'")


def check_tenant_stackable(config: SoupConfig) -> None:
    """Validate that ``config`` may ride the SERVE TENANT AXIS
    (``srnn_tpu.serve.tenant``): K independent soups with this config —
    same statics, different seeds — stacked into one ``(K, N, P)``
    population-major dispatch via vmap, with every tenant's outputs
    BITWISE-equal to its solo run.

    Only the parallel row-major path qualifies: the popmajor lane layout's
    reductions reassociate under a leading vmap axis (measured: the
    stacked weights drift from solo by float noise), and the sequential
    strict-parity scan is a per-particle validation mode with nothing to
    amortize.  The serve scheduler falls back to solo dispatch for
    configs that fail this check.
    """
    if config.mode != "parallel":
        raise ValueError(
            "tenant stacking rides the parallel step; "
            f"mode={config.mode!r} is unsupported (solo dispatch only)")
    if config.layout != "rowmajor":
        raise ValueError(
            "tenant stacking requires layout='rowmajor': the popmajor "
            "lane layout's reductions reassociate under the tenant vmap "
            "axis, breaking the bitwise-equal-to-solo contract")


def tenant_stackable(config: SoupConfig) -> bool:
    """Would this config's evolve ride the serve tenant axis?  (AOT warmup
    uses this to decide whether the stacked spellings exist for it.)"""
    try:
        check_tenant_stackable(config)
    except ValueError:
        return False
    return True


def fused_supported(config: SoupConfig) -> bool:
    """Would ``generation_impl='fused'`` be a valid spelling of this
    config?  (AOT warmup uses this to decide whether to pre-build the
    ``.fused`` twins of a popmajor config's executables.)"""
    if config.layout != "popmajor" or config.mode != "parallel":
        return False
    try:
        _check_popmajor(config._replace(generation_impl="fused"))
    except ValueError:
        return False
    return True


def _evolve_sequential(config: SoupConfig, state: SoupState) -> Tuple[SoupState, SoupEvents]:
    """Particle-by-particle in-place mutation (reference semantics,
    ``soup.py:51-87``): particle i's action sees all mutations made by
    particles < i this generation."""
    n = config.size
    topo = config.topo
    key, k_gen = jax.random.split(state.key)
    pkeys = jax.random.split(k_gen, n)

    def per_particle(carry, inp):
        w, uids, next_uid = carry
        i, pk = inp
        k_ag, k_at, k_lg, k_lt, k_re = jax.random.split(pk, 5)
        wi = w[i]

        # attack: overwrite the VICTIM's row
        attack = jax.random.uniform(k_ag) < config.attacking_rate
        tgt = jax.random.randint(k_at, (), 0, n)
        new_victim = apply_to_weights(topo, wi, w[tgt])
        w = jnp.where(attack, w.at[tgt].set(new_victim), w)

        # learn_from: mutate SELF toward other's samples
        wi = w[i]
        learn = jax.random.uniform(k_lg) < config.learn_from_rate
        ltgt = jax.random.randint(k_lt, (), 0, n)
        if config.learn_from_rate > 0 and config.learn_from_severity > 0:
            learned, _ = _learn_epochs(config, wi, w[ltgt])
            wi = jnp.where(learn, learned, wi)

        # train
        if config.train > 0:
            wi, loss = _train_epochs(config, wi)
        else:
            loss = jnp.zeros((), w.dtype)

        # respawn self
        dead_div = is_diverged(wi) & config.remove_divergent
        dead_zero = is_zero(wi, config.epsilon) & ~dead_div & config.remove_zero
        dead = dead_div | dead_zero
        fresh = init_population(topo, k_re, 1)[0]
        wi = jnp.where(dead, fresh, wi)
        new_uid = jnp.where(dead, next_uid, uids[i])
        next_uid = next_uid + dead.astype(jnp.int32)

        w = w.at[i].set(wi)
        uids = uids.at[i].set(new_uid)

        action = jnp.where(attack, ACT_ATTACK, ACT_NONE)
        cp = jnp.where(attack, uids[tgt], -1)
        action = jnp.where(learn, ACT_LEARN, action)
        cp = jnp.where(learn, uids[ltgt], cp)
        if config.train > 0:
            action, cp = jnp.full_like(action, ACT_TRAIN), jnp.full_like(cp, -1)
        action = jnp.where(dead_div, ACT_DIV_DEAD, action)
        action = jnp.where(dead_zero, ACT_ZERO_DEAD, action)
        cp = jnp.where(dead, new_uid, cp)
        return (w, uids, next_uid), (action, cp, loss)

    init = (state.weights, state.uids, state.next_uid)
    (w, uids, next_uid), (action, cp, loss) = jax.lax.scan(
        per_particle, init, (jnp.arange(n), pkeys))
    new_state = SoupState(w, uids, next_uid, state.time + 1, key)
    return new_state, SoupEvents(action, cp, loss)


def _evolve_step(config: SoupConfig, state: SoupState) -> Tuple[SoupState, SoupEvents]:
    """One generation (``Soup.evolve`` body, ``soup.py:51-87``)."""
    if config.mode == "sequential" and config.respawn_draws != "perparticle":
        raise ValueError(
            "mode='sequential' is the strict-parity mode and requires "
            "respawn_draws='perparticle'")
    _pop_dtype(config)  # validates population_dtype
    if config.mode == "sequential" and config.population_dtype != "f32":
        raise ValueError(
            "mode='sequential' is the strict-parity mode and requires "
            "population_dtype='f32'")
    if config.generation_impl not in ("phases", "fused"):
        raise ValueError(
            f"unknown generation_impl {config.generation_impl!r}")
    if config.generation_impl == "fused" and config.layout != "popmajor":
        raise ValueError(
            "generation_impl='fused' is the popmajor lane megakernel; "
            "layout='rowmajor' needs generation_impl='phases'")
    if config.train_impl == "pallas" and config.layout != "popmajor":
        raise ValueError(
            "train_impl='pallas' is the popmajor lane kernel; "
            "layout='rowmajor' needs train_impl='xla'")
    if config.apply_impl == "pallas" and config.layout != "popmajor":
        raise ValueError(
            "apply_impl='pallas' is the popmajor lane kernel; "
            "layout='rowmajor' needs apply_impl='xla'")
    if (config.attack_impl != "full" or config.learn_from_impl != "full") \
            and config.layout != "popmajor":
        raise ValueError(
            "attack_impl/learn_from_impl='compact' compact lanes of the "
            "popmajor layout; layout='rowmajor' needs 'full'")
    if config.layout == "popmajor":
        _check_popmajor(config)
        new_state, events, wT = _evolve_parallel_popmajor(config, state,
                                                          state.weights.T)
        return new_state._replace(weights=wT.T), events
    if config.layout != "rowmajor":
        raise ValueError(f"unknown soup layout {config.layout!r}")
    if config.mode == "sequential":
        return _evolve_sequential(config, state)
    if config.mode != "parallel":
        raise ValueError(f"unknown soup mode {config.mode!r}")
    return _evolve_parallel(config, state)


#: jitted single-generation step.  The ``_donated`` twin additionally
#: donates the ``state`` pytree to XLA (``donate_argnums``): generation
#: N+1's population overwrites generation N's buffers in place instead of
#: allocating a second (N, P) array — halving peak HBM for the population
#: at mega-soup scale.  Same program, same bits (tests assert bitwise
#: parity); the only contract change is that the INPUT state is dead after
#: the call, so only rebinding callers (``state = step(cfg, state)``) may
#: use it.  Value-comparing callers (parity tests, layout A/B runs) keep
#: the non-donating spelling.
evolve_step = jax.jit(_evolve_step, static_argnames=("config",))
evolve_step_donated = jax.jit(_evolve_step, static_argnames=("config",),
                              donate_argnums=(1,))


def _lineage_caps(n: int, config, capacity: int) -> Tuple[int, int, int]:
    """Static per-generation edge-compaction widths (attack, learn,
    respawn) for an ``n``-particle population — the Binomial bound for the
    gated phases, full width (clipped to the window) for respawn storms.
    A statically-disabled phase gets width 0, which elides its whole edge
    block from the compiled step (``dynamics.record_step``)."""
    from .telemetry.dynamics import edge_capacity

    return (min(edge_capacity(n, config.attacking_rate), capacity)
            if config.attacking_rate > 0 else 0,
            min(edge_capacity(n, config.learn_from_rate), capacity)
            if config.learn_from_rate > 0 else 0,
            min(n, capacity)
            if (config.remove_divergent or config.remove_zero) else 0)


def _evolve(
    config: SoupConfig,
    state: SoupState,
    generations: int = 1,
    record: bool = False,
    metrics: bool = False,
    health: bool = False,
    lineage: bool = False,
    lineage_state=None,
    lineage_capacity: int = 4096,
):
    """Evolve ``generations`` steps as one scan.

    With ``record=True`` also returns stacked per-generation
    ``(SoupEvents, weights (G, N, P), uids (G, N))`` for trajectory analysis
    (the vectorized stand-in for ``ParticleDecorator.save_state`` histories,
    ``network.py:193-198``).

    With ``metrics=True`` also returns a ``telemetry.device.SoupMetrics``
    carry — the soup-science counters (action histogram, summed train
    loss) accumulated INSIDE the scan, so a metered chunk costs one
    bincount per generation on device and zero extra host round-trips.
    The evolved state is bit-identical to the unmetered program (the
    carry only reads the event record; tests assert parity).

    With ``health=True`` also returns a ``telemetry.device.HealthStats``
    carry — the flight recorder's population-health sentinels (NaN/Inf and
    zero-collapse particle counts, weight-norm quantile sketch) folded
    from each generation's post-step weights, same zero-host-round-trip
    discipline and the same bit-identical-state guarantee.

    With ``lineage=True`` (``lineage_state`` = the persistent
    ``telemetry.dynamics.LineageState`` carry, seeded once per run with
    ``seed_lineage``) additionally returns one replication-dynamics
    window ``(new_lineage_state, LineageWindow, FixpointStats)``:
    per-particle pids with parent/birth advanced through every attack and
    respawn, the window's event-edge buffer (``lineage_capacity`` rows;
    overflow drops and counts), and the end-of-window self-application
    census.  Same bit-identical-state guarantee; parallel mode only.
    Return shape: ``final``, then ``recs`` if recording, then the metrics
    carry, then the health carry, then the lineage triple.
    """
    if metrics:
        from .telemetry.device import (accumulate_soup_metrics,
                                       zero_soup_metrics)
    if health:
        from .telemetry.device import accumulate_health, zero_health
    m0 = zero_soup_metrics() if metrics else None
    h0 = zero_health() if health else None
    l0 = w0 = lincfg = None
    if lineage:
        if config.mode != "parallel":
            raise ValueError(
                "lineage=True rides the parallel step's phase gates; "
                f"mode={config.mode!r} is unsupported")
        if lineage_state is None:
            raise ValueError("lineage=True needs lineage_state= (seed one "
                             "with telemetry.dynamics.seed_lineage)")
        from .telemetry.dynamics import close_window, zero_window

        l0 = lineage_state
        w0 = zero_window(lineage_capacity)
        lincfg = (_lineage_caps(config.size, config, lineage_capacity),
                  lineage_capacity)

    if config.layout == "popmajor":
        # keep the carry transposed across the whole run: one transpose at
        # entry/exit instead of two per generation
        _check_popmajor(config)

        def step_t(carry, _):
            s, wT, m, h, lin, win = carry
            if lineage:
                new_s, ev, new_wT, lin, win = _evolve_parallel_popmajor(
                    config, s, wT, lin, win, lincfg)
            else:
                new_s, ev, new_wT = _evolve_parallel_popmajor(config, s, wT)
            if metrics:
                m = accumulate_soup_metrics(m, ev.action, ev.loss)
            # int8 consumers (health folds, trajectory records) read the
            # dequantized f32 view — raw codes mean nothing without scales
            vT = _stored_view(config, new_wT, new_s.scales, paxis=-1) \
                if (health or record) else new_wT
            if health:
                h = accumulate_health(h, vT, 0, config.epsilon)
            out = (ev, vT.T, new_s.uids) if record else None
            return (new_s, new_wT, m, h, lin, win), out

        # the transposed wT is the live weights carry; null the row-major
        # field so the scan doesn't drag a dead (N, P) buffer along
        # (the int8 scales vector keeps riding the state carry — each
        # generation's entry dequant needs the previous exit's scales)
        light = state._replace(weights=jnp.zeros((0,), state.weights.dtype))
        (final, wT, m, h, lin, win), recs = jax.lax.scan(
            step_t, (light, state.weights.T, m0, h0, l0, w0), None,
            length=generations)
        final = final._replace(weights=wT.T)
        if lineage:
            from .ops.popmajor import apply_popmajor

            wc = _upcast(config, wT, final.scales, paxis=-1)
            fw = apply_popmajor(config.topo, wc, wc)
            lin, fstats = close_window(lin, wc, fw, 0, config.epsilon)
    else:
        def step(carry, _):
            s, m, h, lin, win = carry
            if lineage:
                new_s, ev, lin, win = _evolve_parallel(config, s, lin, win,
                                                       lincfg)
            else:
                new_s, ev = evolve_step(config, s)
            if metrics:
                m = accumulate_soup_metrics(m, ev.action, ev.loss)
            v = _stored_view(config, new_s.weights, new_s.scales) \
                if (health or record) else new_s.weights
            if health:
                h = accumulate_health(h, v, -1, config.epsilon)
            out = (ev, v, new_s.uids) if record else None
            return (new_s, m, h, lin, win), out

        (final, m, h, lin, win), recs = jax.lax.scan(
            step, (state, m0, h0, l0, w0), None, length=generations)
        if lineage:
            wc = _upcast(config, final.weights, final.scales)
            fw = jax.vmap(lambda wi: apply_to_weights(config.topo, wi, wi))(
                wc)
            lin, fstats = close_window(lin, wc, fw, -1, config.epsilon)

    out = (final,)
    if record:
        out += (recs,)
    if metrics:
        out += (m,)
    if health:
        out += (h,)
    if lineage:
        out += ((lin, win, fstats),)
    return out if len(out) > 1 else final


#: jitted multi-generation run; ``evolve_donated`` is the in-place-buffer
#: twin (see ``evolve_step_donated``) used by the mega-run hot loops, where
#: the state is always rebound chunk over chunk.
evolve = jax.jit(_evolve, static_argnames=("config", "generations", "record",
                                           "metrics", "health", "lineage",
                                           "lineage_capacity"))
evolve_donated = jax.jit(_evolve,
                         static_argnames=("config", "generations", "record",
                                          "metrics", "health", "lineage",
                                          "lineage_capacity"),
                         donate_argnums=(1,))


@functools.partial(jax.jit, static_argnames=("topo", "epsilon"))
def probe_dynamics(topo: Topology, weights: jnp.ndarray,
                   epsilon: float = DEFAULT_EPSILON):
    """One-shot fixpoint census of a row-major population already in hand
    (the capture-mode chunks' stand-in for the in-scan lineage carry, like
    ``telemetry.device.probe_health``): self-apply every particle once and
    label basins — no pids, no edges, transitions from the unknown row."""
    from .telemetry.dynamics import fixpoint_stats

    fw = jax.vmap(lambda wi: apply_to_weights(topo, wi, wi))(weights)
    prev = jnp.full(weights.shape[0], -1, jnp.int32)
    return fixpoint_stats(weights, fw, -1, epsilon, prev)[1]


@functools.partial(jax.jit, static_argnames=("config",))
def count(config: SoupConfig, state: SoupState) -> jnp.ndarray:
    """(5,) class histogram of the current population
    (``Soup.count``, ``soup.py:89-103``)."""
    return count_classes(classify_batch(
        config.topo, _stored_view(config, state.weights, state.scales),
        config.epsilon))
