"""Deterministic, schedule-driven fault injection for the run supervisor.

Every recovery path in ``resilience.supervisor`` must be exercisable on
CPU CI — a TPU window is too rare to be the first place a retry branch
runs.  ``ChaosMonkey`` arms a fixed schedule of faults that fire at
exact, reproducible points of a mega run:

  * ``device_loss@G[:S]`` — raise a real ``XlaRuntimeError`` at the top
    of the chunk starting at generation ``G`` (the type XLA itself
    raises, so the supervisor's classifier — not a test-only branch —
    routes it).  An optional ``:S`` records that only ``S`` devices
    "survive": the supervisor's live-device probe honors the override,
    which is how a topology shrink (2 shards → 1) is simulated on a
    host whose devices cannot actually die.
  * ``stall@G[:HOLD_S]`` — condemn the finisher of the chunk covering
    generation ``G``: it blocks (default an hour) until the supervisor
    aborts it during recovery, so the armed ``--stall-timeout-s``
    deadline trips the real ``StallError`` path, watched thread and
    all.  The condemned finisher never runs — recovery resumes from the
    last durable checkpoint exactly as it would after a genuine wedge.
  * ``writer@N`` — poison the ``N``-th job submitted to the background
    writer (1-based, counted per attempt) with a permanent ``EIO``:
    exercises the writer's first-error latch, the job-naming error
    message, and the supervisor's ``io`` retry.
  * ``host_loss@G[:H]`` — raise :class:`~srnn_tpu.distributed.HostLost`
    at the top of the chunk starting at generation ``G``, routed through
    the production classifier (kind ``host_loss``).  ``H`` names the
    slice group that "died" (0-based, ``parallel.slice_groups`` order;
    default: the last group): the supervisor's survivor probe then
    reports every device EXCEPT that group's, which is how a whole-slice
    loss — and the re-ramp onto the largest regular surviving mesh — is
    drilled on a host whose slices cannot actually die.  In a
    multi-process run the supervisor instead exits ``EXIT_HOST_LOST``
    and the launcher tier re-ramps (fewer processes).
  * ``coordinator_timeout@G`` — raise
    :class:`~srnn_tpu.distributed.CoordinatorTimeout` at the chunk
    boundary: same classifier kind (a dead coordinator is a lost host as
    far as recovery goes), no survivor override — the probe sees the
    real topology.
  * ``sigterm@G`` — ``kill(self, SIGTERM)`` at the chunk boundary: the
    real signal, the real handler, the graceful-preemption drain.
  * ``sigkill@G`` — ``kill(self, SIGKILL)``: no cleanup of any kind —
    the kill-and-resume e2e runs this in a child process and asserts
    the ``.traj`` stream is bit-identical after resume.

Serve-layer events (PR 13 — the experiment service's recovery ladders,
``srnn_tpu/serve``; the service arms these via its ``--chaos`` flag and
calls :meth:`ChaosMonkey.note_submit` / :meth:`ChaosMonkey.serve_dispatch`
from its production admission/dispatch paths):

  * ``serve_kill@N`` — ``kill(self, SIGKILL)`` at the top of the ``N``-th
    dispatch execution attempt (1-based): admitted tickets are journaled
    but unfinished — the kill -9 drill the durable-journal replay e2e
    restarts from.
  * ``serve_dispatch_fault@N:kind`` — raise the classified fault ``kind``
    (one of :data:`SERVE_FAULT_KINDS`: ``device_loss`` as a real
    ``XlaRuntimeError``, ``io`` as an ``EIO`` ``OSError``, ``stall`` as a
    real ``StallError``) at the ``N``-th dispatch attempt, routed through
    the supervisor's production ``classify_fault`` — the service's
    bounded deterministic-backoff retry path.
  * ``serve_poison_tenant@N`` — the ``N``-th ADMITTED ticket (1-based;
    journal replays re-admit in journal order first) is poisoned: every
    dispatch attempt containing it raises a deterministic (FATAL-class)
    config error, so retries cannot mask it and the service's bisection
    must isolate and quarantine it while its groupmates complete.

Every event fires **once per process**; an in-process restart keeps the
consumed schedule, so recovery cannot loop on its own injector.  The
schedule string is not persisted into ``config.json`` — a later
``--resume`` of a chaos run is chaos-free unless re-armed explicitly.
"""

from __future__ import annotations

import errno
import os
import signal
import threading
from typing import Callable, List, Optional

KINDS = ("device_loss", "host_loss", "coordinator_timeout", "stall",
         "writer", "sigterm", "sigkill",
         "serve_kill", "serve_dispatch_fault", "serve_poison_tenant")

#: the ``serve_dispatch_fault`` menu — retryable kinds by the
#: supervisor's taxonomy (the fault-taxonomy srnnlint pass checks each
#: stays one of the supervisor's RETRYABLE kind values, T009)
SERVE_FAULT_KINDS = ("device_loss", "io", "stall")

#: events whose ordinal ``N`` is 1-based (the first countable thing is 1)
_ONE_BASED = ("writer", "serve_kill", "serve_dispatch_fault",
              "serve_poison_tenant")

#: how long a condemned finisher holds before giving up on an abort (the
#: supervisor aborts it within one backoff; this is the safety net)
DEFAULT_STALL_HOLD_S = 3600.0


class ChaosEvent:
    __slots__ = ("kind", "at", "arg", "fired")

    def __init__(self, kind: str, at: int, arg=None):
        self.kind = kind
        self.at = int(at)   # generation (writer/serve_*: 1-based ordinal)
        self.arg = arg      # float, or a fault-kind string (serve menu)
        self.fired = False

    def __repr__(self):
        if isinstance(self.arg, float):
            suffix = f":{self.arg:g}"
        elif self.arg is not None:
            suffix = f":{self.arg}"
        else:
            suffix = ""
        return (f"ChaosEvent({self.kind}@{self.at}{suffix}"
                + (" fired" if self.fired else "") + ")")


def parse_schedule(spec: str) -> List[ChaosEvent]:
    """Parse ``kind@N[:arg],…`` (see module docstring).  Raises
    ``ValueError`` on an unknown kind or malformed entry."""
    events = []
    for raw in spec.split(","):
        entry = raw.strip()
        if not entry:
            continue
        try:
            kind, rest = entry.split("@", 1)
            arg = None
            if ":" in rest:
                rest, args_ = rest.split(":", 1)
                try:
                    arg = float(args_)
                except ValueError:
                    # string args are the serve fault menu's spelling
                    # (serve_dispatch_fault@N:io); validated below
                    arg = args_
            at = int(rest)
        except ValueError:
            raise ValueError(
                f"bad chaos entry {entry!r} (want kind@N or kind@N:arg)")
        if kind not in KINDS:
            raise ValueError(
                f"unknown chaos kind {kind!r} (one of {', '.join(KINDS)})")
        if isinstance(arg, str) and kind != "serve_dispatch_fault":
            raise ValueError(
                f"non-numeric argument in chaos entry {entry!r} (string "
                "args belong to serve_dispatch_fault@N:kind only)")
        if kind == "serve_dispatch_fault":
            arg = "io" if arg is None else arg
            if not isinstance(arg, str) or arg not in SERVE_FAULT_KINDS:
                raise ValueError(
                    f"serve_dispatch_fault kind must be one of "
                    f"{', '.join(SERVE_FAULT_KINDS)}: {entry!r}")
        if at < 0 or (isinstance(arg, float) and arg < 0):
            raise ValueError(f"negative value in chaos entry {entry!r}")
        if kind in _ONE_BASED and at < 1:
            raise ValueError(
                f"{kind} ordinals are 1-based: {entry!r} would never "
                f"fire (the first countable event is {kind}@1)")
        if kind == "host_loss" and arg is not None and arg != int(arg):
            raise ValueError(
                f"host_loss slice-group ordinal must be an integer: "
                f"{entry!r}")
        if kind == "coordinator_timeout" and arg is not None:
            raise ValueError(
                f"coordinator_timeout takes no argument: {entry!r} (there "
                "is no survivor override — the probe sees the real "
                "topology)")
        events.append(ChaosEvent(kind, at, arg))
    events.sort(key=lambda e: e.at)
    return events


def _surviving_after_group_loss(group: Optional[int]) -> "tuple[list, int]":
    """(surviving devices, lost-group ordinal) after slice group
    ``group`` (default: the last) of the CURRENT topology dies — the
    forced-survivor list the supervisor's probe consumes.  A spec that
    cannot fire as written (ordinal past the live groups, or a topology
    with nothing left to survive) fails LOUDLY — the ordinal cannot be
    validated at parse time because the group count is only known at
    fire time, so this is where the writer@0-style strictness lives."""
    import jax

    from ..parallel.multihost import slice_groups

    groups = slice_groups(jax.devices())
    g = len(groups) - 1 if group is None else int(group)
    if g >= len(groups):
        raise ValueError(
            f"--chaos host_loss slice-group ordinal {g} is out of range: "
            f"the live topology has {len(groups)} slice group(s)")
    if len(groups) <= 1:
        raise ValueError(
            "--chaos host_loss would leave no surviving slice (the live "
            "topology has a single group); use device_loss@G[:S], or "
            "shape the topology with SRNN_FORCE_SLICES")
    return [d for i, grp in enumerate(groups) if i != g for d in grp], g


def _raise_host_loss(gen: int, group: Optional[int]) -> None:
    """Raise the typed host-loss fault the distributed runtime raises, so
    the classifier's production ``host_loss`` branch routes it."""
    from ..distributed import HostLost

    raise HostLost(
        f"chaos: simulated host/slice loss at generation {gen}"
        + (f" (slice group {group} lost)" if group is not None else ""))


def _raise_coordinator_timeout(gen: int) -> None:
    from ..distributed import CoordinatorTimeout

    raise CoordinatorTimeout(
        f"chaos: simulated coordinator timeout at generation {gen}")


def _raise_serve_fault(kind: str, attempt: int) -> None:
    """Raise the classified fault ``kind`` the way production raises it,
    so the service's supervised dispatch — via the supervisor's REAL
    ``classify_fault``, not a test shim — routes the retry."""
    if kind == "io":
        raise OSError(errno.EIO,
                      f"chaos: injected io fault in serve dispatch "
                      f"attempt {attempt}")
    if kind == "stall":
        from ..utils.pipeline import StallError

        raise StallError(f"chaos: injected dispatch stall in serve "
                         f"dispatch attempt {attempt}")
    _raise_device_loss(attempt, None)


def _raise_device_loss(gen: int, survivors: Optional[int]) -> None:
    """Raise the same exception type a real device loss surfaces as, so
    the classifier's production branch — not a test shim — handles it."""
    msg = (f"INTERNAL: chaos: simulated device loss at generation {gen}"
           + (f" ({survivors} device(s) survive)" if survivors else ""))
    try:
        from jaxlib.xla_extension import XlaRuntimeError

        raise XlaRuntimeError(msg)
    except ImportError:  # pragma: no cover - jaxlib always has it here
        raise RuntimeError(f"device lost — {msg}")


class ChaosMonkey:
    """The armed schedule plus the per-run injection hooks the mega loops
    call (``chunk_start``/``wrap_finisher``/``attach_writer``)."""

    def __init__(self, events: List[ChaosEvent]):
        self.events = list(events)
        #: device count the supervisor's live probe reports after a
        #: shrinking device_loss event (0 = no override; consumed by
        #: ``take_forced_live`` so only the event that set it is
        #: simulated — later losses probe for real)
        self.forced_live = 0
        #: surviving-device LIST after a host_loss event (None = no
        #: override; consumed by ``take_forced_survivors`` — one probe
        #: per event, like ``forced_live``)
        self.forced_survivors: Optional[list] = None
        # one release event PER condemned finisher: a global flag would
        # stay set after the first recovery and make every later stall
        # event skip its finisher silently instead of stalling
        self._holds: List[threading.Event] = []
        self._holds_lock = threading.Lock()
        #: tickets poisoned by serve_poison_tenant@N: the POISON persists
        #: (unlike the one-shot event that armed it) so retries cannot
        #: mask it and the service's bisection must isolate it
        self.poisoned_tickets = set()
        self._serve_submits = 0    # admitted tickets seen (1-based)
        self._serve_attempts = 0   # dispatch execution attempts (1-based)

    # -- construction -----------------------------------------------------

    @classmethod
    def from_args(cls, args) -> Optional["ChaosMonkey"]:
        """Build from the ``--chaos`` CLI spec (None when unset) and
        fail fast on schedules that cannot fire as written."""
        spec = getattr(args, "chaos", None)
        if not spec:
            return None
        try:
            events = parse_schedule(spec)
        except ValueError as e:
            raise SystemExit(f"--chaos: {e}")
        if not events:
            raise SystemExit("--chaos: empty schedule")
        if any(e.kind == "stall" for e in events) \
                and not getattr(args, "stall_timeout_s", 0.0):
            raise SystemExit("--chaos stall@N needs --stall-timeout-s > 0 "
                             "(nothing would convert the injected hang "
                             "into a StallError)")
        return cls(events)

    # -- injection hooks ---------------------------------------------------

    def chunk_start(self, gen: int) -> None:
        """Fire every due generation-keyed event; called by the mega loops
        at the top of each chunk iteration, before the chunk's dispatch."""
        for ev in self.events:
            if ev.fired or gen < ev.at \
                    or ev.kind in ("writer", "stall", "serve_kill",
                                   "serve_dispatch_fault",
                                   "serve_poison_tenant"):
                continue
            ev.fired = True
            if ev.kind == "device_loss":
                if ev.arg:
                    self.forced_live = int(ev.arg)
                _raise_device_loss(gen, int(ev.arg) if ev.arg else None)
            elif ev.kind == "host_loss":
                from ..distributed import context

                if context().active:
                    # multi-process: the survivor override is moot — the
                    # supervisor exits EXIT_HOST_LOST without probing and
                    # the LAUNCHER re-ramps the topology
                    _raise_host_loss(gen, int(ev.arg)
                                     if ev.arg is not None else None)
                survivors, lost = _surviving_after_group_loss(
                    int(ev.arg) if ev.arg is not None else None)
                self.forced_survivors = survivors
                _raise_host_loss(gen, lost)
            elif ev.kind == "coordinator_timeout":
                _raise_coordinator_timeout(gen)
            elif ev.kind == "sigterm":
                os.kill(os.getpid(), signal.SIGTERM)
            elif ev.kind == "sigkill":  # pragma: no cover - kills the proc
                os.kill(os.getpid(), signal.SIGKILL)

    def wrap_finisher(self, finish: Callable[[], None],
                      gen_end: int) -> Callable[[], None]:
        """Condemn the finisher of the chunk ending at ``gen_end`` when a
        stall event is due: the replacement blocks until the supervisor's
        recovery aborts it (or the hold elapses) and NEVER runs the real
        finisher — its chunk is lost exactly as a genuine wedge loses it."""
        ev = next((e for e in self.events
                   if e.kind == "stall" and not e.fired and e.at <= gen_end),
                  None)
        if ev is None:
            return finish
        ev.fired = True
        hold = ev.arg if ev.arg else DEFAULT_STALL_HOLD_S
        release = threading.Event()
        with self._holds_lock:
            self._holds.append(release)

        def stalled():
            release.wait(hold)

        return stalled

    def attach_writer(self, writer) -> None:
        """Arm the next pending ``writer@N`` event on a freshly-built
        :class:`~srnn_tpu.utils.pipeline.BackgroundWriter`: its ``N``-th
        submitted job (1-based) is replaced with one that raises a
        permanent ``EIO`` — the latch path, with the job named."""
        ev = next((e for e in self.events
                   if e.kind == "writer" and not e.fired), None)
        if ev is None or writer is None:
            return
        orig = writer.submit
        count = [0]

        def submit(fn, *a, **k):
            count[0] += 1
            if count[0] == ev.at and not ev.fired:
                ev.fired = True
                label = getattr(fn, "__name__", repr(fn))
                ordinal = count[0]  # bind NOW: the job executes later,
                # when the shared counter has already moved past it

                def chaos_poisoned_job(*_a, **_k):
                    raise OSError(
                        errno.EIO,
                        f"chaos: injected permanent writer fault in place "
                        f"of job {ordinal} ({label})")

                return orig(chaos_poisoned_job)
            return orig(fn, *a, **k)

        writer.submit = submit

    def note_submit(self, ticket: str) -> None:
        """Serve admission hook: count admitted tickets (journal replays
        re-admit first, in journal order) and arm any due
        ``serve_poison_tenant@N`` on the ``N``-th one."""
        self._serve_submits += 1
        for ev in self.events:
            if ev.kind == "serve_poison_tenant" and not ev.fired \
                    and self._serve_submits >= ev.at:
                ev.fired = True
                self.poisoned_tickets.add(ticket)

    def serve_dispatch(self, requests) -> None:
        """Serve dispatch hook, called at the top of EVERY dispatch
        execution attempt (retries and bisection halves included).
        Poisoned tickets raise a deterministic (FATAL-class) error first
        — the poison outlives its arming event by design; then the
        attempt counter advances and any due ``serve_kill`` /
        ``serve_dispatch_fault`` fires once."""
        bad = sorted(r.ticket for r in requests
                     if r.ticket in self.poisoned_tickets)
        if bad:
            raise RuntimeError(
                "chaos: poisoned tenant config for ticket(s) "
                + ",".join(bad) + " (deterministic; survives retries)")
        self._serve_attempts += 1
        for ev in self.events:
            if ev.fired or ev.kind not in ("serve_kill",
                                           "serve_dispatch_fault") \
                    or self._serve_attempts < ev.at:
                continue
            ev.fired = True
            if ev.kind == "serve_kill":  # pragma: no cover - kills us
                os.kill(os.getpid(), signal.SIGKILL)
            else:
                _raise_serve_fault(str(ev.arg), self._serve_attempts)

    def abort_pending(self) -> None:
        """Release the currently-condemned finisher threads (recovery
        calls this before restarting, so no chaos thread outlives its
        attempt).  Later stall events get fresh holds — releasing is per
        recovery, never a permanent disarm."""
        with self._holds_lock:
            holds, self._holds = self._holds, []
        for h in holds:
            h.set()

    def take_forced_survivors(self) -> Optional[list]:
        """Consume the simulated surviving-device list (None = none
        pending): each ``host_loss@G[:H]`` overrides exactly ONE recovery
        probe, so a later un-annotated loss probes the real topology."""
        forced, self.forced_survivors = self.forced_survivors, None
        return forced

    def take_forced_live(self) -> int:
        """Consume the simulated survivor count (0 = none pending): each
        ``device_loss@G:S`` overrides exactly ONE recovery probe, so a
        later un-annotated loss probes the real topology."""
        forced, self.forced_live = self.forced_live, 0
        return forced

    @property
    def pending(self) -> List[ChaosEvent]:
        return [e for e in self.events if not e.fired]
