"""Elastic run supervision: fault classification, retry/backoff, topology
re-ramp and the deterministic chaos harness (see ``supervisor`` and
``chaos`` module docstrings, and DESIGN.md §13)."""

from .chaos import ChaosEvent, ChaosMonkey, parse_schedule
from .supervisor import (DEVICE_LOSS, EXIT_CODE_NAMES, EXIT_HOST_LOST,
                         EXIT_PREEMPTED_CLEAN, EXIT_RECOVERED,
                         EXIT_RETRIES_EXHAUSTED, FATAL, HOST_LOSS, IO,
                         PREEMPT, RETRYABLE, STALL, AttemptContext,
                         BackoffPolicy, Preempted, Supervisor,
                         classify_fault, exit_code_for_report,
                         preempt_requested, supervised_run)

__all__ = [
    "AttemptContext",
    "BackoffPolicy",
    "ChaosEvent",
    "ChaosMonkey",
    "DEVICE_LOSS",
    "EXIT_CODE_NAMES",
    "EXIT_PREEMPTED_CLEAN",
    "EXIT_RECOVERED",
    "EXIT_RETRIES_EXHAUSTED",
    "FATAL",
    "IO",
    "PREEMPT",
    "Preempted",
    "RETRYABLE",
    "STALL",
    "Supervisor",
    "classify_fault",
    "exit_code_for_report",
    "parse_schedule",
    "preempt_requested",
    "supervised_run",
]
