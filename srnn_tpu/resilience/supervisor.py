"""The elastic run supervisor: preemption-tolerant mega runs.

The paper's soups only say something about fixpoint/divergence dynamics
if the run *finishes*, yet the opportunistic-TPU machinery
(``scripts/tpu_watch.sh``, a BENCH history full of wedges and timeouts)
shows hardware that comes and goes.  Every ingredient for survival
already exists — bit-exact ``--resume`` from orbax checkpoints, the
flight recorder's triage bundles, donation-safe ``snapshot()``,
``StallError`` deadlines — but before this module, nothing turned a
failure into anything other than a dead process.  The supervisor is
that missing layer: it wraps one attempt of a mega loop
(``mega_soup``/``mega_multisoup``) and converts classified faults into
**checkpoint-from-last-snapshot → bounded retries with exponential
backoff + deterministic jitter → topology re-ramp**.

Fault taxonomy (:func:`classify_fault`):

  * ``device_loss`` — ``XlaRuntimeError`` (XLA device loss, TPU goaway/
    maintenance preemption surfacing through a dispatch) or a
    ``RuntimeError`` whose text names a lost/halted device.  Recovery
    re-enumerates live devices and may **re-ramp**: rebuild the mesh on
    the survivors (8→4 devices; repeated losses without an observed
    shrink degrade by halving) and re-shard the restored population
    onto it.  TPU→CPU degradation needs a fresh process (a jax backend
    cannot be re-initialized in-process) — that tier is
    ``scripts/tpu_watch.sh``'s, driven by this module's exit codes.
  * ``host_loss`` — :class:`~srnn_tpu.distributed.HostLost` (a peer
    process / slice host is gone) or
    :class:`~srnn_tpu.distributed.CoordinatorTimeout` (bring-up or a
    barrier never reached the coordinator).  A single-process multislice
    run recovers in-process like a device loss — the re-ramp rebuilds
    the largest regular multislice mesh from the surviving slices
    (``parallel.reramp_soup_mesh``).  A MULTI-process run cannot change
    its ``jax.distributed`` membership in-process, so it exits
    :data:`EXIT_HOST_LOST` and the launcher tier
    (``distributed.launch``) re-ramps: fewer processes, resumed from the
    last durable checkpoint.
  * ``stall`` — :class:`~srnn_tpu.utils.pipeline.StallError` from the
    ``ChunkDriver`` finisher deadline (device results never landed).
  * ``io`` — :class:`~srnn_tpu.utils.pipeline.WriterError` (a
    background job failed past its own retry budget) or an ``OSError``
    with a plausibly-transient errno.  ``FileNotFoundError`` and
    permission errors are deliberately **fatal**: they are user or
    programming errors that a retry can only repeat.
  * ``preempt`` — :class:`Preempted`, raised by the mega loops at the
    next chunk boundary after SIGTERM (TPU maintenance sends SIGTERM
    before reclaiming a slice).  Never retried: the loop has already
    drained its pipeline, so the final checkpoint is durable, and the
    process exits :data:`EXIT_PREEMPTED_CLEAN` so the watch tier knows
    the run is resumable, not wedged.
  * ``fatal`` — everything else (including ``SystemExit`` from CLI
    validation): re-raised unchanged.

Recovery is **resume**: the supervisor points the next attempt at the
faulted run directory whenever a finalized checkpoint exists there, so
the entire restore path (config pinning, torn-tail truncation, lineage
sidecar, ``own_pytree``) is the one ``--resume`` already bit-exact
tests.  An unchanged-topology recovery therefore replays bit-exactly
against an uninterrupted single-host run — the parity oracle the chaos
harness (``resilience.chaos``) asserts on CPU CI.

Exit-code vocabulary (consumed by ``scripts/tpu_watch.sh`` and named by
``bench.py``):

  * ``0`` — clean success, no faults.
  * :data:`EXIT_RECOVERED` (3) — success after ≥1 in-process restart
    (CLI only; the Python API returns the run dir either way).
  * :data:`EXIT_RETRIES_EXHAUSTED` (69, ``EX_UNAVAILABLE``) — the
    retry budget is spent; the last traceback was printed.
  * :data:`EXIT_HOST_LOST` (71, ``EX_OSERR``) — a multi-process run
    lost a peer (or its coordinator); the launcher tier re-ramps with
    fewer processes from the last durable checkpoint.
  * :data:`EXIT_PREEMPTED_CLEAN` (75, ``EX_TEMPFAIL``) — SIGTERM was
    honored with a graceful final checkpoint; resume when hardware
    returns.
"""

from __future__ import annotations

import random
import re
import signal
import sys
import threading
import time
import traceback
from typing import Any, Callable, List, Optional

# -- fault taxonomy ---------------------------------------------------------

DEVICE_LOSS = "device_loss"
HOST_LOSS = "host_loss"
STALL = "stall"
IO = "io"
PREEMPT = "preempt"
FATAL = "fatal"

#: retryable faults (everything except PREEMPT, which exits clean, and
#: FATAL, which re-raises).  HOST_LOSS is retryable only in-process for
#: single-process runs (multislice CPU/TPU topologies re-ramp onto the
#: surviving slices); a MULTI-process run cannot change its
#: ``jax.distributed`` membership in-process, so HOST_LOSS there exits
#: :data:`EXIT_HOST_LOST` for the launcher tier
#: (``distributed.launch``) to re-ramp.
RETRYABLE = (DEVICE_LOSS, HOST_LOSS, STALL, IO)

# CLI exit codes (sysexits.h where one fits); see module docstring
EXIT_RECOVERED = 3
EXIT_RETRIES_EXHAUSTED = 69   # EX_UNAVAILABLE
EXIT_HOST_LOST = 71           # EX_OSERR: a peer process/slice is gone
EXIT_PREEMPTED_CLEAN = 75     # EX_TEMPFAIL

EXIT_CODE_NAMES = {
    EXIT_RECOVERED: "recovered",
    EXIT_RETRIES_EXHAUSTED: "retries-exhausted",
    EXIT_HOST_LOST: "host-lost",
    EXIT_PREEMPTED_CLEAN: "preempted-clean",
}

#: last supervised run's report — ``setups.__main__`` maps it to the CLI
#: exit code (the Python API returns run dirs, not codes)
LAST_REPORT: Optional[dict] = None


class Preempted(Exception):
    """SIGTERM was honored: the mega loop stopped at a chunk boundary,
    drained its pipeline (final checkpoint durable) and unwound.  Carries
    the generation of the last durable checkpoint."""

    def __init__(self, generation: int):
        super().__init__(f"preempted at generation {generation} "
                         "(final checkpoint durable)")
        self.generation = generation


# a RuntimeError whose text names a lost/halted device counts as device
# loss even when the concrete XlaRuntimeError type is unavailable
_DEVICE_LOSS_RE = re.compile(
    r"goaway|preempt|data_loss|slice.*health|device.*(lost|loss|halt|fail)",
    re.IGNORECASE)

# XLA statuses that are DETERMINISTIC program/shape/memory failures: a
# retry repeats them, and the re-ramp's budget-halving makes an OOM
# strictly worse (fewer devices => bigger shards).  These stay fatal even
# though they arrive as XlaRuntimeError.
_DETERMINISTIC_XLA_RE = re.compile(
    r"RESOURCE_EXHAUSTED|INVALID_ARGUMENT|FAILED_PRECONDITION"
    r"|UNIMPLEMENTED|OUT_OF_RANGE", re.IGNORECASE)

# a cross-process collective dying because its PEER went away (observed
# spelling: "FAILED_PRECONDITION: ... Gloo all-reduce failed: ...
# Connection closed by peer") — checked BEFORE the deterministic-status
# table, because the wrapping status is FAILED_PRECONDITION even though
# the fault is a lost host, not a program error
_PEER_LOSS_RE = re.compile(
    r"gloo.*(connection closed|connection reset|connect failure"
    r"|timed out)|connection closed by peer|distributed runtime"
    r".*(unavailable|shut ?down)", re.IGNORECASE)

# OSError errnos worth retrying (transient by nature); everything else —
# ENOENT, EACCES, EISDIR… — is a user/programming error a retry repeats
_RETRYABLE_ERRNOS = frozenset({
    4,    # EINTR
    5,    # EIO (flaky storage / NFS blips)
    11,   # EAGAIN
    28,   # ENOSPC (logs may rotate; the writer already burned its grace)
    110,  # ETIMEDOUT
    116,  # ESTALE
})


def _xla_error_types() -> tuple:
    types: List[type] = []
    try:  # jax >= 0.4.14 re-exports it
        from jax.errors import JaxRuntimeError
        types.append(JaxRuntimeError)
    except Exception:
        pass
    try:
        from jaxlib.xla_extension import XlaRuntimeError
        types.append(XlaRuntimeError)
    except Exception:
        pass
    return tuple(types)


def classify_fault(exc: BaseException) -> str:
    """Map an exception to the fault taxonomy (module docstring)."""
    from ..distributed import CoordinatorTimeout, HostLost
    from ..utils.pipeline import StallError, WriterError

    if isinstance(exc, Preempted):
        return PREEMPT
    if isinstance(exc, (KeyboardInterrupt, SystemExit, GeneratorExit)):
        return FATAL
    if isinstance(exc, (HostLost, CoordinatorTimeout)):
        # a peer process/slice is gone, or the coordinator never answered
        # (indistinguishable at this layer): single-process runs re-ramp
        # onto surviving slices in-process; multi-process runs exit
        # EXIT_HOST_LOST for the launcher tier (see Supervisor.run)
        return HOST_LOSS
    if isinstance(exc, StallError):
        return STALL
    if isinstance(exc, WriterError):
        # the wrapper is only as retryable as what it wraps: a job that
        # died on ENOENT/EACCES — or on a deterministic logic error —
        # re-dies identically on every retry, while a device loss
        # surfacing through a deferred resolve on the writer thread must
        # keep its device_loss classification (and its re-ramp)
        cause = exc.__cause__
        if cause is None:
            return IO  # writer-internal refusal (closed/latched)
        inner = classify_fault(cause)
        return inner if inner in (IO, DEVICE_LOSS) else FATAL
    xla_types = _xla_error_types()
    if xla_types and isinstance(exc, xla_types):
        if _PEER_LOSS_RE.search(str(exc)):
            return HOST_LOSS
        return FATAL if _DETERMINISTIC_XLA_RE.search(str(exc)) \
            else DEVICE_LOSS
    if isinstance(exc, OSError):
        return IO if exc.errno in _RETRYABLE_ERRNOS else FATAL
    if isinstance(exc, RuntimeError) and _DEVICE_LOSS_RE.search(str(exc)):
        return DEVICE_LOSS
    return FATAL


def _in_multiprocess_run() -> bool:
    """Is this process one of several in a ``jax.distributed`` job?
    Consults the bootstrap context (never probes devices — the caller may
    be handling the very fault that makes probing hang)."""
    from ..distributed import context

    return context().active


# -- SIGTERM / preemption machinery -----------------------------------------

_PREEMPT = threading.Event()


def preempt_requested() -> bool:
    """True once SIGTERM arrived — the mega loops poll this at every chunk
    boundary and stop gracefully (drain → final checkpoint → unwind)."""
    return _PREEMPT.is_set()


def _on_sigterm(signum, frame):  # pragma: no cover - trivial
    _PREEMPT.set()


class _SigtermGuard:
    """Install the graceful-preemption SIGTERM handler for the duration of
    a supervised run; restore the previous disposition (and clear the
    flag) on the way out.  A non-main-thread caller (no signal access)
    degrades to a no-op — preemption then follows the default path."""

    _NOT_INSTALLED = object()

    def __enter__(self):
        _PREEMPT.clear()
        self._prev = self._NOT_INSTALLED
        try:
            self._prev = signal.signal(signal.SIGTERM, _on_sigterm)
        except ValueError:
            pass
        return self

    def __exit__(self, *exc):
        if self._prev is not self._NOT_INSTALLED:
            try:
                signal.signal(signal.SIGTERM, self._prev)
            except (ValueError, TypeError):
                pass
        _PREEMPT.clear()
        return False


# -- retry policy -----------------------------------------------------------


class BackoffPolicy:
    """Bounded exponential backoff with **deterministic** jitter.

    ``delay(k)`` for restart ``k`` is ``base * 2**k`` capped at ``max_s``,
    scaled by ``1 ± jitter`` drawn from a ``random.Random(seed)`` stream —
    the same seed yields the same delay sequence, so a chaos-harness run
    is reproducible end to end (the jitter still decorrelates real fleets,
    whose seeds differ)."""

    def __init__(self, max_restarts: int = 3, base_s: float = 2.0,
                 max_s: float = 60.0, jitter: float = 0.1, seed: int = 0):
        self.max_restarts = max(0, int(max_restarts))
        self.base_s = max(0.0, float(base_s))
        self.max_s = max(0.0, float(max_s))
        self.jitter = min(1.0, max(0.0, float(jitter)))
        self._rng = random.Random(int(seed) ^ 0x5E51)

    def delay(self, restart: int) -> float:
        d = min(self.base_s * (2.0 ** max(0, int(restart))), self.max_s)
        if self.jitter:
            d *= 1.0 + self.jitter * self._rng.uniform(-1.0, 1.0)
        return d


# -- the supervisor ---------------------------------------------------------


class AttemptContext:
    """What one attempt of a mega loop shares with its supervisor.

    The loop publishes ``run_dir`` (as soon as its Experiment exists) and
    ``last_seen_devices``; it reads ``chaos`` (the fault injector, if
    armed), ``restarts``/``recoveries`` (for the restart log line and the
    telemetry fold) and ``device_budget`` via :meth:`mesh_devices`."""

    def __init__(self, chaos=None, device_budget: Optional[int] = None):
        self.chaos = chaos
        self.device_budget = device_budget  # None = all visible devices
        self.attempt = 0
        self.restarts = 0
        self.run_dir: Optional[str] = None
        self.last_seen_devices: Optional[int] = None
        #: the verified-live device OBJECTS from the last re-ramp probe —
        #: identity matters, not just count: slicing jax.devices() to a
        #: count could hand the next mesh the very chip that died
        self.survivor_devices: Optional[list] = None
        #: population size(s) the particle axis must divide over — the
        #: loops publish these before building a mesh so a re-ramp can
        #: only land on a device count the shards actually fit (a
        #: 1M-particle soup on 3 survivors would otherwise turn a
        #: retryable loss into a fatal divisibility error)
        self.shard_sizes: "tuple[int, ...]" = ()
        self.recoveries: List[dict] = []

    def mesh_devices(self, snap: bool = True) -> Optional[list]:
        """Devices the next mesh should ride (None = all visible): the
        verified survivors of the last re-ramp when there are any,
        intersected with what exists now, clamped to the budget, and
        snapped DOWN to a count that divides every published shard size
        — so a stale budget can fail neither ``soup_mesh``'s fail-fast
        check nor the sharded state placement.  ``snap=False`` skips the
        1-D divisor snap: the multislice mesh builder
        (``parallel.reramp_soup_mesh``) applies its own slice-aware snap
        — dropping whole slices before shaving devices — so snapping
        here first could needlessly break a slice boundary."""
        if self.device_budget is None and self.survivor_devices is None:
            return None
        import jax

        visible = jax.devices()
        devs = [d for d in (self.survivor_devices or visible)
                if d in visible] or list(visible)
        if self.device_budget is not None:
            devs = devs[:max(1, min(self.device_budget, len(devs)))]
        if snap:
            n = len(devs)
            while n > 1 and any(s % n for s in self.shard_sizes):
                n -= 1
            devs = devs[:n]
        self.last_seen_devices = len(devs)
        return devs


class Supervisor:
    """Run ``run_once(args, ctx)`` until it finishes, converting retryable
    faults into checkpoint-resume attempts (see module docstring)."""

    def __init__(self, policy: BackoffPolicy, chaos=None,
                 device_budget: Optional[int] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 log: Callable[[str], None] = None):
        self.policy = policy
        self.chaos = chaos
        self.ctx = AttemptContext(chaos=chaos, device_budget=device_budget)
        self._sleep = sleep
        self._log = log or (lambda msg: print(f"supervisor: {msg}",
                                              file=sys.stderr, flush=True))

    # -- device enumeration / topology re-ramp --------------------------

    def _probe_survivors(self) -> "tuple[Optional[int], Optional[list]]":
        """(count, devices) of what survived — the chaos overrides first
        (consumed per event: ``host_loss@G[:H]`` forces the surviving
        device LIST — a whole slice group dropped — while
        ``device_loss@G:S`` simulates shrink by count, the first N
        visible devices standing in for the survivors), then a verifying
        re-enumeration that keeps device IDENTITIES (slicing a count off
        ``jax.devices()`` could re-adopt the dead chip).
        ``(None, None)`` when the backend cannot even be asked."""
        if self.chaos is not None:
            forced_devs = self.chaos.take_forced_survivors()
            if forced_devs is not None:
                return (len(forced_devs) or None), (forced_devs or None)
        forced = self.chaos.take_forced_live() if self.chaos is not None \
            else 0
        if forced:
            try:
                import jax

                return forced, list(jax.devices())[:forced]
            except Exception:
                return forced, None
        try:
            from ..parallel.mesh import probe_devices

            alive = probe_devices(verify=True)
            return (len(alive) or None), (alive or None)
        except Exception:
            return None, None

    def _reramp(self) -> bool:
        """Choose the next attempt's device budget after a device loss.
        Survivors win; a loss with no observed shrink (the fault keeps
        firing on the same topology) degrades by halving, floored at one
        device.  Returns True when the budget changed.  An attempt that
        never built a mesh (unsharded) has nothing to re-ramp — retry
        rides the same single device, and a chip that is truly gone
        exhausts the budget into the process-restart tier."""
        ctx = self.ctx
        prev = ctx.device_budget if ctx.device_budget is not None \
            else ctx.last_seen_devices
        if prev is None:
            return False
        live, survivors = self._probe_survivors()
        if survivors is not None:
            # verified-alive identities win regardless of count — the
            # next mesh must never re-adopt the chip that just died
            ctx.survivor_devices = survivors
        repeat = bool(ctx.recoveries) \
            and ctx.recoveries[-1]["kind"] in (DEVICE_LOSS, HOST_LOSS)
        if live is not None and live < prev:
            new = live
        elif repeat:
            # the loss REPEATS on a topology that still enumerates whole:
            # degrade below it
            new = max(1, int(prev) // 2)
        else:
            # first loss and the probe shows everything alive — a
            # transient blip (tunnel hiccup, resolved goaway): retry on
            # the same topology, halve only when it repeats
            new = prev
        changed = new != prev
        ctx.device_budget = new
        return changed

    # -- the attempt loop ------------------------------------------------

    def _recover(self, kind: str, exc: BaseException, args) -> None:
        ctx = self.ctx
        t0 = time.monotonic()
        self._log(f"attempt {ctx.attempt + 1} failed with {kind} fault: "
                  f"{type(exc).__name__}: {exc}")
        if self.chaos is not None:
            # release any chaos-condemned finisher threads so this
            # attempt's pipeline cannot leak into the next one
            self.chaos.abort_pending()
        reramped = False
        if kind in (DEVICE_LOSS, HOST_LOSS):
            reramped = self._reramp()
            if reramped:
                self._log(f"topology re-ramp: next attempt on "
                          f"{ctx.device_budget} device(s)")
        delay = self.policy.delay(ctx.restarts)
        if delay > 0:
            self._log(f"backing off {delay:.2f}s before restart "
                      f"{ctx.restarts + 1}/{self.policy.max_restarts}")
            self._sleep(delay)
        # recovery IS resume whenever a finalized checkpoint exists —
        # the bit-exact restore path the mega loops already test
        if ctx.run_dir and not getattr(args, "resume", None):
            from ..setups.common import latest_checkpoint

            try:
                latest_checkpoint(ctx.run_dir)
                args.resume = ctx.run_dir
                self._log(f"resuming {ctx.run_dir} from its latest "
                          "finalized checkpoint")
            except FileNotFoundError:
                self._log("no finalized checkpoint yet; retrying from "
                          "scratch (same seed, fresh run dir)")
        ctx.restarts += 1
        ctx.attempt += 1
        ctx.recoveries.append({
            "kind": kind,
            "error": f"{type(exc).__name__}: {exc}",
            "backoff_s": round(delay, 3),
            "reramped": reramped,
            "device_budget": ctx.device_budget,
            "seconds": round(time.monotonic() - t0, 3),
        })

    def report(self, outcome: str) -> dict:
        ctx = self.ctx
        return {
            "outcome": outcome,
            "attempts": ctx.attempt + 1,
            "restarts": ctx.restarts,
            "reramps": sum(1 for r in ctx.recoveries if r["reramped"]),
            "device_budget": ctx.device_budget,
            "run_dir": ctx.run_dir,
            "recoveries": list(ctx.recoveries),
        }

    def run(self, run_once: Callable[[Any, AttemptContext], Any],
            args) -> Any:
        global LAST_REPORT
        LAST_REPORT = None
        ctx = self.ctx
        with _SigtermGuard():
            while True:
                try:
                    out = run_once(args, ctx)
                except BaseException as e:
                    kind = classify_fault(e)
                    if kind == PREEMPT:
                        LAST_REPORT = self.report("preempted")
                        self._log(f"{e} — exiting "
                                  f"{EXIT_PREEMPTED_CLEAN} (preempted-clean)")
                        raise SystemExit(EXIT_PREEMPTED_CLEAN) from e
                    if kind in RETRYABLE and _in_multiprocess_run():
                        # NO in-process restart in a multi-process run —
                        # not just for host loss: a one-sided restart
                        # (an IO fault on one process's writer, a
                        # transient XLA error on one host) would replay
                        # collectives from the checkpoint while peers
                        # block mid-schedule, desynchronizing the gloo
                        # sequence and wedging the whole mesh.  The
                        # process leaves the job (peers' collectives
                        # then fail over to host_loss themselves) and
                        # the launcher tier relaunches the survivors
                        # from the last durable checkpoint.
                        LAST_REPORT = self.report("host-lost")
                        self._log(
                            f"{kind} fault in a multi-process run "
                            f"({type(e).__name__}: {e}) — in-process "
                            "restart would desync the mesh; exiting "
                            f"{EXIT_HOST_LOST} (host-lost) for the "
                            "launcher tier to relaunch")
                        # setups/__main__ converts this to os._exit for
                        # real multi-process workers (the interpreter's
                        # atexit jax shutdown barrier would block on
                        # peers mid-collective and then ABORT, destroying
                        # this code); in-process callers (tests) see the
                        # ordinary SystemExit
                        raise SystemExit(EXIT_HOST_LOST) from e
                    if kind == FATAL or self.policy.max_restarts <= 0:
                        # unsupervised (or unclassifiable) failures keep
                        # their original type — tooling that matches on
                        # StallError/SystemExit sees what it always saw
                        raise
                    if ctx.restarts >= self.policy.max_restarts:
                        traceback.print_exc()
                        LAST_REPORT = self.report("exhausted")
                        self._log(
                            f"{kind} fault after {ctx.restarts} restart(s); "
                            f"retry budget spent — exiting "
                            f"{EXIT_RETRIES_EXHAUSTED} (retries-exhausted)")
                        raise SystemExit(EXIT_RETRIES_EXHAUSTED) from e
                    self._recover(kind, e, args)
                    continue
                LAST_REPORT = self.report(
                    "recovered" if ctx.restarts else "clean")
                if ctx.restarts:
                    self._log(f"run completed after {ctx.restarts} "
                              f"restart(s)")
                return out


def exit_code_for_report(report: Optional[dict]) -> int:
    """CLI exit code for a completed (non-raising) supervised run: 0 for a
    clean pass, :data:`EXIT_RECOVERED` when restarts were needed.  The
    raising outcomes (preempted/exhausted) exit via ``SystemExit`` with
    their codes directly."""
    if report is not None and report.get("outcome") == "recovered":
        return EXIT_RECOVERED
    return 0


def supervised_run(args, run_once: Callable[[Any, AttemptContext], Any]):
    """The mega loops' entry: build the chaos injector + policy from the
    CLI namespace (``setups.common.add_resilience_args``) and run
    ``run_once`` under a :class:`Supervisor`.  Returns the run dir."""
    from .chaos import ChaosMonkey

    chaos = ChaosMonkey.from_args(args)
    policy = BackoffPolicy(
        max_restarts=getattr(args, "max_restarts", 0),
        base_s=getattr(args, "backoff_base_s", 2.0),
        max_s=getattr(args, "backoff_max_s", 60.0),
        jitter=getattr(args, "backoff_jitter", 0.1),
        seed=getattr(args, "seed", 0))
    sup = Supervisor(policy, chaos=chaos,
                     device_budget=getattr(args, "max_devices", 0) or None)
    return sup.run(run_once, args)
