from .dispatch import apply_fn, samples_fn, apply_to_weights, compute_samples

__all__ = ["apply_fn", "samples_fn", "apply_to_weights", "compute_samples"]
