"""Recurrent variant: a SimpleRNN stack consuming the weight vector as a
length-P sequence.

Reference: ``RecurrentNeuralNetwork`` (``network.py:524-574``).  The target's
flat weights become a (T=P, features=1) sequence; the stack (units=width per
layer, final layer units=1, ``return_sequences=True`` everywhere,
``network.py:526-535``) maps it to a new length-P sequence written back
positionally.

TPU-native form: one ``lax.scan`` per RNN layer over the time axis.  The
per-step recurrence is sequential by nature; for long sequences the
context-parallel ring decomposition lives in ``srnn_tpu.parallel.ring_rnn``.
Note keras' SimpleRNN state update is h_t = act(x_t @ K + h_{t-1} @ R) with
no bias here; the reference's ``keras_params`` (activation='linear',
use_bias=False, ``network.py:80``) applies to every layer.
"""

import jax
import jax.numpy as jnp

from ..ops.activations import resolve_activation
from ..ops.flatten import unflatten
from ..ops.linalg import matmul
from ..topology import Topology


def forward(topo: Topology, self_flat: jnp.ndarray, seq: jnp.ndarray) -> jnp.ndarray:
    """Run the stacked RNN over seq (T, 1) -> (T, 1)."""
    if topo.rnn_scan == "associative":
        return _forward_associative(topo, self_flat, seq)
    act = resolve_activation(topo.activation)
    mats = unflatten(topo, self_flat)
    x = seq
    for layer, (_, units) in enumerate(topo.rnn_layer_dims):
        kernel, recurrent = mats[2 * layer], mats[2 * layer + 1]

        def step(h, xt, kernel=kernel, recurrent=recurrent, act=act):
            h_new = act(matmul(topo, xt, kernel) + matmul(topo, h, recurrent))
            return h_new, h_new

        h0 = jnp.zeros((units,), dtype=seq.dtype)
        _, x = jax.lax.scan(step, h0, x)
    return x


def _forward_associative(topo: Topology, self_flat: jnp.ndarray,
                         seq: jnp.ndarray) -> jnp.ndarray:
    """Linear-activation fast path (``Topology.rnn_scan='associative'``).

    With the identity activation the keras SimpleRNN step
    ``h_t = x_t @ K + h_{t-1} @ R`` is an affine map of the hidden state, so
    each layer solves as an ``associative_scan`` over composed affine maps
    ``(A, b): h -> h @ A + b`` in O(log T) depth instead of a length-T
    serial chain — the TPU-native answer to the reference's only inherently
    sequential transform (``network.py:544-564``).  Same math as the serial
    scan up to float reassociation (composition products ``R^k`` are formed
    in a different order).
    """
    assert topo.activation == "linear", "associative scan requires affine recurrence"
    mats = unflatten(topo, self_flat)
    x = seq
    for layer, (_, units) in enumerate(topo.rnn_layer_dims):
        kernel, recurrent = mats[2 * layer], mats[2 * layer + 1]
        t = x.shape[0]
        b = matmul(topo, x, kernel)                          # (T, units)
        a = jnp.broadcast_to(recurrent, (t, units, units))   # (T, units, units)

        def combine(lhs, rhs):
            a1, b1 = lhs
            a2, b2 = rhs
            # (h@A1 + b1)@A2 + b2 = h@(A1@A2) + (b1@A2 + b2)
            return (matmul(topo, a1, a2),
                    matmul(topo, b1[:, None, :], a2)[:, 0, :] + b2)

        # h0 = 0 (keras default), so h_t is just the accumulated offset
        _, x = jax.lax.associative_scan(combine, (a, b))
    return x


def apply(topo: Topology, self_flat: jnp.ndarray, target_flat: jnp.ndarray,
          key=None) -> jnp.ndarray:
    """One predict over the whole weight sequence (``network.py:544-564``)."""
    del key
    return forward(topo, self_flat, target_flat[:, None])[:, 0]


def samples(topo: Topology, flat: jnp.ndarray):
    """x = y = the (1, T, 1) weight sequence (``compute_samples``,
    ``network.py:566-574``)."""
    seq = flat[None, :, None]
    return seq, seq
