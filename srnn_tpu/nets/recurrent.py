"""Recurrent variant: a SimpleRNN stack consuming the weight vector as a
length-P sequence.

Reference: ``RecurrentNeuralNetwork`` (``network.py:524-574``).  The target's
flat weights become a (T=P, features=1) sequence; the stack (units=width per
layer, final layer units=1, ``return_sequences=True`` everywhere,
``network.py:526-535``) maps it to a new length-P sequence written back
positionally.

TPU-native form: one ``lax.scan`` per RNN layer over the time axis.  The
per-step recurrence is sequential by nature; for long sequences the
context-parallel ring decomposition lives in ``srnn_tpu.parallel.ring_rnn``.
Note keras' SimpleRNN state update is h_t = act(x_t @ K + h_{t-1} @ R) with
no bias here; the reference's ``keras_params`` (activation='linear',
use_bias=False, ``network.py:80``) applies to every layer.
"""

import jax
import jax.numpy as jnp

from ..ops.activations import resolve_activation
from ..ops.flatten import unflatten
from ..ops.linalg import matmul
from ..topology import Topology


def forward(topo: Topology, self_flat: jnp.ndarray, seq: jnp.ndarray) -> jnp.ndarray:
    """Run the stacked RNN over seq (T, 1) -> (T, 1)."""
    act = resolve_activation(topo.activation)
    mats = unflatten(topo, self_flat)
    x = seq
    for layer, (_, units) in enumerate(topo.rnn_layer_dims):
        kernel, recurrent = mats[2 * layer], mats[2 * layer + 1]

        def step(h, xt, kernel=kernel, recurrent=recurrent, act=act):
            h_new = act(matmul(topo, xt, kernel) + matmul(topo, h, recurrent))
            return h_new, h_new

        h0 = jnp.zeros((units,), dtype=seq.dtype)
        _, x = jax.lax.scan(step, h0, x)
    return x


def apply(topo: Topology, self_flat: jnp.ndarray, target_flat: jnp.ndarray,
          key=None) -> jnp.ndarray:
    """One predict over the whole weight sequence (``network.py:544-564``)."""
    del key
    return forward(topo, self_flat, target_flat[:, None])[:, 0]


def samples(topo: Topology, flat: jnp.ndarray):
    """x = y = the (1, T, 1) weight sequence (``compute_samples``,
    ``network.py:566-574``)."""
    seq = flat[None, :, None]
    return seq, seq
