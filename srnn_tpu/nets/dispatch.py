"""Variant dispatch: map a Topology to its pure transform functions.

All variants share the same functional surface:

  ``apply_to_weights(topo, self_flat, target_flat, key=None) -> new_target``
      the self-application operator (reference ``apply_to_weights``,
      dispatched per class at ``network.py:265/359/494/544``).

  ``compute_samples(topo, flat) -> (x, y)``
      the self-training data (reference ``compute_samples`` per class).

Dispatch happens on the static ``topo.variant`` string at trace time, so jit
sees a single fused computation per topology.
"""

from .. import topology as _topology
from . import aggregating, fft, recurrent, weightwise

_MODULES = {
    "weightwise": weightwise,
    "aggregating": aggregating,
    "fft": fft,
    "recurrent": recurrent,
}


def apply_fn(topo: "_topology.Topology"):
    return _MODULES[topo.variant].apply


def samples_fn(topo: "_topology.Topology"):
    return _MODULES[topo.variant].samples


def apply_to_weights(topo, self_flat, target_flat, key=None):
    return _MODULES[topo.variant].apply(topo, self_flat, target_flat, key)


def compute_samples(topo, flat):
    return _MODULES[topo.variant].samples(topo, flat)
