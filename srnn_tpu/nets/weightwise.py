"""Weightwise variant: an MLP f: R^4 -> R^1 applied once per scalar weight.

Reference: ``WeightwiseNeuralNetwork`` (``network.py:213-289``).  There, each
weight produces a point ``[w, layer_id, cell_id, weight_id]`` (ids normalized
per ``normalize_id``) and is rewritten by **one ``model.predict`` call per
scalar** — the dominant cost of the whole reference codebase (SURVEY §3.1).

TPU-native form: the (P, 3) normalized-coordinate table is a trace-time
constant; self-application is ONE batched forward over all P points, which
vmaps across particles into a single ``(N*P, 4) @ ...`` matmul chain on the
MXU.
"""

import jax.numpy as jnp

from ..ops.mlp import mlp_forward
from ..topology import Topology, normalized_weight_coords


def forward(topo: Topology, self_flat: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """Batched MLP forward: x (..., 4) -> (..., 1)."""
    return mlp_forward(topo, self_flat, x)


def points(topo: Topology, target_flat: jnp.ndarray) -> jnp.ndarray:
    """Normalized duplex weight points (P, 4): [w, layer, cell, weight].

    Matches ``compute_all_duplex_weight_points`` (``network.py:239-255``).
    """
    coords = jnp.asarray(normalized_weight_coords(topo), dtype=target_flat.dtype)
    return jnp.concatenate([target_flat[:, None], coords], axis=1)


def apply(topo: Topology, self_flat: jnp.ndarray, target_flat: jnp.ndarray,
          key=None) -> jnp.ndarray:
    """Self-application: rewrite every target weight via the net.

    Equivalent of ``apply_to_weights`` (``network.py:265-279``) minus the
    per-scalar predict loop.
    """
    del key
    return forward(topo, self_flat, points(topo, target_flat))[:, 0]


def samples(topo: Topology, flat: jnp.ndarray):
    """Training pairs: x = all normalized points, y = current weights.

    ``compute_samples`` (``network.py:281-289``) — regressing your own
    weights is "learn to be a fixpoint".
    """
    x = points(topo, flat)
    return x, flat
