"""Aggregating variant: an MLP f: R^k -> R^k over k weight aggregates.

Reference: ``AggregatingNeuralNetwork`` (``network.py:292-439``).  The P
weights are chunked (in flat enumeration order) into k collections of
``P // k`` elements, trailing leftovers appended to the LAST collection
(``collect_weights``, ``network.py:388-403``); each collection is reduced to
one aggregate (default: average), the k-vector goes through the net once, and
each output aggregate is replicated back over its collection
(``deaggregate_identically``, ``network.py:310-312``).

TPU-native form: the segment structure is a constant one-hot matrix, so
collect = one matmul, deaggregate = its transpose — no gathers in the hot
path and everything fuses into the MLP matmul chain.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.linalg import matmul
from ..ops.mlp import mlp_forward
from ..topology import Topology, aggregation_segments


@functools.lru_cache(maxsize=None)
def _segment_onehot(topo: Topology) -> np.ndarray:
    """(P, k) one-hot membership matrix in float32."""
    seg, _ = aggregation_segments(topo)
    k = topo.aggregates
    return np.eye(k, dtype=np.float32)[seg]


def aggregate(topo: Topology, target_flat: jnp.ndarray) -> jnp.ndarray:
    """Reduce (P,) weights -> (k,) aggregates under ``topo.aggregator``."""
    seg, counts = aggregation_segments(topo)
    if topo.aggregator == "average":
        onehot = jnp.asarray(_segment_onehot(topo), dtype=target_flat.dtype)
        return matmul(topo, target_flat, onehot) / jnp.asarray(counts, dtype=target_flat.dtype)
    if topo.aggregator == "max":
        # deliberate fix of the reference's falsy-max quirk (network.py:303-308)
        return jax.ops.segment_max(
            target_flat, jnp.asarray(seg), num_segments=topo.aggregates,
            indices_are_sorted=True)
    if topo.aggregator == "max_buggy":
        # bit-faithful replication of ``aggregate_max``: a candidate only
        # replaces the running max when it is greater AND truthy (!= 0.0),
        # so a positive max of exactly 0.0 can never win (network.py:303-308).
        seg_arr = jnp.asarray(seg)
        starts = jnp.asarray(
            np.searchsorted(seg, np.arange(topo.aggregates)), dtype=jnp.int32)
        init = target_flat[starts]

        def step(m, wi):
            w, s = wi
            cand = m[s]
            new = jnp.where((w > cand) & (w != 0.0), w, cand)
            return m.at[s].set(new), None

        out, _ = jax.lax.scan(step, init, (target_flat, seg_arr))
        return out
    raise ValueError(f"unknown aggregator {topo.aggregator!r}")


def forward(topo: Topology, self_flat: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    """MLP forward (..., k) -> (..., k)."""
    return mlp_forward(topo, self_flat, x)


def deaggregate(topo: Topology, aggs: jnp.ndarray, key=None) -> jnp.ndarray:
    """Replicate (k,) aggregates back over their collections -> (P,).

    With ``topo.shuffler == 'random'`` the replicated list is permuted, the
    functional analog of ``shuffle_random`` (``network.py:318-322``); a PRNG
    key is then required.
    """
    onehot = jnp.asarray(_segment_onehot(topo), dtype=aggs.dtype)
    flat = matmul(topo, onehot, aggs)
    if topo.shuffler == "random":
        if key is None:
            raise ValueError("shuffler='random' requires a PRNG key")
        flat = jax.random.permutation(key, flat)
    elif topo.shuffler != "not":
        raise ValueError(f"unknown shuffler {topo.shuffler!r}")
    return flat


def apply(topo: Topology, self_flat: jnp.ndarray, target_flat: jnp.ndarray,
          key=None) -> jnp.ndarray:
    """collect -> aggregate -> one forward -> deaggregate -> write back.

    Equivalent of ``apply_to_weights`` (``network.py:359-386``).
    """
    aggs = aggregate(topo, target_flat)
    new_aggs = forward(topo, self_flat, aggs[None, :])[0]
    return deaggregate(topo, new_aggs, key)


def samples(topo: Topology, flat: jnp.ndarray):
    """x = y = the (1, k) aggregate vector (``compute_samples``,
    ``network.py:414-417``): self-training seeks a fixpoint in aggregate
    space."""
    aggs = aggregate(topo, flat)[None, :]
    return aggs, aggs


def is_fixpoint_after_aggregation(
    topo: Topology, flat: jnp.ndarray, degree: int = 1, epsilon: float = 1e-4,
    key=None,
):
    """Fixpoint test in aggregate space (``network.py:419-439``).

    Returns ``(ok, new_aggregations)`` where ok is False on divergence —
    unlike the reference, the return type is uniform (quirk §2.4.4 fixed).
    """
    old_aggs = aggregate(topo, flat)
    new = flat
    keys = [None] * degree if key is None else list(jax.random.split(key, degree))
    for k in keys:
        new = apply(topo, flat, new, k)
    new_aggs = aggregate(topo, new)
    diverged = jnp.any(~jnp.isfinite(new))
    close = jnp.all(jnp.abs(new_aggs - old_aggs) < epsilon)
    return ~diverged & close, new_aggs
