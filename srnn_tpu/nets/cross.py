"""Cross-architecture self-application: any attacker variant vs any victim.

The reference's ``attack(other)`` (``network.py:116-118``) is only ever
exercised between same-class nets (soups are homogeneous; ``mixed-soup.py``
runs separate soups per class).  But the operator itself is well-defined for
ANY victim: the weightwise transform rewrites *per scalar weight of the
victim* from the victim's own coordinates, the aggregating transform chunks
*whatever weight count the victim has* into the attacker's k collections,
the FFT transform inverse-expands to the victim's length, and the recurrent
transform consumes the victim's weights as a sequence of arbitrary length.

This module generalizes each transform to (attacker topology, victim
topology) pairs, enabling heterogeneous soups (``srnn_tpu.multisoup``) —
the EP-style mixed-population capability SURVEY §2.5 maps to expert-
parallel grouping.

``cross_apply(t, a, t, v)`` with equal topologies is exactly
``apply_to_weights(t, a, v)``; tests assert that.
"""

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.linalg import matmul
from ..topology import Topology, segments_for
from . import fft as fft_mod
from . import recurrent as rnn_mod
from . import weightwise as ww_mod


def _cross_aggregate(attacker: Topology, victim_flat: jnp.ndarray) -> jnp.ndarray:
    """Chunk the victim's weights into the ATTACKER's k collections
    (reference ``collect_weights`` rule applied to the victim's count)."""
    p = victim_flat.shape[0]
    seg, counts = segments_for(p, attacker.aggregates)
    onehot = jnp.asarray(np.eye(attacker.aggregates, dtype=np.float32)[seg],
                         dtype=victim_flat.dtype)
    if attacker.aggregator == "average":
        return matmul(attacker, victim_flat, onehot) / jnp.asarray(
            counts, dtype=victim_flat.dtype)
    if attacker.aggregator in ("max", "max_buggy"):
        # cross-shape max: the real max; the falsy-max quirk is only
        # reproduced for same-topology application (aggregating.apply)
        return jax.ops.segment_max(victim_flat, jnp.asarray(seg),
                                   num_segments=attacker.aggregates,
                                   indices_are_sorted=True)
    raise ValueError(f"unknown aggregator {attacker.aggregator!r}")


def _cross_deaggregate(attacker: Topology, aggs: jnp.ndarray, p: int,
                       key=None) -> jnp.ndarray:
    seg, _ = segments_for(p, attacker.aggregates)
    flat = aggs[jnp.asarray(seg)]
    if attacker.shuffler == "random":
        if key is None:
            raise ValueError("shuffler='random' requires a PRNG key")
        flat = jax.random.permutation(key, flat)
    return flat


def cross_apply(attacker: Topology, attacker_flat: jnp.ndarray,
                victim: Topology, victim_flat: jnp.ndarray,
                key=None) -> jnp.ndarray:
    """Apply the attacker's transform to the victim's weights; returns the
    victim's new flat vector (same length as ``victim_flat``)."""
    if attacker.variant == "weightwise":
        # victim's coordinate table, attacker's MLP
        pts = ww_mod.points(victim, victim_flat)
        return ww_mod.forward(attacker, attacker_flat, pts)[:, 0]
    if attacker.variant == "aggregating":
        aggs = _cross_aggregate(attacker, victim_flat)
        new_aggs = ww_mod.forward(attacker, attacker_flat, aggs[None, :])[0]
        return _cross_deaggregate(attacker, new_aggs, victim_flat.shape[0], key)
    if attacker.variant == "fft":
        src = victim_flat if attacker.fft_use_target else attacker_flat
        coeffs = jnp.fft.fft(src, n=attacker.aggregates).real.astype(
            victim_flat.dtype)
        new_coeffs = fft_mod.forward(attacker, attacker_flat, coeffs[None, :])[0]
        out = jnp.fft.ifft(new_coeffs, n=victim_flat.shape[0]).real.astype(
            victim_flat.dtype)
        if attacker.shuffler == "random":
            if key is None:
                raise ValueError("shuffler='random' requires a PRNG key")
            out = jax.random.permutation(key, out)
        return out
    if attacker.variant == "recurrent":
        return rnn_mod.forward(attacker, attacker_flat, victim_flat[:, None])[:, 0]
    raise ValueError(f"unknown variant {attacker.variant!r}")
