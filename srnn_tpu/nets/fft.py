"""FFT variant: an MLP f: R^k -> R^k over truncated Fourier coefficients.

Reference: ``FFTNeuralNetwork`` (``network.py:442-521``).  Semantics tracked
deliberately (SURVEY §2.4.2):

  * The transform FFTs the net's **own current** flat weights — the
    ``old_weights`` argument is ignored for the input (``network.py:494-499``)
    — so ``attack(other)`` writes self-derived values into the victim.  We
    keep that as the default (``topo.fft_use_target=False``) and offer the
    fixed behavior behind the flag.
  * keras ``predict`` on a complex array casts to float32, silently dropping
    the imaginary part; likewise ``ifftn`` output written back into float32
    weight arrays keeps only the real part (``network.py:503-508``).  We make
    both casts explicit (``.real``).

The forward FFT truncates to k coefficients (``np.fft.fftn(flat, k)``); the
inverse expands back to P samples (``np.fft.ifftn(agg, P)``), i.e. a
low-pass reconstruction of the weight vector.
"""

import jax
import jax.numpy as jnp

from ..ops.mlp import mlp_forward
from ..topology import Topology


def coefficients(topo: Topology, flat: jnp.ndarray) -> jnp.ndarray:
    """Real parts of the first k DFT coefficients (``aggregate_fft``,
    ``network.py:444-448`` + the keras complex->float32 cast).

    ``fft_mode='rfft'`` uses the real-input transform instead — the EP
    prototype's alternative reduction (``related/EP/src/FeatureReduction.py``);
    the first k rfft bins (zero-padded if the spectrum is shorter than k).
    """
    if topo.fft_mode == "rfft":
        spec = jnp.fft.rfft(flat).real.astype(flat.dtype)
        k = topo.aggregates
        n = spec.shape[-1]
        if n >= k:
            return spec[..., :k]
        return jnp.pad(spec, [(0, 0)] * (spec.ndim - 1) + [(0, k - n)])
    return jnp.fft.fft(flat, n=topo.aggregates).real.astype(flat.dtype)


def forward(topo: Topology, self_flat: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    return mlp_forward(topo, self_flat, x)


def apply(topo: Topology, self_flat: jnp.ndarray, target_flat: jnp.ndarray,
          key=None) -> jnp.ndarray:
    """FFT -> one forward over k coefficients -> inverse FFT to P weights.

    Equivalent of ``apply_to_weights`` (``network.py:494-516``).
    """
    src = target_flat if topo.fft_use_target else self_flat
    coeffs = coefficients(topo, src)
    new_coeffs = forward(topo, self_flat, coeffs[None, :])[0]
    if topo.fft_mode == "rfft":
        new_flat = jnp.fft.irfft(new_coeffs, n=topo.num_weights).astype(target_flat.dtype)
    else:
        new_flat = jnp.fft.ifft(new_coeffs, n=topo.num_weights).real.astype(target_flat.dtype)
    if topo.shuffler == "random":
        if key is None:
            raise ValueError("shuffler='random' requires a PRNG key")
        new_flat = jax.random.permutation(key, new_flat)
    return new_flat


def samples(topo: Topology, flat: jnp.ndarray):
    """x = y = the (1, k) coefficient vector.

    Deliberate deviation: the reference's ``compute_samples``
    (``network.py:518-521``) builds ``np.asarray(list_of_ragged_kernels)``,
    which produces an object array that keras cannot fit — dead-on-arrival
    code (the repo's own ``fixpoint-density.py:34-35`` notes "FFT doesn't
    work though").  Training on the coefficient vector is the consistent
    analog of the aggregating variant's aggregate-space self-training.
    """
    coeffs = coefficients(topo, flat)[None, :]
    return coeffs, coeffs
