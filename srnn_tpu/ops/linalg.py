"""Precision-aware matmul used by every variant forward.

Tiny self-replicating nets operate at epsilon=1e-4 fixpoint resolution
(reference overrides, e.g. ``training-fixpoints.py:38``); default TPU bf16
matmul passes introduce ~3e-3 error at unit scale, which would flip fixpoint
predicates.  All transforms therefore default to f32 accumulation
(``Topology.precision='highest'``).
"""

import jax.lax
import jax.numpy as jnp

_PRECISIONS = {
    "default": jax.lax.Precision.DEFAULT,
    "high": jax.lax.Precision.HIGH,
    "highest": jax.lax.Precision.HIGHEST,
}


def matmul(topo, a: jnp.ndarray, b: jnp.ndarray) -> jnp.ndarray:
    return jnp.matmul(a, b, precision=_PRECISIONS[topo.precision])
