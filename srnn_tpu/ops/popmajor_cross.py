"""Population-major cross-architecture attacks (the lane-layout twin of
``nets.cross.cross_apply``).

Heterogeneous soups (``srnn_tpu.multisoup``) apply ANY attacker variant's
transform to ANY victim type's weights.  In the lane layout the victim
population is a (P_vic, N) matrix and the attacker parameters arrive as a
(P_att, N) column-gathered matrix (attacker n rewrites victim n), so each
(attacker-variant, victim-shape) pair lowers to the same per-lane math as
the homogeneous kernels — only the shape constants (the victim's coordinate
table, segment chunking of the victim's weight count, the inverse-DFT
length) come from the victim side, mirroring ``nets/cross.py`` decision for
decision:

  * weightwise: the VICTIM's normalized duplex coordinates, the attacker's
    MLP (``cross.py`` weightwise arm);
  * aggregating: the victim's weight count chunked into the attacker's k
    collections; cross-shape max is the REAL max (the falsy-max quirk is
    same-topology-only); deaggregate is the row-gather replication;
  * fft: always the plain DFT (the cross path ignores ``fft_mode``), source
    = attacker's own weights unless ``fft_use_target``;
  * recurrent: the victim's weights as the input sequence, any length.

``shuffler='random'`` stays row-major-only (per-lane permutation — same
fence as the homogeneous popmajor layout).
"""

import jax.numpy as jnp
import numpy as np

from ..topology import Topology, normalized_weight_coords, segments_for
from .activations import resolve_activation
from .linalg import matmul
from .popmajor_kvec import _mlp_forward_lanes


def _check_lane_capable(att: Topology) -> None:
    if att.shuffler == "random":
        raise ValueError(
            "shuffler='random' is a per-lane permutation — use the "
            "row-major multisoup layout")


def _ww_cross(att: Topology, selfT: jnp.ndarray, vic: Topology,
              targetT: jnp.ndarray) -> jnp.ndarray:
    """Attacker's weightwise MLP over the victim's duplex points: input
    features per victim weight p are [w_p, victim-layer, -cell, -weight]
    (victim's own coordinate table, ``cross.py`` weightwise arm)."""
    coords = normalized_weight_coords(vic)
    act = resolve_activation(att.activation)
    p, n = targetT.shape
    h = [targetT] + [
        jnp.broadcast_to(jnp.asarray(coords[:, k][:, None], targetT.dtype),
                         (p, n))
        for k in range(3)
    ]
    for (a, b), o in zip(att.layer_shapes, att.offsets):
        nxt = []
        for j in range(b):
            acc = h[0] * selfT[o + j, :]
            for i in range(1, a):
                acc = acc + h[i] * selfT[o + i * b + j, :]
            nxt.append(act(acc))
        h = nxt
    return h[0]


def _agg_cross(att: Topology, selfT: jnp.ndarray,
               targetT: jnp.ndarray) -> jnp.ndarray:
    p = targetT.shape[0]
    seg, counts = segments_for(p, att.aggregates)
    if att.aggregator == "average":
        onehotT = jnp.asarray(
            np.eye(att.aggregates, dtype=np.float32)[seg].T, targetT.dtype)
        aggs = matmul(att, onehotT, targetT) / jnp.asarray(
            counts, targetT.dtype)[:, None]
    elif att.aggregator in ("max", "max_buggy"):
        # cross-shape max is the real max (nets/cross.py:42-47)
        starts = np.searchsorted(seg, np.arange(att.aggregates))
        ends = starts + counts
        aggs = jnp.stack([jnp.max(targetT[s:e], axis=0)
                          for s, e in zip(starts, ends)])
    else:
        raise ValueError(f"unknown aggregator {att.aggregator!r}")
    new_aggs = _mlp_forward_lanes(att, selfT, aggs)
    # replication by row gather (cross_deaggregate, nets/cross.py:51-59)
    return new_aggs[jnp.asarray(seg)]


def _fft_cross(att: Topology, selfT: jnp.ndarray,
               targetT: jnp.ndarray) -> jnp.ndarray:
    src = targetT if att.fft_use_target else selfT
    coeffs = jnp.fft.fft(src, n=att.aggregates, axis=0).real.astype(
        targetT.dtype)
    new_coeffs = _mlp_forward_lanes(att, selfT, coeffs)
    return jnp.fft.ifft(new_coeffs, n=targetT.shape[0], axis=0).real.astype(
        targetT.dtype)


def cross_apply_popmajor(att: Topology, selfT: jnp.ndarray, vic: Topology,
                         targetT: jnp.ndarray,
                         impl: str = "xla") -> jnp.ndarray:
    """Lane-layout ``cross_apply``: attacker n (parameters ``selfT[:, n]``,
    shape (P_att, N)) rewrites victim n (``targetT[:, n]``, shape
    (P_vic, N)).  Returns the victims' new (P_vic, N) weights.

    ``impl='pallas'`` routes a recurrent ATTACKER's serial forward to the
    fused VMEM kernel (cross-shape capable — the sequence length is the
    victim's weight count); other attacker variants fall back to the XLA
    lane programs, mirroring the per-type train dispatch."""
    _check_lane_capable(att)
    if att.variant == "weightwise":
        return _ww_cross(att, selfT, vic, targetT)
    if att.variant == "aggregating":
        return _agg_cross(att, selfT, targetT)
    if att.variant == "fft":
        return _fft_cross(att, selfT, targetT)
    if att.variant == "recurrent":
        # one dispatch for homogeneous and cross attacks (the recurrent
        # transform is shape-generic: T = the victim's weight count)
        from .popmajor import apply_popmajor

        return apply_popmajor(att, selfT, targetT, impl=impl)
    raise ValueError(f"unknown variant {att.variant!r}")
