"""Fused Pallas TPU kernel for k-vector-variant batch-1 SGD
(aggregating / fft).

Round-5 TPU train-phase decomposition (RESULTS.md): the aggregating and
fft variants' XLA train paths run at 2.4x / 2.9x the fused weightwise
kernel's per-particle cost — each scan(epochs) step round-trips the (P, N)
population through HBM for a gradient whose arithmetic is a few dozen
lane-elementwise FMAs.  This kernel fuses the whole multi-epoch chain in
VMEM per lane block, like its weightwise and recurrent siblings.

Semantics mirror ``ops/popmajor_kvec`` (reference ``network.py:414-417`` /
``:518-521``): ONE sample per epoch (x = y = the particle's k-aggregate /
DFT-coefficient vector), so each reference batch-1 epoch is a single
full-batch gradient step; self-training re-reduces x from the current
weights at each epoch top, imitation keeps x fixed at the counterpart's
reduction.  Gradients do not flow through the reduction (the XLA path
stop-gradients the sample — keras regenerates x outside the graph).

The reductions become trace-time-constant lane arithmetic:

  * aggregating 'average': per-segment add chains scaled by 1/count
    (reference ``collect_weights`` leftover rule, ``network.py:388-403``);
    'max' / 'max_buggy' are the same comparison chains as the popmajor
    path (including the falsy-max quirk, ``network.py:303-308``);
  * fft: the truncated real-part DFT is a (k, P) cosine-basis constant
    matrix applied as per-row multiply-add chains — the same
    real-arithmetic decomposition ``parallel/sharded_apply.py`` uses (the
    imaginary parts are discarded by the reference's float cast, so only
    the cos basis survives; ``network.py:444-448``).

The MLP backward is the hand-derived chain shared with the weightwise
kernel (act' from stored post-activations,
``activations.resolve_output_grad``); the epoch loop is a
``lax.fori_loop`` (Mosaic's loop-lowering requirement).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..topology import Topology, aggregation_segments
from .activations import resolve_activation, resolve_output_grad
from .pallas_sgd_common import lane_call, make_learn_kernel, make_train_kernel


@functools.lru_cache(maxsize=None)
def _dft_cos_rows(topo: Topology):
    """(k, P) real cosine-basis rows of the variant's truncated DFT, as a
    tuple-of-tuples of Python floats (trace-time constants).

    fft_mode='fft': ``fft(x, n=k)`` crops/pads x to length k, so bin j
    reads only the first min(k, P) weights with basis cos(2*pi*j*m/k).
    fft_mode='rfft': bins are the first k of the full-length real FFT,
    basis cos(2*pi*j*m/P); bins beyond P//2+1 are zero (the popmajor
    path's explicit pad)."""
    assert topo.variant == "fft"
    p, k = topo.num_weights, topo.aggregates
    rows = np.zeros((k, p), dtype=np.float64)
    if topo.fft_mode == "fft":
        for j in range(k):
            for m in range(min(k, p)):
                rows[j, m] = np.cos(2.0 * np.pi * j * m / k)
    else:
        n_bins = p // 2 + 1
        for j in range(min(k, n_bins)):
            for m in range(p):
                rows[j, m] = np.cos(2.0 * np.pi * j * m / p)
    return tuple(tuple(float(v) for v in r) for r in rows)


def _reduce_rows(topo: Topology, rows):
    """P lane-vector rows -> k lane-vector aggregates (the kernel-side twin
    of ``popmajor_kvec.kvec_reduce_popmajor``)."""
    if topo.variant == "fft":
        out = []
        for coeffs in _dft_cos_rows(topo):
            acc = None
            for m, c in enumerate(coeffs):
                if c == 0.0:
                    continue
                term = rows[m] if c == 1.0 else rows[m] * c
                acc = term if acc is None else acc + term
            out.append(acc if acc is not None
                       else jnp.zeros_like(rows[0]))
        return out
    assert topo.variant == "aggregating"
    from .popmajor_kvec import _segment_bounds

    _, counts = aggregation_segments(topo)
    starts, ends = _segment_bounds(topo)
    out = []
    # matmul-equivalence for 'average': the XLA path's one-hot matmul
    # (kvec_reduce_popmajor) carries a 0.0-weighted term for every
    # OUT-of-segment row, so a non-finite weight elsewhere poisons an
    # aggregate (0*Inf = NaN) while a non-finite weight in its OWN
    # segment enters at full value (Inf stays Inf).  Segments are
    # contiguous, so the exact exclusion sums come from prefix/suffix
    # chains of the 0.0-weighted rows at O(P) total — NOT one shared
    # all-rows poison term, which would wrongly NaN the home segment of
    # an Inf weight (round-5 review repro: XLA [inf, nan, nan, nan] vs
    # shared-poison [nan, nan, nan, nan]).
    zpre = zsuf = None
    if topo.aggregator == "average":
        p_rows = len(rows)
        zero = jnp.zeros_like(rows[0])
        zpre = [zero]
        for r in range(p_rows):
            zpre.append(zpre[-1] + rows[r] * 0.0)
        zsuf = [zero]
        for r in range(p_rows - 1, -1, -1):
            zsuf.append(zsuf[-1] + rows[r] * 0.0)
        zsuf = zsuf[::-1]  # zsuf[i] = sum of 0*rows[i:]
    for s, e, c in zip(starts, ends, counts):
        s, e = int(s), int(e)
        if topo.aggregator == "average":
            acc = rows[s]
            for r in range(s + 1, e):
                acc = acc + rows[r]
            out.append((acc + zpre[s] + zsuf[e]) * (1.0 / float(c)))
        elif topo.aggregator == "max":
            acc = rows[s]
            for r in range(s + 1, e):
                acc = jnp.maximum(acc, rows[r])
            out.append(acc)
        else:  # max_buggy: bit-faithful falsy-max (network.py:303-308)
            acc = rows[s]
            for r in range(s + 1, e):
                w = rows[r]
                acc = jnp.where((w > acc) & (w != 0.0), w, acc)
            out.append(acc)
    return out


def _sgd_epochs(topo: Topology, rows0, snap_xk, epochs: int, lr: float,
                refresh: bool):
    """``epochs`` full-batch MSE-SGD steps on the k-vector sample."""
    p = topo.num_weights
    k = topo.aggregates
    shapes = topo.layer_shapes
    offs = topo.offsets
    act = resolve_activation(topo.activation)
    act_grad = resolve_output_grad(topo.activation)

    def epoch(e, carry):
        rows, _ = carry
        xk = _reduce_rows(topo, rows) if refresh else snap_xk
        # forward, storing post-activations for the backward
        acts = [xk]
        h = xk
        for (a, b), o in zip(shapes, offs):
            nxt = []
            for j in range(b):
                acc = h[0] * rows[o + j]
                for i in range(1, a):
                    acc = acc + h[i] * rows[o + i * b + j]
                nxt.append(act(acc))
            acts.append(nxt)
            h = nxt
        err = [h[j] - xk[j] for j in range(k)]
        loss = err[0] * err[0]
        for j in range(1, k):
            loss = loss + err[j] * err[j]
        loss = loss / k
        # backward
        dh = [err[j] * (2.0 / k) for j in range(k)]
        grads = [None] * p
        for li in range(len(shapes) - 1, -1, -1):
            a, b = shapes[li]
            o = offs[li]
            prev = acts[li]
            if act_grad is not None:
                dh = [dh[j] * act_grad(acts[li + 1][j]) for j in range(b)]
            dprev = []
            for i in range(a):
                acc = dh[0] * rows[o + i * b + 0]
                for j in range(1, b):
                    acc = acc + dh[j] * rows[o + i * b + j]
                dprev.append(acc)
                for j in range(b):
                    grads[o + i * b + j] = dh[j] * prev[i]
            dh = dprev
        new_rows = tuple(rows[r] - lr * grads[r] for r in range(p))
        return new_rows, loss

    return jax.lax.fori_loop(0, epochs, epoch,
                             (rows0, jnp.zeros_like(rows0[0])))


_train_kernel = make_train_kernel(_sgd_epochs)
_learn_kernel = make_learn_kernel(_sgd_epochs, snap_fn=_reduce_rows)


def _supported(topo: Topology) -> None:
    assert topo.variant in ("aggregating", "fft")
    resolve_output_grad(topo.activation)  # raises for unsupported


@functools.partial(jax.jit,
                   static_argnames=("topo", "epochs", "lr", "interpret"))
def kvec_train_epochs_pallas(topo: Topology, wT: jnp.ndarray, epochs: int,
                             lr: float = 0.01, interpret: bool = False):
    """``epochs`` of self-training SGD, the entire chain fused in VMEM per
    lane block.  Same semantics as
    ``ops.popmajor_kvec.kvec_train_epochs_popmajor``.
    Returns (new_wT, last epoch per-particle loss (N,))."""
    _supported(topo)
    return lane_call(_train_kernel, topo, [wT], epochs, lr, interpret)


@functools.partial(jax.jit,
                   static_argnames=("topo", "severity", "lr", "interpret"))
def kvec_learn_epochs_pallas(topo: Topology, wT: jnp.ndarray,
                             otherT: jnp.ndarray, severity: int,
                             lr: float = 0.01, interpret: bool = False):
    """``severity`` imitation epochs toward the counterparts' (fixed)
    k-vector sample, fused in VMEM.  Same semantics as
    ``ops.popmajor_kvec.kvec_learn_epochs_popmajor``."""
    _supported(topo)
    return lane_call(_learn_kernel, topo, [wT, otherT], severity, lr,
                     interpret)
