"""Population-major (P, N) lane layout for the k-vector variants
(aggregating / fft).

Same rationale as the weightwise twin (``ops/popmajor.py``): row-major
``vmap`` leaves per-particle tensors whose minor dims (k ~ 4, w ~ 2) waste
the (8, 128) vector tiles, while the transposed layout puts the particle
axis on the 128-wide lanes and turns every per-particle op into an
elementwise op over lanes.  What is new here is the reduce/expand pair
around the tiny MLP:

  * aggregating: collect = one (k, P) constant matmul over the lane matrix
    (reference ``collect_weights``, ``network.py:388-403``), deaggregate =
    its (P, k) transpose (``deaggregate_identically``, ``network.py:310-312``)
    — both MXU-trivial and bitwise-equal to the row-major path's matmuls;
  * fft: the truncated DFT rides ``jnp.fft`` along axis 0 of the (P, N)
    matrix — one batched FFT for the whole population instead of N vmapped
    ones (reference ``aggregate_fft``, ``network.py:444-448``).

Self-training for these variants has exactly ONE sample per epoch (x = y =
the k-aggregate vector, ``network.py:414-417``/``:518-521``), so the
reference's batch_size=1 epoch (``network.py:613-617``) IS a single
full-batch step — sequential and full_batch modes coincide and the
multi-epoch driver is a plain scan(epochs){grad}, no flattened sample nest
needed.

``shuffler='random'`` stays row-major-only: a per-particle permutation of
the P axis is a per-lane gather that defeats the lane layout (fenced in
``soup._check_popmajor``).
"""

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..topology import Topology, aggregation_segments
from .activations import resolve_activation
from .linalg import matmul

DEFAULT_LR = 0.01  # keras SGD default (mirrors train.DEFAULT_LR)


@functools.lru_cache(maxsize=None)
def _segment_onehot(topo: Topology) -> np.ndarray:
    """(P, k) one-hot membership matrix (same construction as
    ``nets.aggregating._segment_onehot``; cached per topology)."""
    seg, _ = aggregation_segments(topo)
    return np.eye(topo.aggregates, dtype=np.float32)[seg]


def _mlp_forward_lanes(topo: Topology, wT: jnp.ndarray,
                       xk: jnp.ndarray) -> jnp.ndarray:
    """The variant's tiny MLP with per-lane parameters: ``wT`` (P, N) holds
    each particle's flat weights, ``xk`` (k, N) each particle's input
    vector.  Keras kernel order: flat index o + i*b + j = kernel[i, j]
    (fan_in i, fan_out j), so out_j = act(sum_i x_i * w[o + i*b + j]).
    Returns (k, N)."""
    act = resolve_activation(topo.activation)
    h = [xk[i] for i in range(xk.shape[0])]
    for (a, b), o in zip(topo.layer_shapes, topo.offsets):
        nxt = []
        for j in range(b):
            acc = h[0] * wT[o + j, :]
            for i in range(1, a):
                acc = acc + h[i] * wT[o + i * b + j, :]
            nxt.append(act(acc))
        h = nxt
    return jnp.stack(h)


def _segment_bounds(topo: Topology):
    seg, counts = aggregation_segments(topo)
    starts = np.searchsorted(seg, np.arange(topo.aggregates))
    ends = starts + counts
    return starts, ends


def kvec_reduce_popmajor(topo: Topology, targetT: jnp.ndarray) -> jnp.ndarray:
    """(P, N) weights -> (k, N) aggregates / DFT coefficients, per variant."""
    if topo.variant == "fft":
        if topo.fft_mode == "rfft":
            spec = jnp.fft.rfft(targetT, axis=0).real.astype(targetT.dtype)
            k, n = topo.aggregates, spec.shape[0]
            if n >= k:
                return spec[:k]
            return jnp.pad(spec, ((0, k - n), (0, 0)))
        return jnp.fft.fft(targetT, n=topo.aggregates, axis=0).real.astype(
            targetT.dtype)
    assert topo.variant == "aggregating"
    _, counts = aggregation_segments(topo)
    if topo.aggregator == "average":
        onehotT = jnp.asarray(_segment_onehot(topo).T, targetT.dtype)
        return matmul(topo, onehotT, targetT) / jnp.asarray(
            counts, targetT.dtype)[:, None]
    starts, ends = _segment_bounds(topo)
    if topo.aggregator == "max":
        return jnp.stack([jnp.max(targetT[s:e], axis=0)
                          for s, e in zip(starts, ends)])
    if topo.aggregator == "max_buggy":
        # bit-faithful falsy-max (network.py:303-308), unrolled over the
        # small segment: identical comparison chain to the row-major scan,
        # so NaN/zero edge cases resolve the same way
        rows = []
        for s, e in zip(starts, ends):
            acc = targetT[s]
            for r in range(s + 1, e):
                w = targetT[r]
                acc = jnp.where((w > acc) & (w != 0.0), w, acc)
            rows.append(acc)
        return jnp.stack(rows)
    raise ValueError(f"unknown aggregator {topo.aggregator!r}")


def kvec_expand_popmajor(topo: Topology, aggs: jnp.ndarray) -> jnp.ndarray:
    """(k, N) outputs -> (P, N) weights, per variant (replication /
    inverse FFT)."""
    if topo.variant == "fft":
        if topo.fft_mode == "rfft":
            return jnp.fft.irfft(aggs, n=topo.num_weights, axis=0).astype(
                aggs.dtype)
        return jnp.fft.ifft(aggs, n=topo.num_weights, axis=0).real.astype(
            aggs.dtype)
    assert topo.variant == "aggregating"
    # matmul (not a row gather) so 0*NaN propagation matches the row-major
    # deaggregate (aggregating.deaggregate) bit-for-bit
    onehot = jnp.asarray(_segment_onehot(topo), aggs.dtype)
    return matmul(topo, onehot, aggs)


def kvec_apply_popmajor(topo: Topology, selfT: jnp.ndarray,
                        targetT: jnp.ndarray) -> jnp.ndarray:
    """Population-major self-application / attack: each particle's transform
    (parameters = column of ``selfT``) rewrites the matching column of
    ``targetT``.  Mirrors ``aggregating.apply`` / ``fft.apply`` vmapped over
    the population, arithmetic reassociated onto lanes."""
    if topo.variant == "fft":
        src = targetT if topo.fft_use_target else selfT
    else:
        src = targetT
    aggs = kvec_reduce_popmajor(topo, src)
    new_aggs = _mlp_forward_lanes(topo, selfT, aggs)
    return kvec_expand_popmajor(topo, new_aggs)


def _kvec_epoch_grad(topo: Topology, wT: jnp.ndarray,
                     xk: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One mse-SGD step on the single sample x = y = ``xk`` (k, N).
    Returns (grads, per-particle pre-update loss (N,))."""
    xk = jax.lax.stop_gradient(xk)

    def loss_fn(w):
        pred = _mlp_forward_lanes(topo, w, xk)
        per_particle = jnp.mean((pred - xk) ** 2, axis=0)
        return per_particle.sum(), per_particle

    return jax.grad(loss_fn, has_aux=True)(wT)


def kvec_train_epochs_popmajor(
    topo: Topology,
    wT: jnp.ndarray,
    epochs: int,
    lr: float = DEFAULT_LR,
    mode: str = "sequential",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``epochs`` self-training calls: samples re-reduced from the CURRENT
    weights before every epoch (repeated ``train()``, ``network.py:613-618``).
    One sample per epoch, so 'sequential' (batch-1) and 'full_batch' are the
    same program.  Returns (new_wT, last epoch per-particle loss (N,))."""
    if mode not in ("sequential", "full_batch"):
        raise ValueError(f"unknown train mode {mode!r}")
    if epochs <= 0:
        return wT, jnp.zeros(wT.shape[1], wT.dtype)

    def body(w, _):
        grads, per_particle = _kvec_epoch_grad(
            topo, w, kvec_reduce_popmajor(topo, w))
        return w - lr * grads, per_particle

    new_wT, losses = jax.lax.scan(body, wT, None, length=epochs)
    return new_wT, losses[-1]


def kvec_learn_epochs_popmajor(
    topo: Topology,
    wT: jnp.ndarray,
    otherT: jnp.ndarray,
    severity: int,
    lr: float = DEFAULT_LR,
    mode: str = "sequential",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``severity`` imitation epochs toward the counterparts' sample (x = y =
    other's aggregate vector, fixed across the call — ``network.py:620-626``).
    ``otherT`` (P, N) holds each particle's counterpart column."""
    if mode not in ("sequential", "full_batch"):
        raise ValueError(f"unknown train mode {mode!r}")
    if severity <= 0:
        return wT, jnp.zeros(wT.shape[1], wT.dtype)
    xk = jax.lax.stop_gradient(kvec_reduce_popmajor(topo, otherT))

    def body(w, _):
        grads, per_particle = _kvec_epoch_grad(topo, w, xk)
        return w - lr * grads, per_particle

    new_wT, losses = jax.lax.scan(body, wT, None, length=severity)
    return new_wT, losses[-1]
