"""Fused Pallas TPU megakernel for one WHOLE soup generation
(attack + learn_from + self-train + respawn) per lane block.

BENCH_r05's micro_dispatch rows showed per-generation dispatch and
gather/compact/scatter glue is a first-order cost at small N: the phase
chain runs attack, learn_from, train and respawn as separate XLA fusions
with the (P, N) population round-tripping HBM between each, plus one
gather per counterpart lookup.  This kernel executes the entire
generation for a block of particles in ONE ``pallas_call``: the block's
weights load into VMEM once, every phase runs on the resident rows with
*phase masks* (the attack/learn gates) replacing the per-phase
gather/compact/scatter glue, and the block writes back once — one HBM
read + one write of the population per generation, regardless of phase
count.

Building blocks are the existing Pallas legs, composed:

  * attack / self-application transform: the weightwise unrolled MLP
    (``pallas_ww``'s math), ``pallas_rnn_train.rnn_forward_rows`` for the
    recurrent variant, and the k-vector reduce → MLP → expand chain
    (reduce shared with ``pallas_kvec_train``; the expand basis is a
    trace-time constant — irfft/ifft of unit vectors for the fft variant,
    segment replication with explicit 0-poison terms for aggregating so
    NaN/Inf propagation matches the XLA one-hot matmul).
  * learn_from / train SGD chains: ``pallas_ww_train._sgd_chain``,
    ``pallas_rnn_train._sgd_epochs``, ``pallas_kvec_train._sgd_epochs`` —
    the already-parity-tested fused chains, now called on rows that never
    left VMEM.
  * respawn: divergent/zero predicates evaluated on the resident
    post-train rows, replacements selected from a pre-drawn fresh block
    (PRNG stays in XLA — the draw is one threefry call per generation).

Counterpart columns (the attacker seen by each victim, each learner's
imitation target) are gathered OUTSIDE the kernel from the
start-of-generation population — the only phase-ordering wrinkle is that
the single-device phase chain lets a learner imitate a victim attacked
*this* generation (post-attack weights).  The kernel reproduces that
without a mid-generation HBM round trip by RECOMPUTING the counterpart's
attack in-block: the learn operands carry the target's pre-attack column
plus its attacker's column, and a mask says whether to re-apply the
transform.  One extra forward per generation — noise next to the SGD
chains.

Mixed precision: a ``bfloat16`` population loads into VMEM at half the
bytes; rows upcast to f32 at block load, every phase computes in f32, and
the result rounds back to bf16 exactly once at block store (the same
once-per-generation rounding points the XLA bf16 path uses), so the
kernel and XLA spellings of ``population_dtype='bf16'`` agree on where
precision is lost.

Backend routing mirrors the other kernels: native Mosaic backends run the
kernel; everywhere else ``soup.py``/``multisoup.py`` fall back to the XLA
phase chain (bit-identical to ``generation_impl='phases'`` by
construction — that fallback IS the acceptance oracle), and
``interpret=True`` runs this kernel in the Pallas interpreter for CPU
parity tests (float-tolerance, like every fused chain).
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..topology import Topology, aggregation_segments, normalized_weight_coords
from .activations import output_grad_activations, resolve_activation
from .pallas_ww import LANE_BLOCK, native_mosaic_backend

#: VMEM budget knob: lanes per grid step scale down as the particle's row
#: count grows, keeping the resident set (population + attacker +
#: counterpart + counterpart-attacker + fresh blocks, ~5 tiles f32)
#: comfortably under ~4 MiB so the SGD chains' live intermediates fit too.
_ROWS_BUDGET = 32768


def generation_block(p: int) -> int:
    """Lanes per grid step for a ``p``-weight topology (128-multiple)."""
    return min(LANE_BLOCK, max(128, (_ROWS_BUDGET // max(p, 1)) // 128 * 128))


def fused_kernel_route(topo: Topology, train_mode: str) -> bool:
    """Does a fused generation take the Mosaic megakernel for this
    topology on this backend?  THE single routing predicate — the soup
    and the multisoup's per-type dispatch both delegate here, so an
    envelope change cannot desynchronize them.  Non-Mosaic backends run
    the full-width masked phase chain instead (the same program as the
    default path — the CPU bit-identity oracle)."""
    return native_mosaic_backend() and fused_kernel_supported(topo,
                                                              train_mode)


def fused_kernel_supported(topo: Topology, train_mode: str) -> bool:
    """Can this topology's generation run as the fused megakernel?

    Same envelope as the fused SGD chains (``popmajor._use_pallas_sgd``):
    activations with output-expressible derivatives, particles up to 64
    weights (the unrolled chains' compile-size fence), and the weightwise
    variant's chain requires the sequential (batch-1) mode.  Off-envelope
    configs run the XLA phase-chain spelling of ``generation_impl='fused'``
    instead (full-width masked phases, no compaction).
    """
    if topo.activation not in output_grad_activations():
        return False
    if topo.num_weights > 64:
        return False
    if topo.variant == "weightwise" and train_mode != "sequential":
        return False
    if topo.shuffler == "random":
        return False
    return True


# ---------------------------------------------------------------------------
# row-level transforms (length-P tuples of (B,) lane vectors)
# ---------------------------------------------------------------------------


def _mlp_rows(topo: Topology, rows, feats):
    """The variant's tiny MLP on one lane block: ``rows`` the per-lane flat
    parameters, ``feats`` the input feature lane-vectors.  Keras kernel
    order (flat o + i*b + j = kernel[i, j]), same accumulation order as
    every popmajor/XLA forward."""
    act = resolve_activation(topo.activation)
    h = list(feats)
    for (a, b), o in zip(topo.layer_shapes, topo.offsets):
        nxt = []
        for j in range(b):
            acc = h[0] * rows[o + j]
            for i in range(1, a):
                acc = acc + h[i] * rows[o + i * b + j]
            nxt.append(act(acc))
        h = nxt
    return h


@functools.lru_cache(maxsize=None)
def _kvec_expand_basis(topo: Topology):
    """(P, k) trace-time constant expand basis for the fft variant: column
    j is the real inverse transform of the j-th unit coefficient vector,
    so ``rows[m] = sum_j basis[m][j] * aggs[j]`` equals
    ``kvec_expand_popmajor`` exactly (ifft real part / irfft)."""
    assert topo.variant == "fft"
    p, k = topo.num_weights, topo.aggregates
    basis = np.zeros((p, k), dtype=np.float64)
    for j in range(k):
        e = np.zeros(k)
        e[j] = 1.0
        if topo.fft_mode == "rfft":
            basis[:, j] = np.fft.irfft(e, n=p)
        else:
            basis[:, j] = np.fft.ifft(e, n=p).real
    return tuple(tuple(float(v) for v in row) for row in basis)


def apply_rows(topo: Topology, self_rows, x_rows):
    """Self-application / attack transform on one lane block — the
    kernel-side twin of ``popmajor.apply_popmajor`` (same-topology pairs;
    cross-type attacks stay in XLA, see ``multisoup``)."""
    p = topo.num_weights
    if topo.variant == "weightwise":
        coords = normalized_weight_coords(topo)
        out = []
        for s in range(p):
            x = x_rows[s]
            feats = [x] + [jnp.full_like(x, float(coords[s, k]))
                           for k in range(3)]
            out.append(_mlp_rows(topo, self_rows, feats)[0])
        return out
    if topo.variant == "recurrent":
        from .pallas_rnn_train import rnn_forward_rows

        seqs = rnn_forward_rows(topo, self_rows, x_rows)
        return [seqs[-1][t][0] for t in range(len(x_rows))]
    # k-vector variants: reduce -> MLP -> expand.  The fft transform reads
    # its OWN weights unless the quirk-fix flag says otherwise
    # (``network.py:494-499``); aggregating always reduces the target.
    from .pallas_kvec_train import _reduce_rows

    src = x_rows if (topo.variant == "aggregating" or topo.fft_use_target) \
        else self_rows
    aggs = _reduce_rows(topo, src)
    outk = _mlp_rows(topo, self_rows, aggs)
    k = topo.aggregates
    if topo.variant == "fft":
        out = []
        for m in range(p):
            coeffs = _kvec_expand_basis(topo)[m]
            acc = None
            for j, c in enumerate(coeffs):
                term = outk[j] if c == 1.0 else outk[j] * c
                acc = term if acc is None else acc + term
            out.append(acc)
        return out
    # aggregating: replicate each segment's output to its rows; the
    # explicit 0.0-weighted terms reproduce the XLA one-hot matmul's
    # NaN/Inf poisoning (0 * Inf = NaN) for out-of-segment aggregates
    seg, _ = aggregation_segments(topo)
    out = []
    for m in range(p):
        acc = None
        for j in range(k):
            term = outk[j] if j == int(seg[m]) else outk[j] * 0.0
            acc = term if acc is None else acc + term
        out.append(acc)
    return out


def _chain_for(topo: Topology):
    """(chain, snap_fn) — the variant's fused SGD chain
    (``chain(topo, rows, snap, epochs, lr, refresh) -> (rows, loss)``)
    and the imitation-snapshot derivation (identity when None)."""
    if topo.variant == "weightwise":
        from .pallas_ww_train import _sgd_chain

        return _sgd_chain, None
    if topo.variant == "recurrent":
        from .pallas_rnn_train import _sgd_epochs

        return _sgd_epochs, None
    from .pallas_kvec_train import _reduce_rows, _sgd_epochs

    return _sgd_epochs, _reduce_rows


# ---------------------------------------------------------------------------
# the megakernel
# ---------------------------------------------------------------------------


def _make_generation_kernel(topo: Topology, *, attack: bool, learn: int,
                            train: int, lr: float, remove_divergent: bool,
                            remove_zero: bool, epsilon: float,
                            recompute_other: bool):
    """Kernel body for one (P, B) lane block.  Operand order (after the
    gates/population/fresh prefix) follows the statics: attacker rows iff
    ``attack``, counterpart rows iff ``learn``, counterpart-attacker rows
    iff ``learn and recompute_other``.  Outputs: new rows, last train
    loss, (div, zero) dead masks."""
    chain, snap_fn = _chain_for(topo)
    p = topo.num_weights

    def kernel(gates_ref, w_ref, fresh_ref, *rest):
        rest = list(rest)
        atk_ref = rest.pop(0) if attack else None
        oth_ref = rest.pop(0) if learn else None
        oatk_ref = rest.pop(0) if (learn and recompute_other) else None
        out_ref, loss_ref, dead_ref = rest
        f32 = jnp.float32

        rows = tuple(w_ref[r, :].astype(f32) for r in range(p))

        # --- attack: mask-selected in-block transform --------------------
        if attack:
            atk_rows = tuple(atk_ref[r, :].astype(f32) for r in range(p))
            attacked = apply_rows(topo, atk_rows, rows)
            m = gates_ref[0, :] != 0
            rows = tuple(jnp.where(m, a, w) for a, w in zip(attacked, rows))

        # --- learn_from: counterpart recomputed to post-attack, then the
        # fused imitation chain on the resident rows -----------------------
        if learn:
            oth = tuple(oth_ref[r, :].astype(f32) for r in range(p))
            if recompute_other:
                oatk = tuple(oatk_ref[r, :].astype(f32) for r in range(p))
                oth_att = apply_rows(topo, oatk, oth)
                ma = gates_ref[2, :] != 0
                oth = tuple(jnp.where(ma, a, o)
                            for a, o in zip(oth_att, oth))
            snap = snap_fn(topo, oth) if snap_fn is not None else oth
            learned, _ = chain(topo, rows, snap, learn, lr, False)
            ml = gates_ref[1, :] != 0
            rows = tuple(jnp.where(ml, l, w) for l, w in zip(learned, rows))

        # --- self-train: the fused chain, snapshot refreshed per epoch ---
        if train:
            rows, loss = chain(topo, rows, None, train, lr, True)
        else:
            loss = jnp.zeros_like(rows[0])

        # --- respawn: predicates on resident rows, pre-drawn fresh block -
        div = jnp.zeros_like(loss, dtype=bool)
        if remove_divergent:
            fin = jnp.isfinite(rows[0])
            for r in range(1, p):
                fin = fin & jnp.isfinite(rows[r])
            div = ~fin
        zero = jnp.zeros_like(div)
        if remove_zero:
            z = (rows[0] >= -epsilon) & (rows[0] <= epsilon)
            for r in range(1, p):
                z = z & (rows[r] >= -epsilon) & (rows[r] <= epsilon)
            zero = z & ~div
        dead = div | zero
        for r in range(p):
            out_ref[r, :] = jnp.where(
                dead, fresh_ref[r, :].astype(f32), rows[r]
            ).astype(out_ref.dtype)
        loss_ref[0, :] = loss
        dead_ref[0, :] = div.astype(jnp.int32)
        dead_ref[1, :] = zero.astype(jnp.int32)

    return kernel


@functools.partial(jax.jit, static_argnames=(
    "topo", "severity", "train", "lr", "remove_divergent", "remove_zero",
    "epsilon", "interpret", "block"))
def _generation_popmajor(topo: Topology, wT, freshT, attackerT=None,
                         has_attacker=None, otherT=None, other_attackerT=None,
                         other_attacked=None, learn_gate=None, *,
                         severity: int = 0, train: int = 0, lr: float = 0.01,
                         remove_divergent: bool = False,
                         remove_zero: bool = False, epsilon: float = 1e-4,
                         interpret: bool = False, block: int = None):
    """One fused generation over a (P, N) population block-by-block.

    ``attackerT``/``has_attacker`` enable the in-kernel attack phase
    (``attackerT[:, n]`` is the column that rewrites lane ``n``; both
    ``None`` = attack pre-applied or disabled, e.g. the multisoup's
    cross-type XLA attack).  ``otherT``/``learn_gate`` enable the
    imitation phase (``severity`` epochs); ``other_attackerT``/
    ``other_attacked`` additionally recompute the counterpart's own
    attack in-block so imitation sees post-attack weights like the
    single-device phase chain.  ``freshT`` supplies respawn replacements.

    Returns ``(new_wT, last-train-loss (N,) f32, dead_div (N,) bool,
    dead_zero (N,) bool)``.  dtype of ``new_wT`` follows ``wT`` (bf16
    populations round once, at block store).
    """
    p, n = wT.shape
    attack = attackerT is not None
    learn = otherT is not None and severity > 0
    recompute_other = learn and other_attackerT is not None
    if not attack:
        has_attacker = jnp.zeros(n, bool)
    if learn_gate is None:
        learn_gate = jnp.zeros(n, bool)
    if not recompute_other:
        other_attacked = jnp.zeros(n, bool)
    gates = jnp.stack([has_attacker.astype(jnp.int32),
                       learn_gate.astype(jnp.int32),
                       other_attacked.astype(jnp.int32)])

    block = min(block or generation_block(p), n)
    pad = (-n) % block
    arrays = [wT, freshT]
    if attack:
        arrays.append(attackerT)
    if learn:
        arrays.append(otherT)
        if recompute_other:
            arrays.append(other_attackerT)
    if pad:
        gates = jnp.pad(gates, ((0, 0), (0, pad)))
        arrays = [jnp.pad(a, ((0, 0), (0, pad))) for a in arrays]
    padded = n + pad

    kernel = _make_generation_kernel(
        topo, attack=attack, learn=severity if learn else 0, train=train,
        lr=float(lr), remove_divergent=remove_divergent,
        remove_zero=remove_zero, epsilon=float(epsilon),
        recompute_other=recompute_other)
    spec = lambda rows: pl.BlockSpec((rows, block), lambda i: (0, i),
                                     memory_space=pltpu.VMEM)
    out, loss, dead = pl.pallas_call(
        kernel,
        out_shape=(jax.ShapeDtypeStruct((p, padded), wT.dtype),
                   jax.ShapeDtypeStruct((1, padded), jnp.float32),
                   jax.ShapeDtypeStruct((2, padded), jnp.int32)),
        grid=(padded // block,),
        in_specs=[spec(3)] + [spec(a.shape[0]) for a in arrays],
        out_specs=(spec(p), spec(1), spec(2)),
        interpret=interpret,
    )(gates, *arrays)
    if pad:
        out, loss, dead = out[:, :n], loss[:, :n], dead[:, :n]
    return out, loss[0], dead[0] != 0, dead[1] != 0


def generation_popmajor(topo: Topology, wT, freshT, attackerT=None,
                        has_attacker=None, otherT=None, other_attackerT=None,
                        other_attacked=None, learn_gate=None, *,
                        severity: int = 0, train: int = 0, lr: float = 0.01,
                        remove_divergent: bool = False,
                        remove_zero: bool = False, epsilon: float = 1e-4,
                        interpret: bool = False, block: int = None):
    """Public spelling of the fused generation: ``block=None`` resolves
    the lane block through the autotuner's tuning table (``srnn_tpu.
    autotune``; pure in-memory/file lookup at trace time, never a
    measurement) and falls back to the :func:`generation_block` VMEM
    formula when the key is untuned or ``SRNN_NO_AUTOTUNE=1``.  The
    block only tiles the grid — every output column is computed from
    that column alone — so results are bitwise block-invariant and the
    untuned path is the tuned path's A/B oracle."""
    if block is None:
        from .. import autotune

        block = autotune.lookup("generation", topo.variant, wT.shape[1],
                                topo.num_weights, dtype=str(wT.dtype))
    return _generation_popmajor(
        topo, wT, freshT, attackerT, has_attacker, otherT, other_attackerT,
        other_attacked, learn_gate, severity=severity, train=train, lr=lr,
        remove_divergent=remove_divergent, remove_zero=remove_zero,
        epsilon=epsilon, interpret=interpret, block=block)


# ---------------------------------------------------------------------------
# lane-blocked chained self-application: the megakernel idea as a pure-XLA
# program — the CPU fast path for bench.py's applications/sec workload
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("topo", "steps", "block"))
def _apply_chain_blocked(topo: Topology, wT, steps: int, block: int = 2048):
    """``steps`` chained self-applications with the chain UNROLLED per lane
    block: a ``lax.scan`` walks (P, block) tiles and each tile runs the
    whole chain while it is cache-resident, so HBM/DRAM traffic is one
    read + one write of the population regardless of ``steps`` — the XLA
    spelling of the megakernel's residency argument.  On CPU this beats
    the step-by-step ``lax.scan`` (which round-trips the full (P, N)
    matrix through memory every step) once N is past cache scale; on
    Mosaic backends prefer ``pallas_ww.ww_apply_population``.
    Same math as ``steps`` iterations of ``apply_popmajor(topo, w, w)``.
    """
    from .popmajor import apply_popmajor

    p, n = wT.shape
    block = min(block, n)
    pad = (-n) % block
    if pad:
        wT = jnp.pad(wT, ((0, 0), (0, pad)))
    nb = (n + pad) // block
    tiles = jnp.moveaxis(wT.reshape(p, nb, block), 1, 0)  # (nb, P, B)

    def one_tile(_, tile):
        w = tile
        for _ in range(steps):
            w = apply_popmajor(topo, w, w)
        return None, w

    _, out = jax.lax.scan(one_tile, None, tiles)
    out = jnp.moveaxis(out, 0, 1).reshape(p, nb * block)
    return out[:, :n] if pad else out


def apply_chain_blocked(topo: Topology, wT, steps: int, block: int = None):
    """Public spelling of the lane-blocked chain: ``block=None`` resolves
    the tile through the autotuner's tuning table (``srnn_tpu.autotune``)
    and falls back to the historical 2048 default when the key is untuned
    or ``SRNN_NO_AUTOTUNE=1``.  Each output column depends only on its
    own column, so every block size computes bitwise-identical results —
    tuning moves the cache cliff, not the math."""
    if block is None:
        from .. import autotune

        block = autotune.lookup("apply_chain", topo.variant, wT.shape[1],
                                topo.num_weights,
                                dtype=str(wT.dtype)) or 2048
    return _apply_chain_blocked(topo, wT, steps, block=block)
