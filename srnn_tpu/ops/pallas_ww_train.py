"""Fused Pallas TPU kernel for weightwise batch-1 sequential SGD.

The full-dynamics soup's dominant cost is the training phase: ``train``
epochs of batch_size=1 SGD are ``epochs * P`` sequential gradient steps
(reference ``network.py:613-617`` semantics), and the XLA scan pays ~2-3
HBM round-trips of the (P, N) population per step — ~140 round-trips per
generation at the paper's train=10.  This kernel runs the ENTIRE flattened
epoch*sample chain inside VMEM per lane block: one HBM read + one write of
the population per ``train()`` phase, like ``pallas_ww.py`` does for
chained self-application.

The backward pass is hand-derived for the LINEAR activation (the science
default every reference experiment effectively ran — SURVEY quirk
§2.4.11): with h_{l+1}[j] = sum_i h_l[i] * W_l[i, j], the per-sample
gradients are

    dL/dpred         = 2 (pred - y)
    dL/dW_l[i, j]    = dh_{l+1}[j] * h_l[i]
    dh_l[i]          = sum_j dh_{l+1}[j] * W_l[i, j]

all elementwise over the lane axis (per-particle parameters are per-lane
scalars).  Per-step math mirrors ``ops/popmajor._ww_seq_sgd_flat``: the
sample snapshot refreshes at each epoch top (self-training) or stays fixed
(imitation / learn_from), updates run in enumeration order, and the
returned loss is the last epoch's mean PRE-update loss (keras history
semantics).  Parity with the XLA path is tested to float tolerance
(reassociation differs).

Mosaic notes (learned compiling on a real v5e, round 5): the epoch loop is
a ``lax.fori_loop`` — Mosaic's loop lowering pattern-matches fori_loop and
rejects a raw ``lax.scan`` ("not a fori_loop index"); and the normalized
duplex coordinates are NOT a kernel operand — they are trace-time
constants of the topology, baked in as Python floats (the previous (P, 3)
VMEM table needed scalar loads Mosaic has no clean lowering for).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..topology import Topology, normalized_weight_coords

LANE_BLOCK = 2048  # particles per grid step (matches pallas_ww)


def _sgd_chain(topo: Topology, rows0, snap_rows, epochs: int, lr: float,
               refresh: bool):
    """The flattened epochs x samples batch-1 SGD chain on one lane block.

    ``rows0`` is a length-P tuple of (B,) lane vectors (one per weight);
    ``snap_rows`` supplies the fixed imitation target when ``refresh`` is
    False, ignored otherwise.  Returns (rows tuple, last_loss (B,))."""
    p = topo.num_weights
    shapes = topo.layer_shapes
    offs = topo.offsets
    coords = normalized_weight_coords(topo)  # (P, 3) trace-time constants

    def epoch(e, carry):
        rows, _ = carry
        snap = rows if refresh else snap_rows
        loss_acc = jnp.zeros_like(rows[0])
        rows = list(rows)
        for s in range(p):
            x = snap[s]
            feats = [x] + [jnp.full_like(x, float(coords[s, k]))
                           for k in range(3)]
            # forward, keeping every layer's activations for the backward
            acts = [feats]
            h = feats
            for (a, b), o in zip(shapes, offs):
                nxt = []
                for j in range(b):
                    acc = h[0] * rows[o + j]
                    for i in range(1, a):
                        acc = acc + h[i] * rows[o + i * b + j]
                    nxt.append(acc)
                acts.append(nxt)
                h = nxt
            pred = h[0]
            loss_acc = loss_acc + (pred - x) * (pred - x)
            # backward (linear layers), building per-row weight updates
            dh = [2.0 * (pred - x)]
            grads = [None] * p
            for li in range(len(shapes) - 1, -1, -1):
                a, b = shapes[li]
                o = offs[li]
                prev = acts[li]
                dprev = []
                for i in range(a):
                    acc = dh[0] * rows[o + i * b + 0]
                    for j in range(1, b):
                        acc = acc + dh[j] * rows[o + i * b + j]
                    dprev.append(acc)
                    for j in range(b):
                        grads[o + i * b + j] = dh[j] * prev[i]
                dh = dprev
            for r in range(p):
                rows[r] = rows[r] - lr * grads[r]
        return tuple(rows), loss_acc / p

    return jax.lax.fori_loop(0, epochs, epoch,
                             (rows0, jnp.zeros_like(rows0[0])))


def _train_kernel(w_ref, out_ref, loss_ref, *, topo, epochs, lr):
    p = topo.num_weights
    rows0 = tuple(w_ref[r, :] for r in range(p))
    rows, loss = _sgd_chain(topo, rows0, None, epochs, lr, refresh=True)
    for r in range(p):
        out_ref[r, :] = rows[r]
    loss_ref[0, :] = loss


def _learn_kernel(w_ref, other_ref, out_ref, loss_ref, *, topo, epochs, lr):
    p = topo.num_weights
    rows0 = tuple(w_ref[r, :] for r in range(p))
    snap = tuple(other_ref[r, :] for r in range(p))
    rows, loss = _sgd_chain(topo, rows0, snap, epochs, lr, refresh=False)
    for r in range(p):
        out_ref[r, :] = rows[r]
    loss_ref[0, :] = loss


def _supported(topo: Topology) -> None:
    assert topo.variant == "weightwise"
    if topo.activation != "linear":
        raise ValueError(
            "the fused Pallas SGD kernel hand-derives the linear backward; "
            f"activation={topo.activation!r} uses the XLA path")


@functools.partial(jax.jit,
                   static_argnames=("topo", "epochs", "lr", "interpret"))
def ww_train_epochs_pallas(topo: Topology, wT: jnp.ndarray, epochs: int,
                           lr: float = 0.01, interpret: bool = False):
    """``epochs`` of batch-1 sequential self-training, entire chain fused
    in VMEM per lane block.  Same semantics as
    ``ops.popmajor.ww_train_epochs_popmajor(mode='sequential')``.
    Returns (new_wT, last epoch per-particle loss (N,))."""
    _supported(topo)
    p, n = wT.shape
    block = min(LANE_BLOCK, n)
    pad = (-n) % block
    if pad:
        wT = jnp.pad(wT, ((0, 0), (0, pad)))
    padded = n + pad
    out, loss = pl.pallas_call(
        functools.partial(_train_kernel, topo=topo, epochs=epochs,
                          lr=float(lr)),
        out_shape=(jax.ShapeDtypeStruct((p, padded), wT.dtype),
                   jax.ShapeDtypeStruct((1, padded), wT.dtype)),
        grid=(padded // block,),
        in_specs=[
            pl.BlockSpec((p, block), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(pl.BlockSpec((p, block), lambda i: (0, i),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((1, block), lambda i: (0, i),
                                memory_space=pltpu.VMEM)),
        interpret=interpret,
    )(wT)
    return (out[:, :n], loss[0, :n]) if pad else (out, loss[0])


@functools.partial(jax.jit,
                   static_argnames=("topo", "severity", "lr", "interpret"))
def ww_learn_epochs_pallas(topo: Topology, wT: jnp.ndarray,
                           otherT: jnp.ndarray, severity: int,
                           lr: float = 0.01, interpret: bool = False):
    """``severity`` imitation epochs toward the counterparts' (fixed)
    samples, fused in VMEM.  Same semantics as
    ``ops.popmajor.ww_learn_epochs_popmajor(mode='sequential')``."""
    _supported(topo)
    p, n = wT.shape
    block = min(LANE_BLOCK, n)
    pad = (-n) % block
    if pad:
        wT = jnp.pad(wT, ((0, 0), (0, pad)))
        otherT = jnp.pad(otherT, ((0, 0), (0, pad)))
    padded = n + pad
    out, loss = pl.pallas_call(
        functools.partial(_learn_kernel, topo=topo, epochs=severity,
                          lr=float(lr)),
        out_shape=(jax.ShapeDtypeStruct((p, padded), wT.dtype),
                   jax.ShapeDtypeStruct((1, padded), wT.dtype)),
        grid=(padded // block,),
        in_specs=[
            pl.BlockSpec((p, block), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((p, block), lambda i: (0, i),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=(pl.BlockSpec((p, block), lambda i: (0, i),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((1, block), lambda i: (0, i),
                                memory_space=pltpu.VMEM)),
        interpret=interpret,
    )(wT, otherT)
    return (out[:, :n], loss[0, :n]) if pad else (out, loss[0])
