"""Fused Pallas TPU kernel for weightwise batch-1 sequential SGD.

The full-dynamics soup's dominant cost is the training phase: ``train``
epochs of batch_size=1 SGD are ``epochs * P`` sequential gradient steps
(reference ``network.py:613-617`` semantics), and the XLA scan pays ~2-3
HBM round-trips of the (P, N) population per step — ~140 round-trips per
generation at the paper's train=10.  This kernel runs the ENTIRE flattened
epoch*sample chain inside VMEM per lane block: one HBM read + one write of
the population per ``train()`` phase, like ``pallas_ww.py`` does for
chained self-application.

The backward pass is hand-derived: with h_{l+1}[j] = act(z[j]),
z[j] = sum_i h_l[i] * W_l[i, j], the per-sample gradients are

    dL/dpred         = 2 (pred - y)
    dz[j]            = dh_{l+1}[j] * act'(h_{l+1}[j])
    dL/dW_l[i, j]    = dz[j] * h_l[i]
    dh_l[i]          = sum_j dz[j] * W_l[i, j]

all elementwise over the lane axis (per-particle parameters are per-lane
scalars).  act' comes from the stored post-activations
(`activations.resolve_output_grad`), so the kernel covers
linear/sigmoid/tanh/relu; 'linear' (the science default every reference
experiment effectively ran — SURVEY quirk §2.4.11) skips the multiplier.

Per-step math mirrors ``ops/popmajor._ww_seq_sgd_flat``: the sample
snapshot refreshes at each epoch top (self-training) or stays fixed
(imitation / learn_from), updates run in enumeration order, and the
returned loss is the last epoch's mean PRE-update loss (keras history
semantics).  Parity with the XLA path is tested to float tolerance
(reassociation differs).

Mosaic notes (learned compiling on a real v5e, round 5): the epoch loop is
a ``lax.fori_loop`` — Mosaic's loop lowering pattern-matches fori_loop and
rejects a raw ``lax.scan`` ("not a fori_loop index"); and the normalized
duplex coordinates are NOT a kernel operand — they are trace-time
constants of the topology, baked in as Python floats (the previous (P, 3)
VMEM table needed scalar loads Mosaic has no clean lowering for).
"""

import functools

import jax
import jax.numpy as jnp

from ..topology import Topology, normalized_weight_coords
from .activations import resolve_activation, resolve_output_grad
from .pallas_sgd_common import lane_call, make_learn_kernel, make_train_kernel


def _sgd_chain(topo: Topology, rows0, snap_rows, epochs: int, lr: float,
               refresh: bool):
    """The flattened epochs x samples batch-1 SGD chain on one lane block.

    ``rows0`` is a length-P tuple of (B,) lane vectors (one per weight);
    ``snap_rows`` supplies the fixed imitation target when ``refresh`` is
    False, ignored otherwise.  Returns (rows tuple, last_loss (B,))."""
    p = topo.num_weights
    shapes = topo.layer_shapes
    offs = topo.offsets
    coords = normalized_weight_coords(topo)  # (P, 3) trace-time constants
    act = resolve_activation(topo.activation)
    act_grad = resolve_output_grad(topo.activation)

    def epoch(e, carry):
        rows, _ = carry
        snap = rows if refresh else snap_rows
        loss_acc = jnp.zeros_like(rows[0])
        rows = list(rows)
        for s in range(p):
            x = snap[s]
            feats = [x] + [jnp.full_like(x, float(coords[s, k]))
                           for k in range(3)]
            # forward, keeping every layer's activations for the backward
            acts = [feats]
            h = feats
            for (a, b), o in zip(shapes, offs):
                nxt = []
                for j in range(b):
                    acc = h[0] * rows[o + j]
                    for i in range(1, a):
                        acc = acc + h[i] * rows[o + i * b + j]
                    nxt.append(act(acc))
                acts.append(nxt)
                h = nxt
            pred = h[0]
            loss_acc = loss_acc + (pred - x) * (pred - x)
            # backward, building per-row weight updates; dh holds the
            # gradient w.r.t. each layer's POST-activation output
            dh = [2.0 * (pred - x)]
            grads = [None] * p
            for li in range(len(shapes) - 1, -1, -1):
                a, b = shapes[li]
                o = offs[li]
                prev = acts[li]
                if act_grad is not None:
                    dh = [dh[j] * act_grad(acts[li + 1][j])
                          for j in range(b)]
                dprev = []
                for i in range(a):
                    acc = dh[0] * rows[o + i * b + 0]
                    for j in range(1, b):
                        acc = acc + dh[j] * rows[o + i * b + j]
                    dprev.append(acc)
                    for j in range(b):
                        grads[o + i * b + j] = dh[j] * prev[i]
                dh = dprev
            for r in range(p):
                rows[r] = rows[r] - lr * grads[r]
        return tuple(rows), loss_acc / p

    return jax.lax.fori_loop(0, epochs, epoch,
                             (rows0, jnp.zeros_like(rows0[0])))


_train_kernel = make_train_kernel(_sgd_chain)
_learn_kernel = make_learn_kernel(_sgd_chain)


def _supported(topo: Topology) -> None:
    assert topo.variant == "weightwise"
    resolve_output_grad(topo.activation)  # raises for unsupported


@functools.partial(jax.jit,
                   static_argnames=("topo", "epochs", "lr", "interpret"))
def ww_train_epochs_pallas(topo: Topology, wT: jnp.ndarray, epochs: int,
                           lr: float = 0.01, interpret: bool = False):
    """``epochs`` of batch-1 sequential self-training, entire chain fused
    in VMEM per lane block.  Same semantics as
    ``ops.popmajor.ww_train_epochs_popmajor(mode='sequential')``.
    Returns (new_wT, last epoch per-particle loss (N,))."""
    _supported(topo)
    return lane_call(_train_kernel, topo, [wT], epochs, lr, interpret)


@functools.partial(jax.jit,
                   static_argnames=("topo", "severity", "lr", "interpret"))
def ww_learn_epochs_pallas(topo: Topology, wT: jnp.ndarray,
                           otherT: jnp.ndarray, severity: int,
                           lr: float = 0.01, interpret: bool = False):
    """``severity`` imitation epochs toward the counterparts' (fixed)
    samples, fused in VMEM.  Same semantics as
    ``ops.popmajor.ww_learn_epochs_popmajor(mode='sequential')``."""
    _supported(topo)
    return lane_call(_learn_kernel, topo, [wT, otherT], severity, lr,
                     interpret)
