"""Shared MLP forward used by the weightwise / aggregating / fft variants.

One matmul chain with the topology's activation after every layer (keras
builds each Dense with the same ``keras_params`` — reference
``network.py:226-230``, ``:329-333``, ``:470-474``).
"""

import jax.numpy as jnp

from .activations import resolve_activation
from .flatten import unflatten
from .linalg import matmul


def mlp_forward(topo, self_flat: jnp.ndarray, x: jnp.ndarray) -> jnp.ndarray:
    act = resolve_activation(topo.activation)
    h = x
    for m in unflatten(topo, self_flat):
        h = act(matmul(topo, h, m))
    return h
