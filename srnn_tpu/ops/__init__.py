from .activations import resolve_activation
from .flatten import unflatten, flatten_mats
from .predicates import (
    is_diverged,
    is_zero,
    is_fixpoint,
    classify,
    CLASS_NAMES,
    CLS_DIVERGENT,
    CLS_FIX_ZERO,
    CLS_FIX_OTHER,
    CLS_FIX_SEC,
    CLS_OTHER,
)

__all__ = [
    "resolve_activation",
    "unflatten",
    "flatten_mats",
    "is_diverged",
    "is_zero",
    "is_fixpoint",
    "classify",
    "CLASS_NAMES",
    "CLS_DIVERGENT",
    "CLS_FIX_ZERO",
    "CLS_FIX_OTHER",
    "CLS_FIX_SEC",
    "CLS_OTHER",
]
