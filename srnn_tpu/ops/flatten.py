"""Flat-vector <-> kernel-matrix conversion.

The reference keeps weights as keras' list of 2-D kernels and flattens with
``np.hstack([w.flatten() for w in weights])`` (``network.py:103-104``); its
``fill_weights`` writes a flat list back in layer -> row -> column order
(``network.py:64-74``).  Here the flat ``(P,)`` vector *is* the canonical
representation and these helpers materialize the per-layer matrix views
inside jitted transforms.  Slicing uses static offsets so XLA sees fixed
shapes.
"""

from typing import List, Sequence

import jax.numpy as jnp

from ..topology import Topology


def unflatten(topo: Topology, flat: jnp.ndarray) -> List[jnp.ndarray]:
    """Split a ``(P,)`` (or ``(..., P)``) vector into kernel matrices.

    Row-major reshape reproduces the reference's layer->cell->weight
    enumeration (``network.py:64-74``).
    """
    mats = []
    for (a, b), start in zip(topo.layer_shapes, topo.offsets):
        mats.append(flat[..., start : start + a * b].reshape(*flat.shape[:-1], a, b))
    return mats


def flatten_mats(mats: Sequence[jnp.ndarray]) -> jnp.ndarray:
    """Inverse of :func:`unflatten` (``get_weights_flat``, ``network.py:103-104``)."""
    lead = mats[0].shape[:-2]
    return jnp.concatenate([m.reshape(*lead, -1) for m in mats], axis=-1)
