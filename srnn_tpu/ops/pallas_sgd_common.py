"""Shared plumbing for the fused Pallas SGD kernels
(``pallas_ww_train`` / ``pallas_rnn_train`` / ``pallas_kvec_train``).

All three kernel families have the same shape: a lane-blocked (P, N)
population in VMEM, an SGD *chain* function
``chain(topo, rows0, snap, epochs, lr, refresh) -> (rows, last_loss)``
over length-P tuples of (B,) lane vectors, and train/learn entry points
that differ only in whether the sample snapshot refreshes from the current
rows (self-training) or is derived once from a counterpart operand
(imitation).  This module owns the pallas_call grid/BlockSpec/pad
boilerplate and the kernel-body adapters so a fix to blocking or padding
lands in exactly one place.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_ww import LANE_BLOCK  # one block size for every lane kernel


def make_train_kernel(chain):
    """Kernel body for self-training: the snapshot refreshes from the
    current rows at each epoch top (``refresh=True``)."""

    def kernel(w_ref, out_ref, loss_ref, *, topo, epochs, lr):
        p = topo.num_weights
        rows0 = tuple(w_ref[r, :] for r in range(p))
        rows, loss = chain(topo, rows0, None, epochs, lr, True)
        for r in range(p):
            out_ref[r, :] = rows[r]
        loss_ref[0, :] = loss

    return kernel


def make_learn_kernel(chain, snap_fn=None):
    """Kernel body for imitation: the snapshot derives ONCE from the
    counterpart rows — via ``snap_fn`` (e.g. the k-vector reduction) or
    identity — and stays fixed across epochs (``refresh=False``)."""

    def kernel(w_ref, other_ref, out_ref, loss_ref, *, topo, epochs, lr):
        p = topo.num_weights
        rows0 = tuple(w_ref[r, :] for r in range(p))
        other = tuple(other_ref[r, :] for r in range(p))
        snap = snap_fn(topo, other) if snap_fn is not None else other
        rows, loss = chain(topo, rows0, snap, epochs, lr, False)
        for r in range(p):
            out_ref[r, :] = rows[r]
        loss_ref[0, :] = loss

    return kernel


def lane_call(kernel, topo, arrays, epochs, lr, interpret):
    """Blocked pallas_call over the lane axis: pad N to a multiple of the
    lane block, run the kernel per (P, block) tile, strip the pad.
    Returns (new (P, N) population, (N,) last-epoch loss)."""
    p, n = arrays[0].shape
    block = min(LANE_BLOCK, n)
    pad = (-n) % block
    if pad:
        arrays = [jnp.pad(a, ((0, 0), (0, pad))) for a in arrays]
    padded = n + pad
    out, loss = pl.pallas_call(
        functools.partial(kernel, topo=topo, epochs=epochs, lr=float(lr)),
        out_shape=(jax.ShapeDtypeStruct((p, padded), arrays[0].dtype),
                   jax.ShapeDtypeStruct((1, padded), arrays[0].dtype)),
        grid=(padded // block,),
        in_specs=[pl.BlockSpec((p, block), lambda i: (0, i),
                               memory_space=pltpu.VMEM)] * len(arrays),
        out_specs=(pl.BlockSpec((p, block), lambda i: (0, i),
                                memory_space=pltpu.VMEM),
                   pl.BlockSpec((1, block), lambda i: (0, i),
                                memory_space=pltpu.VMEM)),
        interpret=interpret,
    )(*arrays)
    return (out[:, :n], loss[0, :n]) if pad else (out, loss[0])
