"""Fused Pallas TPU kernel for weightwise self-application at population scale.

Motivation (measured on v5e): the natural row-major ``vmap`` of the
weightwise transform compiles to per-particle (14x4)@(4x2) batched matmuls —
~2% MXU lane utilization — and XLA materializes every intermediate, giving
~24M applications/s/chip at N=1M.  The TPU-native layout is
**population-major**: the particle axis lives on the 128-wide lane
dimension, per-particle weights become per-lane scalars, and the whole MLP
unrolls into ~14 fused multiply-adds on (P, lane-block) tiles held in VMEM.
Chaining ``steps`` applications per HBM round-trip removes the bandwidth
roof entirely (measured: ~0.3 GB/s HBM at steps=2000 vs the 819 GB/s
spec); the kernel is VPU-compute-bound at ~2.2 Tflop/s f32 — see the
roofline table in RESULTS.md.

Layout: ``wT`` is the transposed population, shape (P, N) — row p holds
weight p of every particle.  The positional-encoding coordinates
(reference ``network.py:239-255``) are compile-time constants baked into
the kernel.

Only the weightwise variant gets a hand kernel: it is the reference's
headline experiment and the only transform whose naive form is
pathologically MXU-hostile.  Aggregating/FFT reduce to k-vector ops, and
the recurrent scan is latency- not layout-bound.
"""

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..ops.activations import resolve_activation
from ..topology import Topology, normalized_weight_coords

LANE_BLOCK = 2048  # particles per grid step; (14, 2048) f32 tiles = 112 KiB


def native_mosaic_backend() -> bool:
    """True when the default backend lowers Mosaic kernels natively.

    Conservative: only 'tpu'.  The tunneled 'axon' backend advertises a
    remote Pallas compile path (PALLAS_AXON_REMOTE_COMPILE) but has never
    been verified to lower these kernels — extend the set once proven on a
    live tunnel.  Shared by bench.py and the popmajor SGD dispatch so the
    two sites cannot diverge."""
    return jax.default_backend() == "tpu"


def _ww_kernel(coords_ref, w_ref, out_ref, *, topo: Topology, steps: int):
    """One lane-block: w_ref/out_ref are (P, BN) VMEM tiles; coords_ref is
    the (P, 3) normalized positional-encoding table (same for all blocks).

    ``steps`` chained self-applications run entirely in VMEM — per-block HBM
    traffic is one read + one write regardless of step count, so sustained
    throughput approaches steps x the bandwidth roof.
    """
    act = resolve_activation(topo.activation)
    offs = topo.offsets
    shapes = topo.layer_shapes

    def apply_once(w):
        # input features per point p: [w_p, layer, cell, weight];
        # feature 0 varies per lane, features 1..3 are per-row constants
        h = [w] + [coords_ref[:, k][:, None] + jnp.zeros_like(w) for k in range(3)]
        # unrolled MLP: weights of layer l for particle n are rows of the tile
        for (a, b), o in zip(shapes, offs):
            nxt = []
            for j in range(b):
                acc = h[0] * w[o + 0 * b + j, :]
                for i in range(1, a):
                    acc = acc + h[i] * w[o + i * b + j, :]
                nxt.append(act(acc))
            h = nxt
        return h[0]

    out_ref[:, :] = jax.lax.fori_loop(
        0, steps, lambda _, w: apply_once(w), w_ref[:, :])


@functools.partial(jax.jit, static_argnames=("topo", "steps", "interpret"))
def ww_apply_population(topo: Topology, wT: jnp.ndarray, steps: int = 1,
                        interpret: bool = False) -> jnp.ndarray:
    """Self-apply every particle of a population-major (P, N) weight matrix
    ``steps`` times (chained in VMEM).

    Semantically identical to ``steps`` iterations of
    ``vmap(lambda w: weightwise.apply(topo, w, w))`` on the transposed
    layout.  ``interpret=True`` runs the kernel in the Pallas interpreter
    (for CPU tests).
    """
    assert topo.variant == "weightwise"
    p, n = wT.shape
    assert p == topo.num_weights
    block = min(LANE_BLOCK, n)
    pad = (-n) % block
    if pad:
        wT = jnp.pad(wT, ((0, 0), (0, pad)))
    padded_n = n + pad

    coords = jnp.asarray(normalized_weight_coords(topo), dtype=wT.dtype)
    out = pl.pallas_call(
        functools.partial(_ww_kernel, topo=topo, steps=steps),
        out_shape=jax.ShapeDtypeStruct((p, padded_n), wT.dtype),
        grid=(padded_n // block,),
        in_specs=[
            pl.BlockSpec((p, 3), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((p, block), lambda i: (0, i), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((p, block), lambda i: (0, i), memory_space=pltpu.VMEM),
        interpret=interpret,
    )(coords, wT)
    return out[:, :n] if pad else out


@functools.partial(jax.jit, static_argnames=("topo",))
def ww_apply_population_jnp(topo: Topology, wT: jnp.ndarray) -> jnp.ndarray:
    """Pure-jnp population-major fallback (same math, XLA-scheduled) for
    platforms without Mosaic support."""
    coords = normalized_weight_coords(topo)
    act = resolve_activation(topo.activation)
    p, n = wT.shape
    h = [wT] + [
        jnp.broadcast_to(jnp.asarray(coords[:, k][:, None], wT.dtype), (p, n))
        for k in range(3)
    ]
    for (a, b), o in zip(topo.layer_shapes, topo.offsets):
        nxt = []
        for j in range(b):
            acc = h[0] * wT[o + j, :]
            for i in range(1, a):
                acc = acc + h[i] * wT[o + i * b + j, :]
            nxt.append(act(acc))
        h = nxt
    return h[0]
