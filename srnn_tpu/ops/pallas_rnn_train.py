"""Fused Pallas TPU kernel for recurrent-variant batch-1 SGD (BPTT).

The round-5 TPU train-phase decomposition (`benchmarks/train_generality.py`,
RESULTS.md) measured the recurrent variant's XLA train path at **118x** the
fused weightwise kernel's per-particle cost — by far the worst row, and the
reason the heterogeneous multisoup is stuck at ~2.5 gens/s (its generation
is dominated by the recurrent member).  The XLA path pays scan(epochs) x
{forward scan(T) + reverse BPTT scan(T)} with the (P, N) population and
(units, N) hidden state round-tripping HBM at every step.

This kernel runs the ENTIRE multi-epoch BPTT chain in VMEM per lane block:
one HBM read + one write of the population per train/learn phase, exactly
like `pallas_ww_train.py` does for the weightwise chain.

Semantics mirror `ops/popmajor_rnn` (reference `network.py:544-574`
semantics): the training sample is ONE sequence x = y = the flat weight
vector (T = P timesteps, feature dim 1), so each reference batch-1 epoch IS
a single full-batch gradient step; self-training re-snapshots x from the
current weights at each epoch top, imitation (`learn_from`) keeps x fixed
at the counterpart's weights.  The returned loss is the last epoch's
per-particle PRE-update loss (keras history semantics).

The backward is hand-derived backprop-through-time over the stacked
SimpleRNN law h_t = act(x_t @ K + h_{t-1} @ R) (keras kernel order:
K[i, u] at flat `ko + i*units + u`, R[v, u] at `ro + v*units + u`):

    dh_t[u]   = dOut_t[u] + sum_u' dz_{t+1}[u'] * R[u, u']
    dz_t[u]   = dh_t[u] * act'(h_t[u])
    dK[i, u] += x_t[i] * dz_t[u]
    dR[v, u] += h_{t-1}[v] * dz_t[u]
    dX_t[i]   = sum_u dz_t[u] * K[i, u]      (the layer-below dOut)

with act' taken from the stored post-activations
(`activations.resolve_output_grad` — linear/sigmoid/tanh/relu).  All of it
is elementwise over the lane axis; the T-step time loop and the layer stack
unroll at trace time (T = P <= 64 by the dispatch fence), and the epoch
loop is a `lax.fori_loop` (Mosaic's loop lowering requirement, learned on a
real v5e in round 5).
"""

import functools

import jax
import jax.numpy as jnp

from ..topology import Topology
from .activations import resolve_activation, resolve_output_grad
from .pallas_sgd_common import lane_call, make_learn_kernel, make_train_kernel


def rnn_forward_rows(topo: Topology, rows, x_rows):
    """Unrolled stacked-SimpleRNN forward on one lane block: ``rows`` the
    attacker's length-P parameter rows, ``x_rows`` the length-T input
    sequence (T = the TARGET's weight count — cross-architecture attacks
    feed another topology's sequence length).  Returns every layer's full
    output sequence (``seqs[0]`` is the input, ``seqs[-1][t][0]`` the
    prediction at step t) so the BPTT backward and the forward-only apply
    kernel (``pallas_rnn_apply``) share one definition."""
    act = resolve_activation(topo.activation)
    t_len = len(x_rows)
    seqs = [[[x_rows[t]] for t in range(t_len)]]  # layer 0 input: (T, 1)
    for layer, (ind, units) in enumerate(topo.rnn_layer_dims):
        ko = topo.offsets[2 * layer]
        ro = topo.offsets[2 * layer + 1]
        inp = seqs[-1]
        out = []
        # h_{-1} = 0, kept as explicit zero terms so NaN/Inf propagation
        # (0 * inf = nan) matches the XLA scan path bit-for-bit
        h = [jnp.zeros_like(rows[0])] * units
        for t in range(t_len):
            nxt = []
            for u in range(units):
                acc = inp[t][0] * rows[ko + u]
                for i in range(1, ind):
                    acc = acc + inp[t][i] * rows[ko + i * units + u]
                for v in range(units):
                    acc = acc + h[v] * rows[ro + v * units + u]
                nxt.append(act(acc))
            out.append(nxt)
            h = nxt
        seqs.append(out)
    return seqs


def _bptt_epoch(topo: Topology, rows, x_rows):
    """One full-batch MSE-SGD gradient on one lane block.

    ``rows`` / ``x_rows`` are length-P tuples of (B,) lane vectors (current
    parameters / the sequence sample).  Returns (grads list, per-particle
    pre-update loss (B,))."""
    act_grad = resolve_output_grad(topo.activation)
    p = topo.num_weights
    t_len = p  # the sequence IS the flat weight vector

    seqs = rnn_forward_rows(topo, rows, x_rows)
    pred = [seqs[-1][t][0] for t in range(t_len)]
    err = [pred[t] - x_rows[t] for t in range(t_len)]
    loss = err[0] * err[0]
    for t in range(1, t_len):
        loss = loss + err[t] * err[t]
    loss = loss / t_len

    # ---- backward through layers (top-down) and time (reverse) ----------
    grads = [jnp.zeros_like(rows[0]) for _ in range(p)]
    scale = 2.0 / t_len
    d_out = [[err[t] * scale] for t in range(t_len)]  # dL/d pred_t
    for layer in range(len(topo.rnn_layer_dims) - 1, -1, -1):
        ind, units = topo.rnn_layer_dims[layer]
        ko = topo.offsets[2 * layer]
        ro = topo.offsets[2 * layer + 1]
        inp = seqs[layer]
        out = seqs[layer + 1]
        zero = jnp.zeros_like(rows[0])
        d_inp = [None] * t_len
        dcarry = None  # gradient flowing into h_t from step t+1
        for t in range(t_len - 1, -1, -1):
            dz = []
            for u in range(units):
                dh = d_out[t][u]
                if dcarry is not None:
                    dh = dh + dcarry[u]
                if act_grad is not None:
                    dh = dh * act_grad(out[t][u])
                dz.append(dh)
            for u in range(units):
                for i in range(ind):
                    gi = ko + i * units + u
                    grads[gi] = grads[gi] + inp[t][i] * dz[u]
                for v in range(units):
                    gr = ro + v * units + u
                    prev = out[t - 1][v] if t > 0 else zero
                    grads[gr] = grads[gr] + prev * dz[u]
            d_inp[t] = [
                functools.reduce(
                    lambda a, b: a + b,
                    [dz[u] * rows[ko + i * units + u] for u in range(units)])
                for i in range(ind)
            ]
            dcarry = [
                functools.reduce(
                    lambda a, b: a + b,
                    [dz[u] * rows[ro + v * units + u] for u in range(units)])
                for v in range(units)
            ]
        d_out = d_inp  # becomes the layer below's upstream gradient
    return grads, loss


def _sgd_epochs(topo: Topology, rows0, snap_rows, epochs: int, lr: float,
                refresh: bool):
    """``epochs`` full-batch BPTT-SGD steps; the sample re-snapshots from
    the current rows (self-training) or stays fixed (imitation)."""
    p = topo.num_weights

    def epoch(e, carry):
        rows, _ = carry
        x_rows = rows if refresh else snap_rows
        grads, loss = _bptt_epoch(topo, rows, x_rows)
        new_rows = tuple(rows[r] - lr * grads[r] for r in range(p))
        return new_rows, loss

    return jax.lax.fori_loop(0, epochs, epoch,
                             (rows0, jnp.zeros_like(rows0[0])))


_train_kernel = make_train_kernel(_sgd_epochs)
_learn_kernel = make_learn_kernel(_sgd_epochs)


def _supported(topo: Topology) -> None:
    assert topo.variant == "recurrent"
    resolve_output_grad(topo.activation)  # raises for unsupported


@functools.partial(jax.jit,
                   static_argnames=("topo", "epochs", "lr", "interpret"))
def rnn_train_epochs_pallas(topo: Topology, wT: jnp.ndarray, epochs: int,
                            lr: float = 0.01, interpret: bool = False):
    """``epochs`` of self-training BPTT-SGD, the entire chain fused in VMEM
    per lane block.  Same semantics as
    ``ops.popmajor_rnn.rnn_train_epochs_popmajor``.
    Returns (new_wT, last epoch per-particle loss (N,))."""
    _supported(topo)
    return lane_call(_train_kernel, topo, [wT], epochs, lr, interpret)


@functools.partial(jax.jit,
                   static_argnames=("topo", "severity", "lr", "interpret"))
def rnn_learn_epochs_pallas(topo: Topology, wT: jnp.ndarray,
                            otherT: jnp.ndarray, severity: int,
                            lr: float = 0.01, interpret: bool = False):
    """``severity`` imitation epochs toward the counterparts' (fixed)
    sequence, fused in VMEM.  Same semantics as
    ``ops.popmajor_rnn.rnn_learn_epochs_popmajor``."""
    _supported(topo)
    return lane_call(_learn_kernel, topo, [wT, otherT], severity, lr,
                     interpret)
