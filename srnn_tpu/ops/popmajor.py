"""Population-major (P, N) weightwise ops: the TPU-native layout for
mega-soup dynamics, in plain jnp.

Rationale (measured at N=1M on v5e): row-major ``vmap`` keeps per-particle
tensors of shape (N, samples, features) whose minor dims (14, 4) waste the
(8, 128) vector tiles — the full-batch trainer ran 4x SLOWER than the
batch-1 scan purely from layout.  Transposed, the particle axis rides the
128-wide lanes, every op is elementwise over lanes, and **autodiff of the
population-major forward stays population-major** — the backward pass is
elementwise too, no batched tiny matmuls.  The same 10-epoch trainer drops
893 ms -> 55 ms (16x); a full soup generation's apply/train phases gain
similarly (``benchmarks/soup_throughput.py --layout popmajor``).

This module is the jnp twin of the Pallas kernel in ``pallas_ww.py``
(which fuses chained self-applications in VMEM); here the win is pure
layout, so it works on any backend and — crucially — under ``jax.grad``.

Compile-pathology note: the multi-epoch batch-1 drivers used to nest
scan(epochs) x scan(samples) x grad, and remote TPU compile services took
unboundedly long on that nest at N=1M once the soup's generations scan
wrapped it (three scan levels).  ``_ww_seq_sgd_flat`` flattens epochs and
samples into ONE scan (epoch-start sample snapshot carried, refreshed when
the flattened index wraps), so the full soup is scan(generations) x
scan(epochs*samples) x grad — the same two-level shape as full_batch mode,
with bounded compile at mega-N (measured: see RESULTS.md).

The aggregating/fft variants get the same layout in ``popmajor_kvec.py``
(their reduce/expand pair is a constant matmul / batched FFT over lanes);
the ``apply_popmajor`` / ``train_epochs_popmajor`` / ``learn_epochs_popmajor``
dispatchers at the bottom of this module route per variant.  Only the
recurrent transform stays row-major (time- not layout-bound, SURVEY §3.1).
"""

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..topology import Topology, normalized_weight_coords
from .activations import resolve_activation

DEFAULT_LR = 0.01  # keras SGD default (mirrors train.DEFAULT_LR, no import cycle)


def ww_forward_popmajor(topo: Topology, wT: jnp.ndarray,
                        xT: jnp.ndarray) -> jnp.ndarray:
    """f_w(points(x)) for every particle, population-major.

    ``wT`` (P, N) holds the nets' parameters, ``xT`` (P, N) the weight
    feature of each duplex point (reference ``network.py:239-255``: point =
    [x_p, layer, cell, weight]; the coordinate features are compile-time
    constants).  Returns (P, N) predictions.  Self-application is
    ``ww_forward_popmajor(topo, wT, wT)``; an attack by a permuted
    population is ``ww_forward_popmajor(topo, wT[:, att], wT)``.
    """
    coords = normalized_weight_coords(topo)
    act = resolve_activation(topo.activation)
    p, n = xT.shape
    h = [xT] + [
        jnp.broadcast_to(jnp.asarray(coords[:, k][:, None], xT.dtype), (p, n))
        for k in range(3)
    ]
    for (a, b), o in zip(topo.layer_shapes, topo.offsets):
        nxt = []
        for j in range(b):
            acc = h[0] * wT[o + j, :]
            for i in range(1, a):
                acc = acc + h[i] * wT[o + i * b + j, :]
            nxt.append(act(acc))
        h = nxt
    return h[0]


def _forward_one_sample(topo: Topology, wT: jnp.ndarray, x_s: jnp.ndarray,
                        coord_s: jnp.ndarray) -> jnp.ndarray:
    """Forward a single duplex point per particle: x_s (N,), coord_s (3,)."""
    act = resolve_activation(topo.activation)
    h = [x_s] + [jnp.broadcast_to(coord_s[k].astype(x_s.dtype), x_s.shape)
                 for k in range(3)]
    for (a, b), o in zip(topo.layer_shapes, topo.offsets):
        nxt = []
        for j in range(b):
            acc = h[0] * wT[o + j, :]
            for i in range(1, a):
                acc = acc + h[i] * wT[o + i * b + j, :]
            nxt.append(act(acc))
        h = nxt
    return h[0]


def ww_fit_epoch_popmajor(
    topo: Topology,
    wT: jnp.ndarray,
    xT: jnp.ndarray,
    yT: jnp.ndarray,
    lr: float = DEFAULT_LR,
    mode: str = "sequential",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One epoch of mse-SGD on fixed samples, every particle at once.

    Same semantics as ``train.fit_epoch`` vmapped over particles —
    ``'sequential'`` is the reference's batch_size=1 per-sample scan
    (``network.py:613-617``), ``'full_batch'`` one step on the mean loss —
    but all arrays are (P, N) and gradients flow through the
    population-major forward.  Returns (new_wT, per-particle epoch loss
    (N,), pre-update keras-history semantics).
    """
    xT = jax.lax.stop_gradient(xT)
    yT = jax.lax.stop_gradient(yT)
    coords = jnp.asarray(normalized_weight_coords(topo))

    if mode == "full_batch":
        def batch_loss(w):
            pred = ww_forward_popmajor(topo, w, xT)
            per_particle = jnp.mean((pred - yT) ** 2, axis=0)
            return per_particle.sum(), per_particle

        grads, per_particle = jax.grad(batch_loss, has_aux=True)(wT)
        return wT - lr * grads, per_particle
    if mode != "sequential":
        raise ValueError(f"unknown train mode {mode!r}")

    def step(w, xs):
        x_s, y_s, coord_s = xs  # scan slices the sample axis — no gathers

        def sample_loss(wi):
            pred = _forward_one_sample(topo, wi, x_s, coord_s)
            per_particle = (pred - y_s) ** 2
            return per_particle.sum(), per_particle

        grads, per_particle = jax.grad(sample_loss, has_aux=True)(w)
        return w - lr * grads, per_particle

    wT, losses = jax.lax.scan(step, wT, (xT, yT, coords))
    return wT, losses.mean(axis=0)


def _ww_seq_sgd_flat(
    topo: Topology,
    wT: jnp.ndarray,
    epochs: int,
    lr: float,
    fixed_xyT: Optional[jnp.ndarray] = None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``epochs`` passes of batch-1 SGD over the P samples as ONE flattened
    scan of length ``epochs * P`` — the compile-bounded replacement for the
    old scan(epochs) x scan(samples) nest.

    ``fixed_xyT is None`` is self-training: the sample set (x = y = weights)
    is re-snapshotted from the CURRENT weights whenever the flattened sample
    index wraps to 0, reproducing "samples recomputed before every epoch"
    (``network.py:613-618``).  Otherwise ``fixed_xyT`` (P, N) is a fixed
    imitation target (``learn_from``, ``network.py:620-626``).

    Per-step math is identical to ``ww_fit_epoch_popmajor('sequential')`` —
    same update order, same pre-update keras-history loss — and everything
    is elementwise over the lane axis, so the sharded soup can call this on
    a lane shard bitwise-identically.  Returns (new_wT, last epoch's mean
    pre-update loss (N,)).
    """
    p, n = wT.shape
    coords = jnp.asarray(normalized_weight_coords(topo))
    refresh = fixed_xyT is None
    snap0 = wT if refresh else jax.lax.stop_gradient(fixed_xyT)
    zeros = jnp.zeros(n, wT.dtype)
    s_seq = jnp.tile(jnp.arange(p), max(epochs, 0))

    def step(carry, s_idx):
        w, snap, accum, last = carry
        if refresh:
            snap = jnp.where(s_idx == 0, w, snap)
        x_s = jax.lax.stop_gradient(snap[s_idx])
        coord_s = coords[s_idx]

        def sample_loss(wi):
            pred = _forward_one_sample(topo, wi, x_s, coord_s)
            per_particle = (pred - x_s) ** 2
            return per_particle.sum(), per_particle

        grads, per_particle = jax.grad(sample_loss, has_aux=True)(w)
        w = w - lr * grads
        accum = accum + per_particle
        done = s_idx == p - 1
        last = jnp.where(done, accum / p, last)
        accum = jnp.where(done, jnp.zeros_like(accum), accum)
        return (w, snap, accum, last), None

    (new_wT, _, _, last), _ = jax.lax.scan(
        step, (wT, snap0, zeros, zeros), s_seq)
    return new_wT, last


def ww_train_epochs_popmajor(
    topo: Topology,
    wT: jnp.ndarray,
    epochs: int,
    lr: float = DEFAULT_LR,
    mode: str = "sequential",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``epochs`` self-training calls (samples recomputed from the current
    weights before every epoch, matching repeated ``train()``,
    ``network.py:613-618``).  Returns (new_wT, last epoch loss (N,))."""
    if epochs <= 0:
        return wT, jnp.zeros(wT.shape[1], wT.dtype)
    if mode == "sequential":
        return _ww_seq_sgd_flat(topo, wT, epochs, lr)

    def body(w, _):
        new_w, loss = ww_fit_epoch_popmajor(topo, w, w, w, lr, mode)
        return new_w, loss

    new_wT, losses = jax.lax.scan(body, wT, None, length=epochs)
    return new_wT, losses[-1]


def ww_learn_epochs_popmajor(
    topo: Topology,
    wT: jnp.ndarray,
    otherT: jnp.ndarray,
    severity: int,
    lr: float = DEFAULT_LR,
    mode: str = "sequential",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``severity`` imitation epochs toward the counterparts' samples
    (x = y = other's weights, fixed across the call — ``network.py:620-626``).
    ``otherT`` (P, N) is each particle's counterpart column."""
    if severity <= 0:
        return wT, jnp.zeros(wT.shape[1], wT.dtype)
    if mode == "sequential":
        return _ww_seq_sgd_flat(topo, wT, severity, lr, otherT)

    def body(w, _):
        new_w, loss = ww_fit_epoch_popmajor(topo, w, otherT, otherT, lr, mode)
        return new_w, loss

    new_wT, losses = jax.lax.scan(body, wT, None, length=severity)
    return new_wT, losses[-1]


# ---------------------------------------------------------------------------
# Variant dispatch: one population-major surface for the soup / sharded soup.
# ---------------------------------------------------------------------------


def _use_pallas_apply(topo: Topology, impl: str,
                      target_p: int = None) -> bool:
    """Route the apply transform to a fused kernel?  Only the recurrent
    variant has one (``pallas_rnn_apply``) — its serial T-step scan is the
    only memory-bound apply; the other variants' dense lane programs are
    already single XLA fusions.  Unsupported combinations fall back
    silently (mirrors ``_use_pallas_sgd``).  ``target_p`` is the VICTIM's
    weight count — the kernel unrolls T = target_p timesteps, so the
    compile-size fence must bound it too (cross-type attacks can pair a
    small recurrent attacker with an arbitrarily large victim)."""
    if impl != "pallas":
        return False
    from .activations import output_grad_activations

    return (topo.variant == "recurrent"
            and topo.activation in output_grad_activations()
            and topo.num_weights <= 64
            and (target_p is None or target_p <= 64))


def apply_popmajor(topo: Topology, selfT: jnp.ndarray,
                   targetT: jnp.ndarray, impl: str = "xla") -> jnp.ndarray:
    """Population-major self-application / attack for any variant: particle
    n's transform (parameters ``selfT[:, n]``) rewrites ``targetT[:, n]``.
    The recurrent variant runs the serial time scan (lanes parallelize the
    population; the associative decomposition only matters for the
    weight-axis-sharded path, ``parallel/sharded_apply.py``) — or, with
    ``impl='pallas'``, the unrolled VMEM kernel."""
    if _use_pallas_apply(topo, impl, target_p=targetT.shape[0]):
        from .pallas_rnn_apply import rnn_apply_pallas

        return rnn_apply_pallas(topo, selfT, targetT,
                                interpret=_pallas_interpret(selfT.shape[1]))
    if topo.variant == "weightwise":
        return ww_forward_popmajor(topo, selfT, targetT)
    if topo.variant == "recurrent":
        from .popmajor_rnn import rnn_forward_popmajor

        return rnn_forward_popmajor(topo, selfT, targetT)
    from .popmajor_kvec import kvec_apply_popmajor

    return kvec_apply_popmajor(topo, selfT, targetT)


def _use_pallas_sgd(topo: Topology, mode: str, impl: str) -> bool:
    """Route to a fused Pallas SGD chain?  Round-5 coverage: EVERY variant
    (pallas_ww_train / pallas_rnn_train / pallas_kvec_train), activations
    with output-expressible derivatives (linear/sigmoid/tanh/relu).  The
    weightwise kernel additionally requires the sequential (batch-1) mode —
    its fused chain IS the per-sample update order; the other variants have
    ONE sample per epoch, so sequential and full_batch coincide and both
    take the kernel.  Any unsupported combination — activation, mode, or a
    particle beyond 64 weights (unrolled-chain length grows ~P^2 per epoch
    for ww / ~P*T for rnn; compile cost dwarfs the fusion win) — falls back
    silently: the heterogeneous multisoup dispatches per type by design,
    and ``resolved_train_impl`` surfaces what actually runs.  The
    homogeneous-soup entry points reject unsupported configs UPFRONT with
    a message (``soup._check_popmajor``), so this dispatch never needs to
    raise — raising here would make the multisoup's reported per-type
    resolution disagree with its execution."""
    if impl != "pallas":
        return False
    from .activations import output_grad_activations

    if topo.activation not in output_grad_activations():
        return False
    if topo.variant == "weightwise" and mode != "sequential":
        return False  # full_batch is a genuinely different program
    if topo.num_weights > 64:
        return False
    return True


def resolved_train_impl(topo: Topology, mode: str, impl: str) -> str:
    """The impl the train phase will ACTUALLY run for this type: 'pallas'
    only where the fused kernel applies, else 'xla'.

    The multisoup dispatch falls back per type silently by design
    (``_use_pallas_sgd``); run headers should surface the resolution so a
    ``train_impl='pallas'`` run states which types took the kernel rather
    than leaving it to be inferred from the fence rules."""
    return "pallas" if _use_pallas_sgd(topo, mode, impl) else "xla"


def _pallas_interpret(n: int) -> bool:
    """Interpreter only at test scale on non-Mosaic backends; at
    population scale it would be a silent near-hang, so demand the XLA
    path explicitly instead."""
    from .pallas_ww import native_mosaic_backend

    if native_mosaic_backend():
        return False
    if n <= 4096:
        return True
    raise ValueError(
        "the fused Pallas kernels need a native Mosaic backend at this "
        "population size (the interpreter would be pathologically slow); "
        "use train_impl='xla' / apply_impl='xla' on this platform")


def _check_train_mode(mode: str) -> None:
    # validated here for every impl: the pallas route treats the two modes
    # as coinciding for single-sample variants and would otherwise accept
    # any string the XLA twins reject
    if mode not in ("sequential", "full_batch"):
        raise ValueError(f"unknown train mode {mode!r}")


def train_epochs_popmajor(topo: Topology, wT: jnp.ndarray, epochs: int,
                          lr: float = DEFAULT_LR, mode: str = "sequential",
                          impl: str = "xla"):
    _check_train_mode(mode)
    if _use_pallas_sgd(topo, mode, impl):
        interpret = _pallas_interpret(wT.shape[1])
        if topo.variant == "weightwise":
            from .pallas_ww_train import ww_train_epochs_pallas

            return ww_train_epochs_pallas(topo, wT, epochs, lr,
                                          interpret=interpret)
        if topo.variant == "recurrent":
            from .pallas_rnn_train import rnn_train_epochs_pallas

            return rnn_train_epochs_pallas(topo, wT, epochs, lr,
                                           interpret=interpret)
        from .pallas_kvec_train import kvec_train_epochs_pallas

        return kvec_train_epochs_pallas(topo, wT, epochs, lr,
                                        interpret=interpret)
    if topo.variant == "weightwise":
        return ww_train_epochs_popmajor(topo, wT, epochs, lr, mode)
    if topo.variant == "recurrent":
        from .popmajor_rnn import rnn_train_epochs_popmajor

        return rnn_train_epochs_popmajor(topo, wT, epochs, lr, mode)
    from .popmajor_kvec import kvec_train_epochs_popmajor

    return kvec_train_epochs_popmajor(topo, wT, epochs, lr, mode)


def learn_epochs_popmajor(topo: Topology, wT: jnp.ndarray, otherT: jnp.ndarray,
                          severity: int, lr: float = DEFAULT_LR,
                          mode: str = "sequential", impl: str = "xla"):
    _check_train_mode(mode)
    if _use_pallas_sgd(topo, mode, impl):
        interpret = _pallas_interpret(wT.shape[1])
        if topo.variant == "weightwise":
            from .pallas_ww_train import ww_learn_epochs_pallas

            return ww_learn_epochs_pallas(topo, wT, otherT, severity, lr,
                                          interpret=interpret)
        if topo.variant == "recurrent":
            from .pallas_rnn_train import rnn_learn_epochs_pallas

            return rnn_learn_epochs_pallas(topo, wT, otherT, severity, lr,
                                           interpret=interpret)
        from .pallas_kvec_train import kvec_learn_epochs_pallas

        return kvec_learn_epochs_pallas(topo, wT, otherT, severity, lr,
                                        interpret=interpret)
    if topo.variant == "weightwise":
        return ww_learn_epochs_popmajor(topo, wT, otherT, severity, lr, mode)
    if topo.variant == "recurrent":
        from .popmajor_rnn import rnn_learn_epochs_popmajor

        return rnn_learn_epochs_popmajor(topo, wT, otherT, severity, lr, mode)
    from .popmajor_kvec import kvec_learn_epochs_popmajor

    return kvec_learn_epochs_popmajor(topo, wT, otherT, severity, lr, mode)
