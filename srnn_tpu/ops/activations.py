"""Activation registry matching keras activation-string semantics
(reference passes activation names through ``keras_params``, ``network.py:80``)."""

import jax.numpy as jnp
import jax.nn


def _linear(x):
    return x


_ACTIVATIONS = {
    "linear": _linear,
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "elu": jax.nn.elu,
    "softmax": jax.nn.softmax,
    "swish": jax.nn.swish,
    "gelu": jax.nn.gelu,
}


def resolve_activation(name):
    if callable(name):
        return name
    try:
        return _ACTIVATIONS[name]
    except KeyError:
        raise ValueError(
            f"unknown activation {name!r}; known: {sorted(_ACTIVATIONS)}"
        ) from None


# Activations whose derivative is expressible from the OUTPUT alone —
# what the hand-derived Pallas backward passes need, since they store
# post-activation values (not pre-activations) in VMEM.  relu's gradient
# at exactly 0 is 0, matching jax.nn.relu's VJP.
_OUTPUT_GRADS = {
    "linear": None,                    # multiplier 1 — callers skip the mul
    "sigmoid": lambda h: h * (1.0 - h),
    "tanh": lambda h: 1.0 - h * h,
    "relu": lambda h: (h > 0.0).astype(h.dtype),
}


def output_grad_activations():
    """Activation names the fused Pallas SGD kernels can differentiate."""
    return tuple(sorted(_OUTPUT_GRADS))


def resolve_output_grad(name):
    """act'(z) as a function of h = act(z); returns None for 'linear'
    (identity multiplier)."""
    try:
        return _OUTPUT_GRADS[name]
    except KeyError:
        raise ValueError(
            f"activation {name!r} has no output-expressible derivative; "
            f"the fused kernels support {sorted(_OUTPUT_GRADS)}") from None
