"""Activation registry matching keras activation-string semantics
(reference passes activation names through ``keras_params``, ``network.py:80``)."""

import jax.numpy as jnp
import jax.nn


def _linear(x):
    return x


_ACTIVATIONS = {
    "linear": _linear,
    "sigmoid": jax.nn.sigmoid,
    "tanh": jnp.tanh,
    "relu": jax.nn.relu,
    "elu": jax.nn.elu,
    "softmax": jax.nn.softmax,
    "swish": jax.nn.swish,
    "gelu": jax.nn.gelu,
}


def resolve_activation(name):
    if callable(name):
        return name
    try:
        return _ACTIVATIONS[name]
    except KeyError:
        raise ValueError(
            f"unknown activation {name!r}; known: {sorted(_ACTIVATIONS)}"
        ) from None
