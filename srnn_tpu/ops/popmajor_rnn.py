"""Population-major (P, N) lane layout for the recurrent variant.

The SimpleRNN transform is inherently sequential over its length-T weight
sequence (reference ``network.py:544-564``), but the POPULATION axis is
embarrassingly parallel — so the lane layout applies exactly as it does for
the other variants: hidden state lives as a (units, N) lane matrix, each of
the T scan steps is ~(in+units)*units fused multiply-adds over the 128-wide
lanes, and per-particle parameters are per-lane scalars (rows of the (P, N)
transposed population).  The time axis stays a ``lax.scan``; what the
layout removes is the row-major path's per-particle batched tiny matmuls
(vmap of (1,w)@(w,w) — ~2% lane utilization).

Self-training for this variant has ONE sample per epoch (x = y = the whole
weight sequence, ``network.py:566-574``), so — like the k-vector variants —
the batch_size=1 reference epoch is a single full-batch gradient step and
the multi-epoch driver is scan(epochs){grad through the time scan}.
"""

from typing import Tuple

import jax
import jax.numpy as jnp

from ..topology import Topology
from .activations import resolve_activation

DEFAULT_LR = 0.01  # keras SGD default (mirrors train.DEFAULT_LR)


def rnn_forward_popmajor(topo: Topology, wT: jnp.ndarray,
                         xT: jnp.ndarray) -> jnp.ndarray:
    """Stacked SimpleRNN over lanes: ``wT`` (P, N) per-lane parameters,
    ``xT`` (T, N) the input sequence's single feature per lane.  Keras law
    h_t = act(x_t @ K + h_{t-1} @ R) with kernel[i, u] at flat offset
    ko + i*units + u and recurrent[v, u] at ro + v*units + u
    (``Topology.layer_shapes`` interleaves kernel/recurrent per layer).
    Returns the final layer's (T, N) output sequence."""
    act = resolve_activation(topo.activation)
    n = xT.shape[1]
    x = xT[:, None, :]  # (T, in=1, N)
    for layer, (ind, units) in enumerate(topo.rnn_layer_dims):
        ko = topo.offsets[2 * layer]
        ro = topo.offsets[2 * layer + 1]

        def step(h, x_t, ko=ko, ro=ro, ind=ind, units=units):
            outs = []
            for u in range(units):
                acc = x_t[0] * wT[ko + u, :]
                for i in range(1, ind):
                    acc = acc + x_t[i] * wT[ko + i * units + u, :]
                for v in range(units):
                    acc = acc + h[v] * wT[ro + v * units + u, :]
                outs.append(act(acc))
            h_new = jnp.stack(outs)
            return h_new, h_new

        h0 = jnp.zeros((units, n), xT.dtype)
        _, x = jax.lax.scan(step, h0, x)
    return x[:, 0, :]


def _rnn_epoch_grad(topo: Topology, wT: jnp.ndarray,
                    xT: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One mse-SGD step on the single sequence sample x = y = ``xT`` (T, N).
    Returns (grads, per-particle pre-update loss (N,))."""
    xT = jax.lax.stop_gradient(xT)

    def loss_fn(w):
        pred = rnn_forward_popmajor(topo, w, xT)
        per_particle = jnp.mean((pred - xT) ** 2, axis=0)
        return per_particle.sum(), per_particle

    return jax.grad(loss_fn, has_aux=True)(wT)


def rnn_train_epochs_popmajor(
    topo: Topology,
    wT: jnp.ndarray,
    epochs: int,
    lr: float = DEFAULT_LR,
    mode: str = "sequential",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``epochs`` self-training calls (the sample sequence is the CURRENT
    weights, re-snapshotted before every epoch — repeated ``train()``,
    ``network.py:613-618``)."""
    if mode not in ("sequential", "full_batch"):
        raise ValueError(f"unknown train mode {mode!r}")
    if epochs <= 0:
        return wT, jnp.zeros(wT.shape[1], wT.dtype)

    def body(w, _):
        grads, per_particle = _rnn_epoch_grad(topo, w, w)
        return w - lr * grads, per_particle

    new_wT, losses = jax.lax.scan(body, wT, None, length=epochs)
    return new_wT, losses[-1]


def rnn_learn_epochs_popmajor(
    topo: Topology,
    wT: jnp.ndarray,
    otherT: jnp.ndarray,
    severity: int,
    lr: float = DEFAULT_LR,
    mode: str = "sequential",
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """``severity`` imitation epochs toward the counterparts' sequence
    (fixed across the call — ``network.py:620-626``)."""
    if mode not in ("sequential", "full_batch"):
        raise ValueError(f"unknown train mode {mode!r}")
    if severity <= 0:
        return wT, jnp.zeros(wT.shape[1], wT.dtype)
    xT = jax.lax.stop_gradient(otherT)

    def body(w, _):
        grads, per_particle = _rnn_epoch_grad(topo, w, xT)
        return w - lr * grads, per_particle

    new_wT, losses = jax.lax.scan(body, wT, None, length=severity)
    return new_wT, losses[-1]
