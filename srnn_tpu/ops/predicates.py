"""Jittable fixpoint predicates and the 5-way classification.

Semantics tracked from the reference:
  - ``are_weights_diverged``: any NaN/Inf anywhere      (``network.py:43-52``)
  - ``is_zero``: every weight within [-eps, +eps], *inclusive* bounds
    (``network.py:54-62,136-138``); NaN weights are never "zero" because the
    chained comparison fails.
  - ``is_fixpoint(degree)``: apply the net ``degree`` times to its own
    weights; False if the result diverged, else True iff every
    ``|new - old| < eps`` (strict — a delta of exactly eps fails)
    (``network.py:140-157``).
  - classification order: divergent > fix_zero > fix_other > fix_sec > other
    (``experiment.py:79-91``, duplicated at ``soup.py:89-103``).

All functions are branchless array ops so they vmap/shard cleanly.
"""

from typing import Callable

import jax.numpy as jnp

CLASS_NAMES = ("divergent", "fix_zero", "fix_other", "fix_sec", "other")
CLS_DIVERGENT, CLS_FIX_ZERO, CLS_FIX_OTHER, CLS_FIX_SEC, CLS_OTHER = range(5)

DEFAULT_EPSILON = 1e-4  # every reference experiment overrides the 1e-14
                        # constructor default to 1e-4 (e.g. training-fixpoints.py:38)


def is_diverged(flat: jnp.ndarray, axis: int = -1) -> jnp.ndarray:
    """True if any weight is NaN or +-Inf. Reduces over ``axis`` (the weight
    axis: last for (N, P) row-major, 0 for (P, N) population-major)."""
    return jnp.any(~jnp.isfinite(flat), axis=axis)


def is_zero(flat: jnp.ndarray, epsilon: float = DEFAULT_EPSILON,
            axis: int = -1) -> jnp.ndarray:
    """True if all weights lie in the closed interval [-eps, eps]."""
    return jnp.all((flat >= -epsilon) & (flat <= epsilon), axis=axis)


def is_fixpoint(
    apply_self: Callable[[jnp.ndarray], jnp.ndarray],
    flat: jnp.ndarray,
    degree: int = 1,
    epsilon: float = DEFAULT_EPSILON,
) -> jnp.ndarray:
    """Degree-d fixpoint test for a single flat weight vector.

    ``apply_self`` must be the net's self-application with its *own* weights
    bound, i.e. ``target -> f_w(target)``; it is iterated ``degree`` times
    starting from ``flat`` while the net itself stays fixed
    (``network.py:140-157``).
    """
    assert degree >= 1, "degree must be >= 1"
    new = flat
    for _ in range(degree):
        new = apply_self(new)
    close = jnp.all(jnp.abs(new - flat) < epsilon, axis=-1)
    return ~is_diverged(new) & close


def classify(
    apply_self: Callable[[jnp.ndarray], jnp.ndarray],
    flat: jnp.ndarray,
    epsilon: float = DEFAULT_EPSILON,
) -> jnp.ndarray:
    """5-way class id for one particle (int32 scalar).

    Evaluates both degree-1 and degree-2 applications once and resolves the
    reference's elif-chain as nested ``where`` so the whole thing stays
    branchless and vmappable.
    """
    new1 = apply_self(flat)
    new2 = apply_self(new1)
    div = is_diverged(flat)
    fix1 = ~is_diverged(new1) & jnp.all(jnp.abs(new1 - flat) < epsilon, axis=-1)
    fix2 = ~is_diverged(new2) & jnp.all(jnp.abs(new2 - flat) < epsilon, axis=-1)
    zero = is_zero(flat, epsilon)
    return jnp.where(
        div,
        CLS_DIVERGENT,
        jnp.where(
            fix1 & zero,
            CLS_FIX_ZERO,
            jnp.where(fix1, CLS_FIX_OTHER, jnp.where(fix2, CLS_FIX_SEC, CLS_OTHER)),
        ),
    ).astype(jnp.int32)


def count_classes(class_ids: jnp.ndarray) -> jnp.ndarray:
    """Histogram of class ids -> (5,) int32 counter vector.

    The array analog of the reference's counter dicts
    (``experiment.py:67``, ``soup.py:90``).
    """
    return (class_ids[..., None] == jnp.arange(5)).sum(axis=tuple(range(class_ids.ndim))).astype(jnp.int32)
