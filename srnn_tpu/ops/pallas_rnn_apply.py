"""Fused Pallas TPU kernel for the recurrent variant's APPLY transform
(attack / self-application forward).

The recurrent transform is a serial scan over the length-T weight sequence
(reference ``network.py:544-564``); under XLA every one of the T steps
reads the (P, N) parameter matrix from HBM and round-trips the (units, N)
hidden state — the same memory-bound structure that made the recurrent
TRAIN path 118x off the fused kernels' per-particle cost (RESULTS.md
round-5 campaign).  This kernel holds the attacker parameters and the
victim sequence in VMEM per lane block and unrolls the T timesteps, so an
attack phase costs one HBM read of each operand and one write of the
result.

The forward definition is shared with the BPTT kernel
(``pallas_rnn_train.rnn_forward_rows``), including the explicit zero
h_{-1} terms that keep NaN/Inf propagation identical to the XLA scan.
Cross-architecture ready: the sequence length is the TARGET's weight
count, independent of the attacker's parameter count.
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..topology import Topology
from .activations import resolve_output_grad
from .pallas_rnn_train import rnn_forward_rows
from .pallas_sgd_common import LANE_BLOCK


def _apply_kernel(self_ref, target_ref, out_ref, *, topo):
    rows = tuple(self_ref[r, :] for r in range(self_ref.shape[0]))
    x_rows = tuple(target_ref[r, :] for r in range(target_ref.shape[0]))
    seqs = rnn_forward_rows(topo, rows, x_rows)
    for r in range(len(x_rows)):
        out_ref[r, :] = seqs[-1][r][0]


def _supported(topo: Topology) -> None:
    assert topo.variant == "recurrent"
    # same activation envelope as the SGD kernels (forward needs only the
    # activation itself, but keeping one envelope keeps the fences simple)
    resolve_output_grad(topo.activation)


@functools.partial(jax.jit, static_argnames=("topo", "interpret"))
def rnn_apply_pallas(topo: Topology, selfT: jnp.ndarray,
                     targetT: jnp.ndarray, interpret: bool = False):
    """Population-major attack: particle n's transform (parameters
    ``selfT[:, n]``) rewrites ``targetT[:, n]``.  Same semantics as
    ``ops.popmajor_rnn.rnn_forward_popmajor`` with per-lane parameters."""
    _supported(topo)
    p_self, n = selfT.shape
    p_tgt = targetT.shape[0]
    block = min(LANE_BLOCK, n)
    pad = (-n) % block
    if pad:
        selfT = jnp.pad(selfT, ((0, 0), (0, pad)))
        targetT = jnp.pad(targetT, ((0, 0), (0, pad)))
    padded = n + pad
    out = pl.pallas_call(
        functools.partial(_apply_kernel, topo=topo),
        out_shape=jax.ShapeDtypeStruct((p_tgt, padded), targetT.dtype),
        grid=(padded // block,),
        in_specs=[pl.BlockSpec((p_self, block), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
                  pl.BlockSpec((p_tgt, block), lambda i: (0, i),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((p_tgt, block), lambda i: (0, i),
                               memory_space=pltpu.VMEM),
        interpret=interpret,
    )(selfT, targetT)
    return out[:, :n] if pad else out
