"""Benchmark: self-applications/sec on the current accelerator.

Workload: the reference's hot operation — weightwise self-application
(reference ``network.py:265-279``: one keras ``predict`` per scalar weight
there) — at the BASELINE.json mega-soup scale of 1M particles, using the
fused population-major Pallas kernel (``srnn_tpu/ops/pallas_ww.py``): the
particle axis rides the 128-wide TPU lanes and chained steps stay in VMEM.

North star (BASELINE.json): >= 10M self-applications/sec on a v4-32, i.e.
312,500/sec/chip.  ``vs_baseline`` is the per-chip multiple of that.

Timing notes: on the tunneled 'axon' platform ``block_until_ready`` does
not actually synchronize, so the measurement forces a scalar readback; per-
call RPC latency is amortized by running many chained steps per dispatch.

Prints exactly one JSON line.
"""

import json
import time

import jax

from srnn_tpu import Topology, init_population
from srnn_tpu.ops.pallas_ww import ww_apply_population

N = 1_000_000
STEPS_PER_CALL = 2000
CALLS = 3
BASELINE_PER_CHIP = 10_000_000 / 32  # BASELINE.json north star, v4-32


def main():
    topo = Topology("weightwise", width=2, depth=2)  # science-default f32 precision
    # damped init keeps the iteration numerically tame for the whole run;
    # throughput is magnitude-independent
    wT = (init_population(topo, jax.random.key(0), N) * 0.05).T

    use_pallas = jax.default_backend() == "tpu"  # Mosaic kernel is TPU-only

    @jax.jit
    def run(wT):
        if use_pallas:
            out = ww_apply_population(topo, wT, steps=STEPS_PER_CALL)
        else:
            from srnn_tpu.ops.pallas_ww import ww_apply_population_jnp

            def step(w, _):
                return ww_apply_population_jnp(topo, w), None
            out = jax.lax.scan(step, wT, None, length=STEPS_PER_CALL)[0]
        return out, out.sum()

    _ = float(run(wT)[1])  # compile + warm
    t0 = time.perf_counter()
    for _ in range(CALLS):
        _ = float(run(wT)[1])  # scalar readback forces completion
    dt = time.perf_counter() - t0

    apps_per_sec = N * STEPS_PER_CALL * CALLS / dt
    per_chip = apps_per_sec / jax.device_count()
    print(json.dumps({
        "metric": "self-applications/sec/chip",
        "value": round(per_chip),
        "unit": "applications/s",
        "vs_baseline": round(per_chip / BASELINE_PER_CHIP, 2),
    }))


if __name__ == "__main__":
    main()
