"""Benchmark: self-applications/sec on the current accelerator.

Workload: the reference's hot operation — weightwise self-application
(reference ``network.py:265-279``: one keras ``predict`` per scalar weight
there) — at the BASELINE.json mega-soup scale of 1M particles, using the
fused population-major Pallas kernel (``srnn_tpu/ops/pallas_ww.py``): the
particle axis rides the 128-wide TPU lanes and chained steps stay in VMEM.

North star (BASELINE.json): >= 10M self-applications/sec on a v4-32, i.e.
312,500/sec/chip (convention: per-chip = total / 32 mesh devices, per
BASELINE.json's v4-32 device count).  ``vs_baseline`` is the per-chip
multiple of that.

Robustness (round-3 hardening): the tunneled 'axon' platform flakes at
backend *init* (the round-1 failure), so the backend is probed with retries
+ registry clears (``srnn_tpu.utils.backend.ensure_backend``), the workload
ramps (tiny compile-check first, then the full 1M-particle run), and every
failure path still prints one well-formed JSON line carrying the best
measurement obtained so far plus an ``error`` field — never a bare stack
trace.

Timing notes: on 'axon' ``block_until_ready`` does not actually
synchronize, so the measurement forces a scalar readback; per-call RPC
latency is amortized by running many chained steps per dispatch.

Prints exactly one JSON line.
"""

import json
import time
import traceback

N = 1_000_000
STEPS_PER_CALL = 2000
CALLS = 3
RAMP_N = 8192
RAMP_STEPS = 50
BASELINE_PER_CHIP = 10_000_000 / 32  # BASELINE.json north star, v4-32


def _measure(topo, n, steps, calls):
    """Ramped measurement unit: returns applications/sec for (n, steps)."""
    import jax

    from srnn_tpu import init_population
    from srnn_tpu.ops.pallas_ww import ww_apply_population

    # damped init keeps the iteration numerically tame for the whole run;
    # throughput is magnitude-independent
    wT = (init_population(topo, jax.random.key(0), n) * 0.05).T

    use_pallas = jax.default_backend() == "tpu"  # Mosaic kernel is TPU-only

    @jax.jit
    def run(wT):
        if use_pallas:
            out = ww_apply_population(topo, wT, steps=steps)
        else:
            from srnn_tpu.ops.pallas_ww import ww_apply_population_jnp

            def step(w, _):
                return ww_apply_population_jnp(topo, w), None
            out = jax.lax.scan(step, wT, None, length=steps)[0]
        return out, out.sum()

    _ = float(run(wT)[1])  # compile + warm
    t0 = time.perf_counter()
    for _ in range(calls):
        _ = float(run(wT)[1])  # scalar readback forces completion
    dt = time.perf_counter() - t0
    return n * steps * calls / dt


WATCHDOG_S = 1500.0  # hard bound on the whole bench (init wedges included)


def main():
    result = {
        "metric": "self-applications/sec/chip",
        "value": 0,
        "unit": "applications/s",
        "vs_baseline": 0.0,
    }

    def emit():
        result["vs_baseline"] = round(result["value"] / BASELINE_PER_CHIP, 2)
        print(json.dumps(result), flush=True)

    from srnn_tpu.utils.backend import ensure_backend, watchdog

    # the tunnel's OTHER failure mode is a hang (init/compile wedges instead
    # of raising) — retries can't catch that, so the whole bench runs under
    # a watchdog that still emits the fail-soft JSON line before exiting
    cancel = watchdog(
        WATCHDOG_S,
        on_fire=lambda: (result.setdefault(
            "error", f"watchdog: wedged > {WATCHDOG_S:.0f}s"), emit()))
    try:
        platform, fell_back = ensure_backend(retries=5, sleep_s=15.0,
                                             fallback_cpu=True)
        import jax

        from srnn_tpu import Topology

        topo = Topology("weightwise", width=2, depth=2)  # science-default f32

        # ramp stage: tiny shapes — proves compile + execute end-to-end and
        # leaves a nonzero fail-soft number if the full run dies
        apps = _measure(topo, RAMP_N, RAMP_STEPS, 1)
        result["value"] = round(apps / jax.device_count())
        result["ramp_only"] = True

        if fell_back:
            # degraded run: the full 1M x 2000-step workload would take
            # hours on host CPU; report a reduced honest measurement
            result["backend"] = "cpu-fallback"
            apps = _measure(topo, 100_000, 20, 1)
        else:
            apps = _measure(topo, N, STEPS_PER_CALL, CALLS)
        result["value"] = round(apps / jax.device_count())
        del result["ramp_only"]
    except Exception as e:  # fail-soft: always emit the JSON line
        result["error"] = f"{type(e).__name__}: {e}"
        traceback.print_exc()
    cancel()
    emit()


if __name__ == "__main__":
    main()
