"""Benchmark: self-applications/sec on the current accelerator.

Workload: the reference's hot operation — weightwise self-application
(reference ``network.py:265-279``: one keras ``predict`` per scalar weight
there) — at the BASELINE.json mega-soup scale of 1M particles, using the
fused population-major Pallas kernel (``srnn_tpu/ops/pallas_ww.py``): the
particle axis rides the 128-wide TPU lanes and chained steps stay in VMEM.

North star (BASELINE.json): >= 10M self-applications/sec on a v4-32, i.e.
312,500/sec/chip (convention: per-chip = total / jax.device_count(); the
JSON records ``device_count`` so the normalization is interpretable on any
topology).  ``vs_baseline`` is the per-chip multiple of that.

Robustness (round-4 rework): the tunneled 'axon' platform has TWO failure
modes — init that *raises* (round-1) and init/compile that *hangs*
(round-3, where an in-process watchdog could only emit value=0 because the
wedge killed every later stage in the same process).  So the bench is now
subprocess-isolated:

  * the PARENT never imports jax — it cannot wedge.  It spawns each stage
    (``--stage ramp``, ``--stage full``) as a fresh child process with its
    own timeout, kills and retries on a hang (the flake is per-process init
    luck — a fresh process is the only retry that can work), keeps the best
    measurement so far, and always prints exactly ONE JSON line.
  * children share a persistent ``JAX_COMPILATION_CACHE_DIR`` so a retry
    after a wedge does not re-pay the compile that wedged.
  * the ramp stage (tiny shapes) lands a nonzero fail-soft number before
    the full 1M-particle run is attempted.

Timing notes: on 'axon' ``block_until_ready`` does not actually
synchronize, so the measurement forces a scalar readback; per-call RPC
latency is amortized by running many chained steps per dispatch.

Prints exactly one JSON line (on the parent's stdout; child diagnostics go
to stderr, child results travel on a sentinel-prefixed stdout line).
"""

import json
import os
import subprocess
import sys
import time

N = 1_000_000
STEPS_PER_CALL = 2000
CALLS = 3
RAMP_N = 8192
RAMP_STEPS = 50
BASELINE_PER_CHIP = 10_000_000 / 32  # BASELINE.json north star, v4-32

# Stage budget (seconds).  The parent clamps every stage to the remaining
# global deadline so the single JSON line is always emitted before the
# driver's external timeout.  All overridable for tests.
DEADLINE_S = float(os.environ.get("SRNN_BENCH_DEADLINE_S", "1400"))
RAMP_TIMEOUT_S = float(os.environ.get("SRNN_BENCH_RAMP_TIMEOUT_S", "420"))
FULL_TIMEOUT_S = float(os.environ.get("SRNN_BENCH_FULL_TIMEOUT_S", "650"))
# r4 lesson: after the first ramp attempt hangs for the full 420s, two more
# 420s attempts learn nothing new — retries get a shorter leash, and
# production-scale attempts are SPACED so they sample different tunnel
# states instead of hammering the same wedge back-to-back
RAMP_RETRY_TIMEOUT_S = float(
    os.environ.get("SRNN_BENCH_RAMP_RETRY_TIMEOUT_S", "240"))
# compile-only warmer before any measurement: fills the persistent
# executable cache so the ramp/full children's timed window pays execution
# only.  Best-effort — a failure or timeout costs budget but never blocks
# the measurement stages.
PRECOMPILE_TIMEOUT_S = float(
    os.environ.get("SRNN_BENCH_PRECOMPILE_TIMEOUT_S", "180"))
# skip the warmer when the pre-reserve budget is this thin (the
# measurement stages need whatever is left more than a warm cache)
PRECOMPILE_MIN_BUDGET_S = 45.0
RETRY_SPACING_S = float(os.environ.get("SRNN_BENCH_RETRY_SPACING_S", "150"))
# spacing only makes sense at production proportions; test-scale timeouts
# (seconds) must not inherit multi-minute sleeps
SPACING_MIN_TIMEOUT_S = 300.0
RAMP_ATTEMPTS = 3
FULL_ATTEMPTS = 2
# deadline slice the ramp/full stages may NOT eat into: keeps the cpu-rescue
# leg runnable even when every accelerator attempt times out at full budget
# (without it, 3x420 + 2x650 > 1400 and a persistently wedged tunnel starves
# the rescue — reproducing the r3 value=0 scorecard)
RESCUE_RESERVE_S = 330.0
# the multi-tenant experiment-service load leg (srnn_tpu.serve): runs
# FIRST (host-CPU pinned — a wedged tunnel cannot eat it) and reports
# requests/sec at measured p50/p95 plus the 8-concurrent-sweeps vs
# 8-solo-processes comparison, then the 1/2/4-worker fleet saturation
# sweep (three subprocess fleets at ~20s each, hence the bigger default
# than the other CPU legs).  0 disables (the bench e2e tests pin tiny
# deadlines and must not inherit a multi-minute extra stage).
SERVE_TIMEOUT_S = float(os.environ.get("SRNN_BENCH_SERVE_TIMEOUT_S", "600"))
# the distributed-tier leg (srnn_tpu.distributed): a 2-process CPU-mesh
# mega_soup through the launcher vs the single-process run of the same
# config — proves the multi-host plumbing end to end on this host
# (bitwise-verified) and records the DCN-tax of the CPU spelling.  The
# TPU-pod row stays wired-not-measured until the next TPU window.  0
# disables (bench e2e tests pin tiny deadlines).
MULTIHOST_TIMEOUT_S = float(
    os.environ.get("SRNN_BENCH_MULTIHOST_TIMEOUT_S", "420"))

_SENTINEL = "@@BENCH_RESULT "
#: child-side heartbeat lines: milestone rows on the piped stdout, so a
#: TIMED-OUT child's partial output still names the last step it finished
#: (backend init / compile / call k of n) instead of just "timeout"
_HB_SENTINEL = "@@BENCH_HB "

#: child-side stall watchdog state (see _arm_stall_sentinel): the ring
#: doubles as the triage bundle's flight-recorder trail, the sentinel is
#: the dead-man's switch that dumps it when the child wedges
_STALL_RING = None
_STALL_SENTINEL = None


# --------------------------------------------------------------------------
# child side: one stage per process
# --------------------------------------------------------------------------

def _hb(stage, step, **extra):
    """Emit one child heartbeat row (parent salvages the last one from a
    killed child's partial stdout and records it in the stage log)."""
    row = {"stage": stage, "step": step, "t": round(time.time(), 3)}
    row.update(extra)
    if _STALL_RING is not None:
        _STALL_RING.record(dict(row))
    if _STALL_SENTINEL is not None:
        _STALL_SENTINEL.mark(f"{stage}:{step}")
    print(_HB_SENTINEL + json.dumps(row), flush=True)


def _arm_stall_sentinel(stage: str) -> None:
    """Arm the flight-recorder dead-man's switch for this child: if no
    heartbeat lands within SRNN_BENCH_STALL_S seconds (the parent exports
    ~80% of the attempt timeout), a daemon timer writes a host-only triage
    bundle — the heartbeat ring, backend metadata, the last mark — and
    prints its path as a final heartbeat row.  The parent lifts that path
    into the attempt's stage_log entry, so a timed-out attempt points at
    an artifact instead of just "timeout".  The wedge typically hangs a
    blocking C call (tunnel recvfrom), which releases the GIL, so the
    timer thread still runs."""
    global _STALL_RING, _STALL_SENTINEL

    deadline = float(os.environ.get("SRNN_BENCH_STALL_S", "0") or 0)
    if deadline <= 0:
        return
    from srnn_tpu.telemetry.flightrec import (FlightRecorder, StallSentinel,
                                              write_triage_bundle)

    ring = FlightRecorder(capacity=64)
    root = os.environ.get(
        "SRNN_BENCH_TRIAGE_DIR",
        os.path.join(os.path.dirname(os.path.abspath(__file__)),
                     ".bench_triage"))

    def on_stall(last_mark, waited_s):
        os.makedirs(root, exist_ok=True)
        bundle = write_triage_bundle(
            root, ["stall"], {"stage": stage, "last_mark": last_mark,
                              "stalled_after_s": round(waited_s, 1)},
            recorder=ring, thresholds={"stall_s": deadline})
        # printed WITHOUT _hb (a mark here would re-arm the deadline)
        row = {"stage": stage, "step": "stall", "t": round(time.time(), 3),
               "last_mark": last_mark, "triage_bundle": bundle}
        print(_HB_SENTINEL + json.dumps(row), flush=True)
        sys.stdout.flush()

    _STALL_RING = ring
    _STALL_SENTINEL = StallSentinel(deadline, on_stall,
                                    name=f"bench-{stage}-stall")

def _bench_fn(topo, steps, impl="auto"):
    """The measured program: ``steps`` chained self-applications over the
    whole (P, N) population.  One definition shared by the measurement and
    precompile stages, so the AOT-compiled executable and the measured
    dispatch hit the SAME persistent-cache entry.

    ``impl``: 'auto' picks the backend's fast path — the Pallas VMEM chain
    on Mosaic backends, elsewhere the lane-blocked fused chain
    (``pallas_generation.apply_chain_blocked``: the whole chain unrolled
    per cache-resident tile — measured ~1.3-1.4x the step-by-step scan on
    this repo's CPU rescue shape, which round-trips the full (P, N)
    matrix through memory every step).  'scan' forces that legacy scan
    spelling (kept as the comparison row in the CPU child's output)."""
    import jax

    from srnn_tpu.ops.pallas_ww import (native_mosaic_backend,
                                        ww_apply_population)

    use_pallas = impl == "auto" and native_mosaic_backend()

    @jax.jit
    def run(wT):
        if use_pallas:
            out = ww_apply_population(topo, wT, steps=steps)
        elif impl == "scan":
            from srnn_tpu.ops.pallas_ww import ww_apply_population_jnp

            def step(w, _):
                return ww_apply_population_jnp(topo, w), None
            out = jax.lax.scan(step, wT, None, length=steps)[0]
        else:
            from srnn_tpu.ops.pallas_generation import apply_chain_blocked

            out = apply_chain_blocked(topo, wT, steps)
        return out, out.sum()

    return run


def _measure(topo, n, steps, calls, stage=None, impl="auto", best=False):
    """Ramped measurement unit: returns (applications/sec, overlap summary)
    for (n, steps).  The overlap summary is ``OverlapMeter.summary()`` —
    wall vs device-wait vs host seconds — and the same cumulative numbers
    ride every heartbeat row, so even a KILLED child's last heartbeat
    attributes where its budget went (host stall vs device compute).

    ``best=True`` reports the FASTEST single dispatch instead of the
    cumulative rate — the autotuner's min-wall protocol
    (``autotune._measure_walls``): the quantity being compared is the
    program's speed, and on a shared host scheduler noise only ever
    adds.  The degraded CPU legs use this (their per-dispatch walls are
    ~60ms, where one preemption costs 20%); accelerator legs keep the
    cumulative honest-throughput rate."""
    import jax

    from srnn_tpu import init_population
    from srnn_tpu.utils.pipeline import OverlapMeter

    # damped init keeps the iteration numerically tame for the whole run;
    # throughput is magnitude-independent
    wT = (init_population(topo, jax.random.key(0), n) * 0.05).T
    run = _bench_fn(topo, steps, impl)
    meter = OverlapMeter()

    def attr():
        t = meter.totals
        return {"device_wait_s": round(t["device_wait_s"], 3),
                "wall_s": round(t["wall_s"], 3)}

    if stage:
        _hb(stage, "init", n=n, steps=steps)

    t0 = time.perf_counter()
    with meter.waiting():
        _ = float(run(wT)[1])  # compile (persistent-cache served) + warm
    meter.chunk_done(time.perf_counter() - t0)
    if stage:
        _hb(stage, "compiled+warm", **attr())
    # time each dispatch individually so the liveness heartbeat between
    # calls never contaminates the measured window
    dt = 0.0
    best_call = float("inf")
    for i in range(calls):
        t0 = time.perf_counter()
        with meter.waiting():
            _ = float(run(wT)[1])  # scalar readback forces completion
        call_s = time.perf_counter() - t0
        dt += call_s
        best_call = min(best_call, call_s)
        meter.chunk_done(call_s)
        if stage:
            _hb(stage, "call", call=i + 1, calls=calls, **attr())
    if best:
        return n * steps / best_call, meter.summary()
    return n * steps * calls / dt, meter.summary()


def _precompile(topo, shapes):
    """AOT-lower + compile the bench program for each (n, steps, impl)
    WITHOUT executing anything, filling the shared persistent executable
    cache so the ramp/full children's timed region pays execution only."""
    import jax
    import jax.numpy as jnp

    from srnn_tpu.utils.aot import aot_compile

    rows = []
    for n, steps, impl in shapes:
        run = _bench_fn(topo, steps, impl)
        wT = jax.ShapeDtypeStruct((topo.num_weights, n), jnp.float32)
        name = f"bench.run.{n}x{steps}.{impl}"
        e = aot_compile(name, run, (wT,))
        row = {"n": n, "steps": steps, "impl": impl,
               "lower_s": round(e.lower_s, 3),
               "compile_s": round(e.compile_s, 3)}
        # cost-plane attribution (telemetry.costs): the compiled
        # program's HLO flops, when the backend reports them — the same
        # numbers land in compile_ledger.jsonl next to the cache
        try:
            from srnn_tpu.telemetry import costs

            row["flops"] = costs.entry_flops(name)
        except Exception:
            pass
        rows.append(row)
        _hb("precompile", "compiled", n=n, steps=steps, impl=impl,
            compile_s=round(e.compile_s, 3))
    return rows


def _serve_leg() -> dict:
    """The experiment-service load benchmark (one in-process service +
    Unix-socket clients, host CPU):

      * ``sweeps``: N concurrent fixpoint-density sweeps through the
        service (stacked into one tenant-axis dispatch) vs N SEQUENTIAL
        solo processes of the same sweep — aggregate wall-clock speedup,
        compile count during serving, and a per-tenant bitwise parity
        check against the solo processes' saved artifacts.
      * ``load``: closed-loop requests/sec at measured p50/p95 latency
        (C client threads submitting tiny sweeps for a fixed window),
        under the continuous-batching controller (the production
        default) — plus the window-occupancy ratio (ticket time spent
        waiting for stackmates over total request time) the adaptive
        windows exist to shrink.
      * ``saturation``: the same closed-loop load against real
        ``python -m srnn_tpu.serve`` processes at 1/2/4 dispatch
        workers — the scale-out curve over the shared journal/AOT-cache
        substrate (admitted vs replayed counts keep the recovery story
        on the record).
    """
    import shutil
    import tempfile
    import threading

    import numpy as np

    from srnn_tpu.serve import ExperimentService
    from srnn_tpu.serve.client import ServiceClient
    from srnn_tpu.serve.server import ServiceServer
    from srnn_tpu.telemetry.metrics import quantile_from_times
    from srnn_tpu.utils.pipeline import spawn_thread

    sweeps = int(os.environ.get("SRNN_BENCH_SERVE_SWEEPS", "8"))
    trials = int(os.environ.get("SRNN_BENCH_SERVE_TRIALS", "2048"))
    batch = int(os.environ.get("SRNN_BENCH_SERVE_BATCH", "512"))
    load_s = float(os.environ.get("SRNN_BENCH_SERVE_LOAD_S", "8"))
    load_clients = int(os.environ.get("SRNN_BENCH_SERVE_CLIENTS", "4"))
    # the load leg's latency target — the adaptive controller's set
    # point: windows shrink under violations and grow on headroom, so
    # measured p95 hovers at this value.  100ms is well under the
    # fixed-window p95 ~312ms PR 10 measured (with SLO headroom the law
    # correctly grows back to the 250ms ceiling and the leg would just
    # re-measure the fixed window); it is also ~4x the tiny sweep's
    # dispatch time, so the windows still buy real stacking
    slo_ms = float(os.environ.get("SRNN_BENCH_SERVE_SLO_P95_MS", "100"))
    # admission control: a BOUNDED queue keeps the saturation story
    # honest — past it the service pushes back with typed overload
    # rejections (counted below) instead of hiding load in the queue
    max_queue = int(os.environ.get("SRNN_BENCH_SERVE_MAX_QUEUE", "64"))
    load_trials = 64

    repo = os.path.dirname(os.path.abspath(__file__))
    root = tempfile.mkdtemp(prefix="srnn_serve_bench_")
    out = {"sweeps": sweeps, "trials": trials, "batch": batch}
    svc = server_thread = None
    try:
        svc = ExperimentService(os.path.join(root, "svc"),
                                max_stack=sweeps, slo_p95_ms=slo_ms,
                                max_queue=max_queue)
        _hb("serve", "warmup")
        svc.warm("fixpoint_density", {"trials": trials, "batch": batch})
        # EVERY width 1..C: the adaptive floor-start windows make odd
        # stack widths (a drain catching 2 of 4 clients) routine, and a
        # cold width mid-load would bill its compile to the p95
        svc.warm("fixpoint_density",
                 {"trials": load_trials, "batch": load_trials},
                 widths=tuple(range(1, load_clients + 1)))
        sock = os.path.join(root, "serve.sock")
        # the sweeps phase runs FIXED-window (one guaranteed width-8
        # stack — the amortization/parity story, comparable to the
        # committed fixed-window rounds); the controller attaches before
        # the load phase, which measures the adaptive tier
        server = ServiceServer(svc, sock, batch_window_s=0.25)
        server_thread = spawn_thread(server.serve_until_shutdown,
                                     name="bench-serve-server")
        client = ServiceClient(sock)
        client.wait_until_up(30)

        # -- solo baseline: N sequential fresh processes (each pays its
        # own interpreter + jax import + dispatch; they share the
        # persistent compile cache, so this is the steady-state floor,
        # not a cold-compile strawman)
        _hb("serve", "solo_processes")
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["SRNN_SETUPS_PLATFORM"] = "cpu"
        env.pop("PYTHONPATH", None)   # never dial the axon tunnel
        solo_root = os.path.join(root, "solo")
        t0 = time.monotonic()
        for i in range(sweeps):
            subprocess.run(
                [sys.executable, "-m", "srnn_tpu.setups",
                 "fixpoint_density", "--trials", str(trials), "--batch",
                 str(batch), "--seed", str(i), "--root", solo_root],
                cwd=repo, env=env, check=True, timeout=240,
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
            _hb("serve", "solo_done", i=i + 1)
        solo_wall = time.monotonic() - t0

        # -- N concurrent sweeps through the warm service
        _hb("serve", "service_sweeps")
        programs_before = client.stats()["distinct_programs"]
        results = [None] * sweeps

        def one(i):
            results[i] = client.request(
                "fixpoint_density",
                {"seed": i, "trials": trials, "batch": batch},
                tenant=f"sweep{i}", timeout_s=300)

        t0 = time.monotonic()
        threads = [spawn_thread(one, name=f"bench-serve-c{i}", args=(i,))
                   for i in range(sweeps)]
        for t in threads:
            t.join()
        service_wall = time.monotonic() - t0
        stats = client.stats()

        # per-tenant bitwise parity vs the solo processes' artifacts
        # (one seed -> run-dir map; not a per-sweep re-walk)
        by_seed = {}
        for d in os.listdir(solo_root):
            if d.startswith("exp-"):
                with open(os.path.join(solo_root, d, "meta.json")) as f:
                    by_seed[json.load(f).get("seed")] = d
        parity = True
        for i in range(sweeps):
            match = by_seed.get(i)
            if match is None:
                parity = False
                continue
            with np.load(os.path.join(solo_root, match,
                                      "all_counters.npz")) as z:
                solo_counters = z[z.files[0]]
            if not np.array_equal(np.asarray(results[i]["counters"],
                                             np.int64),
                                  np.asarray(solo_counters, np.int64)):
                parity = False
        out["sweeps_solo_wall_s"] = round(solo_wall, 2)
        out["sweeps_service_wall_s"] = round(service_wall, 2)
        out["sweeps_speedup_x"] = round(solo_wall
                                        / max(service_wall, 1e-9), 2)
        out["sweeps_compiles_during_serving"] = (
            stats["distinct_programs"] - programs_before)
        out["sweeps_bitwise_equal_to_solo"] = parity
        out["dispatch_modes"] = {
            k.split("mode=")[-1].strip('"}'): v
            for k, v in stats["metrics"].items()
            if k.startswith("srnn_serve_dispatches_total")}

        # -- closed-loop load: C clients hammering tiny sweeps (each with
        # its own seeded-backoff client, so an overload rejection backs
        # off deterministically instead of hammering the full queue)
        _hb("serve", "load", seconds=load_s, clients=load_clients)
        # flip the dispatcher to continuous batching for the load phase
        # (the dispatch loop reads .controller every cycle)
        from srnn_tpu.serve.controller import make_controller

        controller = make_controller(0.25, slo_ms)
        svc.attach_controller(controller)
        server.controller = controller
        pre_stats = client.stats()
        rejections_before = (pre_stats.get("self_healing") or {}).get(
            "overload_rejections", 0)
        rows_before = pre_stats.get("metrics") or {}
        stop_at = time.monotonic() + load_s
        lat_lists = [[] for _ in range(load_clients)]

        def loader(lats, seed):
            c = ServiceClient(sock, retries=6, backoff_base_s=0.05,
                              seed=seed)
            n = 0
            while time.monotonic() < stop_at:
                t1 = time.monotonic()
                n += 1
                # per-request idempotency key: makes the client's
                # mid-op-disconnect retry safe (a keyless request is
                # deliberately NOT retried after delivery risk)
                c.request("fixpoint_density",
                          {"seed": seed, "trials": load_trials,
                           "batch": load_trials},
                          tenant=f"load{seed}", timeout_s=60,
                          idempotency_key=f"load{seed}-{n}")
                lats.append(time.monotonic() - t1)

        t0 = time.monotonic()
        threads = [spawn_thread(loader, name=f"bench-serve-load{i}",
                                args=(lat_lists[i], i))
                   for i in range(load_clients)]
        for t in threads:
            t.join()
        load_wall = time.monotonic() - t0
        lats = [x for lst in lat_lists for x in lst]
        load_stats = client.stats()
        slo = load_stats.get("slo") or {}
        sh = load_stats.get("self_healing") or {}
        rejected = (sh.get("overload_rejections", 0) or 0) \
            - (rejections_before or 0)
        rows_after = load_stats.get("metrics") or {}

        def _hist_sum_delta(prefix):
            after = sum(v for k, v in rows_after.items()
                        if k.startswith(prefix))
            return after - sum(v for k, v in rows_before.items()
                               if k.startswith(prefix))

        # window occupancy: of the load window's total request seconds,
        # the share spent WAITING for stackmates — the fixed 250ms window
        # ran this near 0.8 (window-bound); the adaptive floor-start
        # windows should read well under that
        win_sum = _hist_sum_delta("srnn_serve_ticket_window_seconds_sum")
        req_sum = _hist_sum_delta("srnn_serve_request_seconds_sum")
        out["load"] = {
            "clients": load_clients,
            "window_s": round(load_wall, 2),
            "requests": len(lats),
            "requests_per_sec": round(len(lats) / max(load_wall, 1e-9), 2),
            "p50_ms": round(1e3 * quantile_from_times(lats, 0.5), 1),
            "p95_ms": round(1e3 * quantile_from_times(lats, 0.95), 1),
            "slo_target_p95_ms": slo.get("target_p95_ms"),
            "slo_violations": slo.get("violations"),
            # admitted counts COMPLETED closed-loop requests; rejected is
            # the overload pushback during the window — together they are
            # the honest saturation story (a rejected submit retried and
            # eventually admitted still counts once in each)
            "max_queue": max_queue,
            "admitted": len(lats),
            "rejected": rejected,
            "replayed": sh.get("replayed", 0),
            "window_occupancy": round(win_sum / req_sum, 4)
            if req_sum > 0 else None,
            "dispatch": load_stats.get("dispatch"),
        }

        # -- saturation sweep: the same closed-loop load against REAL
        # `python -m srnn_tpu.serve` processes at 1/2/4 dispatch workers
        # (fleet mode: shared persistent AOT cache, per-tenant sticky
        # round-robin, journal-backed replay on worker death) — the
        # scale-out curve the continuous-batching tier exists to bend
        sat_s = float(os.environ.get("SRNN_BENCH_SERVE_SAT_S", "5"))
        sat_workers = [int(x) for x in os.environ.get(
            "SRNN_BENCH_SERVE_SAT_WORKERS", "1,2,4").split(",") if x]

        def saturation_row(nw):
            froot = os.path.join(root, f"fleet{nw}")
            fsock = os.path.join(froot, "serve.sock")
            os.makedirs(froot, exist_ok=True)
            proc = subprocess.Popen(
                [sys.executable, "-m", "srnn_tpu.serve", "--root", froot,
                 "--workers", str(nw), "--batch-window-s", "0.25",
                 "--slo-p95-ms", str(slo_ms),
                 "--max-queue", str(max_queue),
                 "--warm-fixpoint-density", f"{load_trials},{load_trials}"],
                cwd=repo, env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL)
            row = {"workers": nw}
            try:
                fc = ServiceClient(fsock, retries=6, backoff_base_s=0.05,
                                   seed=nw)
                fc.wait_until_up(180)
                # pin + warm each load tenant's sticky worker OUTSIDE the
                # timed window (worker startup is the fleet's cold cost,
                # not its steady-state latency)
                for s in range(load_clients):
                    fc.request("fixpoint_density",
                               {"seed": s, "trials": load_trials,
                                "batch": load_trials},
                               tenant=f"sat{s}", timeout_s=180,
                               idempotency_key=f"satwarm{nw}-{s}")
                # concurrent warm bursts until a full round serves fast:
                # the adaptive windows stack whatever widths the arrival
                # pattern produces, and each worker must have compiled
                # (or cache-loaded) ITS widths before the timed window
                for burst in range(20):
                    tb = time.monotonic()
                    bthreads = [
                        spawn_thread(
                            lambda s=s, b=burst: fc.request(
                                "fixpoint_density",
                                {"seed": s, "trials": load_trials,
                                 "batch": load_trials},
                                tenant=f"sat{s}", timeout_s=180,
                                idempotency_key=f"satburst{nw}-{b}-{s}"),
                            name=f"bench-serve-warm{i}")
                        for i, s in enumerate(range(load_clients))]
                    for t in bthreads:
                        t.join()
                    if time.monotonic() - tb < 1.0:
                        break
                stop_at = time.monotonic() + sat_s
                sat_lats = [[] for _ in range(load_clients)]

                def sat_loader(lats, seed):
                    c = ServiceClient(fsock, retries=6,
                                      backoff_base_s=0.05, seed=seed)
                    n = 0
                    while time.monotonic() < stop_at:
                        t1 = time.monotonic()
                        n += 1
                        c.request("fixpoint_density",
                                  {"seed": seed, "trials": load_trials,
                                   "batch": load_trials},
                                  tenant=f"sat{seed}", timeout_s=60,
                                  idempotency_key=f"sat{nw}-{seed}-{n}")
                        lats.append(time.monotonic() - t1)

                t1 = time.monotonic()
                sat_threads = [
                    spawn_thread(sat_loader, name=f"bench-serve-sat{i}",
                                 args=(sat_lats[i], i))
                    for i in range(load_clients)]
                for t in sat_threads:
                    t.join()
                wall = time.monotonic() - t1
                flat = [x for lst in sat_lats for x in lst]
                st = fc.stats()
                front = st.get("front") or {}
                row.update(
                    clients=load_clients,
                    window_s=round(wall, 2),
                    requests=len(flat),
                    requests_per_sec=round(len(flat) / max(wall, 1e-9), 2),
                    p50_ms=round(1e3 * quantile_from_times(flat, 0.5), 1),
                    p95_ms=round(1e3 * quantile_from_times(flat, 0.95), 1),
                    # admitted-vs-replayed: replay > 0 here would mean a
                    # worker died mid-load and the journal healed it —
                    # on a clean bench box both rows read replays=0
                    admitted=front.get("admitted", len(flat)),
                    replayed=front.get(
                        "replayed",
                        (st.get("self_healing") or {}).get("replayed", 0)),
                    deaths=front.get("deaths", 0))
            finally:
                try:
                    ServiceClient(fsock).shutdown()
                except Exception:
                    pass
                try:
                    proc.wait(timeout=60)
                except subprocess.TimeoutExpired:
                    proc.kill()
                    proc.wait(timeout=10)
            return row

        out["saturation"] = {}
        for nw in sat_workers:
            _hb("serve", "saturation", workers=nw)
            out["saturation"][f"w{nw}"] = saturation_row(nw)
    finally:
        # teardown runs on EVERY path: an exception above must not leave
        # the non-daemon server/writer threads alive (the child would
        # burn the whole stage timeout instead of failing fast) or rmtree
        # the root out from under a live server
        if server_thread is not None:
            try:
                ServiceClient(sock).shutdown()
            except Exception:
                pass
            server_thread.join(timeout=30)
        if svc is not None:
            try:
                svc.close()
            except Exception:
                pass
        shutil.rmtree(root, ignore_errors=True)
    return out


def _multihost_leg() -> dict:
    """The distributed-tier benchmark (host CPU, 2 processes over a gloo
    CPU mesh): ONE mega_soup config run twice — single-process sharded,
    then through ``python -m srnn_tpu.distributed.launch --processes 2``
    — wall-clocked end to end (compile served by the shared persistent
    cache) with the final checkpoints compared BITWISE.  On this host
    the multi-process spelling pays the gloo/process tax (the honest
    number this leg exists to record); the TPU-pod row is wired for the
    next TPU window (``scripts/tpu_window.sh`` + the supervisor's
    ``--stall-timeout-s`` triage path) rather than faked here."""
    import shutil
    import tempfile

    import numpy as np

    size = int(os.environ.get("SRNN_BENCH_MULTIHOST_N", "4096"))
    gens = int(os.environ.get("SRNN_BENCH_MULTIHOST_GENS", "24"))
    procs = int(os.environ.get("SRNN_BENCH_MULTIHOST_PROCS", "2"))
    repo = os.path.dirname(os.path.abspath(__file__))
    root = tempfile.mkdtemp(prefix="srnn_multihost_bench_")
    cfg = ["mega_soup", "--size", str(size), "--generations", str(gens),
           "--checkpoint-every", str(max(1, gens // 3)), "--seed", "29",
           "--sharded"]
    env = dict(os.environ)
    env["SRNN_SETUPS_PLATFORM"] = "cpu"
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    out = {"size": size, "generations": gens, "processes": procs,
           "tpu_pod": "wired, pending next TPU window: drive via "
                      "scripts/tpu_window.sh with --stall-timeout-s so a "
                      "wedge yields a triage bundle, not a dead row"}
    try:
        _hb("multihost", "solo", size=size, gens=gens)
        t0 = time.monotonic()
        solo = subprocess.run(
            [sys.executable, "-m", "srnn_tpu.setups", *cfg,
             "--root", os.path.join(root, "solo")],
            env=env, cwd=repo, capture_output=True, text=True)
        out["solo_wall_s"] = round(time.monotonic() - t0, 2)
        if solo.returncode != 0:
            # tracebacks and launcher diagnostics land on stderr; the
            # stdout tail alone is progress chatter
            out["error"] = f"solo leg rc={solo.returncode}: " \
                + (solo.stderr[-400:] or solo.stdout[-400:])
            return out
        _hb("multihost", "launcher", processes=procs)
        t0 = time.monotonic()
        multi = subprocess.run(
            [sys.executable, "-m", "srnn_tpu.distributed.launch",
             "--processes", str(procs), "--", *cfg,
             "--root", os.path.join(root, "dist")],
            env=env, cwd=repo, capture_output=True, text=True)
        out["multi_wall_s"] = round(time.monotonic() - t0, 2)
        if multi.returncode != 0:
            out["error"] = f"launcher leg rc={multi.returncode}: " \
                + (multi.stderr[-400:] or multi.stdout[-400:])
            return out
        out["solo_gens_per_sec"] = round(gens / out["solo_wall_s"], 3)
        out["multi_gens_per_sec"] = round(gens / out["multi_wall_s"], 3)
        out["process_tax"] = round(out["multi_wall_s"]
                                   / out["solo_wall_s"], 2)
        import glob as _glob

        from srnn_tpu.experiment import restore_checkpoint

        a = restore_checkpoint(
            _glob.glob(os.path.join(root, "solo", "exp-*"))[0]
            + f"/ckpt-gen{gens:08d}")
        b = restore_checkpoint(
            _glob.glob(os.path.join(root, "dist", "exp-*"))[0]
            + f"/ckpt-gen{gens:08d}")
        out["bitwise_equal"] = bool(
            np.array_equal(np.asarray(a.weights), np.asarray(b.weights))
            and np.array_equal(np.asarray(a.uids), np.asarray(b.uids)))
        _hb("multihost", "done", bitwise=out["bitwise_equal"])
    finally:
        shutil.rmtree(root, ignore_errors=True)
    return out


def _emit_result(out: dict) -> None:
    """Print one sentinel result line, with any cost-ledger write
    failures attached (``telemetry.costs``): ledger trouble must surface
    in the parent's stage_log rows, not vanish into child stdout."""
    try:
        from srnn_tpu.telemetry import costs

        errs = costs.consume_ledger_errors()
        if errs:
            out["ledger_errors"] = list(out.get("ledger_errors", [])) + errs
    except Exception:
        pass
    print(_SENTINEL + json.dumps(out), flush=True)
    sys.stdout.flush()


def _child_stage(stage: str) -> None:
    """Run one stage and print its result on a sentinel stdout line."""
    # the dead-man's switch arms BEFORE the simulated/real wedge windows
    # (backend init, compile) so a hang still yields a triage artifact
    _arm_stall_sentinel(stage)
    if stage in os.environ.get("SRNN_BENCH_TEST_HANG", "").split(","):
        time.sleep(3600)  # test hook: simulate a wedged backend init

    from srnn_tpu.utils.backend import ensure_backend, force_cpu

    forced_cpu = os.environ.get("SRNN_BENCH_PLATFORM") == "cpu"
    if forced_cpu:
        # pin via jax.config BEFORE any device probe: the axon sitecustomize
        # overrides the JAX_PLATFORMS env var at register() time, so the env
        # route cannot keep a child off the (possibly wedged) tunnel
        force_cpu()
        platform, fell_back = "cpu", False
    else:
        platform, fell_back = ensure_backend(retries=3, sleep_s=10.0,
                                             fallback_cpu=True)
    _hb(stage, "backend", platform=platform)
    import jax

    from srnn_tpu import Topology
    from srnn_tpu.utils.aot import ensure_compilation_cache

    # persistent executable cache (min-compile-time floor dropped so even
    # the ramp program is cached): the parent exports the dir, this call
    # turns the machinery on for this child
    ensure_compilation_cache()

    if stage == "serve":
        # the experiment-service load leg (host CPU by construction: the
        # parent pins SRNN_BENCH_PLATFORM=cpu so a wedged tunnel cannot
        # eat the only leg that always lands)
        out = {"serve": _serve_leg(), "device_count": jax.device_count(),
               "backend": platform + ("-forced" if forced_cpu else "")}
        _emit_result(out)
        os._exit(0)
    if stage == "multihost":
        # the distributed-tier leg (host CPU, subprocess workers — this
        # child only orchestrates and verifies)
        out = {"multihost": _multihost_leg(),
               "device_count": jax.device_count(),
               "backend": platform + ("-forced" if forced_cpu else "")}
        _emit_result(out)
        os._exit(0)
    topo = Topology("weightwise", width=2, depth=2)  # science-default f32
    on_cpu = platform == "cpu"  # fallback OR a genuinely CPU-default host
    if stage == "precompile":
        # compile-only stage: exactly the shapes/impls the measurement
        # stages will dispatch — on a CPU host the degraded shape in BOTH
        # the fused-chain and the legacy-scan comparison spellings
        shapes = [(RAMP_N, RAMP_STEPS, "auto")]
        shapes += [(100_000, 20, "auto"), (100_000, 20, "scan")] if on_cpu \
            else [(N, STEPS_PER_CALL, "auto")]
        # block autotuner (srnn_tpu.autotune): measure-or-memo the
        # apply-chain tile for the measured shape BEFORE compiling the
        # bench entries, so the warmed executables ARE the tuned programs
        # and the measurement children (same tuning.json, next to the
        # shared cache) deserialize them.  Only the non-Mosaic route has
        # the block knob; SRNN_NO_AUTOTUNE=1 is the A/B oracle.
        tuned_block = None
        if on_cpu:
            try:
                from srnn_tpu import autotune

                e = autotune.autotune_apply_chain(topo, 100_000, 20)
                tuned_block = e.get("block") if e else None
                _hb(stage, "autotune", block=tuned_block)
            except Exception:
                pass
        rows = _precompile(topo, shapes)
        out = {"precompile": rows, "device_count": jax.device_count(),
               "backend": platform, "autotune_block": tuned_block}
        _emit_result(out)
        os._exit(0)
    cpu_degraded = False
    if stage == "ramp":
        # tiny shapes — proves compile + execute end-to-end and leaves a
        # nonzero fail-soft number if the full run dies
        apps, overlap = _measure(topo, RAMP_N, RAMP_STEPS, 1, stage=stage)
    elif on_cpu:
        # degraded run: the full 1M x 2000-step workload would take hours
        # on host CPU; report a reduced honest measurement on the
        # lane-blocked fused chain (min-wall over 3 dispatches — same
        # protocol the autotuner judges this exact program by)
        cpu_degraded = True
        apps, overlap = _measure(topo, 100_000, 20, 5, stage=stage,
                                 best=True)
    else:
        apps, overlap = _measure(topo, N, STEPS_PER_CALL, CALLS, stage=stage)
    out = {
        "apps_per_chip": apps / jax.device_count(),
        "device_count": jax.device_count(),
        "backend": platform + ("-fallback" if fell_back else
                               "-forced" if forced_cpu else ""),
        "pipeline": overlap,
    }
    # the PRIMARY measurement is delivered before any secondary work: the
    # parent keeps the LAST intact sentinel, so a kill during the
    # comparison below still salvages this line
    _emit_result(out)
    if cpu_degraded:
        # comparison row: the legacy step-by-step scan at the same shape,
        # so the fused-chain win is visible inside ONE session (this
        # host's load drifts session to session); re-emit the merged row
        scan_apps, _ = _measure(topo, 100_000, 20, 5, stage=stage,
                                impl="scan", best=True)
        out["impl"] = "fused-chain"
        out["scan_apps_per_chip"] = scan_apps / jax.device_count()
        # which lane block the fused-chain leg actually ran (None =
        # untuned default 2048) — regress.py's tuned-leg sentinel reads
        # this to catch an autotuning regression, not just a wall one
        try:
            from srnn_tpu import autotune

            out["tuned_block"] = autotune.lookup(
                "apply_chain", topo.variant, 100_000, topo.num_weights,
                dtype="float32")
        except Exception:
            pass
        _emit_result(out)
    # skip interpreter/backend teardown: a dead tunnel can hang atexit
    # handlers after the measurement is already delivered
    os._exit(0)


# --------------------------------------------------------------------------
# parent side: orchestration only (no jax import — cannot wedge)
# --------------------------------------------------------------------------

#: how many meaningful child-stderr lines the parent relays per stage —
#: the driver captures only the TAIL of this process's combined output, so
#: an unbounded relay lets one noisy child evict the useful last lines
STDERR_TAIL_LINES = int(os.environ.get("SRNN_BENCH_STDERR_TAIL", "15"))


def _relay_child_stderr(stage: str, stderr_bytes) -> None:
    """Bounded, de-flooded relay of a captured child stderr onto the
    parent's stderr-diagnostics stream.

    BENCH_r05's tail was eaten by ONE diagnostic: jax's persistent
    compilation cache warns about a 'machine features' mismatch with the
    full +avx…/-amx… feature inventory of both machines (>4 KB per line),
    which evicted every useful line from the driver's captured tail.
    Those lines collapse to a one-line count; everything else keeps only
    the final ``STDERR_TAIL_LINES`` meaningful (non-blank) lines."""
    if not stderr_bytes:
        return
    kept, suppressed = [], 0
    for ln in stderr_bytes.decode(errors="replace").splitlines():
        if not ln.strip():
            continue
        if "machine features" in ln:
            suppressed += 1
            continue
        if len(ln) > 2000:
            # unrelated long lines (an XLA status with an HLO snippet, a
            # long traceback line) stay VISIBLE, just bounded
            ln = ln[:400] + " ...[truncated]"
        kept.append(ln)
    if suppressed:
        kept.append(f"[{suppressed} compilation-cache machine-features "
                    "mismatch diagnostic(s) suppressed]")
    if len(kept) > STDERR_TAIL_LINES:
        omitted = len(kept) - STDERR_TAIL_LINES
        kept = [f"... {omitted} earlier line(s) omitted"] \
            + kept[-STDERR_TAIL_LINES:]
    for ln in kept:
        print(f"bench[{stage}] {ln}", file=sys.stderr, flush=True)


def _run_child(stage: str, timeout: float, env: dict):
    """Spawn one stage as a fresh process.  Returns (result_dict | None,
    error_str | None, last_heartbeat | None).  On timeout the child is
    killed — a wedged backend dies with its process, which an in-process
    retry provably cannot do (BENCH_r03); its partial stdout still yields
    the last heartbeat it printed, attributing WHERE the budget went.
    Child stderr is captured and relayed truncated/de-flooded
    (``_relay_child_stderr``) so diagnostics survive the driver's
    tail-capture without evicting the JSON result lines."""
    cmd = [sys.executable, os.path.abspath(__file__), "--stage", stage]
    try:
        proc = subprocess.run(cmd, stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE,
                              timeout=timeout, env=env)
        out, err, rc = proc.stdout, proc.stderr, proc.returncode
    except subprocess.TimeoutExpired as e:
        # the child may have PRINTED its measurement and then hung in
        # backend teardown — salvage the sentinel from the partial stdout
        # rather than discarding a completed run
        out, err, rc = e.stdout, e.stderr, None
    _relay_child_stderr(stage, err)
    parsed = _parse_result(out)
    hb = _parse_last_heartbeat(out)
    if parsed is not None:
        return parsed, None, hb
    if rc is None:
        return None, f"timeout>{timeout:.0f}s", hb
    # the elastic supervisor's exit-code vocabulary (srnn_tpu.resilience):
    # a preempted or retry-exhausted child is a NAMED outcome in the
    # stage_log, not an anonymous nonzero rc that reads like a wedge
    try:
        from srnn_tpu.resilience import EXIT_CODE_NAMES
        named = EXIT_CODE_NAMES.get(rc)
    except Exception:
        named = None
    suffix = f" ({named})" if named else ""
    return None, f"rc={rc}{suffix}, no result line", hb


def _scan_sentinel(stdout_bytes, sentinel):
    if not stdout_bytes:
        return None
    for line in reversed(stdout_bytes.decode(errors="replace").splitlines()):
        if line.startswith(sentinel):
            try:
                return json.loads(line[len(sentinel):])
            except json.JSONDecodeError:
                # a killed child's LAST line may be torn or interleaved
                # with C++ runtime noise — keep scanning for an earlier
                # intact row rather than discarding the whole trail
                continue
    return None


def _parse_result(stdout_bytes):
    return _scan_sentinel(stdout_bytes, _SENTINEL)


def _parse_last_heartbeat(stdout_bytes):
    return _scan_sentinel(stdout_bytes, _HB_SENTINEL)


LINT_TIMEOUT_S = float(os.environ.get("SRNN_BENCH_LINT_TIMEOUT_S", "120"))


def _lint_preflight(stage_log, errors, env, t_start) -> bool:
    """Run ``python -m srnn_tpu.analysis --fast`` before any measured
    stage.  rc 1 (unwaived findings) FAILS the bench; rc 0 passes;
    anything else — analyzer crash, timeout — is recorded as
    inconclusive and does not block (the lint tier must never be able to
    wedge a bench run the way the tunnel can)."""
    att = {"stage": "lint", "attempt": 1,
           "t_start_s": round(time.monotonic() - t_start, 1)}
    child_env = dict(env)
    child_env["JAX_PLATFORMS"] = "cpu"   # no device needed for analysis
    child_env.pop("PYTHONPATH", None)    # never dial the axon tunnel
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "srnn_tpu.analysis", "--fast"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            env=child_env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            timeout=LINT_TIMEOUT_S)
        rc = proc.returncode
        out = proc.stdout.decode("utf-8", "replace")
    except Exception as e:  # TimeoutExpired, missing interpreter, ...
        att["outcome"] = f"inconclusive: {type(e).__name__}"
        att["t_end_s"] = round(time.monotonic() - t_start, 1)
        stage_log.append(att)
        return True
    att["t_end_s"] = round(time.monotonic() - t_start, 1)
    if rc == 0:
        att["outcome"] = "ok"
        stage_log.append(att)
        return True
    if rc == 1:
        att["outcome"] = "findings"
        att["findings"] = [l for l in out.strip().splitlines() if l][-12:]
        errors.append("lint: unwaived srnnlint findings; run "
                      "`python -m srnn_tpu.analysis` locally")
        stage_log.append(att)
        return False
    att["outcome"] = f"inconclusive: rc={rc}"
    stage_log.append(att)
    return True


REGRESS_TIMEOUT_S = float(os.environ.get("SRNN_BENCH_REGRESS_TIMEOUT_S",
                                         "60"))


def _regress_sentinel(result) -> None:
    """Advisory perf-regression verdict (``benchmarks/regress.py``): the
    fresh result vs the committed BENCH_*.json trajectory, embedded as
    ``result["regression"]`` with its own stage_log row — a throughput
    regression is flagged in the round that causes it, not three windows
    later.  Advisory by design: findings never change the bench's exit
    or its measured values.  Subprocess like every other stage (the
    parent stays un-wedgeable); pure stdlib child, but bounded anyway."""
    stage_log = result.setdefault("stage_log", [])
    att = {"stage": "regress", "attempt": 1}
    try:
        proc = subprocess.run(
            [sys.executable, os.path.join("benchmarks", "regress.py"),
             "-", "--json", "--include-self"],
            cwd=os.path.dirname(os.path.abspath(__file__)),
            input=json.dumps(result).encode(), stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, timeout=REGRESS_TIMEOUT_S)
        verdict = json.loads(proc.stdout.decode("utf-8", "replace"))
    except Exception as e:  # advisory: never let the sentinel hurt the row
        att["outcome"] = f"inconclusive: {type(e).__name__}"
        stage_log.append(att)
        return
    regressions = verdict.get("regressions", [])
    att["outcome"] = "ok" if not regressions \
        else f"{len(regressions)} regression(s)"
    if regressions:
        # the findings land in the stage_log TOO (the driver's tail
        # capture reads stage_log rows; result["regression"] carries the
        # full per-leg table)
        att["findings"] = [f["message"] for f in regressions]
    stage_log.append(att)
    result["regression"] = verdict


#: bench-round archive next to the BENCH_*.json trajectory: every round
#: joins the longitudinal history whether or not it gets committed, and
#: ``regress.py --from-archive`` medians over ALL of them.  The row
#: format ({"kind": "bench_round", "t": ..., "result": {...}}) is shared
#: with regress.py's stdlib reader; telemetry.archive documents it.
BENCH_ARCHIVE_NAME = "BENCH_archive.jsonl"
BENCH_ARCHIVE_MAX_ROUNDS = 200


def _archive_sentinel(result) -> None:
    """Append the round to ``BENCH_archive.jsonl`` (bounded: compacted to
    the newest rounds past the cap).  Pure stdlib INLINE — the parent's
    un-wedgeable contract forbids importing srnn_tpu (and with it jax)
    here, which is why this does not call telemetry.archive.  Advisory
    like the regress sentinel: a failure costs a stage_log note, never
    the round.  ``SRNN_BENCH_ARCHIVE=0`` opts out (tests and throwaway
    runs keep the repo root clean)."""
    stage_log = result.setdefault("stage_log", [])
    att = {"stage": "archive", "attempt": 1}
    if os.environ.get("SRNN_BENCH_ARCHIVE", "1") == "0":
        att["outcome"] = "disabled"
        stage_log.append(att)
        return
    try:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            BENCH_ARCHIVE_NAME)
        with open(path, "a") as f:
            f.write(json.dumps({"kind": "bench_round", "t": time.time(),
                                "result": result}) + "\n")
            f.flush()
            os.fsync(f.fileno())
        rows = []
        with open(path) as f:
            for line in f:
                line = line.strip()
                if line:
                    rows.append(line)
        if len(rows) > BENCH_ARCHIVE_MAX_ROUNDS:
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write("\n".join(rows[-BENCH_ARCHIVE_MAX_ROUNDS:]) + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            rows = rows[-BENCH_ARCHIVE_MAX_ROUNDS:]
        att["outcome"] = "ok"
        att["path"] = path
        att["rounds"] = len(rows)
    except Exception as e:  # advisory: never let the archive hurt the row
        att["outcome"] = f"inconclusive: {type(e).__name__}"
    stage_log.append(att)


def main():
    result = {
        "metric": "self-applications/sec/chip",
        "value": 0,
        "unit": "applications/s",
        "vs_baseline": 0.0,
    }
    try:
        _orchestrate(result)
    except Exception as e:  # fail-soft: the one-JSON-line contract holds
        import traceback

        traceback.print_exc()
        result.setdefault("error", f"parent: {type(e).__name__}: {e}")
    result["vs_baseline"] = round(result["value"] / BASELINE_PER_CHIP, 2)
    try:
        _regress_sentinel(result)
    except Exception:
        pass  # the one-JSON-line contract always wins
    try:
        _archive_sentinel(result)
    except Exception:
        pass
    print(json.dumps(result), flush=True)


def _orchestrate(result):
    t_start = time.monotonic()
    errors = []
    # per-attempt heartbeat trail in the emitted JSON: every child attempt
    # gets a start/end/outcome row (+ the child's last milestone heartbeat
    # when it timed out), so a bad round's BENCH_*.json names which stage
    # and which step ate the deadline instead of just "deadline exhausted"
    stage_log = result["stage_log"] = []

    env = dict(os.environ)
    # persistent compile cache: a retried stage skips the compile that
    # wedged; also shared ramp -> full within one run
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                ".jax_cache"))
    try:
        os.makedirs(env["JAX_COMPILATION_CACHE_DIR"], exist_ok=True)
    except OSError:
        # never let cache-dir trouble break the one-JSON-line contract;
        # children just run uncached
        env.pop("JAX_COMPILATION_CACHE_DIR", None)

    # srnnlint preflight: a static-analysis regression fails the bench in
    # SECONDS, before any measured stage spends minutes compiling — the
    # numbers' provenance is only trustworthy over a lint-clean tree
    if not _lint_preflight(stage_log, errors, env, t_start):
        result["error"] = "srnnlint preflight failed (unwaived findings); " \
                          "see stage_log"
        return

    def remaining():
        return DEADLINE_S - (time.monotonic() - t_start)

    def run_stage(stage, attempts, per_timeout, stage_env=None, reserve=0.0,
                  retry_timeout=None, tag=None):
        # retries never get a LONGER leash than the stage's own timeout
        # (an operator-lowered SRNN_BENCH_RAMP_TIMEOUT_S must win)
        retry_want = per_timeout if retry_timeout is None \
            else min(per_timeout, retry_timeout)
        for i in range(attempts):
            if remaining() - reserve <= 10:
                errors.append(f"{tag or stage}: deadline exhausted"
                              + (" (rescue slice reserved)" if reserve else ""))
                stage_log.append({"stage": tag or stage, "attempt": i + 1,
                                  "outcome": "skipped: deadline exhausted",
                                  "t_start_s": round(time.monotonic()
                                                     - t_start, 1)})
                return None
            want = per_timeout if i == 0 else retry_want
            t = min(want, remaining() - reserve)
            att = {"stage": tag or stage, "attempt": i + 1,
                   "timeout_s": round(t, 1),
                   "t_start_s": round(time.monotonic() - t_start, 1)}
            # arm the child's stall sentinel just inside this attempt's
            # timeout, so a wedge writes its triage bundle BEFORE the kill
            # (an operator-exported SRNN_BENCH_STALL_S wins)
            child_env = dict(stage_env or env)
            child_env.setdefault("SRNN_BENCH_STALL_S",
                                 str(round(max(20.0, t * 0.8), 1)))
            r, err, hb = _run_child(stage, t, child_env)
            att["t_end_s"] = round(time.monotonic() - t_start, 1)
            att["outcome"] = "ok" if r is not None else err
            if hb is not None:
                att["last_heartbeat"] = hb
                if r is None and hb.get("triage_bundle"):
                    # a failed/timed-out attempt names its artifact: the
                    # child's stall sentinel wrote a bundle before the kill
                    att["triage_bundle"] = hb["triage_bundle"]
            if r is not None and "pipeline" in r:
                # device-idle/overlap attribution alongside the stage_log
                # row: a slow-but-successful attempt names host stall vs
                # device compute (timed-out attempts carry the same
                # cumulative numbers on their last_heartbeat)
                att["pipeline"] = r["pipeline"]
            if r is not None and r.get("ledger_errors"):
                # cost-ledger write failures surface HERE (stage_log
                # discipline, like the multihost error rows) instead of
                # vanishing into child stdout
                att["ledger_errors"] = r["ledger_errors"]
                errors.append(f"{tag or stage}: cost-ledger write "
                              f"failure(s): {r['ledger_errors'][0]}")
            stage_log.append(att)
            if r is not None:
                return r
            errors.append(f"{tag or stage} attempt {i + 1}/{attempts}: {err}")
            print(f"bench: {errors[-1]}; retrying in a fresh process"
                  if i + 1 < attempts else f"bench: {errors[-1]}",
                  file=sys.stderr, flush=True)
            # a HANG at production scale: space the next attempt out so it
            # samples a different tunnel state (back-to-back retries after
            # a 400s wedge learned nothing in r4).  Production-ness is the
            # STAGE's configured timeout (test-scale stages must not
            # inherit multi-minute sleeps); never sleep into the reserve
            # or below the NEXT attempt's own budget
            if (err and err.startswith("timeout") and i + 1 < attempts
                    and per_timeout >= SPACING_MIN_TIMEOUT_S
                    and remaining() - reserve
                    > RETRY_SPACING_S + retry_want + 30):
                time.sleep(RETRY_SPACING_S)
        return None

    def take(measured, stage_tag):
        result["value"] = round(measured["apps_per_chip"])
        result["device_count"] = measured["device_count"]
        result["backend"] = measured["backend"]
        if "impl" in measured:
            # the fused-chain CPU spelling carries its legacy-scan
            # comparison row so the fused win is visible in ONE session
            result["impl"] = measured["impl"]
            result["scan_apps_per_chip"] = round(
                measured["scan_apps_per_chip"])
            # the lane block the fused-chain leg ran (None = untuned
            # 2048 default) — regress.py's tuned-leg sentinel input
            result["tuned_block"] = measured.get("tuned_block")
        else:
            result.pop("impl", None)
            result.pop("scan_apps_per_chip", None)
            result.pop("tuned_block", None)
        if stage_tag:
            result["stage"] = stage_tag
        else:
            result.pop("stage", None)

    def run_rescue(tag="cpu-rescue"):
        # a labeled host-CPU number is strictly more information than
        # value=0 (the r3 scorecard)
        cpu_env = dict(env)
        cpu_env["SRNN_BENCH_PLATFORM"] = "cpu"
        # the hang hook simulates a wedged TUNNEL; a CPU-pinned rescue child
        # never dials it, so the simulated wedge does not apply
        cpu_env.pop("SRNN_BENCH_TEST_HANG", None)
        return run_stage("full", 1, 300.0, stage_env=cpu_env, tag=tag)

    # CPU-first throughput bank: on a burstable single-vCPU host the
    # serve + multihost fleets below drain the hypervisor's burst budget,
    # throttling any CPU measurement taken after them by ~40% (r08
    # triage: the same full-stage child measures 36.9M apps/s solo vs
    # 23-25M when run 85s into the bench, with zero competing processes).
    # Bank the degraded-CPU number FIRST, while the budget is intact; an
    # accelerator window is unaffected — a non-CPU ramp/full measurement
    # below overwrites this row, and only the CPU-only host skips its
    # (throttled, duplicate) full re-measure.
    cpu_first = None
    if SERVE_TIMEOUT_S > 0 or MULTIHOST_TIMEOUT_S > 0:
        cpu_first = run_rescue(tag="cpu-first")
        if cpu_first is not None:
            take(cpu_first, "cpu-first")

    # experiment-service load leg next: CPU-pinned (immune to the
    # tunnel), bounded, and the round's BENCH row for the serve subsystem
    # — running it up front guarantees it lands even when every
    # accelerator attempt later eats its full timeout.  Reserves the
    # rescue slice so a slow serve leg cannot starve the one
    # accelerator-value guarantee.
    if SERVE_TIMEOUT_S > 0:
        serve_env = dict(env)
        serve_env["SRNN_BENCH_PLATFORM"] = "cpu"
        serve_env.pop("SRNN_BENCH_TEST_HANG", None)  # CPU leg never dials
        srv = run_stage("serve", 1,
                        min(SERVE_TIMEOUT_S,
                            max(60.0, remaining() - RESCUE_RESERVE_S
                                - 420)),
                        stage_env=serve_env, reserve=RESCUE_RESERVE_S,
                        tag="serve")
        if srv is not None and "serve" in srv:
            result["serve"] = srv["serve"]

    # distributed-tier leg: CPU-pinned like serve (immune to the tunnel),
    # bounded, rescue slice reserved — the round's BENCH row for the
    # multi-host runtime (2-process CPU mesh, bitwise-verified; the TPU
    # pod row stays wired-not-measured until the next window)
    if MULTIHOST_TIMEOUT_S > 0:
        mh_env = dict(env)
        mh_env["SRNN_BENCH_PLATFORM"] = "cpu"
        mh_env.pop("SRNN_BENCH_TEST_HANG", None)  # CPU leg never dials
        mh = run_stage("multihost", 1,
                       min(MULTIHOST_TIMEOUT_S,
                           max(60.0, remaining() - RESCUE_RESERVE_S
                               - 420)),
                       stage_env=mh_env, reserve=RESCUE_RESERVE_S,
                       tag="multihost")
        if mh is not None and "multihost" in mh:
            result["multihost"] = mh["multihost"]

    # compile-only warm-up: one bounded child fills the shared persistent
    # cache (ramp + full shapes), so the measurement children below
    # deserialize executables instead of compiling inside their timed
    # window.  Skipped when the budget is already thin; a timeout here is
    # recorded but never blocks the stages that actually measure.
    if remaining() - RESCUE_RESERVE_S > PRECOMPILE_MIN_BUDGET_S:
        pre = run_stage("precompile", 1,
                        min(PRECOMPILE_TIMEOUT_S,
                            remaining() - RESCUE_RESERVE_S - 15))
        if pre is not None and "precompile" in pre:
            result["precompile"] = pre["precompile"]

    ramp = run_stage("ramp", RAMP_ATTEMPTS, RAMP_TIMEOUT_S,
                     reserve=RESCUE_RESERVE_S,
                     retry_timeout=RAMP_RETRY_TIMEOUT_S)
    if ramp is not None:
        # a host-CPU ramp re-measures the cpu-first workload on a
        # now-throttled host — never let it overwrite the honest banked
        # row; an accelerator ramp is new information and always wins
        if not (cpu_first is not None
                and ramp["backend"].startswith("cpu")):
            take(ramp, "ramp-only")

    banked = None
    if ramp is None and cpu_first is None:
        # every ramp attempt wedged: BANK the rescue number NOW (r4's
        # policy only ran it after the full attempts also burned their
        # budget), then still spend the remaining window on accelerator
        # retries — a later success simply overwrites the banked row
        banked = run_rescue()
        if banked is not None:
            take(banked, "cpu-rescue")

    # once any measurement exists the final rescue leg is moot, so the
    # full stage may spend the whole remaining deadline.  A CPU-only
    # host (ramp measured on host CPU) with a banked cpu-first row skips
    # the full stage outright: it would repeat the exact cpu-first
    # measurement on a now-throttled host and overwrite the honest row
    # with a worse one.
    full = None
    cpu_only_host = ramp is not None and ramp["backend"].startswith("cpu")
    if not (cpu_first is not None and cpu_only_host):
        full = run_stage("full", FULL_ATTEMPTS, FULL_TIMEOUT_S,
                         reserve=0.0 if (ramp is not None
                                         or banked is not None
                                         or cpu_first is not None)
                         else RESCUE_RESERVE_S)
    if full is not None:
        # keep the BEST measurement: a full-stage child whose own backend
        # init fell back to host CPU (per-process tunnel luck) must not
        # overwrite a real accelerator ramp number — nor the banked rescue
        # row (the fallback full run is the same degraded CPU workload,
        # only unlabeled)
        accel_ramp = ramp is not None and not ramp["backend"].endswith(
            ("-fallback", "-forced"))
        if full["backend"].endswith("-fallback") and (
                accel_ramp or banked is not None):
            errors.append("full stage fell back to CPU; keeping the "
                          + ("accelerator ramp" if accel_ramp
                             else "banked cpu-rescue") + " measurement")
        else:
            take(full, None)

    if ramp is None and full is None and banked is None \
            and cpu_first is None:
        rescue = run_rescue()
        if rescue is not None:
            take(rescue, "cpu-rescue")

    # Healthy-window piggyback: a successful ACCELERATOR measurement proves
    # the tunnel is open RIGHT NOW — possibly the round's only window — so
    # spend whatever deadline remains capturing the newest perf-lever rows
    # via the opportunistic harness (it appends JSONL evidence itself; its
    # stdout is discarded to preserve this script's one-JSON-line
    # contract).  Bounded by the remaining budget; a timeout keeps the
    # rows already captured.
    # SRNN_REQUIRE_TPU marks a child spawned BY the opportunistic harness
    # (its kernel row runs this script) — piggybacking there would recurse
    # and run every lever twice inside the same window
    if (result["value"] > 0 and "cpu" not in result.get("backend", "cpu")
            and remaining() > 150
            and os.environ.get("SRNN_REQUIRE_TPU", "0") in ("", "0")):
        lever_rows = ["train_generality", "soup_rnn_apply", "soup_full",
                      "soup_mixed", "profile"]
        budget = max(remaining() - 30, 60)
        # the opportunistic PARENT must start without the axon
        # sitecustomize on PYTHONPATH (a tunnel wedge would otherwise
        # block its interpreter in recvfrom before main() — its own
        # documented contract); it recomposes each child's PYTHONPATH
        p_env = dict(env)
        p_env["PYTHONPATH"] = ""
        try:
            proc = subprocess.Popen(
                [sys.executable, "benchmarks/opportunistic.py",
                 "--rows", *lever_rows,
                 "--row-timeout", str(round(budget))],
                cwd=os.path.dirname(os.path.abspath(__file__)),
                env=p_env, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL, start_new_session=True)
            try:
                proc.wait(timeout=budget)
            except subprocess.TimeoutExpired:
                # kill the whole session so an in-flight row child cannot
                # keep holding the tunnel after bench exits
                import signal

                os.killpg(proc.pid, signal.SIGKILL)
                proc.wait(timeout=10)
            result["opportunistic"] = "attempted (see "\
                "results_tpu/opportunistic_log.jsonl)"
        except Exception as e:
            errors.append(f"opportunistic piggyback: {type(e).__name__}")

    if errors:  # always surface what happened, even when a stage recovered
        result["error"] = "; ".join(errors)


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--stage":
        _child_stage(sys.argv[2])
    else:
        main()
