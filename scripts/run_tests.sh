#!/usr/bin/env bash
# One-command test suite for srnn_tpu.
#
# WHY THIS EXISTS: running all ~250 tests in a single pytest process
# accumulates toward a segfault inside XLA-CPU's backend_compile_and_load
# (observed rounds 3-5; bisected in round 4 to upstream XLA state that one
# process's hundreds of distinct compiles build up — each test file passes
# solo, every mid-size subset passes, the one-process full suite dies
# ~25 min in).  The cure is process isolation: this script runs each test
# FILE in its own pytest process, sequentially.  The shared compilation
# cache (JAX_COMPILATION_CACHE_DIR, managed by tests/conftest.py together
# with its crash-marker hygiene) keeps repeat compiles cheap, so the cost
# of isolation is only ~8 s of JAX import per file.
#
# Usage:
#   scripts/run_tests.sh              # whole suite
#   scripts/run_tests.sh -k pattern   # extra args forwarded to every group
#
# Exit code is nonzero if ANY group fails; a per-group summary prints at
# the end either way.
set -u
cd "$(dirname "$0")/.."

# The suite is CPU-only (tests/conftest.py pins jax_platforms=cpu), but the
# axon TPU-tunnel sitecustomize on PYTHONPATH dials the relay at EVERY
# python startup — and when the tunnel is wedged that handshake blocks in
# recvfrom() before pytest even begins (observed round 5: interpreter hung
# 12+ min at startup, 0% CPU).  Strip it: tests/conftest.py puts the repo
# root on sys.path itself, so nothing else is lost.
export PYTHONPATH=
export JAX_PLATFORMS=cpu

pass=0; fail=0; failed_groups=()
summary=""

for f in tests/test_*.py; do
    t0=$SECONDS
    if python -m pytest "$f" -q --no-header "$@"; then
        status=ok; pass=$((pass+1))
    else
        status=FAIL; fail=$((fail+1)); failed_groups+=("$f")
    fi
    summary+=$(printf '%-34s %-4s %4ss' "$f" "$status" "$((SECONDS-t0))")$'\n'
done

echo
echo "=== run_tests.sh summary ==="
printf '%s' "$summary"
echo "groups: $((pass+fail)), failed: $fail"
if [ "$fail" -gt 0 ]; then
    printf 'failed: %s\n' "${failed_groups[@]}"
    exit 1
fi
