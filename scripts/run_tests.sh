#!/usr/bin/env bash
# One-command test suite for srnn_tpu.
#
# WHY THIS EXISTS: running all ~250 tests in a single pytest process
# accumulates toward a segfault inside XLA-CPU's backend_compile_and_load
# (observed rounds 3-5; bisected in round 4 to upstream XLA state that one
# process's hundreds of distinct compiles build up — each test file passes
# solo, every mid-size subset passes, the one-process full suite dies
# ~25 min in).  The cure is process isolation: this script runs each test
# FILE in its own pytest process, sequentially.  The shared compilation
# cache (JAX_COMPILATION_CACHE_DIR, managed by tests/conftest.py together
# with its crash-marker hygiene) keeps repeat compiles cheap, so the cost
# of isolation is only ~8 s of JAX import per file.
#
# Usage:
#   scripts/run_tests.sh              # whole suite
#   scripts/run_tests.sh -k pattern   # extra args forwarded to every group
#
# Exit code is nonzero if ANY group fails; a per-group summary prints at
# the end either way.
set -u
cd "$(dirname "$0")/.."

# The suite is CPU-only (tests/conftest.py pins jax_platforms=cpu), but the
# axon TPU-tunnel sitecustomize on PYTHONPATH dials the relay at EVERY
# python startup — and when the tunnel is wedged that handshake blocks in
# recvfrom() before pytest even begins (observed round 5: interpreter hung
# 12+ min at startup, 0% CPU).  Strip it: tests/conftest.py puts the repo
# root on sys.path itself, so nothing else is lost.
export PYTHONPATH=
export JAX_PLATFORMS=cpu

pass=0; fail=0; failed_groups=()
summary=""

# srnnlint first: a static-analysis regression fails in seconds, before
# the suite spends its 870s budget discovering the same thing (or worse,
# not discovering it).  Same CPU-pinned, tunnel-free env as the suite.
t0=$SECONDS
if python -m srnn_tpu.analysis --fast; then
    status=ok; pass=$((pass+1))
else
    status=FAIL; fail=$((fail+1)); failed_groups+=("srnnlint")
fi
summary+=$(printf '%-34s %-4s %4ss' "srnnlint" "$status" "$((SECONDS-t0))")$'\n'

for f in tests/test_*.py; do
    t0=$SECONDS
    if python -m pytest "$f" -q --no-header "$@"; then
        status=ok; pass=$((pass+1))
    else
        status=FAIL; fail=$((fail+1)); failed_groups+=("$f")
    fi
    summary+=$(printf '%-34s %-4s %4ss' "$f" "$status" "$((SECONDS-t0))")$'\n'
done

# Fast chaos smoke (srnn_tpu/resilience/): one injected finisher stall +
# one poisoned background-writer job in a single supervised smoke run
# must both be RECOVERED — exit 3 ("recovered") and a "supervisor:
# restart" line in the run log.  This drills the retry/resume machinery
# itself on every suite run, not just when the slow e2es are selected.
t0=$SECONDS
smoke_root=$(mktemp -d)
SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.setups mega_soup --smoke \
    --root "$smoke_root" --chaos "stall@2,writer@8" --stall-timeout-s 5 \
    --backoff-base-s 0.1 --backoff-max-s 1 --max-restarts 3 \
    > "$smoke_root/out.log" 2>&1
rc=$?
if [ "$rc" -eq 3 ] && grep -q "supervisor: restart" "$smoke_root"/exp-*/log.txt; then
    status=ok; pass=$((pass+1))
else
    status=FAIL; fail=$((fail+1)); failed_groups+=("chaos_smoke(rc=$rc)")
    tail -n 30 "$smoke_root/out.log"
fi
rm -rf "$smoke_root"
summary+=$(printf '%-34s %-4s %4ss' "chaos_smoke" "$status" "$((SECONDS-t0))")$'\n'

# Fast experiment-service smoke (srnn_tpu/serve/): a real service process
# on a Unix socket, two fixpoint-density smokes submitted concurrently
# (same shapes -> ONE stacked dispatch) plus one odd-shaped run (solo
# fallback).  All three clients must complete and metrics.prom must show
# exactly one stacked + one solo dispatch — the scheduler's grouping and
# fallback drilled on every suite run.
t0=$SECONDS
serve_root=$(mktemp -d)
SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.serve --root "$serve_root/svc" \
    --batch-window-s 2 > "$serve_root/serve.log" 2>&1 &
serve_pid=$!
serve_ok=1
up=0
for _ in $(seq 1 150); do
    if SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.serve \
            --socket "$serve_root/svc/serve.sock" --ping 2>/dev/null; then
        up=1; break
    fi
    sleep 0.2
done
if [ "$up" -eq 1 ]; then
    SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.setups fixpoint_density \
        --smoke --seed 0 --root "$serve_root/exp" \
        --service "$serve_root/svc/serve.sock" \
        >> "$serve_root/serve.log" 2>&1 &
    c1=$!
    SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.setups fixpoint_density \
        --smoke --seed 1 --root "$serve_root/exp" \
        --service "$serve_root/svc/serve.sock" \
        >> "$serve_root/serve.log" 2>&1 &
    c2=$!
    SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.setups fixpoint_density \
        --trials 48 --batch 24 --seed 2 --root "$serve_root/exp" \
        --service "$serve_root/svc/serve.sock" \
        >> "$serve_root/serve.log" 2>&1 &
    c3=$!
    wait $c1 || serve_ok=0
    wait $c2 || serve_ok=0
    wait $c3 || serve_ok=0
    SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.serve \
        --socket "$serve_root/svc/serve.sock" --shutdown \
        >> "$serve_root/serve.log" 2>&1 || serve_ok=0
    wait $serve_pid || serve_ok=0
    grep -q 'srnn_serve_dispatches_total{kind="fixpoint_density",mode="stacked"} 1' \
        "$serve_root/svc/metrics.prom" || serve_ok=0
    grep -q 'srnn_serve_dispatches_total{kind="fixpoint_density",mode="solo"} 1' \
        "$serve_root/svc/metrics.prom" || serve_ok=0
else
    serve_ok=0
    kill "$serve_pid" 2>/dev/null
fi
if [ "$serve_ok" -eq 1 ]; then
    status=ok; pass=$((pass+1))
else
    status=FAIL; fail=$((fail+1)); failed_groups+=("service_smoke")
    tail -n 40 "$serve_root/serve.log"
fi
rm -rf "$serve_root"
summary+=$(printf '%-34s %-4s %4ss' "service_smoke" "$status" "$((SECONDS-t0))")$'\n'

# Self-healing service chaos smoke (srnn_tpu/serve journal + supervised
# dispatch): a service armed with serve_kill@1 SIGKILLs ITSELF (through
# the production dispatch path) with 8 admitted tickets journaled but
# unfinished; the restart — armed with serve_poison_tenant@1 — must
# REPLAY all 8 under their original ids, bisect-quarantine the poisoned
# one while the other 7 complete, dedupe an idempotent resubmit against
# the journal, render the self-heal stats in `watch --service --once`,
# and leave metrics.prom showing the replay + quarantine counters.
# Live telemetry leg (PR 15): the restart runs with --max-queue 8 and
# --metrics-port, so the 8-ticket replay restores a queue AT the
# admission bound — the serve_queue_full alert must fire (events.jsonl
# row + soup_alerts_total in metrics.prom), and a live /metrics scrape
# after the drain must agree with the on-disk snapshot's counters.
t0=$SECONDS
sc_root=$(mktemp -d)
sc_ok=1
sc_port=$(python - <<'PY'
import socket
s = socket.socket(); s.bind(("127.0.0.1", 0)); print(s.getsockname()[1]); s.close()
PY
)
SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.serve --root "$sc_root/svc" \
    --batch-window-s 1.5 --chaos serve_kill@1 > "$sc_root/serve.log" 2>&1 &
sc_pid=$!
up=0
for _ in $(seq 1 150); do
    if SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.serve \
            --socket "$sc_root/svc/serve.sock" --ping 2>/dev/null; then
        up=1; break
    fi
    sleep 0.2
done
if [ "$up" -eq 1 ]; then
    SRNN_SETUPS_PLATFORM=cpu python - "$sc_root/svc/serve.sock" \
        >> "$sc_root/serve.log" 2>&1 <<'PY' || sc_ok=0
import sys
from srnn_tpu.serve.client import ServiceClient
c = ServiceClient(sys.argv[1])
for i in range(8):
    t = c.submit("fixpoint_density", {"seed": i, "trials": 32, "batch": 32},
                 tenant=f"chaos{i}", idempotency_key=f"smoke-{i}")
    assert t == f"t{i + 1:06d}", t
PY
    wait "$sc_pid"
    rc=$?
    if [ "$rc" -ne 137 ]; then
        echo "serve_chaos_smoke: serve_kill rc=$rc (want 137)" \
            >> "$sc_root/serve.log"
        sc_ok=0
    fi
    SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.serve --root "$sc_root/svc" \
        --batch-window-s 0.2 --chaos serve_poison_tenant@1 \
        --max-queue 8 --metrics-port "$sc_port" \
        >> "$sc_root/serve.log" 2>&1 &
    sc_pid=$!
    up=0
    for _ in $(seq 1 150); do
        if SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.serve \
                --socket "$sc_root/svc/serve.sock" --ping 2>/dev/null; then
            up=1; break
        fi
        sleep 0.2
    done
    if [ "$up" -eq 1 ]; then
        SRNN_SETUPS_PLATFORM=cpu python - "$sc_root/svc/serve.sock" \
            >> "$sc_root/serve.log" 2>&1 <<'PY' || sc_ok=0
import sys
from srnn_tpu.serve.client import ServiceClient
from srnn_tpu.serve.client import ServiceError
c = ServiceClient(sys.argv[1], retries=3, backoff_base_s=0.2)
# resubmit-after-restart dedupes against the journal: same ticket back
assert c.submit("fixpoint_density", {"seed": 3, "trials": 32, "batch": 32},
                idempotency_key="smoke-3") == "t000004"
# the poisoned ticket (first admitted = first replayed) fails quarantined;
# its 7 innocent groupmates complete
try:
    c.wait("t000001", timeout_s=180)
    raise AssertionError("poisoned ticket completed")
except ServiceError as e:
    assert "poisoned" in str(e), e
for i in range(1, 8):
    result = c.wait(f"t{i + 1:06d}", timeout_s=180)
    assert result["counters"], result
stats = c.stats()["self_healing"]
assert stats["replayed"] == 8 and stats["quarantined"] == 1, stats
PY
        SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.telemetry.watch \
            --service "$sc_root/svc/serve.sock" --once \
            > "$sc_root/watch.json" 2>>"$sc_root/serve.log" || sc_ok=0
        python - "$sc_root/watch.json" >> "$sc_root/serve.log" 2>&1 <<'PY' || sc_ok=0
import json, sys
svc = json.load(open(sys.argv[1]))["service"]
sh = svc["self_healing"]
assert sh["replayed"] == 8 and sh["quarantined"] == 1, sh
assert "overload_rejections" in sh and "deadline_expirations" in sh
# the replay restored a queue at the admission bound: the queue-depth
# alert fired (and cleared once the drain emptied it)
assert svc["alerts"] and svc["alerts"]["fired"] >= 1, svc["alerts"]
print("serve_chaos_smoke: watch --service self-heal + alert stats OK")
PY
        python - "$sc_port" >> "$sc_root/serve.log" 2>&1 <<'PY' || sc_ok=0
import sys, urllib.request
body = urllib.request.urlopen(
    f"http://127.0.0.1:{int(sys.argv[1])}/metrics", timeout=5).read().decode()
# live scrape agrees with the settled counters the on-disk snapshot
# shows after shutdown (asserted below) — one registry, two views
assert "srnn_serve_journal_replays_total 8" in body, body[:400]
assert 'srnn_soup_alerts_total{rule="serve_queue_full"}' in body
print("serve_chaos_smoke: live /metrics scrape OK")
PY
        SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.serve \
            --socket "$sc_root/svc/serve.sock" --shutdown \
            >> "$sc_root/serve.log" 2>&1 || sc_ok=0
        wait "$sc_pid" || sc_ok=0
        grep -q 'srnn_serve_journal_replays_total 8' \
            "$sc_root/svc/metrics.prom" || sc_ok=0
        grep -Eq 'srnn_serve_quarantined_tenants_total\{[^}]*\} 1' \
            "$sc_root/svc/metrics.prom" || sc_ok=0
        grep -q '"rule": "serve_queue_full", "state": "firing"' \
            "$sc_root/svc/events.jsonl" || sc_ok=0
        grep -Eq 'srnn_soup_alerts_total\{rule="serve_queue_full"\} [1-9]' \
            "$sc_root/svc/metrics.prom" || sc_ok=0
    else
        sc_ok=0
        kill -9 "$sc_pid" 2>/dev/null
    fi
else
    sc_ok=0
    kill -9 "$sc_pid" 2>/dev/null
fi
if [ "$sc_ok" -eq 1 ]; then
    status=ok; pass=$((pass+1))
else
    status=FAIL; fail=$((fail+1)); failed_groups+=("serve_chaos_smoke")
    tail -n 40 "$sc_root/serve.log"
fi
rm -rf "$sc_root"
summary+=$(printf '%-34s %-4s %4ss' "serve_chaos_smoke" "$status" "$((SECONDS-t0))")$'\n'

# Continuous-batching fleet smoke (srnn_tpu/serve pool + adaptive
# windows): a REAL `--workers 2` fleet behind one front socket takes 12
# tickets from two concurrent client processes, one worker is SIGKILLed
# mid-load, and EVERY acknowledged ticket must still complete (the front
# replays the corpse's journal suffix onto the survivor).  Afterwards
# /healthz must agree (ok:true once healed, the death on the record),
# `watch --service --once` must render the fleet rows, and the front's
# metrics.prom must carry the death + replay counters.
t0=$SECONDS
ss_root=$(mktemp -d)
ss_ok=1
ss_port=$(python - <<'PY'
import socket
s = socket.socket(); s.bind(("127.0.0.1", 0)); print(s.getsockname()[1]); s.close()
PY
)
SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.serve --root "$ss_root/svc" \
    --workers 2 --batch-window-s 0.25 --slo-p95-ms 2000 \
    --metrics-port "$ss_port" > "$ss_root/serve.log" 2>&1 &
ss_pid=$!
up=0
for _ in $(seq 1 300); do
    if SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.serve \
            --socket "$ss_root/svc/serve.sock" --ping 2>/dev/null; then
        up=1; break
    fi
    sleep 0.2
done
if [ "$up" -eq 1 ]; then
    # two concurrent clients submit 6 tickets each (4 tenants spread
    # sticky round-robin across both workers), drop a marker once their
    # submits are ACKNOWLEDGED, hold at a barrier until the driver's
    # SIGKILL has landed (so the kill is guaranteed mid-load: every
    # admitted ticket still uncollected, the corpse's share stranded),
    # then collect — BOTH clients' waits must still complete.
    ss_clients=()
    for half in 0 1; do
        SRNN_SETUPS_PLATFORM=cpu python - "$ss_root/svc/serve.sock" \
            "$half" "$ss_root/submitted.$half" "$ss_root/killed" \
            >> "$ss_root/serve.log" 2>&1 <<'PY' &
import os
import sys
import time
from srnn_tpu.serve.client import ServiceClient
sock, half = sys.argv[1], int(sys.argv[2])
marker, barrier = sys.argv[3], sys.argv[4]
c = ServiceClient(sock, retries=5, backoff_base_s=0.2, seed=half)
tickets = [c.submit("fixpoint_density",
                    {"seed": half * 6 + i, "trials": 32, "batch": 32},
                    tenant=f"tn{(half * 6 + i) % 4}",
                    idempotency_key=f"scale-{half}-{i}")
           for i in range(6)]
open(marker, "w").write("\n".join(tickets))
deadline = time.monotonic() + 180
while not os.path.exists(barrier):
    assert time.monotonic() < deadline, "kill barrier never dropped"
    time.sleep(0.2)
for t in tickets:
    assert c.wait(t, timeout_s=300) is not None, t
PY
        ss_clients+=($!)
    done
    marked=0
    for _ in $(seq 1 300); do
        if [ -f "$ss_root/submitted.0" ] && [ -f "$ss_root/submitted.1" ]; then
            marked=1; break
        fi
        sleep 0.2
    done
    [ "$marked" -eq 1 ] || ss_ok=0
    w0_pid=$(SRNN_SETUPS_PLATFORM=cpu python - "$ss_root/svc/serve.sock" \
        2>>"$ss_root/serve.log" <<'PY'
import sys
from srnn_tpu.serve.client import ServiceClient
print(ServiceClient(sys.argv[1]).stats()["fleet"]["w0"]["pid"])
PY
    )
    if [ -n "$w0_pid" ]; then
        kill -9 "$w0_pid" 2>/dev/null || ss_ok=0
    else
        ss_ok=0
    fi
    touch "$ss_root/killed"   # release the clients' collect barrier
    wait "${ss_clients[0]}" || ss_ok=0
    wait "${ss_clients[1]}" || ss_ok=0
    # the fleet healed: healthz ok again, the death on the record, and
    # the watch console renders the front + per-worker fleet rows
    python - "$ss_port" >> "$ss_root/serve.log" 2>&1 <<'PY' || ss_ok=0
import json, sys, urllib.request
health = json.load(urllib.request.urlopen(
    f"http://127.0.0.1:{int(sys.argv[1])}/healthz", timeout=5))
assert health["ok"] is True, health
assert health["deaths"] == 1 and health["replayed"] >= 1, health
assert health["workers"]["0"]["ok"] is False, health
assert health["workers"]["1"]["ok"] is True, health
print("serve_scale_smoke: healthz loss-then-heal OK")
PY
    SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.telemetry.watch \
        --service "$ss_root/svc/serve.sock" --once \
        > "$ss_root/watch.json" 2>>"$ss_root/serve.log" || ss_ok=0
    python - "$ss_root/watch.json" >> "$ss_root/serve.log" 2>&1 <<'PY' || ss_ok=0
import json, sys
svc = json.load(open(sys.argv[1]))["service"]
front, fleet = svc["front"], svc["fleet"]
assert front["completed"] == 12 and front["pending"] == 0, front
assert front["deaths"] == 1 and front["replayed"] >= 1, front
assert fleet["w0"]["alive"] is False, fleet
assert fleet["w1"]["alive"] is True and fleet["w1"]["adaptive"], fleet
print("serve_scale_smoke: watch --service fleet view OK")
PY
    SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.serve \
        --socket "$ss_root/svc/serve.sock" --shutdown \
        >> "$ss_root/serve.log" 2>&1 || ss_ok=0
    wait "$ss_pid" || ss_ok=0
    grep -q 'srnn_serve_worker_deaths_total 1' \
        "$ss_root/svc/metrics.prom" || ss_ok=0
    grep -Eq 'srnn_serve_worker_replays_total [1-9]' \
        "$ss_root/svc/metrics.prom" || ss_ok=0
else
    ss_ok=0
    kill -9 "$ss_pid" 2>/dev/null
fi
if [ "$ss_ok" -eq 1 ]; then
    status=ok; pass=$((pass+1))
else
    status=FAIL; fail=$((fail+1)); failed_groups+=("serve_scale_smoke")
    tail -n 60 "$ss_root/serve.log"
fi
rm -rf "$ss_root"
summary+=$(printf '%-34s %-4s %4ss' "serve_scale_smoke" "$status" "$((SECONDS-t0))")$'\n'

# Fleet tracing smoke (PR 17, srnn_tpu/serve + telemetry/fleet): a
# `--workers 2` pool takes 8 traced tickets, worker w0 is SIGKILLed
# mid-flight (after its serve.admit spans have demonstrably landed in
# workers/w0/events.jsonl), and the replayed work completes on the
# survivor.  Then `report --trace` must emit paired Perfetto flow
# events (ph "s" at the front's relay spans, ph "f" at the workers'
# adopted spans), and `report --trace-request <replayed ticket>` must
# exit 0 with ONE trace_id spanning the front lane AND both worker
# lanes — the kill -9 story as a single connected trace.
t0=$SECONDS
ts_root=$(mktemp -d)
ts_ok=1
SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.serve --root "$ts_root/svc" \
    --workers 2 --batch-window-s 0.25 > "$ts_root/serve.log" 2>&1 &
ts_pid=$!
up=0
for _ in $(seq 1 300); do
    if SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.serve \
            --socket "$ts_root/svc/serve.sock" --ping 2>/dev/null; then
        up=1; break
    fi
    sleep 0.2
done
if [ "$up" -eq 1 ]; then
    SRNN_SETUPS_PLATFORM=cpu python - "$ts_root/svc/serve.sock" \
        "$ts_root/submitted" "$ts_root/killed" \
        >> "$ts_root/serve.log" 2>&1 <<'PY' &
import os
import sys
import time
from srnn_tpu.serve.client import ServiceClient
sock, marker, barrier = sys.argv[1], sys.argv[2], sys.argv[3]
c = ServiceClient(sock, retries=5, backoff_base_s=0.2)
tickets = [c.submit("fixpoint_density",
                    {"seed": i, "trials": 32, "batch": 32},
                    tenant=f"tn{i % 4}", idempotency_key=f"trace-{i}")
           for i in range(8)]
open(marker, "w").write("\n".join(tickets))
deadline = time.monotonic() + 180
while not os.path.exists(barrier):
    assert time.monotonic() < deadline, "kill barrier never dropped"
    time.sleep(0.2)
for t in tickets:
    assert c.wait(t, timeout_s=300) is not None, t
PY
    ts_client=$!
    # kill only once the corpse-to-be has ADMITTED work on the record:
    # its serve.admit spans in workers/w0/events.jsonl are what the
    # merged trace must later show for the dead lane
    admitted=0
    for _ in $(seq 1 300); do
        if [ -f "$ts_root/submitted" ] && \
                grep -q '"span": "serve.admit"' \
                    "$ts_root/svc/workers/w0/events.jsonl" 2>/dev/null; then
            admitted=1; break
        fi
        sleep 0.2
    done
    [ "$admitted" -eq 1 ] || ts_ok=0
    w0_pid=$(SRNN_SETUPS_PLATFORM=cpu python - "$ts_root/svc/serve.sock" \
        2>>"$ts_root/serve.log" <<'PY'
import sys
from srnn_tpu.serve.client import ServiceClient
print(ServiceClient(sys.argv[1]).stats()["fleet"]["w0"]["pid"])
PY
    )
    if [ -n "$w0_pid" ]; then
        kill -9 "$w0_pid" 2>/dev/null || ts_ok=0
    else
        ts_ok=0
    fi
    touch "$ts_root/killed"
    wait "$ts_client" || ts_ok=0
    SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.serve \
        --socket "$ts_root/svc/serve.sock" --shutdown \
        >> "$ts_root/serve.log" 2>&1 || ts_ok=0
    wait "$ts_pid" || ts_ok=0
    SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.telemetry.report \
        --trace "$ts_root/svc" >> "$ts_root/serve.log" 2>&1 || ts_ok=0
    SRNN_SETUPS_PLATFORM=cpu python - "$ts_root/svc" \
        >> "$ts_root/serve.log" 2>&1 <<'PY' || ts_ok=0
import json, sys
from srnn_tpu.telemetry import fleet
run = sys.argv[1]
doc = json.load(open(run + "/trace.json"))
flows = [e for e in doc["traceEvents"] if e.get("cat") == "flow"]
starts = [e for e in flows if e["ph"] == "s"]
finishes = [e for e in flows if e["ph"] == "f"]
assert starts and finishes, "no flow arrows in the merged trace"
assert {e["id"] for e in starts} == {e["id"] for e in finishes}
assert all(e["pid"] == 0 for e in starts), "hops must source at the front"
assert any(e["pid"] != 0 for e in finishes), "no worker-side flow binds"
# the replayed tickets: the front's own front.replay spans name them
replayed = [json.loads(l) for l in open(run + "/events.jsonl")
            if '"front.replay"' in l]
assert replayed, "no front.replay span — the kill never forced a replay"
full = None
for row in replayed:
    s = fleet.trace_request(run, row["ticket"])
    assert s is not None, f"trace_request knows nothing about {row}"
    assert s["cross_process_links"] >= 1, s
    names = {r.get("span") for r in s["spans"]}
    assert "front.replay" in names and "serve.ticket" in names, \
        sorted(names)
    assert s["processes"][0] == 0 and len(s["processes"]) >= 2, s
    # a ticket the corpse had ADMITTED (its serve.admit flushed before
    # the kill) renders as ONE trace across all three lanes
    if s["processes"] == [0, 1, 2]:
        full = s
assert full is not None, "no replayed trace spans front+corpse+survivor"
print(f"trace_smoke: one trace across lanes {full['processes']} "
      f"({full['cross_process_links']} cross-process links) OK")
PY
    SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.telemetry.report \
        "$ts_root/svc" --trace-request "$(head -1 "$ts_root/submitted")" \
        > "$ts_root/trace_req.txt" 2>>"$ts_root/serve.log" || ts_ok=0
    grep -q 'critical path' "$ts_root/trace_req.txt" || ts_ok=0
else
    ts_ok=0
    kill -9 "$ts_pid" 2>/dev/null
fi
if [ "$ts_ok" -eq 1 ]; then
    status=ok; pass=$((pass+1))
else
    status=FAIL; fail=$((fail+1)); failed_groups+=("trace_smoke")
    tail -n 60 "$ts_root/serve.log"
fi
rm -rf "$ts_root"
summary+=$(printf '%-34s %-4s %4ss' "trace_smoke" "$status" "$((SECONDS-t0))")$'\n'

# Distributed smoke (srnn_tpu/distributed/): a REAL 2-process CPU-mesh
# launcher run (gloo collectives, process-0-gated host I/O) must end
# bitwise-equal to the single-process run of the same config, write each
# run artifact exactly once (workers keep only per-process heartbeats),
# and a SIGKILLed worker must propagate cleanly as 137 instead of
# wedging the launcher.
t0=$SECONDS
dist_root=$(mktemp -d)
dist_ok=1
# share the pytest suite's persistent compile cache: three cold smoke
# runs (solo + 2x launcher) would otherwise each repay XLA on this host
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-/tmp/jax_test_cache}"
SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.setups mega_soup --smoke \
    --seed 23 --root "$dist_root/solo" --lineage \
    > "$dist_root/out.log" 2>&1 || dist_ok=0
SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.distributed.launch \
    --processes 2 -- mega_soup --smoke --seed 23 --sharded --lineage \
    --root "$dist_root/dist" >> "$dist_root/out.log" 2>&1 || dist_ok=0
if [ "$dist_ok" -eq 1 ]; then
    SRNN_SETUPS_PLATFORM=cpu python - "$dist_root" >> "$dist_root/out.log" 2>&1 <<'PY' || dist_ok=0
import glob, json, sys
import numpy as np
from srnn_tpu.experiment import restore_checkpoint
root = sys.argv[1]
solo = glob.glob(root + "/solo/exp-*")[0]
dist = glob.glob(root + "/dist/exp-*")[0]
a = restore_checkpoint(solo + "/ckpt-gen00000006")
b = restore_checkpoint(dist + "/ckpt-gen00000006")
np.testing.assert_array_equal(np.asarray(a.weights), np.asarray(b.weights))
np.testing.assert_array_equal(np.asarray(a.uids), np.asarray(b.uids))
import os
assert os.path.exists(dist + "/metrics.prom")
assert os.path.exists(dist + "/events-p1.jsonl")
wa = [r for r in map(json.loads, open(solo + "/lineage.jsonl")) if r.get("kind") == "window"]
wb = [r for r in map(json.loads, open(dist + "/lineage.jsonl")) if r.get("kind") == "window"]
assert len(wa) == len(wb) > 0
for ra, rb in zip(wa, wb):
    assert sorted(map(tuple, ra["edges"])) == sorted(map(tuple, rb["edges"]))
    for k in ("fixpoints", "births_attack", "births_respawn", "next_pid"):
        assert ra[k] == rb[k], k
print("distributed_smoke: bitwise parity + process-0 gating OK")
PY
fi
SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.distributed.launch \
    --processes 2 --grace-s 5 --max-reramps 0 -- mega_soup --smoke \
    --seed 23 --sharded --root "$dist_root/kill" --chaos sigkill@2 \
    >> "$dist_root/out.log" 2>&1
rc=$?
if [ "$rc" -ne 137 ]; then
    echo "distributed_smoke: killed-worker propagation rc=$rc (want 137)" \
        >> "$dist_root/out.log"
    dist_ok=0
fi
if [ "$dist_ok" -eq 1 ]; then
    status=ok; pass=$((pass+1))
else
    status=FAIL; fail=$((fail+1)); failed_groups+=("distributed_smoke")
    tail -n 40 "$dist_root/out.log"
fi
rm -rf "$dist_root"
summary+=$(printf '%-34s %-4s %4ss' "distributed_smoke" "$status" "$((SECONDS-t0))")$'\n'

# Observability smoke (srnn_tpu/telemetry/ fleet observatory): a REAL
# 2-process launcher run must produce ONE merged `report --fleet`
# timeline rendering BOTH process lanes (straggler attribution included),
# and `watch --once` must return valid JSON carrying a generation field
# for every process — the fleet merge + live console drilled on every
# suite run, not just when the slow e2e is selected.
t0=$SECONDS
obs_root=$(mktemp -d)
obs_ok=1
SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.distributed.launch \
    --processes 2 -- mega_soup --smoke --seed 29 --sharded \
    --root "$obs_root/run" > "$obs_root/out.log" 2>&1 || obs_ok=0
if [ "$obs_ok" -eq 1 ]; then
    obs_dir=$(ls -d "$obs_root"/run/exp-* 2>/dev/null | head -1)
    SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.telemetry.report \
        --fleet "$obs_dir" > "$obs_root/fleet.txt" 2>>"$obs_root/out.log" \
        || obs_ok=0
    grep -q '^  p0 ' "$obs_root/fleet.txt" || obs_ok=0
    grep -q '^  p1 ' "$obs_root/fleet.txt" || obs_ok=0
    grep -q '^straggler: ' "$obs_root/fleet.txt" || obs_ok=0
    SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.telemetry.watch \
        "$obs_dir" --once > "$obs_root/snap.json" 2>>"$obs_root/out.log" \
        || obs_ok=0
    python - "$obs_root/snap.json" >> "$obs_root/out.log" 2>&1 <<'PY' || obs_ok=0
import json, sys
snap = json.load(open(sys.argv[1]))
procs = snap["processes"]
assert set(procs) >= {"0", "1"}, sorted(procs)
for p, lane in procs.items():
    assert isinstance(lane.get("generation"), int), (p, lane)
assert snap["straggler"] is not None
print("observability_smoke: fleet lanes + watch snapshot OK")
PY
fi
if [ "$obs_ok" -eq 1 ]; then
    status=ok; pass=$((pass+1))
else
    status=FAIL; fail=$((fail+1)); failed_groups+=("observability_smoke")
    tail -n 40 "$obs_root/out.log"; cat "$obs_root/fleet.txt" 2>/dev/null
fi
rm -rf "$obs_root"
summary+=$(printf '%-34s %-4s %4ss' "observability_smoke" "$status" "$((SECONDS-t0))")$'\n'

# Cost observatory smoke (srnn_tpu/telemetry/costs + report --trace +
# benchmarks/regress.py): a tiny warmed mega-soup run must leave a
# non-empty compile_ledger.jsonl and soup_hlo_flops/soup_hbm_bytes
# gauges in metrics.prom; `report --trace` must emit a Perfetto-loadable
# trace.json (ph/ts/pid validated); and the perf-regression sentinel
# must exit clean against the committed BENCH history while flagging a
# synthetic -30% row — the advisory gate that catches a throughput
# regression in the PR that causes it.
t0=$SECONDS
cost_root=$(mktemp -d)
cost_ok=1
SRNN_SETUPS_PLATFORM=cpu SRNN_COST_LEDGER="$cost_root/ledger.jsonl" \
    python -m srnn_tpu.setups mega_soup --smoke --seed 31 \
    --root "$cost_root/run" > "$cost_root/out.log" 2>&1 || cost_ok=0
if [ "$cost_ok" -eq 1 ]; then
    cost_dir=$(ls -d "$cost_root"/run/exp-* 2>/dev/null | head -1)
    [ -s "$cost_root/ledger.jsonl" ] || { echo "cost_smoke: empty ledger" \
        >> "$cost_root/out.log"; cost_ok=0; }
    grep -q 'srnn_soup_hlo_flops{entry=' "$cost_dir/metrics.prom" \
        || cost_ok=0
    grep -q 'srnn_soup_hbm_bytes{' "$cost_dir/metrics.prom" || cost_ok=0
    SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.telemetry.report \
        --trace "$cost_dir" >> "$cost_root/out.log" 2>&1 || cost_ok=0
    python - "$cost_dir/trace.json" >> "$cost_root/out.log" 2>&1 <<'PY' || cost_ok=0
import json, sys
doc = json.load(open(sys.argv[1]))
evs = doc["traceEvents"]
assert evs, "no trace events"
for e in evs:
    assert "ph" in e and "pid" in e, e
    if e["ph"] != "M":
        assert isinstance(e.get("ts"), (int, float)), e
assert any(e["ph"] == "X" for e in evs), "no span slices"
assert doc["otherData"]["processes"], "no process lanes"
print("cost_smoke: Perfetto trace schema OK")
PY
fi
python benchmarks/regress.py BENCH_r07.json --json \
    > "$cost_root/regress.json" 2>>"$cost_root/out.log" || cost_ok=0
python benchmarks/regress.py BENCH_r07.json --scale apps_per_chip=0.6 \
    >> "$cost_root/out.log" 2>&1
if [ "$?" -ne 1 ]; then
    echo "cost_smoke: synthetic -30% row not flagged" >> "$cost_root/out.log"
    cost_ok=0
fi
if [ "$cost_ok" -eq 1 ]; then
    status=ok; pass=$((pass+1))
else
    status=FAIL; fail=$((fail+1)); failed_groups+=("cost_smoke")
    tail -n 40 "$cost_root/out.log"
fi
rm -rf "$cost_root"
summary+=$(printf '%-34s %-4s %4ss' "cost_smoke" "$status" "$((SECONDS-t0))")$'\n'

# Autotune smoke (srnn_tpu/autotune): a fused mega-soup smoke run with
# the deterministic grid (SRNN_AUTOTUNE_FIXED=1, isolated cache dir)
# must WRITE tuning.json next to the executable cache, count its grid
# measurements, and publish the chosen block in the run's metrics.prom;
# a second identical run in a fresh process must MEMO-HIT the persisted
# table — cache-hit counter up, zero new measurements — with the same
# block gauge.  The restart-amortization story drilled end to end.
t0=$SECONDS
at_root=$(mktemp -d)
at_ok=1
at_env="SRNN_SETUPS_PLATFORM=cpu SRNN_AUTOTUNE_FIXED=1 \
JAX_COMPILATION_CACHE_DIR=$at_root/cache SRNN_COMPILE_CACHE_DIR=$at_root/cache"
env $at_env python -m srnn_tpu.setups mega_soup --smoke --seed 5 \
    --root "$at_root/run1" --layout popmajor --generation-impl fused \
    > "$at_root/out.log" 2>&1 || at_ok=0
[ -s "$at_root/cache/tuning.json" ] || { echo "autotune_smoke: no \
tuning.json after run 1" >> "$at_root/out.log"; at_ok=0; }
at1=$(ls -d "$at_root"/run1/exp-* 2>/dev/null | head -1)
grep -q 'srnn_soup_autotune_block{kind="generation"' \
    "$at1/metrics.prom" 2>/dev/null || at_ok=0
grep -Eq 'srnn_soup_autotune_measurements_total [1-9]' \
    "$at1/metrics.prom" 2>/dev/null || at_ok=0
grep -q '"kind": "autotune"' "$at1/events.jsonl" 2>/dev/null || at_ok=0
env $at_env python -m srnn_tpu.setups mega_soup --smoke --seed 5 \
    --root "$at_root/run2" --layout popmajor --generation-impl fused \
    >> "$at_root/out.log" 2>&1 || at_ok=0
at2=$(ls -d "$at_root"/run2/exp-* 2>/dev/null | head -1)
grep -Eq 'srnn_soup_autotune_cache_hits_total [1-9]' \
    "$at2/metrics.prom" 2>/dev/null || at_ok=0
if grep -q 'srnn_soup_autotune_measurements_total' \
        "$at2/metrics.prom" 2>/dev/null; then
    echo "autotune_smoke: run 2 re-measured instead of memo-hitting" \
        >> "$at_root/out.log"
    at_ok=0
fi
grep -q 'srnn_soup_autotune_block{kind="generation"' \
    "$at2/metrics.prom" 2>/dev/null || at_ok=0
if [ "$at_ok" -eq 1 ]; then
    status=ok; pass=$((pass+1))
else
    status=FAIL; fail=$((fail+1)); failed_groups+=("autotune_smoke")
    tail -n 40 "$at_root/out.log"
fi
rm -rf "$at_root"
summary+=$(printf '%-34s %-4s %4ss' "autotune_smoke" "$status" "$((SECONDS-t0))")$'\n'

# Live telemetry alerts smoke (srnn_tpu/telemetry exporter + alerts): a
# REAL 2-process launcher run exports each worker's /metrics on
# base_port+i with a floor straggler threshold (skew >= 1.0 always
# holds, so the rule must fire on the first fleet fold).  Both workers'
# endpoints are scraped MID-RUN (plus the primary's /healthz, which
# aggregates worker liveness from the heartbeat lanes); afterwards the
# straggler alert must be in events.jsonl and the watch panel.
t0=$SECONDS
al_root=$(mktemp -d)
al_ok=1
al_port=$(python - <<'PY'
import socket
s1, s2 = socket.socket(), socket.socket()
for _ in range(64):
    s1.bind(("127.0.0.1", 0))
    p = s1.getsockname()[1]
    try:
        s2.bind(("127.0.0.1", p + 1))
        break
    except OSError:
        s1.close(); s1 = socket.socket()
print(p); s1.close(); s2.close()
PY
)
SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.distributed.launch \
    --processes 2 -- mega_soup --smoke --seed 43 --sharded \
    --generations 24 --root "$al_root/run" \
    --metrics-port "$al_port" --alert-straggler-skew 1.0 \
    > "$al_root/out.log" 2>&1 &
al_pid=$!
scraped=0
for _ in $(seq 1 450); do
    if python - "$al_port" >> "$al_root/scrape.log" 2>&1 <<'PY'
import json, sys, urllib.request
p = int(sys.argv[1])
for off in (0, 1):   # primary exports on p, worker 1 on p+1
    body = urllib.request.urlopen(
        f"http://127.0.0.1:{p+off}/metrics", timeout=2).read().decode()
    assert "srnn_heartbeat_generation" in body \
        or "srnn_soup_precision_weight_bits" in body, body[:200]
health = json.load(urllib.request.urlopen(
    f"http://127.0.0.1:{p}/healthz", timeout=2))
assert health.get("ok") is True, health
assert "workers" in health, health
print("alerts_smoke: scraped both workers + aggregated healthz")
PY
    then scraped=1; break; fi
    kill -0 "$al_pid" 2>/dev/null || break
    sleep 0.2
done
if [ "$scraped" -ne 1 ]; then
    echo "alerts_smoke: mid-run scrape of both workers failed" \
        >> "$al_root/out.log"
    tail -n 5 "$al_root/scrape.log" >> "$al_root/out.log" 2>/dev/null
    al_ok=0
fi
wait "$al_pid" || al_ok=0
al_dir=$(ls -d "$al_root"/run/exp-* 2>/dev/null | head -1)
if [ -n "$al_dir" ]; then
    grep -q '"rule": "soup_straggler_skew", "state": "firing"' \
        "$al_dir/events.jsonl" || al_ok=0
    grep -Eq 'srnn_soup_alerts_total\{rule="soup_straggler_skew"\} [1-9]' \
        "$al_dir/metrics.prom" || al_ok=0
    SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.telemetry.watch \
        "$al_dir" --once > "$al_root/snap.json" 2>>"$al_root/out.log" \
        || al_ok=0
    python - "$al_root/snap.json" >> "$al_root/out.log" 2>&1 <<'PY' || al_ok=0
import json, sys
snap = json.load(open(sys.argv[1]))
alerts = snap["alerts"]
assert alerts["fired"] >= 1, alerts
assert "soup_straggler_skew" in alerts["active"], alerts
assert snap["history"] and snap["history"]["samples"] >= 1, snap["history"]
print("alerts_smoke: watch panel shows the firing straggler alert")
PY
else
    al_ok=0
fi
if [ "$al_ok" -eq 1 ]; then
    status=ok; pass=$((pass+1))
else
    status=FAIL; fail=$((fail+1)); failed_groups+=("alerts_smoke")
    tail -n 40 "$al_root/out.log"
fi
rm -rf "$al_root"
summary+=$(printf '%-34s %-4s %4ss' "alerts_smoke" "$status" "$((SECONDS-t0))")$'\n'

# Run-archive smoke (PR 19, srnn_tpu/telemetry/archive): one clean smoke
# run and one chaos-RECOVERED smoke run under a single results root, then
# the cross-run observatory over it: `report --runs` must classify both
# outcomes (clean + recovered) and group them into campaign rollups,
# `report --compare` must render the pairwise diff, and a second ingest
# pass must be a pure watermark no-op (zero rows appended) — the
# longitudinal index drilled against REAL run dirs, not fixtures.
t0=$SECONDS
ar_root=$(mktemp -d)
ar_ok=1
SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.setups mega_soup --smoke \
    --seed 7 --root "$ar_root/runs" > "$ar_root/out.log" 2>&1 || ar_ok=0
SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.setups mega_soup --smoke \
    --seed 11 --root "$ar_root/runs" --chaos "stall@2" \
    --stall-timeout-s 5 --backoff-base-s 0.1 --backoff-max-s 1 \
    --max-restarts 3 >> "$ar_root/out.log" 2>&1
rc=$?
if [ "$rc" -ne 3 ]; then
    echo "archive_smoke: chaos run rc=$rc (want 3 recovered)" \
        >> "$ar_root/out.log"
    ar_ok=0
fi
SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.telemetry.report \
    "$ar_root/runs" --runs --json > "$ar_root/runs.json" \
    2>>"$ar_root/out.log" || ar_ok=0
python - "$ar_root/runs.json" "$ar_root" >> "$ar_root/out.log" 2>&1 <<'PY' || ar_ok=0
import json, sys
doc = json.load(open(sys.argv[1]))
outcomes = {r["run"]: r["outcome"] for r in doc["runs"]}
# the chaos run's FIRST attempt leaves its own dir (the stall fault on
# its meta.json -> "failed") before the supervisor resumes into a new
# one -> "recovered"; the table must carry the clean and recovered runs
# either way
assert {"clean", "recovered"} <= set(outcomes.values()), outcomes
recovered = next(r for r in doc["runs"] if r["outcome"] == "recovered")
assert recovered["restarts"] >= 1 and recovered["exit_code"] == 3, recovered
# a --smoke seed sweep is ONE campaign: every dir under one fingerprint
camps = doc["campaigns"]
assert len(camps) == 1 and camps[0]["runs"] == len(doc["runs"]), camps
# hand the driver the clean + recovered dirs for the --compare leg
clean = next(r["dir"] for r in doc["runs"] if r["outcome"] == "clean")
open(sys.argv[2] + "/dirs.txt", "w").write(
    clean + "\n" + recovered["dir"])
print("archive_smoke: run table outcomes + campaign rollup OK")
PY
if [ "$ar_ok" -eq 1 ]; then
    ar_a=$(head -1 "$ar_root/dirs.txt")
    ar_b=$(tail -1 "$ar_root/dirs.txt")
    SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.telemetry.report \
        "$ar_b" --compare "$ar_a" > "$ar_root/compare.txt" \
        2>>"$ar_root/out.log" || ar_ok=0
    grep -q 'same campaign' "$ar_root/compare.txt" || ar_ok=0
    grep -q 'wall_seconds' "$ar_root/compare.txt" || ar_ok=0
    # re-ingest of the untouched root: a watermark no-op, zero appends
    SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.telemetry.archive \
        ingest "$ar_root/runs" --json > "$ar_root/reingest.json" \
        2>>"$ar_root/out.log" || ar_ok=0
    python - "$ar_root/reingest.json" >> "$ar_root/out.log" 2>&1 <<'PY' || ar_ok=0
import json, sys
res = json.load(open(sys.argv[1]))
assert res["ingested"] == [] and res["unchanged"] >= 2, res
assert res["wrote"] is False, res
print("archive_smoke: re-ingest watermark no-op OK")
PY
fi
if [ "$ar_ok" -eq 1 ]; then
    status=ok; pass=$((pass+1))
else
    status=FAIL; fail=$((fail+1)); failed_groups+=("archive_smoke")
    tail -n 40 "$ar_root/out.log"
fi
rm -rf "$ar_root"
summary+=$(printf '%-34s %-4s %4ss' "archive_smoke" "$status" "$((SECONDS-t0))")$'\n'

# Continuous-profiling smoke (PR 20, srnn_tpu/telemetry/profiler): a
# smoke run with a floor alert threshold (nan_frac >= -1.0 always
# holds, so the rule fires on the first sample) must publish an
# anomaly/<rule>-<seq>/ black-box bundle — non-empty folded samples,
# thread dump, registry snapshot — plus the cumulative profile.folded
# and the soup_profile_*/soup_utilization_* families in metrics.prom;
# then `report --profile` must render the capture index, and the same
# run WITH --no-profile must leave no profile artifacts at all.
t0=$SECONDS
pf_root=$(mktemp -d)
pf_ok=1
SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.setups mega_soup --smoke \
    --seed 13 --root "$pf_root/run" --alert-nan-frac -1.0 \
    > "$pf_root/out.log" 2>&1 || pf_ok=0
pf_dir=$(ls -d "$pf_root"/run/exp-* 2>/dev/null | head -1)
if [ -n "$pf_dir" ]; then
    [ -s "$pf_dir/profile.folded" ] || { echo "profile_smoke: no \
profile.folded" >> "$pf_root/out.log"; pf_ok=0; }
    grep -Eq 'srnn_soup_profile_samples_total [1-9]' \
        "$pf_dir/metrics.prom" || pf_ok=0
    grep -q 'srnn_soup_utilization_device_busy' \
        "$pf_dir/metrics.prom" || pf_ok=0
    grep -q 'srnn_soup_anomaly_captures_total{rule="soup_nan_frac"} 1' \
        "$pf_dir/metrics.prom" || pf_ok=0
    pf_bundle=$(ls -d "$pf_dir"/anomaly/soup_nan_frac-* 2>/dev/null | head -1)
    if [ -n "$pf_bundle" ]; then
        [ -s "$pf_bundle/samples.jsonl" ] || pf_ok=0
        grep -q '"stacks"' "$pf_bundle/samples.jsonl" || pf_ok=0
        grep -q '"n_threads"' "$pf_bundle/threads.json" || pf_ok=0
        grep -q '"rule": "soup_nan_frac"' "$pf_bundle/capture.json" || pf_ok=0
        grep -q 'srnn_soup_health_nan_frac' "$pf_bundle/metrics.json" \
            || pf_ok=0
    else
        echo "profile_smoke: no anomaly bundle published" \
            >> "$pf_root/out.log"
        pf_ok=0
    fi
    SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.telemetry.report \
        --profile "$pf_dir" > "$pf_root/profile.txt" \
        2>>"$pf_root/out.log" || pf_ok=0
    grep -q '^  sampler: ' "$pf_root/profile.txt" || pf_ok=0
    grep -q 'anomaly captures (1' "$pf_root/profile.txt" || pf_ok=0
else
    pf_ok=0
fi
SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.setups mega_soup --smoke \
    --seed 13 --root "$pf_root/off" --no-profile \
    >> "$pf_root/out.log" 2>&1 || pf_ok=0
pf_off=$(ls -d "$pf_root"/off/exp-* 2>/dev/null | head -1)
if [ -n "$pf_off" ]; then
    if [ -e "$pf_off/profile.folded" ] || [ -e "$pf_off/anomaly" ]; then
        echo "profile_smoke: --no-profile left profile artifacts" \
            >> "$pf_root/out.log"
        pf_ok=0
    fi
    # the no-data contract: a --no-profile run dir exits 2, not an
    # empty-but-valid render
    SRNN_SETUPS_PLATFORM=cpu python -m srnn_tpu.telemetry.report \
        --profile "$pf_off" >> "$pf_root/out.log" 2>&1
    if [ "$?" -ne 2 ]; then
        echo "profile_smoke: report --profile on --no-profile run did \
not exit 2" >> "$pf_root/out.log"
        pf_ok=0
    fi
else
    pf_ok=0
fi
if [ "$pf_ok" -eq 1 ]; then
    status=ok; pass=$((pass+1))
else
    status=FAIL; fail=$((fail+1)); failed_groups+=("profile_smoke")
    tail -n 40 "$pf_root/out.log"
fi
rm -rf "$pf_root"
summary+=$(printf '%-34s %-4s %4ss' "profile_smoke" "$status" "$((SECONDS-t0))")$'\n'

echo
echo "=== run_tests.sh summary ==="
printf '%s' "$summary"
echo "groups: $((pass+fail)), failed: $fail"
if [ "$fail" -gt 0 ]; then
    printf 'failed: %s\n' "${failed_groups[@]}"
    exit 1
fi
