#!/usr/bin/env bash
# Unattended tunnel watch: probe every INTERVAL seconds; on the FIRST
# healthy probe, run the full TPU-window capture (scripts/tpu_window.sh)
# and EXIT on success.  Only a failed capture resumes the watch loop (so
# a later window can retry); a completed capture ends the watch.
#
# Start detached:  PYTHONPATH= nohup bash scripts/tpu_watch.sh &
# Log:             /tmp/tpu_watch.log (or $TPU_WATCH_LOG)
# The parent MUST run with PYTHONPATH stripped (see tpu_window.sh) so a
# startup-level tunnel wedge cannot hang the watch loop itself.
set -u
cd "$(dirname "$0")/.."
INTERVAL="${TPU_WATCH_INTERVAL_S:-600}"
LOG="${TPU_WATCH_LOG:-/tmp/tpu_watch.log}"
# Opt-in fleet snapshot: point TPU_WATCH_SNAPSHOT_DIR at a run dir and
# every poll appends one `telemetry.watch --once` JSON snapshot (stage /
# generation / gens-per-sec / straggler across all processes) to the log
# — the unattended window's liveness trail without tail-ing heartbeat
# files by hand.  CPU-pinned and PYTHONPATH-stripped like the probe: the
# watch is a pure file reader and must never dial the tunnel.
SNAPSHOT_DIR="${TPU_WATCH_SNAPSHOT_DIR:-}"

echo "$(date -u +%FT%TZ) tpu_watch: probing every ${INTERVAL}s" >> "$LOG"
while true; do
    if [ -n "$SNAPSHOT_DIR" ] && [ -d "$SNAPSHOT_DIR" ]; then
        PYTHONPATH= JAX_PLATFORMS=cpu timeout 60 python -m \
            srnn_tpu.telemetry.watch "$SNAPSHOT_DIR" --once >> "$LOG" 2>&1
    fi
    if PYTHONPATH= timeout 280 python benchmarks/opportunistic.py \
            --probe-only >> "$LOG" 2>&1; then
        echo "$(date -u +%FT%TZ) tpu_watch: HEALTHY — running window capture" >> "$LOG"
        PYTHONPATH= bash scripts/tpu_window.sh >> "$LOG" 2>&1
        rc=$?
        # The elastic supervisor (srnn_tpu/resilience/) speaks a distinct
        # exit-code vocabulary; honor it instead of reading every nonzero
        # exit as a wedge:
        #   0  clean            3  recovered (succeeded after restarts)
        #   75 preempted-clean  (SIGTERM honored; checkpoint resumable)
        #   69 retries-exhausted (recovery budget spent)
        #   71 host-lost        (a distributed peer/coordinator died and
        #                        the launcher's re-ramp budget is spent)
        case "$rc" in
            0)  echo "$(date -u +%FT%TZ) tpu_watch: window capture complete" >> "$LOG"
                exit 0 ;;
            3)  echo "$(date -u +%FT%TZ) tpu_watch: window capture complete (recovered after in-run restarts)" >> "$LOG"
                exit 0 ;;
            75) echo "$(date -u +%FT%TZ) tpu_watch: preempted-clean — resumable checkpoint on disk; watching for the next window" >> "$LOG" ;;
            69) echo "$(date -u +%FT%TZ) tpu_watch: retries exhausted inside the window; watching for the next window" >> "$LOG" ;;
            71) echo "$(date -u +%FT%TZ) tpu_watch: host lost beyond the launcher's re-ramp budget; checkpoint resumable — watching for the next window" >> "$LOG" ;;
            *)  echo "$(date -u +%FT%TZ) tpu_watch: capture failed (rc=$rc, possible wedge); resuming watch" >> "$LOG" ;;
        esac
    fi
    sleep "$INTERVAL"
done
