"""Package a completed (or checkpointed) mega_soup / mega_multisoup run
for results_tpu/.

The live run dir holds artifacts at two scales: small evidence files
(config/meta/log/events, the class-count curve) and bulk state (the
full-population ``soup.traj`` frames at ~56 MB each, orbax checkpoints).
This packager commits the evidence and a DETERMINISTIC 2048-particle
sample of the trajectory frames (same even stride the render cap uses),
leaving the bulk on disk:

    python scripts/package_mega_run.py <run_dir> <out_dir>

Outputs in <out_dir>:
    config.json meta.json log.txt events.jsonl   (copied verbatim)
    mega_curve.png                               (class counts/generation)
    soup_trajectories_3d.png/.html               (sampled 3-D PCA views)
    trajectories_sample.npz                      (weights/uids/generations
                                                  for the sampled slots)
    PACKAGE.json                                 (what was sampled, from
                                                  what, when; final counts)
"""

import json
import os
import shutil
import sys
import time

import numpy as np


def main(run_dir: str, out_dir: str) -> int:
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    # packaging is pure host work; never let the (possibly wedged) tunnel
    # backend initialize under the srnn_tpu import chain
    from srnn_tpu.utils.backend import force_cpu
    force_cpu()
    from srnn_tpu import viz

    os.makedirs(out_dir, exist_ok=True)
    for name in ("config.json", "meta.json", "log.txt", "events.jsonl"):
        src = os.path.join(run_dir, name)
        if os.path.exists(src):
            shutil.copy2(src, os.path.join(out_dir, name))

    # class-count curve + trajectory views (render caps keep this bounded
    # at mega scale); renders land in out_dir, inputs read from run_dir
    outputs = viz.search_and_apply(run_dir, redo=True, out_dir=out_dir)

    package = {"run_dir": os.path.abspath(run_dir),
               "packaged_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                            time.gmtime()),
               "renders": [os.path.basename(o) for o in outputs]}

    # homogeneous runs capture one soup.traj; heterogeneous mega_multisoup
    # runs capture one soup.tN.traj per type — sample whichever exist
    # (glob, not a sequential probe, so a missing/corrupt t0 cannot
    # silently skip the later types)
    import glob as _glob
    import re as _re

    stores = [("soup.traj", "trajectories_sample.npz", None)]
    for path in sorted(_glob.glob(os.path.join(run_dir, "soup.t*.traj"))):
        m = _re.fullmatch(r"soup\.t(\d+)\.traj", os.path.basename(path))
        if m:
            t = int(m.group(1))
            stores.append((f"soup.t{t}.traj",
                           f"trajectories_sample.t{t}.npz", t))
    for base, out_name, type_idx in stores:
        traj = os.path.join(run_dir, base)
        if not os.path.exists(traj):
            continue
        from srnn_tpu.utils.trajstore import read_store_sampled, store_shape

        # the SAME deterministic stride the renders use, sampled at read
        # time (streaming windows — a long mega capture's full frames
        # would not fit in host RAM)
        n, p = store_shape(traj)
        cols = viz.render_columns(n)
        store = read_store_sampled(traj, cols)
        np.savez_compressed(
            os.path.join(out_dir, out_name),
            weights=store["weights"].astype(np.float32),
            uids=store["uids"],
            generations=store["generations"],
            sampled_columns=cols)
        sample = {
            "frames": int(len(store["generations"])), "population": int(n),
            "sampled_slots": int(len(cols)), "weights_per_particle": int(p)}
        if type_idx is None:
            package["trajectory_sample"] = sample
        else:
            package.setdefault("trajectory_samples_per_type", {})[
                f"t{type_idx}"] = sample

    events = os.path.join(run_dir, "events.jsonl")
    if os.path.exists(events):
        last = None
        with open(events) as fh:
            for line in fh:
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                if "counts" in ev:
                    last = ev
        if last is not None:
            package["final"] = {"generation": last.get("generation"),
                                "counts": last.get("counts")}

    with open(os.path.join(out_dir, "PACKAGE.json"), "w") as fh:
        json.dump(package, fh, indent=1)
    print(json.dumps(package))
    return 0


if __name__ == "__main__":
    if len(sys.argv) != 3:
        raise SystemExit(__doc__)
    sys.exit(main(sys.argv[1], sys.argv[2]))
