#!/usr/bin/env bash
# One-command TPU capture for a healthy tunnel window.
#
# The tunneled v5e backend is healthy only intermittently (see
# results_tpu/opportunistic_log.jsonl for the probe history), so when a
# window opens, everything TPU-evidence-worthy must run unattended from a
# single invocation:
#   1. probe (bounded; exits fast if the tunnel is wedged)
#   2. the full opportunistic row set (bench kernel, soup levers incl. the
#      round-5 fused train/apply kernels, mixed soup, train generality)
#   3. the north-star mega-soup (1M x 1000 generations, full dynamics,
#      best config) into results_tpu/ with checkpoints + capture
#
# Invoke the PARENT with a stripped PYTHONPATH so a mid-run wedge cannot
# hang this script's own interpreter at startup (children re-add the axon
# site explicitly — benchmarks/opportunistic.py handles that):
#   PYTHONPATH= bash scripts/tpu_window.sh
set -u
cd "$(dirname "$0")/.."

echo "== probe =="
PYTHONPATH= python benchmarks/opportunistic.py --probe-only || exit 1
probe_ok=$(tail -1 results_tpu/opportunistic_log.jsonl |
    python -c "import json,sys; r=json.load(sys.stdin); \
print(1 if r.get('status')=='ok' and r.get('platform') not in (None,'cpu') else 0)")
if [ "$probe_ok" != "1" ]; then
    echo "tunnel not healthy; see results_tpu/opportunistic_log.jsonl"
    exit 2
fi

echo "== opportunistic rows =="
PYTHONPATH= python benchmarks/opportunistic.py

echo "== north-star mega-soup on TPU =="
# The stripped parent PYTHONPATH must NOT leak into this step: without
# the axon sitecustomize dir the plugin never registers and the flagship
# run would silently execute on CPU while claiming a TPU window.  Re-add
# the site explicitly (SRNN_AXON_SITE overrides the conventional default,
# same knob benchmarks/opportunistic.py honors) and hard-gate on a live
# accelerator first.
AXON_PP="$PWD:${SRNN_AXON_SITE:-/root/.axon_site}"
if ! PYTHONPATH="$AXON_PP" timeout 300 python -c "
from srnn_tpu.utils.backend import ensure_backend
p, _ = ensure_backend(retries=2, sleep_s=5.0, fallback_cpu=False)
raise SystemExit(0 if p != 'cpu' else 4)"; then
    # exit 4, NOT 3: 3 now means "recovered" in the supervisor exit
    # vocabulary tpu_watch.sh branches on; 4 lands in its wedge/retry arm
    echo "accelerator gate failed; NOT running mega_soup on CPU"
    exit 4
fi
# full dynamics at the flagship scale — the same config as the committed
# CPU north-star run (results_tpu/exp-mega-soup-_1785434317.9088535-0)
# plus the round-5 fused train kernel; resumable run dir under
# results_tpu/ (bit-exact resume if the window closes mid-run).
#
# Cross-window elasticity: if the NEWEST mega-soup run dir holds an
# unfinished checkpoint (gen < 1000 — e.g. last window ended in a
# preempted-clean exit 75), CONTINUE it instead of starting over; the
# run's saved config wins over the flags below, so the continuation is
# bit-exact.  This is what makes tpu_watch.sh's "resumable checkpoint on
# disk; watching for the next window" actually pay off unattended.
RESUME=""
latest_run=$(ls -dt results_tpu/exp-mega-soup-*/ 2>/dev/null | head -1)
if [ -n "$latest_run" ] && [ -f "$latest_run/config.json" ]; then
    last_ckpt=$(ls -d "$latest_run"ckpt-gen* 2>/dev/null \
        | grep -E 'ckpt-gen[0-9]+/?$' | sort | tail -1)
    if [ -n "$last_ckpt" ]; then
        gen=$((10#$(basename "$last_ckpt" | sed 's/ckpt-gen//')))
        if [ "$gen" -lt 1000 ]; then
            RESUME="${latest_run%/}"
            echo "resuming unfinished mega-soup at gen $gen: $RESUME"
        fi
    fi
fi
PYTHONPATH="$AXON_PP" python -m srnn_tpu.setups mega_soup \
    ${RESUME:+--resume "$RESUME"} \
    --root results_tpu \
    --size 1000000 --generations 1000 \
    --attacking-rate 0.1 --learn-from-rate 0.1 --train 10 \
    --layout popmajor --respawn-draws fused --train-impl pallas \
    --capture-every 50 --checkpoint-every 100 --seed 7
rc=$?
# supervisor exit vocabulary (srnn_tpu/resilience): 3 = recovered after
# in-run restarts, still a success; 75/69 propagate to tpu_watch.sh
case "$rc" in
    0) ;;
    3) echo "mega_soup recovered after in-run restart(s); run completed" ;;
    75|69|71) echo "mega_soup exited $rc (supervisor); rows above still stand"
           exit "$rc" ;;
    *) echo "mega_soup failed (rc=$rc); rows above still stand" ;;
esac

echo "== done; commit results_tpu/ + RESULTS.md updates =="
