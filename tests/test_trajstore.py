"""Native + pure-Python trajectory store: format compatibility, CRC
integrity, truncation recovery, streaming capture."""

import os
import struct

import jax
import numpy as np
import pytest

from srnn_tpu import Topology
from srnn_tpu.soup import SoupConfig, count, evolve, seed
from srnn_tpu.utils import TrajStore, evolve_captured, read_store, read_store_artifact
from srnn_tpu.utils.trajstore import native_available


def _frames(n, p, g, seed_=0):
    rng = np.random.default_rng(seed_)
    return [dict(
        generation=i + 1,
        weights=rng.normal(size=(n, p)).astype(np.float32),
        uids=rng.integers(0, 100, size=n).astype(np.int32),
        action=rng.integers(0, 7, size=n).astype(np.int32),
        counterpart=rng.integers(-1, 100, size=n).astype(np.int32),
        loss=rng.normal(size=n).astype(np.float32),
    ) for i in range(g)]


def _write(path, frames, n, p, native):
    with TrajStore(str(path), n, p, native=native) as s:
        for fr in frames:
            s.append(fr["generation"], fr["weights"], fr["uids"],
                     fr["action"], fr["counterpart"], fr["loss"])


@pytest.mark.parametrize("native", [False, True])
def test_roundtrip(tmp_path, native):
    if native and not native_available():
        pytest.skip("native lib unavailable")
    n, p, g = 6, 14, 5
    frames = _frames(n, p, g)
    path = tmp_path / "run.traj"
    _write(path, frames, n, p, native)
    out = read_store(str(path))
    assert out["weights"].shape == (g, n, p)
    for i, fr in enumerate(frames):
        np.testing.assert_array_equal(out["weights"][i], fr["weights"])
        np.testing.assert_array_equal(out["uids"][i], fr["uids"])
        np.testing.assert_array_equal(out["action"][i], fr["action"])
        np.testing.assert_array_equal(out["counterpart"][i], fr["counterpart"])
        np.testing.assert_array_equal(out["loss"][i], fr["loss"])
        assert out["generations"][i] == fr["generation"]


@pytest.mark.skipif(not native_available(), reason="native lib unavailable")
def test_cross_writer_compatibility(tmp_path):
    """Files written natively parse with the python reader and vice versa."""
    n, p, g = 3, 7, 4
    frames = _frames(n, p, g, seed_=1)
    _write(tmp_path / "native.traj", frames, n, p, native=True)
    _write(tmp_path / "py.traj", frames, n, p, native=False)
    a = open(tmp_path / "native.traj", "rb").read()
    b = open(tmp_path / "py.traj", "rb").read()
    assert a == b  # byte-identical format, CRCs included
    from srnn_tpu.utils.trajstore import _read_store_py
    native_file_py_reader = _read_store_py(str(tmp_path / "native.traj"), 0, None)
    np.testing.assert_array_equal(
        native_file_py_reader["weights"], np.stack([f["weights"] for f in frames]))


def test_truncation_recovery_and_crc(tmp_path):
    n, p, g = 4, 5, 3
    frames = _frames(n, p, g, seed_=2)
    path = tmp_path / "t.traj"
    _write(path, frames, n, p, native=False)
    size = os.path.getsize(path)
    # torn final frame (crash mid-write): reader sees only complete frames
    with open(path, "r+b") as f:
        f.truncate(size - 10)
    out = read_store(str(path))
    assert out["weights"].shape[0] == g - 1
    # bit-flip inside a frame payload -> CRC failure surfaces as an error
    with open(path, "r+b") as f:
        f.seek(60)
        byte = f.read(1)
        f.seek(60)
        f.write(bytes([byte[0] ^ 0xFF]))
    with pytest.raises(OSError, match="CRC|format|-2"):
        read_store(str(path))


def test_range_reads(tmp_path):
    n, p, g = 2, 3, 6
    frames = _frames(n, p, g, seed_=3)
    path = tmp_path / "r.traj"
    _write(path, frames, n, p, native=False)
    mid = read_store(str(path), start=2, count=3)
    assert mid["weights"].shape == (3, 2, 3)
    assert mid["generations"].tolist() == [3, 4, 5]
    with pytest.raises(OSError):
        read_store(str(path), start=5, count=3)


@pytest.mark.parametrize("native", [False, True])
def test_append_mode_preserves_existing_frames(tmp_path, native):
    """Resume path: mode='a' appends after existing frames instead of
    truncating them (the round-2 data-loss bug: open(path, 'wb') wiped all
    previously captured trajectories on resume)."""
    if native and not native_available():
        pytest.skip("native lib unavailable")
    n, p = 4, 9
    first, second = _frames(n, p, 3, seed_=4), _frames(n, p, 2, seed_=5)
    path = tmp_path / "resume.traj"
    _write(path, first, n, p, native)
    with TrajStore(str(path), n, p, native=native, mode="a") as s:
        assert s.existing_frames == 3
        for fr in second:
            s.append(fr["generation"] + 3, fr["weights"], fr["uids"],
                     fr["action"], fr["counterpart"], fr["loss"])
    out = read_store(str(path))
    assert out["weights"].shape[0] == 5
    np.testing.assert_array_equal(out["weights"][0], first[0]["weights"])
    np.testing.assert_array_equal(out["weights"][3], second[0]["weights"])
    np.testing.assert_array_equal(out["weights"][4], second[1]["weights"])


@pytest.mark.parametrize("native", [False, True])
def test_append_mode_drops_torn_tail(tmp_path, native):
    """A crash mid-frame leaves a torn tail; reopening for append truncates
    it and the next append lands on a clean frame boundary."""
    if native and not native_available():
        pytest.skip("native lib unavailable")
    n, p = 3, 5
    frames = _frames(n, p, 3, seed_=6)
    path = tmp_path / "torn.traj"
    _write(path, frames, n, p, native)
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) - 7)
    extra = _frames(n, p, 1, seed_=7)[0]
    with TrajStore(str(path), n, p, native=native, mode="a") as s:
        assert s.existing_frames == 2  # torn 3rd frame dropped
        s.append(99, extra["weights"], extra["uids"], extra["action"],
                 extra["counterpart"], extra["loss"])
    out = read_store(str(path))
    assert out["generations"].tolist() == [1, 2, 99]
    np.testing.assert_array_equal(out["weights"][2], extra["weights"])


@pytest.mark.parametrize("native", [False, True])
def test_append_mode_rejects_shape_mismatch(tmp_path, native):
    if native and not native_available():
        pytest.skip("native lib unavailable")
    n, p = 4, 9
    path = tmp_path / "mismatch.traj"
    _write(path, _frames(n, p, 1, seed_=8), n, p, native)
    with pytest.raises(OSError):
        TrajStore(str(path), n + 1, p, native=native, mode="a")


@pytest.mark.parametrize("native", [False, True])
def test_append_mode_creates_missing_file(tmp_path, native):
    """mode='a' on a fresh path behaves like a new store."""
    if native and not native_available():
        pytest.skip("native lib unavailable")
    n, p = 2, 3
    path = tmp_path / "fresh.traj"
    fr = _frames(n, p, 1, seed_=9)[0]
    with TrajStore(str(path), n, p, native=native, mode="a") as s:
        assert s.existing_frames == 0
        s.append(1, fr["weights"], fr["uids"], fr["action"],
                 fr["counterpart"], fr["loss"])
    assert read_store(str(path))["weights"].shape[0] == 1


def test_truncate_frames_reconciles_post_checkpoint_captures(tmp_path):
    """truncate_frames drops frames past the restored checkpoint so a resume
    can't append duplicates; no-op when already consistent."""
    from srnn_tpu.utils import truncate_frames

    n, p = 3, 4
    frames = _frames(n, p, 5, seed_=10)
    path = tmp_path / "dup.traj"
    _write(path, frames, n, p, native=False)
    assert truncate_frames(str(path), 3) == 3
    out = read_store(str(path))
    assert out["generations"].tolist() == [1, 2, 3]
    assert truncate_frames(str(path), 99) == 3  # no-op beyond current count
    assert truncate_frames(str(tmp_path / "absent.traj"), 2) == 0


@pytest.mark.parametrize("native", [False, True])
def test_append_mode_recreates_torn_header(tmp_path, native):
    """A crash right after store creation can leave a 0-byte file (buffered
    header never flushed); mode='a' must recreate rather than fail the
    whole resume."""
    if native and not native_available():
        pytest.skip("native lib unavailable")
    n, p = 2, 3
    path = tmp_path / "torn_header.traj"
    path.write_bytes(b"SRNN")  # shorter than the header
    fr = _frames(n, p, 1, seed_=11)[0]
    with TrajStore(str(path), n, p, native=native, mode="a") as s:
        assert s.existing_frames == 0
        s.append(1, fr["weights"], fr["uids"], fr["action"],
                 fr["counterpart"], fr["loss"])
    assert read_store(str(path))["weights"].shape[0] == 1


def test_evolve_captured_stride_and_viz_artifact(tmp_path):
    """Streaming capture: strided frames match an unstrided device run at
    the captured generations, and the artifact renders in viz."""
    cfg = SoupConfig(topo=Topology("weightwise"), size=6,
                     attacking_rate=0.3, train=0,
                     remove_divergent=True, remove_zero=True)
    st0 = seed(cfg, jax.random.key(3))
    path = str(tmp_path / "cap.traj")
    with TrajStore(path, cfg.size, cfg.topo.num_weights) as store:
        final = evolve_captured(cfg, st0, generations=6, store=store, every=2)
    # reference run without capture must agree bit-exactly
    ref = evolve(cfg, st0, generations=6)
    np.testing.assert_array_equal(np.asarray(final.weights), np.asarray(ref.weights))

    out = read_store(path)
    assert out["generations"].tolist() == [2, 4, 6]
    np.testing.assert_array_equal(out["weights"][-1], np.asarray(ref.weights))

    from srnn_tpu import viz
    art = read_store_artifact(path)
    img = viz.plot_latent_trajectories_3d(art, str(tmp_path / "cap.png"))
    assert os.path.getsize(img) > 5000


# ------------------------------------------------- multihost shard capture


def _sharded_cap_cfg():
    return SoupConfig(topo=Topology("weightwise"), size=8,
                      attacking_rate=0.4, train=0,
                      remove_divergent=True, remove_zero=True)


def test_sharded_capture_shards_merge_to_global_frames(tmp_path, mesh):
    """Per-process .traj shards (each process appends only its particle-row
    block) merge back into the exact global frames a single-store capture
    writes.  Two simulated processes write their shards from identical
    deterministic runs — the real multihost layout, minus the second host."""
    from srnn_tpu.parallel import make_sharded_state
    from srnn_tpu.utils import (open_process_shard, read_sharded_store,
                                sharded_evolve_captured)

    cfg = _sharded_cap_cfg()
    base = str(tmp_path / "soup.traj")

    # reference: single-store sharded capture (process_count=1 -> plain path)
    ref_base = str(tmp_path / "ref.traj")
    st = make_sharded_state(cfg, mesh, jax.random.key(5))
    with open_process_shard(cfg, ref_base) as store:
        final_ref = sharded_evolve_captured(cfg, mesh, st, 6, store, every=2)

    # simulated 2-process capture: each "process" replays the same
    # deterministic evolution, writing only its shard
    for pi in range(2):
        st = make_sharded_state(cfg, mesh, jax.random.key(5))
        with open_process_shard(cfg, base, process_index=pi,
                                num_processes=2) as store:
            final = sharded_evolve_captured(cfg, mesh, st, 6, store, every=2,
                                            process_index=pi, num_processes=2)
    np.testing.assert_array_equal(np.asarray(final.weights),
                                  np.asarray(final_ref.weights))

    merged = read_sharded_store(base)
    single = read_store(ref_base)
    assert merged["generations"].tolist() == [2, 4, 6]
    for key in ("weights", "uids", "action", "counterpart", "loss"):
        np.testing.assert_array_equal(merged[key], single[key])


def test_sharded_capture_kill_resume_with_mergeable_shards(tmp_path, mesh):
    """Kill/resume across shards: truncate_sharded_frames drops the frames
    past the restored checkpoint in EVERY shard, appends continue cleanly,
    and the merged read sees one consistent timeline.  A shard set where
    one file is longer (kill mid-capture) only exposes complete frames."""
    from srnn_tpu.parallel import make_sharded_state
    from srnn_tpu.utils import (open_process_shard, read_sharded_store,
                                sharded_evolve_captured)
    from srnn_tpu.utils.trajstore import truncate_sharded_frames

    cfg = _sharded_cap_cfg()
    base = str(tmp_path / "soup.traj")

    def run_shard(pi, generations, mode):
        st = make_sharded_state(cfg, mesh, jax.random.key(7))
        with open_process_shard(cfg, base, mode=mode, process_index=pi,
                                num_processes=2) as store:
            sharded_evolve_captured(cfg, mesh, st, generations, store,
                                    every=2, process_index=pi,
                                    num_processes=2)

    # initial capture: 3 frames in each shard (gens 2, 4, 6)
    for pi in range(2):
        run_shard(pi, 6, "w")
    # simulate a kill after a checkpoint at gen 4: reconcile to 2 frames
    assert truncate_sharded_frames(base, 2) == 2
    # resumed run appends gen 6 again (same stream -> same values)
    for pi in range(2):
        run_shard(pi, 6, "a")
    merged = read_sharded_store(base)
    # gens 2,4 from before the kill + 2,4,6 re-run: the resume path in
    # mega_soup truncates to the checkpoint so only one timeline exists —
    # here we wrote a fresh identical run after truncation, so frames are
    # [2, 4] + [2, 4, 6] at shard level; complete-merge sees all 5
    assert merged["generations"].tolist() == [2, 4, 2, 4, 6]

    # torn shard set: make shard 0 one frame longer than shard 1
    run_shard(0, 2, "a")
    merged2 = read_sharded_store(base)
    assert merged2["generations"].shape[0] == 5  # torn 6th frame excluded


def test_sharded_artifact_renders_in_viz(tmp_path, mesh):
    """read_store_artifact accepts a shard-set base path, so the analysis
    pipeline (viz) consumes multihost captures unchanged."""
    from srnn_tpu import viz
    from srnn_tpu.parallel import make_sharded_state
    from srnn_tpu.utils import (open_process_shard, read_store_artifact,
                                sharded_evolve_captured)

    cfg = _sharded_cap_cfg()
    base = str(tmp_path / "soup.traj")
    for pi in range(2):
        st = make_sharded_state(cfg, mesh, jax.random.key(5))
        with open_process_shard(cfg, base, process_index=pi,
                                num_processes=2) as store:
            sharded_evolve_captured(cfg, mesh, st, 6, store, every=2,
                                    process_index=pi, num_processes=2)
    art = read_store_artifact(base)
    assert art["weights"].shape == (3, cfg.size, cfg.topo.num_weights)
    img = viz.plot_latent_trajectories_3d(art, str(tmp_path / "m.png"))
    assert os.path.getsize(img) > 5000
    # the run-dir walker discovers shard sets too (no plain .traj exists)
    outputs = viz.search_and_apply(str(tmp_path))
    assert any("soup_trajectories_3d" in o for o in outputs)


def test_sampled_read_matches_full_read(tmp_path):
    """read_store_sampled streams frame windows and keeps only the given
    columns; result must equal slicing the full read, including
    generations, and store_shape must report the merged shape without
    reading frames."""
    from srnn_tpu.utils.trajstore import (read_store_sampled, store_shape)

    n, p, g = 20, 14, 7
    frames = _frames(n, p, g)
    path = tmp_path / "big.traj"
    _write(path, frames, n, p, native=False)
    assert store_shape(str(path)) == (n, p)
    cols = np.array([0, 3, 11, 19])
    full = read_store(str(path))
    sampled = read_store_sampled(str(path), cols, chunk_frames=3)
    np.testing.assert_array_equal(sampled["generations"],
                                  full["generations"])
    for key in ("weights", "uids", "action", "counterpart", "loss"):
        np.testing.assert_array_equal(sampled[key], full[key][:, cols])
