import numpy as np
import pytest

from srnn_tpu.topology import (
    Topology,
    aggregation_segments,
    normalized_weight_coords,
    weight_coords,
)


def test_weightwise_shapes():
    t = Topology("weightwise", width=2, depth=2)
    assert t.layer_shapes == ((4, 2), (2, 2), (2, 1))
    assert t.num_weights == 14
    assert t.offsets == (0, 8, 12, 14)


def test_aggregating_shapes():
    t = Topology("aggregating", width=2, depth=2, aggregates=4)
    assert t.layer_shapes == ((4, 2), (2, 2), (2, 4))
    assert t.num_weights == 20


def test_recurrent_shapes():
    # SimpleRNN(2) -> SimpleRNN(2) -> SimpleRNN(1), each with (kernel, recurrent)
    t = Topology("recurrent", width=2, depth=2)
    assert t.layer_shapes == ((1, 2), (2, 2), (2, 2), (2, 2), (2, 1), (1, 1))
    assert t.num_weights == 17
    assert t.rnn_layer_dims == ((1, 2), (2, 2), (2, 1))


def test_unknown_variant_rejected():
    with pytest.raises(ValueError):
        Topology("banana")


def test_weight_coords_enumeration_order():
    t = Topology("weightwise", width=2, depth=2)
    c = weight_coords(t)
    assert c.shape == (14, 3)
    # first kernel (4,2): layer 0, cells 0..3, weights 0..1, row-major
    assert c[0].tolist() == [0, 0, 0]
    assert c[1].tolist() == [0, 0, 1]
    assert c[2].tolist() == [0, 1, 0]
    assert c[7].tolist() == [0, 3, 1]
    # second kernel starts at flat index 8
    assert c[8].tolist() == [1, 0, 0]
    # last kernel (2,1)
    assert c[12].tolist() == [2, 0, 0]
    assert c[13].tolist() == [2, 1, 0]


def test_normalized_coords_match_reference_rule():
    # normalize_id divides only when the max id > 1 (network.py:215-220)
    t = Topology("weightwise", width=2, depth=2)
    n = normalized_weight_coords(t)
    # layer ids: max 2 -> divided by 2
    assert n[0, 0] == 0.0 and n[8, 0] == pytest.approx(0.5) and n[12, 0] == 1.0
    # layer0 cells: max 3 -> divided by 3
    assert n[2, 1] == pytest.approx(1 / 3)
    assert n[7, 1] == 1.0
    # layer0 weight ids: max 1 -> NOT divided (norm=1 fails `norm > 1`)
    assert n[1, 2] == 1.0
    # layer2 (2,1): weight id max 0 -> raw 0
    assert n[12, 2] == 0.0


def test_aggregation_segments_leftover_rule():
    # P=16 with k=3: size 5, leftover 1 appended to LAST collection
    t = Topology("aggregating", width=2, depth=2, aggregates=3)
    assert t.num_weights == 16
    seg, counts = aggregation_segments(t)
    assert counts.tolist() == [5, 5, 6]
    assert seg[:5].tolist() == [0] * 5
    assert seg[-6:].tolist() == [2] * 6


def test_aggregation_segments_exact_division():
    t = Topology("aggregating", width=2, depth=2, aggregates=4)
    seg, counts = aggregation_segments(t)
    assert counts.tolist() == [5, 5, 5, 5]
    assert seg.tolist() == sorted(seg.tolist())
