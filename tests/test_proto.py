"""Second-gen prototype networks + gradient-free hill climber."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from srnn_tpu import Topology
from srnn_tpu.fixtures import identity_fixpoint_flat
from srnn_tpu.optimize import fixpoint_loss, hillclimb
from srnn_tpu.proto import (ProtoTopology, apply_self, fit, forward_ff,
                            init_proto)


def test_shapes_and_builder_count_quirk():
    ff = ProtoTopology(features=2, cells=2, layers=2, recurrent=False)
    # true count: (2,2) + (2,2) + (2,1) = 4 + 4 + 2
    assert ff.num_weights == 10
    # the reference's announced count over-counts the head (methods.py:36)
    assert ff.builder_parameter_count == 12

    rnn = ProtoTopology(features=2, cells=2, layers=2, recurrent=True)
    # (2,2)+(2,2) + (2,2)+(2,2) + (2,2) head = 20; formula agrees (assert
    # enabled in the reference for RNN, methods.py:104)
    assert rnn.num_weights == 20
    assert rnn.builder_parameter_count == 20
    assert rnn.seq_len == 10


def test_ff_forward_is_linear_chain():
    pt = ProtoTopology(features=2, cells=2, layers=1)
    # single (2,2) layer then (2,1) head: y = x @ A @ b
    a = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
    b = np.array([[1.0], [-1.0]], np.float32)
    flat = jnp.asarray(np.concatenate([a.reshape(-1), b.reshape(-1)]))
    x = jnp.asarray(np.array([[1.0, 1.0]], np.float32))
    out = forward_ff(pt, flat, x)
    np.testing.assert_allclose(np.asarray(out), (x @ a @ b), atol=1e-6)


def test_fit_loss_semantics():
    """losses[t] must equal MSE(f(w_t), w_t) evaluated BEFORE the update
    (methods.py:125: compares y against the still-old weights)."""
    pt = ProtoTopology(features=2, cells=2, layers=2)
    w0 = init_proto(pt, jax.random.key(0)) * 0.5
    final, losses = fit(pt, w0, epochs=3)
    w1, l0 = apply_self(pt, w0)
    np.testing.assert_allclose(float(losses[0]), float(l0), rtol=1e-6)
    w2, l1 = apply_self(pt, w1)
    np.testing.assert_allclose(float(losses[1]), float(l1), rtol=1e-6)
    w3, _ = apply_self(pt, w2)
    np.testing.assert_allclose(np.asarray(final), np.asarray(w3), rtol=1e-6)


def test_fit_rnn_runs():
    pt = ProtoTopology(features=2, cells=2, layers=2, recurrent=True)
    w0 = init_proto(pt, jax.random.key(1)) * 0.3
    final, losses = fit(pt, w0, epochs=5)
    assert final.shape == (20,) and losses.shape == (5,)
    assert np.isfinite(np.asarray(losses)).all()


def test_hillclimb_monotone_and_improves():
    topo = Topology("aggregating", width=2, depth=2, aggregates=4)
    from srnn_tpu.init import init_flat

    w0 = init_flat(topo, jax.random.key(2))
    best, trace = hillclimb(topo, w0, jax.random.key(3), shots=16, rounds=40,
                            std=0.05)
    trace = np.asarray(trace)
    assert (np.diff(trace) <= 1e-12).all()  # monotone non-increasing
    assert trace[-1] < float(fixpoint_loss(topo, w0))  # actually improved
    assert float(fixpoint_loss(topo, best)) == pytest.approx(float(trace[-1]))


def test_hillclimb_keeps_perfect_fixpoint():
    topo = Topology("weightwise", width=2, depth=2)
    flat = identity_fixpoint_flat(topo)
    assert float(fixpoint_loss(topo, flat)) == 0.0
    best, trace = hillclimb(topo, flat, jax.random.key(4), shots=8, rounds=5)
    np.testing.assert_array_equal(np.asarray(best), np.asarray(flat))
