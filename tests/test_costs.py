"""Cost observatory: the compile/FLOP/memory ledger (telemetry.costs),
the Perfetto trace export (report --trace), the perf-regression sentinel
(benchmarks/regress.py), and the watch/report no-data hardening.

The load-bearing invariant mirrors --no-spans: the cost plane is
host-side compile metadata only, so a run with it is bitwise-identical
to a run without (--no-costs, tested below)."""

import importlib.util
import io
import json
import os
import sys

import jax
import numpy as np
import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from srnn_tpu.experiment import restore_checkpoint          # noqa: E402
from srnn_tpu.setups.common import REGISTRY                 # noqa: E402
from srnn_tpu.telemetry import costs, fleet, watch          # noqa: E402
from srnn_tpu.telemetry.metrics import MetricsRegistry      # noqa: E402
from srnn_tpu.utils import aot                              # noqa: E402


@pytest.fixture
def ledger(tmp_path, monkeypatch):
    """Point the cost plane at a private ledger + a clean accumulator."""
    path = str(tmp_path / "compile_ledger.jsonl")
    monkeypatch.setenv(costs.LEDGER_PATH_ENV, path)
    monkeypatch.delenv(costs.DISABLE_ENV, raising=False)
    costs.reset_for_tests()
    yield path
    costs.reset_for_tests()


def _tiny_entry(tag="a"):
    @jax.jit
    def f(x):
        return (x * 2.0).sum()

    return f, (jax.ShapeDtypeStruct((8, 8), jax.numpy.float32),), tag


# ---------------------------------------------------------------------------
# ledger round-trip
# ---------------------------------------------------------------------------


def test_ledger_records_miss_then_hit_and_matches_memo_counters(ledger):
    aot.clear_executable_cache()
    f, args, _ = _tiny_entry()
    e1 = aot.aot_compile("costs.test.tiny", f, args)
    e2 = aot.aot_compile("costs.test.tiny", f, args)
    assert not e1.cached and e2.cached
    rows, skipped = costs.read_ledger(ledger)
    assert skipped == 0
    mine = [r for r in rows if r["entry"] == "costs.test.tiny"]
    assert [r["cached"] for r in mine] == [False, True]
    # hit/miss accounting matches the aot memo outcome exactly
    snap = costs.snapshot()
    assert snap["hits"] == 1 and snap["misses"] == 1
    # the miss row carries cost/memory analysis on this backend; every
    # field is ALLOWED to be null, but must exist (graceful-null contract)
    miss = mine[0]
    for k in ("flops", "bytes_accessed", "temp_bytes", "argument_bytes",
              "output_bytes"):
        assert k in miss
    assert miss["compile_s"] >= 0 and miss["backend"] == "cpu"


def test_ledger_torn_tail_skipped(ledger):
    aot.clear_executable_cache()
    f, args, _ = _tiny_entry()
    aot.aot_compile("costs.test.torn", f, args)
    with open(ledger, "a") as fh:
        fh.write('{"entry": "half-written row, no clos')
    rows, skipped = costs.read_ledger(ledger)
    assert skipped == 1
    assert any(r["entry"] == "costs.test.torn" for r in rows)


def test_extract_costs_graceful_on_hostile_backend(ledger):
    class Hostile:
        def cost_analysis(self):
            raise RuntimeError("no cost model on this backend")

        def memory_analysis(self):
            raise RuntimeError("nope")

    out = costs.extract_costs(Hostile())
    assert set(out) >= {"flops", "temp_bytes"}
    assert all(v is None for v in out.values())
    # and a record with such an object still lands a parseable row
    costs.record_compile("costs.test.hostile", cached=False, lower_s=0.1,
                         compile_s=0.2, persistent=True,
                         compiled=Hostile(), backend="weird")
    rows, skipped = costs.read_ledger(ledger)
    row = [r for r in rows if r["entry"] == "costs.test.hostile"][0]
    assert skipped == 0 and row["flops"] is None


def test_ledger_write_failure_collected_not_raised(tmp_path, monkeypatch):
    monkeypatch.setenv(costs.LEDGER_PATH_ENV,
                       str(tmp_path / "nope" / "ledger.jsonl"))
    costs.reset_for_tests()
    # a ledger path whose parent cannot be created must not raise
    monkeypatch.setattr(os, "makedirs",
                        lambda *a, **k: (_ for _ in ()).throw(OSError(30)))
    costs.record_compile("costs.test.fail", cached=False, lower_s=0.0,
                         compile_s=0.0, persistent=True, backend="cpu")
    errs = costs.consume_ledger_errors()
    assert errs and "ledger append failed" in errs[0]
    assert costs.consume_ledger_errors() == []   # drained


def test_fold_cost_metrics_is_idempotent_and_exports(ledger):
    aot.clear_executable_cache()
    f, args, _ = _tiny_entry()
    aot.aot_compile("costs.test.fold", f, args)
    reg = MetricsRegistry()
    costs.fold_cost_metrics(reg)
    costs.fold_cost_metrics(reg)   # delta-fold: calling twice is safe
    snap = costs.snapshot()
    assert reg.counter("soup_aot_cache_misses_total").value() \
        == snap["misses"]
    assert abs(reg.counter("soup_compile_seconds_total").value()
               - snap["compile_seconds"]) < 1e-9
    prom = reg.to_prometheus()
    assert "srnn_soup_hlo_flops" in prom
    assert "srnn_soup_hbm_bytes" in prom
    if snap["entry_flops"].get("costs.test.fold") is not None:
        assert 'srnn_soup_hlo_flops{entry="costs.test.fold"}' in prom


def test_disabled_cost_plane_writes_nothing(tmp_path, monkeypatch):
    monkeypatch.setenv(costs.DISABLE_ENV, "1")
    monkeypatch.setenv(costs.LEDGER_PATH_ENV, str(tmp_path / "l.jsonl"))
    costs.reset_for_tests()
    aot.clear_executable_cache()
    f, args, _ = _tiny_entry()
    aot.aot_compile("costs.test.disabled", f, args)
    assert costs.ledger_path() is None
    assert not os.path.exists(tmp_path / "l.jsonl")
    assert costs.snapshot()["misses"] == 0


# ---------------------------------------------------------------------------
# the A/B oracle: cost plane on == off, bitwise
# ---------------------------------------------------------------------------


def test_cost_plane_does_not_perturb_results(tmp_path, monkeypatch):
    """mega_soup default vs --no-costs: weights/uids/PRNG bitwise equal;
    the default run carries the cost gauges + ledger + roofline source
    row, the --no-costs run none of them."""
    monkeypatch.setenv(costs.LEDGER_PATH_ENV,
                       str(tmp_path / "compile_ledger.jsonl"))
    costs.reset_for_tests()
    with_costs = REGISTRY["mega_soup"](
        ["--smoke", "--seed", "47", "--root", str(tmp_path / "a")])
    without = REGISTRY["mega_soup"](
        ["--smoke", "--seed", "47", "--no-costs",
         "--root", str(tmp_path / "b")])
    a = restore_checkpoint(os.path.join(with_costs, "ckpt-gen00000006"))
    b = restore_checkpoint(os.path.join(without, "ckpt-gen00000006"))
    np.testing.assert_array_equal(np.asarray(a.weights),
                                  np.asarray(b.weights))
    np.testing.assert_array_equal(np.asarray(a.uids), np.asarray(b.uids))
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(a.key)),
        np.asarray(jax.random.key_data(b.key)))

    def cost_rows(d):
        return [json.loads(l) for l in
                open(os.path.join(d, "events.jsonl"))
                if '"kind": "cost"' in l]

    rows = cost_rows(with_costs)
    assert len(rows) == 1 and rows[0]["entry"] == "mega_soup.chunk"
    assert rows[0]["particles"] == 64 and rows[0]["generations"] == 2
    assert not cost_rows(without)
    prom = open(os.path.join(with_costs, "metrics.prom")).read()
    assert "srnn_soup_hlo_flops" in prom and "srnn_soup_hbm_bytes" in prom
    assert 'srnn_soup_hlo_flops{entry="mega_soup.chunk"}' in prom
    prom_b = open(os.path.join(without, "metrics.prom")).read()
    assert "srnn_soup_hlo_flops{" not in prom_b
    ledger_rows, _ = costs.read_ledger()
    assert any(r["entry"] == "mega_soup.chunk" for r in ledger_rows)

    # the report renders the cost block + derived roofline from the run
    from srnn_tpu.telemetry import report

    s = report.summarize(with_costs)
    assert len(s["costs"]) == 1
    rf = s["costs"][0]["roofline"]
    if s["costs"][0]["row"]["flops"] is not None:
        assert rf["flops_per_app"] > 0 and rf["apps_per_sec"] > 0
    out = io.StringIO()
    report._render(s, out)
    assert "cost: mega_soup.chunk" in out.getvalue()


# ---------------------------------------------------------------------------
# Perfetto export
# ---------------------------------------------------------------------------


def _write_events(run_dir, rows, name="events.jsonl"):
    os.makedirs(run_dir, exist_ok=True)
    with open(os.path.join(run_dir, name), "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


def test_perfetto_trace_lanes_and_schema(tmp_path):
    """One lane group per process; serve.* spans land on the serve-ticket
    lane; every non-metadata event carries ph/ts/pid."""
    run = str(tmp_path / "run")
    _write_events(run, [
        {"kind": "span", "span": "mega_soup.chunk", "span_id": 1,
         "process": 0, "start_s": 0.5, "seconds": 1.0, "t": 1.5},
        {"kind": "heartbeat", "stage": "mega_soup", "t": 1.6,
         "generation": 2, "gens_per_sec": 3.5},
        {"kind": "span", "span": "serve.ticket", "span_id": 2,
         "trace_id": "t000001", "tenant": "sweep0", "process": 0,
         "start_s": 2.0, "seconds": 0.25, "t": 2.25},
        {"kind": "restart", "t": 3.0, "fault": "device_loss"},
    ])
    _write_events(run, [
        {"kind": "span", "span": "mega_soup.chunk", "span_id": 1,
         "process": 1, "start_s": 0.6, "seconds": 0.9, "t": 1.5},
    ], name="events-p1.jsonl")
    doc = fleet.perfetto_trace(run)
    evs = doc["traceEvents"]
    for e in evs:
        assert "ph" in e and "pid" in e
        if e["ph"] != "M":
            assert isinstance(e["ts"], (int, float))
    assert doc["otherData"]["processes"] == [0, 1]
    slices = [e for e in evs if e["ph"] == "X"]
    assert {e["pid"] for e in slices} == {0, 1}
    serve_slices = [e for e in slices if e["name"] == "serve.ticket"]
    assert serve_slices and serve_slices[0]["tid"] == fleet._TID_SERVE
    assert serve_slices[0]["args"]["tenant"] == "sweep0"
    host = [e for e in slices if e["name"] == "mega_soup.chunk"]
    assert all(e["tid"] == fleet._TID_SPANS for e in host)
    # ts is microseconds of the run-relative start
    assert host[0]["ts"] == pytest.approx(0.5e6)
    counters = [e for e in evs if e["ph"] == "C"]
    assert counters and counters[0]["args"]["gens_per_sec"] == 3.5
    markers = [e for e in evs if e["ph"] == "i"]
    assert markers and markers[0]["args"]["fault"] == "device_loss"


def test_perfetto_links_triage_device_trace(tmp_path):
    run = str(tmp_path / "run")
    _write_events(run, [
        {"kind": "span", "span": "x", "span_id": 1, "process": 0,
         "start_s": 0.0, "seconds": 0.1, "t": 0.1}])
    trace_dir = os.path.join(run, "triage-gen00000004-stall", "trace")
    os.makedirs(trace_dir)
    open(os.path.join(trace_dir, "events.pb"), "w").write("x")
    doc = fleet.perfetto_trace(run)
    assert doc["otherData"]["device_traces"] == [os.path.abspath(trace_dir)]
    # an EMPTY trace dir (profiler armed but never captured) is not linked
    empty = os.path.join(run, "triage-gen00000009-nan", "trace")
    os.makedirs(empty)
    assert fleet.perfetto_trace(run)["otherData"]["device_traces"] \
        == [os.path.abspath(trace_dir)]


def test_report_trace_cli_writes_trace_json(tmp_path, capsys):
    from srnn_tpu.telemetry import report

    run = str(tmp_path / "run")
    _write_events(run, [
        {"kind": "span", "span": "mega_soup.chunk", "span_id": 1,
         "process": 0, "start_s": 0.0, "seconds": 0.5, "t": 0.5}])
    assert report.main(["--trace", run]) == 0
    doc = json.load(open(os.path.join(run, "trace.json")))
    assert doc["traceEvents"]
    assert "trace:" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# no-data hardening (watch --once / report --fleet on a just-created dir)
# ---------------------------------------------------------------------------


def test_report_fleet_on_just_created_run_dir(tmp_path, capsys):
    from srnn_tpu.telemetry import report

    run = str(tmp_path / "fresh")
    os.makedirs(run)
    open(os.path.join(run, "events.jsonl"), "w").close()  # zero-length
    assert report.main(["--fleet", run]) == 0
    out = capsys.readouterr().out
    assert "no data yet" in out
    s = fleet.fleet_summary(run)
    assert s["no_data"] and s["processes"] == {}
    # report --trace names the same state instead of writing a dead file
    assert report.main(["--trace", run]) == 2
    assert not os.path.exists(os.path.join(run, "trace.json"))


def test_watch_once_on_just_created_run_dir(tmp_path, capsys):
    run = str(tmp_path / "fresh")
    os.makedirs(run)
    assert watch.main([run, "--once"]) == 0
    snap = json.loads(capsys.readouterr().out)
    assert snap["no_data"] is True
    assert snap["last_event_age_s"] is None and snap["health"] is None
    # the refresh-loop renderer takes the same snapshot without distress
    out = io.StringIO()
    watch.render(dict(snap), out)
    assert "no data yet" in out.getvalue()


# ---------------------------------------------------------------------------
# the perf-regression sentinel
# ---------------------------------------------------------------------------


def _regress():
    spec = importlib.util.spec_from_file_location(
        "regress", os.path.join(REPO_ROOT, "benchmarks", "regress.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_regress_clean_against_committed_history():
    regress = _regress()
    fresh = regress.load_result(os.path.join(REPO_ROOT, "BENCH_r07.json"))
    history = regress.load_history(os.path.join(REPO_ROOT, "BENCH_*.json"))
    verdict = regress.compare(fresh, history)
    assert verdict["ok"], verdict["regressions"]
    legs = {l["leg"]: l for l in verdict["legs"]}
    assert legs["apps_per_chip"]["verdict"] == "ok"
    # r01-r05 wrapper files unwrapped; accelerator r02 excluded from the
    # cpu family's comparison set
    assert "BENCH_r02.json" not in legs["apps_per_chip"]["history_rounds"]


def test_regress_flags_synthetic_regression():
    regress = _regress()
    fresh = regress.load_result(os.path.join(REPO_ROOT, "BENCH_r07.json"))
    fresh["value"] *= 0.6
    history = regress.load_history(os.path.join(REPO_ROOT, "BENCH_*.json"))
    verdict = regress.compare(fresh, history)
    assert not verdict["ok"]
    (finding,) = verdict["regressions"]
    assert finding["kind"] == "soup_bench_regression"
    assert finding["leg"] == "apps_per_chip" and finding["ratio"] < 0.75
    # higher-is-worse direction: a p95 blowup also flags
    fresh2 = regress.load_result(os.path.join(REPO_ROOT, "BENCH_r07.json"))
    fresh2["serve"]["load"]["p95_ms"] *= 10
    v2 = regress.compare(fresh2, history + [("BENCH_r07.json",
                                             regress.load_result(
                                                 os.path.join(
                                                     REPO_ROOT,
                                                     "BENCH_r07.json")))])
    assert any(f["leg"] == "serve_load_p95_ms" for f in v2["regressions"])


def test_regress_cli_and_micro_mode(tmp_path):
    regress = _regress()
    # CLI: clean -> 0, synthetic scale -> 1, garbage -> 2
    assert regress.main([os.path.join(REPO_ROOT, "BENCH_r07.json")]) == 0
    assert regress.main([os.path.join(REPO_ROOT, "BENCH_r07.json"),
                         "--scale", "apps_per_chip=0.6"]) == 1
    bad = tmp_path / "bad.json"
    bad.write_text("{not json")
    assert regress.main([str(bad)]) == 2
    # micro docs: warning-only, never a failing verdict
    micro = tmp_path / "micro.json"
    micro.write_text(json.dumps({"bench": "micro_dispatch", "rows": [
        {"row": "telemetry", "overhead_pct": 3.0},
        {"row": "health", "overhead_pct": 55.0}]}))
    assert regress.main([str(micro), "--json"]) == 0
    verdict = regress.compare_micro(json.loads(micro.read_text()))
    assert verdict["ok"]
    assert [w["leg"] for w in verdict["warnings"]] == ["micro.health"]


# ---------------------------------------------------------------------------
# serve: per-tenant flops attribution
# ---------------------------------------------------------------------------


def test_serve_attributes_tenant_flops(tmp_path, monkeypatch):
    from srnn_tpu.serve import ExperimentService

    monkeypatch.setenv(costs.LEDGER_PATH_ENV,
                       str(tmp_path / "ledger.jsonl"))
    costs.reset_for_tests()
    svc = ExperimentService(str(tmp_path / "svc"), max_stack=2)
    try:
        t1 = svc.submit("fixpoint_density",
                        {"seed": 0, "trials": 32, "batch": 32},
                        tenant="alpha")
        t2 = svc.submit("fixpoint_density",
                        {"seed": 1, "trials": 32, "batch": 32},
                        tenant="beta")
        svc.run_pending()
        assert svc.wait(t1, 60)["status"] == "done"
        assert svc.wait(t2, 60)["status"] == "done"
        c = svc.registry.counter("serve_tenant_flops_total")
        va = c.value(tenant="alpha", kind="fixpoint_density",
                     mode="stacked")
        vb = c.value(tenant="beta", kind="fixpoint_density",
                     mode="stacked")
        # CPU reports HLO flops; the stacked program's cost splits evenly
        assert va > 0 and va == vb
        # and the service's stats snapshot exposes the series
        assert any(k.startswith("srnn_serve_tenant_flops_total")
                   for k in svc.stats()["metrics"])
    finally:
        svc.close()
