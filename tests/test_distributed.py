"""The distributed runtime tier (srnn_tpu/distributed/): bootstrap,
process-0-gated host I/O, the multislice mesh builder as the LIVE path,
host-loss chaos + classification, and the multi-process CPU launcher.

Parity oracles (DESIGN §16): a multi-process run over D total devices is
bitwise-equal to the single-host SHARDED run over the same D (the
sharded suite's own oracle then connects popmajor mega_soup all the way
to the unsharded single-device run); a chaos-injected slice loss either
re-ramps in-process (single-process multislice) or exits
``EXIT_HOST_LOST`` for the launcher tier to re-ramp — both ending
bitwise-equal to the uninterrupted run.
"""

import glob
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from srnn_tpu.distributed import (CoordinatorTimeout, HostLost, bootstrap,
                                  launch)
from srnn_tpu.distributed.hostio import WorkerLog
from srnn_tpu.experiment import restore_checkpoint
from srnn_tpu.resilience import (EXIT_HOST_LOST, EXIT_RECOVERED, HOST_LOSS,
                                 BackoffPolicy, ChaosMonkey, Supervisor,
                                 classify_fault, exit_code_for_report,
                                 parse_schedule, supervisor)
from srnn_tpu.setups import REGISTRY

FAST = ["--backoff-base-s", "0.01", "--backoff-max-s", "0.05"]

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _worker_env(**extra):
    """Env for launcher subprocesses: CPU-pinned, tunnel-free, sharing
    the suite's persistent compile cache — and ONE device per worker
    (the suite's 8-virtual-device forcing is for in-process sharding
    tests; inheriting it would hand every worker 8 devices and compile a
    16-way SPMD program per process on this small host)."""
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO_ROOT
    env["SRNN_SETUPS_PLATFORM"] = "cpu"
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    env.update(extra)
    return env


# ---------------------------------------------------------------------------
# exit-code vocabulary stays mirrored (the launcher must not import the
# jax-importing resilience layer, so it spells the codes as literals)
# ---------------------------------------------------------------------------


def test_launcher_exit_codes_mirror_supervisor():
    assert launch.EXIT_HOST_LOST == supervisor.EXIT_HOST_LOST == 71
    assert launch.EXIT_RECOVERED == supervisor.EXIT_RECOVERED == 3
    assert supervisor.EXIT_CODE_NAMES[EXIT_HOST_LOST] == "host-lost"


# ---------------------------------------------------------------------------
# bootstrap resolution (no actual jax.distributed bring-up)
# ---------------------------------------------------------------------------


def test_bootstrap_resolve_env_and_flag_priority(monkeypatch):
    class A:
        dist_coordinator = None
        dist_processes = None
        dist_process_id = None

    monkeypatch.delenv(bootstrap.COORD_ENV, raising=False)
    assert bootstrap._resolve(A()) == (None, None, None)
    monkeypatch.setenv(bootstrap.COORD_ENV, "127.0.0.1:9999")
    monkeypatch.setenv(bootstrap.PROCS_ENV, "2")
    monkeypatch.setenv(bootstrap.PID_ENV, "1")
    assert bootstrap._resolve(A()) == ("127.0.0.1:9999", 2, 1)
    # explicit flags win over env
    flagged = A()
    flagged.dist_coordinator = "10.0.0.1:1234"
    flagged.dist_processes = 4
    flagged.dist_process_id = 3
    assert bootstrap._resolve(flagged) == ("10.0.0.1:1234", 4, 3)


def test_ensure_initialized_rejects_partial_spec(monkeypatch):
    """A partial --dist-* spec must fail loudly, not run solo while the
    correctly-configured peers block on a coordinator that never forms."""

    class A:
        dist_coordinator = "10.0.0.1:1234"
        dist_processes = None
        dist_process_id = 1

    monkeypatch.delenv(bootstrap.COORD_ENV, raising=False)
    monkeypatch.setattr(bootstrap, "_CONTEXT", None)
    with pytest.raises(SystemExit, match="all three"):
        bootstrap.ensure_initialized(A())
    # a 1-process spec (the launcher's re-ramp floor) is just a solo run
    class Solo:
        dist_coordinator = "127.0.0.1:1"
        dist_processes = 1
        dist_process_id = 0

    monkeypatch.setattr(bootstrap, "_CONTEXT", None)
    assert not bootstrap.ensure_initialized(Solo()).active
    monkeypatch.setattr(bootstrap, "_CONTEXT", None)


def test_ensure_initialized_inactive_for_plain_runs(monkeypatch):
    monkeypatch.delenv(bootstrap.COORD_ENV, raising=False)
    monkeypatch.setattr(bootstrap, "_CONTEXT", None)
    ctx = bootstrap.ensure_initialized(None)
    assert not ctx.active and ctx.primary
    # idempotent: the second call returns the same context
    assert bootstrap.ensure_initialized(None) is ctx
    monkeypatch.setattr(bootstrap, "_CONTEXT", None)


# ---------------------------------------------------------------------------
# WorkerLog: the non-primary Experiment shim
# ---------------------------------------------------------------------------


def test_worker_log_heartbeat_file_and_noop_saves(tmp_path, capsys):
    with WorkerLog(str(tmp_path), 1) as wl:
        assert wl.dir == str(tmp_path)
        wl.log("hello", generation=4)
        wl.event(_fsync=True, kind="heartbeat", stage="mega_soup@p1/2")
        assert wl.save(foo=1) == {}
    rows = [json.loads(line)
            for line in open(tmp_path / "events-p1.jsonl")]
    assert [r.get("kind") for r in rows] == [None, "heartbeat"]
    assert all(r["process"] == 1 for r in rows)
    assert rows[1]["stage"] == "mega_soup@p1/2"
    assert "[p1] hello" in capsys.readouterr().err
    # no primary artifacts were created
    assert not (tmp_path / "events.jsonl").exists()
    assert not (tmp_path / "log.txt").exists()


# ---------------------------------------------------------------------------
# slice grouping + the divisor-aware re-ramp ladder (the satellites'
# edge cases: ragged survivors, single intact group, modal ties, the
# 1M-on-3-survivors snap interacting with the slice axis)
# ---------------------------------------------------------------------------


class _Dev:
    def __init__(self, i, s=None, p=0):
        self.id = i
        if s is not None:
            self.slice_index = s
        self.process_index = p


def test_slice_groups_forced_split_and_real_topology_wins(monkeypatch):
    from srnn_tpu.parallel import slice_groups

    flat = [_Dev(i) for i in range(8)]
    assert len(slice_groups(flat)) == 1
    assert [len(g) for g in slice_groups(flat, force_slices=2)] == [4, 4]
    monkeypatch.setenv("SRNN_FORCE_SLICES", "4")
    assert [len(g) for g in slice_groups(flat)] == [2, 2, 2, 2]
    # a non-dividing override is ignored, not ragged
    assert len(slice_groups(flat, force_slices=3)) == 1
    # a REAL topology (distinct slice indices) wins over the override
    real = [_Dev(i, s=i // 4) for i in range(8)]
    assert [len(g) for g in slice_groups(real)] == [4, 4]
    monkeypatch.delenv("SRNN_FORCE_SLICES")


def test_reramp_mesh_divisor_snap_drops_slices_first():
    from srnn_tpu.parallel import reramp_soup_mesh

    # 3 whole slices of 4: 1M % 12 != 0 -> drop one slice -> (2, 4)
    devs = [_Dev(i, s=i // 4) for i in range(12)]
    m = reramp_soup_mesh(devs, shard_sizes=(1_000_000,))
    assert m.axis_names == ("slices", "soup") and m.devices.shape == (2, 4)
    # without the size constraint all three slices ride
    assert reramp_soup_mesh(devs).devices.shape == (3, 4)


def test_reramp_mesh_ragged_survivors_fall_back_to_largest_group():
    from srnn_tpu.parallel import reramp_soup_mesh

    # ragged: slices of 4, 3, 2 -> single intact group of 4, 1-D
    devs = [_Dev(i, s=0) for i in range(4)] \
        + [_Dev(10 + i, s=1) for i in range(3)] \
        + [_Dev(20 + i, s=2) for i in range(2)]
    m = reramp_soup_mesh(devs)
    assert m.axis_names == ("soup",) and m.devices.shape == (4,)


def test_reramp_mesh_modal_tie_prefers_larger_slice_size():
    from srnn_tpu.parallel import reramp_soup_mesh

    # tie: two slices of 2 and two of 4 -> modal resolves to 4 -> (2, 4)
    devs = [_Dev(i, s=i // 2) for i in range(4)] \
        + [_Dev(10 + i, s=10 + i // 4) for i in range(8)]
    m = reramp_soup_mesh(devs)
    assert m.axis_names == ("slices", "soup") and m.devices.shape == (2, 4)


def test_reramp_mesh_one_d_divisor_snap_1m_on_3_survivors():
    from srnn_tpu.parallel import reramp_soup_mesh

    devs = [_Dev(i, s=0) for i in range(3)]
    m = reramp_soup_mesh(devs, shard_sizes=(1_000_000,))
    # 1M % 3 != 0 -> snap DOWN to 2 (the mesh_devices snap, slice-aware)
    assert m.axis_names == ("soup",) and m.devices.shape == (2,)
    # divisor snap honors EVERY published shard size
    m = reramp_soup_mesh([_Dev(i, s=0) for i in range(6)],
                         shard_sizes=(1_000_000, 300_000))
    assert m.devices.shape == (5,)  # 6 fails 1M; 5 divides both


# ---------------------------------------------------------------------------
# classification: the new host-loss faults
# ---------------------------------------------------------------------------


def test_classify_host_faults():
    from jaxlib.xla_extension import XlaRuntimeError

    assert classify_fault(HostLost("slice 1 gone")) == HOST_LOSS
    assert classify_fault(CoordinatorTimeout("no coordinator")) == HOST_LOSS
    # a cross-process collective dying because its peer went away wraps
    # in FAILED_PRECONDITION — it must classify host_loss, not fatal
    gloo = XlaRuntimeError(
        "FAILED_PRECONDITION: Buffer Definition Event: Gloo all-reduce "
        "failed: [external/gloo] Connection closed by peer [127.0.0.1]")
    assert classify_fault(gloo) == HOST_LOSS
    # a genuine deterministic FAILED_PRECONDITION stays fatal
    assert classify_fault(
        XlaRuntimeError("FAILED_PRECONDITION: bad program")) == "fatal"


def test_chaos_parse_validates_new_kinds():
    evs = parse_schedule("host_loss@4:1,coordinator_timeout@2")
    assert [(e.kind, e.at) for e in evs] == [("coordinator_timeout", 2),
                                             ("host_loss", 4)]
    with pytest.raises(ValueError, match="takes no argument"):
        parse_schedule("coordinator_timeout@2:5")
    with pytest.raises(ValueError, match="integer"):
        parse_schedule("host_loss@2:1.5")


def test_chaos_host_loss_rejects_unfirable_specs(monkeypatch):
    """Fire-time strictness (the group count is unknowable at parse
    time): an out-of-range ordinal, or a topology with nothing left to
    survive, fails loudly instead of clamping to a different drill."""
    monkeypatch.setenv("SRNN_FORCE_SLICES", "2")
    monkey = ChaosMonkey(parse_schedule("host_loss@1:7"))
    with pytest.raises(ValueError, match="out of range"):
        monkey.chunk_start(1)
    monkeypatch.delenv("SRNN_FORCE_SLICES")
    # a flat (single-group) topology has nothing left to survive
    flat = ChaosMonkey(parse_schedule("host_loss@1"))
    with pytest.raises(ValueError, match="no surviving slice"):
        flat.chunk_start(1)


def test_chaos_host_loss_forces_survivor_list(monkeypatch):
    import jax

    monkeypatch.setenv("SRNN_FORCE_SLICES", "2")
    monkey = ChaosMonkey(parse_schedule("host_loss@3:0"))
    with pytest.raises(HostLost, match="slice group 0 lost"):
        monkey.chunk_start(3)
    survivors = monkey.take_forced_survivors()
    n = len(jax.devices())
    assert [d.id for d in survivors] == [d.id for d in jax.devices()[n // 2:]]
    # consumed: a later probe sees the real topology
    assert monkey.take_forced_survivors() is None
    # fire-once
    monkey.chunk_start(5)


def test_supervisor_multiprocess_host_loss_exits_71(monkeypatch):
    # simulate being one process of a jax.distributed job
    monkeypatch.setattr(bootstrap, "_CONTEXT",
                        bootstrap.DistContext(active=True, process_id=1,
                                              num_processes=2))
    sup = Supervisor(BackoffPolicy(max_restarts=3, base_s=0.0),
                     log=lambda m: None)

    def run_once(args, ctx):
        raise HostLost("peer gone")

    with pytest.raises(SystemExit) as e:
        sup.run(run_once, object())
    assert e.value.code == EXIT_HOST_LOST
    assert supervisor.LAST_REPORT["outcome"] == "host-lost"
    monkeypatch.setattr(bootstrap, "_CONTEXT", None)


# ---------------------------------------------------------------------------
# launcher mechanics (no jax in these paths)
# ---------------------------------------------------------------------------


def test_launcher_strip_flag_and_propagate():
    argv = ["mega_soup", "--chaos", "host_loss@4", "--smoke",
            "--chaos=stall@2", "--resume", "old", "--sharded"]
    out = launch._strip_flag(argv, "--chaos")
    out = launch._strip_flag(out, "--resume")
    assert out == ["mega_soup", "--smoke", "--sharded"]
    assert launch._propagate([0, 0], set()) == 0
    assert launch._propagate([0, 3], set()) == 3
    assert launch._propagate([1, EXIT_HOST_LOST], set()) == EXIT_HOST_LOST
    assert launch._propagate([0, -9], set()) == 137
    # launcher-reaped workers' codes are consequences, not causes
    assert launch._propagate([75, -15], {1}) == 75
    assert launch._propagate([0, -15], {1}) == 1


# ---------------------------------------------------------------------------
# e2e: single-process multislice — reramp_soup_mesh as the LIVE path,
# in-process slice-loss recovery, exit-3 mapping, bitwise oracle
# ---------------------------------------------------------------------------


def test_multislice_host_loss_reramps_in_process_bitwise(tmp_path,
                                                        monkeypatch):
    """The acceptance drill, single-process spelling: a forced 2-slice
    CPU topology runs mega_soup on a (slices, soup) mesh; chaos kills
    slice group 1 mid-run; the supervisor re-ramps onto the surviving
    slice via reramp_soup_mesh and completes — CLI exit 3, final state
    bitwise-equal to the uninterrupted run."""
    oracle = REGISTRY["mega_soup"](
        ["--smoke", "--seed", "11", "--root", str(tmp_path / "oracle")])
    want = restore_checkpoint(os.path.join(oracle, "ckpt-gen00000006"))

    monkeypatch.setenv("SRNN_FORCE_SLICES", "2")
    d = REGISTRY["mega_soup"](
        ["--smoke", "--seed", "11", "--sharded", "--root",
         str(tmp_path / "loss"), "--chaos", "host_loss@4:1"] + FAST)
    monkeypatch.delenv("SRNN_FORCE_SLICES")
    got = restore_checkpoint(os.path.join(d, "ckpt-gen00000006"))
    np.testing.assert_array_equal(np.asarray(want.weights),
                                  np.asarray(got.weights))
    np.testing.assert_array_equal(np.asarray(want.uids),
                                  np.asarray(got.uids))
    assert exit_code_for_report(supervisor.LAST_REPORT) == EXIT_RECOVERED
    assert supervisor.LAST_REPORT["reramps"] == 1
    log = open(os.path.join(d, "log.txt")).read()
    assert "restart 1 after host_loss fault" in log
    prom = open(os.path.join(d, "metrics.prom")).read()
    assert "srnn_soup_distributed_host_losses_total 1" in prom
    assert "srnn_soup_distributed_slices" in prom


# ---------------------------------------------------------------------------
# e2e: the multi-process CPU launcher
# ---------------------------------------------------------------------------


def test_two_process_launcher_bitwise_parity(tmp_path):
    """The tentpole oracle: a 2-process CPU-mesh mega_soup run is
    bitwise-equal (weights/uids/PRNG key/lineage) to the single-process
    run of the same config, with every run artifact written exactly once
    (process-0 gating) and per-process heartbeats present."""
    oracle = REGISTRY["mega_soup"](
        ["--smoke", "--seed", "13", "--root", str(tmp_path / "solo"),
         "--lineage"])

    proc = subprocess.run(
        [sys.executable, "-m", "srnn_tpu.distributed.launch",
         "--processes", "2", "--",
         "mega_soup", "--smoke", "--seed", "13", "--sharded", "--lineage",
         "--root", str(tmp_path / "dist")],
        env=_worker_env(), capture_output=True, text=True, timeout=540,
        cwd=REPO_ROOT)
    assert proc.returncode == 0, proc.stdout[-3000:] + proc.stderr[-2000:]
    dist_dir = glob.glob(str(tmp_path / "dist" / "exp-*"))[0]

    import jax

    want = restore_checkpoint(os.path.join(oracle, "ckpt-gen00000006"))
    got = restore_checkpoint(os.path.join(dist_dir, "ckpt-gen00000006"))
    np.testing.assert_array_equal(np.asarray(want.weights),
                                  np.asarray(got.weights))
    np.testing.assert_array_equal(np.asarray(want.uids),
                                  np.asarray(got.uids))
    np.testing.assert_array_equal(
        np.asarray(jax.random.key_data(want.key)),
        np.asarray(jax.random.key_data(got.key)))

    # lineage: same windows, same edge SETS (sharded windows concatenate
    # per-shard buffers, so within-window order is shard-interleaved —
    # the same documented property the single-host sharded path has)
    la = [json.loads(line) for line in open(os.path.join(oracle,
                                                         "lineage.jsonl"))]
    lb = [json.loads(line) for line in open(os.path.join(dist_dir,
                                                         "lineage.jsonl"))]
    wa = [r for r in la if r.get("kind") == "window"]
    wb = [r for r in lb if r.get("kind") == "window"]
    assert len(wa) == len(wb) > 0
    for ra, rb in zip(wa, wb):
        assert sorted(map(tuple, ra["edges"])) == sorted(map(tuple,
                                                             rb["edges"]))
        for k in ("fixpoints", "births_attack", "births_respawn",
                  "gen_start", "gen_end", "next_pid"):
            assert ra[k] == rb[k], k

    # process-0 I/O contract: exactly one of each run artifact, plus the
    # worker's own heartbeat stream
    for name in ("metrics.prom", "lineage.jsonl", "log.txt",
                 "events.jsonl"):
        assert os.path.exists(os.path.join(dist_dir, name))
    assert not glob.glob(os.path.join(dist_dir, "metrics*.prom.p*"))
    assert os.path.exists(os.path.join(dist_dir, "events-p1.jsonl"))
    hb = [json.loads(line) for line in open(os.path.join(
        dist_dir, "events-p1.jsonl"))]
    assert any(r.get("stage") == "mega_soup@p1/2" for r in hb)


def test_launcher_propagates_killed_worker_exit_code(tmp_path):
    """A SIGKILLed worker must surface as 128+9 from the launcher, not
    hang it (peers are reaped after the grace window)."""
    proc = subprocess.run(
        [sys.executable, "-m", "srnn_tpu.distributed.launch",
         "--processes", "2", "--grace-s", "5", "--max-reramps", "0", "--",
         "mega_soup", "--smoke", "--seed", "17", "--sharded",
         "--root", str(tmp_path / "kill"), "--chaos", "sigkill@2"],
        env=_worker_env(), capture_output=True, text=True, timeout=540,
        cwd=REPO_ROOT)
    assert proc.returncode == 137, proc.stdout[-3000:] + proc.stderr[-2000:]


def test_one_sided_io_fault_escalates_to_launcher(tmp_path):
    """A retryable fault on ONE process of a multi-process run must NOT
    restart in-process (a one-sided restart desynchronizes the
    collective schedule and wedges the mesh): the faulting process exits
    71, its peer's broken collectives classify host_loss too, and the
    launcher relaunches — completing recovered (exit 3)."""
    proc = subprocess.run(
        [sys.executable, "-m", "srnn_tpu.distributed.launch",
         "--processes", "2", "--grace-s", "15", "--",
         "mega_soup", "--smoke", "--seed", "31", "--sharded",
         "--root", str(tmp_path / "io"), "--chaos", "writer@2"] + FAST,
        env=_worker_env(), capture_output=True, text=True, timeout=540,
        cwd=REPO_ROOT)
    out = proc.stdout + proc.stderr
    assert proc.returncode == EXIT_RECOVERED, out[-3000:]
    assert "in-process restart would desync the mesh" in out
    assert "supervisor: restart" not in out  # never restarted in-process


@pytest.mark.slow
def test_launcher_host_loss_reramp_completes_recovered(tmp_path):
    """The full launcher-tier re-ramp: chaos host loss mid-run -> every
    worker exits 71 -> relaunch with one fewer process resuming the run
    dir -> completion -> launcher exits 3 (recovered), bitwise-equal to
    the uninterrupted run."""
    oracle = REGISTRY["mega_soup"](
        ["--smoke", "--seed", "19", "--root", str(tmp_path / "solo")])
    proc = subprocess.run(
        [sys.executable, "-m", "srnn_tpu.distributed.launch",
         "--processes", "2", "--grace-s", "10", "--",
         "mega_soup", "--smoke", "--seed", "19", "--sharded",
         "--root", str(tmp_path / "dist"), "--chaos", "host_loss@4"],
        env=_worker_env(), capture_output=True, text=True, timeout=540,
        cwd=REPO_ROOT)
    assert proc.returncode == EXIT_RECOVERED, \
        proc.stdout[-3000:] + proc.stderr[-2000:]
    assert "re-ramp 1/" in proc.stderr + proc.stdout
    dist_dir = glob.glob(str(tmp_path / "dist" / "exp-*"))[0]
    want = restore_checkpoint(os.path.join(oracle, "ckpt-gen00000006"))
    got = restore_checkpoint(os.path.join(dist_dir, "ckpt-gen00000006"))
    np.testing.assert_array_equal(np.asarray(want.weights),
                                  np.asarray(got.weights))
    np.testing.assert_array_equal(np.asarray(want.uids),
                                  np.asarray(got.uids))
