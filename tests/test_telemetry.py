"""Telemetry subsystem: in-scan metrics carry (parity + recount), sinks
round-trip (events.jsonl / Prometheus textfile / report CLI), heartbeats,
spans, and the mega-run wiring (one dispatch per flush interval)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from srnn_tpu import Topology
from srnn_tpu.experiment import Experiment
from srnn_tpu.soup import ACTION_NAMES, SoupConfig, evolve, seed
from srnn_tpu import telemetry
from srnn_tpu.telemetry import report


def _full_cfg(layout):
    return SoupConfig(topo=Topology("weightwise"), size=12,
                      attacking_rate=0.3, learn_from_rate=0.2,
                      learn_from_severity=1, train=1,
                      remove_divergent=True, remove_zero=True, layout=layout)


# ---------------------------------------------------------------------------
# device-side metrics carry
# ---------------------------------------------------------------------------


def test_action_code_layout_in_sync():
    assert len(ACTION_NAMES) == telemetry.N_ACTIONS


@pytest.mark.parametrize("layout", ["rowmajor", "popmajor"])
def test_metrics_carry_parity_and_recount(layout):
    """Metered evolution is bit-identical to unmetered, and the carry's
    counters match a NumPy recount of the recorded SoupEvents."""
    cfg = _full_cfg(layout)
    st = seed(cfg, jax.random.key(3))
    plain = evolve(cfg, st, generations=4)
    metered, m = evolve(cfg, st, generations=4, metrics=True)
    np.testing.assert_array_equal(np.asarray(plain.weights),
                                  np.asarray(metered.weights))
    np.testing.assert_array_equal(np.asarray(plain.uids),
                                  np.asarray(metered.uids))

    _final, (ev, _w, _u) = evolve(cfg, st, generations=4, record=True)
    recount = np.bincount(np.asarray(ev.action).reshape(-1),
                          minlength=telemetry.N_ACTIONS)
    np.testing.assert_array_equal(recount, np.asarray(m.actions))
    assert int(m.generations) == 4
    np.testing.assert_allclose(float(m.loss_sum),
                               float(np.asarray(ev.loss).sum()), rtol=1e-5)
    # record + metrics compose
    _f, _recs, m2 = evolve(cfg, st, generations=4, record=True, metrics=True)
    np.testing.assert_array_equal(np.asarray(m2.actions), np.asarray(m.actions))


def test_multi_metrics_parity_and_recount():
    from srnn_tpu.multisoup import (MultiSoupConfig, evolve_multi,
                                    evolve_multi_step, seed_multi)

    mc = MultiSoupConfig(
        topos=(Topology("weightwise"), Topology("aggregating", aggregates=4)),
        sizes=(6, 6), attacking_rate=0.4, learn_from_rate=0.3,
        learn_from_severity=1, train=1, remove_divergent=True,
        remove_zero=True)
    st = seed_multi(mc, jax.random.key(0))
    plain = evolve_multi(mc, st, generations=3)
    metered, ms = evolve_multi(mc, st, generations=3, metrics=True)
    for wa, wb in zip(plain.weights, metered.weights):
        np.testing.assert_array_equal(np.asarray(wa), np.asarray(wb))
    # recount from the step-by-step event stream (same PRNG path)
    s, rec = st, [np.zeros(telemetry.N_ACTIONS, int) for _ in mc.topos]
    for _ in range(3):
        s, ev = evolve_multi_step(mc, s)
        for t in range(len(mc.topos)):
            rec[t] += np.bincount(np.asarray(ev.action[t]),
                                  minlength=telemetry.N_ACTIONS)
    for t in range(len(mc.topos)):
        np.testing.assert_array_equal(rec[t], np.asarray(ms[t].actions))
        assert int(ms[t].generations) == 3


def test_sharded_metrics_match_unsharded(mesh):
    """The metered sharded scan psums per-shard carries into the same
    global counters the single-device carry produces; integer state stays
    bitwise, weights to the suite's usual fusion tolerance."""
    from srnn_tpu.parallel import make_sharded_state
    from srnn_tpu.parallel.sharded_soup import sharded_evolve

    cfg = SoupConfig(topo=Topology("weightwise"), size=16,
                     attacking_rate=0.4, remove_divergent=True,
                     remove_zero=True, layout="popmajor")
    sst = make_sharded_state(cfg, mesh, jax.random.key(1))
    sh, m_sh = sharded_evolve(cfg, mesh, sst, generations=4, metrics=True)
    un, m_un = evolve(cfg, seed(cfg, jax.random.key(1)), generations=4,
                      metrics=True)
    np.testing.assert_array_equal(np.asarray(m_un.actions),
                                  np.asarray(m_sh.actions))
    assert int(m_sh.generations) == int(m_un.generations) == 4
    np.testing.assert_array_equal(np.asarray(un.uids), np.asarray(sh.uids))
    np.testing.assert_allclose(np.asarray(un.weights),
                               np.asarray(sh.weights), rtol=0, atol=2e-6)


# ---------------------------------------------------------------------------
# host-side registry + sinks
# ---------------------------------------------------------------------------


def test_registry_sinks_roundtrip(tmp_path):
    reg = telemetry.MetricsRegistry()
    reg.counter("soup_attacks_total", help="attacks").inc(7)
    reg.counter("soup_attacks_total").inc(3)
    reg.gauge("gens_per_sec", unit="1/s").set(12.5, stage="test")
    reg.histogram("span_seconds").observe(0.02, span="chunk")
    rows = reg.rows()
    assert rows["srnn_soup_attacks_total"] == 10
    assert rows['srnn_gens_per_sec{stage="test"}'] == 12.5
    assert rows['srnn_span_seconds_count{span="chunk"}'] == 1

    # kind-mismatched re-registration is an error, not silent data loss
    with pytest.raises(TypeError, match="already registered"):
        reg.gauge("soup_attacks_total")

    # Prometheus textfile exposition
    prom = tmp_path / "metrics.prom"
    reg.write_textfile(str(prom))
    text = prom.read_text()
    assert "# TYPE srnn_soup_attacks_total counter" in text
    assert "srnn_soup_attacks_total 10" in text
    assert 'srnn_span_seconds_bucket{span="chunk",le="+Inf"} 1' in text

    # events.jsonl sink through the Experiment channel
    with Experiment("telemetry", root=str(tmp_path)) as exp:
        reg.flush_events(exp)
        run_dir = exp.dir
    recs = [json.loads(l) for l in
            open(os.path.join(run_dir, "events.jsonl"))]
    mrows = [r for r in recs if r.get("kind") == "metrics"]
    assert mrows and mrows[-1]["metrics"]["srnn_soup_attacks_total"] == 10


def test_heartbeat_rows_and_report(tmp_path, capsys):
    reg = telemetry.MetricsRegistry()
    with Experiment("hb", root=str(tmp_path)) as exp:
        hb = telemetry.Heartbeat(exp, stage="unit",
                                 total_generations=10, registry=reg)
        hb.beat(generation=2, gens_per_sec=5.0)
        hb.beat(generation=4, gens_per_sec=6.0, extra_field=1)
        with telemetry.span("unit.block", registry=reg, exp=exp) as s:
            s.sync(jnp.ones(4).sum())
        reg.flush_events(exp)
        run_dir = exp.dir
    recs = [json.loads(l) for l in
            open(os.path.join(run_dir, "events.jsonl"))]
    beats = [r for r in recs if r.get("kind") == "heartbeat"]
    assert [b["generation"] for b in beats] == [2, 4]
    assert beats[1]["beat"] == 1 and beats[1]["since_last_s"] >= 0
    assert beats[0]["total_generations"] == 10
    assert "rss_mb" in beats[0]  # linux /proc is available in CI
    spans = [r for r in recs if r.get("kind") == "span"]
    assert spans and spans[0]["span"] == "unit.block" \
        and spans[0]["seconds"] > 0
    assert s.seconds is not None and s.seconds > 0
    assert reg.histogram("span_seconds").count(span="unit.block") == 1

    # the report CLI renders the trail
    assert report.main([run_dir]) == 0
    out = capsys.readouterr().out
    assert "unit: 2 beats, last at gen 4/10" in out
    assert "unit.block" in out and "srnn_gens_per_sec" in out
    # machine-readable summary agrees
    s = report.summarize(run_dir)
    assert s["heartbeats"]["unit"]["beats"] == 2
    assert s["metrics_flushes"] == 1
    assert report.main([str(tmp_path / "nope")]) == 2


def test_annotate_is_trace_safe():
    @telemetry.annotate("test.annotated")
    def f(x):
        return x * 2

    assert int(jax.jit(f)(jnp.int32(4))) == 8


# ---------------------------------------------------------------------------
# capture + mega-run wiring
# ---------------------------------------------------------------------------


def test_capture_meters_every_generation(tmp_path):
    from srnn_tpu.utils import TrajStore, evolve_captured

    cfg = SoupConfig(topo=Topology("weightwise"), size=8, attacking_rate=0.5,
                     remove_divergent=True, remove_zero=True)
    st = seed(cfg, jax.random.key(2))
    reg = telemetry.MetricsRegistry()
    store = TrajStore(str(tmp_path / "s.traj"), n_particles=8,
                      n_weights=cfg.topo.num_weights)
    try:
        evolve_captured(cfg, st, generations=4, store=store, every=2,
                        registry=reg)
    finally:
        store.close()
    rows = reg.rows()
    # every generation counted, not just the captured stride
    assert rows["srnn_soup_generations_total"] == 4
    assert rows["srnn_soup_particle_generations_total"] == 32
    # recount the same evolution's events for the attack total
    _f, (ev, _w, _u) = evolve(cfg, st, generations=4, record=True)
    attacks = int((np.asarray(ev.action)
                   == ACTION_NAMES.index("attacking")).sum())
    assert rows["srnn_soup_attacks_total"] == attacks


def test_mega_soup_one_dispatch_per_flush(tmp_path, monkeypatch, capsys):
    """The metered mega-run loop dispatches exactly ONE executable per
    flush interval (checkpoint chunk) — metrics accumulate in-scan, not
    via per-generation host syncs — and its run dir carries the full
    telemetry trail (heartbeats + metrics rows + metrics.prom) that the
    report CLI renders."""
    import srnn_tpu.setups.mega_soup as ms

    calls = []
    orig = ms.evolve_donated

    def counting(cfg, state, **kw):
        calls.append(kw)
        return orig(cfg, state, **kw)

    monkeypatch.setattr(ms, "evolve_donated", counting)
    run_dir = ms.run(ms.build_parser().parse_args(
        ["--smoke", "--size", "16", "--generations", "4",
         "--checkpoint-every", "2", "--root", str(tmp_path)]))
    assert len(calls) == 2, "one dispatch per 2-generation flush interval"
    assert all(kw.get("metrics") for kw in calls)

    recs = [json.loads(l) for l in
            open(os.path.join(run_dir, "events.jsonl"))]
    kinds = {r.get("kind") for r in recs}
    assert {"heartbeat", "metrics"} <= kinds
    last_metrics = [r for r in recs if r.get("kind") == "metrics"][-1]
    assert last_metrics["metrics"]["srnn_soup_generations_total"] == 4
    hb = [r for r in recs if r.get("kind") == "heartbeat"][-1]
    assert hb["generation"] == 4 and hb["stage"] == "mega_soup"
    assert os.path.exists(os.path.join(run_dir, "metrics.prom"))

    capsys.readouterr()
    assert report.main([run_dir]) == 0
    out = capsys.readouterr().out
    assert "mega_soup" in out and "srnn_soup_generations_total = 4" in out


# ---------------------------------------------------------------------------
# satellites
# ---------------------------------------------------------------------------


def test_timed_honors_warmup_zero():
    from srnn_tpu.utils import timed

    ncalls = []

    def fn():
        ncalls.append(1)
        return jnp.float32(1.0)

    stats = timed(fn, iters=3, warmup=0)
    assert len(ncalls) == 3 and stats["iters"] == 3
    ncalls.clear()
    timed(fn, iters=2, warmup=2)
    assert len(ncalls) == 4


def test_aot_compile_records_runtime_metrics():
    from srnn_tpu.telemetry.metrics import RUNTIME
    from srnn_tpu.utils import aot

    cfg = SoupConfig(topo=Topology("weightwise"), size=4)
    from srnn_tpu.soup import evolve_step

    aot.clear_executable_cache()
    name = "telemetry.test.entry"
    before = RUNTIME.counter("aot_compiles_total").value(entry=name)
    aot.aot_compile(name, evolve_step, (cfg, aot.abstract_soup_state(cfg)))
    assert RUNTIME.counter("aot_compiles_total").value(entry=name) \
        == before + 1
    hits_before = RUNTIME.counter("aot_memo_hits_total").value(entry=name)
    aot.aot_compile(name, evolve_step, (cfg, aot.abstract_soup_state(cfg)))
    assert RUNTIME.counter("aot_memo_hits_total").value(entry=name) \
        == hits_before + 1
