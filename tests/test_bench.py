"""bench.py robustness: the driver perf gate must survive a wedged backend.

Round-3 post-mortem (VERDICT r3): the tunneled platform hung at init, the
in-process watchdog fired, and BENCH_r03.json carried value=0 — an
in-process retry provably cannot recover a hang.  bench.py now runs each
stage in a fresh subprocess with its own timeout; these tests simulate a
hung child (SRNN_BENCH_TEST_HANG) and assert the parent still emits ONE
well-formed JSON line carrying the best measurement obtained so far.

Children are pinned to host CPU via SRNN_BENCH_PLATFORM (jax.config-level:
the axon sitecustomize overrides the JAX_PLATFORMS env var) so the suite
never dials the real tunnel.
"""

import json
import os
import subprocess
import sys

import pytest

BENCH = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                     "bench.py")


def _run_bench(extra_env, timeout=600):
    env = dict(os.environ)
    # children must never touch the real (tunneled) backend from the test
    # suite; this pin survives the axon sitecustomize (config-level)
    env["SRNN_BENCH_PLATFORM"] = "cpu"
    # the serve and multihost legs are their own multi-minute stages
    # (covered by tests/test_serve.py and tests/test_distributed.py at
    # smoke scale); these e2es drill the wedge/rescue machinery against
    # tiny pinned deadlines
    env.setdefault("SRNN_BENCH_SERVE_TIMEOUT_S", "0")
    env.setdefault("SRNN_BENCH_MULTIHOST_TIMEOUT_S", "0")
    # throwaway rounds must not pollute the repo-root BENCH_archive
    # sidecar (the archive hook's documented opt-out)
    env.setdefault("SRNN_BENCH_ARCHIVE", "0")
    env.update(extra_env)
    proc = subprocess.run([sys.executable, BENCH], stdout=subprocess.PIPE,
                          stderr=subprocess.PIPE, timeout=timeout, env=env)
    lines = [l for l in proc.stdout.decode().splitlines() if l.strip()]
    assert len(lines) == 1, f"expected exactly one JSON line, got {lines!r}"
    return proc.returncode, json.loads(lines[0])


@pytest.mark.slow
def test_hung_full_stage_still_reports_ramp_number():
    rc, out = _run_bench({
        "SRNN_BENCH_TEST_HANG": "full",      # full stage wedges forever
        "SRNN_BENCH_FULL_TIMEOUT_S": "6",
        "SRNN_BENCH_RAMP_TIMEOUT_S": "240",
        "SRNN_BENCH_DEADLINE_S": "500",
    })
    assert rc == 0  # fail-soft: the gate line is the product, not the rc
    assert out["value"] > 0, "ramp measurement must survive the full-stage hang"
    assert out["stage"] == "ramp-only"
    assert "timeout" in out["error"]
    assert out["device_count"] >= 1
    assert out["vs_baseline"] == round(out["value"] / (10_000_000 / 32), 2)


def test_hung_ramp_recovers_via_full_stage():
    # ramp wedges on every attempt; the full stage (reduced CPU workload)
    # must still land a real number and clear the ramp-only marker
    rc, out = _run_bench({
        "SRNN_BENCH_TEST_HANG": "ramp",
        "SRNN_BENCH_RAMP_TIMEOUT_S": "4",
        "SRNN_BENCH_FULL_TIMEOUT_S": "240",
        "SRNN_BENCH_DEADLINE_S": "500",
    })
    assert rc == 0
    assert out["value"] > 0
    assert "stage" not in out
    assert "timeout" in out["error"]
    assert out["backend"] == "cpu-forced"


@pytest.mark.slow
def test_persistent_wedge_reserves_rescue_budget():
    # production-shaped proportions: stage timeouts large relative to the
    # deadline.  The rescue reserve (RESCUE_RESERVE_S=330) must clamp the
    # accelerator attempts so the rescue leg still has budget — without it
    # the hung stages eat the whole deadline and the bench emits value=0.
    rc, out = _run_bench({
        "SRNN_BENCH_TEST_HANG": "ramp,full",
        "SRNN_BENCH_RAMP_TIMEOUT_S": "75",
        "SRNN_BENCH_FULL_TIMEOUT_S": "75",
        "SRNN_BENCH_DEADLINE_S": "360",
    })
    assert rc == 0
    assert out["value"] > 0, "rescue leg must survive a persistent wedge"
    assert out["stage"] == "cpu-rescue"


def test_all_stages_wedged_lands_cpu_rescue_number():
    # every accelerator attempt wedges -> the labeled host-CPU rescue leg
    # must still land a nonzero measurement (r3 recorded 0 here)
    rc, out = _run_bench({
        "SRNN_BENCH_TEST_HANG": "ramp,full",
        "SRNN_BENCH_RAMP_TIMEOUT_S": "4",
        "SRNN_BENCH_FULL_TIMEOUT_S": "4",
        "SRNN_BENCH_DEADLINE_S": "500",
    })
    assert rc == 0
    assert out["value"] > 0
    assert out["stage"] == "cpu-rescue"
    assert out["backend"] == "cpu-forced"
    assert "timeout" in out["error"]


@pytest.mark.slow
def test_stalled_child_names_triage_bundle(tmp_path):
    """The flight-recorder satellite: a wedged child's stall sentinel
    fires INSIDE the attempt timeout, writes a host-only triage bundle,
    and the parent lifts its path into that attempt's stage_log row — so
    deadline exhaustion points at an artifact, not just 'timeout'."""
    triage_root = str(tmp_path / "triage")
    rc, out = _run_bench({
        "SRNN_BENCH_TEST_HANG": "ramp,full",
        "SRNN_BENCH_RAMP_TIMEOUT_S": "12",
        "SRNN_BENCH_FULL_TIMEOUT_S": "12",
        "SRNN_BENCH_DEADLINE_S": "500",
        "SRNN_BENCH_STALL_S": "3",        # operator pin beats the 80% rule
        "SRNN_BENCH_TRIAGE_DIR": triage_root,
    })
    assert rc == 0
    stalled = [a for a in out["stage_log"] if a.get("triage_bundle")]
    assert stalled, f"no attempt carried a bundle: {out['stage_log']}"
    for att in stalled:
        assert att["outcome"].startswith("timeout")
        bundle = att["triage_bundle"]
        assert os.path.isdir(bundle)
        assert os.path.dirname(bundle) == triage_root
        trip = json.load(open(os.path.join(bundle, "trip.json")))
        assert trip["reasons"] == ["stall"]
        assert trip["row"]["stage"] in ("ramp", "full")
        assert trip["thresholds"]["stall_s"] == 3.0
        # the heartbeat ring rode along (empty here: the test hook wedges
        # before the first real heartbeat, exactly like a dead tunnel)
        assert os.path.exists(os.path.join(bundle, "ring.jsonl"))
