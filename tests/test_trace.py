"""Fleet-wide request tracing (PR 17): trace-context propagation from
client through the pool front to worker dispatch, the cross-process
fleet merge with Perfetto flow arrows, tail-kept exemplar retention,
and the ``report --trace-request`` critical-path view.

The load-bearing contracts drilled here:

  * the journal is FORWARD-COMPATIBLE — trace fields (and any unknown
    field a newer writer adds) survive recovery compaction verbatim,
    and a traceless journal compacts byte-identically to pre-tracing
    builds;
  * a pool worker's ``workers/w<i>/events.jsonl`` rows hardcode
    ``process: 0`` (each worker is a solo service) — the fleet merge
    must FORCE them onto lane ``i+1`` from the file layout;
  * a replayed ticket is ONE trace: a single ``trace_id`` spanning the
    front and every worker lane, connected by ``remote_parent`` links
    that render as paired Perfetto flow events.
"""

import json
import os

import pytest

from srnn_tpu.serve.journal import (TicketJournal, read_journal)
from srnn_tpu.telemetry import fleet
from srnn_tpu.telemetry.exemplars import (EXEMPLARS_NAME, ExemplarRing,
                                          find_exemplar, read_exemplars)

# ---------------------------------------------------------------------------
# journal: trace context + forward compatibility
# ---------------------------------------------------------------------------


def test_journal_trace_fields_round_trip(tmp_path):
    j = TicketJournal(str(tmp_path))
    j.record_submit(ticket="t000001", kind="soup", params={"seed": 1},
                    tenant="a", wall=10.0, trace_id="cafe0123",
                    parent_span=7)
    j.record_submit(ticket="t000002", kind="soup", params={"seed": 2},
                    tenant="b", wall=11.0)
    j.close()
    entries, torn, nxt = read_journal(j.path)
    assert torn == 0 and nxt == 3
    assert (entries[0].trace_id, entries[0].parent_span) == ("cafe0123", 7)
    assert (entries[1].trace_id, entries[1].parent_span) == (None, None)
    # traceless submits journal WITHOUT the trace keys (byte-compat)
    lines = [json.loads(l) for l in open(j.path)]
    assert "trace_id" in lines[0] and "trace_id" not in lines[1]
    assert "parent_span" not in lines[1]


def test_journal_preserves_unknown_fields_through_compaction(tmp_path):
    """A journal written by a NEWER version carries fields this reader
    does not know; recovery compaction must pass them through verbatim
    (downgrade-then-upgrade never strips them)."""
    path = tmp_path / "journal.jsonl"
    with open(path, "w") as f:
        f.write(json.dumps({
            "e": "submit", "ticket": "t000001", "kind": "soup",
            "params": {}, "tenant": "a", "key": None,
            "deadline_wall": None, "wall": 1.0,
            "trace_id": "feed0001", "parent_span": 3,
            "priority": "high", "baggage": {"x": 1}}) + "\n")
        f.write(json.dumps({"e": "submit", "ticket": "t000002",
                            "kind": "soup", "params": {}, "tenant": "b",
                            "key": None, "deadline_wall": None,
                            "wall": 2.0}) + "\n")
        f.write(json.dumps({"e": "done", "ticket": "t000002",
                            "status": "done"}) + "\n")
    entries, _torn, _nxt = read_journal(str(path))
    assert entries[0].extra == {"priority": "high", "baggage": {"x": 1}}
    assert entries[1].ticket if len(entries) > 1 else True  # t2 is done
    j = TicketJournal(str(tmp_path))
    unfinished, torn, nxt = j.recover()
    j.close()
    assert [e.ticket for e in unfinished] == ["t000001"]
    rows = [json.loads(l) for l in open(path)]
    assert rows[0] == {"e": "mark", "next_ticket": 3}
    sub = rows[1]
    assert sub["priority"] == "high" and sub["baggage"] == {"x": 1}
    assert sub["trace_id"] == "feed0001" and sub["parent_span"] == 3
    # a second recovery is a fixed point: nothing decays per cycle
    j2 = TicketJournal(str(tmp_path))
    j2.recover()
    j2.close()
    rows2 = [json.loads(l) for l in open(path)]
    assert rows2 == rows


# ---------------------------------------------------------------------------
# exemplar ring: tail-kept traces
# ---------------------------------------------------------------------------


def test_exemplar_ring_append_find_and_compaction(tmp_path):
    path = str(tmp_path / EXEMPLARS_NAME)
    ring = ExemplarRing(path, capacity=4)
    for i in range(10):
        ring.add({"ticket": f"t{i:06d}", "trace_id": f"tr{i}",
                  "reason": "slo", "spans": [{"span": "serve.ticket"}]})
    rows = read_exemplars(path)
    # compacts past 2*capacity down to the newest `capacity`
    assert len(rows) <= 2 * 4
    assert rows[-1]["ticket"] == "t000009"
    # newest-wins lookup, by ticket OR trace id
    ring.add({"ticket": "t000009", "trace_id": "tr9", "reason": "replayed"})
    assert find_exemplar(path, "t000009")["reason"] == "replayed"
    assert find_exemplar(path, "tr9")["reason"] == "replayed"
    assert find_exemplar(path, "never-issued") is None
    # a torn tail (kill -9 mid-append) is skipped, not fatal
    with open(path, "a") as f:
        f.write('{"ticket": "t999999", "tr')
    assert find_exemplar(path, "t999999") is None
    assert read_exemplars(path)[-1]["ticket"] == "t000009"


def test_exemplar_ring_adopts_existing_file(tmp_path):
    path = str(tmp_path / EXEMPLARS_NAME)
    ExemplarRing(path, capacity=2).add({"ticket": "a"})
    ring = ExemplarRing(path, capacity=2)   # restart: adopts line count
    for t in ("b", "c", "d", "e"):
        ring.add({"ticket": t})
    assert len(read_exemplars(path)) <= 4
    assert read_exemplars(path)[-1]["ticket"] == "e"


# ---------------------------------------------------------------------------
# service: trace adoption end to end (submit -> spans -> exemplars)
# ---------------------------------------------------------------------------


def test_service_adopts_propagated_trace_context(tmp_path):
    """A submit carrying trace context (the pool-forwarded case): the
    serve.admit span and the whole serve.ticket family adopt the
    propagated trace_id, the root records the far side of the hop as
    remote_parent (never parent), the SLO-violating ticket keeps its
    FULL span family in the exemplar ring, and stats surfaces the
    slowest-traces panel."""
    from srnn_tpu.serve.service import ExperimentService

    root = str(tmp_path / "svc")
    svc = ExperimentService(root, max_stack=8, slo_p95_ms=0.001)
    with svc:
        t1 = svc.submit("fixpoint_density",
                        {"seed": 0, "trials": 64, "batch": 32},
                        tenant="a", trace_id="cafe0123", parent_span=42)
        assert svc.run_pending(window_s=0.05) == 1
        assert svc.wait(t1)["status"] == "done"
        stats = svc.stats()
        svc.writer.flush()
    rows = [json.loads(l) for l in open(os.path.join(root, "events.jsonl"))]
    spans = [r for r in rows if r.get("kind") == "span"]
    admit = [r for r in spans if r["span"] == "serve.admit"]
    assert admit and admit[0]["trace_id"] == "cafe0123"
    assert admit[0]["remote_parent"] == 42 and admit[0]["ticket"] == t1
    assert "parent" not in admit[0]
    fam = [r for r in spans if r.get("trace_id") == "cafe0123"]
    names = {r["span"] for r in fam}
    assert {"serve.admit", "serve.ticket", "serve.ticket.queue",
            "serve.ticket.window", "serve.ticket.dispatch",
            "serve.ticket.publish"} <= names
    (ticket_root,) = [r for r in fam if r["span"] == "serve.ticket"]
    assert ticket_root["remote_parent"] == 42
    # tail retention: the 1-microsecond SLO makes this ticket a keeper
    rec = find_exemplar(os.path.join(root, EXEMPLARS_NAME), t1)
    assert rec is not None and "slo" in rec["reason"]
    assert rec["trace_id"] == "cafe0123"
    assert len(rec["spans"]) == 5   # full family, not just the root
    # the slowest panel carries the pointer the operator follows
    (slow,) = [e for e in stats["slowest"] if e["ticket"] == t1]
    assert slow["trace_id"] == "cafe0123" and slow["slo_violation"]


def test_service_untraced_submit_roots_its_own_trace(tmp_path):
    """No propagated context -> the ticket id IS the trace id (the PR 12
    contract test_serve_ticket_spans_breakdown_and_slo leans on), and a
    sub-SLO ticket retains only its root span."""
    from srnn_tpu.serve.service import ExperimentService

    root = str(tmp_path / "svc")
    with ExperimentService(root, max_stack=8) as svc:   # no SLO target
        t1 = svc.submit("fixpoint_density",
                        {"seed": 0, "trials": 64, "batch": 32}, tenant="a")
        svc.run_pending(window_s=0.05)
        assert svc.wait(t1)["status"] == "done"
        svc.writer.flush()
    rec = find_exemplar(os.path.join(root, EXEMPLARS_NAME), t1)
    assert rec["reason"] == "root" and len(rec["spans"]) == 1
    assert rec["trace_id"] == t1


# ---------------------------------------------------------------------------
# fleet merge: pool layout, forced lanes, flow arrows
# ---------------------------------------------------------------------------


def _span(name, span_id, *, trace_id, t, dur, process=0, **kw):
    row = {"t": t, "kind": "span", "span": name, "span_id": span_id,
           "trace_id": trace_id, "process": process,
           "start_s": round(t - dur, 6), "seconds": dur}
    row.update(kw)
    return row


def _craft_pool_run_dir(tmp_path):
    """A pool front run dir: front events at the root (lane 0) with the
    front.admit/assign/relay/replay hop spans for ticket t000001 (relayed
    to w0, killed, replayed to w1), plus two worker sub-roots whose rows
    all claim ``process: 0`` — w1's file OUT OF ORDER and w0's file with
    a torn tail (the kill -9 corpse)."""
    run = tmp_path / "pool"
    run.mkdir()
    tr = "cafe0123"
    front = [
        _span("front.admit", 1, trace_id=tr, t=1.0, dur=0.001,
              ticket="t000001", tenant="a"),
        _span("front.assign", 2, trace_id=tr, t=1.01, dur=0.0001,
              ticket="t000001", worker=0),
        _span("front.relay", 3, trace_id=tr, t=1.02, dur=0.01,
              ticket="t000001", worker=0, worker_ticket="t000001"),
        _span("front.replay", 4, trace_id=tr, t=3.0, dur=0.01,
              ticket="t000001", worker=1, worker_ticket="t000001",
              replays=1),
    ]
    with open(run / "events.jsonl", "w") as f:
        for row in front:
            f.write(json.dumps(row) + "\n")
    # dead worker w0: adopted the trace (remote_parent = relay span 3),
    # then a torn tail where the kill landed
    w0 = run / "workers" / "w0"
    w0.mkdir(parents=True)
    with open(w0 / "events.jsonl", "w") as f:
        f.write(json.dumps(_span("serve.admit", 1, trace_id=tr, t=1.03,
                                 dur=0.001, ticket="t000001",
                                 remote_parent=3)) + "\n")
        f.write('{"t": 1.9, "kind": "span", "span": "serve.tick')
    # survivor w1: replayed family, root + children — written OUT OF
    # ORDER so the merge must sort, not trust file order
    w1 = run / "workers" / "w1"
    w1.mkdir(parents=True)
    fam = [
        _span("serve.ticket", 10, trace_id=tr, t=3.6, dur=0.5,
              ticket="t000001", remote_parent=4, mode="stacked"),
        _span("serve.ticket.queue", 11, trace_id=tr, t=3.2, dur=0.1,
              parent=10),
        _span("serve.ticket.dispatch", 12, trace_id=tr, t=3.55, dur=0.35,
              parent=10),
    ]
    with open(w1 / "events.jsonl", "w") as f:
        for row in (fam[2], fam[0], fam[1]):
            f.write(json.dumps(row) + "\n")
    return run, tr


def test_pool_merge_forces_worker_lanes(tmp_path):
    run, tr = _craft_pool_run_dir(tmp_path)
    rows, skipped = fleet.merged_timeline(str(run))
    assert skipped == 1   # w0's torn tail dropped, not fatal
    ts = [r["t"] for r in rows]
    assert ts == sorted(ts)
    # worker rows said process 0; the layout overrode them to lanes 1/2
    by_lane = {}
    for r in rows:
        by_lane.setdefault(r["process"], []).append(r["span"])
    assert by_lane[0] == ["front.admit", "front.assign", "front.relay",
                          "front.replay"]
    assert by_lane[1] == ["serve.admit"]
    assert set(by_lane[2]) == {"serve.ticket", "serve.ticket.queue",
                               "serve.ticket.dispatch"}
    # every row across all three lanes is ONE trace
    assert {r["trace_id"] for r in rows} == {tr}
    s = fleet.fleet_summary(str(run))
    assert s["worker_files"] == [os.path.join("workers", "w0",
                                              "events.jsonl"),
                                 os.path.join("workers", "w1",
                                              "events.jsonl")]
    assert set(s["processes"]) == {"0", "1", "2"}


def test_perfetto_flow_events_pair_across_the_hop(tmp_path):
    """Every remote_parent becomes a paired ph:"s"/"f" flow bound to the
    front span that minted the id — the kill-9 story renders as ONE
    connected trace: front.relay -> dead w0, front.replay -> survivor
    w1."""
    run, tr = _craft_pool_run_dir(tmp_path)
    doc = fleet.perfetto_trace(str(run))
    flows = [e for e in doc["traceEvents"] if e.get("cat") == "flow"]
    starts = {e["id"]: e for e in flows if e["ph"] == "s"}
    finishes = {e["id"]: e for e in flows if e["ph"] == "f"}
    assert len(starts) == len(finishes) == 2   # relay hop + replay hop
    assert set(starts) == set(finishes)
    hops = set()
    for fid, s in starts.items():
        f = finishes[fid]
        assert f["bp"] == "e"
        assert s["args"]["trace_id"] == f["args"]["trace_id"] == tr
        assert s["pid"] == 0 and f["pid"] != 0   # front -> worker, always
        assert s["ts"] <= f["ts"]                # arrows never point back
        hops.add((s["pid"], f["pid"]))
    assert hops == {(0, 1), (0, 2)}
    # the span slices themselves land on the serve lane of each process
    serve_evs = [e for e in doc["traceEvents"]
                 if e.get("cat") == "span" and
                 (e["name"].startswith("serve.") or
                  e["name"].startswith("front."))]
    assert {e["tid"] for e in serve_evs} == {2}
    assert {e["pid"] for e in serve_evs} == {0, 1, 2}


def test_trace_request_resolves_by_ticket_and_trace_id(tmp_path):
    run, tr = _craft_pool_run_dir(tmp_path)
    for want in ("t000001", tr):
        s = fleet.trace_request(str(run), want)
        assert s is not None and s["source"] == "events"
        assert s["trace_id"] == tr
        assert s["processes"] == [0, 1, 2]
        # w0's admit + w1's root each carry a cross-process link
        assert s["cross_process_links"] == 2
        assert s["by_name"]["front.replay"]["count"] == 1
        assert s["root_seconds"] == pytest.approx(0.5)
        crit = {c["span"]: c for c in s["critical_path"]}
        assert set(crit) == {"serve.ticket.queue",
                             "serve.ticket.dispatch"}
        assert crit["serve.ticket.dispatch"]["fraction"] == \
            pytest.approx(0.35 / 0.5, abs=1e-3)
    assert fleet.trace_request(str(run), "never-issued") is None


def test_trace_request_falls_back_to_exemplar_rings(tmp_path):
    """Events rotated past the ticket but tail retention kept it: the
    front ring holds the front spans keyed by the FRONT ticket, the
    worker ring its family keyed by the WORKER ticket — the fallback
    joins them through the shared trace id."""
    run = tmp_path / "pool"
    (run / "workers" / "w0").mkdir(parents=True)
    with open(run / "workers" / "w0" / "events.jsonl", "w") as f:
        f.write("")   # present (the lane exists) but empty
    with open(run / "events.jsonl", "w") as f:
        f.write("")
    tr = "feed0042"
    front_ring = ExemplarRing(str(run / EXEMPLARS_NAME))
    front_ring.add({"ticket": "t000007", "trace_id": tr,
                    "reason": "replayed",
                    "spans": [{"kind": "span", "span": "front.admit",
                               "span_id": 1, "trace_id": tr,
                               "start_s": 1.0, "seconds": 0.001,
                               "ticket": "t000007"}]})
    wring = ExemplarRing(str(run / "workers" / "w0" / EXEMPLARS_NAME))
    wring.add({"ticket": "t000031", "trace_id": tr, "reason": "slo",
               "spans": [{"kind": "span", "span": "serve.ticket",
                          "span_id": 9, "trace_id": tr,
                          "remote_parent": 3, "start_s": 1.2,
                          "seconds": 0.4, "ticket": "t000031"}]})
    s = fleet.trace_request(str(run), "t000007")
    assert s is not None and s["source"] == "exemplars"
    assert s["trace_id"] == tr
    assert s["processes"] == [0, 1]
    assert s["cross_process_links"] == 1
    assert {r["span"] for r in s["spans"]} == {"front.admit",
                                               "serve.ticket"}
    # resolving by the WORKER's ticket finds the same joined trace
    s2 = fleet.trace_request(str(run), "t000031")
    assert s2 is not None and s2["trace_id"] == tr
    assert s2["processes"] == [0, 1]


# ---------------------------------------------------------------------------
# report / watch surfaces
# ---------------------------------------------------------------------------


def test_report_trace_request_cli(tmp_path, capsys):
    from srnn_tpu.telemetry import report

    run, tr = _craft_pool_run_dir(tmp_path)
    assert report.main([str(run), "--trace-request", "t000001"]) == 0
    text = capsys.readouterr().out
    assert tr in text and "front.relay" in text
    assert "<-hop" in text and "critical path" in text
    assert report.main([str(run), "--trace-request", "t000001",
                        "--json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert doc["trace_id"] == tr and doc["cross_process_links"] == 2
    assert report.main([str(run), "--trace-request", "nope"]) == 2


def test_watch_service_render_slowest_panel():
    from srnn_tpu.telemetry import watch

    out = []

    class Out:
        write = staticmethod(out.append)

    watch.render_service(
        {"socket": "/tmp/s.sock", "completed": 3, "queue_depth": 0,
         "requests_per_sec": 1.0, "uptime_s": 5.0, "distinct_programs": 1,
         "slowest": [
             {"ticket": "t000001", "trace_id": "cafe0123",
              "seconds": 1.25, "kind": "soup", "tenant": "a",
              "slo_violation": True, "failed": False,
              "quarantined": False, "replays": 1, "worker": "w1"}]},
        Out())
    text = "".join(out)
    assert "slowest traces" in text and "--trace-request" in text
    assert "t000001" in text and "1.2500s" in text
    assert "SLO" in text and "replayed" in text and "@w1" in text
