"""Golden replay of the reference's committed 2019 dill artifacts.

The reference ships actual *recorded weight trajectories* computed by its
2019 tf.keras code (``ParticleDecorator.make_state`` snapshots,
``/root/reference/code/network.py:185-198``).  These tests replay those
recorded ``w_t -> w_{t+1}`` pairs through this repo's transforms — checking
our math against the reference's own TF numerics step by step, which is
far stronger evidence than the distributional parity in
``test_parity.py``:

* **Self-application** (deterministic): must match at f32 precision.
  - WW:  ``setups/experiments/exp-weightwise_self_application-*``, 20
    particles, 97 step pairs (config: ``network_trajectorys.py:20-29``).
  - Agg: ``results/self_application_aggregation_network``, 10 particles,
    37 step pairs (config: ``network_trajectorys.py:31-40``).
* **Self-training** (keras ``model.fit`` with its default ``shuffle=True``
  permuting the 14 weight samples each epoch): exact replay is only
  defined up to the per-epoch sample order, so the recorded step must lie
  *inside the permutation cloud* of our sequential-SGD epoch, and much
  closer to the nearest sampled permutation than the cloud radius.
  - ``results/self_training_weightwise_network``, 10 particles x 101
    one-epoch ``train()`` calls (config: ``network_trajectorys.py:53-67``).
* **Soup generations**: ``results/Soup`` (20 particles x 100 generations,
  ``soup_trajectorys.py:12-32``, params attacking_rate=0.1, train=30).
  A generation in which the particle received no attack is exactly 30
  sequential train epochs; ~90% of pairs should replay within the
  30-epoch shuffle tolerance, and the recorded keras-history loss (mean
  pre-update per-sample loss of the last epoch — the quantity
  ``fit_epochs_flat`` returns) must track ours.

The artifact *inventory* is itself a test: scanning every ``.dill`` in the
reference proves which trajectory data exists at all — in particular that
**no RecurrentNeuralNetwork trajectory (and no recorded RNN init) exists
anywhere**, settling what evidence the open RNN-training-parity row
(RESULTS.md) can and cannot ever get.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from srnn_tpu import reference_artifacts as ra
from srnn_tpu import train as tr
from srnn_tpu.nets import aggregating, weightwise
from srnn_tpu.topology import Topology

pytestmark = pytest.mark.skipif(
    not os.path.isdir(ra.REFERENCE_ROOT),
    reason="reference artifact tree not present")

TOPO = Topology(variant="weightwise", width=2, depth=2)


def _finite_rel_err(pred: np.ndarray, want: np.ndarray) -> float:
    """Max relative error, meaningful on diverging trajectories where
    |w| reaches 1e20 (absolute error is huge, relative ~f32 eps)."""
    return float(np.max(np.abs(pred - want) / (1e-6 + np.abs(want))))


# ---------------------------------------------------------------------------
# inventory
# ---------------------------------------------------------------------------


def test_all_35_artifacts_load_and_rnn_has_no_recordings():
    rows = ra.scan("/root/reference")
    assert len(rows) == 35, [r["path"] for r in rows]
    failures = [r for r in rows if not r["loads"]]
    assert not failures, failures
    classes = {}
    for r in rows:
        for cls, n in r["classes"].items():
            classes[cls] = classes.get(cls, 0) + n
    # the complete census of recorded particle trajectories in the
    # reference: WW (self-application + training), Agg (self-application),
    # TrainingNeuralNetworkDecorator-wrapped WW (the two soup runs) — and
    # **zero** RNN/FFT recordings.  The RNN z=5.4 parity row can therefore
    # never be settled against recorded 2019 inits; the named candidate
    # (exp-training_fixpoint trajectorys.dill) is an empty
    # without_particles() shell.
    assert classes == {
        "WeightwiseNeuralNetwork": 60,
        "AggregatingNeuralNetwork": 20,
        "TrainingNeuralNetworkDecorator": 40,
    }, classes
    empty_shell = ra.load_artifact(ra.reference_path(
        "setups/experiments/exp-training_fixpoint-_1552658296.0913951-0/"
        "trajectorys.dill"))
    assert ra.particle_states(empty_shell) == {}


# ---------------------------------------------------------------------------
# self-application: deterministic, must match at f32 precision
# ---------------------------------------------------------------------------


def test_ww_self_application_replays_f32_exact():
    states = ra.particle_states(
        ra.load_artifact(ra.reference_path(ra.WW_SELF_APPLICATION)))
    assert len(states) == 20

    @jax.jit
    def step(w):
        return weightwise.apply(TOPO, w, w)

    n_pairs, worst = 0, 0.0
    for particle in states.values():
        for a, b in ra.step_pairs(particle):
            want = np.ravel(b["weights"]).astype(np.float32)
            if not np.all(np.isfinite(want)):
                continue
            pred = np.asarray(step(jnp.asarray(np.ravel(a["weights"]),
                                               jnp.float32)))
            worst = max(worst, _finite_rel_err(pred, want))
            n_pairs += 1
    assert n_pairs >= 90
    # measured: 7.8e-6 worst-case relative (f32 rounding on small-|w|
    # entries; median abs err is 1.2e-7)
    assert worst < 1e-4, worst


def test_agg_self_application_replays_f32_exact():
    topo = Topology(variant="aggregating", width=2, depth=2, aggregates=4)
    states = ra.particle_states(
        ra.load_artifact(ra.reference_path(ra.AGG_SELF_APPLICATION)))
    assert len(states) == 10

    @jax.jit
    def step(w):
        return aggregating.apply(topo, w, w)

    n_pairs, worst = 0, 0.0
    for particle in states.values():
        for a, b in ra.step_pairs(particle):
            want = np.ravel(b["weights"]).astype(np.float32)
            if not np.all(np.isfinite(want)):
                continue
            pred = np.asarray(step(jnp.asarray(np.ravel(a["weights"]),
                                               jnp.float32)))
            worst = max(worst, _finite_rel_err(pred, want))
            n_pairs += 1
    assert n_pairs >= 35
    # measured: 8.7e-6 worst-case
    assert worst < 1e-4, worst


# ---------------------------------------------------------------------------
# self-training: exact up to keras fit's per-epoch sample shuffle
# ---------------------------------------------------------------------------


def test_ww_training_replay_is_within_shuffle_cloud():
    """The recorded epoch must (a) deviate from our enumeration-order epoch
    by no more than the permutation-cloud radius, and (b) sit an order of
    magnitude closer to the nearest of 256 sampled permutations than to
    the cloud radius — the signature of 'same per-sample update math,
    different sample order' as opposed to 'different math'."""
    states = ra.particle_states(
        ra.load_artifact(ra.reference_path(ra.WW_SELF_TRAINING)))
    assert len(states) == 10
    assert all(len(s) == 102 for s in states.values())

    @jax.jit
    def epoch(w, key):
        x, y = weightwise.samples(TOPO, w)
        new_w, _ = tr.fit_epoch(TOPO, w, x, y, tr.DEFAULT_LR, "sequential",
                                key=key)
        return new_w

    @jax.jit
    def epoch_seq(w):
        x, y = weightwise.samples(TOPO, w)
        return tr.fit_epoch(TOPO, w, x, y, tr.DEFAULT_LR, "sequential")[0]

    particle = next(iter(states.values()))
    checked = 0
    for t in (0, 3, 10, 50):
        a, b = particle[t], particle[t + 1]
        w0 = jnp.asarray(np.ravel(a["weights"]), jnp.float32)
        want = np.ravel(b["weights"]).astype(np.float32)
        seq = np.asarray(epoch_seq(w0))
        keys = jax.random.split(jax.random.PRNGKey(t), 256)
        cloud = np.asarray(jax.vmap(lambda k: epoch(w0, k))(keys))
        d_rec = np.linalg.norm(want - seq)
        radius = np.linalg.norm(cloud - seq[None], axis=1).max()
        d_near = np.linalg.norm(cloud - want[None], axis=1).min()
        assert d_rec <= 1.5 * radius, (t, d_rec, radius)
        assert d_near <= 0.35 * max(d_rec, 1e-12), (t, d_near, d_rec)
        checked += 1
    assert checked == 4

    # across ALL 1010 recorded epochs the order-deviation stays small in
    # relative terms (measured median 0.43%)
    rels = []
    for particle in states.values():
        for a, b in ra.step_pairs(particle):
            w0 = jnp.asarray(np.ravel(a["weights"]), jnp.float32)
            want = np.ravel(b["weights"]).astype(np.float32)
            rels.append(_finite_rel_err(np.asarray(epoch_seq(w0)), want))
    assert len(rels) == 1010
    assert np.median(rels) < 0.02, np.median(rels)


# ---------------------------------------------------------------------------
# soup generations
# ---------------------------------------------------------------------------


def test_soup_generation_replay():
    soup = ra.load_artifact(ra.reference_path(ra.SOUP_RUNS[0]))
    assert soup.params["train"] == 30 and soup.params["attacking_rate"] == 0.1
    states = ra.particle_states(soup)
    assert len(states) == 20

    @jax.jit
    def generation(w):
        return tr.fit_epochs_flat(TOPO, w, 30, tr.DEFAULT_LR, "sequential")

    w_errs, loss_errs = [], []
    for particle in states.values():
        for a, b in ra.step_pairs(particle):
            w0 = jnp.asarray(np.ravel(a["weights"]), jnp.float32)
            want = np.ravel(b["weights"]).astype(np.float32)
            pred, loss = generation(w0)
            w_errs.append(_finite_rel_err(np.asarray(pred), want))
            want_loss = float(b["loss"])
            loss_errs.append(abs(float(loss) - want_loss)
                             / (1e-12 + abs(want_loss)))
    w_errs, loss_errs = np.asarray(w_errs), np.asarray(loss_errs)
    assert len(w_errs) == 1980
    # measured: 89.2% of pairs replay within 5% (30 epochs of shuffle
    # accumulation); the rest received attacks mid-generation — at
    # attacking_rate=0.1, N=20, P(>=1 incoming attack) ~ 9.5%
    assert (w_errs < 0.05).mean() > 0.80, (w_errs < 0.05).mean()
    assert np.median(loss_errs) < 0.05, np.median(loss_errs)


# ---------------------------------------------------------------------------
# migration rendering
# ---------------------------------------------------------------------------


def test_reference_tree_renders_via_search_and_apply(tmp_path):
    from srnn_tpu import viz

    src = os.path.dirname(ra.reference_path(ra.WW_SELF_APPLICATION))
    outs = viz.search_and_apply(src, out_dir=str(tmp_path))
    made = {os.path.basename(o) for o in outs}
    assert "trajectorys_ref_trajectories_3d.png" in made, outs
    assert "trajectorys_ref_trajectories_3d.html" in made, outs
    for o in outs:
        assert os.path.getsize(o) > 0
